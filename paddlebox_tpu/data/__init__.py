from paddlebox_tpu.data.schema import SlotDef, DataFeedDesc
from paddlebox_tpu.data.record import SlotRecord, SlotRecordPool
from paddlebox_tpu.data.batch import SlotBatch, BatchBuilder
from paddlebox_tpu.data.parser import (
    SlotTextParser, CriteoParser, register_parser, get_parser,
)
from paddlebox_tpu.data.dataset import (
    DatasetFactory, InMemoryDataset, QueueDataset, PaddleBoxDataset,
)

__all__ = [
    "SlotDef", "DataFeedDesc", "SlotRecord", "SlotRecordPool", "SlotBatch",
    "BatchBuilder", "SlotTextParser", "CriteoParser", "register_parser",
    "get_parser", "DatasetFactory", "InMemoryDataset", "QueueDataset",
    "PaddleBoxDataset",
]
