from paddlebox_tpu.data.schema import SlotDef, DataFeedDesc
from paddlebox_tpu.data.record import SlotRecord, SlotRecordPool
from paddlebox_tpu.data.batch import SlotBatch, BatchBuilder
from paddlebox_tpu.data.parser import (
    SlotTextParser, CriteoParser, register_parser, get_parser,
)
from paddlebox_tpu.data.dataset import (
    DatasetFactory, InMemoryDataset, QueueDataset, PaddleBoxDataset,
)
from paddlebox_tpu.data.pv import (
    PvBatchBuilder, build_rank_offset, group_by_search_id, group_by_uid,
)

__all__ = [
    "SlotDef", "DataFeedDesc", "SlotRecord", "SlotRecordPool", "SlotBatch",
    "BatchBuilder", "SlotTextParser", "CriteoParser", "register_parser",
    "get_parser", "DatasetFactory", "InMemoryDataset", "QueueDataset",
    "PaddleBoxDataset", "PvBatchBuilder", "build_rank_offset",
    "group_by_search_id", "group_by_uid",
]
