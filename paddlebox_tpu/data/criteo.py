"""Synthetic Criteo-style data generation (test/bench fixture).

Reference test fixture analogue: python/paddle/fluid/tests/unittests/
ctr_dataset_reader.py (synthetic CTR data generator used across dataset and
trainer tests).

Generates clicks from a planted logistic model over hashed categorical
features so that learned models have real signal (AUC well above 0.5) —
letting end-to-end tests assert learning, not just shape-correctness.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np


def generate_criteo_files(
    out_dir: str,
    num_files: int = 2,
    rows_per_file: int = 5000,
    vocab_per_slot: int = 1000,
    seed: int = 0,
    planted_dim: int = 8,
    value_base: int = 0,
) -> List[str]:
    """Write criteo-format TSV files; returns file paths.

    ``value_base`` offsets every categorical value — day-k datasets with
    ``value_base=k*vocab_per_slot`` have disjoint feature spaces (fresh
    features per pass, the tiered-PS workload) while keeping the planted
    learnable signal (weights hash from the offset value)."""
    rng = np.random.default_rng(seed)
    # planted model: each (slot, value) id gets a latent weight via hashing
    w_dense = rng.normal(0, 0.3, size=13).astype(np.float32)
    paths = []
    os.makedirs(out_dir, exist_ok=True)
    for fi in range(num_files):
        path = os.path.join(out_dir, f"criteo_part_{fi:03d}.txt")
        with open(path, "w") as fh:
            for _ in range(rows_per_file):
                dense_raw = rng.integers(0, 100, size=13)
                cats = value_base + rng.integers(0, vocab_per_slot,
                                                 size=26)
                # latent weight of a categorical value: deterministic hash → N(0, .25)
                hvals = ((cats * 2654435761 + np.arange(26) * 97) % 1000003)
                w_cat = ((hvals.astype(np.float64) / 1000003.0) - 0.5)
                logit = float(np.log1p(dense_raw) @ w_dense) * 0.2 + float(w_cat.sum()) * 1.2
                p = 1.0 / (1.0 + np.exp(-logit))
                label = int(rng.random() < p)
                dense_s = "\t".join(str(int(v)) if rng.random() > 0.05 else ""
                                    for v in dense_raw)
                cat_s = "\t".join(format(int(c), "x") for c in cats)
                fh.write(f"{label}\t{dense_s}\t{cat_s}\n")
        paths.append(path)
    return paths
