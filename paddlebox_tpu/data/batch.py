"""Device-ready batch layout + builder.

Reference: the GPU minibatch packer ``MiniBatchGpuPack`` + copy kernels
(data_feed.h:529-652, data_feed.cu:1210-1259) which build per-slot LoDTensors.

TPU-native redesign: one flattened key tensor for ALL slots with segment ids,
padded to a static bucket capacity. Ragged per-slot LoD never reaches the
device — pooling is a single ``segment_sum`` over ``segments`` (ins*S + slot),
which XLA lowers to one fused scatter-add; slot boundaries are implicit in the
segment id. Static bucket shapes keep jit recompiles bounded.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from paddlebox_tpu.data.record import SlotRecord
from paddlebox_tpu.data.schema import DataFeedDesc


@dataclasses.dataclass
class SlotBatch:
    """Host (numpy) batch; fields are what the jit train step consumes.

    ``segments[k] == ins*S + slot`` for valid keys, ``B*S`` for padding —
    so ``segment_sum(values, segments, B*S+1)[:-1]`` pools every slot of
    every instance in one op and the padding falls into a discarded bin.
    """

    keys: np.ndarray        # uint64 [K_pad]
    segments: np.ndarray    # int32  [K_pad]
    num_keys: int           # valid prefix length
    dense: np.ndarray       # float32 [B, dense_dim]
    label: np.ndarray       # float32 [B]
    show: np.ndarray        # float32 [B]
    clk: np.ndarray         # float32 [B]
    batch_size: int
    num_slots: int          # S (sparse slots)
    # True when segments[i] == i for every valid key (each record has
    # exactly one key per slot — the one-hot CTR layout): the device side
    # can then derive segments from the key position and the H2D copy
    # skips the segments array entirely.
    segments_trivial: bool = False
    # metric side-channels (WuAUC / cmatch_rank variants)
    uid: Optional[np.ndarray] = None     # int64 [B]
    rank: Optional[np.ndarray] = None    # int32 [B]
    cmatch: Optional[np.ndarray] = None  # int32 [B]
    # ads timestamp tensor (need_time_info, GetTimestampGPU)
    timestamp: Optional[np.ndarray] = None  # int64 [B]
    # sample ids for the dump subsystem (None when no record carries one)
    ins_ids: Optional[list] = None       # list[str], len == #real records

    @property
    def key_capacity(self) -> int:
        return int(self.keys.shape[0])

    @property
    def pad_segment(self) -> int:
        return self.batch_size * self.num_slots


class BatchBuilder:
    """records → SlotBatch with static-bucket key padding."""

    def __init__(self, desc: DataFeedDesc) -> None:
        self.desc = desc
        self.num_slots = len(desc.sparse_slots)
        self.dense_dim = desc.dense_dim

    def build(self, records: Sequence[SlotRecord]) -> SlotBatch:
        desc = self.desc
        bs = desc.batch_size
        n = len(records)
        if n == 0:
            raise ValueError("empty batch")
        if n > bs:
            raise ValueError(f"{n} records > batch_size {bs}")
        S = self.num_slots

        key_arrays: List[np.ndarray] = []
        seg_arrays: List[np.ndarray] = []
        slot_base = np.arange(S, dtype=np.int64)
        for i, r in enumerate(records):
            key_arrays.append(r.keys)
            counts = np.diff(r.slot_offsets)
            seg_arrays.append(np.repeat(i * S + slot_base, counts).astype(np.int32))
        keys = np.concatenate(key_arrays) if key_arrays else np.empty(0, np.uint64)
        segs = np.concatenate(seg_arrays) if seg_arrays else np.empty(0, np.int32)
        nk = int(keys.shape[0])

        cap = desc.key_capacity(nk)
        pad_seg = bs * S
        keys_p = np.zeros(cap, dtype=np.uint64)
        segs_p = np.full(cap, pad_seg, dtype=np.int32)
        keys_p[:nk] = keys
        segs_p[:nk] = segs

        dense = np.zeros((bs, self.dense_dim), dtype=np.float32)
        label = np.zeros(bs, dtype=np.float32)
        show = np.zeros(bs, dtype=np.float32)
        clk = np.zeros(bs, dtype=np.float32)
        uid = np.zeros(bs, dtype=np.int64)
        rank = np.zeros(bs, dtype=np.int32)
        cmatch = np.zeros(bs, dtype=np.int32)
        ts = np.zeros(bs, dtype=np.int64)
        for i, r in enumerate(records):
            if r.dense.size:
                dense[i, :r.dense.size] = r.dense
            label[i] = r.label
            show[i] = r.show
            clk[i] = r.clk
            uid[i] = r.uid
            rank[i] = r.rank
            cmatch[i] = r.cmatch
            ts[i] = r.timestamp
        ins_ids = ([r.ins_id for r in records]
                   if any(r.ins_id for r in records) else None)
        # short batches (tail of a pass): instances [n, bs) have show=0 so
        # they contribute nothing to pooled sums, loss, or metrics.
        trivial = (nk == n * S
                   and bool(np.array_equal(segs, np.arange(nk, dtype=np.int32))))
        return SlotBatch(
            keys=keys_p, segments=segs_p, num_keys=nk, dense=dense,
            label=label, show=show, clk=clk, batch_size=bs, num_slots=S,
            segments_trivial=trivial,
            uid=uid, rank=rank, cmatch=cmatch, timestamp=ts,
            ins_ids=ins_ids,
        )
