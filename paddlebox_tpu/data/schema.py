"""Slot schema — the ``DataFeedDesc`` analogue.

Reference: paddle/fluid/framework/data_feed.proto:43-59 (``DataFeedDesc``:
multi_slot_desc with per-slot {name, type, is_dense, is_used, shape},
batch_size, pipe_command, pv_batch_size, rank_offset, ads fields).

TPU-native difference: instead of per-slot LoDTensors, the schema also fixes
the *static* padded key capacity per batch (XLA wants static shapes), chosen
from a geometric bucket ladder at batch-build time.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class SlotDef:
    """One input slot. ``uint64`` slots carry sparse feature ids (feasigns);
    ``float`` slots carry fixed-dim dense values."""

    name: str
    type: str = "uint64"  # "uint64" | "float"
    dim: int = 1          # float slots: values per record; uint64: unused
    is_used: bool = True

    def __post_init__(self) -> None:
        if self.type not in ("uint64", "float"):
            raise ValueError(f"slot {self.name}: bad type {self.type}")


@dataclasses.dataclass
class DataFeedDesc:
    slots: List[SlotDef] = dataclasses.field(default_factory=list)
    batch_size: int = 512
    parser: str = "slot_text"        # registered parser name
    # shell command each reader pipes a file through before parsing its
    # stdout (data_feed.proto:45 pipe_command / LoadIntoMemoryByCommand)
    pipe_command: Optional[str] = None
    label_slot: Optional[str] = None  # which slot is the click label
    show_slot: Optional[str] = None
    clk_slot: Optional[str] = None
    pv_batch_size: int = 0            # page-view (PV) merged batching
    rank_offset: Optional[str] = None  # rank_offset tensor name for PV mode
    # static padding ladder for flattened sparse keys per batch
    key_bucket_min: int = 1024
    key_bucket_growth: float = 2.0

    @property
    def sparse_slots(self) -> List[SlotDef]:
        return [s for s in self.slots if s.type == "uint64" and s.is_used]

    @property
    def dense_slots(self) -> List[SlotDef]:
        """Float feature slots — excludes the label/show/clk channels, which
        parsers route to their own record fields."""
        special = {self.label_slot, self.show_slot, self.clk_slot}
        return [s for s in self.slots
                if s.type == "float" and s.is_used and s.name not in special]

    @property
    def dense_dim(self) -> int:
        return sum(s.dim for s in self.dense_slots)

    def sparse_slot_index(self, name: str) -> int:
        for i, s in enumerate(self.sparse_slots):
            if s.name == name:
                return i
        raise KeyError(name)

    def key_capacity(self, num_keys: int) -> int:
        """Pick the padded key capacity bucket for a batch with num_keys keys.
        Geometric ladder bounds the number of distinct XLA compilations."""
        cap = self.key_bucket_min
        while cap < num_keys:
            cap = int(cap * self.key_bucket_growth)
        return cap

    @classmethod
    def criteo(cls, batch_size: int = 512) -> "DataFeedDesc":
        """Criteo display-ads schema: 13 dense ints (as one float slot of
        dim 13) + 26 categorical sparse slots + click label."""
        slots: List[SlotDef] = [SlotDef("label", "float", 1)]
        slots.append(SlotDef("dense", "float", 13))
        slots += [SlotDef(f"C{i}", "uint64") for i in range(1, 27)]
        return cls(slots=slots, batch_size=batch_size, parser="criteo",
                   label_slot="label")
