"""Text parsers: line → SlotRecord.

Reference: paddle/fluid/framework/data_feed.{h,cc} — ``MultiSlotDataFeed``
text format parsing (data_feed.cc) and the plugin parser API
``CustomParser``/``ISlotParser`` loaded via dlopen (data_feed.h:450,:1984,
``DLManager`` :698). TPU-native port: parsers are registered python callables
(a custom parser is just an imported class), same extension point without
the .so machinery; a C++ fast-path parser can be slotted in behind the same
registry (see paddlebox_tpu/native).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Type

import numpy as np

from paddlebox_tpu.data.record import SlotRecord
from paddlebox_tpu.data.schema import DataFeedDesc

_HEXDIGITS = set("0123456789abcdefABCDEF")


class BaseParser:
    """Parse one text line into a SlotRecord (None = drop the line)."""

    def __init__(self, desc: DataFeedDesc) -> None:
        self.desc = desc

    def parse(self, line: str) -> Optional[SlotRecord]:
        raise NotImplementedError

    def parse_file_columnar(self, path: str) -> Optional[dict]:
        """Bulk fast path: parse a whole file straight into columnar
        arrays (native/slot_parser.cpp), bypassing per-line python and
        SlotRecord objects. Returns a dict with keys / key_slot /
        offsets / dense / label / show / clk, or None when no native
        fast path exists (caller falls back to per-line parse)."""
        return None


def _slot_text_spec(desc: DataFeedDesc) -> np.ndarray:
    """Compact slot spec for native slot_text_parse: per slot (kind, dim);
    kinds: 0 sparse, 1 dense, 2 label, 3 show, 4 clk, 5 skip."""
    spec = np.zeros((len(desc.slots), 2), np.int32)
    for i, slot in enumerate(desc.slots):
        if slot.type == "uint64":
            spec[i, 0] = 0 if slot.is_used else 5
        elif slot.name == desc.label_slot:
            spec[i, 0] = 2
        elif slot.name == desc.show_slot:
            spec[i, 0] = 3
        elif slot.name == desc.clk_slot:
            spec[i, 0] = 4
        elif slot.is_used:
            spec[i, 0] = 1
            spec[i, 1] = slot.dim
        else:
            spec[i, 0] = 5
    return spec


def _native_lib():
    from paddlebox_tpu.native import load_native
    return load_native()


def _read_bytes(path: str) -> bytes:
    with open(path, "rb") as fh:
        return fh.read()


def _line_count(buf: bytes) -> int:
    n = buf.count(b"\n")
    return n + (1 if buf and not buf.endswith(b"\n") else 0)


def _bulk_slot_text_parse(fn, desc: DataFeedDesc,
                          path: str) -> Optional[dict]:
    """Shared driver for the bulk columnar C ABI (slot_text_parse
    signature — native lib or user plugin .so): buffer sizing, the
    retry-on-key-arena-overflow loop (n == -1 → double), result slicing."""
    import ctypes
    buf = _read_bytes(path)
    max_rec = buf.count(b"\n") + 1
    spec = _slot_text_spec(desc)
    dense_dim = desc.dense_dim
    key_cap = max(1024, max_rec * max(1, len(desc.sparse_slots)))
    while True:
        keys = np.empty(key_cap, np.uint64)
        key_slot = np.empty(key_cap, np.int32)
        offs = np.empty(max_rec + 1, np.int64)
        dense = np.empty((max_rec, dense_dim), np.float32)
        label = np.empty(max_rec, np.float32)
        show = np.empty(max_rec, np.float32)
        clk = np.empty(max_rec, np.float32)
        ptr = lambda a: a.ctypes.data_as(ctypes.c_void_p)
        n = fn(ctypes.c_char_p(buf), ctypes.c_int64(len(buf)), ptr(spec),
               ctypes.c_int64(len(desc.slots)), ctypes.c_int64(dense_dim),
               ctypes.c_int64(max_rec), ctypes.c_int64(key_cap),
               ptr(keys), ptr(key_slot), ptr(offs), ptr(dense),
               ptr(label), ptr(show), ptr(clk))
        if n == -1:  # key arena overflowed: double and retry
            key_cap *= 2
            continue
        n = int(n)
        nk = int(offs[n])
        return dict(keys=keys[:nk].copy(),
                    key_slot=key_slot[:nk].copy(),
                    offsets=offs[:n + 1].copy(),
                    dense=dense[:n].copy(), label=label[:n].copy(),
                    show=show[:n].copy(), clk=clk[:n].copy(),
                    dropped=_line_count(buf) - n)


class _NativeSlotTextMixin:
    """parse_file_columnar via native slot_text_parse."""

    def parse_file_columnar(self, path: str) -> Optional[dict]:
        lib = _native_lib()
        if lib is None:
            return None
        return _bulk_slot_text_parse(lib.slot_text_parse, self.desc, path)


class _NativeCriteoMixin:
    """parse_file_columnar via native criteo_parse."""

    def parse_file_columnar(self, path: str) -> Optional[dict]:
        import ctypes
        lib = _native_lib()
        if lib is None:
            return None
        buf = _read_bytes(path)
        max_rec = buf.count(b"\n") + 1
        keys = np.empty((max_rec, 26), np.uint64)
        dense = np.empty((max_rec, 13), np.float32)
        label = np.empty(max_rec, np.float32)
        ptr = lambda a: a.ctypes.data_as(ctypes.c_void_p)
        n = int(lib.criteo_parse(buf, len(buf), max_rec, ptr(keys),
                                 ptr(dense), ptr(label)))
        label = label[:n].copy()
        return dict(
            keys=keys[:n].reshape(-1).copy(),
            key_slot=np.tile(np.arange(26, dtype=np.int32), n),
            offsets=np.arange(n + 1, dtype=np.int64) * 26,
            dense=dense[:n].copy(), label=label,
            show=np.ones(n, np.float32), clk=label.copy(),
            dropped=_line_count(buf) - n)



class SlotTextParser(_NativeSlotTextMixin, BaseParser):
    """Generic multi-slot text format, one record per line:

        <num> v0 v1 ... <num> v0 ...        (one group per slot, schema order)

    — the ``MultiSlotDataFeed`` wire format (data_feed.cc text path). Sparse
    slot values are uint64 feasigns; float slot groups must carry exactly
    ``dim`` values. The slot named by desc.label_slot feeds ``label``;
    show/clk slots likewise if configured.
    """

    def parse(self, line: str) -> Optional[SlotRecord]:
        toks = line.split()
        desc = self.desc
        pos = 0
        sparse_chunks: List[np.ndarray] = []
        offsets = [0]
        dense_parts: List[float] = []
        label = show = clk = None
        try:
            for slot in desc.slots:
                n = int(toks[pos]); pos += 1
                vals = toks[pos:pos + n]; pos += n
                if len(vals) != n:
                    return None
                if slot.type == "uint64":
                    if slot.is_used:
                        arr = np.array(vals, dtype=np.uint64)
                        sparse_chunks.append(arr)
                        offsets.append(offsets[-1] + n)
                else:
                    fvals = [float(v) for v in vals]
                    if slot.name == desc.label_slot:
                        label = fvals[0] if fvals else 0.0
                    elif slot.name == desc.show_slot:
                        show = fvals[0] if fvals else 1.0
                    elif slot.name == desc.clk_slot:
                        clk = fvals[0] if fvals else 0.0
                    elif slot.is_used:
                        if len(fvals) != slot.dim:
                            return None
                        dense_parts.extend(fvals)
        except (ValueError, IndexError, OverflowError):
            # OverflowError: negative/oversized tokens in a uint64 slot —
            # drop the line (the native parser rejects them the same way)
            return None
        keys = (np.concatenate(sparse_chunks) if sparse_chunks
                else np.empty(0, dtype=np.uint64))
        return SlotRecord(
            keys=keys,
            slot_offsets=np.array(offsets, dtype=np.int32),
            dense=np.array(dense_parts, dtype=np.float32),
            label=0.0 if label is None else label,
            show=1.0 if show is None else show,
            clk=(label if clk is None and label is not None else (clk or 0.0)),
        )


class CriteoParser(_NativeCriteoMixin, BaseParser):
    """Criteo display-ads TSV: label \\t I1..I13 \\t C1..C26 (hex).

    Dense ints get the standard log(x+1) transform; missing dense → 0;
    missing categorical → slot-salted sentinel key. Each categorical value is
    salted with its slot index so ids don't collide across slots in a single
    shared table (the reference keeps per-slot feasign spaces; we fold the
    slot id into the key's high bits instead — one unified key space is the
    TPU-friendly layout for a single sharded table)."""

    _SLOT_SHIFT = 52  # 26 slots fit in high bits; low 52 bits hash payload

    def parse(self, line: str) -> Optional[SlotRecord]:
        f = line.rstrip("\n").split("\t")
        if len(f) != 40:
            return None
        try:
            label = float(f[0])
        except ValueError:
            return None
        dense = np.zeros(13, dtype=np.float32)
        for i in range(13):
            v = f[1 + i]
            if v:
                try:
                    dense[i] = np.log1p(max(float(v), 0.0))
                except ValueError:
                    pass
        keys = np.empty(26, dtype=np.uint64)
        mask = (np.uint64(1) << np.uint64(self._SLOT_SHIFT)) - np.uint64(1)
        hexdigits = _HEXDIGITS
        for i in range(26):
            v = f[14 + i]
            # strict bare-hex only (no 0x/+/_ forms int() would take),
            # invalid → missing-value sentinel, overlong wraps mod 2^64 —
            # all matching native parse_hex64 exactly
            if v and not (set(v) - hexdigits):
                h = np.uint64(int(v, 16) & 0xFFFFFFFFFFFFFFFF)
            else:
                h = np.uint64(0xFFFFFFFF)
            keys[i] = (np.uint64(i + 1) << np.uint64(self._SLOT_SHIFT)) | (h & mask)
        offsets = np.arange(27, dtype=np.int32)  # one key per slot
        return SlotRecord(keys=keys, slot_offsets=offsets, dense=dense,
                          label=label, show=1.0, clk=label)


_PARSERS: Dict[str, Type[BaseParser]] = {}


def register_parser(name: str, cls: Type[BaseParser]) -> None:
    _PARSERS[name] = cls


def get_parser(desc: DataFeedDesc) -> BaseParser:
    try:
        return _PARSERS[desc.parser](desc)
    except KeyError:
        raise KeyError(
            f"unknown parser {desc.parser!r}; registered: {sorted(_PARSERS)}"
        ) from None


class _PluginSoParser(SlotTextParser):
    """Parser backed by a user shared library exposing the bulk columnar
    C ABI (same signature as native/slot_parser.cpp ``slot_text_parse``).
    Per-line fallback is the slot_text format."""

    _lib = None
    _symbol = "slot_text_parse"

    def parse_file_columnar(self, path: str) -> Optional[dict]:
        import ctypes
        fn = getattr(type(self)._lib, type(self)._symbol)
        fn.restype = ctypes.c_int64
        return _bulk_slot_text_parse(fn, self.desc, path)


def load_parser_plugin(spec: str, name: Optional[str] = None) -> List[str]:
    """Load a custom parser plugin and register its parsers — the
    ``DLManager``/``CustomParser`` extension point (data_feed.h:450,:698,
    ``LoadIntoMemoryByLib`` data_feed.h:1675), without requiring the
    paddle .so ABI. Three plugin forms:

    - ``"pkg.module"`` / ``"pkg.module:attr"``: imported; the module either
      self-registers via :func:`register_parser` or exposes a ``PARSERS``
      dict of {name: BaseParser subclass}.
    - ``"/path/to/plugin.py"``: executed as a module, same contract.
    - ``"/path/to/libcustom.so"`` or ``".so:symbol"``: ctypes-loaded
      library exposing the bulk columnar C ABI (the signature of
      native/slot_parser.cpp ``slot_text_parse``); registered under
      ``name`` (default: the file stem).

    Returns the list of parser names registered by this call."""
    import ctypes
    import importlib
    import importlib.util
    import os

    before = set(_PARSERS)

    path, sym = spec, None
    head, colon, tail = spec.rpartition(":")
    if colon and not spec.endswith(".so") and not spec.endswith(".py"):
        path, sym = head, tail

    if path.endswith(".so"):
        lib = ctypes.CDLL(path)
        pname = name or os.path.splitext(os.path.basename(path))[0]
        cls = type(f"PluginParser_{pname}", (_PluginSoParser,),
                   {"_lib": lib, "_symbol": sym or "slot_text_parse"})
        register_parser(pname, cls)
        return [pname]

    if path.endswith(".py"):
        modname = name or os.path.splitext(os.path.basename(path))[0]
        mspec = importlib.util.spec_from_file_location(
            f"pbox_parser_plugin_{modname}", path)
        mod = importlib.util.module_from_spec(mspec)
        mspec.loader.exec_module(mod)
    else:
        mod = importlib.import_module(path)
        if sym:
            mod = getattr(mod, sym)

    for pname, cls in getattr(mod, "PARSERS", {}).items():
        register_parser(pname, cls)
    return sorted(set(_PARSERS) - before)


register_parser("slot_text", SlotTextParser)
register_parser("criteo", CriteoParser)
