"""Columnar in-memory record store — vectorized batch building.

Reference rationale: the reference keeps parsed passes in compact columnar
``SlotRecord`` arenas (data_feed.h:97-433) precisely so the per-batch GPU
pack (MiniBatchGpuPack) is a flat copy, not per-record work. The python
object path (SlotRecord list → BatchBuilder loop) costs ~70ms per 8k batch;
this store makes a batch two numpy slices + one np.repeat (<2ms), keeping
the TPU fed (device step is ~0.3ms — host batch build IS the throughput
ceiling).

Layout: all records' keys concatenated (record-major), with per-key slot
ids; record boundaries via offsets; dense/label/show/clk as [R, …] arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.data.record import SlotRecord
from paddlebox_tpu.data.schema import DataFeedDesc


@dataclasses.dataclass
class ColumnarRecords:
    keys: np.ndarray         # uint64 [total_keys] record-major
    key_slot: np.ndarray     # int32  [total_keys] slot id per key
    offsets: np.ndarray      # int64  [R+1] record key spans
    dense: np.ndarray        # f32 [R, Dd]
    label: np.ndarray        # f32 [R]
    show: np.ndarray         # f32 [R]
    clk: np.ndarray          # f32 [R]
    uid: Optional[np.ndarray] = None     # int64 [R]
    rank: Optional[np.ndarray] = None    # int32 [R]
    cmatch: Optional[np.ndarray] = None  # int32 [R]
    timestamp: Optional[np.ndarray] = None  # int64 [R] (need_time_info)

    @property
    def num_records(self) -> int:
        return int(self.label.shape[0])

    @classmethod
    def from_records(cls, records: Sequence[SlotRecord],
                     dense_dim: int) -> "ColumnarRecords":
        r = len(records)
        counts = np.fromiter((rec.num_keys for rec in records),
                             dtype=np.int64, count=r)
        offsets = np.zeros(r + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        keys = (np.concatenate([rec.keys for rec in records])
                if r else np.empty(0, np.uint64))
        key_slot = np.empty(len(keys), dtype=np.int32)
        pos = 0
        for rec in records:
            sc = np.diff(rec.slot_offsets)
            n = rec.num_keys
            key_slot[pos:pos + n] = np.repeat(
                np.arange(len(sc), dtype=np.int32), sc)
            pos += n
        dense = np.zeros((r, dense_dim), np.float32)
        label = np.empty(r, np.float32)
        show = np.empty(r, np.float32)
        clk = np.empty(r, np.float32)
        uid = np.empty(r, np.int64)
        rank = np.empty(r, np.int32)
        cmatch = np.empty(r, np.int32)
        ts = np.empty(r, np.int64)
        for i, rec in enumerate(records):
            if rec.dense.size:
                dense[i, :rec.dense.size] = rec.dense
            label[i] = rec.label
            show[i] = rec.show
            clk[i] = rec.clk
            uid[i] = rec.uid
            rank[i] = rec.rank
            cmatch[i] = rec.cmatch
            ts[i] = rec.timestamp
        return cls(keys=keys, key_slot=key_slot, offsets=offsets,
                   dense=dense, label=label, show=show, clk=clk, uid=uid,
                   rank=rank, cmatch=cmatch, timestamp=ts)

    def shuffle(self, seed: int = 0) -> "ColumnarRecords":
        """Record-order permutation (one gather per pass, amortized)."""
        perm = np.random.default_rng(seed).permutation(self.num_records)
        counts = np.diff(self.offsets)[perm]
        new_off = np.zeros(self.num_records + 1, dtype=np.int64)
        np.cumsum(counts, out=new_off[1:])
        # gather each permuted record's key span
        src_idx = np.concatenate([
            np.arange(self.offsets[p], self.offsets[p + 1])
            for p in perm]) if len(self.keys) else np.empty(0, np.int64)
        opt = lambda a: None if a is None else a[perm]
        return ColumnarRecords(
            keys=self.keys[src_idx], key_slot=self.key_slot[src_idx],
            offsets=new_off, dense=self.dense[perm], label=self.label[perm],
            show=self.show[perm], clk=self.clk[perm],
            uid=opt(self.uid), rank=opt(self.rank), cmatch=opt(self.cmatch),
            timestamp=opt(self.timestamp))

    def batch(self, start: int, end: int, desc: DataFeedDesc,
              num_slots: int) -> SlotBatch:
        """Records [start, end) → SlotBatch (vectorized)."""
        bs = desc.batch_size
        n = end - start
        ks, ke = self.offsets[start], self.offsets[end]
        nk = int(ke - ks)
        keys = self.keys[ks:ke]
        counts = np.diff(self.offsets[start:end + 1])
        ins = np.repeat(np.arange(n, dtype=np.int64), counts)
        segs = (ins * num_slots + self.key_slot[ks:ke]).astype(np.int32)

        cap = desc.key_capacity(nk)
        pad_seg = bs * num_slots
        keys_p = np.zeros(cap, dtype=np.uint64)
        segs_p = np.full(cap, pad_seg, dtype=np.int32)
        keys_p[:nk] = keys
        segs_p[:nk] = segs

        def padrow(a: np.ndarray, fill: float = 0.0) -> np.ndarray:
            if n == bs:
                return np.ascontiguousarray(a[start:end])
            shape = (bs,) + a.shape[1:]
            out = np.full(shape, fill, a.dtype)
            out[:n] = a[start:end]
            return out

        trivial = (nk == n * num_slots
                   and bool(np.array_equal(segs,
                                           np.arange(nk, dtype=np.int32))))
        opt = lambda a: None if a is None else padrow(a)
        return SlotBatch(
            keys=keys_p, segments=segs_p, num_keys=nk,
            dense=padrow(self.dense), label=padrow(self.label),
            show=padrow(self.show), clk=padrow(self.clk),
            batch_size=bs, num_slots=num_slots, segments_trivial=trivial,
            uid=opt(self.uid), rank=opt(self.rank), cmatch=opt(self.cmatch),
            timestamp=opt(self.timestamp),
        )

    def batches(self, desc: DataFeedDesc, num_slots: int,
                drop_last: bool = False,
                start_batch: int = 0) -> Iterator[SlotBatch]:
        bs = desc.batch_size
        r = self.num_records
        for i in range(start_batch * bs, r, bs):
            j = min(i + bs, r)
            if j - i < bs and drop_last:
                return
            yield self.batch(i, j, desc, num_slots)
