"""Compact in-memory record storage.

Reference: paddle/fluid/framework/data_feed.h:97-433 — ``SlotValues`` (per-slot
values + offsets), ``SlotRecordObject`` (ins_id, search_id, rank/cmatch/
show/clk, slot_uint64_feasigns_, slot_float_feasigns_) and the arena
recycling pool ``SlotObjPool`` (:246,:309).

TPU-native difference: records are numpy-columnar from the moment of parsing
(one uint64 array + one offsets array per record covering *all* sparse slots),
so batch building is pure array concatenation — no per-slot python lists in
the hot path.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class SlotRecord:
    """One training instance.

    ``keys`` holds all sparse feasigns for all S sparse slots concatenated;
    ``slot_offsets`` (len S+1) delimits each slot's span inside ``keys``
    (exactly the SlotValues values/offsets layout, data_feed.h:97)."""

    keys: np.ndarray                 # uint64 [total_keys]
    slot_offsets: np.ndarray         # int32  [S+1]
    dense: np.ndarray                # float32 [dense_dim]
    label: float = 0.0
    show: float = 1.0
    clk: float = 0.0
    ins_id: str = ""
    search_id: int = 0
    rank: int = 0
    cmatch: int = 0
    uid: int = 0                     # user id for WuAUC / uid-merge
    timestamp: int = 0               # cur_timestamp_ (need_time_info path)

    def slot_keys(self, slot_idx: int) -> np.ndarray:
        return self.keys[self.slot_offsets[slot_idx]:self.slot_offsets[slot_idx + 1]]

    @property
    def num_keys(self) -> int:
        return int(self.keys.shape[0])


class SlotRecordPool:
    """Free-list recycler for parsed record batches.

    Reference: ``SlotObjPool``/``SlotRecordPool()`` (data_feed.h:246-433) —
    bounds allocator churn when passes load hundreds of millions of records.
    Python port keeps the API (get/put/clear, capacity from
    FLAGS.record_pool_max_size) so the pipeline code reads the same."""

    def __init__(self, max_size: Optional[int] = None) -> None:
        from paddlebox_tpu.config import FLAGS
        self._max = max_size if max_size is not None else FLAGS.record_pool_max_size
        self._free: List[SlotRecord] = []
        self._lock = threading.Lock()

    def get(self, n: int) -> List[SlotRecord]:
        with self._lock:
            take = min(n, len(self._free))
            out = self._free[len(self._free) - take:]
            del self._free[len(self._free) - take:]
        return out

    def put(self, recs: Sequence[SlotRecord]) -> None:
        with self._lock:
            room = self._max - len(self._free)
            if room > 0:
                self._free.extend(recs[:room])

    def size(self) -> int:
        with self._lock:
            return len(self._free)

    def clear(self) -> None:
        with self._lock:
            self._free.clear()


_GLOBAL_POOL: Optional[SlotRecordPool] = None


def global_record_pool() -> SlotRecordPool:
    global _GLOBAL_POOL
    if _GLOBAL_POOL is None:
        _GLOBAL_POOL = SlotRecordPool()
    return _GLOBAL_POOL
