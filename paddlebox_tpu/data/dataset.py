"""Dataset hierarchy: streaming slot datasets with pass lifecycle.

Reference: paddle/fluid/framework/data_set.{h,cc} — ``Dataset`` interface
(data_set.h:58: filelist, thread num, load/release, local/global shuffle),
``MultiSlotDataset``, ``PadBoxSlotDataset`` (:466 — pass dataset with
preload/wait, MergeInsKeys, MPI global shuffle) — and the Python surface
python/paddle/fluid/dataset.py (``DatasetFactory`` :24, ``InMemoryDataset``
:399, ``QueueDataset`` :1191, ``BoxPSDataset`` :1313).

TPU-native redesign: readers are threads feeding a Channel (no pipe
subprocess per reader unless requested); records are numpy-columnar;
the pass key-set for the embedding store (MergeInsKeys → PSAgent::AddKey)
is collected as a deduped uint64 np array during load; multi-host global
shuffle routes records by hash(ins_id) % nhosts through a pluggable
transport (single-host default is an in-proc identity).
"""

from __future__ import annotations

import glob as globlib
import hashlib
import random
import threading
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.data.batch import BatchBuilder, SlotBatch
from paddlebox_tpu.data.parser import get_parser
from paddlebox_tpu.data.record import SlotRecord
from paddlebox_tpu.data.schema import DataFeedDesc
from paddlebox_tpu.resilience import faults
from paddlebox_tpu.resilience.retry import RetryPolicy, TransientError
from paddlebox_tpu.utils import Channel, ChannelClosed, stat_add
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


def chain_digest(digest: str, files: Sequence[str]) -> str:
    """Left-fold a chained sha256 over ``files`` starting from
    ``digest`` (``""`` for an empty chain). Incremental by construction:
    ``chain_digest(chain_digest("", a), b) == chain_digest("", a + b)``
    — the stream cursor's folded-history fingerprint (a resumed run
    re-derives the whole chain from the filelist prefix and compares;
    trainer._adopt_cursor / QueueDataset.adopt_stream_cursor)."""
    for f in files:
        digest = hashlib.sha256(
            (digest + "\n" + str(f)).encode()).hexdigest()
    return digest


class PoisonedFileError(RuntimeError):
    """A file blew its per-file poison-record budget
    (``FLAGS.poison_budget_records``): more lines failed to parse than
    the budget tolerates — the file is treated as corrupt as a whole and
    becomes a quarantine candidate."""

    def __init__(self, path: str, bad: int, budget: int) -> None:
        super().__init__(
            f"{path}: {bad} unparseable record(s) exceeds the per-file "
            f"poison budget ({budget}) — file is poisoned")
        self.path = path
        self.bad = bad


class PoisonBudgetExceeded(RuntimeError):
    """The load quarantined more files than ``FLAGS.poison_budget_files``
    allows — the pass is broken beyond graceful degradation."""


def shard_filelist(files: Sequence[str], rank: Optional[int] = None,
                   world: Optional[int] = None) -> List[str]:
    """This host's round-robin slice of a file list; rank/world default to
    the launcher env (PBOX_RANK / PBOX_WORLD_SIZE)."""
    import os
    if rank is None:
        rank = int(os.environ.get("PBOX_RANK", "0"))
    if world is None:
        world = int(os.environ.get("PBOX_WORLD_SIZE", "1"))
    if world <= 1:
        return list(files)
    if rank >= world or rank < 0:
        raise ValueError(f"rank {rank} out of range for world {world}")
    return list(files[rank::world])


def _slots_shuffle_columnar(col, sel_slots: np.ndarray, d: np.ndarray):
    """Vectorized SlotsShuffle over a ColumnarRecords store: record i
    keeps its non-selected slots and takes the selected slots' feasigns
    from donor record ``d[i]``."""
    import dataclasses as _dc
    n = col.num_records
    if n == 0:
        return col
    counts = np.diff(col.offsets)
    rec_of_key = np.repeat(np.arange(n, dtype=np.int64), counts)
    mask = np.isin(col.key_slot, sel_slots)
    keep = ~mask
    # CSR of the selected-slot keys, per record
    mrec = rec_of_key[mask]
    mcount = np.bincount(mrec, minlength=n).astype(np.int64)
    moff = np.zeros(n + 1, np.int64)
    np.cumsum(mcount, out=moff[1:])
    glen = moff[d + 1] - moff[d]
    tot = int(glen.sum())
    # concat-of-ranges: indices into the masked arrays for each donor span
    base = np.cumsum(glen) - glen
    idx = (np.arange(tot, dtype=np.int64) - np.repeat(base, glen)
           + np.repeat(moff[d], glen))
    all_keys = np.concatenate([col.keys[keep], col.keys[mask][idx]])
    all_slot = np.concatenate([col.key_slot[keep], col.key_slot[mask][idx]])
    all_rec = np.concatenate([rec_of_key[keep],
                              np.repeat(np.arange(n, dtype=np.int64), glen)])
    order = np.lexsort((all_slot, all_rec))  # keys stay slot-grouped
    new_counts = np.bincount(all_rec, minlength=n).astype(np.int64)
    new_off = np.zeros(n + 1, np.int64)
    np.cumsum(new_counts, out=new_off[1:])
    return _dc.replace(col, keys=all_keys[order], key_slot=all_slot[order],
                       offsets=new_off)


class Dataset:
    """Base: file list + schema + threaded readers."""

    #: True when ``batches(start_batch=k)`` is deterministic — the
    #: in-memory datasets, whose batch order is a pure function of
    #: (filelist, seed). Streaming readers interleave threads, so the
    #: mid-pass resume cursor (docs/RESILIENCE.md) only applies here.
    supports_cursor_resume = False

    #: True when ``batches()`` may be called more than once and yields
    #: the SAME stream each time (the loaded order is frozen in
    #: memory). Streaming datasets consume their readers. Two-phase
    #: pass builds (the q8 streaming front, train/device_pass._front)
    #: key off this.
    supports_reiteration = False

    def __init__(self, desc: Optional[DataFeedDesc] = None) -> None:
        self.desc = desc or DataFeedDesc()
        self.filelist: List[str] = []
        self.thread_num = FLAGS.read_thread_num
        self._builder: Optional[BatchBuilder] = None
        # files isolated by the current/last load: [(path, error_repr)]
        self.quarantined_files: List[Tuple[str, str]] = []
        self._quarantine_lock = threading.Lock()
        # entries in quarantined_files that were PRESEEDED (a resumed
        # cursor's / the mesh consensus's prior decisions) rather than
        # discovered by this process — they must not consume the
        # FLAGS.poison_budget_files budget
        self._quarantine_preseeded = 0

    # --- config surface (mirrors dataset.py setters) ---
    def set_feed_desc(self, desc: DataFeedDesc) -> None:
        self.desc = desc
        self._builder = None

    def set_filelist(self, files: Sequence[str],
                     shard_by_rank: bool = False) -> None:
        """``shard_by_rank=True`` keeps only this host's round-robin slice
        of the file list (multi-host input sharding — each reference MPI
        rank reads its own file subset before the cross-rank global
        shuffle, SURVEY.md §7 Phase 4). Rank/world come from the
        launcher's env (distributed/launch.py)."""
        files = list(files)
        if shard_by_rank:
            files = shard_filelist(files)
        self.filelist = files

    def set_glob(self, pattern: str, shard_by_rank: bool = False) -> None:
        self.set_filelist(sorted(globlib.glob(pattern)),
                          shard_by_rank=shard_by_rank)

    def filelist_fingerprint(self) -> str:
        """Order-sensitive digest of the pass's file list — the resume
        cursor's identity check (checkpoint ``cursor.json``): a cursor
        only applies to a pass over the SAME files in the same order."""
        import hashlib
        h = hashlib.sha256()
        for p in self.filelist:
            h.update(p.encode())
            h.update(b"\0")
        return h.hexdigest()[:16]

    def set_batch_size(self, bs: int) -> None:
        self.desc.batch_size = bs

    def set_thread(self, n: int) -> None:
        self.thread_num = n

    @property
    def builder(self) -> BatchBuilder:
        if self._builder is None:
            self._builder = BatchBuilder(self.desc)
        return self._builder

    # --- failure isolation (docs/RESILIENCE.md) ---
    def _reset_quarantine(self) -> None:
        with self._quarantine_lock:
            self.quarantined_files = []
            self._quarantine_preseeded = 0

    def _quarantine(self, path: str, exc: BaseException) -> bool:
        """Try to isolate a per-file failure instead of killing the load.
        Returns False (caller must abort) when the failure is not
        file-scoped (consumer gone / interrupt) or the quarantine budget
        (``FLAGS.poison_budget_files``) is spent."""
        if not isinstance(exc, Exception) or isinstance(exc, ChannelClosed):
            return False  # consumer-side close / interrupt: not the file
        budget = FLAGS.poison_budget_files
        with self._quarantine_lock:
            mine = len(self.quarantined_files) - self._quarantine_preseeded
            if budget <= 0 or mine >= budget:
                return False
            self.quarantined_files.append((path, repr(exc)))
        log.warning("quarantined bad file %s: %r (budget %d/%d)", path,
                    exc, mine + 1, budget)
        stat_add("files_quarantined", 1)
        try:
            from paddlebox_tpu.obs.hub import get_hub
            hub = get_hub()
            hub.counter("pbox_files_quarantined_total",
                        "dataset files isolated after a failure").inc()
            if hub.active:
                hub.emit("file_quarantined", path=path, error=repr(exc))
        except Exception:
            log.debug("quarantine telemetry emit failed", exc_info=True)
        return True

    # --- reading ---
    def _read_files_into(self, files: Sequence[str], out: Channel,
                         n_threads: int) -> "ReaderGroup":
        parser_factory = lambda: get_parser(self.desc)
        file_ch: Channel[str] = Channel(capacity=len(files) + 1)
        for f in files:
            file_ch.put(f)
        file_ch.close()
        group = ReaderGroup()

        pipe_cmd = self.desc.pipe_command
        record_budget = FLAGS.poison_budget_records
        open_retry = RetryPolicy.from_flags(
            site="dataset.open", retryable=(OSError, TransientError))

        def parse_lines(parser, lines, path) -> tuple:
            n_ok = n_bad = 0
            for line in lines:
                line = faults.inject("parser.record", line, path=path)
                rec = parser.parse(line)
                if rec is None:
                    n_bad += 1
                    if 0 <= record_budget < n_bad:
                        raise PoisonedFileError(path, n_bad, record_budget)
                    continue
                out.put(rec)
                n_ok += 1
            return n_ok, n_bad

        def open_file(path: str, mode: str):
            # the fault seam sits INSIDE the retried callable, so an
            # injected (or real) transient open failure exercises the
            # retry before it can count against the quarantine budget
            faults.inject("dataset.open", path=path)
            return open(path, mode)

        def read_one(parser, path: str) -> None:
            faults.inject("reader.file", path=path)
            if pipe_cmd:
                # LoadIntoMemoryByCommand (data_feed.h:1674): the
                # file streams through a shell command; the parser
                # consumes its stdout
                import subprocess
                with open_retry.call(open_file, path, "rb") as fh:
                    proc = subprocess.Popen(
                        pipe_cmd, shell=True, stdin=fh,
                        stdout=subprocess.PIPE, text=True)
                    try:
                        n_ok, n_bad = parse_lines(parser, proc.stdout,
                                                  path)
                    except BaseException:
                        proc.kill()  # don't leak a blocked child
                        proc.wait()
                        raise
                    if proc.wait() != 0:
                        raise RuntimeError(
                            f"pipe_command {pipe_cmd!r} failed "
                            f"(rc={proc.returncode}) on {path}")
            else:
                with open_retry.call(open_file, path, "r") as fh:
                    n_ok, n_bad = parse_lines(parser, fh, path)
            stat_add("records_parsed", n_ok)
            stat_add("records_dropped", n_bad)
            if n_bad:
                from paddlebox_tpu.obs.hub import get_hub
                get_hub().counter(
                    "pbox_records_poisoned_total",
                    "records dropped as unparseable").inc(n_bad)

        def worker() -> None:
            from paddlebox_tpu.obs import trace
            trace.set_lane(trace.LANE_READER)
            parser = parser_factory()
            for path in file_ch:
                try:
                    with trace.span("read.file", file=path):
                        read_one(parser, path)
                except BaseException as e:
                    if isinstance(e, ChannelClosed):
                        # the CONSUMER cancelled the output channel
                        # (abandoned stream) — a clean shutdown, never a
                        # reader error
                        return
                    # isolate the failure to this file when the poison
                    # budget allows; surviving readers drain the rest of
                    # the file list
                    if self._quarantine(path, e):
                        continue
                    budget = FLAGS.poison_budget_files
                    if (budget > 0 and isinstance(e, Exception)
                            and not isinstance(e, ChannelClosed)):
                        # budget was on and is now spent: name the
                        # condition instead of surfacing whatever the
                        # last file happened to raise
                        wrapped = PoisonBudgetExceeded(
                            f"quarantine budget exhausted "
                            f"({budget} file(s), FLAGS.poison_budget_"
                            f"files) and {path} also failed: {e!r}")
                        wrapped.__cause__ = e
                        group.errors.append(wrapped)
                    else:
                        group.errors.append(e)
                    return

        group.threads = [threading.Thread(target=worker, daemon=True,
                                          name=f"pbox-reader-{i}")
                         for i in range(max(1, n_threads))]
        for t in group.threads:
            t.start()
        return group


class ReaderGroup:
    """Reader threads + their errors; join() re-raises the first failure so
    a dead reader never silently truncates a pass (per-file failures that
    fit the poison budget are quarantined by the dataset instead and never
    reach ``errors``)."""

    def __init__(self) -> None:
        self.threads: List[threading.Thread] = []
        self.errors: List[BaseException] = []

    def join(self) -> None:
        for t in self.threads:
            t.join()
        if self.errors:
            raise self.errors[0]


class InMemoryDataset(Dataset):
    """Load-everything-then-iterate dataset (reference dataset.py:399).

    Also collects the deduped pass key-set during load — the
    ``MergeInsKeys``/``PSAgentBase::AddKey`` role (data_set.cc:2423) that
    feeds the embedding store's per-pass working set."""

    def __init__(self, desc: Optional[DataFeedDesc] = None) -> None:
        super().__init__(desc)
        self.records: List[SlotRecord] = []
        # whether the loaded order is a pure function of (filelist,
        # seed): the native columnar load concatenates per-file chunks
        # in filelist order; the threaded per-line path's channel
        # fan-in order is timing-dependent unless ONE reader drains the
        # list. Cursor resume (docs/RESILIENCE.md) keys off this.
        self._det_order = True
        self._pass_keys: Optional[np.ndarray] = None
        self.columnar = None  # ColumnarRecords once columnarize()d
        self._fea_eval = False
        self._fea_eval_candidates = 10000
        self._merge_size: Optional[int] = None  # set_merge_by_lineid

    def load_into_memory(self) -> None:
        if not self.filelist:
            raise ValueError("set_filelist first")
        self._reset_quarantine()
        # native columnar fast path: only for the plain in-memory dataset —
        # subclasses (PaddleBoxDataset) run record-level pass protocols
        # (global shuffle / key merge) that need SlotRecord objects
        if (FLAGS.native_parse and type(self) is InMemoryDataset
                and not self.desc.pipe_command
                and self._merge_size is None
                and self._load_columnar_native()):
            return
        ch: Channel[SlotRecord] = Channel(capacity=FLAGS.channel_capacity,
                                          name="dataset.load_records")
        group = self._read_files_into(self.filelist, ch, self.thread_num)

        def closer() -> None:
            for t in group.threads:
                t.join()
            ch.close()

        threading.Thread(target=closer, daemon=True).start()
        self.records = list(ch)
        group.join()  # re-raise reader errors
        self._pass_keys = None
        self._det_order = self.thread_num <= 1
        log.info("loaded %d records from %d files",
                 len(self.records), len(self.filelist))
        if self.quarantined_files:
            log.warning("load quarantined %d file(s): %s",
                        len(self.quarantined_files),
                        [p for p, _ in self.quarantined_files])
        if self._merge_size is not None:
            self.merge_records_by_insid()

    def _load_columnar_native(self) -> bool:
        """Native bulk parse: file bytes → columnar arrays per file (C++,
        GIL released during the ctypes call so files parse in parallel),
        concatenated straight into the ColumnarRecords store — the whole
        per-record python layer is skipped. Returns False when the parser
        has no native fast path (per-line fallback runs instead)."""
        from concurrent.futures import ThreadPoolExecutor

        from paddlebox_tpu.data.columnar import ColumnarRecords
        parser = get_parser(self.desc)

        def parse_guarded(path: str):
            """Per-file isolation for the native path: a file whose bulk
            parse fails is quarantined (budget permitting) instead of
            killing the load; returns None for a quarantined file."""
            try:
                faults.inject("dataset.open", path=path)
                return parser.parse_file_columnar(path)
            except Exception as e:
                if self._quarantine(path, e):
                    return None
                if FLAGS.poison_budget_files > 0:
                    raise PoisonBudgetExceeded(
                        f"quarantine budget exhausted "
                        f"({FLAGS.poison_budget_files} file(s), FLAGS."
                        f"poison_budget_files) and {path} also failed: "
                        f"{e!r}") from e
                raise

        # probe the first healthy file for a native fast path at all;
        # on fallback the per-line path re-reads EVERY file, so any
        # quarantine state this aborted attempt accumulated is reset
        # (budget returned, no stale/duplicate entries)
        probe = None
        rest: List[str] = []
        for i, path in enumerate(self.filelist):
            probe = parse_guarded(path)
            if probe is not None:
                rest = list(self.filelist[i + 1:])
                break
            if not self.quarantined_files or \
                    self.quarantined_files[-1][0] != path:
                self._reset_quarantine()
                return False  # no native parser — per-line fallback
        else:
            self._reset_quarantine()
            return False  # every file quarantined (or list empty)
        with ThreadPoolExecutor(max(1, self.thread_num)) as ex:
            chunks = [probe] + [c for c in ex.map(parse_guarded, rest)
                                if c is not None]
        n_rec = sum(len(c["label"]) for c in chunks)
        n_drop = sum(int(c.get("dropped", 0)) for c in chunks)
        offsets = np.zeros(n_rec + 1, np.int64)
        pos, kpos = 0, 0
        for c in chunks:
            m = len(c["label"])
            offsets[pos + 1:pos + m + 1] = c["offsets"][1:] + kpos
            pos += m
            kpos += int(c["offsets"][-1])
        cat = lambda f: (np.concatenate([c[f] for c in chunks]) if chunks
                         else np.empty(0))
        self.columnar = ColumnarRecords(
            keys=cat("keys"), key_slot=cat("key_slot"), offsets=offsets,
            dense=cat("dense"), label=cat("label"), show=cat("show"),
            clk=cat("clk"),
            # text formats carry no metadata columns — default like the
            # record path does (SlotRecord field defaults)
            uid=np.zeros(n_rec, np.int64), rank=np.zeros(n_rec, np.int32),
            cmatch=np.zeros(n_rec, np.int32),
            timestamp=np.zeros(n_rec, np.int64))
        self.records = []
        self._pass_keys = None
        self._det_order = True  # chunks concatenate in filelist order
        stat_add("records_parsed", n_rec)
        stat_add("records_dropped", n_drop)
        log.info("native-parsed %d records from %d files (columnar, "
                 "%d lines dropped)", n_rec, len(self.filelist), n_drop)
        return True

    def columnarize(self, release_records: bool = True) -> None:
        """Convert the loaded pass to the columnar store (data/columnar.py)
        for vectorized batch building; amortized once per pass."""
        if self.columnar is not None and not self.records:
            return  # already columnar (native load path)
        from paddlebox_tpu.data.columnar import ColumnarRecords
        self.columnar = ColumnarRecords.from_records(
            self.records, self.desc.dense_dim)
        if release_records:
            self.records = []

    @property
    def supports_cursor_resume(self) -> bool:
        """True when ``batches(start_batch=k)`` reproduces the original
        stream: the native columnar load (filelist-order concat) always
        does; the threaded per-line path only with ONE reader thread —
        multi-thread channel fan-in order is timing-dependent, so a
        resumed process could not rebuild the same batch order and the
        cursor would splice two different streams."""
        return self._det_order

    # once loaded, the record/columnar order is frozen in memory, so
    # batches() replays the same stream regardless of how deterministic
    # the LOAD itself was (supports_cursor_resume is about reloading in
    # a fresh process; this is about re-walking this one)
    supports_reiteration = True

    def release_memory(self) -> None:
        self.records = []
        self.columnar = None
        self._pass_keys = None

    def local_shuffle(self, seed: Optional[int] = None) -> None:
        if self.columnar is not None:
            self.columnar = self.columnar.shuffle(
                FLAGS.seed if seed is None else seed)
            return
        rng = random.Random(FLAGS.seed if seed is None else seed)
        rng.shuffle(self.records)

    def global_shuffle(self, shuffler: Optional["Shuffler"] = None,
                       seed: Optional[int] = None) -> None:
        """Cross-host record exchange by hash — data_set.cc:2573 ShuffleData.
        Single-host default degenerates to local_shuffle. Must run BEFORE
        columnarize(): the exchange moves record objects between hosts."""
        if shuffler is not None:
            if self.columnar is not None:
                raise RuntimeError(
                    "global_shuffle(shuffler) needs record objects, but "
                    "this dataset is already columnar (columnarize() was "
                    "called, or the native parse fast path loaded it "
                    "columnar directly — set FLAGS.native_parse=False "
                    "before load_into_memory for cross-host exchange)")
            self.records = shuffler.exchange(self.records)
            self._pass_keys = None
        self.local_shuffle(seed)

    def set_merge_by_lineid(self, merge_size: int = 2) -> None:
        """Merge records sharing an ins_id after load (reference
        dataset.py ``set_merge_by_lineid``; MergeByInsId data_set.cc:1517).
        Applied by ``merge_records_by_insid`` or automatically at the end
        of ``load_into_memory`` when set."""
        self._merge_size = int(merge_size)

    def merge_records_by_insid(self) -> int:
        """Run the ins_id merge now; returns the dropped-record count."""
        from paddlebox_tpu.data.pv import merge_by_insid
        if self.columnar is not None:
            raise RuntimeError("merge_by_insid needs record objects; call "
                               "it before columnarize()")
        ms = self._merge_size if self._merge_size is not None else 2
        self.records, dropped = merge_by_insid(
            self.records, ms, len(self.desc.sparse_slots))
        if dropped:
            log.warning("merge_by_insid dropped %d records", dropped)
        stat_add("records_dropped_by_merge", dropped)
        self._pass_keys = None
        return dropped

    def set_fea_eval(self, record_candidate_size: int = 10000,
                     fea_eval: bool = True) -> None:
        """Enable feature-evaluation mode — precondition for
        ``slots_shuffle`` (reference dataset.py:143 ``set_fea_eval``;
        ``slots_shuffle_fea_eval_`` guard, data_set.cc:1858)."""
        self._fea_eval = fea_eval
        self._fea_eval_candidates = int(record_candidate_size)

    def slots_shuffle(self, slots: Sequence) -> None:
        """Replace the chosen sparse slots' feasigns in every record with
        the feasigns of a RANDOM OTHER record, in place — destroying the
        slot's per-instance signal while preserving its marginal
        distribution (feature-importance eval; MultiSlotDataset::
        SlotsShuffle + GetRandomData, data_set.cc:1713-1881).

        ``slots`` holds sparse slot names or indices. Works on both the
        record-object store and the columnar store."""
        if not self._fea_eval:
            raise RuntimeError(
                "fea eval mode off, need set_fea_eval() for slots_shuffle")
        sel = np.array(
            [self.desc.sparse_slot_index(s) if isinstance(s, str) else int(s)
             for s in slots], dtype=np.int64)
        rng = np.random.default_rng(FLAGS.seed)
        n = len(self.columnar.label) if self.columnar is not None \
            else len(self.records)
        # donor choice: a permutation when the candidate pool spans the
        # pass; a capped random pool otherwise (RecordCandidateList
        # reservoir semantics — set_fea_eval's record_candidate_size)
        cap = self._fea_eval_candidates
        if cap >= n:
            perm = rng.permutation(n)
        else:
            pool = rng.choice(n, size=cap, replace=False)
            perm = pool[rng.integers(0, cap, size=n)]
        if self.columnar is not None:
            self.columnar = _slots_shuffle_columnar(self.columnar, sel,
                                                    perm)
        elif self.records:
            sel_set = set(int(s) for s in sel)
            num_slots = len(self.desc.sparse_slots)
            # snapshot donor spans BEFORE mutating (GetRandomData reads the
            # originals, data_set.cc:1720)
            donor_spans = [
                {s: self.records[perm[i]].slot_keys(s).copy()
                 for s in sel_set} for i in range(n)]
            for i, rec in enumerate(self.records):
                chunks, offs = [], [0]
                for s in range(num_slots):
                    span = (donor_spans[i][s] if s in sel_set
                            else rec.slot_keys(s))
                    chunks.append(span)
                    offs.append(offs[-1] + len(span))
                rec.keys = (np.concatenate(chunks) if chunks
                            else np.empty(0, np.uint64))
                rec.slot_offsets = np.array(offs, dtype=np.int32)
        self._pass_keys = None

    def pass_keys(self) -> np.ndarray:
        """Deduped uint64 key-set of the loaded pass."""
        if self._pass_keys is None:
            if self.columnar is not None:
                self._pass_keys = np.unique(self.columnar.keys)
            elif self.records:
                all_keys = np.concatenate([r.keys for r in self.records])
                self._pass_keys = np.unique(all_keys)
            else:
                self._pass_keys = np.empty(0, dtype=np.uint64)
        return self._pass_keys

    def pass_key_slots(self):
        """(unique keys, slot id of each) — the pass working set WITH
        slots, for tables whose routing needs the slot (multi-mf tiered:
        a key's dim class is its slot's property).

        CONTRACT: a key value must belong to exactly ONE slot (CTR
        feasigns are slot-qualified — the native parser bakes
        ``(slot+1) << 52`` into every key). A key seen under two slots
        would stage into only one dim class and silently reset its other
        class's values each pass, so that case raises here."""
        def check_and_split(all_keys, all_slots):
            keys, first = np.unique(all_keys, return_index=True)
            pairs = np.unique(np.stack(
                [all_keys, all_slots.astype(np.uint64)]), axis=1)
            if pairs.shape[1] != len(keys):
                raise ValueError(
                    "pass_key_slots: some key value appears under more "
                    "than one slot — multi-mf routing requires "
                    "slot-qualified keys (one slot per key value)")
            return keys, all_slots[first].astype(np.int32)

        if self.columnar is not None:
            return check_and_split(self.columnar.keys,
                                   self.columnar.key_slot)
        if self.records:
            all_keys = np.concatenate([r.keys for r in self.records])
            all_slots = np.concatenate([
                np.repeat(np.arange(len(r.slot_offsets) - 1,
                                    dtype=np.int32),
                          np.diff(r.slot_offsets))
                for r in self.records])
            return check_and_split(all_keys, all_slots)
        return (np.empty(0, dtype=np.uint64), np.empty(0, dtype=np.int32))

    def __len__(self) -> int:
        if self.columnar is not None:
            return self.columnar.num_records
        return len(self.records)

    def batches(self, drop_last: bool = False,
                start_batch: int = 0) -> Iterator[SlotBatch]:
        """``start_batch=k`` skips the first k batches WITHOUT building
        them (cursor resume: the skipped prefix was already trained
        before the preemption — docs/RESILIENCE.md)."""
        if self.columnar is not None:
            yield from self.columnar.batches(
                self.desc, len(self.desc.sparse_slots), drop_last,
                start_batch=start_batch)
            return
        bs = self.desc.batch_size
        n = len(self.records)
        for i in range(start_batch * bs, n, bs):
            chunk = self.records[i:i + bs]
            if len(chunk) < bs and drop_last:
                return
            yield self.builder.build(chunk)


class QueueDataset(Dataset):
    """Streaming dataset: batches come off the reader channel without
    materializing the pass (reference dataset.py:1191).

    **Windowed streaming** (``FLAGS.stream_window_files > 0``,
    docs/RESILIENCE.md §Streaming): the filelist is consumed in bounded
    windows of N files. No record crosses a window boundary (the tail
    batch of each window is flushed short), fully-consumed files are
    tracked across ``batches()`` calls, and the trainer's v2 stream
    cursor (``Trainer._pass_cursor`` → ``cursor.json``) records the
    completed-file set plus the open window — a restarted process skips
    completed files and REPLAYS the open window, so a preempted
    unbounded stream loses no completed-window data and re-trains at
    most one window (**at-least-once** for the open window,
    exactly-once for completed windows; never exactly-once end to end).
    ``supports_cursor_resume`` is therefore True in windowed mode only;
    the legacy unwindowed stream keeps refusing ``start_batch != 0``.

    Window completion is tied to CONSUMPTION, not read-ahead: the
    generator records, per window, the yield count of its final batch
    (``mark``), and :meth:`stream_cursor_state` only counts a window
    completed once the trainer reports that many batches TRAINED — a
    prefetch pipeline pulling batches ahead of training can never get a
    half-trained window declared complete."""

    def __init__(self, desc: Optional[DataFeedDesc] = None) -> None:
        super().__init__(desc)
        # --- windowed streaming state (survives across batches() calls;
        # guarded by _stream_lock: the generator runs on a prefetch
        # producer thread while the trainer snapshots cursors) ---
        self._stream_lock = threading.Lock()
        self._files_completed: List[str] = []  # fully-consumed files
        # cursor compaction (fold_completed_history): the first
        # _folded_count entries of _files_completed are ALSO summarized
        # by the chained fingerprint — serialized cursors carry only
        # {count, sha256} for them, not the names
        self._folded_count = 0
        self._folded_digest = ""
        self._windows: List[dict] = []   # open pass: {"files", "mark"}
        self._skip_files: set = set()    # preseeded quarantine decisions
        self._replay_files: List[str] = []  # adopted open window
        self.windows_completed = 0
        self.files_replayed = 0

    # ---- windowed-mode surface (docs/RESILIENCE.md §Streaming) ----
    @property
    def windowed(self) -> bool:
        return FLAGS.stream_window_files > 0

    @property
    def supports_cursor_resume(self) -> bool:
        """True only in windowed mode — and then with the AT-LEAST-ONCE
        caveat: resume replays the whole open window, it does not splice
        back into a thread-interleaved batch stream (which is why the
        unwindowed stream still refuses)."""
        return self.windowed

    @property
    def files_completed(self) -> List[str]:
        """Fully-consumed files, in consumption order. Folding is tied
        to CONSUMPTION (``note_batches_consumed``, called by the
        trainer per trained batch), never to generator read-ahead — an
        abandoned/preempted stream leaves its unconsumed windows
        unfolded, so they replay."""
        with self._stream_lock:
            return list(self._files_completed)

    def fold_completed_history(self) -> int:
        """Compact the cursor's completed-file history: fold every file
        completed so far into a count + chained ``chain_digest``
        fingerprint, so serialized cursors stop growing O(files
        consumed) on an always-on stream (ROADMAP item 5; the PR 6
        known limit). The trainer calls this right AFTER a
        stream-boundary checkpoint publishes — every file folded here
        is recorded BY NAME in that durable boundary cursor, and
        rollback never reaches past the latest boundary, so the names
        are never needed explicitly again. The in-memory list keeps the
        full history (``files_completed`` / per-window filelist
        narrowing are unchanged); only ``stream_cursor_state``'s
        serialized view shrinks. Returns the total folded count."""
        with self._stream_lock:
            new = self._files_completed[self._folded_count:]
            if new:
                self._folded_digest = chain_digest(self._folded_digest,
                                                   new)
                self._folded_count = len(self._files_completed)
            return self._folded_count

    def note_batches_consumed(self, consumed: int) -> None:
        """Trainer callback: ``consumed`` batches of the current
        ``batches()`` call have been TRAINED — fold every window whose
        final batch lies in that prefix into the completed set. Without
        this call (a raw ``batches()`` drain with no trainer) nothing
        folds and a later ``batches()`` call re-streams the filelist,
        like the legacy unwindowed dataset."""
        if not self.windowed:
            return
        with self._quarantine_lock:
            quarantined = {p for p, _ in self.quarantined_files}
        with self._stream_lock:
            while self._windows:
                w = self._windows[0]
                if w["mark"] is None or w["mark"] > consumed:
                    break
                self._files_completed.extend(
                    f for f in w["files"] if f not in quarantined)
                self.windows_completed += 1
                self._windows.pop(0)

    def pending_files(self) -> List[str]:
        """Files not yet consumed, not already dispatched into a window
        of the open pass, and not excluded by a (preseeded or
        discovered) quarantine decision — in filelist order."""
        with self._stream_lock:
            done = set(self._files_completed)
            for w in self._windows:
                done.update(w["files"])
        with self._quarantine_lock:
            skip = self._skip_files | {p for p, _ in self.quarantined_files}
        return [f for f in self.filelist if f not in done
                and f not in skip]

    def stream_cursor_state(self, consumed_batches: Optional[int] = None
                            ) -> Optional[dict]:
        """The dataset half of the v2 stream cursor: the files fully
        consumed once ``consumed_batches`` batches of the CURRENT
        ``batches()`` call have been trained, plus the open window those
        batches stop inside (empty at a stream boundary).
        ``consumed_batches=None`` means "between passes" (boundary
        cursor). Returns None when not in windowed mode."""
        if not self.windowed:
            return None
        with self._quarantine_lock:
            quarantined = {p for p, _ in self.quarantined_files}
        with self._stream_lock:
            completed = list(self._files_completed)
            n_windows = int(self.windows_completed)
            window: List[str] = []
            for w in self._windows:
                mark = w["mark"]
                if (mark is not None and consumed_batches is not None
                        and mark <= consumed_batches):
                    completed.extend(f for f in w["files"]
                                     if f not in quarantined)
                    n_windows += 1
                else:
                    window = list(w["files"])
                    break
            state = {"windowed": True,
                     # folded history is carried as count+fingerprint,
                     # not names — the cursor stays O(files since the
                     # last boundary checkpoint)
                     "files_completed": completed[self._folded_count:],
                     "window_files": window,
                     "windows_completed": n_windows}
            if self._folded_count:
                state["files_folded"] = {
                    "count": int(self._folded_count),
                    "sha256": self._folded_digest,
                }
            return state

    def adopt_stream_cursor(self, stream: dict,
                            quarantined: Sequence[str] = ()) -> None:
        """Restore the stream position from a v2 cursor's ``stream``
        block: completed files will be skipped, the open window replays
        (at-least-once), and the cursor's quarantine decisions are
        preseeded so the resumed run drops the SAME files the preempted
        one did (restart/consensus parity).

        A ``files_folded`` block (compacted history) is expanded from
        THIS dataset's filelist: the first ``count`` non-quarantined
        files must reproduce the chained fingerprint — a mismatch
        raises ``ValueError`` (the filelist no longer extends the
        folded consumption order), never a silent skip of the wrong
        files."""
        completed = [str(f) for f in stream.get("files_completed", [])]
        window = [str(f) for f in stream.get("window_files", [])]
        fold = stream.get("files_folded")
        count, digest, prefix = 0, "", []
        if isinstance(fold, dict) and int(fold.get("count", 0)) > 0:
            count = int(fold["count"])
            digest = str(fold.get("sha256", ""))
            skip = {str(f) for f in quarantined}
            eligible = [f for f in self.filelist if f not in skip]
            prefix = eligible[:count]
            if len(prefix) < count \
                    or chain_digest("", prefix) != digest:
                raise ValueError(
                    f"stream cursor folded history ({count} files) does "
                    "not match this filelist — its leading files no "
                    "longer reproduce the folded fingerprint; resume "
                    "with the original filelist order or roll back to a "
                    "pass boundary")
        with self._stream_lock:
            self._files_completed = prefix + completed
            self._folded_count = count
            self._folded_digest = digest
            self._windows = []
            self._replay_files = window
            self.windows_completed = int(
                stream.get("windows_completed", 0))
        self.preseed_quarantine(quarantined)

    def preseed_quarantine(self, files: Sequence[str]) -> None:
        """Adopt prior quarantine decisions (a resumed cursor's, or the
        mesh consensus union) WITHOUT consuming the local poison budget:
        the files are excluded from future windows and reported in
        ``quarantined_files`` so later cursors carry them forward."""
        with self._quarantine_lock:
            have = {p for p, _ in self.quarantined_files}
            for f in files:
                f = str(f)
                self._skip_files.add(f)
                if f in have:
                    continue
                self.quarantined_files.append(
                    (f, "preseeded quarantine (resume cursor / mesh "
                        "consensus)"))
                self._quarantine_preseeded += 1

    # ---- batch streams -------------------------------------------------
    def batches(self, start_batch: int = 0) -> Iterator[SlotBatch]:
        if self.windowed:
            if start_batch:
                raise ValueError(
                    "windowed QueueDataset resumes by FILE WINDOW (the "
                    "v2 stream cursor), not by batch index — resume via "
                    "Trainer.run_pass/train_stream, which adopts the "
                    "cursor and replays the open window at-least-once")
            return self._windowed_batches()
        if start_batch:
            raise ValueError(
                "QueueDataset streams through threaded readers — batch "
                "order is not deterministic, so cursor resume "
                "(start_batch) needs an in-memory dataset, or windowed "
                "streaming (FLAGS.stream_window_files > 0) with its "
                "at-least-once window replay")
        if not self.filelist:
            raise ValueError("set_filelist first")
        self._reset_quarantine()
        return self._stream_files(self.filelist)

    def _stream_files(self, files: Sequence[str]) -> Iterator[SlotBatch]:
        """Stream ``files`` through the reader group as batches, flushing
        the short tail batch at the end. Reader errors surface within one
        batch of the failure (the group is polled every loop, not only at
        stream end), and an abandoned generator cancels the channel and
        joins every reader thread before returning — no hot channel or
        orphan reader outlives the consumer (the prefetch_iter contract,
        docs/RESILIENCE.md)."""
        bs = self.desc.batch_size
        ch: Channel[SlotRecord] = Channel(capacity=FLAGS.channel_capacity,
                                          block_size=bs,
                                          name="dataset.stream_records")
        group = self._read_files_into(files, ch, self.thread_num)

        def closer() -> None:
            for t in group.threads:
                t.join()
            ch.close()

        closer_th = threading.Thread(target=closer, daemon=True)
        closer_th.start()
        try:
            pending: List[SlotRecord] = []
            while True:
                if group.errors:
                    # a reader died (budget spent / fatal): raise within
                    # one batch instead of silently draining the channel
                    raise group.errors[0]
                got = ch.get_batch(bs - len(pending))
                if not got and ch.closed and len(ch) == 0:
                    break
                pending.extend(got)
                if len(pending) >= bs:
                    yield self.builder.build(pending[:bs])
                    pending = pending[bs:]
            if pending:
                yield self.builder.build(pending)
            group.join()  # surface reader errors at stream end
        finally:
            # consumer-abandon cleanup: without this, readers blocked on
            # ch.put (and the closer waiting on them) outlive the
            # abandoned generator and the channel stays hot
            ch.cancel()
            for t in group.threads:
                t.join()
            closer_th.join()

    def _windowed_batches(self) -> Iterator[SlotBatch]:
        if not self.filelist:
            raise ValueError("set_filelist first")
        wsize = FLAGS.stream_window_files
        with self._quarantine_lock:
            # per-LOAD budget semantics (FLAGS.poison_budget_files is
            # "per load", config.py): fold prior loads' discovered
            # quarantines into the preseeded count — still sticky
            # (pending_files keeps excluding them) but no longer charged
            # against this load's budget, so an always-on stream is not
            # slowly exhausted by bad files weeks apart
            self._quarantine_preseeded = len(self.quarantined_files)
        with self._stream_lock:
            self._windows = []  # fresh pass over the pending files
            replay = set(self._replay_files)
            self._replay_files = []
        yielded = 0
        while True:
            pending = self.pending_files()
            if not pending:
                break
            files = pending[:wsize]
            win = {"files": list(files), "mark": None}
            with self._stream_lock:
                self._windows.append(win)
                widx = self.windows_completed + len(self._windows) - 1
            hit = [f for f in files if f in replay]
            if hit:
                replay -= set(hit)
                self.files_replayed += len(hit)
                self._note_replay(hit)
            # chaos seam: a seeded fault here breaks the window dispatch
            # deterministically (scripts/chaos_check.py recovery drill)
            faults.inject("stream.window", path=files[0], window=widx,
                          files=len(files))
            for batch in self._stream_files(files):
                yielded += 1
                yield batch
            # consumption-tied completion: the window only counts as
            # complete once the trainer has TRAINED `yielded` batches
            # (note_batches_consumed folds it; stream_cursor_state
            # reads unfolded marks the same way)
            with self._stream_lock:
                win["mark"] = yielded

    def _note_replay(self, files: Sequence[str]) -> None:
        log.warning("stream resume: replaying open window "
                    "(at-least-once): %s", list(files))
        try:
            from paddlebox_tpu.obs.hub import get_hub
            hub = get_hub()
            hub.counter("pbox_stream_replayed_files_total",
                        "open-window files replayed after a stream "
                        "resume (at-least-once)").inc(len(files))
            if hub.active:
                hub.emit("stream_replay", files=list(files))
        except Exception:
            log.debug("stream replay telemetry emit failed",
                      exc_info=True)


class PaddleBoxDataset(InMemoryDataset):
    """Pass-lifecycle dataset — the ``BoxPSDataset``/``PadBoxSlotDataset``
    surface (dataset.py:1313,:1446): double-buffered preload of pass k+1
    while pass k trains, begin/end pass hooks that stage the embedding
    store's working set (SURVEY.md §3.3)."""

    def __init__(self, desc: Optional[DataFeedDesc] = None) -> None:
        super().__init__(desc)
        self._preload_thread: Optional[threading.Thread] = None
        self._preload_exc: Optional[BaseException] = None
        self._date: Optional[str] = None
        self.pass_id = 0
        # hooks the trainer/PS wires up (BoxHelper Begin/EndFeedPass etc.)
        self.on_begin_pass: Optional[Callable[["PaddleBoxDataset"], None]] = None
        self.on_end_pass: Optional[Callable[["PaddleBoxDataset", bool], None]] = None

    def set_date(self, date: str) -> None:
        self._date = date

    @property
    def date(self) -> Optional[str]:
        return self._date

    def preload_into_memory(self) -> None:
        if self._preload_thread is not None:
            raise RuntimeError("preload already in flight")
        self._preload_exc = None

        def run() -> None:
            try:
                self.load_into_memory()
            except BaseException as e:  # surfaced in wait_preload_done
                self._preload_exc = e

        self._preload_thread = threading.Thread(target=run, daemon=True)
        self._preload_thread.start()

    def wait_preload_done(self) -> None:
        if self._preload_thread is None:
            return
        self._preload_thread.join()
        self._preload_thread = None
        if self._preload_exc is not None:
            raise self._preload_exc

    def begin_pass(self) -> None:
        self.pass_id += 1
        if self.on_begin_pass is not None:
            self.on_begin_pass(self)

    def end_pass(self, need_save_delta: bool = False) -> None:
        if self.on_end_pass is not None:
            self.on_end_pass(self, need_save_delta)
        self.release_memory()


class Shuffler:
    """Cross-host record exchange transport (PaddleShuffler analogue,
    data_set.cc:2573). Implementations route each record to
    ``hash(record) % world_size`` and return the records received."""

    def exchange(self, records: List[SlotRecord]) -> List[SlotRecord]:
        raise NotImplementedError


class DatasetFactory:
    """Reference: dataset.py:24."""

    _KINDS = {
        "InMemoryDataset": InMemoryDataset,
        "QueueDataset": QueueDataset,
        "PaddleBoxDataset": PaddleBoxDataset,
        "BoxPSDataset": PaddleBoxDataset,       # alias for migration
        "PadBoxSlotDataset": PaddleBoxDataset,  # alias for migration
    }

    def create_dataset(self, kind: str = "QueueDataset",
                       desc: Optional[DataFeedDesc] = None) -> Dataset:
        try:
            return self._KINDS[kind](desc)
        except KeyError:
            raise KeyError(
                f"unknown dataset kind {kind!r}; one of {sorted(self._KINDS)}"
            ) from None
