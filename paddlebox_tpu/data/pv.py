"""Page-view (PV) merge batching + rank_offset construction.

Reference: PadBoxSlotDataset::PreprocessInstance (data_set.cc:2825) sorts
records by search_id and merges consecutive equal-sid records into one
PvInstance; PaddleBoxDataFeed::GetRankOffset (data_feed.cc:1855) /
CopyRankOffsetKernel (data_feed.cu:1319) then build the ``rank_offset``
int matrix [ins_num, 2*max_rank+1] consumed by the rank_attention op:

- col 0: the ad's own 1-based rank, valid only when cmatch ∈ {222, 223}
  and 0 < rank <= max_rank; else -1.
- for every co-shown ad k in the same PV with valid rank r, cols
  (2*(r-1)+1, 2*(r-1)+2) hold (r, global-row-index-of-k). Rows whose own
  rank is invalid keep -1 everywhere past col 0.

TPU-native: the matrix is built host-side in numpy (it is pure data prep,
shape [B, 7] for max_rank=3) and padded to the static batch size so the
jit step never sees ragged shapes; padding rows are all -1 which
rank_attention treats as "contribute nothing".
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from paddlebox_tpu.data.batch import BatchBuilder, SlotBatch
from paddlebox_tpu.data.record import SlotRecord
from paddlebox_tpu.data.schema import DataFeedDesc

VALID_CMATCH = (222, 223)


def group_by_search_id(records: Sequence[SlotRecord]) -> List[List[SlotRecord]]:
    """Stable sort by search_id, then merge consecutive equal sids into one
    PV (mirrors PreprocessInstance's merge_by_sid path)."""
    order = sorted(range(len(records)), key=lambda i: records[i].search_id)
    pvs: List[List[SlotRecord]] = []
    last_sid = None
    for i in order:
        r = records[i]
        if last_sid is None or r.search_id != last_sid:
            pvs.append([r])
            last_sid = r.search_id
        else:
            pvs[-1].append(r)
    return pvs


def group_by_uid(records: Sequence[SlotRecord]) -> List[List[SlotRecord]]:
    """Group records by uid (merge_by_uid path: user timeline grouping)."""
    buckets: Dict[int, List[SlotRecord]] = {}
    for r in records:
        buckets.setdefault(r.uid, []).append(r)
    return list(buckets.values())


def _valid_rank(rank: int, cmatch: int, max_rank: int) -> int:
    if cmatch in VALID_CMATCH and 0 < rank <= max_rank:
        return rank
    return -1


def build_rank_offset(pvs: Sequence[Sequence[SlotRecord]],
                      max_rank: int = 3,
                      pad_to: int = 0) -> np.ndarray:
    """int32 [max(ins_num, pad_to), 2*max_rank+1], padding rows all -1."""
    ins_num = sum(len(pv) for pv in pvs)
    rows = max(ins_num, pad_to)
    cols = 2 * max_rank + 1
    mat = np.full((rows, cols), -1, dtype=np.int32)

    base = 0
    for pv in pvs:
        vr = np.array([_valid_rank(r.rank, r.cmatch, max_rank) for r in pv],
                      dtype=np.int32)
        mat[base:base + len(pv), 0] = vr
        valid_k = np.nonzero(vr > 0)[0]
        for j in range(len(pv)):
            if vr[j] <= 0:
                continue
            for k in valid_k:
                m = vr[k] - 1
                mat[base + j, 2 * m + 1] = vr[k]
                mat[base + j, 2 * m + 2] = base + k
        base += len(pv)
    return mat


class PvBatchBuilder:
    """PV-merged minibatches: ``pv_batch_size`` PVs per batch, flattened ads
    padded to ``desc.batch_size`` rows, plus the rank_offset matrix.

    Reference flow: PaddleBoxDataFeed::PutToFeedVec(pv_vec)
    (data_feed.cc:1915) = GetRankOffset + flatten ads into the normal
    instance batch path.
    """

    def __init__(self, desc: DataFeedDesc, max_rank: int = 3) -> None:
        if desc.pv_batch_size <= 0:
            raise ValueError("desc.pv_batch_size must be > 0 for PV batching")
        self.desc = desc
        self.max_rank = max_rank
        self._builder = BatchBuilder(desc)

    def batches(self, records: Sequence[SlotRecord]
                ) -> List[Tuple[SlotBatch, np.ndarray]]:
        pvs = group_by_search_id(records)
        out: List[Tuple[SlotBatch, np.ndarray]] = []
        pvb = self.desc.pv_batch_size
        for i in range(0, len(pvs), pvb):
            chunk = pvs[i:i + pvb]
            flat = [r for pv in chunk for r in pv]
            if len(flat) > self.desc.batch_size:
                raise ValueError(
                    f"PV chunk flattens to {len(flat)} ads > batch_size "
                    f"{self.desc.batch_size}; lower pv_batch_size")
            batch = self._builder.build(flat)
            ro = build_rank_offset(chunk, self.max_rank,
                                   pad_to=self.desc.batch_size)
            out.append((batch, ro))
        return out
