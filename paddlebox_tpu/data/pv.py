"""Page-view (PV) merge batching + rank_offset construction.

Reference: PadBoxSlotDataset::PreprocessInstance (data_set.cc:2825) sorts
records by search_id and merges consecutive equal-sid records into one
PvInstance; PaddleBoxDataFeed::GetRankOffset (data_feed.cc:1855) /
CopyRankOffsetKernel (data_feed.cu:1319) then build the ``rank_offset``
int matrix [ins_num, 2*max_rank+1] consumed by the rank_attention op:

- col 0: the ad's own 1-based rank, valid only when cmatch ∈ {222, 223}
  and 0 < rank <= max_rank; else -1.
- for every co-shown ad k in the same PV with valid rank r, cols
  (2*(r-1)+1, 2*(r-1)+2) hold (r, global-row-index-of-k). Rows whose own
  rank is invalid keep -1 everywhere past col 0.

TPU-native: the matrix is built host-side in numpy (it is pure data prep,
shape [B, 7] for max_rank=3) and padded to the static batch size so the
jit step never sees ragged shapes; padding rows are all -1 which
rank_attention treats as "contribute nothing".
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from paddlebox_tpu.data.batch import BatchBuilder, SlotBatch
from paddlebox_tpu.data.record import SlotRecord
from paddlebox_tpu.data.schema import DataFeedDesc

VALID_CMATCH = (222, 223)


def group_by_search_id(records: Sequence[SlotRecord]) -> List[List[SlotRecord]]:
    """Stable sort by search_id, then merge consecutive equal sids into one
    PV (mirrors PreprocessInstance's merge_by_sid path)."""
    order = sorted(range(len(records)), key=lambda i: records[i].search_id)
    pvs: List[List[SlotRecord]] = []
    last_sid = None
    for i in order:
        r = records[i]
        if last_sid is None or r.search_id != last_sid:
            pvs.append([r])
            last_sid = r.search_id
        else:
            pvs[-1].append(r)
    return pvs


def merge_by_insid(records: Sequence[SlotRecord], merge_size: int = 2,
                   num_slots: int = 0) -> Tuple[List[SlotRecord], int]:
    """Merge records sharing an ``ins_id`` into one record
    (MultiSlotDataset::MergeByInsId, data_set.cc:1517): sparse slots
    concatenate across the group's records (slot order preserved); dense/
    label/show/clk come from the first record. When ``merge_size`` > 0,
    groups whose size differs are DROPPED (reference drops and warns).
    Returns (merged_records, dropped_count)."""
    buckets: Dict[str, List[SlotRecord]] = {}
    for r in records:
        buckets.setdefault(r.ins_id, []).append(r)
    merged: List[SlotRecord] = []
    dropped = 0
    for ins_id in sorted(buckets):
        grp = buckets[ins_id]
        if merge_size > 0 and len(grp) != merge_size:
            dropped += len(grp)
            continue
        if len(grp) == 1:
            merged.append(grp[0])
            continue
        first = grp[0]
        s = (num_slots or len(first.slot_offsets) - 1)
        chunks: List[np.ndarray] = []
        offs = [0]
        for slot in range(s):
            for r in grp:
                chunks.append(r.slot_keys(slot))
            offs.append(offs[-1] + sum(
                len(r.slot_keys(slot)) for r in grp))
        merged.append(SlotRecord(
            keys=(np.concatenate(chunks) if offs[-1]
                  else np.empty(0, np.uint64)),
            slot_offsets=np.array(offs, dtype=np.int32),
            dense=first.dense, label=first.label, show=first.show,
            clk=first.clk, ins_id=ins_id, search_id=first.search_id,
            rank=first.rank, cmatch=first.cmatch, uid=first.uid,
            timestamp=first.timestamp))
    return merged, dropped


def group_by_uid(records: Sequence[SlotRecord],
                 sort_by_time: bool = True) -> List[List[SlotRecord]]:
    """Group records by uid (merge_by_uid path: user timeline grouping),
    each timeline time-ordered (cur_timestamp_) so the window split
    (split_uid_groups) sees a temporal sequence."""
    buckets: Dict[int, List[SlotRecord]] = {}
    for r in records:
        buckets.setdefault(r.uid, []).append(r)
    groups = list(buckets.values())
    if sort_by_time:
        for g in groups:
            g.sort(key=lambda r: r.timestamp)
    return groups


def compute_split_num_and_mask(ins_count: int, seq_length: int,
                               train_length: int
                               ) -> Tuple[List[Tuple[int, int]], List[int]]:
    """Sliding test-train windows over a user timeline — direct port of
    ``compute_split_num_and_mask`` (data_set.cc:2783). Returns per-window
    [start, end) offsets and the window's zero-mask prefix length (the
    leading ``seq_length - train_length`` context records that do NOT
    train). Invariant (asserted, as the reference PADDLE_ENFORCEs): every
    record trains in exactly one window."""
    window_num = (ins_count - seq_length) // train_length + 1
    offsets: List[Tuple[int, int]] = [(0, ins_count - window_num * train_length)]
    zero_mask: List[int] = [0]
    s = offsets[0][1] - (seq_length - train_length)
    e = offsets[0][1] + train_length
    while e <= ins_count:
        offsets.append((s, e))
        zero_mask.append(seq_length - train_length)
        s += train_length
        e += train_length
    train_num = sum((b - a) - z for (a, b), z in zip(offsets, zero_mask))
    assert train_num == ins_count, "window split lost/duplicated train rows"
    return offsets, zero_mask


def split_uid_groups(groups: Sequence[Sequence[SlotRecord]], method: int,
                     split_size: int = 0, train_size: int = 0
                     ) -> List[Tuple[List[SlotRecord], int]]:
    """Split uid-merged timelines into PV chunks with a zero-mask count —
    ``merge_by_uid_split_method`` (data_feed.h:624, data_set.cc:2871-2927):

    - 0: whole timeline as one chunk, mask 0.
    - 1: direct split into ``split_size`` chunks aligned to the END of the
      timeline (the reference opens a new chunk when
      ``(count - j) % split_size == 0``), all records train.
    - 2: sliding test-train windows (``compute_split_num_and_mask``): each
      window's first ``split_size - train_size`` records are frozen
      context (zero mask), the rest train; a record trains exactly once.

    Returns [(records, zero_mask_num)] — feed to ``build_train_mask``.
    """
    if method == 2 and split_size > 0 and train_size > split_size:
        raise ValueError(
            f"train_size ({train_size}) must be <= split_size "
            f"({split_size}) — the window's context prefix would be "
            "negative")
    out: List[Tuple[List[SlotRecord], int]] = []
    for g in groups:
        n = len(g)
        if method == 1 and split_size > 0:
            chunk: List[SlotRecord] = []
            for j, r in enumerate(g):
                if j > 0 and (n - j) % split_size == 0:
                    out.append((chunk, 0))
                    chunk = []
                chunk.append(r)
            out.append((chunk, 0))
        elif method == 2 and 0 < split_size < n and train_size > 0:
            offsets, zmask = compute_split_num_and_mask(
                n, split_size, train_size)
            for (a, b), z in zip(offsets, zmask):
                if b > a:  # the first window can be empty when the
                    out.append((list(g[a:b]), z))  # timeline tiles exactly
        else:
            out.append((list(g), 0))
    return out


def build_train_mask(chunks: Sequence[Tuple[Sequence[SlotRecord], int]],
                     pad_to: int = 0) -> np.ndarray:
    """Flattened per-record ``ads_train_mask`` (data_feed.proto:57,
    MiniBatchGpuPack::pack_pvinstance data_feed.cc:4787-4791): per chunk,
    ``zero_mask_num`` zeros then ones; batch padding rows are 0."""
    ins = sum(len(c) for c, _ in chunks)
    mask = np.zeros(max(ins, pad_to), dtype=np.int64)
    pos = 0
    for recs, z in chunks:
        mask[pos + z:pos + len(recs)] = 1
        pos += len(recs)
    return mask


def timestamp_range_mask(timestamp: np.ndarray, lo: int,
                         hi: int) -> np.ndarray:
    """1.0 where timestamp ∈ [lo, hi) — the test-phase timestamp window
    (SetTestTimestampRange, data_feed.h:2038: eval restricted to a time
    range of the uid timeline). Combine multiplicatively with ins_w /
    ads_train_mask."""
    ts = np.asarray(timestamp)
    return ((ts >= lo) & (ts < hi)).astype(np.float32)


def _valid_rank(rank: int, cmatch: int, max_rank: int) -> int:
    if cmatch in VALID_CMATCH and 0 < rank <= max_rank:
        return rank
    return -1


def build_rank_offset(pvs: Sequence[Sequence[SlotRecord]],
                      max_rank: int = 3,
                      pad_to: int = 0) -> np.ndarray:
    """int32 [max(ins_num, pad_to), 2*max_rank+1], padding rows all -1."""
    ins_num = sum(len(pv) for pv in pvs)
    rows = max(ins_num, pad_to)
    cols = 2 * max_rank + 1
    mat = np.full((rows, cols), -1, dtype=np.int32)

    base = 0
    for pv in pvs:
        vr = np.array([_valid_rank(r.rank, r.cmatch, max_rank) for r in pv],
                      dtype=np.int32)
        mat[base:base + len(pv), 0] = vr
        valid_k = np.nonzero(vr > 0)[0]
        for j in range(len(pv)):
            if vr[j] <= 0:
                continue
            for k in valid_k:
                m = vr[k] - 1
                mat[base + j, 2 * m + 1] = vr[k]
                mat[base + j, 2 * m + 2] = base + k
        base += len(pv)
    return mat


class PvBatchBuilder:
    """PV-merged minibatches: ``pv_batch_size`` PVs per batch, flattened ads
    padded to ``desc.batch_size`` rows, plus the rank_offset matrix.

    Reference flow: PaddleBoxDataFeed::PutToFeedVec(pv_vec)
    (data_feed.cc:1915) = GetRankOffset + flatten ads into the normal
    instance batch path.
    """

    def __init__(self, desc: DataFeedDesc, max_rank: int = 3) -> None:
        if desc.pv_batch_size <= 0:
            raise ValueError("desc.pv_batch_size must be > 0 for PV batching")
        self.desc = desc
        self.max_rank = max_rank
        self._builder = BatchBuilder(desc)

    def batches(self, records: Sequence[SlotRecord]
                ) -> List[Tuple[SlotBatch, np.ndarray]]:
        pvs = group_by_search_id(records)
        out: List[Tuple[SlotBatch, np.ndarray]] = []
        pvb = self.desc.pv_batch_size
        for i in range(0, len(pvs), pvb):
            chunk = pvs[i:i + pvb]
            flat = [r for pv in chunk for r in pv]
            if len(flat) > self.desc.batch_size:
                raise ValueError(
                    f"PV chunk flattens to {len(flat)} ads > batch_size "
                    f"{self.desc.batch_size}; lower pv_batch_size")
            batch = self._builder.build(flat)
            ro = build_rank_offset(chunk, self.max_rank,
                                   pad_to=self.desc.batch_size)
            out.append((batch, ro))
        return out
