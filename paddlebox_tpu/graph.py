"""Graph store + neighbor sampling (GNN training support).

Reference: the GPU graph PS in heter_ps — ``GpuPsCommGraph`` (CSR
neighbor lists per shard, gpu_graph_node.h:35), ``GpuPsGraphTable``
(graph_neighbor_sample/_v2/_v3, graph_gpu_ps_table.h:128-140),
``graph_sampler`` walk generation, and ``GraphDataGenerator``
(data_feed.h:908) which feeds sampled walks into the training pipeline.

TPU-native redesign: the graph lives as two device arrays (CSR
``indptr``/``indices``) — node ids are compacted to dense row ids the
same way the embedding PS compacts feature keys. Sampling is one jitted
gather: uniform neighbor draws are ``indptr[n] + floor(u * deg)`` with
isolated nodes padded to -1 (static shapes, no host sync), so a sampling
step fuses into the surrounding training step instead of being a
separate RPC to a graph server. Walks are ``lax.scan`` over hops.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class GraphStore:
    """CSR graph with dense node ids [0, n_nodes)."""

    def __init__(self, indptr: np.ndarray, indices: np.ndarray) -> None:
        self.indptr = np.asarray(indptr, np.int32)
        self.indices = np.asarray(indices, np.int32)
        self.n_nodes = self.indptr.size - 1
        self._dev: Optional[Tuple[jax.Array, jax.Array]] = None

    @classmethod
    def from_edges(cls, src: np.ndarray, dst: np.ndarray,
                   n_nodes: Optional[int] = None,
                   symmetric: bool = False) -> "GraphStore":
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        if symmetric:
            src, dst = (np.concatenate([src, dst]),
                        np.concatenate([dst, src]))
        n = int(n_nodes if n_nodes is not None
                else (max(src.max(), dst.max()) + 1 if src.size else 0))
        if src.size and (src.min() < 0 or dst.min() < 0
                         or src.max() >= n or dst.max() >= n):
            raise ValueError(
                f"edge node ids must lie in [0, {n}); got src range "
                f"[{src.min()}, {src.max()}], dst range "
                f"[{dst.min()}, {dst.max()}]")
        order = np.argsort(src, kind="stable")
        src, dst = src[order], dst[order]
        counts = np.bincount(src, minlength=n)
        indptr = np.zeros(n + 1, np.int64)
        np.cumsum(counts, out=indptr[1:])
        return cls(indptr, dst)

    def degree(self, nodes: Optional[np.ndarray] = None) -> np.ndarray:
        deg = np.diff(self.indptr)
        return deg if nodes is None else deg[np.asarray(nodes)]

    def to_device(self) -> Tuple[jax.Array, jax.Array]:
        if self._dev is None:
            self._dev = (jnp.asarray(self.indptr), jnp.asarray(self.indices))
        return self._dev


def sample_neighbors(indptr: jax.Array, indices: jax.Array,
                     nodes: jax.Array, k: int,
                     rng: jax.Array) -> jax.Array:
    """Uniform with-replacement k-neighbor sample per node → int32 [N, k];
    isolated nodes yield -1 (the reference pads its sample results the
    same way: default_value in graph_neighbor_sample)."""
    start = indptr[nodes]                                    # [N]
    deg = indptr[nodes + 1] - start                          # [N]
    u = jax.random.uniform(rng, (nodes.shape[0], k))
    off = jnp.floor(u * jnp.maximum(deg, 1)[:, None]).astype(jnp.int32)
    neigh = indices[start[:, None] + off]                    # [N, k]
    return jnp.where(deg[:, None] > 0, neigh, -1)


def random_walk(indptr: jax.Array, indices: jax.Array,
                starts: jax.Array, length: int,
                rng: jax.Array) -> jax.Array:
    """DeepWalk-style uniform walks → int32 [N, length+1] (first column =
    starts). A walk stalls (repeats its node) at isolated nodes."""

    def hop(cur, r):
        nxt = sample_neighbors(indptr, indices, cur, 1, r)[:, 0]
        nxt = jnp.where(nxt < 0, cur, nxt)
        return nxt, nxt

    keys = jax.random.split(rng, length)
    _, steps = jax.lax.scan(hop, starts, keys)
    return jnp.concatenate([starts[None, :], steps], axis=0).T


class GraphDataGenerator:
    """Walk-batch generator feeding skip-gram style training (reference:
    GraphDataGenerator data_feed.h:908 — sample walks, emit id batches)."""

    def __init__(self, store: GraphStore, walk_len: int = 5,
                 batch_size: int = 256, seed: int = 0) -> None:
        self.store = store
        self.walk_len = walk_len
        self.batch_size = batch_size
        self._rng = jax.random.PRNGKey(seed)

    def batches(self, epochs: int = 1):
        indptr, indices = self.store.to_device()
        n = self.store.n_nodes
        for _ in range(epochs):
            self._rng, sub = jax.random.split(self._rng)
            perm = np.asarray(jax.random.permutation(sub, n))
            for i in range(0, n, self.batch_size):
                chunk = perm[i:i + self.batch_size]
                if chunk.size < self.batch_size:  # static shapes: pad
                    chunk = np.pad(chunk, (0, self.batch_size - chunk.size),
                                   mode="edge")
                self._rng, sub = jax.random.split(self._rng)
                yield random_walk(indptr, indices, jnp.asarray(chunk),
                                  self.walk_len, sub)
