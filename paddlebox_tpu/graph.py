"""Graph store + neighbor sampling (GNN training support).

Reference: the GPU graph PS in heter_ps — ``GpuPsCommGraph`` (CSR
neighbor lists per shard, gpu_graph_node.h:35), ``GpuPsGraphTable``
(graph_neighbor_sample/_v2/_v3, graph_gpu_ps_table.h:128-140),
``graph_sampler`` walk generation, and ``GraphDataGenerator``
(data_feed.h:908) which feeds sampled walks into the training pipeline.

TPU-native redesign: the graph lives as two device arrays (CSR
``indptr``/``indices``) — node ids are compacted to dense row ids the
same way the embedding PS compacts feature keys. Sampling is one jitted
gather: uniform neighbor draws are ``indptr[n] + floor(u * deg)`` with
isolated nodes padded to -1 (static shapes, no host sync), so a sampling
step fuses into the surrounding training step instead of being a
separate RPC to a graph server. Walks are ``lax.scan`` over hops.

Depth matching graph_gpu_ps_table.h:128-140 / graph_sampler.h:
- edge WEIGHTS: weighted with-replacement draws are one searchsorted
  over the per-node cumulative-weight spans (WeightedSampleKernel role);
- WITHOUT-replacement (uniform or weighted) via the Gumbel top-k trick
  over a bounded neighbor window — the TPU-shaped equivalent of the
  reference's per-node shuffles (static shapes, one top_k);
- typed graphs + METAPATH walks (HeteroGraphStore.metapath_walk — the
  graph_sampler walk schedules over edge types);
- mesh SHARDING by node %% N with all_to_all query routing inside
  shard_map (ShardedGraphStore — the multi-GPU table's partition);
- node feature pull through the embedding PS
  (features_for_nodes == get_feature_of_nodes, graph_gpu_ps_table.h:141).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class GraphStore:
    """CSR graph with dense node ids [0, n_nodes); optional per-edge
    weights (cumulative sums precomputed for weighted draws)."""

    def __init__(self, indptr: np.ndarray, indices: np.ndarray,
                 weights: Optional[np.ndarray] = None) -> None:
        self.indptr = np.asarray(indptr, np.int32)
        self.indices = np.asarray(indices, np.int32)
        self.n_nodes = self.indptr.size - 1
        if weights is not None:
            weights = np.asarray(weights, np.float32)
            if weights.shape != self.indices.shape:
                raise ValueError("one weight per edge required")
            if (weights < 0).any():
                raise ValueError("edge weights must be non-negative")
        self.weights = weights
        # Global cumulative weights: monotone, so a per-node weighted
        # draw is ONE searchsorted into its [indptr[n], indptr[n+1]) span.
        # Weights are NORMALIZED to mean 1 before the (f64) cumsum so the
        # f32 device copy's total ≈ edge count: f32 spacing stays below
        # the smallest normalized span while edges-per-store < ~2^24.
        # Larger graphs must shard (ShardedGraphStore cumsum is
        # per-shard), which also matches the reference's partitioning.
        if weights is not None and weights.size:
            mean_w = float(weights.mean())
            if mean_w <= 0:
                raise ValueError("edge weights must not all be zero")
            self.cumw = np.cumsum(weights / mean_w,
                                  dtype=np.float64).astype(np.float32)
        else:
            self.cumw = None
        self._dev = None
        self._dev_cumw = None

    @classmethod
    def from_edges(cls, src: np.ndarray, dst: np.ndarray,
                   n_nodes: Optional[int] = None,
                   symmetric: bool = False,
                   weights: Optional[np.ndarray] = None) -> "GraphStore":
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        if weights is not None:
            weights = np.asarray(weights, np.float32)
        if symmetric:
            src, dst = (np.concatenate([src, dst]),
                        np.concatenate([dst, src]))
            if weights is not None:
                weights = np.concatenate([weights, weights])
        n = int(n_nodes if n_nodes is not None
                else (max(src.max(), dst.max()) + 1 if src.size else 0))
        if src.size and (src.min() < 0 or dst.min() < 0
                         or src.max() >= n or dst.max() >= n):
            raise ValueError(
                f"edge node ids must lie in [0, {n}); got src range "
                f"[{src.min()}, {src.max()}], dst range "
                f"[{dst.min()}, {dst.max()}]")
        order = np.argsort(src, kind="stable")
        src, dst = src[order], dst[order]
        counts = np.bincount(src, minlength=n)
        indptr = np.zeros(n + 1, np.int64)
        np.cumsum(counts, out=indptr[1:])
        return cls(indptr, dst,
                   weights[order] if weights is not None else None)

    def degree(self, nodes: Optional[np.ndarray] = None) -> np.ndarray:
        deg = np.diff(self.indptr)
        return deg if nodes is None else deg[np.asarray(nodes)]

    def to_device(self) -> Tuple[jax.Array, jax.Array]:
        if self._dev is None:
            self._dev = (jnp.asarray(self.indptr), jnp.asarray(self.indices))
        return self._dev

    def to_device_weighted(self):
        if self.cumw is None:
            raise ValueError("graph has no edge weights")
        if self._dev_cumw is None:
            self._dev_cumw = jnp.asarray(self.cumw)
        return (*self.to_device(), self._dev_cumw)


def sample_neighbors(indptr: jax.Array, indices: jax.Array,
                     nodes: jax.Array, k: int,
                     rng: jax.Array) -> jax.Array:
    """Uniform with-replacement k-neighbor sample per node → int32 [N, k];
    isolated nodes yield -1 (the reference pads its sample results the
    same way: default_value in graph_neighbor_sample)."""
    start = indptr[nodes]                                    # [N]
    deg = indptr[nodes + 1] - start                          # [N]
    u = jax.random.uniform(rng, (nodes.shape[0], k))
    off = jnp.floor(u * jnp.maximum(deg, 1)[:, None]).astype(jnp.int32)
    neigh = indices[start[:, None] + off]                    # [N, k]
    return jnp.where(deg[:, None] > 0, neigh, -1)


def random_walk(indptr: jax.Array, indices: jax.Array,
                starts: jax.Array, length: int,
                rng: jax.Array) -> jax.Array:
    """DeepWalk-style uniform walks → int32 [N, length+1] (first column =
    starts). A walk stalls (repeats its node) at isolated nodes."""

    def hop(cur, r):
        nxt = sample_neighbors(indptr, indices, cur, 1, r)[:, 0]
        nxt = jnp.where(nxt < 0, cur, nxt)
        return nxt, nxt

    keys = jax.random.split(rng, length)
    _, steps = jax.lax.scan(hop, starts, keys)
    return jnp.concatenate([starts[None, :], steps], axis=0).T


class GraphDataGenerator:
    """Walk-batch generator feeding skip-gram style training (reference:
    GraphDataGenerator data_feed.h:908 — sample walks, emit id batches)."""

    def __init__(self, store: GraphStore, walk_len: int = 5,
                 batch_size: int = 256, seed: int = 0) -> None:
        self.store = store
        self.walk_len = walk_len
        self.batch_size = batch_size
        self._rng = jax.random.PRNGKey(seed)

    def batches(self, epochs: int = 1):
        indptr, indices = self.store.to_device()
        n = self.store.n_nodes
        for _ in range(epochs):
            self._rng, sub = jax.random.split(self._rng)
            perm = np.asarray(jax.random.permutation(sub, n))
            for i in range(0, n, self.batch_size):
                chunk = perm[i:i + self.batch_size]
                if chunk.size < self.batch_size:  # static shapes: pad
                    chunk = np.pad(chunk, (0, self.batch_size - chunk.size),
                                   mode="edge")
                self._rng, sub = jax.random.split(self._rng)
                yield random_walk(indptr, indices, jnp.asarray(chunk),
                                  self.walk_len, sub)


def sample_neighbors_weighted(indptr: jax.Array, indices: jax.Array,
                              cumw: jax.Array, nodes: jax.Array, k: int,
                              rng: jax.Array) -> jax.Array:
    """Weight-proportional with-replacement k-sample per node → [N, k];
    isolated / zero-weight nodes yield -1. One vectorized searchsorted
    into each node's cumulative-weight span (the WeightedSampleKernel of
    the reference's sampler, without per-thread rejection loops)."""
    start = indptr[nodes]
    end = indptr[nodes + 1]
    lo = jnp.where(start > 0, cumw[jnp.maximum(start - 1, 0)], 0.0)
    hi = cumw[jnp.maximum(end - 1, 0)]
    total = jnp.where(end > start, hi - lo, 0.0)
    u = jax.random.uniform(rng, (nodes.shape[0], k))
    # strictly inside the span: searchsorted returns the owning edge
    target = lo[:, None] + u * jnp.maximum(total, 1e-30)[:, None]
    idx = jnp.searchsorted(cumw, target, side="left").astype(jnp.int32)
    idx = jnp.clip(idx, start[:, None], jnp.maximum(end[:, None] - 1, 0))
    neigh = indices[idx]
    return jnp.where(total[:, None] > 0, neigh, -1)


def sample_neighbors_without_replacement(
        indptr: jax.Array, indices: jax.Array, nodes: jax.Array, k: int,
        rng: jax.Array, max_degree: int = 128,
        cumw: jax.Array = None) -> jax.Array:
    """WITHOUT-replacement k-sample per node → [N, k] (uniform, or
    weight-proportional when ``cumw`` is given) — the Gumbel top-k
    trick: per candidate edge key = log(w) + Gumbel noise, take top-k
    (exactly Plackett-Luce sequential sampling without replacement).

    TPU-shaped: gathers a bounded [N, max_degree] neighbor window and
    runs ONE lax.top_k — no per-node shuffles or rejection loops.
    Nodes with degree > max_degree sample from a max_degree-wide window
    whose offset is drawn uniformly at random PER CALL — every edge of a
    hub node is sampleable across calls (no permanently-invisible tail,
    unlike a fixed first-window truncation); raise ``max_degree`` for
    hub-heavy graphs to remove the bias within one call. Slots beyond a
    node's degree (or beyond k available) are -1, as in the reference's
    padded NeighborSampleResult."""
    n = nodes.shape[0]
    start = indptr[nodes]
    full_deg = indptr[nodes + 1] - start
    deg = jnp.minimum(full_deg, max_degree)
    rng, rng_off = jax.random.split(rng)
    over = jnp.maximum(full_deg - max_degree, 0)
    # exact integer draw: an f32 uniform*span would quantize offsets for
    # hubs with >2^24 excess edges, re-hiding the tail
    off = jax.random.randint(rng_off, (n,), 0, over + 1)
    pos = jnp.arange(max_degree, dtype=jnp.int32)[None, :]
    edge = jnp.minimum(start[:, None] + off[:, None] + pos,
                       jnp.maximum(indices.shape[0] - 1, 0))
    valid = pos < deg[:, None]
    if cumw is not None:
        w_hi = cumw[edge]
        w_lo = jnp.where(edge > 0, cumw[jnp.maximum(edge - 1, 0)], 0.0)
        span = (w_hi - w_lo).astype(jnp.float32)
        # zero-weight edges are NOT sampleable (matches the
        # with-replacement sampler's zero-total -> -1 contract)
        logw = jnp.where(span > 0, jnp.log(jnp.maximum(span, 1e-30)),
                         -jnp.inf)
    else:
        logw = jnp.zeros((n, max_degree))
    g = -jnp.log(-jnp.log(
        jax.random.uniform(rng, (n, max_degree), minval=1e-12,
                           maxval=1.0)))
    keys = jnp.where(valid, logw + g, -jnp.inf)
    top, arg = jax.lax.top_k(keys, min(k, max_degree))
    neigh = jnp.take_along_axis(
        indices[edge], arg, axis=1)                       # [N, <=k]
    neigh = jnp.where(jnp.isfinite(top), neigh, -1)
    if neigh.shape[1] < k:                                # k > max_degree
        pad = jnp.full((n, k - neigh.shape[1]), -1, neigh.dtype)
        neigh = jnp.concatenate([neigh, pad], axis=1)
    return neigh


class HeteroGraphStore:
    """Typed-edge graph: one CSR per edge type over a SHARED node id
    space (the reference's per-type graph index, idx arg of
    graph_neighbor_sample_v2 / add_graph_table)."""

    def __init__(self, stores) -> None:
        self.stores = dict(stores)
        if not self.stores:
            raise ValueError("need at least one edge type")

    def edge_types(self):
        return sorted(self.stores)

    def metapath_walk(self, metapath, starts: jax.Array,
                      rng: jax.Array) -> jax.Array:
        """Walk following the given edge-type sequence (graph_sampler
        metapath schedules): hop i samples one neighbor through
        ``metapath[i]``'s CSR. Stalls at dead ends. → [N, len+1]."""
        cur = starts
        cols = [starts]
        for i, et in enumerate(metapath):
            indptr, indices = self.stores[et].to_device()
            rng, sub = jax.random.split(rng)
            nxt = sample_neighbors(indptr, indices, cur, 1, sub)[:, 0]
            cur = jnp.where(nxt < 0, cur, nxt)
            cols.append(cur)
        return jnp.stack(cols, axis=1)


class ShardedGraphStore:
    """Mesh-sharded graph table: node n lives on shard n % S (the
    multi-GPU GpuPsGraphTable partition, heter_comm key%N routing).

    Shards are stacked, padded CSR arrays ([S, ...] leading mesh axis);
    sampling runs INSIDE shard_map: queries all_to_all to their owner
    shard, sample locally, all_to_all back — the same two-collective
    route as the sharded embedding pull (train/sharded.py)."""

    def __init__(self, store: GraphStore, n_shards: int) -> None:
        self.n = n_shards
        self.n_nodes = store.n_nodes
        indptrs, indices_l = [], []
        all_deg = np.diff(store.indptr)
        for s in range(n_shards):
            own = np.arange(s, store.n_nodes, n_shards)
            deg = all_deg[own] if own.size else np.zeros(0, np.int64)
            ip = np.zeros(own.size + 1, np.int64)
            np.cumsum(deg, out=ip[1:])
            # one vectorized gather per shard (no per-node python):
            # edge position j of the shard belongs to owned node
            # searchsorted(ip, j, 'right')-1 at offset j - ip[node]
            total = int(ip[-1])
            if total:
                node_of = np.repeat(np.arange(own.size), deg)
                off = np.arange(total) - ip[node_of]
                idx = store.indices[store.indptr[own][node_of] + off]
            else:
                idx = np.zeros(0, np.int32)
            indptrs.append(ip)
            indices_l.append(idx.astype(np.int32, copy=False))
        ip_pad = max(a.size for a in indptrs)
        ix_pad = max(max(a.size for a in indices_l), 1)
        self.indptr = np.zeros((n_shards, ip_pad), np.int32)
        self.indices = np.zeros((n_shards, ix_pad), np.int32)
        for s in range(n_shards):
            # pad indptr by repeating the tail: padded local nodes read
            # degree 0
            self.indptr[s, :indptrs[s].size] = indptrs[s]
            self.indptr[s, indptrs[s].size:] = indptrs[s][-1] \
                if indptrs[s].size else 0
            self.indices[s, :indices_l[s].size] = indices_l[s]

    def make_sampler(self, mesh, k: int, q_per_shard: int,
                     axis: str = "dp"):
        """Jitted mesh sampler: (queries [S, Q] global node ids,
        rng [S, 2]) → [S, Q, k] neighbors (global ids; -1 pads).
        Queries land on their shard row arbitrarily — routing is inside.
        ``q_per_shard`` is the per-owner bucket capacity; Q must not
        exceed it (checked), since overflow would silently route
        queries to the wrong shard."""
        from jax.sharding import PartitionSpec as P
        n = self.n

        def local(indptr, indices, queries, rng):
            # shard_map keeps the sharded leading axis at size 1
            indptr, indices = indptr[0], indices[0]
            queries, rng = queries[0], rng[0]
            me = jax.lax.axis_index(axis)
            owner = queries % n
            # bucket queries by owner (stable sort → positions to undo)
            order = jnp.argsort(owner, stable=True)
            routed = queries[order]
            # equal-split all_to_all needs uniform buckets: count per
            # owner and scatter into [n, cap] slots
            cap = q_per_shard
            dest = owner[order]
            rank_in = jnp.cumsum(
                jnp.ones_like(dest)) - 1 - jnp.searchsorted(
                    dest, dest, side="left").astype(dest.dtype)
            slots = jnp.clip(dest * cap + rank_in, 0, n * cap - 1)
            buf = jnp.full((n * cap,), -1, queries.dtype)
            buf = buf.at[slots].set(routed)
            buf = buf.reshape(n, cap)
            # route to owners; local ids = node // n
            inbox = jax.lax.all_to_all(buf, axis, 0, 0, tiled=False)
            flat = inbox.reshape(-1)
            ok = flat >= 0
            local_ids = jnp.where(ok, flat // n, 0)
            # rng arrives as raw uint32 [2] key data per shard; fold in
            # the shard index so shards draw independent streams
            key = jax.random.fold_in(jax.random.wrap_key_data(rng), me)
            got = sample_neighbors(indptr, indices,
                                   local_ids.astype(jnp.int32), k, key)
            got = jnp.where(ok[:, None], got, -1)
            # send answers back
            back = jax.lax.all_to_all(
                got.reshape(n, cap, k), axis, 0, 0, tiled=False)
            back = back.reshape(n * cap, k)
            # un-bucket: answer for routed[i] sits at slots[i]
            ans_sorted = back[slots]
            out = jnp.zeros((queries.shape[0], k), jnp.int32)
            out = out.at[order].set(ans_sorted, unique_indices=True)
            return out[None]

        def run(indptr_s, indices_s, queries_s, rng_s):
            if queries_s.shape[1] > q_per_shard:
                raise ValueError(
                    f"{queries_s.shape[1]} queries/shard exceeds the "
                    f"bucket capacity q_per_shard={q_per_shard}")
            return jax.shard_map(
                local, mesh=mesh,
                in_specs=(P(axis), P(axis), P(axis), P(axis)),
                out_specs=P(axis),
            )(indptr_s, indices_s, queries_s, rng_s)

        return jax.jit(run)


def features_for_nodes(table, nodes: np.ndarray) -> np.ndarray:
    """get_feature_of_nodes (graph_gpu_ps_table.h:141): pull the
    embedding-PS feature rows for (walk) node ids — node id == feature
    key. Unknown nodes read zeros. → [n, 3 + mf]."""
    return table.host_pull(np.asarray(nodes, np.uint64).ravel())


class BfsSampler:
    """Batched BFS frontier sampler — the BasicBfsGraphSampler role
    (graph_sampler.h:77-110: per-level neighbor sampling from a seed
    frontier with per-node and per-level budgets, filling sample buffers
    level by level).

    TPU-shaped: each level is ONE ``sample_neighbors`` gather over the
    current frontier ([budget] static shape, -1 pads); the next frontier
    is a dedup + budget-clip of the sampled nodes. Returns per-level node
    arrays and the sampled (src, dst) edges — a subgraph batch ready for
    a GNN layer stack."""

    def __init__(self, store: GraphStore, k_per_level=(10, 5),
                 node_budget: int = 4096) -> None:
        self.store = store
        self.k_per_level = tuple(k_per_level)
        self.node_budget = node_budget

    def sample(self, seeds: np.ndarray, rng: jax.Array):
        """→ {"levels": [seeds, l1, l2, ...], "edges": (src, dst)}.
        Levels past the seeds are FIXED-BUDGET (-1-padded to
        ``node_budget``): every level's sample_neighbors dispatch keeps
        one static shape, so the background service never accumulates
        per-frontier-size recompiles. Edges are the sampled adjacency
        (every dst in level i+1 came from a src in level i)."""
        indptr, indices = self.store.to_device()
        levels = [np.asarray(seeds, np.int32)]
        srcs, dsts = [], []
        frontier = jnp.asarray(levels[0])
        for li, k in enumerate(self.k_per_level):
            rng, sub = jax.random.split(rng)
            neigh = sample_neighbors(indptr, indices,
                                     jnp.maximum(frontier, 0), k, sub)
            neigh = jnp.where(frontier[:, None] >= 0, neigh, -1)
            src = jnp.broadcast_to(frontier[:, None], neigh.shape)
            m = np.asarray(neigh).ravel() >= 0
            srcs.append(np.asarray(src).ravel()[m])
            dsts.append(np.asarray(neigh).ravel()[m])
            nxt = np.unique(dsts[-1])[:self.node_budget]  # budget clip
            pad = np.full(self.node_budget, -1, np.int32)
            pad[:len(nxt)] = nxt
            levels.append(pad)
            frontier = jnp.asarray(pad)
        return {"levels": levels,
                "edges": (np.concatenate(srcs) if srcs else
                          np.zeros(0, np.int32),
                          np.concatenate(dsts) if dsts else
                          np.zeros(0, np.int32))}


class GraphSamplerService:
    """Background sampling service — the graph_sampler.h:25-110 role:
    a thread continuously drives a sampler (random walks or BFS
    subgraphs) into a bounded channel feeding the training loop, with
    SAMPLE-RATE control (max batches/sec; the reference's sample-rate
    knob, test_sample_rate.cu).

    The trainer consumes via ``batches()`` — a generator that blocks on
    the channel, so sampling overlaps training exactly like the
    reference's background sampler filling device buffers."""

    def __init__(self, store: GraphStore, mode: str = "walk",
                 batch_size: int = 256, walk_len: int = 5,
                 k_per_level=(10, 5), rate: Optional[float] = None,
                 capacity: int = 8, seed: int = 0) -> None:
        if mode not in ("walk", "bfs"):
            raise ValueError(f"unknown sampler mode {mode!r}")
        from paddlebox_tpu.utils.channel import Channel
        self.store = store
        self.mode = mode
        self.batch_size = batch_size
        self.walk_len = walk_len
        self.bfs = (BfsSampler(store, k_per_level=k_per_level)
                    if mode == "bfs" else None)
        self.rate = rate
        self.chan = Channel(capacity=capacity)
        self._rng = jax.random.PRNGKey(seed)
        self._thread = None
        self._stop = False
        self._err: Optional[BaseException] = None
        self.produced = 0

    # ---- producer thread ----
    def _run(self, max_batches: Optional[int]) -> None:
        import time as _time
        try:
            indptr, indices = self.store.to_device()
            n = self.store.n_nodes
            perm = None
            pos = 0
            t0 = _time.monotonic()
            while not self._stop:
                if max_batches is not None \
                        and self.produced >= max_batches:
                    break
                if self.rate is not None:
                    # token-bucket pacing: never exceed rate batches/sec
                    budget = (_time.monotonic() - t0) * self.rate
                    if self.produced >= budget:
                        _time.sleep(min(0.05,
                                        (self.produced - budget + 1)
                                        / self.rate))
                        continue
                if perm is None or pos + self.batch_size > n:
                    self._rng, sub = jax.random.split(self._rng)
                    perm = np.asarray(jax.random.permutation(sub, n))
                    pos = 0
                seeds = perm[pos:pos + self.batch_size]
                if seeds.size < self.batch_size:
                    seeds = np.pad(seeds,
                                   (0, self.batch_size - seeds.size),
                                   mode="edge")
                pos += self.batch_size
                self._rng, sub = jax.random.split(self._rng)
                if self.mode == "walk":
                    out = np.asarray(random_walk(
                        indptr, indices, jnp.asarray(seeds),
                        self.walk_len, sub))
                else:
                    out = self.bfs.sample(seeds, sub)
                try:
                    self.chan.put(out)
                except Exception:  # ChannelClosed: stop() raced us
                    break
                self.produced += 1
        except BaseException as e:
            self._err = e
        finally:
            self.chan.close()

    # ---- service surface ----
    def start(self, max_batches: Optional[int] = None
              ) -> "GraphSamplerService":
        import threading
        if self._thread is not None:
            raise RuntimeError("service already started")
        if self._stop:
            raise RuntimeError(
                "service was stopped (its channel is closed) — create a "
                "new GraphSamplerService instead of restarting this one")
        self._thread = threading.Thread(
            target=self._run, args=(max_batches,), daemon=True)
        self._thread.start()
        return self

    def batches(self):
        """Drain the channel until the producer finishes/stops; raises
        the producer's error, if any."""
        from paddlebox_tpu.utils.channel import ChannelClosed
        while True:
            try:
                item = self.chan.get()
            except ChannelClosed:
                break
            yield item
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def stop(self) -> None:
        self._stop = True
        self.chan.close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._err is not None:
            err, self._err = self._err, None
            raise err
