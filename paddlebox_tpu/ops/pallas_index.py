"""Device-resident key→row assignment: an open-addressing hash index.

Reference: the HeterPS ``HashTable`` (SURVEY §2.2; heter_ps/hashtable.h
``get``/``insert`` over a GPU bucket array) — the structure that lets
PaddleBox pull/push take RAW feature ids with dedup and row assignment
happening on the accelerator instead of host threads. Here the analogue
is a linear-probe table over three int32 HBM arrays (key-hi, key-lo,
row; 64-bit ids ride as two 32-bit halves so the whole pipeline stays
x64-free):

- ``insert``: probe each key's bucket chain; an EMPTY bucket is claimed
  and the key allocated the next first-seen row. Two formulations with
  IDENTICAL row/new-mask output (gated in tests/test_pallas_index.py):
  * ``_insert_xla`` — vectorized parallel claim rounds in a
    ``while_loop``: every prober scatter-mins its stream index into a
    claim array (the compare-and-swap analogue: claim, then VERIFY the
    readback picked you), losers re-probe; rows come from a first-seen
    prefix-sum over the new-key mask after the loop.
  * ``_insert_pallas`` — a Pallas kernel gridded over key blocks. The
    TPU grid is SEQUENTIAL, so a row cursor in SMEM scratch carried
    across grid steps allocates first-seen rows with NO atomics (the
    per-block cursor of ISSUE 19), and the claim needs no CAS at all —
    the read-check-write on the aliased ANY-space bucket refs is
    race-free by construction.
- ``lookup``: the same probe, read-only; miss → row -1. Termination at
  ``_MAX_PROBE`` is safe because ``insert`` never PLACES a key more
  than ``_MAX_PROBE`` buckets from home (it overflows instead).
- ``scatter_add_update``: unique-row scatter-add of update deltas into
  the value table (aliased in-place Pallas kernel / ``.at[].add`` XLA
  twin) — the push-side op of the megakernel path.

Probe-chain validity note: the parallel-claim and sequential
formulations may place a key in DIFFERENT buckets (a lost claim skips a
bucket the sequential order would have taken), but every placement
leaves the key's whole probe prefix occupied and nothing is ever
deleted, so both layouts are valid linear-probe tables for the SAME key
set and either ``lookup`` finds every key in either layout. Rows depend
only on first-seen allocation order, which both share — parity gates
target rows/new-mask, never bucket bytes.

Mosaic status: random-access single-element HBM loads are not yet a
Mosaic primitive (same constraint that demoted the per-row DMA
gather — see ops/pallas_kernels.py status), so on a REAL TPU backend
``insert``/``lookup`` route to the XLA formulation, which is still
fully device-resident (one fused while_loop program, no host round
trip). The Pallas kernels run under interpret mode everywhere tier-1
runs and are the shape the Mosaic version keeps.

Overflow contract: a key that probes ``_MAX_PROBE`` buckets without
placing, or a batch whose new keys exceed remaining row capacity, makes
the WHOLE call return overflow — the functional bucket updates are
simply not committed, and the caller (``DeviceKeyIndex`` → the
``use_pallas_index`` seam in ps/table.py / ps/sharded.py) degrades
LOUDLY to the host index with both decisions booked in
``pbox_kernel_dispatch_total{kernel="index.*",impl}``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddlebox_tpu.ops.device_unique import dedup_keys_first_seen
from paddlebox_tpu.ops.pallas_kernels import (_book_dispatch, _interpret,
                                              _round_up)
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

_EMPTY = -1        # row sentinel marking an unclaimed bucket
_MAX_PROBE = 64    # probe-chain bound; longer chains overflow to host
_BK = 256          # keys per Pallas grid block


def book_index_dispatch(op: str, impl: str) -> None:
    """Book one index-seam dispatch decision (op ∈ {assign, lookup},
    impl ∈ {pallas, host}) — the loud half of the fallback contract."""
    _book_dispatch(f"index.{op}", impl)


# ---------------------------------------------------------------------------
# Key split / hash
# ---------------------------------------------------------------------------

def split_keys(keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """uint64 [N] → (hi, lo) int32 [N] halves (little-endian word order)."""
    w = np.ascontiguousarray(keys, np.uint64).view(np.uint32)
    lo = np.ascontiguousarray(w[0::2]).view(np.int32)
    hi = np.ascontiguousarray(w[1::2]).view(np.int32)
    return hi, lo


def join_keys(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """(hi, lo) int32 halves → uint64 keys."""
    return ((hi.astype(np.int64).astype(np.uint64) << np.uint64(32))
            | lo.view(np.uint32).astype(np.uint64))


def _hash32(hi: jax.Array, lo: jax.Array) -> jax.Array:
    """uint32 bucket hash MIXING BOTH HALVES (ids that collide mod 2^32
    must not collide here) — two odd-constant folds + an xorshift
    finalizer, murmur3/splitmix style."""
    h = (lo.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
         + hi.astype(jnp.uint32) * jnp.uint32(0x85EBCA77))
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x7FEB352D)
    h = h ^ (h >> 15)
    h = h * jnp.uint32(0x846CA68B)
    return h ^ (h >> 16)


# ---------------------------------------------------------------------------
# insert — XLA parallel-claim formulation
# ---------------------------------------------------------------------------

def _insert_xla(bh, bl, br, kh, kl, num_valid, next_row):
    """Parallel claim rounds: all unplaced keys probe at once; an empty
    bucket goes to the LOWEST stream index probing it this round (the
    first-seen winner), verified by reading the claim back. Returns
    (bh, bl, br, rows, new, overflow) — rows/new padded like kh."""
    k = kh.shape[0]
    nb = br.shape[0]
    mask = jnp.uint32(nb - 1)
    pos = jnp.arange(k, dtype=jnp.int32)
    h = _hash32(kh, kl)

    def cond(st):
        return jnp.any(~st[7]) & (st[8] < _MAX_PROBE)

    def step(st):
        bh, bl, br, off, row, new, newb, done, rounds = st
        b = ((h + off.astype(jnp.uint32)) & mask).astype(jnp.int32)
        r = br[b]
        active = ~done
        is_match = active & (r != _EMPTY) & (bh[b] == kh) & (bl[b] == kl)
        is_empty = active & (r == _EMPTY)
        # claim: scatter-min the stream index, verify the readback —
        # the functional compare-and-swap
        want = jnp.where(is_empty, b, nb)
        claim = jnp.full(nb, k, jnp.int32).at[want].min(pos, mode="drop")
        win = is_empty & (claim[jnp.minimum(want, nb - 1)] == pos)
        wb = jnp.where(win, b, nb)
        bh = bh.at[wb].set(kh, mode="drop")
        bl = bl.at[wb].set(kl, mode="drop")
        # placeholder row: must only read as non-EMPTY; real rows land
        # after the first-seen prefix-sum (no other live key equals a
        # just-claimed key — the stream is deduped)
        br = br.at[wb].set(0, mode="drop")
        row = jnp.where(is_match, r, row)
        new = new | win
        newb = jnp.where(win, b, newb)
        done = done | is_match | win
        off = off + (active & ~is_match & ~win).astype(jnp.int32)
        return bh, bl, br, off, row, new, newb, done, rounds + 1

    valid = pos < num_valid
    st = (bh, bl, br, jnp.zeros(k, jnp.int32), jnp.full(k, -1, jnp.int32),
          jnp.zeros(k, bool), jnp.full(k, nb, jnp.int32), ~valid,
          jnp.int32(0))
    bh, bl, br, _, row, new, newb, done, _ = jax.lax.while_loop(
        cond, step, st)
    overflow = jnp.any(~done)
    rank = jnp.cumsum(new.astype(jnp.int32)) - 1   # first-seen prefix-sum
    nrow = next_row + rank
    row = jnp.where(new, nrow, row)
    br = br.at[jnp.where(new, newb, nb)].set(nrow, mode="drop")
    return bh, bl, br, row, new.astype(jnp.int32), overflow


# ---------------------------------------------------------------------------
# insert — Pallas blocked-grid formulation
# ---------------------------------------------------------------------------

def _insert_kernel(meta_ref, kh_ref, kl_ref, bh_in, bl_in, br_in,
                   bh_ref, bl_ref, br_ref, rows_ref, new_ref, cur_ref):
    del bh_in, bl_in, br_in  # aliased — all access via the out refs
    blk = pl.program_id(0)
    nv = meta_ref[0]
    nb = br_ref.shape[0]
    mask = jnp.uint32(nb - 1)

    @pl.when(blk == 0)
    def _():
        cur_ref[0] = meta_ref[1]   # row cursor starts at next_row

    def body(j, carry):
        del carry
        g = blk * _BK + j
        kh = kh_ref[0, j]
        kl = kl_ref[0, j]
        h = _hash32(kh, kl)

        def cond(st):
            return ~st[3] & (st[0] < _MAX_PROBE)

        def step(st):
            off, row, new, done = st
            b = ((h + off.astype(jnp.uint32)) & mask).astype(jnp.int32)
            r = pl.load(br_ref, (b,))
            is_empty = r == _EMPTY
            is_match = ~is_empty & (pl.load(bh_ref, (b,)) == kh) \
                & (pl.load(bl_ref, (b,)) == kl)
            cur = cur_ref[0]

            @pl.when(is_empty)
            def _():
                # sequential grid ⇒ read-check-write is race-free: the
                # atomic-free claim + per-block cursor of ISSUE 19
                pl.store(bh_ref, (b,), kh)
                pl.store(bl_ref, (b,), kl)
                pl.store(br_ref, (b,), cur)
                cur_ref[0] = cur + 1

            row = jnp.where(is_empty, cur, jnp.where(is_match, r, row))
            return (off + (~is_empty & ~is_match).astype(jnp.int32), row,
                    new | is_empty, done | is_empty | is_match)

        st = (jnp.int32(0), jnp.int32(-1), False, g >= nv)
        _, row, new, _ = jax.lax.while_loop(cond, step, st)
        rows_ref[0, j] = row
        new_ref[0, j] = new.astype(jnp.int32)
        return 0

    jax.lax.fori_loop(0, _BK, body, 0)


def _insert_pallas(bh, bl, br, kh, kl, num_valid, next_row):
    k = kh.shape[0]
    nblk = k // _BK
    meta = jnp.stack([num_valid.astype(jnp.int32),
                      next_row.astype(jnp.int32)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((1, _BK), lambda i, m: (i, 0)),
            pl.BlockSpec((1, _BK), lambda i, m: (i, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, _BK), lambda i, m: (i, 0)),
            pl.BlockSpec((1, _BK), lambda i, m: (i, 0)),
        ],
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
    )
    nb = br.shape[0]
    bh, bl, br, rows2, new2 = pl.pallas_call(
        _insert_kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((nb,), jnp.int32),
            jax.ShapeDtypeStruct((nb,), jnp.int32),
            jax.ShapeDtypeStruct((nb,), jnp.int32),
            jax.ShapeDtypeStruct((nblk, _BK), jnp.int32),
            jax.ShapeDtypeStruct((nblk, _BK), jnp.int32),
        ],
        input_output_aliases={3: 0, 4: 1, 5: 2},
        interpret=_interpret(),
    )(meta, kh.reshape(nblk, _BK), kl.reshape(nblk, _BK), bh, bl, br)
    rows = rows2.reshape(k)
    new = new2.reshape(k)
    pos = jnp.arange(k, dtype=jnp.int32)
    overflow = jnp.any((pos < num_valid) & (rows < 0))
    return bh, bl, br, rows, new, overflow


# ---------------------------------------------------------------------------
# lookup — both formulations
# ---------------------------------------------------------------------------

def _lookup_xla(bh, bl, br, kh, kl, num_valid):
    k = kh.shape[0]
    mask = jnp.uint32(br.shape[0] - 1)
    pos = jnp.arange(k, dtype=jnp.int32)
    h = _hash32(kh, kl)

    def cond(st):
        return jnp.any(~st[2]) & (st[3] < _MAX_PROBE)

    def step(st):
        off, row, done, rounds = st
        b = ((h + off.astype(jnp.uint32)) & mask).astype(jnp.int32)
        r = br[b]
        active = ~done
        is_match = active & (r != _EMPTY) & (bh[b] == kh) & (bl[b] == kl)
        is_empty = active & (r == _EMPTY)   # chain ends → miss
        row = jnp.where(is_match, r, row)
        done = done | is_match | is_empty
        return (off + (active & ~is_match & ~is_empty).astype(jnp.int32),
                row, done, rounds + 1)

    valid = pos < num_valid
    st = (jnp.zeros(k, jnp.int32), jnp.full(k, -1, jnp.int32), ~valid,
          jnp.int32(0))
    _, row, _, _ = jax.lax.while_loop(cond, step, st)
    return row


def _lookup_kernel(meta_ref, kh_ref, kl_ref, bh_ref, bl_ref, br_ref,
                   rows_ref):
    blk = pl.program_id(0)
    nv = meta_ref[0]
    mask = jnp.uint32(br_ref.shape[0] - 1)

    def body(j, carry):
        del carry
        g = blk * _BK + j
        kh = kh_ref[0, j]
        kl = kl_ref[0, j]
        h = _hash32(kh, kl)

        def cond(st):
            return ~st[2] & (st[0] < _MAX_PROBE)

        def step(st):
            off, row, done = st
            b = ((h + off.astype(jnp.uint32)) & mask).astype(jnp.int32)
            r = pl.load(br_ref, (b,))
            is_empty = r == _EMPTY
            is_match = ~is_empty & (pl.load(bh_ref, (b,)) == kh) \
                & (pl.load(bl_ref, (b,)) == kl)
            return (off + 1, jnp.where(is_match, r, row),
                    done | is_empty | is_match)

        st = (jnp.int32(0), jnp.int32(-1), g >= nv)
        _, row, _ = jax.lax.while_loop(cond, step, st)
        rows_ref[0, j] = row
        return 0

    jax.lax.fori_loop(0, _BK, body, 0)


def _lookup_pallas(bh, bl, br, kh, kl, num_valid):
    k = kh.shape[0]
    nblk = k // _BK
    meta = jnp.stack([num_valid.astype(jnp.int32)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((1, _BK), lambda i, m: (i, 0)),
            pl.BlockSpec((1, _BK), lambda i, m: (i, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, _BK), lambda i, m: (i, 0)),
    )
    rows2 = pl.pallas_call(
        _lookup_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nblk, _BK), jnp.int32),
        interpret=_interpret(),
    )(meta, kh.reshape(nblk, _BK), kl.reshape(nblk, _BK), bh, bl, br)
    return rows2.reshape(k)


# ---------------------------------------------------------------------------
# jitted entry points
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("use_pallas",))
def insert(bh, bl, br, kh, kl, num_valid, next_row, *, use_pallas=True):
    """Insert the (deduped, first-seen-ordered) key stream. Returns
    (bh, bl, br, rows, new, overflow); on overflow the caller must
    DISCARD the returned bucket arrays (functional rollback)."""
    if use_pallas:
        return _insert_pallas(bh, bl, br, kh, kl, num_valid, next_row)
    return _insert_xla(bh, bl, br, kh, kl, num_valid, next_row)


@functools.partial(jax.jit, static_argnames=("use_pallas",))
def lookup(bh, bl, br, kh, kl, num_valid, *, use_pallas=True):
    """Probe rows for keys; miss (or pad position) → -1."""
    if use_pallas:
        return _lookup_pallas(bh, bl, br, kh, kl, num_valid)
    return _lookup_xla(bh, bl, br, kh, kl, num_valid)


@functools.partial(jax.jit, static_argnames=("use_pallas",))
def dedup_insert(bh, bl, br, kh, kl, num_valid, next_row, *,
                 use_pallas=True):
    """Raw-id front: device first-seen dedup + insert in ONE program —
    the pull-side shape of the megakernel path. Returns
    (bh, bl, br, uniq_hi, uniq_lo, first_pos, inv, num_unique,
    rows_u, new_u, overflow)."""
    uh, ul, first_pos, inv, nu = dedup_keys_first_seen(kh, kl, num_valid)
    if use_pallas:
        bh, bl, br, rows, new, ovf = _insert_pallas(
            bh, bl, br, uh, ul, nu, next_row)
    else:
        bh, bl, br, rows, new, ovf = _insert_xla(
            bh, bl, br, uh, ul, nu, next_row)
    return bh, bl, br, uh, ul, first_pos, inv, nu, rows, new, ovf


# ---------------------------------------------------------------------------
# scatter_add_update — push-side unique-row delta apply
# ---------------------------------------------------------------------------

def scatter_add_update(values: jax.Array, rows: jax.Array,
                       deltas: jax.Array,
                       use_pallas: Optional[bool] = None) -> jax.Array:
    """values [C, D] += deltas [U, D] at rows [U] (int32, duplicate-free
    in-bounds; rows outside [0, C) are DROPPED). The Pallas impl aliases
    the table and adds in place, one row-block per grid step."""
    if use_pallas is None:
        use_pallas = True
    if not use_pallas:
        c = values.shape[0]
        u = rows.shape[0]
        # negative rows would WRAP pythonically before the drop check —
        # remap them to distinct out-of-bounds ids so they drop too
        # (distinct keeps the unique_indices promise honest)
        safe = jnp.where(rows < 0, c + jnp.arange(u, dtype=rows.dtype),
                         rows)
        return values.at[safe].add(deltas, mode="drop",
                                   unique_indices=True)
    c, d = values.shape
    u = rows.shape[0]
    # dropped rows are routed to a sacrificial row c (stripped on
    # return) so every REAL row's output block is visited exactly once —
    # revisited blocks can read a stale pipeline copy, which is fine
    # only for content nobody keeps
    ext = jnp.concatenate([values, jnp.zeros((1, d), values.dtype)])

    def kernel(rows_ref, tbl_ref, val_ref, out_ref):
        del tbl_ref
        i = pl.program_id(0)
        r = rows_ref[i]
        ok = (r >= 0) & (r < c)
        out_ref[...] = jnp.where(ok, out_ref[...] + val_ref[...],
                                 out_ref[...])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(u,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),   # aliased table
            pl.BlockSpec((1, d), lambda i, rows_ref: (i, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, d),
            lambda i, rows_ref: (jnp.where(
                (rows_ref[i] >= 0) & (rows_ref[i] < c), rows_ref[i], c), 0)),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((c + 1, d), values.dtype),
        input_output_aliases={1: 0},
        interpret=_interpret(),
    )(rows, ext, deltas)
    return out[:c]


# ---------------------------------------------------------------------------
# Host-facing index object
# ---------------------------------------------------------------------------

def _pad_to_block(a: np.ndarray) -> np.ndarray:
    k = _round_up(max(len(a), 1), _BK)
    out = np.zeros(k, a.dtype)
    out[:len(a)] = a
    return out


def default_use_pallas() -> bool:
    """Kernel choice for the device path: Pallas under interpret mode,
    the XLA while_loop formulation on a real TPU (see module docstring —
    Mosaic has no random-access HBM load yet; both are device-resident)."""
    return _interpret()


class DeviceKeyIndex:
    """The device half of one table's id→row index: bucket arrays in
    device memory plus the host-tracked next-row cursor. The host kv
    stays AUTHORITATIVE for lifecycle (save/load/shrink/items); this
    object mirrors it only while the kv's allocation is dense
    (next_row == len(kv), no free-list holes) — any state it cannot
    mirror exactly flips ``degraded`` and the seam falls back to the
    host path, loudly, forever (sticky)."""

    def __init__(self, capacity: int, n_buckets: Optional[int] = None):
        if n_buckets is None:
            n_buckets = max(_BK * 2, 1 << int(2 * capacity - 1).bit_length())
        if n_buckets & (n_buckets - 1):
            raise ValueError(f"n_buckets must be a power of 2: {n_buckets}")
        self.capacity = int(capacity)
        self.n_buckets = int(n_buckets)
        self.bh = jnp.zeros(self.n_buckets, jnp.int32)
        self.bl = jnp.zeros(self.n_buckets, jnp.int32)
        self.br = jnp.full(self.n_buckets, _EMPTY, jnp.int32)
        self.next_row = 0
        self.degraded = False
        self.degrade_reason = ""

    def degrade(self, reason: str) -> None:
        if not self.degraded:
            log.warning("device key index degraded -> host path: %s",
                        reason)
        self.degraded = True
        self.degrade_reason = reason

    def seed_from_kv(self, kv) -> bool:
        """Mirror an existing kv: only possible when its allocation is
        dense (rows are exactly 0..len-1); inserting the keys in row
        order then reproduces every row. Returns False (→ degrade)
        otherwise."""
        keys, rows = kv.items()
        n = len(keys)
        if n == 0:
            return True
        if n > self.capacity:
            return False
        order = np.argsort(rows, kind="stable")
        if not np.array_equal(rows[order],
                              np.arange(n, dtype=rows.dtype)):
            return False
        out = self.assign_unique(keys[order])
        if out is None:
            return False
        srows, snew = out
        return bool(np.array_equal(srows, np.arange(n, dtype=np.int64))
                    and snew.all())

    def assign_unique(self, uniq: np.ndarray
                      ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Assign rows to a duplicate-free first-seen-ordered key
        stream. Returns (rows int64, new_mask bool) or None on
        probe/capacity overflow (state unchanged — functional
        rollback)."""
        n = len(uniq)
        if n == 0:
            return np.zeros(0, np.int64), np.zeros(0, bool)
        hi, lo = split_keys(np.ascontiguousarray(uniq, np.uint64))
        bh, bl, br, rows, new, ovf = insert(
            self.bh, self.bl, self.br,
            jnp.asarray(_pad_to_block(hi)), jnp.asarray(_pad_to_block(lo)),
            jnp.int32(n), jnp.int32(self.next_row),
            use_pallas=default_use_pallas())
        if bool(ovf):
            return None
        rows = np.asarray(rows[:n]).astype(np.int64)
        new = np.asarray(new[:n]).astype(bool)
        num_new = int(new.sum())
        if self.next_row + num_new > self.capacity:
            return None
        self.bh, self.bl, self.br = bh, bl, br
        self.next_row += num_new
        return rows, new

    def assign_raw(self, keys: np.ndarray) -> Optional[Tuple[
            np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
        """Raw-id front door: device dedup + insert in one program.
        Returns (uniq u64, first_idx, inv, rows_u int64, new_mask) in
        first-seen order, or None on overflow (state unchanged)."""
        n = len(keys)
        if n == 0:
            z = np.zeros(0, np.int64)
            return (np.zeros(0, np.uint64), z.astype(np.int32),
                    np.zeros(0, np.int32), z, np.zeros(0, bool))
        hi, lo = split_keys(np.ascontiguousarray(keys, np.uint64))
        bh, bl, br, uh, ul, first_pos, inv, nu, rows, new, ovf = \
            dedup_insert(
                self.bh, self.bl, self.br,
                jnp.asarray(_pad_to_block(hi)),
                jnp.asarray(_pad_to_block(lo)),
                jnp.int32(n), jnp.int32(self.next_row),
                use_pallas=default_use_pallas())
        if bool(ovf):
            return None
        u = int(nu)
        uniq = join_keys(np.asarray(uh[:u]), np.asarray(ul[:u]))
        rows_u = np.asarray(rows[:u]).astype(np.int64)
        new_u = np.asarray(new[:u]).astype(bool)
        num_new = int(new_u.sum())
        if self.next_row + num_new > self.capacity:
            return None
        self.bh, self.bl, self.br = bh, bl, br
        self.next_row += num_new
        return (uniq, np.asarray(first_pos[:u]), np.asarray(inv[:n]),
                rows_u, new_u)

    def lookup_rows(self, keys: np.ndarray) -> np.ndarray:
        """Probe rows for keys (any order, duplicates fine); miss → -1."""
        n = len(keys)
        if n == 0:
            return np.zeros(0, np.int64)
        hi, lo = split_keys(np.ascontiguousarray(keys, np.uint64))
        rows = lookup(self.bh, self.bl, self.br,
                      jnp.asarray(_pad_to_block(hi)),
                      jnp.asarray(_pad_to_block(lo)), jnp.int32(n),
                      use_pallas=default_use_pallas())
        return np.asarray(rows[:n]).astype(np.int64)
