"""Wire bit-packing for pass uploads — host pack (numpy), device unpack
(jit, a few gathers/shifts on the VPU).

Rationale: the resident-pass pack (train/device_pass.py) is pure index
data whose value ranges are far below 32 bits — unique table rows fit 24
bits at the default 8M-row shard, per-key gather positions fit 18 bits at
the default batch sizes. Host→device bandwidth is the scarce resource
(tunneled dev runs measured 8-500 MB/s; production PCIe is shared with
everything else the host streams), so the pack ships split low/high
arrays and the step reassembles them in-register:

  - 24-bit ("u24"): uint16 low + uint8 high  (3 B/value vs 4)
  - 18-bit ("u18"): uint16 low + 2-bit high packed 4/byte (2.25 B/value)

Both unpacks are exact; values must be non-negative and in range (the
packers assert).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def pack_u24(values: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """int array (any shape, values in [0, 2^24)) → (lo uint16, hi uint8)."""
    v = values.astype(np.uint32, copy=False)
    assert v.max(initial=0) < (1 << 24), "pack_u24 range"
    return (v & 0xFFFF).astype(np.uint16), (v >> 16).astype(np.uint8)


def unpack_u24(lo: jax.Array, hi: jax.Array) -> jax.Array:
    """(lo uint16, hi uint8) → int32, elementwise."""
    return (lo.astype(jnp.int32)
            | (hi.astype(jnp.int32) << 16))


def pack_delta(values: np.ndarray, num_real: np.ndarray,
               max_exceptions: int, bits: int = 16):
    """Ascending per-row sequences → ``bits``-wide (8 or 16) delta wire.

    ``values`` int [nb, U]; rows must be ASCENDING over their real prefix
    ``num_real[i]`` (checked — returns None on violation, as a negative
    delta would wrap mod 2^bits and silently decode to a wrong value).
    Returns (d uint{bits} [nb, U], epos int32 [nb, E], eext int32 [nb, E])
    — deltas relative to values[:, 0] (the base travels separately), with
    up to E per-row gap exceptions (delta ≥ 2^bits) as position+remainder
    pairs (unused slots: epos = U, eext = 0) — or None when a row needs
    more than E exceptions (caller falls back to a wider encoding).

    Decode contract (:func:`unpack_delta16`): value[j] = base +
    cumsum(d)[j] + Σ_e [j ≥ epos_e] · eext_e for j < num_real."""
    assert bits in (8, 16)
    d = _delta_matrix(values, num_real)
    if d is None:
        return None
    return _pack_delta_from(d, max_exceptions, bits)


def _delta_matrix(values: np.ndarray, num_real: np.ndarray):
    """Per-row deltas over the real prefix (int64 [nb, U]), or None if
    any real-prefix row is not ascending."""
    nb, u_pad = values.shape
    d = np.zeros((nb, u_pad), np.int64)
    d[:, 1:] = values[:, 1:].astype(np.int64) - values[:, :-1].astype(np.int64)
    real = np.arange(u_pad)[None, :] < num_real[:, None]
    d[~real] = 0
    if (d < 0).any():
        return None
    return d


def _pack_delta_from(d: np.ndarray, max_exceptions: int, bits: int):
    nb, u_pad = d.shape
    big = d >= (1 << bits)
    if int(big.sum(axis=1).max(initial=0)) > max_exceptions:
        return None
    dn = d.astype(np.uint8 if bits == 8 else np.uint16)
    epos = np.full((nb, max_exceptions), u_pad, np.int32)
    eext = np.zeros((nb, max_exceptions), np.int32)
    for i in range(nb):
        bj = np.nonzero(big[i])[0]
        epos[i, :len(bj)] = bj
        eext[i, :len(bj)] = (d[i, bj] - dn[i, bj]).astype(np.int64)
    return dn, epos, eext


def pack_delta_auto(values: np.ndarray, num_real: np.ndarray,
                    max_exc8: int, max_exc16: int):
    """One delta scan, narrowest width that fits: u8 wire (≤ max_exc8
    gap exceptions per row), else u16 (≤ max_exc16), else None."""
    d = _delta_matrix(values, num_real)
    if d is None:
        return None
    return (_pack_delta_from(d, max_exc8, 8)
            or _pack_delta_from(d, max_exc16, 16))



def unpack_delta16(d16: jax.Array, epos: jax.Array, eext: jax.Array,
                   base: jax.Array) -> jax.Array:
    """One row of the pack_delta16 wire → int32 [U] absolute values
    (traced; valid over the real prefix — callers mask the tail)."""
    u_pad = d16.shape[-1]
    upos = jnp.arange(u_pad, dtype=jnp.int32)
    cum = base + jnp.cumsum(d16.astype(jnp.int32))
    corr = jnp.sum(jnp.where(upos[:, None] >= epos[None, :],
                             eext[None, :], 0), axis=1)
    return cum + corr


def pack_u16m(values: np.ndarray, mbits: int
              ) -> Tuple[np.ndarray, np.ndarray]:
    """int array [..., K] (values in [0, 2^(16+m)), m ∈ {1,2,4,8},
    K % (8/m) == 0) → (lo uint16 [..., K], hi uint8 [..., K*m/8] —
    8/m m-bit highs per byte, little-endian within the byte)."""
    assert mbits in (1, 2, 4, 8)
    v = values.astype(np.uint32, copy=False)
    assert v.max(initial=0) < (1 << (16 + mbits)), "pack_u16m range"
    per = 8 // mbits
    assert v.shape[-1] % per == 0, "pack_u16m alignment"
    lo = (v & 0xFFFF).astype(np.uint16)
    hi = (v >> 16).astype(np.uint8)
    h = hi.reshape(*hi.shape[:-1], -1, per)
    packed = np.zeros(h.shape[:-1], np.uint8)
    for j in range(per):
        packed |= h[..., j] << (j * mbits)
    return lo, packed


def unpack_u16m(lo: jax.Array, hi: jax.Array, mbits: int) -> jax.Array:
    """(lo uint16 [K], hi uint8 [K*m/8]) → int32 [K] (traced)."""
    k = lo.shape[-1]
    per = 8 // mbits
    pos = jnp.arange(k, dtype=jnp.int32)
    byte = hi[..., pos // per].astype(jnp.int32)
    h = (byte >> ((pos % per) * mbits)) & ((1 << mbits) - 1)
    return lo.astype(jnp.int32) | (h << 16)


def pack_u12(values: np.ndarray) -> Tuple[np.ndarray]:
    """int array [..., K] (values in [0, 2^12), K % 2 == 0) → one uint8
    stream [..., K*3/2]: value pairs ride as 3 bytes (lo8_a,
    hi4_a | lo4_b<<4, hi8_b). The thousand-slot wire lever: per-slot
    CTR vocabularies are a few thousand entries, so slot-local rows fit
    12 bits and the u16 wire ships 25% padding (docs/BENCH_SHAPES.md
    thousand row — 2,017 B/record, ~all per-key locals)."""
    v = values.astype(np.uint32, copy=False)
    assert v.max(initial=0) < (1 << 12), "pack_u12 range"
    assert v.shape[-1] % 2 == 0, "pack_u12 alignment"
    p = v.reshape(*v.shape[:-1], -1, 2)
    out = np.empty((*p.shape[:-1], 3), np.uint8)
    out[..., 0] = p[..., 0] & 0xFF
    out[..., 1] = ((p[..., 0] >> 8) & 0xF) | ((p[..., 1] & 0xF) << 4)
    out[..., 2] = (p[..., 1] >> 4) & 0xFF
    return (out.reshape(*v.shape[:-1], -1),)


def unpack_u12(b: jax.Array) -> jax.Array:
    """uint8 [K*3/2] → int32 [K] (traced)."""
    t = b.reshape(*b.shape[:-1], -1, 3).astype(jnp.int32)
    a = t[..., 0] | ((t[..., 1] & 0xF) << 8)
    c = (t[..., 1] >> 4) | (t[..., 2] << 4)
    return jnp.stack([a, c], axis=-1).reshape(*b.shape[:-1], -1)


def pack_u18(values: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """18-bit :func:`pack_u16m` (kept for call-site clarity)."""
    return pack_u16m(values, 2)


def unpack_u18(lo: jax.Array, hi2: jax.Array) -> jax.Array:
    """(lo uint16 [K], hi2 uint8 [K/4]) → int32 [K] (traced)."""
    return unpack_u16m(lo, hi2, 2)
