"""Wire bit-packing for pass uploads — host pack (numpy), device unpack
(jit, a few gathers/shifts on the VPU).

Rationale: the resident-pass pack (train/device_pass.py) is pure index
data whose value ranges are far below 32 bits — unique table rows fit 24
bits at the default 8M-row shard, per-key gather positions fit 18 bits at
the default batch sizes. Host→device bandwidth is the scarce resource
(tunneled dev runs measured 8-500 MB/s; production PCIe is shared with
everything else the host streams), so the pack ships split low/high
arrays and the step reassembles them in-register:

  - 24-bit ("u24"): uint16 low + uint8 high  (3 B/value vs 4)
  - 18-bit ("u18"): uint16 low + 2-bit high packed 4/byte (2.25 B/value)

Both unpacks are exact; values must be non-negative and in range (the
packers assert).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def pack_u24(values: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """int array (any shape, values in [0, 2^24)) → (lo uint16, hi uint8)."""
    v = values.astype(np.uint32, copy=False)
    assert v.max(initial=0) < (1 << 24), "pack_u24 range"
    return (v & 0xFFFF).astype(np.uint16), (v >> 16).astype(np.uint8)


def unpack_u24(lo: jax.Array, hi: jax.Array) -> jax.Array:
    """(lo uint16, hi uint8) → int32, elementwise."""
    return (lo.astype(jnp.int32)
            | (hi.astype(jnp.int32) << 16))


def pack_u18(values: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """int array [..., K] (values in [0, 2^18), K % 4 == 0) →
    (lo uint16 [..., K], hi2 uint8 [..., K/4] — four 2-bit highs/byte)."""
    v = values.astype(np.uint32, copy=False)
    assert v.max(initial=0) < (1 << 18), "pack_u18 range"
    assert v.shape[-1] % 4 == 0, "pack_u18 needs K % 4 == 0"
    lo = (v & 0xFFFF).astype(np.uint16)
    hi = (v >> 16).astype(np.uint8)  # < 4
    h = hi.reshape(*hi.shape[:-1], -1, 4)
    hi2 = (h[..., 0] | (h[..., 1] << 2) | (h[..., 2] << 4)
           | (h[..., 3] << 6)).astype(np.uint8)
    return lo, hi2


def unpack_u18(lo: jax.Array, hi2: jax.Array) -> jax.Array:
    """(lo uint16 [K], hi2 uint8 [K/4]) → int32 [K] (traced)."""
    k = lo.shape[-1]
    pos = jnp.arange(k, dtype=jnp.int32)
    byte = hi2[..., pos >> 2].astype(jnp.int32)
    hi = (byte >> ((pos & 3) * 2)) & 3
    return lo.astype(jnp.int32) | (hi << 16)
