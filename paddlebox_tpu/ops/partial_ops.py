"""partial_concat / partial_sum — column-slice concat/sum over N inputs.

Reference: paddle/fluid/operators/partial_concat_op.* and
partial_sum_op.*: each input [N, C] contributes columns
[start, start+length) (length -1 ⇒ to end); outputs are the slices
concatenated (or summed) — used by wide/LR parts of CTR models.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


def _slice(x: jax.Array, start: int, length: int) -> jax.Array:
    c = x.shape[1]
    s = start if start >= 0 else c + start
    e = c if length < 0 else min(s + length, c)
    return x[:, s:e]


def partial_concat(xs: Sequence[jax.Array], start_index: int = 0,
                   length: int = -1) -> jax.Array:
    return jnp.concatenate([_slice(x, start_index, length) for x in xs],
                           axis=1)


def partial_sum(xs: Sequence[jax.Array], start_index: int = 0,
                length: int = -1) -> jax.Array:
    out = _slice(xs[0], start_index, length)
    for x in xs[1:]:
        out = out + _slice(x, start_index, length)
    return out
