"""cross_norm_hadamard — fused cross-network hadamard + normalization.

Reference: paddle/fluid/operators/cross_norm_hadamard_op.{cc,cu} +
cross_norm_hadamard.cu.h (nncross_normforward_multi :*): input is n field
PAIRS of embed_dim vectors ``[B, 2*n*d]``; per pair the output block of
``3d+1`` columns is [a, b, a⊙b, a·b], each column normalized with
data_norm-style summary stats (mean = sum/size, scale = sqrt(size/sq_sum)).
Output ``[B, n*(3d+1)]``. The summary updates with decay
``summary_decay_rate`` (default 0.9999999); ``sync_stats`` (multi-GPU NCCL
reduce of batch stats) maps to a psum over the data axis before
``cross_norm_update`` when training sharded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddlebox_tpu.ops.data_norm import (DataNormSummary, data_norm,
                                         data_norm_update,
                                         init_data_norm_summary)


def cross_features(x: jax.Array, fields_num: int, embed_dim: int) -> jax.Array:
    """[B, 2*n*d] → raw cross features [B, n*(3d+1)] (pre-normalization)."""
    b = x.shape[0]
    n, d = fields_num, embed_dim
    pairs = x.reshape(b, n, 2, d)
    a, bb = pairs[:, :, 0], pairs[:, :, 1]          # [B, n, d]
    had = a * bb
    dot = jnp.sum(had, axis=-1, keepdims=True)      # [B, n, 1]
    return jnp.concatenate([a, bb, had, dot], axis=-1).reshape(b, n * (3 * d + 1))


def cross_norm_hadamard(x: jax.Array, summary: DataNormSummary,
                        fields_num: int, embed_dim: int,
                        epsilon: float = 1e-4) -> jax.Array:
    feats = cross_features(x, fields_num, embed_dim)
    return data_norm(feats, summary, epsilon=epsilon)


def cross_norm_update(summary: DataNormSummary, x: jax.Array,
                      fields_num: int, embed_dim: int,
                      decay: float = 0.9999999) -> DataNormSummary:
    feats = cross_features(x, fields_num, embed_dim)
    return data_norm_update(summary, jax.lax.stop_gradient(feats),
                            decay=decay)


def init_cross_norm_summary(fields_num: int,
                            embed_dim: int) -> DataNormSummary:
    return init_data_norm_summary(fields_num * (3 * embed_dim + 1))
