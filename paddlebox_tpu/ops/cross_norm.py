"""cross_norm_hadamard — fused cross-network hadamard + normalization.

Reference: paddle/fluid/operators/cross_norm_hadamard_op.{cc,cu} +
cross_norm_hadamard.cu.h (nncross_normforward_multi :*): input is n field
PAIRS of embed_dim vectors ``[B, 2*n*d]``; per pair the output block of
``3d+1`` columns is [a, b, a⊙b, a·b], each column normalized with
data_norm-style summary stats (mean = sum/size, scale = sqrt(size/sq_sum)).
Output ``[B, n*(3d+1)]``. The summary updates with decay
``summary_decay_rate`` (default 0.9999999); ``sync_stats`` (multi-GPU NCCL
reduce of batch stats) maps to a psum over the data axis before the
summary fold — pass ``sync_axis`` to :func:`cross_norm_update` inside a
shard_map/pmap when training sharded.

THE dispatch seam (ISSUE 13): under ``FLAGS.use_pallas_cross_norm``
(and the static VMEM residency check) the forward runs as
``ops.pallas_ctr.fused_cross_norm_hadamard`` — one VMEM pass per
(row-block, field) emitting the normalized [a, b, a⊙b, a·b] block in
the same residency. The summary-derived mean/scale are computed here
(outside the kernel) so the summary cotangent chain is unchanged; the
summary UPDATE (and its sync_stats psum) stays outside on every path.
Both decisions book ``pbox_kernel_dispatch_total{kernel="cross_norm"}``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.ops.data_norm import (DataNormSummary, data_norm,
                                         data_norm_fold_stats,
                                         data_norm_mean_scale,
                                         data_norm_update,
                                         init_data_norm_summary)
from paddlebox_tpu.ops.pallas_ctr import (_book_dispatch, cross_norm_fits,
                                          fused_cross_norm_hadamard)


def cross_features(x: jax.Array, fields_num: int, embed_dim: int) -> jax.Array:
    """[B, 2*n*d] → raw cross features [B, n*(3d+1)] (pre-normalization)."""
    b = x.shape[0]
    n, d = fields_num, embed_dim
    pairs = x.reshape(b, n, 2, d)
    a, bb = pairs[:, :, 0], pairs[:, :, 1]          # [B, n, d]
    had = a * bb
    dot = jnp.sum(had, axis=-1, keepdims=True)      # [B, n, 1]
    return jnp.concatenate([a, bb, had, dot], axis=-1).reshape(b, n * (3 * d + 1))


def cross_norm_hadamard(x: jax.Array, summary: DataNormSummary,
                        fields_num: int, embed_dim: int,
                        epsilon: float = 1e-4) -> jax.Array:
    if FLAGS.use_pallas_cross_norm and cross_norm_fits(embed_dim):
        _book_dispatch("cross_norm", "pallas")
        # the data_norm mean/scale derivation stays OUTSIDE the fused
        # op (differentiable — the summary cotangent chain is the
        # composition's); the kernel applies them in-residency
        mean, scale = data_norm_mean_scale(summary, epsilon)
        return fused_cross_norm_hadamard(x, mean, scale, fields_num,
                                         embed_dim)
    _book_dispatch("cross_norm", "xla")
    feats = cross_features(x, fields_num, embed_dim)
    return data_norm(feats, summary, epsilon=epsilon)


def cross_norm_update(summary: DataNormSummary, x: jax.Array,
                      fields_num: int, embed_dim: int,
                      decay: float = 0.9999999,
                      sync_axis: str = None) -> DataNormSummary:
    """Fold a batch's cross-feature stats into the summary.

    ``sync_axis``: the reference's ``sync_stats`` attr (multi-GPU NCCL
    allreduce of the batch count/sum/square-sum BEFORE the decayed fold,
    cross_norm_hadamard_op.cu) — pass the data mesh axis name when
    calling inside shard_map/pmap and every shard folds the GLOBAL
    batch statistics, keeping summaries bit-identical across shards."""
    feats = jax.lax.stop_gradient(
        cross_features(x, fields_num, embed_dim))
    if sync_axis is None:
        return data_norm_update(summary, feats, decay=decay)
    bsz = jax.lax.psum(jnp.asarray(feats.shape[0], jnp.float32), sync_axis)
    s = jax.lax.psum(jnp.sum(feats, axis=0), sync_axis)
    q = jax.lax.psum(jnp.sum(jnp.square(feats), axis=0), sync_axis)
    # the data_norm fold over the psum'd GLOBAL stats — one shared
    # definition, so sync and plain updates cannot drift
    return data_norm_fold_stats(summary, bsz, s, q, decay=decay)


def init_cross_norm_summary(fields_num: int,
                            embed_dim: int) -> DataNormSummary:
    return init_data_norm_summary(fields_num * (3 * embed_dim + 1))
