"""fused_seqpool_cvm — the core CTR fusion.

Reference: paddle/fluid/operators/fused/fused_seqpool_cvm_op.{cc,cu}
(attrs at fused_seqpool_cvm_op.cc:28-106; kernels: FusedSeqpoolKernelNormal/
Quant/QuantFilter :36-133, FusedCVMKernelWithCVM :276-298 —
out0=log(show+1), out1=log(click+1)-log(show+1) — and the backward
FusedSeqpoolCVMGradKernelWithCVM :634-657 where the first ``cvm_offset``
output dims receive the batch CVM values instead of chain-rule grads, so the
pushed sparse grad carries show/clk statistics to the PS).

TPU-native redesign: the reference launches one CUDA kernel over N per-slot
LoDTensors with a device LoD table. Here every slot of every instance is one
segment of a single flattened ``[K, D]`` value tensor (segment id =
ins*S + slot, built host-side by BatchBuilder), so the whole 1000-slot fusion
is ONE ``jax.ops.segment_sum`` + elementwise epilogue — XLA fuses the filter,
quantization, and CVM transform into the scatter-add; no per-slot launches,
no dynamic shapes. Backward is a ``custom_vjp`` replicating the reference's
show/clk-value-as-grad contract (a gather over segments — also one fused op).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from paddlebox_tpu.ops.pallas_kernels import segment_sum


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11, 12))
def fused_seqpool_cvm(
    values: jax.Array,          # [K, D] pulled embeddings (D includes cvm dims)
    segments: jax.Array,        # [K] int32, ins*S + slot; pad rows → B*S
    batch_show_clk: jax.Array,  # [B, cvm_offset] batch show/clk (CVM input)
    batch_size: int,
    num_slots: int,
    use_cvm: bool = True,
    cvm_offset: int = 2,
    pad_value: float = 0.0,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold: float = 0.96,
    quant_ratio: int = 0,
) -> jax.Array:
    """Returns [B, S, D] if use_cvm else [B, S, D - cvm_offset]."""
    out, _ = _fwd(values, segments, batch_show_clk, batch_size, num_slots,
                  use_cvm, cvm_offset, pad_value, need_filter, show_coeff,
                  clk_coeff, threshold, quant_ratio)
    return out


def _fwd(values, segments, batch_show_clk, batch_size, num_slots, use_cvm,
         cvm_offset, pad_value, need_filter, show_coeff, clk_coeff,
         threshold, quant_ratio):
    d = values.shape[1]
    v = values
    if quant_ratio > 0:
        # quantize embedx dims only; cvm dims pass through (:78-90) — safe
        # before the filter since the filter reads only the cvm columns
        q = jnp.floor(v * quant_ratio + 0.5) / quant_ratio
        col = jnp.arange(d) >= cvm_offset
        v = jnp.where(col[None, :], q, v)
    # filter: FusedSeqpoolKernelQuantFilter :93-133 — drop items failing the
    # show/clk significance test
    pooled, keep = _filtered_pool(v, segments, batch_size, num_slots,
                                  pad_value, need_filter, show_coeff,
                                  clk_coeff, threshold)
    if use_cvm:
        # FusedCVMKernelWithCVM :276: [log(show+1), log(clk+1)-log(show+1), …]
        show_l = jnp.log1p(pooled[..., 0:1])
        ctr = jnp.log1p(pooled[..., 1:2]) - show_l
        out = jnp.concatenate([show_l, ctr, pooled[..., cvm_offset:]], axis=-1)
    else:
        out = pooled[..., cvm_offset:]
    # zero-size token carries the primal dtype/width through residuals
    vtoken = jnp.zeros((0, values.shape[1]), values.dtype)
    return out, (segments, keep, vtoken, batch_show_clk)


def _bwd(batch_size, num_slots, use_cvm, cvm_offset, pad_value, need_filter,
         show_coeff, clk_coeff, threshold, quant_ratio, res, g):
    segments, keep, vtoken, batch_show_clk = res
    d = vtoken.shape[1]
    vdtype = vtoken.dtype
    # Reference backward (:634-657): embedx dims broadcast the output grad to
    # every surviving sequence item; the first cvm_offset dims carry the
    # *batch CVM values* (show/clk) so the sparse push learns counters.
    # Quant and the log transform are straight-through, exactly as the CUDA
    # grad kernel ignores them.
    embedx_g = g[..., cvm_offset:] if use_cvm else g
    flat = embedx_g.reshape(batch_size * num_slots, d - cvm_offset)
    if segments is None:
        # trivial layout: key j ↔ segment j — the gather is a pad/slice
        k = keep.shape[0]
        n = batch_size * num_slots
        if k > n:
            flat = jnp.concatenate(
                [flat, jnp.zeros((k - n, d - cvm_offset), flat.dtype)])
        g_embedx = flat[:k]
        seg_ids = jnp.arange(k, dtype=jnp.int32)
        pad = seg_ids >= n
        ins = jnp.minimum(seg_ids // num_slots, batch_size - 1)
    else:
        flat = jnp.concatenate(
            [flat, jnp.zeros((1, d - cvm_offset), flat.dtype)], axis=0)
        g_embedx = flat[segments]                          # [K, D-cvm]
        ins = jnp.minimum(segments // num_slots, batch_size - 1)
        pad = segments >= batch_size * num_slots
    g_cvm = batch_show_clk[ins]                            # [K, cvm_offset]
    g_values = jnp.where(
        (keep & ~pad)[:, None],
        jnp.concatenate([g_cvm.astype(g_embedx.dtype), g_embedx], axis=-1),
        0.0,
    ).astype(vdtype)
    return (g_values, None, None)


fused_seqpool_cvm.defvjp(_fwd, _bwd)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def fused_seqpool_cvm_with_conv(
    values: jax.Array,          # [K, D], D includes 3 cvm cols (show,clk,conv)
    segments: jax.Array,
    batch_show_clk_conv: jax.Array,  # [B, 3]
    batch_size: int,
    num_slots: int,
    use_cvm: bool = True,
    show_filter: bool = False,
    pad_value: float = 0.0,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold: float = 0.96,
) -> jax.Array:
    """Show/click/conversion-rate variant
    (fused/fused_seqpool_cvm_with_conv_op.cu:143-147): CVM head is
    [log(show+1), log(clk+1), log(conv+1)-log(clk+1)]; show_filter strips
    the show column from the output."""
    out, _ = _fwd_conv(values, segments, batch_show_clk_conv, batch_size,
                       num_slots, use_cvm, show_filter, pad_value,
                       need_filter, show_coeff, clk_coeff, threshold)
    return out


_CONV_OFFSET = 3


def _pool_core(values, segments, batch_size, num_slots, keep=None,
               pad_value=0.0):
    """The one shared pooling body: mask → segment-sum → [B, S, D]
    (+pad). Every seqpool op and variant goes through here.

    ``segments=None`` declares the TRIVIAL layout (exactly one key per
    (instance, slot), slot-ordered — the common CTR schema): the pool is
    then a pure reshape, skipping the TPU scatter-add entirely (scatters
    carry ~20ms fixed cost per call on v5p; the reshape is free)."""
    if keep is not None:
        values = jnp.where(keep[:, None], values, 0.0)
    d = values.shape[1]
    if segments is None:
        k = values.shape[0]
        n = batch_size * num_slots
        if k < n:  # key bucket smaller than B*S (partial batches)
            values = jnp.concatenate(
                [values, jnp.zeros((n - k, d), values.dtype)])
        return values[:n].reshape(batch_size, num_slots, d) + pad_value
    num_segments = batch_size * num_slots + 1
    pooled = segment_sum(values, segments, num_segments)
    return pooled[:-1].reshape(batch_size, num_slots, d) + pad_value


def _filtered_pool(values, segments, batch_size, num_slots, pad_value,
                   need_filter, show_coeff, clk_coeff, threshold):
    """Shared filter + segment-sum (both seqpool variants)."""
    k = values.shape[0]
    if need_filter:
        show, clk = values[:, 0], values[:, 1]
        keep = ((show - clk) * show_coeff + clk * clk_coeff) >= threshold
    else:
        keep = jnp.ones((k,), dtype=bool)
    return _pool_core(values, segments, batch_size, num_slots, keep,
                      pad_value), keep


def _fwd_conv(values, segments, batch_cvm, batch_size, num_slots, use_cvm,
              show_filter, pad_value, need_filter, show_coeff, clk_coeff,
              threshold):
    d = values.shape[1]
    pooled, keep = _filtered_pool(values, segments, batch_size, num_slots,
                                  pad_value, need_filter, show_coeff,
                                  clk_coeff, threshold)
    if use_cvm:
        show_l = jnp.log1p(pooled[..., 0:1])
        clk_l = jnp.log1p(pooled[..., 1:2])
        cvr = jnp.log1p(pooled[..., 2:3]) - clk_l
        head = [clk_l, cvr] if show_filter else [show_l, clk_l, cvr]
        out = jnp.concatenate(head + [pooled[..., _CONV_OFFSET:]], axis=-1)
    else:
        out = pooled[..., _CONV_OFFSET:]
    vtoken = jnp.zeros((0, d), values.dtype)
    return out, (segments, keep, vtoken, batch_cvm)


def _bwd_conv(batch_size, num_slots, use_cvm, show_filter, pad_value,
              need_filter, show_coeff, clk_coeff, threshold, res, g):
    segments, keep, vtoken, batch_cvm = res
    d = vtoken.shape[1]
    co = _CONV_OFFSET
    n_head = (co - 1 if show_filter else co) if use_cvm else 0
    embedx_g = g[..., n_head:]
    flat = embedx_g.reshape(batch_size * num_slots, d - co)
    flat = jnp.concatenate(
        [flat, jnp.zeros((1, d - co), flat.dtype)], axis=0)
    g_embedx = flat[segments]
    ins = jnp.minimum(segments // num_slots, batch_size - 1)
    g_cvm = batch_cvm[ins]
    pad = segments >= batch_size * num_slots
    g_values = jnp.where(
        (keep & ~pad)[:, None],
        jnp.concatenate([g_cvm.astype(g_embedx.dtype), g_embedx], axis=-1),
        0.0,
    ).astype(vtoken.dtype)
    return (g_values, None, None)


fused_seqpool_cvm_with_conv.defvjp(_fwd_conv, _bwd_conv)


def fused_seqpool_concat(values, segments, batch_size, num_slots,
                         pad_value=0.0):
    """Plain seqpool + concat (fusion_seqpool_concat_op): our fused op with
    no CVM columns (cvm_offset=0, use_cvm=False path without stripping)."""
    num_segments = batch_size * num_slots + 1
    pooled = segment_sum(values, segments, num_segments)
    return pooled[:-1].reshape(batch_size, num_slots, -1) + pad_value
