"""fused_seqpool_cvm — the core CTR fusion.

Reference: paddle/fluid/operators/fused/fused_seqpool_cvm_op.{cc,cu}
(attrs at fused_seqpool_cvm_op.cc:28-106; kernels: FusedSeqpoolKernelNormal/
Quant/QuantFilter :36-133, FusedCVMKernelWithCVM :276-298 —
out0=log(show+1), out1=log(click+1)-log(show+1) — and the backward
FusedSeqpoolCVMGradKernelWithCVM :634-657 where the first ``cvm_offset``
output dims receive the batch CVM values instead of chain-rule grads, so the
pushed sparse grad carries show/clk statistics to the PS).

TPU-native redesign: the reference launches one CUDA kernel over N per-slot
LoDTensors with a device LoD table. Here every slot of every instance is one
segment of a single flattened ``[K, D]`` value tensor (segment id =
ins*S + slot, built host-side by BatchBuilder), so the whole 1000-slot fusion
is ONE ``jax.ops.segment_sum`` + elementwise epilogue — XLA fuses the filter,
quantization, and CVM transform into the scatter-add; no per-slot launches,
no dynamic shapes. Backward is a ``custom_vjp`` replicating the reference's
show/clk-value-as-grad contract (a gather over segments — also one fused op).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.ops.pallas_kernels import (CVM_CONV, CVM_FULL, CVM_NONE,
                                              CVM_SHOW, _book_dispatch,
                                              fused_pool_cvm_forward,
                                              segment_gather_mxu,
                                              keep_or_ones, segment_sum,
                                              show_clk_keep)


@functools.partial(
    jax.custom_vjp,
    nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17,
                     18))
def fused_seqpool_cvm(
    values: jax.Array,          # [K, D] pulled embeddings (D includes cvm dims)
    segments: jax.Array,        # [K] int32, ins*S + slot; pad rows → B*S
    batch_show_clk: jax.Array,  # [B, cvm_offset] batch show/clk (CVM input)
    batch_size: int,
    num_slots: int,
    use_cvm: bool = True,
    cvm_offset: int = 2,
    pad_value: float = 0.0,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold: float = 0.96,
    quant_ratio: int = 0,
    clk_filter: bool = False,
    embed_threshold_filter: bool = False,
    embed_threshold: float = 0.0,
    embed_thres_size: int = 0,
    embedx_concate_size: int = 1,
    embedx_concate_filter: bool = False,
    key_valid: jax.Array = None,
) -> jax.Array:
    """Full attr surface of fused_seqpool_cvm_op.cc:124-146.

    Output width per slot (InferShape :77-98), with k =
    ``embedx_concate_size``:
      use_cvm, clk_filter  → (D-1)*k    [log(show+1), embedx…] per block
      use_cvm              → D          [log(show+1), log(clk+1)-…, …]
                             (k is IGNORED here — the reference has no
                             concate kernel for the plain-CVM case and
                             InferShape keeps width D)
      no cvm               → (D - cvm_offset - embed_thres_size)*k

    ``embed_threshold_filter`` additionally drops keys whose embed
    magnitude |e0| + ||e1..ets-1|| falls below ``embed_threshold``
    (KernelEmbedQuantFilter :134-176). ``embedx_concate_size`` k > 1
    emits the first k keys of each (ins, slot) sequence individually
    instead of sum-pooling (…EmbedxConcate kernels); filtered keys leave
    pad_value blocks when ``embedx_concate_filter``.

    ``key_valid`` (float [K], 1.0 = real key) masks batch padding in the
    backward when ``segments`` is None (the trivial layout has no pad
    bin to route pads into; without it, callers must guarantee pad
    positions' gather_idx point at masked rows)."""
    out, _ = _fwd(values, segments, batch_show_clk, batch_size, num_slots,
                  use_cvm, cvm_offset, pad_value, need_filter, show_coeff,
                  clk_coeff, threshold, quant_ratio, clk_filter,
                  embed_threshold_filter, embed_threshold,
                  embed_thres_size, embedx_concate_size,
                  embedx_concate_filter, key_valid)
    return out


def _keep_mask(v, cvm_offset, need_filter, show_coeff, clk_coeff, threshold,
               embed_threshold_filter, embed_threshold, embed_thres_size):
    """Key keep flags: show/clk significance (QuantFilter :93-133) and
    the embed-magnitude test (KernelEmbedQuantFilter :134-176)."""
    k, d = v.shape
    if not (need_filter or embed_threshold_filter):
        return jnp.ones((k,), dtype=bool)
    keep = show_clk_keep(v, show_coeff, clk_coeff, threshold)
    if embed_threshold_filter:
        ets = embed_thres_size if embed_thres_size > 0 else d - cvm_offset
        e = v[:, cvm_offset:cvm_offset + ets]
        score = jnp.sqrt(jnp.sum(e[:, 1:] * e[:, 1:], axis=1)) \
            + jnp.abs(e[:, 0])
        keep = keep & (score >= embed_threshold)
    return keep


def _segment_ranks(segments):
    """Occurrence index of each key within its segment (stable)."""
    k = segments.shape[0]
    pos = jnp.arange(k, dtype=jnp.int32)
    ss, order = jax.lax.sort((segments, pos), num_keys=1)
    is_start = jnp.concatenate([jnp.ones(1, bool), ss[1:] != ss[:-1]])
    run_start = jax.lax.cummax(jnp.where(is_start, pos, 0))
    rank_sorted = pos - run_start
    return jnp.zeros(k, jnp.int32).at[order].set(rank_sorted,
                                                 unique_indices=True)


def _fwd(values, segments, batch_show_clk, batch_size, num_slots, use_cvm,
         cvm_offset, pad_value, need_filter, show_coeff, clk_coeff,
         threshold, quant_ratio, clk_filter, embed_threshold_filter,
         embed_threshold, embed_thres_size, embedx_concate_size,
         embedx_concate_filter, key_valid):
    d = values.shape[1]
    kk = embedx_concate_size
    if use_cvm and not clk_filter:
        kk = 1  # reference has no concate kernel for plain CVM
    v = values
    if quant_ratio > 0:
        # quantize embedx dims only; cvm dims pass through (:78-90) — safe
        # before the filter since the filter reads only the cvm columns
        q = jnp.floor(v * quant_ratio + 0.5) / quant_ratio
        col = jnp.arange(d) >= cvm_offset
        v = jnp.where(col[None, :], q, v)
    keep = _keep_mask(v, cvm_offset, need_filter, show_coeff, clk_coeff,
                      threshold, embed_threshold_filter, embed_threshold,
                      embed_thres_size)
    rank = None
    fused_out = None
    if kk == 1:
        if segments is not None and FLAGS.use_pallas_seqpool:
            # THE dispatch seam (ISSUE 12): one fused Pallas pass —
            # blocked gather of the pulled rows + MXU one-hot pooling +
            # in-VMEM CVM epilogue — replaces _pool_core + the jnp CVM
            # transform below. The trivial (segments=None) layout keeps
            # its reshape fast path: it has no scatter to kill, and the
            # reshape is free (see _pool_core).
            _book_dispatch("fused_embed_pool_cvm", "pallas")
            mode = CVM_NONE if not use_cvm else (
                CVM_SHOW if clk_filter else CVM_FULL)
            fused_out = fused_pool_cvm_forward(
                v, segments, keep.astype(jnp.float32), batch_size,
                num_slots, cvm_mode=mode, cvm_offset=cvm_offset,
                ets=(0 if use_cvm else embed_thres_size),
                pad_value=pad_value)
        else:
            _book_dispatch("fused_embed_pool_cvm",
                           "reshape" if segments is None else "xla")
        if fused_out is None:
            pooled = _pool_core(v, segments, batch_size, num_slots, keep,
                                pad_value)                # [B, S, D]
    else:
        # …EmbedxConcate kernels: the j-th block is the (start+j)-th key
        # of the sequence, NOT sum-pooled; keys at rank ≥ k drop
        if segments is None:
            # trivial layout: one key per segment — every rank is 0
            segs = jnp.arange(v.shape[0], dtype=jnp.int32)
            rank = jnp.zeros(v.shape[0], jnp.int32)
        else:
            segs = segments
            rank = _segment_ranks(segs)
        drop = rank >= kk
        if embedx_concate_filter:
            drop = drop | ~keep
        n2 = batch_size * num_slots * kk
        drop_all = drop | (segs >= batch_size * num_slots)
        if FLAGS.use_pallas_seqpool:
            # −1 drop markers keep the non-drop id stream nondecreasing
            # for the MXU pair grid (a mid-stream n2 marker would break
            # the blocked one-hot's monotone output-visit order); the
            # default path keeps its historical n2 discard bin verbatim
            seg2 = jnp.where(drop_all, -1, segs * kk + rank)
        else:
            seg2 = jnp.where(drop_all, n2, segs * kk + rank)
        vv = jnp.where(drop[:, None], 0.0, v)
        pooled = segment_sum(vv, seg2, n2 + 1)[:-1]
        if pad_value:
            # pad_value fills EMPTY blocks only; emitted keys are verbatim
            cnt = segment_sum(jnp.where(drop, 0.0, 1.0)[:, None], seg2,
                              n2 + 1)[:-1]
            pooled = jnp.where(cnt > 0, pooled, pad_value)
        pooled = pooled.reshape(batch_size, num_slots, kk, d)
    if fused_out is not None:
        out = fused_out
    elif use_cvm:
        show_l = jnp.log1p(pooled[..., 0:1])
        if clk_filter:
            # FusedCVMKernelWithShow :301: [log(show+1), embedx…] — the
            # click column is skipped entirely
            out = jnp.concatenate([show_l, pooled[..., cvm_offset:]],
                                  axis=-1)
        else:
            # FusedCVMKernelWithCVM :276: [log(show+1),
            # log(clk+1)-log(show+1), …]
            ctr = jnp.log1p(pooled[..., 1:2]) - show_l
            out = jnp.concatenate([show_l, ctr, pooled[..., cvm_offset:]],
                                  axis=-1)
    else:
        # FusedCVMKernelNoCVM :355: additionally skip the first
        # embed_thres_size embed dims (InferShape width contract :95)
        out = pooled[..., cvm_offset + embed_thres_size:]
    if kk > 1:
        out = out.reshape(batch_size, num_slots, -1)
    # zero-size token carries the primal dtype/width through residuals
    vtoken = jnp.zeros((0, values.shape[1]), values.dtype)
    return out, (segments, keep, vtoken, batch_show_clk, rank, key_valid)


def _bwd(batch_size, num_slots, use_cvm, cvm_offset, pad_value, need_filter,
         show_coeff, clk_coeff, threshold, quant_ratio, clk_filter,
         embed_threshold_filter, embed_threshold, embed_thres_size,
         embedx_concate_size, embedx_concate_filter, res, g):
    segments, keep, vtoken, batch_show_clk, rank, key_valid = res
    d = vtoken.shape[1]
    kk = embedx_concate_size
    vdtype = vtoken.dtype
    # Reference backward (:634-716): embedx dims broadcast the output grad
    # to every surviving sequence item; the first cvm_offset dims carry
    # the *batch CVM values* (show/clk) so the sparse push learns
    # counters. Quant and the log transform are straight-through, exactly
    # as the CUDA grad kernels ignore them.
    kk = 1 if (use_cvm and not clk_filter) else kk
    # the use_cvm output head is the TRANSFORMED columns — one for the
    # clk_filter head, TWO (log1p(show), ctr) otherwise, regardless of
    # cvm_offset (which only sets how many input columns they replace)
    n_head = (1 if clk_filter else 2) if use_cvm else 0
    ets = 0 if use_cvm else embed_thres_size
    w = d - cvm_offset - ets          # embedx dims receiving real grads
    if kk > 1:
        g = g.reshape(batch_size, num_slots, kk, n_head + w)
    embedx_g = g[..., n_head:]
    k_keys = keep.shape[0]
    n = batch_size * num_slots
    if kk > 1:
        segs = (jnp.arange(k_keys, dtype=jnp.int32) if segments is None
                else segments)
        drop = rank >= kk
        if embedx_concate_filter:
            drop = drop | ~keep
        if FLAGS.use_pallas_seqpool:
            # transposed one-hot matmul on the MXU (bitwise a gather);
            # −1 markers drop exactly like the n*kk discard row below
            _book_dispatch("seqpool_grad", "mxu")
            idx = jnp.where(drop | (segs >= n), -1, segs * kk + rank)
            g_embedx = segment_gather_mxu(
                embedx_g.reshape(n * kk, w), idx)
        else:
            flat = jnp.concatenate(
                [embedx_g.reshape(n * kk, w), jnp.zeros((1, w), g.dtype)])
            idx = jnp.where(drop | (segs >= n), n * kk, segs * kk + rank)
            g_embedx = flat[idx]
        ins = jnp.minimum(segs // num_slots, batch_size - 1)
        pad = segs >= n
        contrib = ~drop
    else:
        if segments is None:
            # trivial layout: key j ↔ segment j — the gather is a slice
            flat = embedx_g.reshape(n, w)
            if k_keys > n:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((k_keys - n, w), flat.dtype)])
            g_embedx = flat[:k_keys]
            seg_ids = jnp.arange(k_keys, dtype=jnp.int32)
            pad = seg_ids >= n
            ins = jnp.minimum(seg_ids // num_slots, batch_size - 1)
        elif FLAGS.use_pallas_seqpool:
            # the push-path grad gather on the MXU — the fused kernel's
            # backward half (pads/OOB ids produce zero rows, exactly the
            # discard-row contract of the XLA composition below)
            _book_dispatch("seqpool_grad", "mxu")
            g_embedx = segment_gather_mxu(embedx_g.reshape(n, w),
                                          segments)       # [K, w]
            ins = jnp.minimum(segments // num_slots, batch_size - 1)
            pad = segments >= n
        else:
            flat = jnp.concatenate(
                [embedx_g.reshape(n, w), jnp.zeros((1, w), g.dtype)])
            g_embedx = flat[segments]                      # [K, w]
            ins = jnp.minimum(segments // num_slots, batch_size - 1)
            pad = segments >= n
        contrib = keep
    if key_valid is not None:
        pad = pad | (key_valid <= 0)
    g_cvm = batch_show_clk[ins].astype(g_embedx.dtype)     # [K, cvm_offset]
    parts = [g_cvm]
    if ets:
        parts.append(jnp.zeros((k_keys, ets), g_embedx.dtype))
    parts.append(g_embedx)
    g_values = jnp.where(
        (contrib & ~pad)[:, None],
        jnp.concatenate(parts, axis=-1),
        0.0,
    ).astype(vdtype)
    return (g_values, None, None, None)


fused_seqpool_cvm.defvjp(_fwd, _bwd)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def fused_seqpool_cvm_with_conv(
    values: jax.Array,          # [K, D], D includes 3 cvm cols (show,clk,conv)
    segments: jax.Array,
    batch_show_clk_conv: jax.Array,  # [B, 3]
    batch_size: int,
    num_slots: int,
    use_cvm: bool = True,
    show_filter: bool = False,
    pad_value: float = 0.0,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold: float = 0.96,
) -> jax.Array:
    """Show/click/conversion-rate variant
    (fused/fused_seqpool_cvm_with_conv_op.cu:143-147): CVM head is
    [log(show+1), log(clk+1), log(conv+1)-log(clk+1)]; show_filter strips
    the show column from the output."""
    out, _ = _fwd_conv(values, segments, batch_show_clk_conv, batch_size,
                       num_slots, use_cvm, show_filter, pad_value,
                       need_filter, show_coeff, clk_coeff, threshold)
    return out


_CONV_OFFSET = 3


def _pool_core(values, segments, batch_size, num_slots, keep=None,
               pad_value=0.0):
    """The one shared pooling body: mask → segment-sum → [B, S, D]
    (+pad). Every seqpool op and variant goes through here; the
    ``segment_sum`` call below is itself a dispatch seam
    (``FLAGS.use_pallas_seqpool`` → the MXU one-hot kernel), and the
    main ``fused_seqpool_cvm`` forward bypasses this body entirely
    under the flag in favor of the FUSED pool+CVM Pallas pass
    (ops/pallas_kernels.fused_pool_cvm_forward — ISSUE 12).

    ``segments=None`` declares the TRIVIAL layout (exactly one key per
    (instance, slot), slot-ordered — the common CTR schema): the pool is
    then a pure reshape, skipping the TPU scatter-add entirely (scatters
    carry ~20ms fixed cost per call on v5p; the reshape is free) — the
    Pallas dispatch deliberately leaves this fast path alone."""
    if keep is not None:
        values = jnp.where(keep[:, None], values, 0.0)
    d = values.shape[1]
    if segments is None:
        k = values.shape[0]
        n = batch_size * num_slots
        if k < n:  # key bucket smaller than B*S (partial batches)
            values = jnp.concatenate(
                [values, jnp.zeros((n - k, d), values.dtype)])
        return values[:n].reshape(batch_size, num_slots, d) + pad_value
    num_segments = batch_size * num_slots + 1
    pooled = segment_sum(values, segments, num_segments)
    return pooled[:-1].reshape(batch_size, num_slots, d) + pad_value


def _filtered_pool(values, segments, batch_size, num_slots, pad_value,
                   need_filter, show_coeff, clk_coeff, threshold):
    """Shared filter + segment-sum (both seqpool variants)."""
    keep = keep_or_ones(values, need_filter, show_coeff, clk_coeff,
                        threshold)
    return _pool_core(values, segments, batch_size, num_slots, keep,
                      pad_value), keep


def _fwd_conv(values, segments, batch_cvm, batch_size, num_slots, use_cvm,
              show_filter, pad_value, need_filter, show_coeff, clk_coeff,
              threshold):
    d = values.shape[1]
    if segments is not None and FLAGS.use_pallas_seqpool:
        # same fused dispatch seam, conv head (CVM_CONV transforms the
        # 3-column show/clk/conv head in-VMEM); show_filter slices the
        # show column off the fused output
        _book_dispatch("fused_embed_pool_cvm", "pallas")
        keep = keep_or_ones(values, need_filter, show_coeff, clk_coeff,
                            threshold)
        out = fused_pool_cvm_forward(
            values, segments, keep.astype(jnp.float32), batch_size,
            num_slots, cvm_mode=CVM_CONV if use_cvm else CVM_NONE,
            cvm_offset=_CONV_OFFSET, pad_value=pad_value)
        if use_cvm and show_filter:
            out = out[..., 1:]
        vtoken = jnp.zeros((0, d), values.dtype)
        return out, (segments, keep, vtoken, batch_cvm)
    pooled, keep = _filtered_pool(values, segments, batch_size, num_slots,
                                  pad_value, need_filter, show_coeff,
                                  clk_coeff, threshold)
    if use_cvm:
        show_l = jnp.log1p(pooled[..., 0:1])
        clk_l = jnp.log1p(pooled[..., 1:2])
        cvr = jnp.log1p(pooled[..., 2:3]) - clk_l
        head = [clk_l, cvr] if show_filter else [show_l, clk_l, cvr]
        out = jnp.concatenate(head + [pooled[..., _CONV_OFFSET:]], axis=-1)
    else:
        out = pooled[..., _CONV_OFFSET:]
    vtoken = jnp.zeros((0, d), values.dtype)
    return out, (segments, keep, vtoken, batch_cvm)


def _bwd_conv(batch_size, num_slots, use_cvm, show_filter, pad_value,
              need_filter, show_coeff, clk_coeff, threshold, res, g):
    segments, keep, vtoken, batch_cvm = res
    d = vtoken.shape[1]
    co = _CONV_OFFSET
    n_head = (co - 1 if show_filter else co) if use_cvm else 0
    embedx_g = g[..., n_head:]
    if FLAGS.use_pallas_seqpool:
        g_embedx = segment_gather_mxu(
            embedx_g.reshape(batch_size * num_slots, d - co), segments)
    else:
        flat = embedx_g.reshape(batch_size * num_slots, d - co)
        flat = jnp.concatenate(
            [flat, jnp.zeros((1, d - co), flat.dtype)], axis=0)
        g_embedx = flat[segments]
    ins = jnp.minimum(segments // num_slots, batch_size - 1)
    g_cvm = batch_cvm[ins]
    pad = segments >= batch_size * num_slots
    g_values = jnp.where(
        (keep & ~pad)[:, None],
        jnp.concatenate([g_cvm.astype(g_embedx.dtype), g_embedx], axis=-1),
        0.0,
    ).astype(vtoken.dtype)
    return (g_values, None, None)


fused_seqpool_cvm_with_conv.defvjp(_fwd_conv, _bwd_conv)


def slot_group_bounds(num_slots: int, groups: int):
    """Contiguous slot partition for the chunked sharded exchange
    (FLAGS.a2a_chunks; train/sharded): ``groups`` spans [lo, hi) covering
    [0, num_slots), the first ``num_slots % groups`` spans one slot
    wider. Shared by the host plan builder (ps/sharded.prepare_global)
    and the device step so both sides agree on group membership."""
    groups = max(1, min(groups, num_slots))
    base, rem = divmod(num_slots, groups)
    bounds = []
    lo = 0
    for g in range(groups):
        hi = lo + base + (1 if g < rem else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def fused_seqpool_cvm_slot_group(
    values: jax.Array,          # [K_g, D] the group's pulled embeddings
    segments: jax.Array,        # [K_g] GLOBAL ins*S + slot ids; pads → B*S
    batch_show_clk: jax.Array,  # [B, cvm_offset]
    batch_size: int,
    num_slots_total: int,
    slot_lo: int,
    slot_hi: int,
    use_cvm: bool = True,
    cvm_offset: int = 2,
) -> jax.Array:
    """Group-decomposable pooling entry: pool ONE contiguous slot group
    [slot_lo, slot_hi) of the batch into its [B, S_g, D'] block.

    The full fusion's (ins, slot) bins are independent across slots, so
    pooling a slot group in isolation and concatenating the blocks in
    canonical slot order is BIT-identical to the monolithic
    ``fused_seqpool_cvm`` over all keys — PROVIDED every key of the
    group's segment stream has its slot inside [slot_lo, slot_hi) (the
    slot-qualified contract the chunked plan builder verifies; pads at
    B*S are routed to the group's discard bin). Segment ids renumber
    in-trace: ``ins*S + slot → ins*S_g + (slot - slot_lo)``."""
    s, sg = num_slots_total, slot_hi - slot_lo
    if slot_lo == 0 and slot_hi == s:
        return fused_seqpool_cvm(values, segments, batch_show_clk,
                                 batch_size, s, use_cvm, cvm_offset)
    n_bins = batch_size * s
    ins = segments // s
    local = ins * sg + (segments - ins * s) - slot_lo
    seg_local = jnp.where(segments >= n_bins, batch_size * sg,
                          local).astype(segments.dtype)
    return fused_seqpool_cvm(values, seg_local, batch_show_clk,
                             batch_size, sg, use_cvm, cvm_offset)


def fused_seqpool_concat(values, segments, batch_size, num_slots,
                         pad_value=0.0):
    """Plain seqpool + concat (fusion_seqpool_concat_op): our fused op with
    no CVM columns (cvm_offset=0, use_cvm=False path without stripping)."""
    num_segments = batch_size * num_slots + 1
    pooled = segment_sum(values, segments, num_segments)
    return pooled[:-1].reshape(batch_size, num_slots, -1) + pad_value
