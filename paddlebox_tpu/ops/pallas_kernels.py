"""Pallas TPU kernels for the embedding-PS hot paths.

Reference hot kernels being replaced (SURVEY.md §2.1-2.2, §2.4):
- ``PullCopy``/``CopyForPull`` gather (fleet/box_wrapper.cu:75,945) and the
  HeterPS hashtable ``get`` → here ``gather_rows``: a scalar-prefetch row
  gather where the Pallas pipeline double-buffers one row-block DMA per grid
  step (HBM→VMEM), overlapping fetches across steps.
- ``PushMergeCopy`` scatter (box_wrapper.cu:417) + in-kernel optimizer write
  (heter_ps/optimizer.cuh.h) → ``scatter_rows``: aliased in-place row
  scatter (the optimizer math itself stays in jnp where XLA fuses it against
  the gathered rows; only the irregular-access scatter needs a kernel).
- ``FusedSeqpoolKernelNormal`` (fused/fused_seqpool_cvm_op.cu:36) →
  ``segment_sum_mxu``: the ragged per-slot sum-pool recast as a blocked
  one-hot × values matmul so it runs on the MXU systolic array instead of
  scalar scatter-adds — the TPU-first formulation of segment_sum.

All kernels auto-fall back to interpret mode off-TPU so the whole suite is
testable on the CPU mesh (SURVEY.md §4 implication).

Status (measured on one TPU chip, DeepFM/criteo bench, AoS table
[8M+1, 16] f32, 213k rows/batch):
- XLA's native gather/scatter lowers to PER-ELEMENT access: scatter
  [213k, 16] rows = 26 ms (~7.6 ns/element), gather = 8 ms. The hints
  (unique_indices / indices_are_sorted / mode) change nothing. This is
  the single largest cost in the train step.
- ``gather_rows_dma``/``scatter_rows_dma`` below implement the obvious
  fix — one row DMA per index, _NSEM in flight. Measured verdict:
  (a) D=16 rows cannot compile — every Mosaic memref (HBM included) is
  laid out with a 128-lane minor tile, so a 16-wide row slice is
  "unaligned" regardless of memory space; (b) at D=128 (lane-aligned
  rows) they compile and are CORRECT but the scalar-core loop issues
  DMAs at ~320 µs each (2048 rows = 656 ms) — ~1000x off, so manual
  per-row DMA is not viable on current Mosaic at any width. Kept as
  interpret-mode reference implementations only.
- Conclusion: XLA's native per-element scatter (26 ms/batch) stands as
  the table-update cost on this toolchain; revisit if Mosaic grows a
  batched gather/scatter DMA primitive or SparseCore access.
- ``segment_sum_mxu`` is the right shape for wide-D, high-slot-count
  configs (1000-slot fused pipelines, D≥128); re-evaluate there.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddlebox_tpu.config import FLAGS


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# Row gather (pull_sparse hot path)
# ---------------------------------------------------------------------------

def gather_rows(table: jax.Array, rows: jax.Array) -> jax.Array:
    """table [C, D], rows [U] int32 → [U, D].

    One grid step per row; the row index is scalar-prefetched so the
    pipeline issues the HBM→VMEM DMA for step i+1 while step i copies out.
    Out-of-bounds pad rows (> C-1, the OOB-pad contract of
    table._build_index / device_unique.dedup_rows) clamp to the sentinel
    row C-1, matching XLA's clamped-gather semantics.
    """
    c, d = table.shape
    u = rows.shape[0]

    def kernel(rows_ref, tbl_ref, out_ref):
        del rows_ref
        out_ref[...] = tbl_ref[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(u,),
        in_specs=[pl.BlockSpec(
            (1, d), lambda i, rows_ref: (jnp.minimum(rows_ref[i], c - 1), 0))],
        out_specs=pl.BlockSpec((1, d), lambda i, rows_ref: (i, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((u, d), table.dtype),
        interpret=_interpret(),
    )(rows, table)


# ---------------------------------------------------------------------------
# Row scatter (push_sparse write-back)
# ---------------------------------------------------------------------------

def scatter_rows(table: jax.Array, rows: jax.Array,
                 values: jax.Array) -> jax.Array:
    """REFERENCE-ONLY (interpret mode; no production consumer since the
    packed-line layout made apply_push a masked line scatter-ADD — see
    TableState/DESIGN_NOTES §2): write values[i] into table[rows[i]] in
    place (buffer aliased).

    In-bounds rows must be duplicate-free (the unique-scatter contract);
    out-of-bounds pad rows clamp to the sentinel row C-1, whose racy
    last-write-wins content the callers reset (table.apply_push).
    """
    c, d = table.shape
    u = rows.shape[0]

    def kernel(rows_ref, tbl_ref, val_ref, out_ref):
        del rows_ref, tbl_ref
        out_ref[...] = val_ref[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(u,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # aliased table, untouched
            pl.BlockSpec((1, d), lambda i, rows_ref: (i, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, d), lambda i, rows_ref: (jnp.minimum(rows_ref[i], c - 1), 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((c, d), table.dtype),
        input_output_aliases={1: 0},  # tensor input 0 (table) → output 0
        interpret=_interpret(),
    )(rows, table, values)


# ---------------------------------------------------------------------------
# Manual-DMA row gather/scatter — per-row 64B copies, semaphore ring
# ---------------------------------------------------------------------------
#
# XLA lowers irregular gather/scatter to per-ELEMENT access on TPU; these
# kernels issue one DMA per ROW instead. Rows stream through VMEM in blocks
# of _TR (the pallas pipeline double-buffers the block transfer), and inside
# each block a scalar loop issues per-row DMAs, keeping _NSEM in flight.
# Out-of-bounds row ids (the OOB padding contract of table._build_index /
# device_unique.dedup_rows) are clamped to the sentinel row C — reads there
# return zeros, racy pad writes land on C which apply_push resets.

_TR = 2048    # rows per grid block (VMEM: _TR * D * 4B)
_NSEM = 16    # DMAs in flight


def _dma_body(rows_ref, tbl_ref, io_ref, sem, base, scatter: bool) -> None:
    """Issue one 64B-row DMA per index with a _NSEM-deep in-flight ring.
    rows_ref: SMEM [tr] block-local row ids; io_ref: the full [K, d]
    values/out array in HBM (row base+r ↔ table row); tbl_ref: the whole
    table in HBM. DMAs are HBM→HBM (row slices are contiguous, so no VMEM
    tiling constraint applies)."""
    tr = rows_ref.shape[0]
    c = tbl_ref.shape[0] - 1

    def issue(r):
        row = jnp.minimum(rows_ref[r], c)  # OOB pads clamp to sentinel
        if scatter:
            return pltpu.make_async_copy(
                io_ref.at[base + r], tbl_ref.at[row], sem.at[r % _NSEM])
        return pltpu.make_async_copy(
            tbl_ref.at[row], io_ref.at[base + r], sem.at[r % _NSEM])

    def body(r, carry):
        # reuse slot r%_NSEM: drain the DMA issued _NSEM rows ago
        @pl.when(r >= _NSEM)
        def _():
            issue(r - _NSEM).wait()
        issue(r).start()
        return carry

    jax.lax.fori_loop(0, tr, body, 0)
    start = max(0, tr - _NSEM)

    def drain(i, carry):
        issue(start + i).wait()
        return carry

    jax.lax.fori_loop(0, tr - start, drain, 0)


def scatter_rows_dma(table: jax.Array, rows: jax.Array,
                     values: jax.Array) -> jax.Array:
    """table[rows[i]] = values[i] via per-row DMAs, table aliased in place.

    rows must be duplicate-free among in-bounds ids (the unique-scatter
    contract of table._build_index / device_unique.dedup_rows); OOB pads
    clamp to the sentinel row — racy pad writes land there and the caller
    resets it (apply_push)."""
    c1, d = table.shape
    k = rows.shape[0]
    tr = min(_TR, k)
    assert k % tr == 0, f"pad rows to a multiple of {tr}"

    def kernel(rows_ref, tbl_ref, val_ref, out_ref, sem):
        del tbl_ref  # out_ref is its alias — write through the output
        _dma_body(rows_ref, out_ref, val_ref, sem,
                  pl.program_id(0) * tr, scatter=True)

    return pl.pallas_call(
        kernel,
        grid=(k // tr,),
        in_specs=[
            pl.BlockSpec((tr,), lambda b: (b,), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.HBM),  # table (aliased)
            pl.BlockSpec(memory_space=pltpu.HBM),  # values, stays in HBM
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.HBM),
        scratch_shapes=[pltpu.SemaphoreType.DMA((_NSEM,))],
        out_shape=jax.ShapeDtypeStruct((c1, d), table.dtype),
        input_output_aliases={1: 0},  # table input → output
        interpret=_interpret(),
    )(rows, table, values)


def gather_rows_dma(table: jax.Array, rows: jax.Array) -> jax.Array:
    """out[i] = table[min(rows[i], C)] via per-row DMAs (OOB ids clamp to
    the zero sentinel row — same semantics as XLA's clamped gather)."""
    c1, d = table.shape
    k = rows.shape[0]
    tr = min(_TR, k)
    assert k % tr == 0, f"pad rows to a multiple of {tr}"

    def kernel(rows_ref, tbl_ref, out_ref, sem):
        _dma_body(rows_ref, tbl_ref, out_ref, sem,
                  pl.program_id(0) * tr, scatter=False)

    return pl.pallas_call(
        kernel,
        grid=(k // tr,),
        in_specs=[
            pl.BlockSpec((tr,), lambda b: (b,), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.HBM),  # table
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.HBM),  # written via DMA
        scratch_shapes=[pltpu.SemaphoreType.DMA((_NSEM,))],
        out_shape=jax.ShapeDtypeStruct((k, d), table.dtype),
        interpret=_interpret(),
    )(rows, table)


# ---------------------------------------------------------------------------
# MXU segment-sum (fused_seqpool hot path)
# ---------------------------------------------------------------------------
#
# Block-sparse formulation: segments MUST be nondecreasing (batch builder
# emits segment ids ins*S+slot in key order, so this holds for every seqpool
# caller). A key block of TK keys then overlaps at most TK/TB+1 output
# blocks, so instead of the full (segments × keys) cross product the grid is
# a flat list of (output-block, key-block) pairs built host-side: per key
# block j, pairs i = start_block[j]..end_block[j] (clamped, padded to the
# static TK/TB+1 per block). Work is O(K·TB·D) on the MXU — independent of
# num_segments — vs the scatter-add's O(K·D) serialized irregular writes.

def _seg_sum_kernel(i_ref, first_ref, valid_ref, seg_ref, vals_ref, out_ref,
                    *, tb: int, tk: int):
    p = pl.program_id(0)

    @pl.when(first_ref[p] != 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(valid_ref[p] != 0)
    def _acc():
        base = i_ref[p] * tb
        # onehot[r, k] = 1 iff segments[k] == base + r (never true for -1)
        row_ids = jax.lax.broadcasted_iota(jnp.int32, (tb, tk), 0) + base
        onehot = (row_ids == seg_ref[...]).astype(jnp.float32)
        out_ref[...] += jnp.dot(onehot, vals_ref[...],
                                preferred_element_type=jnp.float32,
                                precision=jax.lax.Precision.HIGHEST)


def _segment_sum_mxu_impl(values: jax.Array, segments: jax.Array,
                          num_segments: int) -> jax.Array:
    k, d = values.shape
    tb = 128
    tk = min(512, max(128, _round_up(max(k, 1), 128)))
    k_pad = _round_up(max(k, 1), tk)
    s_pad = _round_up(num_segments, tb)
    d_pad = _round_up(d, 128)
    nkb = k_pad // tk            # key blocks
    ppb = tk // tb + 1           # max output blocks one key block overlaps
    n_pairs = nkb * ppb

    v = jnp.zeros((k_pad, d_pad), jnp.float32)
    v = v.at[:k, :d].set(values.astype(jnp.float32))
    seg = jnp.full((k_pad,), -1, jnp.int32)
    seg = seg.at[:k].set(segments.astype(jnp.int32))

    # host-side (traced, static shapes) pair construction. −1 drop markers
    # may appear anywhere; only the valid entries must be nondecreasing.
    segs2 = seg.reshape(nkb, tk)
    valid_m = segs2 >= 0
    has_valid = valid_m.any(axis=1)
    first_seg = jnp.min(jnp.where(valid_m, segs2, jnp.iinfo(jnp.int32).max),
                        axis=1)
    last_seg = jnp.max(segs2, axis=1)         # nondecreasing ⇒ max = last
    start_b = jnp.where(has_valid, first_seg // tb, 0)
    end_b = jnp.where(has_valid, last_seg // tb, -1)
    # carry forward so all-pad blocks produce in-bounds, monotone i indices
    prev_end = jnp.maximum(jax.lax.cummax(end_b), 0)
    start_b = jnp.where(has_valid, start_b, prev_end)
    end_b = jnp.where(has_valid, end_b, prev_end)

    slot = jnp.arange(n_pairs, dtype=jnp.int32) % ppb
    jb = jnp.arange(n_pairs, dtype=jnp.int32) // ppb
    i_raw = start_b[jb] + slot
    i_arr = jnp.minimum(i_raw, end_b[jb])
    valid = (i_raw <= end_b[jb]) & has_valid[jb]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), i_arr[1:] != i_arr[:-1]])

    # The static ppb bound holds only when segment occupancy is dense (the
    # CTR seqpool shape: num_segments ≈ B*S ≲ K). If any key block spans
    # more output blocks than ppb (sparse occupancy), branch to the XLA
    # scatter-add at runtime — correctness is unconditional.
    overflow = jnp.any((end_b - start_b + 1) > ppb)

    def pallas_branch(_):
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(n_pairs,),
            in_specs=[
                pl.BlockSpec((1, tk), lambda p, i_a, f, v_: (0, p // ppb)),
                pl.BlockSpec((tk, d_pad),
                             lambda p, i_a, f, v_: (p // ppb, 0)),
            ],
            out_specs=pl.BlockSpec(
                (tb, d_pad), lambda p, i_a, f, v_: (i_a[p], 0)),
        )
        out = pl.pallas_call(
            functools.partial(_seg_sum_kernel, tb=tb, tk=tk),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((s_pad, d_pad), jnp.float32),
            interpret=_interpret(),
        )(i_arr, first.astype(jnp.int32), valid.astype(jnp.int32),
          seg.reshape(1, k_pad), v)
        # segment ranges with no keys map to output blocks no pair visits;
        # their buffers are uninitialized — mask them to zero.
        visited = jnp.zeros((s_pad // tb,), bool).at[i_arr].max(valid)
        return jnp.where(jnp.repeat(visited, tb)[:, None], out, 0.0)

    def xla_branch(_):
        safe = jnp.where(seg >= 0, seg, num_segments)
        out = jax.ops.segment_sum(v, safe, num_segments=num_segments + 1)
        return jnp.zeros((s_pad, d_pad), jnp.float32).at[
            :num_segments].set(out[:num_segments])

    out = jax.lax.cond(overflow, xla_branch, pallas_branch, None)
    return out[:num_segments, :d].astype(values.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def segment_sum_mxu(values: jax.Array, segments: jax.Array,
                    num_segments: int) -> jax.Array:
    """values [K, D], segments [K] int32 → [num_segments, D].
    Contract: −1 entries are dropped (allowed anywhere); the NON-negative
    entries must be nondecreasing in array order. See notes above."""
    return _segment_sum_mxu_impl(values, segments, num_segments)


def _seg_sum_fwd(values, segments, num_segments):
    out = _segment_sum_mxu_impl(values, segments, num_segments)
    vtoken = jnp.zeros((0,), values.dtype)  # carries primal dtype
    return out, (segments, vtoken)


def _seg_sum_bwd(num_segments, res, g):
    segments, vtoken = res
    # d/dvalues of a segment sum is a gather of the cotangent rows
    safe = jnp.clip(segments, 0, num_segments - 1)
    g_values = jnp.where((segments >= 0)[:, None], g[safe], 0.0)
    return (g_values.astype(vtoken.dtype), None)


segment_sum_mxu.defvjp(_seg_sum_fwd, _seg_sum_bwd)


def segment_sum(values: jax.Array, segments: jax.Array,
                num_segments: int) -> jax.Array:
    """Backend dispatch: MXU kernel when enabled (requires nondecreasing
    segments — true for all seqpool callers), XLA scatter-add otherwise
    (flag: FLAGS.use_pallas_seqpool)."""
    if FLAGS.use_pallas_seqpool:
        return segment_sum_mxu(values, segments, num_segments)
    return jax.ops.segment_sum(values, segments, num_segments=num_segments)
