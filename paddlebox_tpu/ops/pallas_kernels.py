"""Pallas TPU kernels for the embedding-PS hot paths.

Reference hot kernels being replaced (SURVEY.md §2.1-2.2, §2.4):
- ``PullCopy``/``CopyForPull`` gather (fleet/box_wrapper.cu:75,945) and the
  HeterPS hashtable ``get`` → here ``gather_rows``: a scalar-prefetch row
  gather where the Pallas pipeline double-buffers one row-block DMA per grid
  step (HBM→VMEM), overlapping fetches across steps.
- ``PushMergeCopy`` scatter (box_wrapper.cu:417) + in-kernel optimizer write
  (heter_ps/optimizer.cuh.h) → ``scatter_rows``: aliased in-place row
  scatter (the optimizer math itself stays in jnp where XLA fuses it against
  the gathered rows; only the irregular-access scatter needs a kernel).
- ``FusedSeqpoolKernelNormal`` (fused/fused_seqpool_cvm_op.cu:36) →
  ``segment_sum_mxu``: the ragged per-slot sum-pool recast as a blocked
  one-hot × values matmul so it runs on the MXU systolic array instead of
  scalar scatter-adds — the TPU-first formulation of segment_sum.

- ``FusedSeqpoolCVMKernel*`` + ``FusedCVMKernelWithCVM``
  (fused/fused_seqpool_cvm_op.cu:36-298) → ``fused_embed_pool_cvm`` /
  ``fused_pool_cvm_forward``: ONE blocked Pallas pass that streams
  key-blocks of pulled embeddings HBM→VMEM (the pipeline double-buffers
  the block DMA, indices scalar-prefetched), pools them on the MXU via
  the one-hot × values matmul, and applies the CVM log transform while
  the output block is still VMEM-resident. The ``custom_vjp`` backward
  produces per-row grads with ``segment_gather_mxu`` — the transposed
  one-hot matmul — instead of an XLA per-element gather.

All kernels auto-fall back to interpret mode off-TPU so the whole suite is
testable on the CPU mesh (SURVEY.md §4 implication).

The suite's CTR op family half (``fused_rank_attention``,
``fused_batch_fc``, ``fused_cross_norm_hadamard`` — ISSUE 13) lives in
the sibling ``ops/pallas_ctr.py``, sharing this module's interpret/
padding/dispatch-booking helpers and the same MXU one-hot recipe.

Status / measured verdict (post ISSUE 12; one TPU chip, DeepFM/criteo
bench, AoS table [8M+1, 16] f32, 213k rows/batch):
- XLA's native gather/scatter lowers to PER-ELEMENT access: scatter
  [213k, 16] rows = 26 ms (~7.6 ns/element), gather = 8 ms. The hints
  (unique_indices / indices_are_sorted / mode) change nothing.
- Manual per-row DMA is NOT viable on current Mosaic at any width:
  (a) D=16 rows cannot compile — every Mosaic memref (HBM included) is
  laid out with a 128-lane minor tile, so a 16-wide row slice is
  "unaligned" regardless of memory space; (b) at D=128 the scalar-core
  loop issues DMAs at ~320 µs each (2048 rows = 656 ms), ~1000x off.
  ``gather_rows_dma``/``scatter_rows_dma`` are therefore DEMOTED to
  interpret-only reference implementations — they raise loudly when
  invoked on a real TPU backend. Revisit only if Mosaic grows a
  batched gather/scatter DMA primitive or SparseCore access.
- The viable TPU formulation of the irregular hot path is the MXU
  one-hot matmul family below: ``segment_sum_mxu`` (pool forward),
  ``segment_gather_mxu`` (pool backward / ragged gather by
  nondecreasing ids), and ``fused_pool_cvm_forward`` (pool + CVM in
  one VMEM residency). The expand gather (``vals_u[gather_idx]``,
  UNSORTED ids) stays on XLA's clamped gather — the one-hot form is
  O(K·U·D) there and per-row DMA is ruled out above. Per-shape numbers:
  ``scripts/profile_keypath.py --set kernels`` →
  ``kernel.{gather,pool_cvm,fused}.{shape}.{backend}`` trajectory rows,
  gated by ``scripts/perf_gate.py`` (docs/PERFORMANCE.md §Device
  kernels).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddlebox_tpu.config import FLAGS


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _book_dispatch(kernel: str, impl: str) -> None:
    """Book one ``pbox_kernel_dispatch_total{kernel,impl}`` tick.

    Dispatch decisions are made at TRACE time (inside jit the python
    branch runs once per compiled executable), so the counter counts
    compiled-program dispatches, not per-batch executions — enough to
    prove which implementation a run's programs actually contain
    (docs/OBSERVABILITY.md §Device kernels). Inert without an active
    hub."""
    try:
        from paddlebox_tpu.obs.hub import get_hub
        hub = get_hub()
        if hub.active:
            hub.counter(
                "pbox_kernel_dispatch_total",
                "device-kernel dispatch decisions by kernel and impl",
            ).inc(kernel=kernel, impl=impl)
    except Exception:  # pragma: no cover - telemetry must never break math
        pass


def _require_interpret(name: str) -> None:
    """DMA reference paths are interpret-only (see module docstring):
    invoking them on a real TPU backend is a ~1000x perf bug, not a
    fallback — fail loudly instead."""
    if not _interpret():
        raise RuntimeError(
            f"{name} is an interpret-mode reference implementation only "
            "(per-row DMA measured ~320 µs/row on Mosaic — see "
            "ops/pallas_kernels.py status); use gather_rows / "
            "segment_sum_mxu / fused_pool_cvm_forward on TPU")


# ---------------------------------------------------------------------------
# Row gather (pull_sparse hot path)
# ---------------------------------------------------------------------------

def gather_rows(table: jax.Array, rows: jax.Array) -> jax.Array:
    """table [C, D], rows [U] int32 → [U, D].

    One grid step per row; the row index is scalar-prefetched so the
    pipeline issues the HBM→VMEM DMA for step i+1 while step i copies out.
    Out-of-bounds pad rows (> C-1, the OOB-pad contract of
    table._build_index / device_unique.dedup_rows) clamp to the sentinel
    row C-1, matching XLA's clamped-gather semantics.
    """
    c, d = table.shape
    u = rows.shape[0]

    def kernel(rows_ref, tbl_ref, out_ref):
        del rows_ref
        out_ref[...] = tbl_ref[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(u,),
        in_specs=[pl.BlockSpec(
            (1, d), lambda i, rows_ref: (jnp.minimum(rows_ref[i], c - 1), 0))],
        out_specs=pl.BlockSpec((1, d), lambda i, rows_ref: (i, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((u, d), table.dtype),
        interpret=_interpret(),
    )(rows, table)


# ---------------------------------------------------------------------------
# Row scatter (push_sparse write-back)
# ---------------------------------------------------------------------------

def scatter_rows(table: jax.Array, rows: jax.Array,
                 values: jax.Array) -> jax.Array:
    """REFERENCE-ONLY (interpret mode; no production consumer since the
    packed-line layout made apply_push a masked line scatter-ADD — see
    TableState/DESIGN_NOTES §2): write values[i] into table[rows[i]] in
    place (buffer aliased).

    In-bounds rows must be duplicate-free (the unique-scatter contract);
    out-of-bounds pad rows clamp to the sentinel row C-1, whose racy
    last-write-wins content the callers reset (table.apply_push).
    """
    c, d = table.shape
    u = rows.shape[0]

    def kernel(rows_ref, tbl_ref, val_ref, out_ref):
        del rows_ref, tbl_ref
        out_ref[...] = val_ref[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(u,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # aliased table, untouched
            pl.BlockSpec((1, d), lambda i, rows_ref: (i, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, d), lambda i, rows_ref: (jnp.minimum(rows_ref[i], c - 1), 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((c, d), table.dtype),
        input_output_aliases={1: 0},  # tensor input 0 (table) → output 0
        interpret=_interpret(),
    )(rows, table, values)


# ---------------------------------------------------------------------------
# Manual-DMA row gather/scatter — per-row 64B copies, semaphore ring
# ---------------------------------------------------------------------------
#
# XLA lowers irregular gather/scatter to per-ELEMENT access on TPU; these
# kernels issue one DMA per ROW instead. Rows stream through VMEM in blocks
# of _TR (the pallas pipeline double-buffers the block transfer), and inside
# each block a scalar loop issues per-row DMAs, keeping _NSEM in flight.
# Out-of-bounds row ids (the OOB padding contract of table._build_index /
# device_unique.dedup_rows) are clamped to the sentinel row C — reads there
# return zeros, racy pad writes land on C which apply_push resets.

_TR = 2048    # rows per grid block (VMEM: _TR * D * 4B)
_NSEM = 16    # DMAs in flight


def _dma_body(rows_ref, tbl_ref, io_ref, sem, base, scatter: bool) -> None:
    """Issue one 64B-row DMA per index with a _NSEM-deep in-flight ring.
    rows_ref: SMEM [tr] block-local row ids; io_ref: the full [K, d]
    values/out array in HBM (row base+r ↔ table row); tbl_ref: the whole
    table in HBM. DMAs are HBM→HBM (row slices are contiguous, so no VMEM
    tiling constraint applies)."""
    tr = rows_ref.shape[0]
    c = tbl_ref.shape[0] - 1

    def issue(r):
        row = jnp.minimum(rows_ref[r], c)  # OOB pads clamp to sentinel
        if scatter:
            return pltpu.make_async_copy(
                io_ref.at[base + r], tbl_ref.at[row], sem.at[r % _NSEM])
        return pltpu.make_async_copy(
            tbl_ref.at[row], io_ref.at[base + r], sem.at[r % _NSEM])

    def body(r, carry):
        # reuse slot r%_NSEM: drain the DMA issued _NSEM rows ago
        @pl.when(r >= _NSEM)
        def _():
            issue(r - _NSEM).wait()
        issue(r).start()
        return carry

    jax.lax.fori_loop(0, tr, body, 0)
    start = max(0, tr - _NSEM)

    def drain(i, carry):
        issue(start + i).wait()
        return carry

    jax.lax.fori_loop(0, tr - start, drain, 0)


def scatter_rows_dma(table: jax.Array, rows: jax.Array,
                     values: jax.Array) -> jax.Array:
    """table[rows[i]] = values[i] via per-row DMAs, table aliased in place.

    rows must be duplicate-free among in-bounds ids (the unique-scatter
    contract of table._build_index / device_unique.dedup_rows); OOB pads
    clamp to the sentinel row — racy pad writes land there and the caller
    resets it (apply_push)."""
    _require_interpret("scatter_rows_dma")
    c1, d = table.shape
    k = rows.shape[0]
    tr = min(_TR, k)
    assert k % tr == 0, f"pad rows to a multiple of {tr}"

    def kernel(rows_ref, tbl_ref, val_ref, out_ref, sem):
        del tbl_ref  # out_ref is its alias — write through the output
        _dma_body(rows_ref, out_ref, val_ref, sem,
                  pl.program_id(0) * tr, scatter=True)

    return pl.pallas_call(
        kernel,
        grid=(k // tr,),
        in_specs=[
            pl.BlockSpec((tr,), lambda b: (b,), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.HBM),  # table (aliased)
            pl.BlockSpec(memory_space=pltpu.HBM),  # values, stays in HBM
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.HBM),
        scratch_shapes=[pltpu.SemaphoreType.DMA((_NSEM,))],
        out_shape=jax.ShapeDtypeStruct((c1, d), table.dtype),
        input_output_aliases={1: 0},  # table input → output
        interpret=_interpret(),
    )(rows, table, values)


def gather_rows_dma(table: jax.Array, rows: jax.Array) -> jax.Array:
    """out[i] = table[min(rows[i], C)] via per-row DMAs (OOB ids clamp to
    the zero sentinel row — same semantics as XLA's clamped gather)."""
    _require_interpret("gather_rows_dma")
    c1, d = table.shape
    k = rows.shape[0]
    tr = min(_TR, k)
    assert k % tr == 0, f"pad rows to a multiple of {tr}"

    def kernel(rows_ref, tbl_ref, out_ref, sem):
        _dma_body(rows_ref, tbl_ref, out_ref, sem,
                  pl.program_id(0) * tr, scatter=False)

    return pl.pallas_call(
        kernel,
        grid=(k // tr,),
        in_specs=[
            pl.BlockSpec((tr,), lambda b: (b,), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.HBM),  # table
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.HBM),  # written via DMA
        scratch_shapes=[pltpu.SemaphoreType.DMA((_NSEM,))],
        out_shape=jax.ShapeDtypeStruct((k, d), table.dtype),
        interpret=_interpret(),
    )(rows, table)


# ---------------------------------------------------------------------------
# MXU segment-sum (fused_seqpool hot path)
# ---------------------------------------------------------------------------
#
# Block-sparse formulation: segments MUST be nondecreasing (batch builder
# emits segment ids ins*S+slot in key order, so this holds for every seqpool
# caller). A key block of TK keys then overlaps at most TK/TB+1 output
# blocks, so instead of the full (segments × keys) cross product the grid is
# a flat list of (output-block, key-block) pairs built host-side: per key
# block j, pairs i = start_block[j]..end_block[j] (clamped, padded to the
# static TK/TB+1 per block). Work is O(K·TB·D) on the MXU — independent of
# num_segments — vs the scatter-add's O(K·D) serialized irregular writes.

def _tiles(k: int, n: int, d: int):
    """Shared pair-grid tiling: (tb, tk, k_pad, s_pad, d_pad, nkb, ppb,
    n_pairs) for K keys × N segments × D features. One definition so
    the tk heuristic and padding rules cannot drift between the one-hot
    kernels."""
    tb = 128
    tk = min(512, max(128, _round_up(max(k, 1), 128)))
    k_pad = _round_up(max(k, 1), tk)
    s_pad = _round_up(max(n, 1), tb)
    d_pad = _round_up(d, 128)
    nkb = k_pad // tk
    ppb = tk // tb + 1
    return tb, tk, k_pad, s_pad, d_pad, nkb, ppb, nkb * ppb


def _pad_ids(ids: jax.Array, k_pad: int, n: int) -> jax.Array:
    """[K] ids → [k_pad] int32 with the −1 drop routing: pads and ids
    outside [0, n) all become the drop marker (the one-hot never
    matches −1)."""
    ii = ids.astype(jnp.int32)
    seg = jnp.full((k_pad,), -1, jnp.int32)
    return seg.at[:ii.shape[0]].set(
        jnp.where((ii < 0) | (ii >= n), -1, ii))


def show_clk_keep(values: jax.Array, show_coeff: float, clk_coeff: float,
                  threshold: float) -> jax.Array:
    """THE show/clk significance filter (QuantFilter :93-133), bool [K].
    Single definition shared by every seqpool keep-mask site."""
    show, clk = values[:, 0], values[:, 1]
    return ((show - clk) * show_coeff + clk * clk_coeff) >= threshold


def keep_or_ones(values: jax.Array, need_filter: bool, show_coeff: float,
                 clk_coeff: float, threshold: float) -> jax.Array:
    """bool [K] keep mask: the show/clk filter when requested, all-ones
    otherwise — the one idiom every need_filter-only seqpool site uses."""
    if need_filter:
        return show_clk_keep(values, show_coeff, clk_coeff, threshold)
    return jnp.ones((values.shape[0],), dtype=bool)


def _pair_grid(seg: jax.Array, nkb: int, tk: int, tb: int):
    """Host-side (traced, static shapes) pair construction shared by the
    one-hot matmul kernels: per key block j, pairs i =
    start_block[j]..end_block[j] (clamped, padded to the static
    ``tk // tb + 1`` per block). −1 drop markers may appear anywhere;
    only the valid entries must be nondecreasing.

    Returns ``(i_arr, first, last, valid, overflow)``: the output-block
    index per pair, whether the pair is the first/last visit of its
    output block (i_arr is monotone, so visits are contiguous — ``first``
    gates the zero-init, ``last`` gates in-VMEM epilogues), the pair
    validity mask, and the runtime overflow predicate (a key block
    spanning more output blocks than the static bound ⇒ the caller must
    branch to its XLA fallback — correctness is unconditional)."""
    ppb = tk // tb + 1
    n_pairs = nkb * ppb
    segs2 = seg.reshape(nkb, tk)
    valid_m = segs2 >= 0
    has_valid = valid_m.any(axis=1)
    first_seg = jnp.min(jnp.where(valid_m, segs2, jnp.iinfo(jnp.int32).max),
                        axis=1)
    last_seg = jnp.max(segs2, axis=1)         # nondecreasing ⇒ max = last
    start_b = jnp.where(has_valid, first_seg // tb, 0)
    end_b = jnp.where(has_valid, last_seg // tb, -1)
    # carry forward so all-pad blocks produce in-bounds, monotone i indices
    prev_end = jnp.maximum(jax.lax.cummax(end_b), 0)
    start_b = jnp.where(has_valid, start_b, prev_end)
    end_b = jnp.where(has_valid, end_b, prev_end)

    slot = jnp.arange(n_pairs, dtype=jnp.int32) % ppb
    jb = jnp.arange(n_pairs, dtype=jnp.int32) // ppb
    i_raw = start_b[jb] + slot
    i_arr = jnp.minimum(i_raw, end_b[jb])
    valid = (i_raw <= end_b[jb]) & has_valid[jb]
    edge = i_arr[1:] != i_arr[:-1]
    first = jnp.concatenate([jnp.ones((1,), bool), edge])
    last = jnp.concatenate([edge, jnp.ones((1,), bool)])
    overflow = jnp.any((end_b - start_b + 1) > ppb)
    return i_arr, first, last, valid, overflow


def _seg_sum_kernel(i_ref, first_ref, valid_ref, seg_ref, vals_ref, out_ref,
                    *, tb: int, tk: int):
    p = pl.program_id(0)

    @pl.when(first_ref[p] != 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(valid_ref[p] != 0)
    def _acc():
        base = i_ref[p] * tb
        # onehot[r, k] = 1 iff segments[k] == base + r (never true for -1)
        row_ids = jax.lax.broadcasted_iota(jnp.int32, (tb, tk), 0) + base
        onehot = (row_ids == seg_ref[...]).astype(jnp.float32)
        out_ref[...] += jnp.dot(onehot, vals_ref[...],
                                preferred_element_type=jnp.float32,
                                precision=jax.lax.Precision.HIGHEST)


def _segment_sum_mxu_impl(values: jax.Array, segments: jax.Array,
                          num_segments: int) -> jax.Array:
    k, d = values.shape
    tb, tk, k_pad, s_pad, d_pad, nkb, ppb, n_pairs = \
        _tiles(k, num_segments, d)

    v = jnp.zeros((k_pad, d_pad), jnp.float32)
    v = v.at[:k, :d].set(values.astype(jnp.float32))
    # historical contract: ids here may legally equal num_segments-1's
    # discard bin, so only pads (not OOB) route to −1
    seg = jnp.full((k_pad,), -1, jnp.int32)
    seg = seg.at[:k].set(segments.astype(jnp.int32))

    # The static ppb bound holds only when segment occupancy is dense (the
    # CTR seqpool shape: num_segments ≈ B*S ≲ K). If any key block spans
    # more output blocks than ppb (sparse occupancy), branch to the XLA
    # scatter-add at runtime — correctness is unconditional.
    i_arr, first, _last, valid, overflow = _pair_grid(seg, nkb, tk, tb)

    def pallas_branch(_):
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(n_pairs,),
            in_specs=[
                pl.BlockSpec((1, tk), lambda p, i_a, f, v_: (0, p // ppb)),
                pl.BlockSpec((tk, d_pad),
                             lambda p, i_a, f, v_: (p // ppb, 0)),
            ],
            out_specs=pl.BlockSpec(
                (tb, d_pad), lambda p, i_a, f, v_: (i_a[p], 0)),
        )
        out = pl.pallas_call(
            functools.partial(_seg_sum_kernel, tb=tb, tk=tk),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((s_pad, d_pad), jnp.float32),
            interpret=_interpret(),
        )(i_arr, first.astype(jnp.int32), valid.astype(jnp.int32),
          seg.reshape(1, k_pad), v)
        # segment ranges with no keys map to output blocks no pair visits;
        # their buffers are uninitialized — mask them to zero.
        visited = jnp.zeros((s_pad // tb,), bool).at[i_arr].max(valid)
        return jnp.where(jnp.repeat(visited, tb)[:, None], out, 0.0)

    def xla_branch(_):
        safe = jnp.where(seg >= 0, seg, num_segments)
        out = jax.ops.segment_sum(v, safe, num_segments=num_segments + 1)
        return jnp.zeros((s_pad, d_pad), jnp.float32).at[
            :num_segments].set(out[:num_segments])

    out = jax.lax.cond(overflow, xla_branch, pallas_branch, None)
    return out[:num_segments, :d].astype(values.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def segment_sum_mxu(values: jax.Array, segments: jax.Array,
                    num_segments: int) -> jax.Array:
    """values [K, D], segments [K] int32 → [num_segments, D].
    Contract: −1 entries are dropped (allowed anywhere); the NON-negative
    entries must be nondecreasing in array order. See notes above."""
    return _segment_sum_mxu_impl(values, segments, num_segments)


def _seg_sum_fwd(values, segments, num_segments):
    out = _segment_sum_mxu_impl(values, segments, num_segments)
    vtoken = jnp.zeros((0,), values.dtype)  # carries primal dtype
    return out, (segments, vtoken)


def _seg_sum_bwd(num_segments, res, g):
    segments, vtoken = res
    # d/dvalues of a segment sum is a gather of the cotangent rows; under
    # the flag it runs as the transposed one-hot matmul on the MXU
    # (bitwise equal for in-contract ids — each output row receives
    # exactly one 1.0·src contribution)
    if FLAGS.use_pallas_seqpool:
        g_values = segment_gather_mxu(g, segments)
    else:
        safe = jnp.clip(segments, 0, num_segments - 1)
        g_values = jnp.where((segments >= 0)[:, None], g[safe], 0.0)
    return (g_values.astype(vtoken.dtype), None)


segment_sum_mxu.defvjp(_seg_sum_fwd, _seg_sum_bwd)


# ---------------------------------------------------------------------------
# MXU segment-gather (seqpool backward / transposed one-hot matmul)
# ---------------------------------------------------------------------------

def _seg_gather_kernel(i_ref, firstk_ref, valid_ref, seg_ref, src_ref,
                       out_ref, *, tb: int, tk: int):
    p = pl.program_id(0)

    @pl.when(firstk_ref[p] != 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(valid_ref[p] != 0)
    def _acc():
        base = i_ref[p] * tb
        row_ids = jax.lax.broadcasted_iota(jnp.int32, (tb, tk), 0) + base
        onehot = (row_ids == seg_ref[...]).astype(jnp.float32)  # [tb, tk]
        # onehotᵀ @ src_block → each key row receives its segment's src
        # row exactly once (single 1.0 contribution — bitwise a gather)
        out_ref[...] += jax.lax.dot_general(
            onehot, src_ref[...],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)


def segment_gather_mxu(src: jax.Array, ids: jax.Array) -> jax.Array:
    """src [N, D], ids [K] int32 → out [K, D] with out[k] = src[ids[k]];
    ids outside [0, N) produce zero rows.

    The transposed one-hot formulation of the segment-sum backward (the
    ``FusedSeqpoolCVMGrad*`` gather): per (key-block, source-block) pair
    the kernel runs onehotᵀ @ src on the MXU instead of XLA's
    per-element gather. Contract mirrors ``segment_sum_mxu``: the
    in-range ids must be nondecreasing in array order (−1/OOB drop
    markers may appear anywhere). Exact — each output row is one
    1.0·src contribution plus exact zeros, so results match the XLA
    gather bitwise (modulo -0.0 + 0.0 = +0.0)."""
    k = ids.shape[0]
    n, d = src.shape
    tb, tk, k_pad, s_pad, d_pad, nkb, ppb, n_pairs = _tiles(k, n, d)

    seg = _pad_ids(ids, k_pad, n)
    s = jnp.zeros((s_pad, d_pad), jnp.float32)
    s = s.at[:n, :d].set(src.astype(jnp.float32))

    i_arr, _first, _last, valid, overflow = _pair_grid(seg, nkb, tk, tb)
    # the OUTPUT here is keyed by key block (p // ppb), whose pairs are
    # consecutive — init on each key block's first pair
    firstk = (jnp.arange(n_pairs, dtype=jnp.int32) % ppb) == 0

    def pallas_branch(_):
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(n_pairs,),
            in_specs=[
                pl.BlockSpec((1, tk), lambda p, i_a, f, v_: (0, p // ppb)),
                pl.BlockSpec((tb, d_pad),
                             lambda p, i_a, f, v_: (i_a[p], 0)),
            ],
            out_specs=pl.BlockSpec(
                (tk, d_pad), lambda p, i_a, f, v_: (p // ppb, 0)),
        )
        return pl.pallas_call(
            functools.partial(_seg_gather_kernel, tb=tb, tk=tk),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
            interpret=_interpret(),
        )(i_arr, firstk.astype(jnp.int32), valid.astype(jnp.int32),
          seg.reshape(1, k_pad), s)

    def xla_branch(_):
        safe = jnp.clip(seg, 0, s_pad - 1)
        return jnp.where((seg >= 0)[:, None], s[safe], 0.0)

    out = jax.lax.cond(overflow, xla_branch, pallas_branch, None)
    return out[:k, :d].astype(src.dtype)


# ---------------------------------------------------------------------------
# Fused embed-pool-CVM (pull gather + fused_seqpool + CVM, one VMEM pass)
# ---------------------------------------------------------------------------
#
# The tentpole kernel (ISSUE 12 / ROADMAP item 1): per pair-grid step the
# Pallas pipeline DMAs one key-block of pulled embeddings HBM→VMEM
# (double-buffered, indices scalar-prefetched — the gather_rows idiom at
# block granularity), accumulates the keep-masked one-hot × values
# matmul on the MXU, and on the LAST visit of each output block applies
# the CVM log transform while the block is still VMEM-resident — the
# TPU shape of PaddleBox's pull_box_sparse → FusedSeqpoolKernel* →
# FusedCVMKernel* CUDA chain, with no intermediate HBM round-trip
# between pool and CVM and no per-element scatter anywhere.

#: static CVM epilogue modes (which head columns transform in-VMEM)
CVM_NONE = 0      # no transform (use_cvm=False; caller slices the head)
CVM_FULL = 1      # [log1p(show), log1p(clk)-log1p(show), embedx…]
CVM_SHOW = 2      # clk_filter head: [log1p(show), embedx…]
CVM_CONV = 3      # conv head: [log1p(show), log1p(clk), log1p(conv)-log1p(clk)]


def _cvm_transform_wide(pooled: jax.Array, cvm_mode: int) -> jax.Array:
    """Column-in-place CVM transform on a lane-padded pooled block
    (shared by the in-kernel epilogue, the XLA overflow branch and the
    empty-segment filler — one definition, identical math)."""
    if cvm_mode == CVM_NONE:
        return pooled
    c = jax.lax.broadcasted_iota(jnp.int32, pooled.shape, pooled.ndim - 1)
    l0 = jnp.log1p(pooled[..., 0:1])
    if cvm_mode == CVM_FULL:
        l1 = jnp.log1p(pooled[..., 1:2]) - l0
        return jnp.where(c == 0, l0, jnp.where(c == 1, l1, pooled))
    if cvm_mode == CVM_SHOW:
        return jnp.where(c == 0, l0, pooled)
    l1 = jnp.log1p(pooled[..., 1:2])
    l2 = jnp.log1p(pooled[..., 2:3]) - l1
    return jnp.where(c == 0, l0,
                     jnp.where(c == 1, l1, jnp.where(c == 2, l2, pooled)))


def _pool_cvm_kernel(i_ref, first_ref, last_ref, valid_ref, seg_ref,
                     keep_ref, vals_ref, out_ref, *, tb: int, tk: int,
                     cvm_mode: int, pad_value: float):
    p = pl.program_id(0)

    @pl.when(first_ref[p] != 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(valid_ref[p] != 0)
    def _acc():
        base = i_ref[p] * tb
        row_ids = jax.lax.broadcasted_iota(jnp.int32, (tb, tk), 0) + base
        # keep folds into the one-hot (0/1 × 0/1 — exact), so filtered
        # keys drop inside the same matmul that pools
        onehot = (row_ids == seg_ref[...]).astype(jnp.float32) \
            * keep_ref[...]
        out_ref[...] += jnp.dot(onehot, vals_ref[...],
                                preferred_element_type=jnp.float32,
                                precision=jax.lax.Precision.HIGHEST)

    @pl.when(last_ref[p] != 0)
    def _epilogue():
        # the block's accumulation is complete (i_arr is monotone —
        # no later pair revisits it): apply pad_value + CVM before the
        # block leaves VMEM
        out_ref[...] = _cvm_transform_wide(out_ref[...] + pad_value,
                                           cvm_mode)


def fused_pool_cvm_forward(values: jax.Array, segments: jax.Array,
                           keep: Optional[jax.Array], batch_size: int,
                           num_slots: int, *, cvm_mode: int = CVM_FULL,
                           cvm_offset: int = 2, ets: int = 0,
                           pad_value: float = 0.0) -> jax.Array:
    """values [K, D] pulled embeddings, segments [K] (ins*S + slot,
    nondecreasing; pads may be ≥ B*S or −1), keep [K] optional 0/1 key
    mask → the CVM-transformed pooled output [B, S, D_out] in ONE fused
    pass (see section comment). ``ets`` (embed_thres_size) only affects
    the CVM_NONE output slice. Raw forward — no custom_vjp; callers
    (ops/seqpool_cvm dispatch seam, ``fused_embed_pool_cvm``) own the
    reference backward contract."""
    k, d = values.shape
    n = batch_size * num_slots
    tb, tk, k_pad, s_pad, d_pad, nkb, ppb, n_pairs = _tiles(k, n, d)

    v = jnp.zeros((k_pad, d_pad), jnp.float32)
    v = v.at[:k, :d].set(values.astype(jnp.float32))
    kp = jnp.zeros((k_pad,), jnp.float32)
    kp = kp.at[:k].set(jnp.ones((k,), jnp.float32) if keep is None
                       else keep.astype(jnp.float32))
    # batch pads (≥ B*S) route to the −1 drop marker: the fused output
    # has no extra discard bin
    seg = _pad_ids(segments, k_pad, n)

    i_arr, first, last, valid, overflow = _pair_grid(seg, nkb, tk, tb)

    def pallas_branch(_):
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(n_pairs,),
            in_specs=[
                pl.BlockSpec((1, tk),
                             lambda p, i_a, f, l, v_: (0, p // ppb)),
                pl.BlockSpec((1, tk),
                             lambda p, i_a, f, l, v_: (0, p // ppb)),
                pl.BlockSpec((tk, d_pad),
                             lambda p, i_a, f, l, v_: (p // ppb, 0)),
            ],
            out_specs=pl.BlockSpec(
                (tb, d_pad), lambda p, i_a, f, l, v_: (i_a[p], 0)),
        )
        out = pl.pallas_call(
            functools.partial(_pool_cvm_kernel, tb=tb, tk=tk,
                              cvm_mode=cvm_mode, pad_value=pad_value),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((s_pad, d_pad), jnp.float32),
            interpret=_interpret(),
        )(i_arr, first.astype(jnp.int32), last.astype(jnp.int32),
          valid.astype(jnp.int32), seg.reshape(1, k_pad),
          kp.reshape(1, k_pad), v)
        # output blocks no valid pair visits hold uninitialized (or
        # zero-only) buffers — fill with the CVM of an empty segment
        # (pad_value everywhere), the same value the XLA branch produces
        visited = jnp.zeros((s_pad // tb,), bool).at[i_arr].max(valid)
        empty = _cvm_transform_wide(
            jnp.full((1, d_pad), pad_value, jnp.float32), cvm_mode)
        return jnp.where(jnp.repeat(visited, tb)[:, None], out, empty)

    def xla_branch(_):
        vk = v * kp[:, None]
        safe = jnp.where(seg >= 0, seg, s_pad)
        pooled = jax.ops.segment_sum(vk, safe,
                                     num_segments=s_pad + 1)[:s_pad]
        return _cvm_transform_wide(pooled + pad_value, cvm_mode)

    buf = jax.lax.cond(overflow, xla_branch, pallas_branch, None)[:n]
    # static column slice per head mode (InferShape width contract)
    if cvm_mode == CVM_NONE:
        out = buf[:, cvm_offset + ets:d]
    elif cvm_mode == CVM_FULL:
        out = buf[:, :d] if cvm_offset == 2 else jnp.concatenate(
            [buf[:, :2], buf[:, cvm_offset:d]], axis=-1)
    elif cvm_mode == CVM_SHOW:
        out = jnp.concatenate([buf[:, 0:1], buf[:, cvm_offset:d]], axis=-1)
    else:  # CVM_CONV: 3-column head transformed in place, full width
        out = buf[:, :d]
    return out.reshape(batch_size, num_slots, -1).astype(values.dtype)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def fused_embed_pool_cvm(
    values: jax.Array,          # [K, D] pulled embeddings (D incl. cvm dims)
    segments: jax.Array,        # [K] int32 ins*S + slot; pads ≥ B*S or −1
    batch_show_clk: jax.Array,  # [B, cvm_offset] batch show/clk
    batch_size: int,
    num_slots: int,
    use_cvm: bool = True,
    cvm_offset: int = 2,
    pad_value: float = 0.0,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold: float = 0.96,
) -> jax.Array:
    """The STANDALONE custom_vjp form of the fused kernel pair: forward
    is ``fused_pool_cvm_forward`` (one VMEM pass), backward replicates
    FusedSeqpoolCVMGradKernelWithCVM — embedx dims broadcast the output
    grad to every surviving key via ``segment_gather_mxu`` (transposed
    one-hot matmul, no XLA per-element gather), the first ``cvm_offset``
    dims carry the batch show/clk values, filtered/pad keys zero.
    Covers the kk=1 attr subset of ``ops.fused_seqpool_cvm``.

    NOTE the production dispatch seam does NOT route through this
    wrapper: ``ops.seqpool_cvm._fwd``/``_bwd`` call
    ``fused_pool_cvm_forward`` / ``segment_gather_mxu`` directly under
    ``FLAGS.use_pallas_seqpool`` (their own custom_vjp already owns the
    full attr surface). Use this op for direct kernel composition and
    for gradient-contract tests; grads match the XLA composition
    bitwise given the same upstream cotangent (gated in
    tests/test_pallas_kernels.py)."""
    out, _ = _fused_epc_fwd(values, segments, batch_show_clk, batch_size,
                            num_slots, use_cvm, cvm_offset, pad_value,
                            need_filter, show_coeff, clk_coeff, threshold)
    return out


def _fused_epc_fwd(values, segments, batch_show_clk, batch_size, num_slots,
                   use_cvm, cvm_offset, pad_value, need_filter, show_coeff,
                   clk_coeff, threshold):
    keep = keep_or_ones(values, need_filter, show_coeff, clk_coeff,
                        threshold).astype(jnp.float32)
    out = fused_pool_cvm_forward(
        values, segments, keep, batch_size, num_slots,
        cvm_mode=CVM_FULL if use_cvm else CVM_NONE,
        cvm_offset=cvm_offset, pad_value=pad_value)
    vtoken = jnp.zeros((0, values.shape[1]), values.dtype)
    return out, (segments, keep, batch_show_clk, vtoken)


def _fused_epc_bwd(batch_size, num_slots, use_cvm, cvm_offset, pad_value,
                   need_filter, show_coeff, clk_coeff, threshold, res, g):
    segments, keep, batch_show_clk, vtoken = res
    d = vtoken.shape[1]
    n = batch_size * num_slots
    # the CVM_FULL forward head is always TWO transformed columns
    # (log1p(show), ctr) regardless of cvm_offset — cvm_offset only
    # sets how many input columns the head REPLACES, so the output
    # slice offset is 2 while the grad width stays d - cvm_offset
    n_head = 2 if use_cvm else 0
    w = d - cvm_offset
    embedx_g = g[..., n_head:].reshape(n, w)
    g_embedx = segment_gather_mxu(embedx_g, segments)          # [K, w]
    ins = jnp.minimum(jnp.clip(segments, 0) // num_slots, batch_size - 1)
    pad = (segments < 0) | (segments >= n)
    g_cvm = batch_show_clk[ins].astype(g_embedx.dtype)
    g_values = jnp.where(
        ((keep > 0) & ~pad)[:, None],
        jnp.concatenate([g_cvm, g_embedx], axis=-1),
        0.0,
    ).astype(vtoken.dtype)
    return (g_values, None, None)


fused_embed_pool_cvm.defvjp(_fused_epc_fwd, _fused_epc_bwd)


def segment_sum(values: jax.Array, segments: jax.Array,
                num_segments: int) -> jax.Array:
    """Backend dispatch: MXU kernel when enabled (requires nondecreasing
    segments — true for all seqpool callers), XLA scatter-add otherwise
    (flag: FLAGS.use_pallas_seqpool)."""
    if FLAGS.use_pallas_seqpool:
        _book_dispatch("segment_sum", "mxu")
        return segment_sum_mxu(values, segments, num_segments)
    _book_dispatch("segment_sum", "xla")
    return jax.ops.segment_sum(values, segments, num_segments=num_segments)
