"""Pallas TPU kernels for the embedding-PS hot paths.

Reference hot kernels being replaced (SURVEY.md §2.1-2.2, §2.4):
- ``PullCopy``/``CopyForPull`` gather (fleet/box_wrapper.cu:75,945) and the
  HeterPS hashtable ``get`` → here ``gather_rows``: a scalar-prefetch row
  gather where the Pallas pipeline double-buffers one row-block DMA per grid
  step (HBM→VMEM), overlapping fetches across steps.
- ``PushMergeCopy`` scatter (box_wrapper.cu:417) + in-kernel optimizer write
  (heter_ps/optimizer.cuh.h) → ``scatter_rows``: aliased in-place row
  scatter (the optimizer math itself stays in jnp where XLA fuses it against
  the gathered rows; only the irregular-access scatter needs a kernel).
- ``FusedSeqpoolKernelNormal`` (fused/fused_seqpool_cvm_op.cu:36) →
  ``segment_sum_mxu``: the ragged per-slot sum-pool recast as a blocked
  one-hot × values matmul so it runs on the MXU systolic array instead of
  scalar scatter-adds — the TPU-first formulation of segment_sum.

All kernels auto-fall back to interpret mode off-TPU so the whole suite is
testable on the CPU mesh (SURVEY.md §4 implication).

Status (measured on one v5p chip, DeepFM/criteo bench, mf_dim=8):
- XLA's native gather/scatter-add is FASTER at small embedding dims (the
  lane padding 11→128 and per-row DMA granularity dominate), so all three
  flags default to False and the jnp paths are the production defaults.
- ``segment_sum_mxu`` is the right shape for wide-D, high-slot-count
  configs (1000-slot fused pipelines, D≥128); re-evaluate there.
- ``gather_rows`` needs a batched-DMA redesign (8 rows/step via manual
  async copies) before it can compete with XLA's gather.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddlebox_tpu.config import FLAGS


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# Row gather (pull_sparse hot path)
# ---------------------------------------------------------------------------

def gather_rows(table: jax.Array, rows: jax.Array) -> jax.Array:
    """table [C, D], rows [U] int32 → [U, D].

    One grid step per row; the row index is scalar-prefetched so the
    pipeline issues the HBM→VMEM DMA for step i+1 while step i copies out.
    """
    c, d = table.shape
    u = rows.shape[0]

    def kernel(rows_ref, tbl_ref, out_ref):
        del rows_ref
        out_ref[...] = tbl_ref[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(u,),
        in_specs=[pl.BlockSpec((1, d), lambda i, rows_ref: (rows_ref[i], 0))],
        out_specs=pl.BlockSpec((1, d), lambda i, rows_ref: (i, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((u, d), table.dtype),
        interpret=_interpret(),
    )(rows, table)


# ---------------------------------------------------------------------------
# Row scatter (push_sparse write-back)
# ---------------------------------------------------------------------------

def scatter_rows(table: jax.Array, rows: jax.Array,
                 values: jax.Array) -> jax.Array:
    """Write values[i] into table[rows[i]] in place (buffer aliased).

    Rows must be unique except for a designated pad/sentinel row, which may
    be written multiple times (last-write-wins nondeterminism is confined to
    that row; callers reset it — see table.apply_push).
    """
    c, d = table.shape
    u = rows.shape[0]

    def kernel(rows_ref, tbl_ref, val_ref, out_ref):
        del rows_ref, tbl_ref
        out_ref[...] = val_ref[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(u,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # aliased table, untouched
            pl.BlockSpec((1, d), lambda i, rows_ref: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, d), lambda i, rows_ref: (rows_ref[i], 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((c, d), table.dtype),
        input_output_aliases={1: 0},  # tensor input 0 (table) → output 0
        interpret=_interpret(),
    )(rows, table, values)


# ---------------------------------------------------------------------------
# MXU segment-sum (fused_seqpool hot path)
# ---------------------------------------------------------------------------
#
# Block-sparse formulation: segments MUST be nondecreasing (batch builder
# emits segment ids ins*S+slot in key order, so this holds for every seqpool
# caller). A key block of TK keys then overlaps at most TK/TB+1 output
# blocks, so instead of the full (segments × keys) cross product the grid is
# a flat list of (output-block, key-block) pairs built host-side: per key
# block j, pairs i = start_block[j]..end_block[j] (clamped, padded to the
# static TK/TB+1 per block). Work is O(K·TB·D) on the MXU — independent of
# num_segments — vs the scatter-add's O(K·D) serialized irregular writes.

def _seg_sum_kernel(i_ref, first_ref, valid_ref, seg_ref, vals_ref, out_ref,
                    *, tb: int, tk: int):
    p = pl.program_id(0)

    @pl.when(first_ref[p] != 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(valid_ref[p] != 0)
    def _acc():
        base = i_ref[p] * tb
        # onehot[r, k] = 1 iff segments[k] == base + r (never true for -1)
        row_ids = jax.lax.broadcasted_iota(jnp.int32, (tb, tk), 0) + base
        onehot = (row_ids == seg_ref[...]).astype(jnp.float32)
        out_ref[...] += jnp.dot(onehot, vals_ref[...],
                                preferred_element_type=jnp.float32,
                                precision=jax.lax.Precision.HIGHEST)


def _segment_sum_mxu_impl(values: jax.Array, segments: jax.Array,
                          num_segments: int) -> jax.Array:
    k, d = values.shape
    tb = 128
    tk = min(512, max(128, _round_up(max(k, 1), 128)))
    k_pad = _round_up(max(k, 1), tk)
    s_pad = _round_up(num_segments, tb)
    d_pad = _round_up(d, 128)
    nkb = k_pad // tk            # key blocks
    ppb = tk // tb + 1           # max output blocks one key block overlaps
    n_pairs = nkb * ppb

    v = jnp.zeros((k_pad, d_pad), jnp.float32)
    v = v.at[:k, :d].set(values.astype(jnp.float32))
    seg = jnp.full((k_pad,), -1, jnp.int32)
    seg = seg.at[:k].set(segments.astype(jnp.int32))

    # host-side (traced, static shapes) pair construction. −1 drop markers
    # may appear anywhere; only the valid entries must be nondecreasing.
    segs2 = seg.reshape(nkb, tk)
    valid_m = segs2 >= 0
    has_valid = valid_m.any(axis=1)
    first_seg = jnp.min(jnp.where(valid_m, segs2, jnp.iinfo(jnp.int32).max),
                        axis=1)
    last_seg = jnp.max(segs2, axis=1)         # nondecreasing ⇒ max = last
    start_b = jnp.where(has_valid, first_seg // tb, 0)
    end_b = jnp.where(has_valid, last_seg // tb, -1)
    # carry forward so all-pad blocks produce in-bounds, monotone i indices
    prev_end = jnp.maximum(jax.lax.cummax(end_b), 0)
    start_b = jnp.where(has_valid, start_b, prev_end)
    end_b = jnp.where(has_valid, end_b, prev_end)

    slot = jnp.arange(n_pairs, dtype=jnp.int32) % ppb
    jb = jnp.arange(n_pairs, dtype=jnp.int32) // ppb
    i_raw = start_b[jb] + slot
    i_arr = jnp.minimum(i_raw, end_b[jb])
    valid = (i_raw <= end_b[jb]) & has_valid[jb]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), i_arr[1:] != i_arr[:-1]])

    # The static ppb bound holds only when segment occupancy is dense (the
    # CTR seqpool shape: num_segments ≈ B*S ≲ K). If any key block spans
    # more output blocks than ppb (sparse occupancy), branch to the XLA
    # scatter-add at runtime — correctness is unconditional.
    overflow = jnp.any((end_b - start_b + 1) > ppb)

    def pallas_branch(_):
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(n_pairs,),
            in_specs=[
                pl.BlockSpec((1, tk), lambda p, i_a, f, v_: (0, p // ppb)),
                pl.BlockSpec((tk, d_pad),
                             lambda p, i_a, f, v_: (p // ppb, 0)),
            ],
            out_specs=pl.BlockSpec(
                (tb, d_pad), lambda p, i_a, f, v_: (i_a[p], 0)),
        )
        out = pl.pallas_call(
            functools.partial(_seg_sum_kernel, tb=tb, tk=tk),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((s_pad, d_pad), jnp.float32),
            interpret=_interpret(),
        )(i_arr, first.astype(jnp.int32), valid.astype(jnp.int32),
          seg.reshape(1, k_pad), v)
        # segment ranges with no keys map to output blocks no pair visits;
        # their buffers are uninitialized — mask them to zero.
        visited = jnp.zeros((s_pad // tb,), bool).at[i_arr].max(valid)
        return jnp.where(jnp.repeat(visited, tb)[:, None], out, 0.0)

    def xla_branch(_):
        safe = jnp.where(seg >= 0, seg, num_segments)
        out = jax.ops.segment_sum(v, safe, num_segments=num_segments + 1)
        return jnp.zeros((s_pad, d_pad), jnp.float32).at[
            :num_segments].set(out[:num_segments])

    out = jax.lax.cond(overflow, xla_branch, pallas_branch, None)
    return out[:num_segments, :d].astype(values.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def segment_sum_mxu(values: jax.Array, segments: jax.Array,
                    num_segments: int) -> jax.Array:
    """values [K, D], segments [K] int32 → [num_segments, D].
    Contract: −1 entries are dropped (allowed anywhere); the NON-negative
    entries must be nondecreasing in array order. See notes above."""
    return _segment_sum_mxu_impl(values, segments, num_segments)


def _seg_sum_fwd(values, segments, num_segments):
    out = _segment_sum_mxu_impl(values, segments, num_segments)
    vtoken = jnp.zeros((0,), values.dtype)  # carries primal dtype
    return out, (segments, vtoken)


def _seg_sum_bwd(num_segments, res, g):
    segments, vtoken = res
    # d/dvalues of a segment sum is a gather of the cotangent rows
    safe = jnp.clip(segments, 0, num_segments - 1)
    g_values = jnp.where((segments >= 0)[:, None], g[safe], 0.0)
    return (g_values.astype(vtoken.dtype), None)


segment_sum_mxu.defvjp(_seg_sum_fwd, _seg_sum_bwd)


def segment_sum(values: jax.Array, segments: jax.Array,
                num_segments: int) -> jax.Array:
    """Backend dispatch: MXU kernel when enabled (requires nondecreasing
    segments — true for all seqpool callers), XLA scatter-add otherwise
    (flag: FLAGS.use_pallas_seqpool)."""
    if FLAGS.use_pallas_seqpool:
        return segment_sum_mxu(values, segments, num_segments)
    return jax.ops.segment_sum(values, segments, num_segments=num_segments)
