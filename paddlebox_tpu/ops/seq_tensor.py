"""fused_seq_tensor — DIN-style ad/user-sequence feature interaction.

Reference: paddle/fluid/operators/fused/fused_seq_tensor_op.{cc,cu} —
inputs ``Input`` (user behavior sequence embeddings,
[ins, batch_count·slot_num·max_length·dim]) and ``ADInput``
([ins, batch_count·ad_slot_num·dim]); outputs (op .cc:95-111):
- DINOut: per sequence position, [in, ad, in−ad, in·ad] interaction
  block over the ad slots (kernel cal_ad_slot_session_kernel, .cu:53-97);
- MaskOut: position non-empty mask via sum-over-slots/dims ≠ 0
  (reduce_sum_max_length, .cu:146-199);
- SideInfoOut: side-info slot slice (cal_sideinfo_kernel);
- ADSlotSessionOut: the ad-slot slice of the input sequence.

TPU-native: four reshape/slice/broadcast expressions fused by XLA — the
CUDA index juggling disappears entirely.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def fused_seq_tensor(
    inputs: jax.Array,      # [ins, batch_count*slot_num*max_length*dim]
    ad_input: jax.Array,    # [ins, batch_count*ad_slot_num*dim]
    batch_count: int,
    max_length: int,
    slot_num: int,
    fea_emb_dim: int,
    ad_slot_num: int,
    ad_slot_offset: int,
    sideinfo_slot_num: int,
    sideinfo_slot_offset: int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (din_out [bc, ins, L, 4·adS·dim], mask [bc, ins, L],
    side_info [bc, ins, L, sideS·dim], ad_session [bc, ins, L, adS·dim])."""
    ins = inputs.shape[0]
    x = inputs.reshape(ins, batch_count, slot_num, max_length, fea_emb_dim)
    ad = ad_input.reshape(ins, batch_count, ad_slot_num, fea_emb_dim)

    seq = x[:, :, ad_slot_offset:ad_slot_offset + ad_slot_num]  # [ins,bc,adS,L,d]
    seq = seq.transpose(1, 0, 3, 2, 4)                          # [bc,ins,L,adS,d]
    adb = ad.transpose(1, 0, 2, 3)[:, :, None]                  # [bc,ins,1,adS,d]
    adb = jnp.broadcast_to(adb, seq.shape)
    din = jnp.stack([seq, adb, seq - adb, seq * adb], axis=3)   # [bc,ins,L,4,adS,d]
    din_out = din.reshape(batch_count, ins, max_length,
                          4 * ad_slot_num * fea_emb_dim)

    pos_sum = x.sum(axis=(2, 4))                                # [ins,bc,L]
    mask = (jnp.abs(pos_sum) > 1e-8).astype(inputs.dtype)
    mask_out = mask.transpose(1, 0, 2)                          # [bc,ins,L]

    side = x[:, :, sideinfo_slot_offset:
             sideinfo_slot_offset + sideinfo_slot_num]
    side_out = side.transpose(1, 0, 3, 2, 4).reshape(
        batch_count, ins, max_length, sideinfo_slot_num * fea_emb_dim)

    ad_session_out = seq.reshape(batch_count, ins, max_length,
                                 ad_slot_num * fea_emb_dim)
    return din_out, mask_out, side_out, ad_session_out
