"""scaled_fc / scaled_int8fc — reduced-precision FC with scale factors.

Reference: paddle/fluid/operators/scaled_fc_op.{cc,cu}: X and bias are
scaled (input_scale_factor/bias_scale_factor), cast to fp16, padded to
multiples of the GEMM tile, multiplied, then the output is unscaled by
1/(input_scale*bias_scale) with inf→nan so bad values propagate to the
NaN guard (kernel_cast_and_cut). grad_scale_factor applies the same trick
to backward. scaled_int8fc_op quantizes to int8 with per-tensor scales.

TPU-native: bf16 shares fp32's exponent range, so loss-scaling is
unnecessary — the op keeps the API (scales still applied/removed for
bit-compat of the math) but runs the matmul in bf16 on the MXU, f32
accumulation. int8 variant uses jnp.int8 with rounding, for parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def scaled_fc(x: jax.Array, w: jax.Array, bias: jax.Array,
              input_scale_factor: float = 1.0,
              bias_scale_factor: float = 1.0,
              compute_dtype=jnp.bfloat16) -> jax.Array:
    # reference wiring (scaled_fc_op.cu:211-222): GEMM alpha=si, bias added
    # scaled by sb, output unscaled by 1/si ⇒ out = x@w + (sb/si)·b
    mm = jnp.dot(x.astype(compute_dtype), w.astype(compute_dtype),
                 preferred_element_type=jnp.float32) * input_scale_factor
    out = mm + (bias * bias_scale_factor).astype(jnp.float32)[None, :]
    return out / input_scale_factor


def scaled_int8fc(x: jax.Array, w: jax.Array, bias: jax.Array,
                  input_scale: float, weight_scale: float) -> jax.Array:
    xq = jnp.clip(jnp.round(x * input_scale), -127, 127).astype(jnp.int8)
    wq = jnp.clip(jnp.round(w * weight_scale), -127, 127).astype(jnp.int8)
    acc = jnp.dot(xq, wq, preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) / (input_scale * weight_scale) + bias[None, :]
