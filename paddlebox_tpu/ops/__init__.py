from paddlebox_tpu.ops.seqpool_cvm import (
    fused_seqpool_cvm, fused_seqpool_cvm_with_conv, fused_seqpool_concat,
)
from paddlebox_tpu.ops.pallas_kernels import (
    fused_embed_pool_cvm, segment_gather_mxu, segment_sum_mxu,
)
from paddlebox_tpu.ops.pallas_ctr import (
    fused_batch_fc, fused_cross_norm_hadamard, fused_rank_attention,
)
from paddlebox_tpu.ops.cvm import cvm, cvm_grad_passthrough
from paddlebox_tpu.ops.rank_attention import (rank_attention,
                                              rank_attention2)
from paddlebox_tpu.ops.batch_fc import batch_fc
from paddlebox_tpu.ops.shuffle_batch import shuffle_batch, unshuffle_batch
from paddlebox_tpu.ops.partial_ops import partial_concat, partial_sum
from paddlebox_tpu.ops.data_norm import (
    DataNormSummary, data_norm, data_norm_update, init_data_norm_summary,
)
from paddlebox_tpu.ops.cross_norm import (
    cross_norm_hadamard, cross_norm_update, init_cross_norm_summary,
)
from paddlebox_tpu.ops.scaled_fc import scaled_fc, scaled_int8fc
from paddlebox_tpu.ops.seqpool_variants import (
    fused_seqpool_cvm_with_diff_thres, fused_seqpool_cvm_tradew,
    fused_seqpool_cvm_with_credit, fused_seqpool_cvm_with_pcoc,
)
from paddlebox_tpu.ops.seq_tensor import fused_seq_tensor

__all__ = [
    "fused_seqpool_cvm", "fused_seqpool_cvm_with_conv",
    "fused_seqpool_concat", "cvm", "cvm_grad_passthrough", "rank_attention",
    "rank_attention2",
    "batch_fc", "shuffle_batch", "unshuffle_batch", "partial_concat",
    "partial_sum", "DataNormSummary", "data_norm", "data_norm_update",
    "init_data_norm_summary", "cross_norm_hadamard", "cross_norm_update",
    "init_cross_norm_summary", "scaled_fc", "scaled_int8fc",
    "fused_seqpool_cvm_with_diff_thres", "fused_seqpool_cvm_tradew",
    "fused_seqpool_cvm_with_credit", "fused_seqpool_cvm_with_pcoc",
    "fused_seq_tensor", "fused_embed_pool_cvm", "segment_gather_mxu",
    "segment_sum_mxu", "fused_rank_attention", "fused_batch_fc",
    "fused_cross_norm_hadamard",
]
