from paddlebox_tpu.ops.seqpool_cvm import fused_seqpool_cvm
from paddlebox_tpu.ops.cvm import cvm, cvm_grad_passthrough

__all__ = ["fused_seqpool_cvm", "cvm", "cvm_grad_passthrough"]
