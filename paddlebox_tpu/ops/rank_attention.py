"""rank_attention — per-ad rank-position attention.

Reference: paddle/fluid/operators/rank_attention_op.{cc,cu,h} +
rank_attention.cu.h. Semantics (expand_input_by_rank_kernel :30-45,
expand_rank_attention_param_kernel :60-90): ``rank_offset[:, 0]`` is the
instance's own 1-based rank (0 ⇒ invalid); for each k < max_rank the pair
(rank_offset[:, 2k+1], rank_offset[:, 2k+2]) gives the 1-based rank and the
X-row index of the k-th co-shown ad. Output[i] = Σ_k X[idx_k] @
P[(own-1)*max_rank + (rank_k-1)] where RankParam is viewed as
[max_rank*max_rank, input_dim, out_dim] blocks; invalid entries contribute 0.

TPU-native: the CUDA path materializes expanded input/param then runs a
batched GEMM. The XLA composition here is BLOCK-GROUPED (ISSUE 13): the
sum regroups by param block b ∈ [0, max_rank²) —
``out = Σ_b (Σ_{k: blk(i,k)=b} X[idx_k]) @ P[b]`` — two einsums over a
[N, K, max_rank²] one-hot, so the peak intermediate is the [max_rank²,
N, D] grouped input instead of the ``param[block]`` gather's
[N, K, D, P] blow-up (~800 MB at N=4096, D=P=128; the absence of that
tensor is pinned by an HLO check in tests/test_extended_ops.py). X
gradients flow only when ``enable_input_bp`` is True
(rank_attention_op.cu computes dX only under EnableInputBp).

THE dispatch seam: under ``FLAGS.use_pallas_rank_attention`` (and the
static VMEM residency check) the same math runs as the fused Pallas
kernel ``ops.pallas_ctr.fused_rank_attention`` — param blocks
VMEM-resident, one-hot folded into the MXU matmul. Both decisions book
``pbox_kernel_dispatch_total{kernel="rank_attention"}``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.ops.pallas_ctr import (_book_dispatch,
                                          decode_rank_offset,
                                          fused_rank_attention,
                                          normalize_rank_param,
                                          rank_attention_fits)


def _rank_attention_xla(x: jax.Array, rank_offset: jax.Array,
                        param3: jax.Array, max_rank: int) -> jax.Array:
    """Block-grouped XLA composition (see module docstring)."""
    n = x.shape[0]
    mr2 = max_rank * max_rank
    blk, idx, valid = decode_rank_offset(rank_offset, max_rank, n)
    x_k = jnp.where(valid[..., None], x[idx], 0.0)        # [N, K, D]
    onehot = (blk[..., None] == jnp.arange(mr2)).astype(x.dtype)
    gmat = jnp.einsum("nkd,nkb->bnd", x_k, onehot)        # [MR2, N, D]
    return jnp.einsum("bnd,bdp->np", gmat, param3)


def rank_attention(x: jax.Array, rank_offset: jax.Array,
                   rank_param: jax.Array, max_rank: int = 3,
                   enable_input_bp: bool = False) -> jax.Array:
    """x: [N, D]; rank_offset: int32 [N, 1+2*max_rank];
    rank_param: [max_rank*max_rank*D, P] (reference layout) or
    [max_rank*max_rank, D, P]. Returns [N, P]."""
    n, d = x.shape
    param3 = normalize_rank_param(rank_param, max_rank, d)
    p = param3.shape[-1]
    if FLAGS.use_pallas_rank_attention and rank_attention_fits(max_rank,
                                                              d, p):
        # the fused kernel's custom_vjp owns the enable_input_bp gate
        _book_dispatch("rank_attention", "pallas")
        return fused_rank_attention(x, rank_offset, rank_param, max_rank,
                                    enable_input_bp)
    _book_dispatch("rank_attention", "xla")
    if not enable_input_bp:
        x = jax.lax.stop_gradient(x)
    return _rank_attention_xla(x, rank_offset, param3, max_rank)


def rank_attention2(x: jax.Array, rank_offset: jax.Array,
                    rank_param: jax.Array, max_rank: int = 3) -> jax.Array:
    """rank_attention2 (rank_attention_op.cc:179-308).

    Same attention sum as :func:`rank_attention`
    (kernel_rank_feed_forward, rank_attention_op.cu:216-254 — identical
    block math) but the op registers gradients ONLY for RankParam: the
    grad kernel (kernel_rank_back_propagate :257-294) accumulates
    out_para_grad and the X/RankOffset inputs are declared "not use
    data". Equivalent to the v1 path with input backprop disabled, minus
    the expanded-helper buffers the CUDA v1 materializes (irrelevant
    here — XLA never materializes them)."""
    return rank_attention(x, rank_offset, rank_param, max_rank,
                          enable_input_bp=False)
