"""rank_attention — per-ad rank-position attention.

Reference: paddle/fluid/operators/rank_attention_op.{cc,cu,h} +
rank_attention.cu.h. Semantics (expand_input_by_rank_kernel :30-45,
expand_rank_attention_param_kernel :60-90): ``rank_offset[:, 0]`` is the
instance's own 1-based rank (0 ⇒ invalid); for each k < max_rank the pair
(rank_offset[:, 2k+1], rank_offset[:, 2k+2]) gives the 1-based rank and the
X-row index of the k-th co-shown ad. Output[i] = Σ_k X[idx_k] @
P[(own-1)*max_rank + (rank_k-1)] where RankParam is viewed as
[max_rank*max_rank, input_dim, out_dim] blocks; invalid entries contribute 0.

TPU-native: the CUDA path materializes expanded input/param then runs a
batched GEMM; here it's two gathers + one einsum — XLA fuses the masking and
batches the GEMM on the MXU. X gradients flow only when ``enable_input_bp``
is True (rank_attention_op.cu computes dX only under EnableInputBp).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rank_attention(x: jax.Array, rank_offset: jax.Array,
                   rank_param: jax.Array, max_rank: int = 3,
                   enable_input_bp: bool = False) -> jax.Array:
    """x: [N, D]; rank_offset: int32 [N, 1+2*max_rank];
    rank_param: [max_rank*max_rank*D, P] (reference layout) or
    [max_rank*max_rank, D, P]. Returns [N, P]."""
    n, d = x.shape
    if rank_param.ndim == 2:
        p = rank_param.shape[-1]
        param = rank_param.reshape(max_rank * max_rank, d, p)
    else:
        param = rank_param
        p = param.shape[-1]
    if not enable_input_bp:
        x = jax.lax.stop_gradient(x)

    own = rank_offset[:, 0] - 1                      # [N] -1 ⇒ invalid
    ks = jnp.arange(max_rank)
    faster = rank_offset[:, 1 + 2 * ks] - 1          # [N, K]
    idx = rank_offset[:, 2 + 2 * ks]                 # [N, K]
    valid = (own[:, None] >= 0) & (faster >= 0)      # [N, K]

    x_k = jnp.where(valid[..., None],
                    x[jnp.clip(idx, 0, n - 1)], 0.0)          # [N, K, D]
    block = jnp.clip(own[:, None], 0, max_rank - 1) * max_rank \
        + jnp.clip(faster, 0, max_rank - 1)                   # [N, K]
    # x_k is already zeroed for invalid (i,k), so the param gather needs no
    # mask — the einsum contribution and the param cotangent are both 0
    return jnp.einsum("nkd,nkdp->np", x_k, param[block])


def rank_attention2(x: jax.Array, rank_offset: jax.Array,
                    rank_param: jax.Array, max_rank: int = 3) -> jax.Array:
    """rank_attention2 (rank_attention_op.cc:179-308).

    Same attention sum as :func:`rank_attention`
    (kernel_rank_feed_forward, rank_attention_op.cu:216-254 — identical
    block math) but the op registers gradients ONLY for RankParam: the
    grad kernel (kernel_rank_back_propagate :257-294) accumulates
    out_para_grad and the X/RankOffset inputs are declared "not use
    data". Equivalent to the v1 path with input backprop disabled, minus
    the expanded-helper buffers the CUDA v1 materializes (irrelevant
    here — XLA never materializes them)."""
    return rank_attention(x, rank_offset, rank_param, max_rank,
                          enable_input_bp=False)
