"""Unfused CVM transform op.

Reference: paddle/fluid/operators/cvm_op.{h,cc,cu} — ``CvmComputeKernel``
(cvm_op.h:25-40): with use_cvm, y0=log(x0+1), y1=log(x1+1)-y0, rest copied
(same width); without, the two cvm columns are stripped. Backward
``CvmGradComputeKernel`` (:43-58): dx[0:2] = CVM batch values, embed dims
pass the upstream grad straight through (log is NOT differentiated — the
show/clk columns are statistics channels for the PS, not trained weights).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def cvm(x: jax.Array, batch_cvm: jax.Array, use_cvm: bool = True) -> jax.Array:
    """x: [B, D] with x[:,0]=show, x[:,1]=clk; batch_cvm: [B, 2].
    Returns [B, D] (use_cvm) or [B, D-2]."""
    out, _ = _fwd(x, batch_cvm, use_cvm)
    return out


def _fwd(x, batch_cvm, use_cvm):
    if use_cvm:
        show = jnp.log1p(x[:, 0:1])
        ctr = jnp.log1p(x[:, 1:2]) - show
        out = jnp.concatenate([show, ctr, x[:, 2:]], axis=1)
    else:
        out = x[:, 2:]
    return out, (batch_cvm, jnp.zeros((0,), x.dtype))


def _bwd(use_cvm, res, g):
    batch_cvm, xtoken = res
    g_embed = g[:, 2:] if use_cvm else g
    dx = jnp.concatenate([batch_cvm.astype(g_embed.dtype), g_embed], axis=1)
    return (dx.astype(xtoken.dtype), None)


cvm.defvjp(_fwd, _bwd)


def cvm_grad_passthrough(x: jax.Array) -> jax.Array:
    """Identity whose gradient skips the first two (show/clk) columns —
    convenience for models wiring raw pulled values into non-CVM heads."""
    zero2 = jnp.concatenate(
        [jnp.zeros_like(x[:, :2]), jnp.ones_like(x[:, 2:])], axis=1)
    return x * zero2 + jax.lax.stop_gradient(x * (1 - zero2))
