"""Pallas TPU kernels for the device-side CTR op family (ISSUE 13).

The rest of PaddleBox's CTR op family after the PR 11 embed-pool-CVM
suite — the ops `rank_attention_op.cu`, `batch_fc_op.cu` and
`cross_norm_hadamard.cu.h` run as hand-fused CUDA kernels in the
reference (SURVEY §0) but were naive XLA compositions here. Worst
offender: the `rank_attention` einsum gathered `param[block]` into an
`[N, K, D, P]` tensor (~800 MB at N=4096, D=P=128) where the CUDA
reference streams batched GEMMs. The kernels below apply the PR 11
recipe (blocked VMEM residency + one-hot matmuls on the MXU — the
FusedMM / Ragged-Paged-Attention shape, PAPERS.md):

- ``fused_rank_attention`` — block-grouped formulation: the at most
  ``max_rank²`` (≤ 9) param blocks stay VMEM-resident for the whole
  grid; per grid step one TN-row block of the gathered co-shown-ad
  features streams in and, per param block b, a (row, key)-one-hot
  [TN, TN·K] folds the keep mask into the MXU matmul
  ``onehot_b @ x_block @ P[b]`` accumulated into the output block —
  the `[N, K, D, P]` gather is never materialized. The ``custom_vjp``
  scatters the param cotangent into the max_rank² blocks and lets dX
  flow only under ``enable_input_bp`` (covers ``rank_attention`` and
  ``rank_attention2``).
- ``fused_batch_fc`` — per-slot blocked batched GEMM: one slot's
  weight block stays VMEM-resident while TN-row input blocks stream
  through, with the bias add fused while the output block is still in
  VMEM (default, batchcount and transpose_weight modes — the
  transpose rides ``dot_general`` dimension numbers, no materialized
  weight transpose).
- ``fused_cross_norm_hadamard`` — one VMEM pass per (row-block,
  field): loads the field's [a, b] pair block once and emits the
  normalized ``[a, b, a⊙b, a·b]`` output block in the same residency
  (the data_norm mean/scale are applied before the block leaves VMEM;
  the summary update and the sharded ``sync_stats`` psum stay outside
  in ``ops/cross_norm``).

Backwards are hand-written jnp mirroring the XLA compositions'
autodiff ops exactly, so given the same upstream cotangent the grads
match the flag-off path bitwise (gated in tests/test_pallas_ctr.py);
only the forwards carry MXU summation-order f32 drift.

Dispatch: each op's module owns ONE seam reading its
``FLAGS.use_pallas_{rank_attention,batch_fc,cross_norm}`` flag
(ops/rank_attention.py, ops/batch_fc.py, ops/cross_norm.py); a shape
that overflows the kernel's VMEM residency budget (checked statically
— these ops have no runtime raggedness) falls back to the XLA
composition, and both decisions book
``pbox_kernel_dispatch_total{kernel,impl}``. All kernels run in
interpret mode off-TPU (the CPU-mesh testability contract of
ops/pallas_kernels).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddlebox_tpu.ops.pallas_kernels import (_book_dispatch, _interpret,
                                              _round_up)

#: rows per grid step (output block height) shared by the CTR kernels
_TN = 128
#: VMEM budget for a kernel's resident working set (bytes) — param
#: blocks + one streamed input/output block must fit comfortably under
#: the ~16 MB VMEM with room for the pipeline's double buffer
_VMEM_BUDGET = 8 * 1024 * 1024


# ---------------------------------------------------------------------------
# rank_attention — block-grouped MXU kernel
# ---------------------------------------------------------------------------

def decode_rank_offset(rank_offset: jax.Array, max_rank: int,
                       n: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """``rank_offset`` [N, 1+2K] → (blk [N, K] int32 with −1 for
    invalid entries, idx [N, K] clipped X-row indices, valid [N, K]).

    blk = (own−1)·max_rank + (rank_k−1), the RankParam block id of the
    (own-rank, co-rank) pair (rank_attention_op.cu:60-90); entries with
    own ≤ 0 or rank_k ≤ 0 get the −1 drop marker (they contribute
    nothing on every path). Out-of-range ranks clip into the block
    table exactly like the historical einsum path."""
    ks = jnp.arange(max_rank)
    own = rank_offset[:, 0] - 1                       # [N], −1 ⇒ invalid
    faster = rank_offset[:, 1 + 2 * ks] - 1           # [N, K]
    idx = jnp.clip(rank_offset[:, 2 + 2 * ks], 0, n - 1)
    valid = (own[:, None] >= 0) & (faster >= 0)
    blk = jnp.clip(own[:, None], 0, max_rank - 1) * max_rank \
        + jnp.clip(faster, 0, max_rank - 1)
    return jnp.where(valid, blk, -1).astype(jnp.int32), idx, valid


def normalize_rank_param(rank_param: jax.Array, max_rank: int,
                         d: int) -> jax.Array:
    """[max_rank²·D, P] (reference layout) or [max_rank², D, P] →
    the 3-D block view."""
    if rank_param.ndim == 2:
        return rank_param.reshape(max_rank * max_rank, d,
                                  rank_param.shape[-1])
    return rank_param


def rank_attention_fits(max_rank: int, d: int, p: int) -> bool:
    """Static residency check for the fused kernel: all max_rank² param
    blocks plus one [TN·K, D] input and [TN, P] output block must sit
    in the VMEM budget (overflow → the seam's XLA fallback)."""
    mr2 = max_rank * max_rank
    d_pad, p_pad = _round_up(d, 128), _round_up(p, 128)
    resident = mr2 * d_pad * p_pad * 4
    streamed = _TN * max_rank * d_pad * 4 + _TN * p_pad * 4
    return mr2 <= 16 and resident + 2 * streamed <= _VMEM_BUDGET


def _rank_attn_kernel(blk_ref, x_ref, p_ref, o_ref, *, tn: int, k: int,
                      mr2: int):
    nk = tn * k
    rows = jax.lax.broadcasted_iota(jnp.int32, (tn, nk), 0)
    row_of = jax.lax.broadcasted_iota(jnp.int32, (tn, nk), 1) // k
    blk = blk_ref[...]                                # [1, nk]
    acc = jnp.zeros(o_ref.shape, jnp.float32)
    for b in range(mr2):                              # ≤ 9, fully unrolled
        # onehot[r, j] = 1 iff key j belongs to row r AND routes to
        # param block b — the keep mask (−1 never matches) folds into
        # the same matmul that groups the gathered rows
        onehot = ((row_of == rows) & (blk == b)).astype(jnp.float32)
        g = jnp.dot(onehot, x_ref[...],
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST)   # [tn, d_pad]
        acc = acc + jnp.dot(g, p_ref[b],
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.HIGHEST)
    o_ref[...] = acc


def _rank_attention_forward(x: jax.Array, rank_offset: jax.Array,
                            rank_param: jax.Array,
                            max_rank: int) -> jax.Array:
    n, d = x.shape
    param3 = normalize_rank_param(rank_param, max_rank, d)
    mr2, _, p = param3.shape
    blk, idx, _valid = decode_rank_offset(rank_offset, max_rank, n)
    k = max_rank
    n_pad = _round_up(max(n, 1), _TN)
    d_pad, p_pad = _round_up(d, 128), _round_up(p, 128)

    # the gathered co-shown-ad features, [N·K, D] — this stays an XLA
    # row gather (cheap, K ≤ max_rank); the kernel's one-hot drops the
    # invalid entries so no pre-masking is needed
    x_flat = x[idx].reshape(n * k, d).astype(jnp.float32)
    xp = jnp.zeros((n_pad * k, d_pad), jnp.float32)
    xp = xp.at[:n * k, :d].set(x_flat)
    blk_row = jnp.full((1, n_pad * k), -1, jnp.int32)
    blk_row = blk_row.at[0, :n * k].set(blk.reshape(n * k))
    pp = jnp.zeros((mr2, d_pad, p_pad), jnp.float32)
    pp = pp.at[:, :d, :p].set(param3.astype(jnp.float32))

    out = pl.pallas_call(
        functools.partial(_rank_attn_kernel, tn=_TN, k=k, mr2=mr2),
        grid=(n_pad // _TN,),
        in_specs=[
            pl.BlockSpec((1, _TN * k), lambda i: (0, i)),
            pl.BlockSpec((_TN * k, d_pad), lambda i: (i, 0)),
            pl.BlockSpec((mr2, d_pad, p_pad), lambda i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((_TN, p_pad), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, p_pad), jnp.float32),
        interpret=_interpret(),
    )(blk_row, xp, pp)
    return out[:n, :p].astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_rank_attention(x: jax.Array, rank_offset: jax.Array,
                         rank_param: jax.Array, max_rank: int = 3,
                         enable_input_bp: bool = False) -> jax.Array:
    """Block-grouped rank attention on the MXU (see module docstring).

    Same contract as ``ops.rank_attention.rank_attention``: x [N, D],
    rank_offset int32 [N, 1+2·max_rank], rank_param [max_rank²·D, P]
    or [max_rank², D, P] → [N, P]. The backward scatters the param
    cotangent into the max_rank² blocks with the SAME einsum forms the
    XLA composition's autodiff produces (bitwise-equal grads given the
    same upstream cotangent); dX flows only under
    ``enable_input_bp``."""
    return _rank_attention_forward(x, rank_offset, rank_param, max_rank)


def _ra_fwd(x, rank_offset, rank_param, max_rank, enable_input_bp):
    out = _rank_attention_forward(x, rank_offset, rank_param, max_rank)
    return out, (x, rank_offset, rank_param)


def _ra_bwd(max_rank, enable_input_bp, res, g):
    x, rank_offset, rank_param = res
    n, d = x.shape
    param3 = normalize_rank_param(rank_param, max_rank, d)
    mr2 = max_rank * max_rank
    blk, idx, valid = decode_rank_offset(rank_offset, max_rank, n)
    # the SAME block-grouped residuals the XLA fallback builds — its
    # autodiff emits exactly these einsums, so flag-on grads match the
    # flag-off path bitwise
    x_k = jnp.where(valid[..., None], x[idx], 0.0)            # [N, K, D]
    onehot = (blk[..., None] == jnp.arange(mr2)).astype(x.dtype)
    gmat = jnp.einsum("nkd,nkb->bnd", x_k, onehot)
    d_param3 = jnp.einsum("bnd,np->bdp", gmat, g)
    d_param = d_param3.reshape(rank_param.shape).astype(rank_param.dtype)
    if enable_input_bp:
        d_gmat = jnp.einsum("np,bdp->bnd", g, param3)
        d_xk = jnp.einsum("bnd,nkb->nkd", d_gmat, onehot)
        d_xk = jnp.where(valid[..., None], d_xk, 0.0)
        dx = jnp.zeros_like(x).at[idx].add(d_xk.astype(x.dtype))
    else:
        dx = jnp.zeros_like(x)
    return (dx, None, d_param)


fused_rank_attention.defvjp(_ra_fwd, _ra_bwd)


# ---------------------------------------------------------------------------
# batch_fc — per-slot blocked batched GEMM, bias fused in-VMEM
# ---------------------------------------------------------------------------

def batch_fc_fits(i_dim: int, o_dim: int) -> bool:
    """Static residency check: one slot's weight block + a streamed
    [TN, I] input and [TN, O] output block within the VMEM budget
    (row-count independent — rows stream in TN blocks)."""
    i_pad, o_pad = _round_up(i_dim, 128), _round_up(o_dim, 128)
    resident = i_pad * o_pad * 4 + o_pad * 4
    streamed = _TN * (i_pad + o_pad) * 4
    return resident + 2 * streamed <= _VMEM_BUDGET


def _batch_fc_kernel(x_ref, w_ref, b_ref, o_ref, *, transpose_weight: bool):
    xb = x_ref[0]                                     # [tn, i_pad]
    wb = w_ref[0]                    # [i_pad, o_pad] or [o_pad, i_pad]
    dims = (((1,), (1,)), ((), ())) if transpose_weight \
        else (((1,), (0,)), ((), ()))
    acc = jax.lax.dot_general(xb, wb, dimension_numbers=dims,
                              preferred_element_type=jnp.float32,
                              precision=jax.lax.Precision.HIGHEST)
    o_ref[0] = acc + b_ref[0]        # bias add while VMEM-resident


def _batch_fc_forward(xb: jax.Array, w: jax.Array, bias: jax.Array,
                      transpose_weight: bool) -> jax.Array:
    """xb [S, N, I] × w [S, I, O] (or [S, O, I] transposed) + bias
    [S, O] → [S, N, O], one slot-weight residency per grid column."""
    s, n, i_dim = xb.shape
    o_dim = w.shape[1] if transpose_weight else w.shape[2]
    n_pad = _round_up(max(n, 1), _TN)
    i_pad, o_pad = _round_up(i_dim, 128), _round_up(o_dim, 128)

    xp = jnp.zeros((s, n_pad, i_pad), jnp.float32)
    xp = xp.at[:, :n, :i_dim].set(xb.astype(jnp.float32))
    wshape = (s, o_pad, i_pad) if transpose_weight else (s, i_pad, o_pad)
    wp = jnp.zeros(wshape, jnp.float32)
    wp = wp.at[:, :w.shape[1], :w.shape[2]].set(w.astype(jnp.float32))
    bp = jnp.zeros((s, 1, o_pad), jnp.float32)
    bp = bp.at[:, 0, :o_dim].set(bias.astype(jnp.float32))

    wi, wo = wshape[1], wshape[2]
    out = pl.pallas_call(
        functools.partial(_batch_fc_kernel,
                          transpose_weight=transpose_weight),
        grid=(s, n_pad // _TN),
        in_specs=[
            pl.BlockSpec((1, _TN, i_pad), lambda si, ni: (si, ni, 0)),
            pl.BlockSpec((1, wi, wo), lambda si, ni: (si, 0, 0)),
            pl.BlockSpec((1, 1, o_pad), lambda si, ni: (si, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _TN, o_pad), lambda si, ni: (si, ni, 0)),
        out_shape=jax.ShapeDtypeStruct((s, n_pad, o_pad), jnp.float32),
        interpret=_interpret(),
    )(xp, wp, bp)
    return out[:, :n, :o_dim].astype(xb.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_batch_fc(x: jax.Array, w: jax.Array, bias: jax.Array,
                   batchcount: int = 0,
                   transpose_weight: bool = False) -> jax.Array:
    """Fused-bias blocked batched GEMM — same contract as
    ``ops.batch_fc.batch_fc`` (default [S, N, I] mode, batchcount flat
    [bc·N, I] mode, transpose_weight — batchcount mode only, like the
    reference attr). Backward mirrors the XLA einsums' autodiff
    bitwise."""
    out, _ = _bfc_fwd(x, w, bias, batchcount, transpose_weight)
    return out


def _bfc_fwd(x, w, bias, batchcount, transpose_weight):
    if transpose_weight and batchcount <= 0:
        # the reference op defines transpose_weight only for the
        # batchcount layout; silently contracting an [S, O, I] weight
        # on the wrong axis would return garbage, not an error
        raise ValueError(
            "batch_fc: transpose_weight requires batchcount > 0")
    if batchcount > 0:
        ins = x.shape[0] // batchcount
        xb = x.reshape(batchcount, ins, x.shape[-1])
        out = _batch_fc_forward(xb, w, bias, transpose_weight)
        out = out.reshape(batchcount * ins, -1)
    else:
        out = _batch_fc_forward(x, w, bias, False)
    return out, (x, w, bias)


def _bfc_bwd(batchcount, transpose_weight, res, g):
    x, w, bias = res
    if batchcount > 0:
        ins = x.shape[0] // batchcount
        xb = x.reshape(batchcount, ins, x.shape[-1])
        gb = g.reshape(batchcount, ins, -1)
        wb = jnp.swapaxes(w, 1, 2) if transpose_weight else w
        dx = jnp.einsum("bno,bio->bni", gb, wb).reshape(x.shape)
        dwb = jnp.einsum("bni,bno->bio", xb, gb)
        dw = jnp.swapaxes(dwb, 1, 2) if transpose_weight else dwb
        db = gb.sum(axis=1)
    else:
        dx = jnp.einsum("sno,sio->sni", g, w)
        dw = jnp.einsum("sni,sno->sio", x, g)
        db = g.sum(axis=1)
    return (dx.astype(x.dtype), dw.astype(w.dtype), db.astype(bias.dtype))


fused_batch_fc.defvjp(_bfc_fwd, _bfc_bwd)


# ---------------------------------------------------------------------------
# cross_norm_hadamard — one-VMEM-pass cross blocks + data_norm apply
# ---------------------------------------------------------------------------

def cross_norm_fits(embed_dim: int) -> bool:
    """Static residency check: one field's [TB, d] a/b pair blocks, the
    [TB, 3d+1] output block and the field's mean/scale rows."""
    d_pad = _round_up(embed_dim, 128)
    w_pad = _round_up(3 * embed_dim + 1, 128)
    streamed = _TN * (2 * d_pad + w_pad) * 4 + 2 * w_pad * 4
    return 2 * streamed <= _VMEM_BUDGET


def _cross_norm_kernel(a_ref, b_ref, m_ref, s_ref, o_ref, *, d: int):
    av = a_ref[:, 0, :]                               # [tb, d_pad]
    bv = b_ref[:, 0, :]
    had = av * bv
    # d_pad tail columns are zero, so the dot product over the padded
    # lane dim is exact
    dot = jnp.sum(had, axis=-1, keepdims=True)        # [tb, 1]
    w_pad = o_ref.shape[-1]
    pad = w_pad - (3 * d + 1)
    feats = jnp.concatenate(
        [av[:, :d], bv[:, :d], had[:, :d], dot,
         jnp.zeros((av.shape[0], pad), jnp.float32)], axis=-1)
    # normalization applied in the SAME residency (mean/scale pads are
    # zero, so the pad columns stay exactly zero)
    o_ref[:, 0, :] = (feats - m_ref[...]) * s_ref[...]


def _cross_norm_forward(x: jax.Array, mean: jax.Array, scale: jax.Array,
                        fields_num: int, embed_dim: int) -> jax.Array:
    b = x.shape[0]
    n, d = fields_num, embed_dim
    w_out = 3 * d + 1
    tb = _TN
    b_pad = _round_up(max(b, 1), tb)
    d_pad, w_pad = _round_up(d, 128), _round_up(w_out, 128)

    pairs = x.reshape(b, n, 2, d).astype(jnp.float32)
    ap = jnp.zeros((b_pad, n, d_pad), jnp.float32)
    ap = ap.at[:b, :, :d].set(pairs[:, :, 0])
    bp = jnp.zeros((b_pad, n, d_pad), jnp.float32)
    bp = bp.at[:b, :, :d].set(pairs[:, :, 1])
    mp = jnp.zeros((n, w_pad), jnp.float32)
    mp = mp.at[:, :w_out].set(mean.reshape(n, w_out).astype(jnp.float32))
    sp = jnp.zeros((n, w_pad), jnp.float32)
    sp = sp.at[:, :w_out].set(scale.reshape(n, w_out).astype(jnp.float32))

    out = pl.pallas_call(
        functools.partial(_cross_norm_kernel, d=d),
        grid=(b_pad // tb, n),
        in_specs=[
            pl.BlockSpec((tb, 1, d_pad), lambda i, j: (i, j, 0)),
            pl.BlockSpec((tb, 1, d_pad), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, w_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((1, w_pad), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((tb, 1, w_pad), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b_pad, n, w_pad), jnp.float32),
        interpret=_interpret(),
    )(ap, bp, mp, sp)
    return out[:b, :, :w_out].reshape(b, n * w_out).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_cross_norm_hadamard(x: jax.Array, mean: jax.Array,
                              scale: jax.Array, fields_num: int,
                              embed_dim: int) -> jax.Array:
    """One fused VMEM pass: per (row-block, field) build the
    ``[a, b, a⊙b, a·b]`` cross block and apply the data_norm
    ``(v - mean)·scale`` while the block is still resident. ``mean``/
    ``scale`` are the flat [fields_num·(3·embed_dim+1)] data_norm
    vectors (the seam in ``ops/cross_norm`` derives them from the
    summary, keeping the summary-cotangent chain outside this op)."""
    out, _ = _cn_fwd(x, mean, scale, fields_num, embed_dim)
    return out


def _cn_fwd(x, mean, scale, fields_num, embed_dim):
    out = _cross_norm_forward(x, mean, scale, fields_num, embed_dim)
    return out, (x, mean, scale)


def _cn_bwd(fields_num, embed_dim, res, g):
    x, mean, scale = res
    n, d = fields_num, embed_dim
    w_out = 3 * d + 1
    b = x.shape[0]
    pairs = x.reshape(b, n, 2, d)
    a, bb = pairs[:, :, 0], pairs[:, :, 1]
    g3 = g.reshape(b, n, w_out)
    sc = scale.reshape(n, w_out)
    mn = mean.reshape(n, w_out)
    ge = g3 * sc[None]                      # d y / d feats = scale
    ga, gb = ge[..., :d], ge[..., d:2 * d]
    gh, gd = ge[..., 2 * d:3 * d], ge[..., 3 * d:]
    da = ga + gh * bb + gd * bb             # dot = Σ a·b ⇒ ∂/∂a = b
    db = gb + gh * a + gd * a
    dx = jnp.stack([da, db], axis=2).reshape(x.shape).astype(x.dtype)
    # feats recomputed for the scale cotangent (cheap — one mul + sum)
    had = a * bb
    feats = jnp.concatenate(
        [a, bb, had, jnp.sum(had, axis=-1, keepdims=True)], axis=-1)
    dmean = (-ge.sum(axis=0)).reshape(mean.shape).astype(mean.dtype)
    dscale = ((g3 * (feats - mn[None])).sum(axis=0)
              ).reshape(scale.shape).astype(scale.dtype)
    return (dx, dmean, dscale)


fused_cross_norm_hadamard.defvjp(_cn_fwd, _cn_bwd)


__all__ = [
    "fused_rank_attention", "fused_batch_fc", "fused_cross_norm_hadamard",
    "decode_rank_offset", "normalize_rank_param", "rank_attention_fits",
    "batch_fc_fits", "cross_norm_fits", "_book_dispatch",
]
