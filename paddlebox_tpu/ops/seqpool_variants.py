"""fused_seqpool_cvm variant family.

Reference ops (paddle/fluid/operators/fused/):
- ``fused_seqpool_cvm_with_diff_thres_op.cu`` — per-slot filter thresholds
  (kernel :100-140: threshold_vec_gpu[slot] replaces the scalar).
- ``fused_seqpool_cvm_tradew_op.cu`` — value layout
  [cvm | trade weights | embed]; normal mode skips the trade columns
  (:37-60); trade_id mode scales embeds by the chosen trade weight
  (:66-90); grads per :269-345 (normal: cvm←batch-cvm, trade←0,
  embed←g; trade_id: cvm←0, chosen trade←Σ g·embed_in, embed←g·w).
- ``fused_seqpool_cvm_with_credit_op.cu`` — cvm_offset=4
  [show,click,conv,credit], CVM head = log1p of each cvm column
  (:53-70); show_filter drops the show column (:75-92).
- ``fused_seqpool_cvm_with_pcoc_op.cu`` — input cvm
  [show,clk,show2,clk2,pclk_1..p]; output head (:122-157):
  [log1p(show), log1p(clk)-log1p(show),
   log1p(pclk_i)-log1p(show2) ∀i, log1p(pclk_i)-log1p(clk2) ∀i];
  backward (:261-293): first 4 cvm cols ← batch cvm values, pclk cols ←
  per-instance q_values, embeds broadcast.

TPU-native: same single-segment-sum formulation as ops/seqpool_cvm.py —
all slots of all instances pool in one fused op; the variant math is the
elementwise epilogue/filter XLA fuses into it. custom_vjp replicates each
reference backward contract exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from paddlebox_tpu.ops.seqpool_cvm import _pool_core as _pool


def _broadcast_grad(flat_g, segments, batch_size, num_slots):
    """[B*S, E] per-segment grads → [K, E] per-item grads (pads → 0)."""
    e = flat_g.shape[1]
    flat_g = jnp.concatenate([flat_g, jnp.zeros((1, e), flat_g.dtype)],
                             axis=0)
    seg = jnp.minimum(segments, batch_size * num_slots)
    return flat_g[seg]


def _ins_of(segments, batch_size, num_slots):
    return jnp.minimum(segments // num_slots, batch_size - 1)


def _pad_mask(segments, batch_size, num_slots):
    return segments >= batch_size * num_slots


# ---------------------------------------------------------------------------
# diff_thres
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11))
def fused_seqpool_cvm_with_diff_thres(
    values: jax.Array,           # [K, D]
    segments: jax.Array,         # [K]
    batch_show_clk: jax.Array,   # [B, 2]
    threshold_vec: jax.Array,    # [S] per-slot thresholds
    batch_size: int,
    num_slots: int,
    use_cvm: bool = True,
    cvm_offset: int = 2,
    pad_value: float = 0.0,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    xbox_diff_thres_filter: bool = True,
) -> jax.Array:
    out, _ = _fwd_dt(values, segments, batch_show_clk, threshold_vec,
                     batch_size, num_slots, use_cvm, cvm_offset, pad_value,
                     show_coeff, clk_coeff, xbox_diff_thres_filter)
    return out


def _fwd_dt(values, segments, batch_show_clk, threshold_vec, batch_size,
            num_slots, use_cvm, cvm_offset, pad_value, show_coeff,
            clk_coeff, xbox):
    slot = jnp.minimum(segments % num_slots, num_slots - 1)
    thr = threshold_vec[slot]
    score = ((values[:, 0] - values[:, 1]) * show_coeff
             + values[:, 1] * clk_coeff)
    keep = score >= thr
    pooled = _pool(values, segments, batch_size, num_slots, keep, pad_value)
    if use_cvm:
        show_l = jnp.log1p(pooled[..., 0:1])
        ctr = jnp.log1p(pooled[..., 1:2]) - show_l
        out = jnp.concatenate([show_l, ctr, pooled[..., cvm_offset:]], -1)
    else:
        out = pooled[..., cvm_offset:]
    vtoken = jnp.zeros((0, values.shape[1]), values.dtype)
    return out, (segments, keep, vtoken, batch_show_clk)


def _bwd_dt(batch_size, num_slots, use_cvm, cvm_offset, pad_value,
            show_coeff, clk_coeff, xbox, res, g):
    segments, keep, vtoken, batch_show_clk = res
    d = vtoken.shape[1]
    embedx_g = g[..., cvm_offset:] if use_cvm else g
    g_embedx = _broadcast_grad(
        embedx_g.reshape(batch_size * num_slots, d - cvm_offset),
        segments, batch_size, num_slots)
    g_cvm = batch_show_clk[_ins_of(segments, batch_size, num_slots)]
    live = (keep & ~_pad_mask(segments, batch_size, num_slots))[:, None]
    g_values = jnp.where(
        live, jnp.concatenate([g_cvm.astype(g_embedx.dtype), g_embedx], -1),
        0.0).astype(vtoken.dtype)
    return (g_values, None, None, None)


fused_seqpool_cvm_with_diff_thres.defvjp(_fwd_dt, _bwd_dt)


# ---------------------------------------------------------------------------
# tradew
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def fused_seqpool_cvm_tradew(
    values: jax.Array,          # [K, cvm_offset + trade_num + E]
    segments: jax.Array,
    batch_show_clk: jax.Array,  # [B, cvm_offset]
    batch_size: int,
    num_slots: int,
    trade_num: int,
    trade_id: int = -1,         # ≥0: scale embeds by that trade weight
    use_cvm: bool = True,
    cvm_offset: int = 2,
) -> jax.Array:
    out, _ = _fwd_tw(values, segments, batch_show_clk, batch_size, num_slots,
                     trade_num, trade_id, use_cvm, cvm_offset)
    return out


def _fwd_tw(values, segments, batch_show_clk, batch_size, num_slots,
            trade_num, trade_id, use_cvm, cvm_offset):
    co, tn = cvm_offset, trade_num
    cvm_cols = values[:, :co]
    embed_cols = values[:, co + tn:]
    if trade_id >= 0:
        w = values[:, co + trade_id:co + trade_id + 1]
        embed_cols = embed_cols * w
    v = jnp.concatenate([cvm_cols, embed_cols], axis=1)
    pooled = _pool(v, segments, batch_size, num_slots)
    if use_cvm:
        show_l = jnp.log1p(pooled[..., 0:1])
        ctr = jnp.log1p(pooled[..., 1:2]) - show_l
        out = jnp.concatenate([show_l, ctr, pooled[..., co:]], -1)
    else:
        out = pooled[..., co:]
    vtoken = jnp.zeros((0, values.shape[1]), values.dtype)
    # normal mode's backward never reads the inputs — keep only the token
    # so the [K, D] activations don't live until backward for nothing
    saved = values if trade_id >= 0 else None
    return out, (segments, saved, vtoken, batch_show_clk)


def _bwd_tw(batch_size, num_slots, trade_num, trade_id, use_cvm, cvm_offset,
            res, g):
    segments, values, vtoken, batch_show_clk = res
    co, tn = cvm_offset, trade_num
    e = values.shape[1] - co - tn
    embedx_g = g[..., co:] if use_cvm else g
    g_embed_seg = _broadcast_grad(
        embedx_g.reshape(batch_size * num_slots, e),
        segments, batch_size, num_slots)                   # [K, E]
    live = ~_pad_mask(segments, batch_size, num_slots)
    g_trade = jnp.zeros((values.shape[0], tn), g_embed_seg.dtype)
    if trade_id >= 0:
        # product rule (FusedSeqpoolCVMTradeWGradKernel :295-345):
        # cvm←0, chosen trade col ← Σ_j g_j·embed_in_j, embed ← g·w
        g_cvm = jnp.zeros((values.shape[0], co), g_embed_seg.dtype)
        embed_in = values[:, co + tn:]
        g_trade = g_trade.at[:, trade_id].set(
            jnp.sum(g_embed_seg * embed_in, axis=1))
        w = values[:, co + trade_id:co + trade_id + 1]
        g_embed = g_embed_seg * w
    else:
        g_cvm = batch_show_clk[
            _ins_of(segments, batch_size, num_slots)].astype(
                g_embed_seg.dtype)
        g_embed = g_embed_seg
    g_values = jnp.where(
        live[:, None], jnp.concatenate([g_cvm, g_trade, g_embed], -1),
        0.0).astype(vtoken.dtype)
    return (g_values, None, None)


fused_seqpool_cvm_tradew.defvjp(_fwd_tw, _bwd_tw)


# ---------------------------------------------------------------------------
# credit
# ---------------------------------------------------------------------------

_CREDIT_OFFSET = 4  # show, click, conv, credit


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def fused_seqpool_cvm_with_credit(
    values: jax.Array,       # [K, 4 + E]
    segments: jax.Array,
    batch_cvm: jax.Array,    # [B, 4]
    batch_size: int,
    num_slots: int,
    use_cvm: bool = True,
    show_filter: bool = False,
) -> jax.Array:
    out, _ = _fwd_cr(values, segments, batch_cvm, batch_size, num_slots,
                     use_cvm, show_filter)
    return out


def _fwd_cr(values, segments, batch_cvm, batch_size, num_slots, use_cvm,
            show_filter):
    co = _CREDIT_OFFSET
    pooled = _pool(values, segments, batch_size, num_slots)
    if use_cvm:
        head = jnp.log1p(pooled[..., :co])
        if show_filter:
            head = head[..., 1:]
        out = jnp.concatenate([head, pooled[..., co:]], -1)
    else:
        out = pooled[..., co:]
    vtoken = jnp.zeros((0, values.shape[1]), values.dtype)
    return out, (segments, vtoken, batch_cvm)


def _bwd_cr(batch_size, num_slots, use_cvm, show_filter, res, g):
    segments, vtoken, batch_cvm = res
    co = _CREDIT_OFFSET
    d = vtoken.shape[1]
    n_head = (co - 1 if show_filter else co) if use_cvm else 0
    embedx_g = g[..., n_head:]
    g_embedx = _broadcast_grad(
        embedx_g.reshape(batch_size * num_slots, d - co),
        segments, batch_size, num_slots)
    g_cvm = batch_cvm[_ins_of(segments, batch_size, num_slots)]
    live = ~_pad_mask(segments, batch_size, num_slots)
    g_values = jnp.where(
        live[:, None],
        jnp.concatenate([g_cvm.astype(g_embedx.dtype), g_embedx], -1),
        0.0).astype(vtoken.dtype)
    return (g_values, None, None)


fused_seqpool_cvm_with_credit.defvjp(_fwd_cr, _bwd_cr)


# ---------------------------------------------------------------------------
# pcoc
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_seqpool_cvm_with_pcoc(
    values: jax.Array,       # [K, 4 + pclk_num + E]
    segments: jax.Array,
    batch_cvm: jax.Array,    # [B, 4 + pclk_num] (show,clk,show2,clk2,pclk…)
    q_values: jax.Array,     # [B, pclk_num]
    batch_size: int,
    num_slots: int,
    use_cvm: bool = True,
) -> jax.Array:
    """Output head (use_cvm): [log1p(show), log1p(clk)-log1p(show),
    {log1p(pclk_i)-log1p(show2)}, {log1p(pclk_i)-log1p(clk2)}] + embeds."""
    out, _ = _fwd_pc(values, segments, batch_cvm, q_values, batch_size,
                     num_slots, use_cvm)
    return out


def _fwd_pc(values, segments, batch_cvm, q_values, batch_size, num_slots,
            use_cvm):
    p = batch_cvm.shape[1] - 4
    used = 4 + p
    pooled = _pool(values, segments, batch_size, num_slots)
    if use_cvm:
        lg = jnp.log1p(pooled[..., :used])
        show_l, clk_l = lg[..., 0:1], lg[..., 1:2]
        show2_l, clk2_l = lg[..., 2:3], lg[..., 3:4]
        pclk_l = lg[..., 4:used]
        out = jnp.concatenate(
            [show_l, clk_l - show_l, pclk_l - show2_l, pclk_l - clk2_l,
             pooled[..., used:]], -1)
    else:
        out = pooled[..., used:]
    vtoken = jnp.zeros((0, values.shape[1]), values.dtype)
    return out, (segments, vtoken, batch_cvm, q_values)


def _bwd_pc(batch_size, num_slots, use_cvm, res, g):
    segments, vtoken, batch_cvm, q_values = res
    p = batch_cvm.shape[1] - 4
    used = 4 + p
    d = vtoken.shape[1]
    n_head = (2 + 2 * p) if use_cvm else 0
    embedx_g = g[..., n_head:]
    g_embedx = _broadcast_grad(
        embedx_g.reshape(batch_size * num_slots, d - used),
        segments, batch_size, num_slots)
    ins = _ins_of(segments, batch_size, num_slots)
    # first 4 cvm cols carry batch cvm; pclk cols carry q_values (:261-293)
    g_cvm = jnp.concatenate([batch_cvm[:, :4], q_values], axis=1)[ins]
    live = ~_pad_mask(segments, batch_size, num_slots)
    g_values = jnp.where(
        live[:, None],
        jnp.concatenate([g_cvm.astype(g_embedx.dtype), g_embedx], -1),
        0.0).astype(vtoken.dtype)
    return (g_values, None, None, None)


fused_seqpool_cvm_with_pcoc.defvjp(_fwd_pc, _bwd_pc)
