"""Static-shape on-device key dedup (DedupKeysAndFillIdx on the chip).

Reference: the host/CUDA dedup pipeline ``DedupKeysAndFillIdx``
(box_wrapper_impl.h:129) runs per batch before the PS pull. In the
device-resident pass mode (train/device_pass.py) the batch's per-key ROWS
are already in HBM, so dedup happens inside the jit step instead — no host
round-trip.

TPU-shaped formulation: XLA wants static shapes and TPU scatters serialize
per update, so both ``jnp.unique`` and a capacity-sized presence bitmap are
out (the bitmap costs ~100 ms at 8M rows — measured). Instead: sort the K
row ids, mark run starts, prefix-sum the marks into dense unique ids, and
compact by re-sorting the masked values — sorts, cumsum over K, gathers and
a vectorized binary search only, all MXU/VPU-friendly and O(K log K) in the
BATCH size, independent of table capacity. Unique order is ascending row id.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def dedup_rows(rows: jax.Array, capacity: int) -> Tuple[jax.Array, jax.Array]:
    """Dedup per-key row ids into a compacted unique list.

    Args:
      rows: int32 [K]; invalid/padding keys must carry the sentinel row
        ``capacity`` (the zero row) — it then appears as one regular
        unique entry, exactly like the host path's miss collapse.
      capacity: table row capacity (sentinel row id).

    Returns:
      (unique_rows, gather_idx): int32 [K] unique row list, and int32 [K]
      mapping each key to its unique position — the (unique_rows,
      gather_idx) contract of ``PullIndex``. Padding positions (≥ U) hold
      DISTINCT out-of-bounds values > capacity, never pointed at by
      gather_idx, so that (a) gathers through them clamp to the zero
      sentinel row and (b) table scatters can promise ``unique_indices``
      (OOB updates drop) — the difference between a vectorized and a
      serialized TPU scatter.
    """
    k = rows.shape[0]
    # ONE sort carrying original positions — replaces the earlier
    # sort + 18-deep searchsorted + second sort formulation (the
    # binary-search loop alone measured ~27 ms at K=213k on v5p; the
    # two K-scalar scatters below are ~4 ms each)
    pos = jnp.arange(k, dtype=jnp.int32)
    sr, perm = jax.lax.sort((rows, pos), num_keys=1)
    is_first = jnp.concatenate(
        [jnp.ones(1, bool), sr[1:] != sr[:-1]])
    uid_sorted = jnp.cumsum(is_first.astype(jnp.int32)) - 1
    # each key's unique id rides back through the sort permutation
    gather_idx = jnp.zeros(k, jnp.int32).at[perm].set(
        uid_sorted, unique_indices=True)
    # compaction: duplicates of a run write the SAME value to the same
    # uid slot (commutes); pads prefill with distinct OOB ids
    oob = capacity + 1 + pos
    unique_rows = oob.at[uid_sorted].set(sr)
    return unique_rows, gather_idx
