"""Static-shape on-device key dedup (DedupKeysAndFillIdx on the chip).

Reference: the host/CUDA dedup pipeline ``DedupKeysAndFillIdx``
(box_wrapper_impl.h:129) runs per batch before the PS pull. In the
device-resident pass mode (train/device_pass.py) the batch's per-key ROWS
are already in HBM, so dedup happens inside the jit step instead — no host
round-trip.

TPU-shaped formulation: XLA wants static shapes and TPU scatters serialize
per update, so both ``jnp.unique`` and a capacity-sized presence bitmap are
out (the bitmap costs ~100 ms at 8M rows — measured). Instead: sort the K
row ids, mark run starts, prefix-sum the marks into dense unique ids, and
compact by re-sorting the masked values — sorts, cumsum over K, gathers and
a vectorized binary search only, all MXU/VPU-friendly and O(K log K) in the
BATCH size, independent of table capacity. Unique order is ascending row id.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def dedup_rows(rows: jax.Array, capacity: int) -> Tuple[jax.Array, jax.Array]:
    """Dedup per-key row ids into a compacted unique list.

    Args:
      rows: int32 [K]; invalid/padding keys must carry the sentinel row
        ``capacity`` (the zero row) — it then appears as one regular
        unique entry, exactly like the host path's miss collapse.
      capacity: table row capacity (sentinel row id).

    Returns:
      (unique_rows, gather_idx): int32 [K] unique row list, and int32 [K]
      mapping each key to its unique position — the (unique_rows,
      gather_idx) contract of ``PullIndex``. Padding positions (≥ U) hold
      DISTINCT out-of-bounds values > capacity, never pointed at by
      gather_idx, so that (a) gathers through them clamp to the zero
      sentinel row and (b) table scatters can promise ``unique_indices``
      (OOB updates drop) — the difference between a vectorized and a
      serialized TPU scatter.
    """
    k = rows.shape[0]
    # ONE sort carrying original positions — replaces the earlier
    # sort + 18-deep searchsorted + second sort formulation (the
    # binary-search loop alone measured ~27 ms at K=213k on v5p; the
    # two K-scalar scatters below are ~4 ms each)
    pos = jnp.arange(k, dtype=jnp.int32)
    sr, perm = jax.lax.sort((rows, pos), num_keys=1)
    is_first = jnp.concatenate(
        [jnp.ones(1, bool), sr[1:] != sr[:-1]])
    uid_sorted = jnp.cumsum(is_first.astype(jnp.int32)) - 1
    # each key's unique id rides back through the sort permutation
    gather_idx = jnp.zeros(k, jnp.int32).at[perm].set(
        uid_sorted, unique_indices=True)
    # compaction: duplicates of a run write the SAME value to the same
    # uid slot (commutes); pads prefill with distinct OOB ids
    oob = capacity + 1 + pos
    unique_rows = oob.at[uid_sorted].set(sr)
    return unique_rows, gather_idx


def dedup_keys_first_seen(
        key_hi: jax.Array, key_lo: jax.Array, num_valid: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """First-seen dedup of 64-bit FEATURE IDS (not row ids) on device —
    the bitwise generalization of ``ps/table.dedup_first_seen``
    (ISSUE 19 stage a): raw ids ride as (hi, lo) int32 halves so the
    whole pipeline stays x64-free.

    Args:
      key_hi, key_lo: int32 [K_pad] — the key's upper/lower 32 bits
        (any bit pattern; keys are compared for EQUALITY only, so
        signedness never matters). Positions ≥ num_valid are padding
        and may hold anything.
      num_valid: int32 scalar — number of real keys.

    Returns ``(uniq_hi, uniq_lo, first_pos, inv, num_unique)``, all
    padded to K_pad:
      - uniq_hi/uniq_lo [K_pad]: the distinct keys in FIRST-SEEN order
        (positions ≥ num_unique hold pad-key garbage — callers slice
        by num_unique).
      - first_pos [K_pad] int32: each unique's first occurrence
        position in the input stream (ascending by construction; pads
        hold K_pad).
      - inv [K_pad] int32: per input position, the unique's first-seen
        rank (``uniq[inv[i]] == key[i]``); pad positions point past
        num_unique.
      - num_unique: int32 scalar count of real uniques.

    Matches the host oracle bit for bit: ``uniq`` equals
    ``dedup_first_seen(keys)[0]``, ``first_pos[:U]`` its first-index
    array and ``inv[:nv]`` its inverse — gated in tier-1
    (tests/test_pallas_index.py)."""
    k = key_hi.shape[0]
    pos = jnp.arange(k, dtype=jnp.int32)
    valid = pos < num_valid
    # validity is the LEADING sort key: pads group after every real key
    # and never merge into a real run even when their stale bits match
    # a real id; (hi, lo) only need to group equal keys, so the signed
    # int32 sort order is fine
    vkey = (~valid).astype(jnp.int32)
    _, sh, sl, perm = jax.lax.sort(
        (vkey, key_hi.astype(jnp.int32), key_lo.astype(jnp.int32), pos),
        num_keys=3)
    sv = perm < num_valid
    is_first = jnp.concatenate(
        [jnp.ones(1, bool),
         (sh[1:] != sh[:-1]) | (sl[1:] != sl[:-1])
         | (sv[1:] != sv[:-1])])
    uid_sorted = jnp.cumsum(is_first.astype(jnp.int32)) - 1
    # each run's first stream position: the sort is stable on pos (it
    # rides as the last key), so a segment-min over the run recovers it
    first_pos = jnp.full(k, k, jnp.int32).at[uid_sorted].min(perm)
    # first-seen rank = order of runs by first position; the pad run
    # (first pad position == num_valid) sorts after every real run and
    # unused slots (first_pos == K_pad) sort last
    order = jnp.argsort(first_pos)
    rank = jnp.zeros(k, jnp.int32).at[order].set(pos)
    inv = jnp.zeros(k, jnp.int32).at[perm].set(rank[uid_sorted],
                                               unique_indices=True)
    fp = first_pos[order]
    gather_at = jnp.minimum(fp, k - 1)
    return (key_hi[gather_at], key_lo[gather_at],
            jnp.where(fp < num_valid, fp, k).astype(jnp.int32), inv,
            jnp.sum((is_first & sv).astype(jnp.int32)))
