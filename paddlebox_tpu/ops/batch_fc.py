"""batch_fc — per-slot batched fully-connected.

Reference: paddle/fluid/operators/batch_fc_op.{cc,cu,h} (567-line CUDA
batched GEMM). Default mode: Input [slot_pairs, ins, in_dim] × W
[slot_pairs, in_dim, out_dim] + Bias [slot_pairs, out_dim]; batchcount mode
flattens a [bc*ins, in] input against [bc, in, out] weights
(transpose_weight option). One einsum on the MXU replaces the hand-rolled
stream-batched GEMMs.

THE dispatch seam (ISSUE 13): under ``FLAGS.use_pallas_batch_fc`` (and
the static VMEM residency check) the op runs as
``ops.pallas_ctr.fused_batch_fc`` — one slot's weight block
VMEM-resident per grid column, TN-row input blocks streamed through,
the bias add fused before the output block leaves VMEM, and the
transpose_weight mode riding dot_general dimension numbers instead of
a materialized weight transpose. Both decisions book
``pbox_kernel_dispatch_total{kernel="batch_fc"}``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.ops.pallas_ctr import (_book_dispatch, batch_fc_fits,
                                          fused_batch_fc)


def batch_fc(x: jax.Array, w: jax.Array, bias: jax.Array,
             batchcount: int = 0, transpose_weight: bool = False) -> jax.Array:
    if transpose_weight and batchcount <= 0:
        # reference attr surface: transpose_weight exists only for the
        # batchcount layout — fail loudly instead of contracting an
        # [S, O, I] weight on the wrong axis
        raise ValueError(
            "batch_fc: transpose_weight requires batchcount > 0")
    i_dim = x.shape[-1]
    o_dim = w.shape[1] if transpose_weight else w.shape[2]
    if FLAGS.use_pallas_batch_fc and batch_fc_fits(i_dim, o_dim):
        _book_dispatch("batch_fc", "pallas")
        return fused_batch_fc(x, w, bias, batchcount, transpose_weight)
    _book_dispatch("batch_fc", "xla")
    if batchcount > 0:
        xb = x.reshape(batchcount, x.shape[0] // batchcount, x.shape[-1])
        wb = jnp.swapaxes(w, 1, 2) if transpose_weight else w
        out = jnp.einsum("bni,bio->bno", xb, wb) + bias[:, None, :]
        return out.reshape(x.shape[0], -1)
    return jnp.einsum("sni,sio->sno", x, w) + bias[:, None, :]
