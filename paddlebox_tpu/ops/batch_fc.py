"""batch_fc — per-slot batched fully-connected.

Reference: paddle/fluid/operators/batch_fc_op.{cc,cu,h} (567-line CUDA
batched GEMM). Default mode: Input [slot_pairs, ins, in_dim] × W
[slot_pairs, in_dim, out_dim] + Bias [slot_pairs, out_dim]; batchcount mode
flattens a [bc*ins, in] input against [bc, in, out] weights
(transpose_weight option). One einsum on the MXU replaces the hand-rolled
stream-batched GEMMs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def batch_fc(x: jax.Array, w: jax.Array, bias: jax.Array,
             batchcount: int = 0, transpose_weight: bool = False) -> jax.Array:
    if batchcount > 0:
        ins = x.shape[0] // batchcount
        xb = x.reshape(batchcount, ins, x.shape[-1])
        wb = jnp.swapaxes(w, 1, 2) if transpose_weight else w
        out = jnp.einsum("bni,bio->bno", xb, wb) + bias[:, None, :]
        return out.reshape(batchcount * ins, -1)
    return jnp.einsum("sni,sio->sno", x, w) + bias[:, None, :]
