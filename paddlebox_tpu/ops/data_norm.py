"""data_norm — batch-statistics normalization with running summaries.

Reference: paddle/fluid/operators/data_norm_op.{cc,cu}: per-column summaries
{batch_size, batch_sum, batch_square_sum}; forward uses
``mean = batch_sum / batch_size`` and ``scale = sqrt(batch_size /
batch_square_sum)`` (data_norm_op.cc means_arr/scales_arr), y = (x-mean)*
scale. The summary is itself trained: the backward emits per-column summary
"gradients" (counts/sums of the batch) that the dense table applies with a
decay (BoxPSAsynDenseTable DataNorm handling, boxps_worker.cc:93-98).

Functional port: ``data_norm`` is the pure forward; ``data_norm_update``
folds a batch into the summary with the reference decay semantics
(summary = summary*decay + batch_stats), returned as a new summary pytree.
``slot_dim``: skip normalization for all-zero (no-show) slot blocks.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class DataNormSummary(NamedTuple):
    batch_size: jax.Array        # f32 [C]
    batch_sum: jax.Array         # f32 [C]
    batch_square_sum: jax.Array  # f32 [C]


def init_data_norm_summary(c: int, init_size: float = 1e4) -> DataNormSummary:
    # reference initializes size=1e4, sum=0, square_sum=1e4 (unit scale)
    return DataNormSummary(
        batch_size=jnp.full((c,), init_size, jnp.float32),
        batch_sum=jnp.zeros((c,), jnp.float32),
        batch_square_sum=jnp.full((c,), init_size, jnp.float32),
    )


def data_norm_mean_scale(summary: DataNormSummary,
                         epsilon: float = 1e-7
                         ) -> Tuple[jax.Array, jax.Array]:
    """The ONE (mean, scale) derivation (means_arr/scales_arr,
    data_norm_op.cc) — shared by :func:`data_norm` and the fused
    cross_norm forward's in-kernel apply (ops/cross_norm), so the
    flag-on and flag-off normalization formulas cannot drift."""
    mean = summary.batch_sum / summary.batch_size
    scale = jnp.sqrt(summary.batch_size /
                     jnp.maximum(summary.batch_square_sum, epsilon))
    return mean, scale


def data_norm(x: jax.Array, summary: DataNormSummary,
              slot_dim: int = -1, epsilon: float = 1e-7) -> jax.Array:
    mean, scale = data_norm_mean_scale(summary, epsilon)
    y = (x - mean[None, :]) * scale[None, :]
    if slot_dim > 0:
        # skip normalization for slot blocks whose first column (show) is 0
        b, c = x.shape
        blocks = x.reshape(b, c // slot_dim, slot_dim)
        has_show = (blocks[..., 0:1] > epsilon)
        y = jnp.where(
            jnp.broadcast_to(has_show, blocks.shape).reshape(b, c),
            y, x)
    return y


def data_norm_fold_stats(summary: DataNormSummary, count, s: jax.Array,
                         q: jax.Array, decay: float = 0.9999999,
                         squared_sum_epsilon: float = 1e-4
                         ) -> DataNormSummary:
    """The ONE decayed summary fold over precomputed batch stats
    (count, Σx, Σx²) — shared by the plain per-batch update and the
    sync_stats psum path (ops/cross_norm.cross_norm_update), so the
    fold/epsilon semantics cannot drift between them. The epsilon is
    added once per UPDATE, not once per shard."""
    return DataNormSummary(
        batch_size=summary.batch_size * decay + count,
        batch_sum=summary.batch_sum * decay + s,
        batch_square_sum=summary.batch_square_sum * decay + q
        + squared_sum_epsilon,
    )


def data_norm_update(summary: DataNormSummary, x: jax.Array,
                     decay: float = 0.9999999,
                     squared_sum_epsilon: float = 1e-4) -> DataNormSummary:
    return data_norm_fold_stats(
        summary, x.shape[0], jnp.sum(x, axis=0),
        jnp.sum(jnp.square(x), axis=0), decay=decay,
        squared_sum_epsilon=squared_sum_epsilon)
