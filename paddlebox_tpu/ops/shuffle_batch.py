"""shuffle_batch — in-batch row shuffle for negative sampling.

Reference: paddle/fluid/operators/shuffle_batch_op.{cc,h}: forward permutes
rows (recording ShuffleIdx), backward routes grads through the inverse
permutation. Functional port: permutation from a jax PRNG key; the inverse
scatter comes from autodiff through ``take`` for free.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def shuffle_batch(x: jax.Array, rng: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Returns (shuffled_x, shuffle_idx). Gradient w.r.t. x is unshuffled
    automatically (gather autodiff)."""
    idx = jax.random.permutation(rng, x.shape[0])
    return jnp.take(x, idx, axis=0), idx


def unshuffle_batch(y: jax.Array, shuffle_idx: jax.Array) -> jax.Array:
    """Restore original order (ShuffleIdx consumer)."""
    inv = jnp.argsort(shuffle_idx)
    return jnp.take(y, inv, axis=0)
