"""Resilience layer: retry/backoff policies, deterministic fault
injection, and pass-level recovery primitives.

The reference system survives day-scale production runs because AIBox
tolerates flaky AFS/HDFS IO and node hiccups around the BoxPS core
(SURVEY.md §5; the hadoop CLI is retried at the shell layer and a bad
pass is re-fed). This package gives the TPU-native stack the same
property, provably:

- :mod:`paddlebox_tpu.resilience.retry` — ``RetryPolicy``: exponential
  backoff with seeded jitter, attempt/deadline caps, and a
  retryable-exception classification, applied at the IO seams
  (CommandBackend, checkpoint file IO, dataset file opens).
- :mod:`paddlebox_tpu.resilience.faults` — ``FaultPlan``: a
  deterministic, seed-driven fault-injection harness installable at the
  FileMgr/parser/checkpoint seams so recovery paths are exercised by
  tests (tests/test_resilience.py, scripts/chaos_check.py) instead of
  hoped-for.
- :mod:`paddlebox_tpu.resilience.preemption` — graceful shutdown:
  SIGTERM/SIGINT → stop flag → emergency checkpoint + mid-pass resume
  cursor + resume marker (``PreemptedError``, ``EXIT_RESUME``).
- :mod:`paddlebox_tpu.resilience.consensus` — shared-dir consensus for
  multihost-consistent recovery: every process restores the same agreed
  step and drops the same quarantined files (SPMD batch identity).

Everything emits through the obs/ TelemetryHub (``pbox_retry_*``,
``pbox_files_quarantined_total``, ``pbox_faults_injected_total``,
``pbox_pass_retries_total`` — docs/RESILIENCE.md has the catalog).
"""

from paddlebox_tpu.resilience.retry import (RetryExhausted, RetryPolicy,
                                            TransientError, is_retryable)
from paddlebox_tpu.resilience.faults import (FaultPlan, FaultSpec,
                                             InjectedCrash, InjectedFault,
                                             TransientInjectedError,
                                             active_plan, clear_plan,
                                             inject, install_plan,
                                             installed)
from paddlebox_tpu.resilience.preemption import (EXIT_RESUME,
                                                 PreemptedError,
                                                 clear_stop,
                                                 install_signal_handlers,
                                                 request_stop,
                                                 stop_requested)
from paddlebox_tpu.resilience.consensus import (ConsensusTimeout,
                                                DirConsensusStore,
                                                RestoreConsensus,
                                                consensus_restore,
                                                sync_shared_quarantine)

__all__ = [
    "RetryPolicy", "RetryExhausted", "TransientError", "is_retryable",
    "FaultPlan", "FaultSpec", "InjectedFault", "InjectedCrash",
    "TransientInjectedError", "inject", "install_plan", "clear_plan",
    "active_plan", "installed",
    "PreemptedError", "EXIT_RESUME", "request_stop", "stop_requested",
    "clear_stop", "install_signal_handlers",
    "RestoreConsensus", "DirConsensusStore", "ConsensusTimeout",
    "consensus_restore", "sync_shared_quarantine",
]
