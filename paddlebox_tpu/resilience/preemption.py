"""Graceful preemption handling — stop flags, signal handlers, resume
markers (docs/RESILIENCE.md §Preemption & mid-pass resume).

On TPU pods the dominant failure mode is not a flaky syscall but
*preemption*: the scheduler reclaims the slice mid-pass with a SIGTERM
and a short grace window. This module turns that signal into a clean
shutdown protocol:

1. :func:`install_signal_handlers` converts SIGTERM/SIGINT into a
   process-wide **stop flag** (``request_stop`` — also callable
   programmatically, the seam tests and chaos runs use).
2. The training loop polls :func:`stop_requested` at every batch
   boundary (``Trainer.train_pass``), finishes the in-flight step,
   writes an *emergency checkpoint* with a mid-pass resume cursor
   (train/checkpoint.py ``cursor.json``), and raises
   :class:`PreemptedError` — which ``Trainer.run_pass`` never retries
   (a deliberate shutdown is not a failure).
3. A **resume marker** (``RESUME.json`` next to the checkpoints) plus
   the distinct :data:`EXIT_RESUME` exit code (75, ``EX_TEMPFAIL``)
   tell the launcher "restart me and resume", distinguishing
   preemption from a real crash.

Chaos seam: a ``fail`` fault at ``preempt.signal`` models SIGTERM
delivery — :func:`stop_requested` converts it into ``request_stop``
instead of letting it propagate, so a seeded plan preempts the loop at
an exact batch boundary deterministically
(``preempt.signal:fail:nth=K`` + scripts/preempt_check.py).
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Dict, Optional

from paddlebox_tpu.resilience import faults
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: distinct exit code for "preempted, restart and resume" (EX_TEMPFAIL —
#: launchers treat it as retriable, unlike a crash's nonzero codes)
EXIT_RESUME = 75

#: marker file written next to the checkpoints on graceful shutdown
RESUME_MARKER = "RESUME.json"


class PreemptedError(RuntimeError):
    """Raised at a batch boundary after a stop request. NOT a failure:
    ``Trainer.run_pass`` re-raises it untouched (never retried), and the
    launcher exits :data:`EXIT_RESUME`. Carries the resume position."""

    def __init__(self, msg: str, step: Optional[int] = None,
                 batch_index: Optional[int] = None,
                 checkpoint_path: Optional[str] = None) -> None:
        super().__init__(msg)
        self.step = step
        self.batch_index = batch_index
        self.checkpoint_path = checkpoint_path

    @property
    def checkpointed(self) -> bool:
        return self.checkpoint_path is not None


_STOP = threading.Event()
_LOCK = threading.Lock()
_REASON: Optional[str] = None
_INSTALLED: Dict[int, object] = {}  # signum -> previous handler
#: set by the SIGNAL HANDLER only — a plain (GIL-atomic) assignment.
#: The handler runs on the main thread between bytecodes and may
#: interrupt code holding _LOCK, the telemetry hub's lock, or the
#: logging lock; touching ANY of those from the handler could deadlock
#: the process during its grace window. The next stop poll drains this
#: into a full request_stop() from normal thread context.
_SIG_PENDING: Optional[str] = None


def request_stop(reason: str = "request_stop") -> None:
    """Arm the stop flag (idempotent — the first reason wins). The
    programmatic seam for tests, fault injection, and launchers that
    learn about preemption out-of-band (e.g. a metadata-server notice
    ahead of the SIGTERM)."""
    global _REASON
    with _LOCK:
        first = not _STOP.is_set()
        if first:
            _REASON = reason
        _STOP.set()
    if not first:
        return
    log.warning("stop requested (%s): training will halt at the next "
                "batch boundary with an emergency checkpoint", reason)
    try:
        from paddlebox_tpu.obs.hub import get_hub
        hub = get_hub()
        hub.counter("pbox_preempt_requests_total",
                    "graceful-shutdown requests received").inc()
        if hub.active:
            hub.emit("preempt_requested", reason=reason)
    except Exception:
        log.debug("preempt telemetry emit failed", exc_info=True)


def _drain_signal() -> None:
    """Promote a handler-recorded signal into a full stop request —
    from NORMAL thread context, where locks/logging/telemetry are
    safe."""
    global _SIG_PENDING
    reason = _SIG_PENDING
    if reason is not None:
        _SIG_PENDING = None
        request_stop(reason)


def stop_requested() -> bool:
    """The batch-boundary poll. Also hosts the ``preempt.signal`` chaos
    seam: an injected ``fail`` fault here IS a simulated SIGTERM — it
    becomes a stop request, never an exception (every ``exc=`` variant,
    including the plain-``OSError`` one, which is not an InjectedFault
    subclass)."""
    _drain_signal()
    try:
        faults.inject("preempt.signal")
    except (faults.InjectedFault, OSError) as e:
        request_stop(f"injected:{e}")
    return _STOP.is_set()


def stop_pending() -> bool:
    """Flag state WITHOUT the chaos seam — for polls that are not batch
    boundaries (e.g. ``run_pass``'s between-pass check), so a seeded
    ``preempt.signal:fail:nth=K`` still means "the K-th BATCH
    boundary"."""
    _drain_signal()
    return _STOP.is_set()


def stop_reason() -> Optional[str]:
    return _REASON


def clear_stop() -> None:
    """Reset the flag (a restarted in-process run; tests)."""
    global _REASON, _SIG_PENDING
    with _LOCK:
        _STOP.clear()
        _REASON = None
        _SIG_PENDING = None


def _handler(signum, frame) -> None:
    """LOCK-FREE by design: runs on the main thread between bytecodes
    and may interrupt code holding any lock (telemetry hub, logging,
    this module's own) — so it only records the signal with plain
    assignments; the next stop poll does the real work."""
    global _SIG_PENDING
    if (_STOP.is_set() or _SIG_PENDING is not None) \
            and signum == signal.SIGINT:
        # a second ctrl-C means "now" — restore default behavior
        raise KeyboardInterrupt
    _SIG_PENDING = f"signal:{signal.Signals(signum).name}"


def install_signal_handlers(signums=(signal.SIGTERM,
                                     signal.SIGINT)) -> bool:
    """Route SIGTERM/SIGINT into :func:`request_stop`. Idempotent; must
    run on the main thread (returns False elsewhere — e.g. a trainer
    constructed inside a worker thread — rather than raising). Enabled
    by ``FLAGS.graceful_shutdown`` at Trainer init."""
    try:
        for s in signums:
            if s in _INSTALLED:
                continue
            _INSTALLED[s] = signal.signal(s, _handler)
        return True
    except ValueError:
        log.warning("signal handlers need the main thread — graceful "
                    "shutdown will rely on request_stop() only")
        return False


def uninstall_signal_handlers() -> None:
    for s, prev in list(_INSTALLED.items()):
        try:
            signal.signal(s, prev)
        except (ValueError, TypeError):
            pass
        del _INSTALLED[s]


# ---- resume marker -----------------------------------------------------
def write_resume_marker(root: str, **info) -> str:
    """Atomically publish ``RESUME.json`` under ``root`` (the checkpoint
    root) so the launcher knows this exit expects a resume. ``info``
    typically carries step / batch_index / reason."""
    from paddlebox_tpu.utils.fsio import atomic_write_json
    os.makedirs(root, exist_ok=True)
    return atomic_write_json(os.path.join(root, RESUME_MARKER),
                             dict(info, exit_code=EXIT_RESUME))


def read_resume_marker(root: str) -> Optional[dict]:
    from paddlebox_tpu.utils.fsio import read_json
    return read_json(os.path.join(root, RESUME_MARKER))


def clear_resume_marker(root: str) -> bool:
    """Consume the marker (the resumed run calls this once it has
    adopted the cursor). Returns True if a marker was removed."""
    try:
        os.unlink(os.path.join(root, RESUME_MARKER))
        return True
    except OSError:
        return False
