"""Multihost-consistent recovery: shared-dir consensus on the restore
step and on the quarantine set (docs/RESILIENCE.md §Multihost-consistent
restore).

The SPMD host contract (train/multihost.py) demands byte-identical
batches on every process. Two recovery paths used to be able to break
it silently:

- **restore**: after a crash, each process restores its own "latest"
  checkpoint — but an interrupted save can leave the newest step on
  only SOME hosts, so ranks would train from different steps;
- **quarantine**: PR 2's per-file quarantine is a *process-local*
  decision — a file that only one host fails to read would be dropped
  on that host alone, skewing every batch after it.

Both are fixed by the same primitive: every process publishes its local
view into a shared directory (the ``DirHeartbeatStore`` NFS/FUSE
pattern — atomic write-then-rename JSON files, torn reads tolerated),
waits for the full mesh, and applies a deterministic pure function of
the gathered set (``min`` for steps, sorted union for quarantines) so
every process reaches the same answer from the same data.

Chaos seam: ``restore.consensus`` fires on every publish, so a seeded
plan can kill a specific rank's publish deterministically and tests can
assert the timeout/abort behavior.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence

from paddlebox_tpu.resilience import faults
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class ConsensusTimeout(RuntimeError):
    """The mesh did not fully publish within the timeout — some process
    is dead or unreachable; the launcher must resolve membership before
    recovery can proceed."""


class DirConsensusStore:
    """One ``<topic>_<process>.json`` per process per topic in a shared
    directory (NFS/FUSE on real pods). Same conventions as
    ``obs.watchdog.DirHeartbeatStore``: atomic write-then-rename so
    readers never see a torn file; unreadable/foreign files are skipped
    (the next poll sees the completed rename)."""

    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(path, exist_ok=True)

    def publish(self, topic: str, process: int, payload: dict) -> None:
        from paddlebox_tpu.utils.fsio import atomic_write_json
        atomic_write_json(
            os.path.join(self.path, f"{topic}_{process}.json"),
            dict(payload, process=process))

    def read(self, topic: str) -> Dict[int, dict]:
        from paddlebox_tpu.utils.fsio import read_json
        out: Dict[int, dict] = {}
        prefix = f"{topic}_"
        try:
            names = os.listdir(self.path)
        except OSError:
            return out
        for n in names:
            if not (n.startswith(prefix) and n.endswith(".json")):
                continue
            try:
                int(n[len(prefix):-len(".json")])
            except ValueError:
                continue  # a different topic sharing the prefix
            d = read_json(os.path.join(self.path, n))
            try:
                out[int(d["process"])] = d
            except (TypeError, ValueError, KeyError):
                continue  # torn/foreign file
        return out

    def clear_process(self, process: int) -> None:
        """Drop every file THIS process published (any topic). Only
        rank ``process`` ever writes ``*_<process>.json``, so this is
        race-free — the restart hygiene each ``RestoreConsensus``
        instance applies for its own rank."""
        suffix = f"_{process}.json"
        try:
            names = os.listdir(self.path)
        except OSError:
            return
        for n in names:
            if n.endswith(suffix):
                try:
                    os.unlink(os.path.join(self.path, n))
                except OSError:
                    pass


class RestoreConsensus:
    """Publish-then-agree over a shared dir for one recovery episode.

    ``epoch`` namespaces the topic files so a directory reused across
    restarts (or across retry attempts) never lets a previous episode's
    answers satisfy this one — pass a value that changes per episode
    (the launcher's restart counter; tests use the default 0).

    LOCKSTEP CONTRACT: every process must issue the same sequence of
    agreement calls on its own instance. Each instance additionally
    counts its gathers per topic and bakes the count into the topic
    name, so repeated agreements (a quarantine sync per pass, a second
    restore after another failure) never read a previous call's stale
    files — matching calls across ranks land on matching topics.
    """

    def __init__(self, store, process_index: int, num_processes: int,
                 timeout: Optional[float] = None,
                 poll_interval: float = 0.05, epoch: int = 0,
                 clock=time.monotonic, sleep=time.sleep,
                 participants: Optional[Sequence[int]] = None) -> None:
        if isinstance(store, str):
            store = DirConsensusStore(store)
        self.store = store
        self.process_index = int(process_index)
        self.num_processes = int(num_processes)
        # elastic worlds: the set of ranks expected to publish. Default
        # = the full mesh; after a scale-down the survivors call
        # set_participants() so agreements stop waiting on the dead.
        self._participants: List[int] = sorted(
            int(p) for p in (participants
                             if participants is not None
                             else range(int(num_processes))))
        if timeout is None:
            from paddlebox_tpu.config import FLAGS
            timeout = FLAGS.consensus_timeout_sec
        self.timeout = float(timeout)
        self.poll_interval = poll_interval
        self.epoch = int(epoch)
        self.clock = clock
        self.sleep = sleep
        self._gathers: Dict[str, int] = {}  # per-topic call counter
        # restart hygiene: drop THIS rank's files from any previous
        # episode reusing the directory (race-free: only we write them).
        # Other ranks' stale files are defeated by the confirm barrier
        # in _gather.
        if hasattr(self.store, "clear_process"):
            self.store.clear_process(self.process_index)

    # ---- elastic membership --------------------------------------------
    @property
    def participants(self) -> List[int]:
        return list(self._participants)

    def set_participants(self, ranks: Sequence[int]) -> None:
        """Restrict agreements to ``ranks`` (the surviving world after a
        scale event). Every surviving rank must apply the SAME set
        before its next agreement call — the set is part of the lockstep
        contract. Publishes from non-participants are ignored, so a dead
        rank's stale (or late) files can neither satisfy nor skew a
        survivor agreement."""
        ranks = sorted(int(p) for p in ranks)
        if not ranks:
            raise ValueError("participants must be non-empty")
        if self.process_index not in ranks:
            raise ValueError(
                f"process {self.process_index} cannot agree in a world "
                f"it is not part of ({ranks})")
        if ranks != self._participants:
            log.info("restore consensus: participants %s -> %s",
                     self._participants, ranks)
        self._participants = ranks

    # ---- core gather ---------------------------------------------------
    def _gather_once(self, topic: str, payload: dict) -> Dict[int, dict]:
        """Publish this process's view under a per-call topic, then
        block until every process of the mesh has published it (or the
        timeout expires)."""
        n = self._gathers.get(topic, 0)
        self._gathers[topic] = n + 1
        topic = f"e{self.epoch}_c{n}_{topic}"
        faults.inject("restore.consensus", op=f"publish:{topic}",
                      process=self.process_index)
        self.store.publish(topic, self.process_index, payload)
        deadline = self.clock() + self.timeout
        while True:
            got = self.store.read(topic)
            got = {p: d for p, d in got.items()
                   if p in set(self._participants)}
            missing = [p for p in self._participants if p not in got]
            if not missing:
                return got
            if self.clock() > deadline:
                raise ConsensusTimeout(
                    f"consensus on {topic!r} timed out after "
                    f"{self.timeout:.1f}s: process(es) {missing} never "
                    "published — resolve mesh membership before "
                    "restoring")
            self.sleep(self.poll_interval)

    def _gather(self, topic: str, payload: dict) -> Dict[int, dict]:
        """Gather + digest-confirm barrier. The per-instance call
        counters (lockstep contract above) keep repeated agreements on
        fresh topics, and the confirm round makes a stale file from a
        previous episode HARMLESS: if any rank gathered different data
        (e.g. a leftover pre-crash publish it read before that rank
        restarted and overwrote it), the digests mismatch and every
        rank that saw the mismatch retries on fresh topics — divergent
        data can never be silently agreed on; the worst case is a loud
        ConsensusTimeout."""
        import hashlib
        import json as _json
        last = None
        for attempt in range(5):
            got = self._gather_once(topic, payload)
            digest = hashlib.sha256(_json.dumps(
                got, sort_keys=True).encode()).hexdigest()
            conf = self._gather_once(f"{topic}.confirm",
                                     {"digest": digest})
            digests = {d.get("digest") for d in conf.values()}
            if len(digests) == 1:
                return got
            last = sorted(d or "?" for d in digests)
            log.warning("consensus gather on %r round %d: digests "
                        "disagree (%s) — stale episode files suspected, "
                        "retrying on fresh topics", topic, attempt, last)
        raise ConsensusTimeout(
            f"consensus on {topic!r} never converged: digests kept "
            f"disagreeing across retries ({last}) — clear the consensus "
            "dir or bump the epoch")

    # ---- restore-step agreement ----------------------------------------
    def agree_restore_step(self,
                           local_step: Optional[int]) -> Optional[int]:
        """Publish this process's latest locally-verified restorable
        step; return ``min`` over the mesh once everyone has published.
        ``None``/-1 means "no restorable checkpoint here", which forces
        the agreed answer to None (fresh start) — restoring a step ANY
        process lacks would diverge the mesh."""
        mine = -1 if local_step is None else int(local_step)
        got = self._gather("restore_step", {"step": mine})
        steps = {p: int(d.get("step", -1)) for p, d in got.items()}
        agreed = min(steps.values())
        self._emit("step", local=mine, agreed=agreed,
                   steps={str(p): s for p, s in sorted(steps.items())})
        log.info("restore consensus: local step %s, mesh %s -> agreed %s",
                 mine, sorted(steps.values()), agreed)
        return None if agreed < 0 else agreed

    def agree_restore_set(self,
                          local_steps: Sequence[int]) -> Optional[int]:
        """Publish EVERY locally-verified restorable step; return the
        newest step present on the WHOLE mesh (max of the intersection),
        or None when no step is commonly restorable. Stricter than
        :meth:`agree_restore_step`: the agreed step is guaranteed to
        exist (and verify) on every rank even when retention windows
        have drifted apart."""
        mine = sorted({int(s) for s in local_steps})
        got = self._gather("restore_set", {"steps": mine})
        sets = [set(d.get("steps", [])) for d in got.values()]
        common = set.intersection(*sets) if sets else set()
        agreed = max(common) if common else None
        self._emit("step", local=mine[-1] if mine else -1,
                   agreed=-1 if agreed is None else agreed,
                   common=sorted(common))
        log.info("restore consensus: local steps %s -> commonly "
                 "restorable %s -> agreed %s", mine, sorted(common),
                 agreed)
        return agreed

    # ---- quarantine agreement ------------------------------------------
    def _quarantine_round(self, files: Sequence[str], rnd: int
                          ) -> tuple:
        """One quarantine barrier round: publish ``files``, gather the
        mesh. Returns ``(union, converged)`` — converged is True when
        every process published the same set (a pure function of the
        gathered data, so every process sees the same answer)."""
        mine = sorted(set(files))
        got = self._gather(f"quarantine_r{rnd}", {"files": mine})
        published = [frozenset(d.get("files", [])) for d in got.values()]
        union = sorted(frozenset().union(*published))
        self._emit("quarantine", round=rnd, local=len(mine),
                   agreed=len(union), files=union)
        return union, all(s == frozenset(union) for s in published)

    def agree_quarantine(self, files: Sequence[str],
                         round: int = 0) -> List[str]:
        """Publish this process's quarantine list for ``round``; return
        the sorted mesh-wide union. Every process must call with the
        same round sequence (see :func:`sync_shared_quarantine`)."""
        return self._quarantine_round(files, round)[0]

    def _emit(self, kind: str, **fields) -> None:
        try:
            from paddlebox_tpu.obs.hub import get_hub
            hub = get_hub()
            hub.counter("pbox_restore_consensus_total",
                        "consensus agreements reached").inc(kind=kind)
            if hub.active:
                hub.emit("restore_consensus", kind=kind,
                         process=self.process_index, **fields)
        except Exception:
            log.debug("consensus telemetry emit failed", exc_info=True)


def consensus_restore(checkpoint, trainer, consensus: RestoreConsensus
                      ) -> Optional[int]:
    """Multihost-consistent restore: every process publishes its
    locally-verified restorable steps (full base+delta chain
    checksummed — ``CheckpointManager.verified_steps``), the mesh
    agrees on the newest COMMON step, and every process restores THAT
    step. Publishing the full verified set (not just the newest step)
    means the agreed step is guaranteed to exist on every rank even
    when crash timing or retention windows made the rank's checkpoint
    sets drift apart. Returns the restored step, or None when no step
    is commonly restorable (fresh start everywhere — the only
    mesh-consistent answer)."""
    local = checkpoint.verified_steps()
    agreed = consensus.agree_restore_set(local)
    if agreed is None:
        log.warning("consensus restore: mesh has no commonly-restorable "
                    "step — starting fresh")
        return None
    restored = checkpoint.restore(trainer, step=agreed)
    if local and agreed != local[-1]:
        log.warning("consensus restore: rolled back from local step %d "
                    "to mesh-agreed step %d", local[-1], agreed)
    return restored


def sync_shared_quarantine(dataset, consensus: RestoreConsensus,
                           max_rounds: int = 4) -> List[str]:
    """Make quarantine decisions mesh-consistent: publish this process's
    quarantined files and adopt the union, so every process drops the
    SAME files and the byte-identical-batches contract survives a
    single-process file fault.

    Runs in rounds, each a full-mesh barrier every process executes in
    lockstep. A round where the published sets are NOT all equal makes
    every process adopt the union (reloading without the newly-dropped
    files — which may quarantine new files, feeding the next round).
    The stop condition — "all published sets equal" — is a pure
    function of the gathered data, so every process stops at the same
    round. Returns the final agreed quarantine list.

    Needs a dataset that can reload (``load_into_memory``) — i.e. the
    in-memory family that the SPMD identical-batches contract applies
    to — OR a WINDOWED streaming QueueDataset, whose quarantine union
    is adopted as a preseeded skip set instead of a reload (records of
    not-yet-consumed files simply never stream; files a rank partially
    read before quarantining fall under the stream's documented
    at-least-once accounting, not the byte-identical-batches contract).
    Legacy unwindowed streams are still refused up front.

    TIMEOUT SIZING: a rank that adopts peer drops RELOADS the pass
    between rounds while its peers already wait in the next round's
    gather — ``FLAGS.consensus_timeout_sec`` (or the consensus's
    ``timeout=``) must therefore cover a full pass reload, not just
    filesystem latency."""
    if not hasattr(dataset, "load_into_memory"):
        if getattr(dataset, "windowed", False) and \
                hasattr(dataset, "preseed_quarantine"):
            return _sync_stream_quarantine(dataset, consensus,
                                           max_rounds)
        raise TypeError(
            "sync_shared_quarantine needs an in-memory dataset (it "
            "reloads without the mesh-quarantined files) or a WINDOWED "
            "streaming QueueDataset (FLAGS.stream_window_files, which "
            "adopts the union as a skip set); "
            f"{type(dataset).__name__} can do neither")
    applied = {p for p, _ in dataset.quarantined_files}
    for rnd in range(max_rounds):
        local = sorted({p for p, _ in dataset.quarantined_files}
                       | applied)
        union, converged = consensus._quarantine_round(local, rnd)
        if converged:
            applied = set(union)
            break  # everyone published the same set: mesh converged
        in_list = [p for p in union if p in dataset.filelist]
        # locally-quarantined files already excluded their records —
        # only files a PEER dropped (still loaded here) force a reload
        local_q = {p for p, _ in dataset.quarantined_files}
        extra = [p for p in in_list if p not in local_q]
        if in_list:
            dataset.set_filelist(
                [p for p in dataset.filelist if p not in union])
        if extra:
            log.warning("shared quarantine: dropping %d file(s) "
                        "quarantined on peer process(es): %s",
                        len(extra), extra)
            dataset.load_into_memory()  # fresh failures join next round
        applied = set(union)
    else:
        raise RuntimeError(
            f"shared quarantine did not converge in {max_rounds} rounds "
            f"— files keep failing; last union: {sorted(applied)}")
    have = dict(dataset.quarantined_files)
    with dataset._quarantine_lock:
        dataset.quarantined_files = [
            (p, have.get(p, "quarantined on a peer process"))
            for p in sorted(applied)]
    return sorted(applied)


def _sync_stream_quarantine(dataset, consensus: RestoreConsensus,
                            max_rounds: int = 4) -> List[str]:
    """Quarantine-union agreement for a WINDOWED streaming dataset: the
    mesh union is adopted as a PRESEEDED skip set
    (``QueueDataset.preseed_quarantine`` — budget-free, carried forward
    by every later stream cursor) so every rank's future windows drop
    the same files. Same lockstep round contract as the in-memory path
    (``agree_quarantine`` rounds must align across ranks)."""
    union: List[str] = []
    for rnd in range(max_rounds):
        local = sorted({p for p, _ in dataset.quarantined_files})
        union, converged = consensus._quarantine_round(local, rnd)
        if converged:
            break
        extra = [p for p in union if p not in set(local)]
        if extra:
            log.warning("shared quarantine (stream): preseeding %d "
                        "file(s) quarantined on peer process(es): %s",
                        len(extra), extra)
        dataset.preseed_quarantine(union)
    else:
        raise RuntimeError(
            f"shared quarantine did not converge in {max_rounds} "
            f"rounds — files keep failing; last union: {union}")
    dataset.preseed_quarantine(union)
    return sorted(union)
