"""Deterministic, seed-driven fault injection for chaos testing.

A ``FaultPlan`` is a list of ``FaultSpec``s, each bound to a named
**site** — a seam in the production code that calls
:func:`inject(site, ...)<inject>`. With no plan installed the seam costs
one module-global read; with a plan, firing is decided purely by the
spec's call counter (+ optional glob match and seeded probability), so
the same seed fires the same faults in the same places across runs —
recovery is *provable*, not hoped-for.

Instrumented sites (docs/RESILIENCE.md):

==========================  =============================================
site                        seam
==========================  =============================================
``file_mgr.command``        every CommandBackend CLI invocation
``dataset.open``            each file a dataset reader opens (both the
                            per-line and native-columnar paths)
``parser.record``           each text line before parsing (``corrupt``
                            mutates the line into garbage the parser
                            rejects)
``reader.file``             once per file per reader (``slow`` sleeps)
``checkpoint.io``           checkpoint meta/dense file reads+writes
``checkpoint.save_commit``  just before the atomic rename that publishes
                            a checkpoint (``fail`` == crash mid-save)
``checkpoint.cursor``       resume-cursor save/load (cursor.json)
``trainer.pass``            start of every Trainer.run_pass attempt
``preempt.signal``          the batch-boundary stop poll; a ``fail``
                            fault here IS a simulated SIGTERM — it
                            becomes a graceful stop request, never an
                            exception (resilience/preemption)
``restore.consensus``       every shared-dir consensus publish
                            (restore-step / quarantine agreement)
``endpass.writeback``       each async end-pass write-back job before
                            the D2H pull lands rows in the host tier
                            (ps/tiered.py, ps/pass_table.py): a ``fail``
                            surfaces at the next epilogue fence as
                            ``EndPassWritebackError`` — never as silent
                            row loss
``ssd.io``                  every SSD-tier segment file operation
                            (append / read / unlink — ps/ssd.py): a
                            transient ``fail`` retries on the seeded
                            RetryPolicy (site ``ssd.io``); repeated
                            failures surface through the demote/promote
                            caller (the epilogue fence for background
                            demotes — never silent zeros)
``artifact.publish``        just before the atomic rename that publishes
                            an artifact version (artifacts.py): a
                            transient ``fail`` retries on the seeded
                            RetryPolicy (site ``artifact.publish``), a
                            ``crash`` models the writer dying after
                            staging — recovery is the carcass sweep +
                            the previous complete version
``artifact.read``           every registry read (manifest, sidecar,
                            payload digest) on the consumer side:
                            ``corrupt`` mangles the bytes so the
                            checksum chain refuses the version
                            (``ArtifactCorruptError``) and adoption
                            degrades to the newest verifiable one
``serving.reload``          start of every background hot-reload poll
                            (serving.ReloadLoop.poll_once): a ``fail``
                            fault here (or anywhere inside the poll's
                            store reads) NEVER reaches the query path —
                            the loop books
                            ``pbox_serving_reload_refused_total``,
                            keeps serving the prior snapshot and
                            re-polls on the seeded RetryPolicy backoff
                            (docs/SERVING.md); transient
                            ``artifact.read`` failures inside the poll
                            retry on their own seeded policy without a
                            refusal (chaos fault 7)
``stream.window``           each streaming window dispatch (windowed
                            ``QueueDataset``, data/dataset.py): fires as
                            a window's readers are about to start, ctx
                            carries the window index and its first file
                            — a transient ``fail`` here exercises the
                            stream recovery path (run_pass rolls back to
                            the last stream checkpoint and REPLAYS the
                            window, at-least-once)
``online.supervise``        the online daemon's supervisor seams
                            (online.OnlineLearner.run / serve-leg
                            start): a transient ``fail`` on the train
                            leg retries on the seeded RetryPolicy (site
                            ``online.supervise``, mode ``degraded``
                            while backing off); a deterministic one
                            degrades the daemon to ``serve_only`` /
                            ``train_only`` LOUDLY instead of dying
                            (docs/ONLINE.md)
``online.shrink``           start of every feature-lifecycle shrink
                            attempt (online.OnlineLearner): transient
                            failures retry on the seeded policy (site
                            ``online.shrink``); a hard/exhausted
                            failure SKIPS the cycle loudly
                            (``pbox_online_shrink_skipped_total`` + a
                            ``shrink_skipped`` flight-recorder trigger)
                            without stalling training — the cadence
                            re-fires ``shrink_every_windows`` later
``elastic.kv``              every membership-store operation
                            (distributed/elastic.FileKVStore put / get /
                            delete / list / mtime / touch; ctx carries
                            ``op`` and ``key``): a transient ``fail``
                            retries on the seeded RetryPolicy (site
                            ``elastic.kv``) at the manager level — a
                            lease refresh or alive-poll survives a
                            flaky NFS round trip without a spurious
                            scale event (chaos fault 8)
``elastic.rendezvous``      each ``wait_for_np`` poll iteration
                            (distributed/elastic.ElasticManager): a
                            transient ``fail`` is one missed
                            observation absorbed by the rendezvous
                            window; on timeout the error names the
                            hosts that never showed up
==========================  =============================================

Fault kinds: ``fail`` (raise — ``exc=transient|crash|os`` picks the
type), ``corrupt`` (mutate the value flowing through the seam),
``slow`` (sleep ``delay`` seconds).

Spec string (FLAGS.fault_plan / scripts/chaos_check.py)::

    seed=7; file_mgr.command:fail:nth=1; parser.record:corrupt:nth=3,
    match=*part_001*; checkpoint.save_commit:fail:nth=1,exc=crash

i.e. ``;``-separated ``site:kind[:k=v,k=v...]`` entries with an
optional leading ``seed=N``. Keys: ``nth`` (1-based call index the
fault first fires at, default 1), ``times`` (how many consecutive
matching calls fire, default 1; ``0`` = every call), ``match`` (glob
against the seam's ``path``/``op`` context), ``p`` (fire with seeded
probability instead of a call index), ``delay`` (seconds, ``slow``),
``exc`` (``fail`` exception class).
"""

from __future__ import annotations

import fnmatch
import random
import threading
import time
from typing import Dict, List, Optional

from paddlebox_tpu.resilience.retry import TransientError
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class InjectedFault(RuntimeError):
    """Base of every exception raised by fault injection."""


class TransientInjectedError(InjectedFault, TransientError):
    """Injected *retryable* failure (RetryPolicy classifies it
    transient, like the real CLI/IO errors it stands in for)."""


class InjectedCrash(InjectedFault):
    """Injected hard crash (NOT retryable — models a process dying
    mid-operation; recovery must come from atomicity/checkpoints)."""


_EXC_KINDS = {"transient": TransientInjectedError,
              "crash": InjectedCrash,
              "os": OSError}


class FaultSpec:
    """One fault at one site. Thread-safe: the per-spec call counter
    advances under the plan lock."""

    def __init__(self, site: str, kind: str, nth: int = 1, times: int = 1,
                 match: Optional[str] = None, p: Optional[float] = None,
                 delay: float = 0.05, exc: str = "transient") -> None:
        if kind not in ("fail", "corrupt", "slow"):
            raise ValueError(f"unknown fault kind {kind!r} "
                             "(one of fail/corrupt/slow)")
        if exc not in _EXC_KINDS:
            raise ValueError(f"unknown exc {exc!r} "
                             f"(one of {sorted(_EXC_KINDS)})")
        self.site = site
        self.kind = kind
        self.nth = int(nth)
        self.times = int(times)
        self.match = match
        self.p = None if p is None else float(p)
        self.delay = float(delay)
        self.exc = exc
        self.calls = 0   # matching calls seen
        self.fired = 0   # faults actually fired

    def _matches_ctx(self, ctx: Dict[str, object]) -> bool:
        if self.match is None:
            return True
        hay = str(ctx.get("path", ctx.get("op", "")))
        return fnmatch.fnmatch(hay, self.match)

    def should_fire(self, ctx: Dict[str, object],
                    rng: random.Random) -> bool:
        if not self._matches_ctx(ctx):
            return False
        self.calls += 1
        if self.p is not None:
            hit = rng.random() < self.p
        else:
            hit = (self.calls >= self.nth
                   and (self.times == 0
                        or self.calls < self.nth + self.times))
        if hit:
            self.fired += 1
        return hit

    def describe(self) -> str:
        tail = f"nth={self.nth},times={self.times}" if self.p is None \
            else f"p={self.p}"
        m = f",match={self.match}" if self.match else ""
        return f"{self.site}:{self.kind}:{tail}{m}"


def _corrupt_value(value, rng: random.Random):
    """Deterministically mangle the value at a ``corrupt`` seam. Strings
    and bytes become reversed garbage with a marker every parser rejects
    (criteo: wrong field count; slot text: non-numeric tokens)."""
    if isinstance(value, str):
        return "\x00CORRUPT\x00 " + value[::-1]
    if isinstance(value, (bytes, bytearray)):
        return b"\x00CORRUPT\x00 " + bytes(value)[::-1]
    return None  # non-text seams: the canonical "torn value"


class FaultPlan:
    def __init__(self, specs: List[FaultSpec], seed: int = 0) -> None:
        self.specs = list(specs)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._rngs: Dict[str, random.Random] = {}
        self._by_site: Dict[str, List[FaultSpec]] = {}
        for s in self.specs:
            self._by_site.setdefault(s.site, []).append(s)

    # ---- construction --------------------------------------------------
    @classmethod
    def parse(cls, text: str, seed: Optional[int] = None) -> "FaultPlan":
        """Build a plan from the compact spec string (module docstring).
        An empty/whitespace string yields an empty plan."""
        specs: List[FaultSpec] = []
        plan_seed = 0 if seed is None else int(seed)
        for raw in text.split(";"):
            entry = raw.strip()
            if not entry:
                continue
            if entry.startswith("seed="):
                if seed is None:
                    plan_seed = int(entry[5:])
                continue
            parts = entry.split(":", 2)
            if len(parts) < 2:
                raise ValueError(
                    f"bad fault spec {entry!r}: want site:kind[:k=v,...]")
            site, kind = parts[0].strip(), parts[1].strip()
            kw: Dict[str, object] = {}
            if len(parts) == 3 and parts[2].strip():
                for pair in parts[2].split(","):
                    k, _, v = pair.partition("=")
                    k = k.strip()
                    if k in ("nth", "times"):
                        kw[k] = int(v)
                    elif k in ("p", "delay"):
                        kw[k] = float(v)
                    elif k in ("match", "exc"):
                        kw[k] = v.strip()
                    else:
                        raise ValueError(
                            f"bad fault spec key {k!r} in {entry!r}")
            specs.append(FaultSpec(site, kind, **kw))
        return cls(specs, seed=plan_seed)

    def _site_rng(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            rng = self._rngs[site] = random.Random(f"{self.seed}:{site}")
        return rng

    # ---- firing --------------------------------------------------------
    def inject(self, site: str, value=None, **ctx):
        """Run the seam: may raise (``fail``), sleep (``slow``), or
        return a mutated ``value`` (``corrupt``); otherwise returns
        ``value`` untouched."""
        specs = self._by_site.get(site)
        if not specs:
            return value
        to_fire: List[FaultSpec] = []
        with self._lock:
            rng = self._site_rng(site)
            for spec in specs:
                if spec.should_fire(ctx, rng):
                    to_fire.append(spec)
        for spec in to_fire:
            value = self._fire(spec, site, value, ctx)
        return value

    def _fire(self, spec: FaultSpec, site: str, value,
              ctx: Dict[str, object]):
        desc = spec.describe()
        log.warning("fault injected at %s (%s) ctx=%s", site, desc, ctx)
        try:
            from paddlebox_tpu.obs.hub import get_hub
            hub = get_hub()
            hub.counter("pbox_faults_injected_total",
                        "faults fired by the installed FaultPlan").inc(
                            site=site, kind=spec.kind)
            if hub.active:
                hub.emit("fault_injected", site=site, kind=spec.kind,
                         spec=desc, **{k: str(v) for k, v in ctx.items()})
        except Exception:
            log.debug("fault telemetry emit failed", exc_info=True)
        if spec.kind == "slow":
            time.sleep(spec.delay)
            return value
        if spec.kind == "corrupt":
            with self._lock:
                return _corrupt_value(value, self._site_rng(site))
        exc_cls = _EXC_KINDS[spec.exc]
        raise exc_cls(f"injected fault at {site} ({desc}, ctx={ctx})")

    # ---- reporting -----------------------------------------------------
    def stats(self) -> Dict[str, Dict[str, int]]:
        """``{"site:kind": {"calls": n, "fired": m}}`` — deterministic
        across runs with the same seed (chaos_check asserts equality)."""
        with self._lock:
            return {f"{s.site}:{s.kind}": {"calls": s.calls,
                                           "fired": s.fired}
                    for s in self.specs}

    # ---- installation --------------------------------------------------
    def install(self) -> "FaultPlan":
        install_plan(self)
        return self


_PLAN: Optional[FaultPlan] = None
_PLAN_LOCK = threading.Lock()


def install_plan(plan: FaultPlan) -> None:
    global _PLAN
    with _PLAN_LOCK:
        _PLAN = plan
    if plan.specs:
        log.warning("fault plan INSTALLED (seed=%d): %s", plan.seed,
                    "; ".join(s.describe() for s in plan.specs))


def clear_plan() -> None:
    global _PLAN
    with _PLAN_LOCK:
        _PLAN = None


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def install_from_flags() -> Optional[FaultPlan]:
    """Install ``FLAGS.fault_plan`` (no-op when the flag is empty);
    called by Trainer init so env-driven chaos runs need no code."""
    from paddlebox_tpu.config import FLAGS
    if not FLAGS.fault_plan:
        return None
    plan = FaultPlan.parse(FLAGS.fault_plan,
                           seed=FLAGS.seed).install()
    return plan


class installed:
    """Context manager scoping a plan: ``with installed(plan): ...``
    (tests); restores the previously installed plan on exit."""

    def __init__(self, plan: FaultPlan) -> None:
        self.plan = plan
        self._prev: Optional[FaultPlan] = None

    def __enter__(self) -> FaultPlan:
        self._prev = active_plan()
        install_plan(self.plan)
        return self.plan

    def __exit__(self, *exc) -> None:
        if self._prev is None:
            clear_plan()
        else:
            install_plan(self._prev)


def inject(site: str, value=None, **ctx):
    """THE seam hook. One global read + None check when no plan is
    installed — cheap enough for per-line call sites."""
    plan = _PLAN
    if plan is None:
        return value
    return plan.inject(site, value, **ctx)
