"""RetryPolicy — exponential backoff with seeded jitter for IO seams.

Design goals (ISSUE 2 tentpole):

- **Bounded**: both an attempt cap and a wall-clock deadline; a flaky
  seam degrades a run, it never wedges one.
- **Deterministic**: jitter comes from a ``random.Random`` seeded from
  ``(seed, site)`` — two runs with the same seed produce the same delay
  sequence, so chaos tests (tests/test_resilience.py) can assert exact
  behavior and production incidents replay.
- **Classified**: only *transient* failures retry. ``TransientError``
  (and its fault-injection subclass), ``OSError`` and subprocess
  timeouts are transient by default; programming errors
  (TypeError/KeyError/...) never are. Callers narrow or widen the set
  per seam (``retryable=`` / ``classify=``).
- **Observable**: every retry increments
  ``pbox_retry_attempts_total{site=...}`` and (when a telemetry sink is
  attached) emits a ``retry`` event with the attempt, delay and error —
  chaos runs are diagnosable straight from the JSONL.

Usage::

    policy = RetryPolicy.from_flags(site="file_mgr.command")
    out = policy.call(lambda: backend._run_once("-ls", path))
"""

from __future__ import annotations

import dataclasses
import random
import subprocess
import time
from typing import Callable, Optional, Tuple, Type

from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class TransientError(RuntimeError):
    """A failure worth retrying: transient IO/RPC/CLI trouble, not a
    programming error. Subclassed by ``TransientCommandError``
    (utils/file_mgr) and ``TransientInjectedError`` (resilience/faults)."""


class RetryExhausted(RuntimeError):
    """Raised when a policy gives up; ``__cause__`` is the last error."""

    def __init__(self, msg: str, attempts: int,
                 last: BaseException) -> None:
        super().__init__(msg)
        self.attempts = attempts
        self.last = last


#: Exception types retryable by default at every seam.
DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    TransientError, OSError, subprocess.TimeoutExpired, TimeoutError)

#: Deterministic filesystem outcomes — retrying cannot change them, so
#: they propagate on the first attempt even where OSError is retryable.
NON_TRANSIENT_OS: Tuple[Type[BaseException], ...] = (
    FileNotFoundError, NotADirectoryError, IsADirectoryError,
    FileExistsError, PermissionError)


def is_retryable(exc: BaseException,
                 retryable: Tuple[Type[BaseException], ...]
                 = DEFAULT_RETRYABLE) -> bool:
    """True when ``exc`` is classified transient (worth a retry)."""
    if isinstance(exc, NON_TRANSIENT_OS):
        return False
    return isinstance(exc, retryable)


@dataclasses.dataclass
class RetryPolicy:
    """Exponential backoff: attempt k (1-based) sleeps
    ``min(max_delay, base_delay * 2**(k-1))`` scaled by a seeded jitter
    factor in ``[1-jitter, 1+jitter]``. ``max_attempts`` counts total
    tries (1 == no retry); ``deadline`` bounds the summed wall time a
    single ``call`` may spend across tries and sleeps."""

    site: str = ""
    max_attempts: int = 4
    base_delay: float = 0.05
    max_delay: float = 2.0
    deadline: Optional[float] = 30.0
    jitter: float = 0.25
    seed: int = 0
    retryable: Tuple[Type[BaseException], ...] = DEFAULT_RETRYABLE
    # optional override: classify(exc) -> bool decides retryability
    classify: Optional[Callable[[BaseException], bool]] = None
    sleep: Callable[[float], None] = time.sleep
    clock: Callable[[], float] = time.monotonic

    @classmethod
    def from_flags(cls, site: str = "", **overrides) -> "RetryPolicy":
        """Policy from the process-wide ``FLAGS.retry_*`` knobs."""
        from paddlebox_tpu.config import FLAGS
        kw = dict(site=site,
                  max_attempts=FLAGS.retry_max_attempts,
                  base_delay=FLAGS.retry_base_delay_sec,
                  max_delay=FLAGS.retry_max_delay_sec,
                  deadline=(FLAGS.retry_deadline_sec
                            if FLAGS.retry_deadline_sec > 0 else None),
                  jitter=FLAGS.retry_jitter,
                  seed=FLAGS.seed)
        kw.update(overrides)
        return cls(**kw)

    def _rng(self) -> random.Random:
        return random.Random(f"{self.seed}:{self.site}")

    def delays(self):
        """The deterministic backoff schedule (one delay per retry);
        exposed so tests can assert the exact seeded sequence."""
        rng = self._rng()
        for k in range(1, max(1, self.max_attempts)):
            # exponent clamp: past 2**64 the doubling is irrelevant
            # (min() already plateaus at max_delay) but the raw int
            # would overflow float() around k~1024 — a real hazard for
            # long-lived schedules like the stream idle poll
            d = min(self.max_delay,
                    self.base_delay * (2.0 ** min(k - 1, 64)))
            if self.jitter > 0:
                d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
            yield max(0.0, d)

    def _is_retryable(self, exc: BaseException) -> bool:
        if self.classify is not None:
            return bool(self.classify(exc))
        return is_retryable(exc, self.retryable)

    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn(*args, **kwargs)`` under this policy. Non-retryable
        errors propagate untouched on the first attempt; exhausting the
        policy raises ``RetryExhausted`` with the last error chained."""
        start = self.clock()
        attempts = 0
        last: Optional[BaseException] = None
        schedule = self.delays()
        while True:
            attempts += 1
            try:
                return fn(*args, **kwargs)
            except BaseException as e:
                if not self._is_retryable(e):
                    raise
                last = e
            delay = next(schedule, None)
            elapsed = self.clock() - start
            over_deadline = (self.deadline is not None
                             and elapsed + (delay or 0.0) > self.deadline)
            if delay is None or over_deadline:
                why = ("deadline" if over_deadline else "attempts")
                raise RetryExhausted(
                    f"{self.site or 'retry'}: gave up after {attempts} "
                    f"attempt(s) ({why} exhausted, {elapsed:.2f}s): "
                    f"{last!r}", attempts, last) from last
            self._note_retry(attempts, delay, last)
            self.sleep(delay)

    def wrap(self, fn: Callable) -> Callable:
        """Decorator form of :meth:`call`."""
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)
        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped

    def _note_retry(self, attempt: int, delay: float,
                    exc: BaseException) -> None:
        log.warning("%s: attempt %d failed (%r) — retrying in %.3fs",
                    self.site or "retry", attempt, exc, delay)
        try:
            from paddlebox_tpu.obs.hub import get_hub
            hub = get_hub()
            hub.counter("pbox_retry_attempts_total",
                        "IO retries per seam").inc(site=self.site or "?")
            if hub.active:
                hub.emit("retry", site=self.site, attempt=attempt,
                         delay_sec=round(delay, 4), error=repr(exc))
        except Exception:  # telemetry must never take the retry down
            log.debug("retry telemetry emit failed", exc_info=True)


def retry_counters() -> dict:
    """Snapshot of the resilience counters (the ``resilience`` block the
    per-pass telemetry event carries — obs/hub.emit_pass_event)."""
    from paddlebox_tpu.obs.hub import get_hub
    hub = get_hub()

    def total(name: str) -> float:
        return sum(v for _, v in hub.counter(name).series())

    return {
        "retry_attempts": total("pbox_retry_attempts_total"),
        "files_quarantined": total("pbox_files_quarantined_total"),
        "records_poisoned": total("pbox_records_poisoned_total"),
        "faults_injected": total("pbox_faults_injected_total"),
        "pass_retries": total("pbox_pass_retries_total"),
    }
