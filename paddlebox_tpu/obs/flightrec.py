"""Anomaly flight recorder — the always-on black box.

A bounded in-memory ring of the most recent telemetry (events, spans,
critical-path blocks) plus a trigger registry. When an anomaly fires —
a NaN rollback, a refused serving reload, a ``PipelineHangError``, a
watchdog escalation, an SLO breach from the alert engine, or an
explicit ``hub.dump_blackbox(reason)`` — the recorder atomically
publishes ONE self-contained postmortem bundle: the ring contents, a
``snapshot()`` of every instrument, the last-N critical-path blocks,
the resolved FLAGS, live thread stacks (``sys._current_frames``) and
the run/pass identity, via the same write-tmp → fsync → ``os.replace``
discipline as the artifact layer (``utils.fsio.atomic_write_json``).

Hot-loop contract (same as ``trace.py``): with no recorder installed,
``trigger()`` is one module-global read; the ring itself only receives
records while it is registered as a hub sink, which only happens when
``FLAGS.flightrec_dir`` is set — default-off runs stay bit-identical.
Per-trigger debounce collapses anomaly storms into one bundle per
window, and a retention cap bounds the on-disk footprint.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional

from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: bundle schema version (bump on layout changes; consumers check it)
BUNDLE_SCHEMA = 1

#: the trigger catalog (docs/OBSERVABILITY.md §Flight recorder). Names
#: outside this set are rejected — a typo'd trigger must fail loudly in
#: tests, not silently produce an unknown bundle family.
TRIGGERS = ("nan_rollback", "reload_degrade", "pipeline_hang",
            "watchdog_escalation", "slo_breach", "manual",
            "shrink_skipped", "online_degrade", "membership_change")

#: critical-path blocks retained for the bundle (newest last)
KEEP_CRITICAL_PATH = 16


class FlightRecorder:
    """Ring buffer + trigger registry + atomic bundle publisher.

    Registers on the hub as a dual (event + span) sink; ``emit`` /
    ``span_full`` appends are lock-light (one deque append under the
    GIL — no explicit lock on the record path)."""

    def __init__(self, out_dir: str, ring_events: int = 512,
                 debounce_sec: float = 60.0, keep: int = 16) -> None:
        self.out_dir = out_dir
        self.debounce_sec = float(debounce_sec)
        self.keep = int(keep)
        self._ring: collections.deque = collections.deque(
            maxlen=max(int(ring_events), 1))
        self._cp: collections.deque = collections.deque(
            maxlen=KEEP_CRITICAL_PATH)
        # trigger bookkeeping under one small lock (trigger paths are
        # cold — they fire on anomalies, never per event)
        self._lock = threading.Lock()
        self._last_fire: Dict[str, float] = {}
        self._seq = 0
        os.makedirs(out_dir, exist_ok=True)

    # ---- sink surface (the ring) ---------------------------------------
    def emit(self, event: Dict) -> None:
        """Event-sink surface: record every hub event; stash the pass
        events' critical-path blocks separately so the bundle carries
        them even after the ring wrapped."""
        self._ring.append({"rec": "event", **event})
        cp = event.get("critical_path")
        if cp:
            self._cp.append({"pass_seq": event.get("pass_seq"),
                             "seq": event.get("seq"), **cp})

    def span_full(self, rec: Dict) -> None:
        """Rich span-sink surface (obs/trace fan-out)."""
        self._ring.append({"rec": "span", **rec})

    def span(self, name: str, start_s: float, dur_s: float,
             attrs: Optional[Dict] = None) -> None:
        """Plain span-sink surface (hub.span fan-out)."""
        self._ring.append({"rec": "span", "name": name, "t0": start_s,
                           "dur": dur_s, **(attrs or {})})

    def close(self) -> None:
        pass

    # ---- triggers ------------------------------------------------------
    def trigger(self, name: str, reason: str = "",
                **ctx) -> Optional[str]:
        """Fire trigger ``name``: publish one postmortem bundle unless
        the per-trigger debounce window is still open. Returns the
        bundle path (None when debounced or the publish failed — a
        failing black box must never compound the anomaly it records).
        """
        if name not in TRIGGERS:
            raise ValueError(f"unknown flight-recorder trigger {name!r} "
                             f"(catalog: {TRIGGERS})")
        now = time.monotonic()
        with self._lock:
            last = self._last_fire.get(name)
            if last is not None and now - last < self.debounce_sec:
                self._book("pbox_flightrec_suppressed_total",
                           "debounced flight-recorder triggers", name)
                return None
            self._last_fire[name] = now
            self._seq += 1
            seq = self._seq
        try:
            path = self._publish(seq, name, reason, ctx)
        except Exception:
            log.error("flight recorder bundle publish failed (%s)",
                      name, exc_info=True)
            return None
        self._book("pbox_flightrec_bundles_total",
                   "postmortem bundles published", name)
        try:
            from paddlebox_tpu.obs.hub import get_hub
            hub = get_hub()
            if hub.active:
                hub.emit("blackbox_dump", trigger=name, reason=reason,
                         path=path)
        except Exception:
            log.debug("blackbox_dump event emit failed", exc_info=True)
        log.error("flight recorder: trigger %r (%s) → %s", name,
                  reason or "-", path)
        return path

    @staticmethod
    def _book(counter: str, help: str, name: str) -> None:
        try:
            from paddlebox_tpu.obs.hub import get_hub
            get_hub().counter(counter, help).inc(trigger=name)
        except Exception:
            log.debug("flightrec counter failed", exc_info=True)

    # ---- bundle assembly -----------------------------------------------
    def _publish(self, seq: int, name: str, reason: str,
                 ctx: Dict) -> str:
        from paddlebox_tpu.config import FLAGS
        from paddlebox_tpu.obs.hub import get_hub
        from paddlebox_tpu.utils.fsio import atomic_write_json
        hub = get_hub()
        bundle = {
            "schema": BUNDLE_SCHEMA,
            "trigger": name,
            "reason": reason,
            "ctx": {k: _jsonable(v) for k, v in ctx.items()},
            "ts": time.time(),
            "run": hub.run_id,
            "health": hub.health(),        # run/pass ids + uptime
            "ring": [dict(r) for r in list(self._ring)],
            "instruments": hub.snapshot(),
            "critical_path": list(self._cp),
            "flags": {k: _jsonable(v) for k, v in
                      dataclasses.asdict(FLAGS).items()},
            "threads": self._thread_stacks(),
        }
        path = os.path.join(self.out_dir,
                            f"blackbox-{seq:05d}-{name}.json")
        atomic_write_json(path, bundle)
        self._retain()
        return path

    @staticmethod
    def _thread_stacks() -> Dict[str, Dict]:
        names = {t.ident: t.name for t in threading.enumerate()}
        out: Dict[str, Dict] = {}
        for tid, frame in sys._current_frames().items():
            out[str(tid)] = {
                "name": names.get(tid, "?"),
                "stack": [ln.rstrip("\n") for ln in
                          traceback.format_stack(frame)],
            }
        return out

    def _retain(self) -> None:
        """Keep the newest ``keep`` bundles (bundle names embed a
        monotone sequence number, so lexical order IS age order)."""
        if self.keep <= 0:
            return
        try:
            bundles = sorted(f for f in os.listdir(self.out_dir)
                             if f.startswith("blackbox-")
                             and f.endswith(".json"))
            for stale in bundles[:-self.keep]:
                os.unlink(os.path.join(self.out_dir, stale))
        except OSError:
            log.debug("bundle retention sweep failed", exc_info=True)

    def bundles(self) -> List[str]:
        """Bundle paths on disk, oldest first."""
        return [os.path.join(self.out_dir, f)
                for f in sorted(os.listdir(self.out_dir))
                if f.startswith("blackbox-") and f.endswith(".json")]


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return repr(v)


# ---- module-level registry (the one-global-read inert path) ------------
_RECORDER: Optional[FlightRecorder] = None
_configured_dir: Optional[str] = None


def get_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def install_recorder(rec: Optional[FlightRecorder],
                     attach: bool = True) -> Optional[FlightRecorder]:
    """Install ``rec`` as the process flight recorder (None uninstalls)
    and register/deregister it as a hub sink. The previous recorder (if
    any) is detached from the hub."""
    global _RECORDER, _configured_dir
    from paddlebox_tpu.obs.hub import get_hub
    hub = get_hub()
    if _RECORDER is not None:
        hub.remove_sink(_RECORDER)
    _RECORDER = rec
    if rec is None:
        _configured_dir = None
    elif attach:
        hub.add_sink(rec, kind="both")
    return rec


def configure_from_flags() -> Optional[FlightRecorder]:
    """Install a recorder when ``FLAGS.flightrec_dir`` is set
    (idempotent per dir; called from ``obs.hub.configure_from_flags``).
    """
    global _configured_dir
    from paddlebox_tpu.config import FLAGS
    d = FLAGS.flightrec_dir
    if not d:
        return _RECORDER
    if d == _configured_dir and _RECORDER is not None:
        return _RECORDER
    rec = FlightRecorder(d, ring_events=FLAGS.flightrec_ring_events,
                         debounce_sec=FLAGS.flightrec_debounce_sec,
                         keep=FLAGS.flightrec_keep)
    install_recorder(rec)
    _configured_dir = d
    return rec


def trigger(name: str, reason: str = "", **ctx) -> Optional[str]:
    """Fire a flight-recorder trigger. With no recorder installed this
    is one module-global read — the seams (trainer NaN rollback,
    serving reload degrade, pipeline hang, watchdog escalation, alert
    engine) call it unconditionally."""
    rec = _RECORDER
    if rec is None:
        return None
    try:
        return rec.trigger(name, reason=reason, **ctx)
    except Exception:
        # a broken black box must never take the recovering run down
        log.error("flight recorder trigger %r failed", name,
                  exc_info=True)
        return None
