"""Multihost heartbeat + straggler watchdog.

A multi-controller pod (train/multihost.py) fails ugliest when ONE
process slows down: every collective stalls, and nothing says which
host. Each process publishes ``(step, wall_ts)`` heartbeats into a
shared store; a watchdog thread compares the mesh and flags any process
whose step counter falls behind the front-runner by more than
``step_lag`` steps or whose heartbeat goes stale past
``heartbeat_timeout``. Detection logs + emits a ``straggler`` telemetry
event; with ``abort_after`` set, a stall that persists past the
deadline makes the NEXT ``beat()`` raise ``StragglerTimeout`` in the
training thread — the safe place to abort, since raising inside the
monitor thread would vanish.

Stores: ``LocalHeartbeatStore`` (in-process — tests, single-host
multi-device) and ``DirHeartbeatStore`` (one JSON file per process in a
shared directory — NFS/FUSE mounts on real pods; atomic
write-then-rename so readers never see a torn file).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class StragglerTimeout(RuntimeError):
    """Raised by ``beat()`` after a stall outlives ``abort_after``."""


class StragglerReport(NamedTuple):
    process: int
    step: int          # -1: never heartbeat
    behind: int        # steps behind the front-runner
    age_sec: float     # seconds since the process's last heartbeat
    reason: str        # "step_lag" | "stale" | "missing"


class LocalHeartbeatStore:
    """In-process store (tests / single-host)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._beats: Dict[int, Tuple[int, float]] = {}

    def publish(self, process: int, step: int, ts: float) -> None:
        with self._lock:
            self._beats[process] = (step, ts)

    def read(self) -> Dict[int, Tuple[int, float]]:
        with self._lock:
            return dict(self._beats)


class DirHeartbeatStore:
    """One ``hb_<process>.json`` per process in a shared directory.
    Reusing a directory across runs is safe: the watchdog's ``check``
    ignores ranks beyond the current mesh and beats older than its own
    start (minus the timeout), so prior-run leftovers never report."""

    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(path, exist_ok=True)

    def publish(self, process: int, step: int, ts: float) -> None:
        from paddlebox_tpu.utils.fsio import atomic_write_json
        # no fsync: heartbeats are ephemeral liveness signals — a beat
        # lost to a crash is exactly what the watchdog detects anyway
        atomic_write_json(os.path.join(self.path, f"hb_{process}.json"),
                          {"process": process, "step": step, "ts": ts},
                          fsync=False)

    def read(self) -> Dict[int, Tuple[int, float]]:
        out: Dict[int, Tuple[int, float]] = {}
        try:
            names = os.listdir(self.path)
        except OSError:
            return out
        for n in names:
            if not (n.startswith("hb_") and n.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.path, n)) as fh:
                    d = json.load(fh)
                out[int(d["process"])] = (int(d["step"]), float(d["ts"]))
            except (OSError, ValueError, KeyError):
                continue  # torn/foreign file — next poll sees the rename
        return out


def requeue_pass_action(handler: Callable[[List[StragglerReport]], None],
                        name: str = "requeue_pass"):
    """Escalation action factory: hand the stalled pass back to a
    scheduler/launcher callback (e.g. re-enqueue the pass spec so a
    healthy rank set re-runs it)."""
    def action(wd: "StragglerWatchdog", reports, stalled_for: float):
        handler(reports)
    action.escalation_name = name
    return action


def shrink_and_continue_action(evict_fn: Callable[[List[StragglerReport]],
                                                  None],
                               name: str = "shrink_and_continue"):
    """Escalation action factory — the rung BETWEEN requeue and abort
    (docs/RESILIENCE.md §Elastic membership): hand the wedged ranks to
    ``evict_fn``, which deregisters their elastic leases
    (``ElasticManager.evict_host``) so the next boundary membership poll
    confirms the death immediately (eviction bypasses the dead-check
    hysteresis) and the survivors re-shard and continue. A hung host
    costs one rollback-to-boundary instead of the job.

    ``evict_fn`` runs on the MONITOR thread; lease deletion is a KV op,
    safe under concurrent training — the re-shard itself happens at the
    training loop's next pass boundary, never here."""
    def action(wd: "StragglerWatchdog", reports, stalled_for: float):
        evict_fn(reports)
    action.escalation_name = name
    return action


def abort_with_checkpoint_action(save_fn: Callable[[], object],
                                 name: str = "abort_with_checkpoint"):
    """Escalation action factory: snapshot state (``save_fn``) and THEN
    arm the abort, so the StragglerTimeout the training thread sees on
    its next ``beat()`` loses no progress.

    ``save_fn`` runs on the MONITOR thread while the local training
    thread may still be mid-pass (e.g. when a *remote* rank is the
    straggler) — it must be safe under concurrent training: either
    snapshot pass-boundary state only (a CheckpointManager save of the
    last synced table is), or set a flag the training loop consumes at
    its next safe point rather than touching live trainer state."""
    def action(wd: "StragglerWatchdog", reports, stalled_for: float):
        try:
            save_fn()
        except Exception:
            log.error("escalation checkpoint save failed — aborting "
                      "without a fresh snapshot", exc_info=True)
        wd.arm_abort(reports, stalled_for)
    action.escalation_name = name
    return action


class StragglerWatchdog:
    def __init__(
        self,
        store,
        process_index: int,
        num_processes: int,
        step_lag: int = 100,
        heartbeat_timeout: float = 60.0,
        poll_interval: float = 5.0,
        abort_after: Optional[float] = None,
        on_straggler: Optional[Callable[[List[StragglerReport]], None]]
        = None,
        clock: Callable[[], float] = time.time,
        hub=None,
        escalations: Optional[List[Tuple[float, Callable]]] = None,
    ) -> None:
        """``clock`` is injectable so tests simulate stalls without
        sleeping; heartbeats carry this clock's timestamps, so every
        process of one job must use the same clock source.

        ``escalations`` is a ladder of ``(after_sec, action)`` rungs:
        once a stall has persisted ``after_sec`` seconds, ``action(wd,
        reports, stalled_for)`` fires (once per stall episode). Built-in
        actions: :func:`requeue_pass_action`,
        :func:`abort_with_checkpoint_action`, and :meth:`arm_abort`
        (what the legacy ``abort_after=`` shorthand installs). Every
        detection already logs + emits the ``straggler`` event, so the
        ladder only needs the *reactions*."""
        self.store = store
        self.process_index = process_index
        self.num_processes = num_processes
        self.step_lag = step_lag
        self.heartbeat_timeout = heartbeat_timeout
        self.poll_interval = poll_interval
        self.abort_after = abort_after
        self.on_straggler = on_straggler
        self.clock = clock
        self._hub = hub
        self._start_ts = clock()
        self._stall_since: Optional[float] = None
        self._abort_exc: Optional[StragglerTimeout] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.last_report: List[StragglerReport] = []
        self.escalations: List[Tuple[float, Callable]] = sorted(
            escalations or [], key=lambda e: e[0])
        if abort_after is not None:
            # legacy shorthand == top rung of the ladder
            def _abort(wd, reports, stalled):
                wd.arm_abort(reports, stalled)
            _abort.escalation_name = "abort"
            self.escalations.append((abort_after, _abort))
            self.escalations.sort(key=lambda e: e[0])
        self._fired_rungs: set = set()

    def _get_hub(self):
        if self._hub is None:
            from paddlebox_tpu.obs.hub import get_hub
            self._hub = get_hub()
        return self._hub

    # ---- producer side -------------------------------------------------
    def beat(self, step: int) -> None:
        """Publish this process's progress; call once per step-window or
        pass. Raises ``StragglerTimeout`` if the monitor armed an abort."""
        if self._abort_exc is not None:
            raise self._abort_exc
        self.store.publish(self.process_index, int(step), self.clock())
        hub = self._get_hub()
        if hub.active:
            hub.gauge("pbox_multihost_step",
                      "last heartbeat step per process").set(
                          int(step), process=self.process_index)

    # ---- monitor side --------------------------------------------------
    def check(self) -> List[StragglerReport]:
        """One detection sweep (pure given the store + clock — the unit
        the tests drive directly). Empty list == healthy mesh."""
        now = self.clock()
        beats = self.store.read()
        # restart hygiene: a reused heartbeat dir holds files from prior
        # runs — ranks beyond this mesh (elastic downsize) or beats that
        # predate this watchdog by more than the timeout. They must not
        # define the front-runner or report as stale: a restarted job
        # would otherwise chase a step count that only existed in the
        # old run's leftovers (and abort_after would kill it healthy).
        # A rank whose only file is pre-run leftover shows up as
        # "missing" after the grace window instead.
        fresh_floor = self._start_ts - self.heartbeat_timeout
        beats = {p: (s, t) for p, (s, t) in beats.items()
                 if p < self.num_processes and t >= fresh_floor}
        reports: List[StragglerReport] = []
        front = max((s for s, _ in beats.values()), default=0)
        for p in range(self.num_processes):
            if p not in beats:
                # a process that never published is only a straggler
                # once the mesh has had time to come up
                if beats and now - self._start_ts > self.heartbeat_timeout:
                    reports.append(StragglerReport(
                        p, -1, front, now - self._start_ts, "missing"))
                continue
            step, ts = beats[p]
            age = now - ts
            if front - step > self.step_lag:
                reports.append(StragglerReport(
                    p, step, front - step, age, "step_lag"))
            elif age > self.heartbeat_timeout:
                reports.append(StragglerReport(
                    p, step, front - step, age, "stale"))
        self.last_report = reports
        return reports

    def arm_abort(self, reports: List[StragglerReport],
                  stalled_for: float) -> None:
        """Final escalation rung: the training thread's NEXT ``beat()``
        raises StragglerTimeout (the safe place to abort — raising in
        the monitor thread would vanish). Idempotent."""
        if self._abort_exc is not None:
            return
        desc = "; ".join(
            f"proc {r.process}: {r.reason} (step={r.step}, "
            f"behind={r.behind}, age={r.age_sec:.1f}s)" for r in reports)
        self._abort_exc = StragglerTimeout(
            f"mesh stalled {stalled_for:.1f}s: {desc}")
        log.error("straggler watchdog: abort armed — next beat() "
                  "raises StragglerTimeout")
        hub = self._get_hub()
        if hub.active:
            hub.emit("straggler_abort",
                     stalled_for_sec=round(stalled_for, 3))

    def _handle(self, reports: List[StragglerReport]) -> None:
        now = self.clock()
        if not reports:
            self._stall_since = None
            self._fired_rungs.clear()  # next stall re-climbs the ladder
            return
        if self._stall_since is None:
            self._stall_since = now
        stalled_for = now - self._stall_since
        desc = "; ".join(
            f"proc {r.process}: {r.reason} (step={r.step}, "
            f"behind={r.behind}, age={r.age_sec:.1f}s)" for r in reports)
        log.warning("straggler watchdog: %s (stalled %.1fs)", desc,
                    stalled_for)
        hub = self._get_hub()
        if hub.active:
            hub.counter("pbox_straggler_events_total",
                        "straggler detections").inc()
            hub.emit("straggler", stalled_for_sec=round(stalled_for, 3),
                     stragglers=[r._asdict() for r in reports])
        if self.on_straggler is not None:
            self.on_straggler(reports)
        # climb the escalation ladder: each rung fires once per stall
        for i, (after_sec, action) in enumerate(self.escalations):
            if i in self._fired_rungs or stalled_for < after_sec:
                continue
            self._fired_rungs.add(i)
            name = getattr(action, "escalation_name",
                           getattr(action, "__name__", f"rung{i}"))
            log.warning("straggler escalation %r fired "
                        "(stalled %.1fs >= %.1fs)", name, stalled_for,
                        after_sec)
            if hub.active:
                hub.counter("pbox_straggler_escalations_total",
                            "escalation rungs fired").inc(action=name)
                hub.emit("straggler_escalation", action=name,
                         after_sec=after_sec,
                         stalled_for_sec=round(stalled_for, 3))
            try:
                # black-box seam (obs/flightrec): capture the mesh
                # state as each escalation rung fires, before the
                # action (abort/restart) mutates it
                from paddlebox_tpu.obs import flightrec
                flightrec.trigger(
                    "watchdog_escalation", reason=name, action=name,
                    after_sec=after_sec,
                    stalled_for_sec=round(stalled_for, 3))
            except Exception:
                log.debug("flightrec trigger failed", exc_info=True)
            try:
                action(self, reports, stalled_for)
            except Exception:
                log.error("straggler escalation %r failed", name,
                          exc_info=True)

    def poll_once(self) -> List[StragglerReport]:
        """check() + alerting/abort arming — one monitor iteration."""
        reports = self.check()
        self._handle(reports)
        return reports

    def start(self) -> "StragglerWatchdog":
        """Run the monitor loop in a daemon thread."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.poll_interval):
                try:
                    self.poll_once()
                except Exception:
                    log.warning("straggler watchdog poll failed",
                                exc_info=True)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="pbox-straggler-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
