"""Model-quality drift monitor — the observability half of feature
lifecycle scoring.

PaddleBox's production loop watches per-pass AUC/calibration and
slot-level feature health and alarms on drift (SURVEY §2.7
``metrics.h``, §5.4 ``delta_score``/``ctr_accessor``); crashes page
someone, quality regressions don't — so this monitor rides THE
per-pass telemetry seam (``obs.hub.emit_pass_event``) and turns each
train/stream pass into windowed drift verdicts:

- **key coverage/churn** — rows used, per-slot key counts, and the
  symmetric-difference churn fraction of the key set between passes;
- **embedding-norm drift** — mean |row| over a deterministic sample of
  used rows vs the trailing-window baseline;
- **CTR calibration** — predicted-vs-observed CTR overall
  (``predicted_ctr``/``actual_ctr`` off the pass AUC result) and per
  coarse prediction bucket (the 1e6-bin AUC tables collapsed into
  ``FLAGS.quality_calibration_buckets``, diffed between passes so each
  window is per-pass, not cumulative);
- **windowed AUC trend** — trailing-half vs leading-half mean over the
  window, with a degradation verdict when the drop exceeds
  ``FLAGS.quality_auc_drop``.

Everything lands as ``pbox_quality_*`` instruments plus one
``quality_window`` event per pass. Default-off
(``FLAGS.quality_window_passes=0``): the hook in ``emit_pass_event``
is one flag read; resident digest gates stay bit-identical.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional

import numpy as np

from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: deterministic embedding-norm sample cap (rows, sorted by feasign)
NORM_SAMPLE_ROWS = 2048
#: key-set churn is exact up to this many used rows, then falls back to
#: the table's staged/evicted delta counts
CHURN_EXACT_ROWS = 1 << 20


class QualityMonitor:
    """Trailing-window quality stats; one ``note_pass`` per pass."""

    def __init__(self, window: int, auc_drop: float = 0.01,
                 calib_buckets: int = 10) -> None:
        self.window = max(int(window), 2)
        self.auc_drop = float(auc_drop)
        self.calib_buckets = max(int(calib_buckets), 2)
        self._auc: collections.deque = collections.deque(
            maxlen=self.window)
        self._norm: collections.deque = collections.deque(
            maxlen=self.window)
        self._prev_keys: Optional[np.ndarray] = None
        self._prev_buckets: Optional[np.ndarray] = None  # [2, nbins]
        self.passes = 0

    # ---- per-pass ingestion --------------------------------------------
    def note_pass(self, ev: Dict, table=None, auc_state=None,
                  hub=None) -> Optional[Dict]:
        """Fold one pass event into the window; returns the
        ``quality_window`` payload (also emitted + mirrored into
        ``pbox_quality_*`` instruments when ``hub`` is active)."""
        if hub is None:
            from paddlebox_tpu.obs.hub import get_hub
            hub = get_hub()
        self.passes += 1
        out: Dict = {"pass_seq": ev.get("pass_seq"),
                     "global_step": ev.get("global_step"),
                     "window": self.window}
        auc = ev.get("auc")
        if auc is not None and not _isnan(auc):
            self._auc.append(float(auc))
        out.update(self._auc_trend())
        out.update(self._calibration(ev, auc_state))
        out.update(self._coverage(table))
        out.update(self._norm_drift(table))
        self._mirror(hub, out)
        if hub.active:
            hub.emit("quality_window", **out)
        return out

    # ---- windowed AUC trend --------------------------------------------
    def _auc_trend(self) -> Dict:
        if not self._auc:
            return {}
        vals = list(self._auc)
        mean = sum(vals) / len(vals)
        out = {"auc": vals[-1], "auc_window_mean": round(mean, 6)}
        if len(vals) >= 2:
            half = len(vals) // 2
            lead = sum(vals[:half]) / half
            trail = sum(vals[half:]) / (len(vals) - half)
            trend = trail - lead
            out["auc_trend"] = round(trend, 6)
            out["degraded"] = bool(trend < -self.auc_drop)
        else:
            out["degraded"] = False
        return out

    # ---- CTR calibration -----------------------------------------------
    def _calibration(self, ev: Dict, auc_state) -> Dict:
        out: Dict = {}
        pred = ev.get("predicted_ctr")
        actual = ev.get("actual_ctr")
        if pred is not None and actual is not None:
            out["predicted_ctr"] = round(float(pred), 6)
            out["actual_ctr"] = round(float(actual), 6)
            out["calibration_ratio"] = round(
                float(pred) / max(float(actual), 1e-9), 6)
        if auc_state is None:
            return out
        try:
            import jax
            pos = np.asarray(jax.device_get(auc_state.pos), np.float64)
            neg = np.asarray(jax.device_get(auc_state.neg), np.float64)
        except Exception:
            log.debug("quality: auc bucket fetch failed", exc_info=True)
            return out
        cur = np.stack([pos, neg])
        prev = self._prev_buckets
        self._prev_buckets = cur
        # diff vs the previous pass: the AUC tables are cumulative
        # until reset_metrics — the window must be per-pass
        delta = cur - prev if (prev is not None
                               and prev.shape == cur.shape) else cur
        delta = np.clip(delta, 0.0, None)   # reset_metrics between passes
        nbins = delta.shape[1]
        k = self.calib_buckets
        edges = np.linspace(0, nbins, k + 1).astype(np.int64)
        centers = (np.arange(nbins, dtype=np.float64) + 0.5) / nbins
        buckets: List[Dict] = []
        for i in range(k):
            sl = slice(edges[i], edges[i + 1])
            clicks = float(delta[0, sl].sum())
            imps = clicks + float(delta[1, sl].sum())
            if imps <= 0:
                continue
            w = delta[0, sl] + delta[1, sl]
            pred_b = float((centers[sl] * w).sum() / imps)
            buckets.append({"bucket": i,
                            "pred_ctr": round(pred_b, 6),
                            "observed_ctr": round(clicks / imps, 6),
                            "examples": imps})
        if buckets:
            out["calibration"] = buckets
        return out

    # ---- key coverage / churn ------------------------------------------
    def _coverage(self, table) -> Dict:
        out: Dict = {}
        stats = {}
        if table is not None and hasattr(table, "obs_stats"):
            try:
                stats = table.obs_stats()
            except Exception:
                log.debug("quality: obs_stats failed", exc_info=True)
        if "used" in stats:
            out["keys_used"] = int(stats["used"])
        index = getattr(table, "index", None)
        if index is None or not hasattr(index, "items"):
            return out
        try:
            keys, rows = index.items()
        except Exception:
            return out
        if len(keys) <= CHURN_EXACT_ROWS:
            cur = np.sort(np.asarray(keys, np.uint64))
            prev = self._prev_keys
            self._prev_keys = cur
            if prev is not None:
                inter = np.intersect1d(cur, prev,
                                       assume_unique=True).size
                churn = (cur.size - inter) + (prev.size - inter)
                out["key_churn_frac"] = round(
                    churn / max(cur.size, prev.size, 1), 6)
        else:
            lp = getattr(table, "last_pass_stats", None) or {}
            moved = float(lp.get("staged", 0)) + float(
                lp.get("evicted", 0))
            out["key_churn_frac"] = round(
                moved / max(float(len(keys)), 1.0), 6)
        slot_host = getattr(table, "slot_host", None)
        if slot_host is not None and len(rows):
            slots = np.asarray(slot_host)[np.asarray(rows)]
            uniq, counts = np.unique(slots, return_counts=True)
            out["slot_keys"] = {int(s): int(c)
                                for s, c in zip(uniq, counts)}
        return out

    # ---- embedding-norm drift ------------------------------------------
    def _norm_drift(self, table) -> Dict:
        index = getattr(table, "index", None)
        state = getattr(table, "state", None)
        if index is None or state is None \
                or not hasattr(index, "items") \
                or not hasattr(state, "data"):
            return {}
        try:
            keys, rows = index.items()
            if not len(rows):
                return {}
            # deterministic sample: the NORM_SAMPLE_ROWS smallest keys
            order = np.argsort(np.asarray(keys, np.uint64),
                               kind="stable")[:NORM_SAMPLE_ROWS]
            import jax
            data = np.asarray(jax.device_get(state.data))
            sample = data[np.asarray(rows)[order]]
            norm = float(np.abs(sample).mean())
        except Exception:
            log.debug("quality: norm sample failed", exc_info=True)
            return {}
        baseline = (sum(self._norm) / len(self._norm)
                    if self._norm else None)
        self._norm.append(norm)
        out = {"embed_norm": round(norm, 8)}
        if baseline is not None and baseline > 0:
            out["embed_norm_drift"] = round(
                (norm - baseline) / baseline, 6)
        return out

    # ---- instrument mirror ---------------------------------------------
    @staticmethod
    def _mirror(hub, out: Dict) -> None:
        g = hub.gauge
        if "auc" in out:
            g("pbox_quality_auc", "latest pass AUC").set(out["auc"])
            g("pbox_quality_auc_window_mean",
              "trailing-window mean AUC").set(out["auc_window_mean"])
        if "auc_trend" in out:
            g("pbox_quality_auc_trend",
              "trailing-half minus leading-half window AUC"
              ).set(out["auc_trend"])
        if "degraded" in out:
            g("pbox_quality_degraded",
              "1 while the windowed AUC trend breaches the "
              "degradation threshold").set(1.0 if out["degraded"]
                                           else 0.0)
        if "calibration_ratio" in out:
            g("pbox_quality_calibration_ratio",
              "windowed predicted/observed CTR"
              ).set(out["calibration_ratio"])
        for b in out.get("calibration", ()):
            g("pbox_quality_calibration_ctr",
              "per-bucket predicted vs observed CTR").set(
                  b["observed_ctr"], bucket=b["bucket"], kind="observed")
            g("pbox_quality_calibration_ctr",
              "per-bucket predicted vs observed CTR").set(
                  b["pred_ctr"], bucket=b["bucket"], kind="predicted")
        if "keys_used" in out:
            g("pbox_quality_keys_used",
              "embedding rows used at pass end").set(out["keys_used"])
        if "key_churn_frac" in out:
            g("pbox_quality_key_churn_frac",
              "key-set churn fraction vs previous pass"
              ).set(out["key_churn_frac"])
        for slot, n in (out.get("slot_keys") or {}).items():
            g("pbox_quality_slot_keys",
              "embedding rows per slot").set(n, slot=slot)
        if "embed_norm" in out:
            g("pbox_quality_embed_norm",
              "mean |w| over the deterministic row sample"
              ).set(out["embed_norm"])
        if "embed_norm_drift" in out:
            g("pbox_quality_embed_norm_drift",
              "relative embedding-norm drift vs the trailing baseline"
              ).set(out["embed_norm_drift"])


def _isnan(x) -> bool:
    try:
        return x != x
    except Exception:
        return False


# ---- module-level hook (emit_pass_event rides this) --------------------
_MONITOR: Optional[QualityMonitor] = None


def get_monitor() -> Optional[QualityMonitor]:
    return _MONITOR


def reset_monitor() -> None:
    global _MONITOR
    _MONITOR = None


def note_pass_event(ev: Dict, table=None, auc_state=None,
                    hub=None) -> None:
    """The pass-event hook: lazily build the monitor from FLAGS and
    fold the pass in. Callers (``emit_pass_event``) gate on
    ``FLAGS.quality_window_passes > 0`` — off costs one flag read."""
    global _MONITOR
    from paddlebox_tpu.config import FLAGS
    if _MONITOR is None or _MONITOR.window != max(
            int(FLAGS.quality_window_passes), 2):
        _MONITOR = QualityMonitor(
            FLAGS.quality_window_passes,
            auc_drop=FLAGS.quality_auc_drop,
            calib_buckets=FLAGS.quality_calibration_buckets)
    try:
        _MONITOR.note_pass(ev, table=table, auc_state=auc_state,
                           hub=hub)
    except Exception:
        # drift monitoring must never take the training loop down
        log.warning("quality monitor pass hook failed", exc_info=True)
