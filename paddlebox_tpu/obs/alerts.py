"""Declarative SLO alert engine over hub instruments.

Rules — threshold / absence / trend — evaluate against the live
``TelemetryHub`` instruments on a cadence thread (or explicitly via
``evaluate_once`` in gates) and drive:

- ``pbox_alerts_active{rule,severity}`` — 1 while firing, 0 after the
  clear (the gauge IS the alarm surface a scraper watches);
- ``alert_fired`` / ``alert_cleared`` events with the observed value
  and threshold;
- the ``/alertz`` route and the ``alerts`` block in ``/healthz``
  (``AlertEngine.status``, registered as the hub's alerts probe);
- the flight recorder: a firing rule is an SLO breach — it fires the
  ``slo_breach`` trigger (per-trigger debounce collapses rule storms
  into one bundle per window).

Rule grammar (docs/OBSERVABILITY.md §Alerts):

- ``threshold`` — sample the metric (counters/gauges: sum of the
  series matching ``labels`` as a subset; histograms: ``quantile`` of
  the exact ``labels`` series) and breach when ``op(sample, value)``;
- ``absence`` — breach when the metric has no samples at all (a
  heartbeat instrument that should exist but doesn't);
- ``trend`` — keep the last ``trend_window`` samples (one per
  evaluation) and breach when ``op(newest - oldest, value)`` — e.g.
  ``>`` 0 fires on ANY increase of a monotone counter between
  evaluations and clears once it goes flat.

``for_count``/``clear_count`` debounce flapping: a rule needs that many
consecutive breaching/clean evaluations to transition. Default rules
(``default_rules``): serving staleness, serving p99, stream lag,
pipeline hang, NaN-rollback rate, AUC degradation.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Deque, Dict, List, Optional

from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

_OPS = {
    ">": lambda a, b: a > b,
    "<": lambda a, b: a < b,
    ">=": lambda a, b: a >= b,
    "<=": lambda a, b: a <= b,
}


@dataclasses.dataclass
class Rule:
    name: str
    metric: str
    kind: str = "threshold"            # threshold | absence | trend
    severity: str = "warn"             # warn | critical
    op: str = ">"
    value: float = 0.0
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    quantile: Optional[float] = None   # histograms only
    trend_window: int = 2              # samples kept for kind=trend
    for_count: int = 1                 # consecutive breaches to fire
    clear_count: int = 1               # consecutive oks to clear
    help: str = ""

    def __post_init__(self) -> None:
        if self.kind not in ("threshold", "absence", "trend"):
            raise ValueError(f"rule {self.name}: unknown kind "
                             f"{self.kind!r}")
        if self.op not in _OPS:
            raise ValueError(f"rule {self.name}: unknown op {self.op!r}")


class _RuleState:
    __slots__ = ("active", "breaches", "oks", "history", "last_value",
                 "since")

    def __init__(self) -> None:
        self.active = False
        self.breaches = 0
        self.oks = 0
        self.history: Deque[float] = collections.deque()
        self.last_value: Optional[float] = None
        self.since: Optional[float] = None


class AlertEngine:
    """Evaluate rules over one hub; fire/clear with hysteresis."""

    def __init__(self, hub=None, rules: Optional[List[Rule]] = None,
                 clock=time.time) -> None:
        if hub is None:
            from paddlebox_tpu.obs.hub import get_hub
            hub = get_hub()
        self.hub = hub
        self.clock = clock
        self.rules: List[Rule] = []
        self._state: Dict[str, _RuleState] = {}
        self._lock = threading.Lock()
        self._evals = 0
        self._fired_total = 0
        self._last_eval_ts: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        for r in rules or ():
            self.add_rule(r)

    def add_rule(self, rule: Rule) -> "AlertEngine":
        with self._lock:
            if any(r.name == rule.name for r in self.rules):
                raise ValueError(f"duplicate alert rule {rule.name!r}")
            self.rules.append(rule)
            self._state[rule.name] = _RuleState()
        return self

    # ---- sampling ------------------------------------------------------
    def _sample(self, rule: Rule) -> Optional[float]:
        """Observe one value for ``rule`` (None == no samples)."""
        with self.hub._lock:
            inst = self.hub._instruments.get(rule.metric)
        if inst is None:
            return None
        if inst.kind == "histogram":
            if inst.series():
                q = rule.quantile if rule.quantile is not None else 0.99
                return inst.quantile(q, **rule.labels)
            return None
        # counters/gauges: sum every series whose labels contain the
        # rule's labels as a subset — a rule over a labeled counter
        # (e.g. pbox_pipeline_hangs_total{stage=...}) watches the total
        want = set((k, str(v)) for k, v in rule.labels.items())
        total, seen = 0.0, False
        for key, val in inst.series():
            if want <= set(key):
                total += float(val)
                seen = True
        return total if seen else None

    # ---- evaluation ----------------------------------------------------
    def evaluate_once(self) -> List[Dict]:
        """One evaluation sweep. Returns the transitions
        (``[{rule, severity, to, value, threshold}]``) and updates
        gauges/events; safe to call concurrently with the cadence
        thread (rule state is lock-protected)."""
        transitions: List[Dict] = []
        now = self.clock()
        with self._lock:
            rules = list(self.rules)
        for rule in rules:
            sample = self._sample(rule)
            with self._lock:
                st = self._state[rule.name]
                breach, value = self._judge(rule, st, sample)
                st.last_value = value
                if breach:
                    st.breaches += 1
                    st.oks = 0
                    if (not st.active
                            and st.breaches >= rule.for_count):
                        st.active = True
                        st.since = now
                        self._fired_total += 1
                        transitions.append(self._transition(
                            rule, "fired", value))
                else:
                    st.oks += 1
                    st.breaches = 0
                    if st.active and st.oks >= rule.clear_count:
                        st.active = False
                        st.since = None
                        transitions.append(self._transition(
                            rule, "cleared", value))
        with self._lock:
            self._evals += 1
            self._last_eval_ts = now
        # mirror EVERY rule's state each sweep (not just transitions):
        # dashboards must see an explicit 0 for a healthy rule — an
        # absent series is indistinguishable from an engine that
        # never ran
        gauge = self.hub.gauge(
            "pbox_alerts_active",
            "1 while the alert rule is firing, 0 when clear")
        with self._lock:
            states = [(r, self._state[r.name].active) for r in rules]
        for rule, active in states:
            gauge.set(1.0 if active else 0.0, rule=rule.name,
                      severity=rule.severity)
        for tr in transitions:
            self._publish(tr)
        return transitions

    @staticmethod
    def _judge(rule: Rule, st: _RuleState, sample: Optional[float]):
        """(breach?, observed value) for one rule given its sample."""
        if rule.kind == "absence":
            return sample is None, (0.0 if sample is None else sample)
        if sample is None:
            return False, None
        if rule.kind == "trend":
            st.history.append(sample)
            while len(st.history) > max(rule.trend_window, 2):
                st.history.popleft()
            if len(st.history) < 2:
                return False, 0.0
            delta = st.history[-1] - st.history[0]
            return _OPS[rule.op](delta, rule.value), delta
        return _OPS[rule.op](sample, rule.value), sample

    def _transition(self, rule: Rule, to: str, value) -> Dict:
        return {"rule": rule.name, "severity": rule.severity, "to": to,
                "value": value, "threshold": rule.value,
                "metric": rule.metric}

    #: rules whose fires are world-membership changes, not SLO breaches
    _MEMBERSHIP_RULE_NAMES = ("rank_dead", "world_degraded")

    def _publish(self, tr: Dict) -> None:
        hub = self.hub
        fired = tr["to"] == "fired"
        hub.gauge("pbox_alerts_active",
                  "1 per firing alert rule").set(
                      1.0 if fired else 0.0,
                      rule=tr["rule"], severity=tr["severity"])
        if fired:
            hub.counter("pbox_alerts_fired_total",
                        "alert rule fire transitions").inc(
                            rule=tr["rule"])
            log.error("ALERT fired: %s (%s) %s=%s threshold=%s",
                      tr["rule"], tr["severity"], tr["metric"],
                      tr["value"], tr["threshold"])
        else:
            log.warning("alert cleared: %s (%s=%s)", tr["rule"],
                        tr["metric"], tr["value"])
        if hub.active:
            hub.emit("alert_fired" if fired else "alert_cleared",
                     rule=tr["rule"], severity=tr["severity"],
                     metric=tr["metric"], value=tr["value"],
                     threshold=tr["threshold"])
        if fired:
            # every firing rule IS an SLO breach — flight-recorder
            # debounce collapses storms into one bundle per window.
            # Membership rules route to their own trigger so a world
            # change and a concurrent SLO breach each get a bundle.
            from paddlebox_tpu.obs import flightrec
            trigger = ("membership_change"
                       if tr["rule"] in self._MEMBERSHIP_RULE_NAMES
                       else "slo_breach")
            flightrec.trigger(trigger,
                              reason=f"alert {tr['rule']}",
                              rule=tr["rule"], severity=tr["severity"],
                              metric=tr["metric"], value=tr["value"],
                              threshold=tr["threshold"])

    # ---- surfaces ------------------------------------------------------
    def active(self) -> List[Dict]:
        with self._lock:
            out = []
            for rule in self.rules:
                st = self._state[rule.name]
                if st.active:
                    out.append({"rule": rule.name,
                                "severity": rule.severity,
                                "metric": rule.metric,
                                "value": st.last_value,
                                "threshold": rule.value,
                                "since": st.since})
            return out

    def status(self) -> Dict:
        """The ``alerts`` block for /healthz and the /alertz payload."""
        with self._lock:
            rules = [{"rule": r.name, "kind": r.kind,
                      "severity": r.severity, "metric": r.metric,
                      "threshold": r.value,
                      "active": self._state[r.name].active,
                      "value": self._state[r.name].last_value}
                     for r in self.rules]
            evals, fired = self._evals, self._fired_total
            last = self._last_eval_ts
        act = [r for r in rules if r["active"]]
        return {"firing": len(act), "active": act, "rules": rules,
                "evaluations": evals, "fired_total": fired,
                "last_eval_ts": last}

    # ---- cadence thread ------------------------------------------------
    def start(self, interval_sec: float) -> "AlertEngine":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(interval_sec):
                try:
                    self.evaluate_once()
                except Exception:
                    log.error("alert evaluation failed", exc_info=True)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="pbox-alerts")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)


def default_rules() -> List[Rule]:
    """The shipped rule set (docs/OBSERVABILITY.md §Alerts)."""
    from paddlebox_tpu.config import FLAGS
    return [
        Rule("serving_staleness", "pbox_serving_staleness_sec",
             kind="threshold", severity="critical", op=">",
             value=float(FLAGS.serving_staleness_max_sec),
             help="serving snapshot older than the staleness SLO"),
        Rule("serving_p99", "pbox_serving_latency_seconds",
             kind="threshold", severity="critical", op=">",
             value=float(FLAGS.alerts_serving_p99_ms) / 1e3,
             labels={"op": "predict"}, quantile=0.99,
             help="predict p99 over the latency SLO"),
        Rule("stream_lag", "pbox_stream_lag_files",
             kind="threshold", severity="warn", op=">",
             value=float(FLAGS.alerts_stream_lag_files),
             help="stream backlog growing faster than training"),
        Rule("pipeline_hang", "pbox_pipeline_hangs_total",
             kind="trend", severity="critical", op=">", value=0.0,
             help="a pipeline wait hit the hang deadline since the "
                  "last evaluation"),
        Rule("nan_rollback", "pbox_nan_rollbacks_total",
             kind="trend", severity="critical", op=">", value=0.0,
             help="a NaN rollback happened since the last evaluation"),
        Rule("auc_degradation", "pbox_quality_degraded",
             kind="threshold", severity="critical", op=">", value=0.5,
             help="windowed AUC trend breached the degradation "
                  "threshold (obs/quality)"),
        # online-daemon lifecycle rules (docs/ONLINE.md): each fire is
        # a flight-recorder trigger like every other rule (_publish)
        Rule("shrink_overdue", "pbox_online_windows_since_shrink",
             kind="threshold", severity="warn", op=">",
             value=float(FLAGS.alerts_shrink_overdue_windows
                         or 2 * max(1, FLAGS.shrink_every_windows)),
             help="feature-lifecycle shrink cycles stopped firing — "
                  "the key space is growing unbounded"),
        Rule("backlog_growth", "pbox_stream_lag_files",
             kind="trend", severity="warn", op=">", value=0.0,
             trend_window=3, for_count=3,
             help="stream backlog rose across three consecutive "
                  "evaluations — ingest is outrunning training"),
        # elastic-membership rules (docs/RESILIENCE.md §Elastic
        # membership): routed to the membership_change flight-recorder
        # trigger in _publish
        Rule("rank_dead", "pbox_membership_scale_events_total",
             kind="trend", severity="critical", op=">", value=0.0,
             labels={"direction": "lost"},
             help="a rank left the effective membership since the "
                  "last evaluation (TTL expiry or watchdog eviction)"),
        Rule("world_degraded", "pbox_membership_degraded",
             kind="threshold", severity="warn", op=">", value=0.5,
             help="effective membership below the target np — the job "
                  "is running shrunk until the lost ranks rejoin"),
    ]


# ---- module-level engine (configure_from_flags) ------------------------
_ENGINE: Optional[AlertEngine] = None


def get_engine() -> Optional[AlertEngine]:
    return _ENGINE


def install_engine(engine: Optional[AlertEngine],
                   register_probe: bool = True) -> Optional[AlertEngine]:
    """Install the process alert engine (None uninstalls + stops) and
    register its ``status`` as the hub's alerts probe."""
    global _ENGINE
    if _ENGINE is not None and _ENGINE is not engine:
        _ENGINE.stop()
    _ENGINE = engine
    from paddlebox_tpu.obs.hub import get_hub
    if register_probe:
        get_hub().set_alerts_probe(
            engine.status if engine is not None else None)
    return engine


def configure_from_flags() -> Optional[AlertEngine]:
    """Start the default-rule engine on the flag cadence (idempotent;
    called from ``obs.hub.configure_from_flags``)."""
    from paddlebox_tpu.config import FLAGS
    if FLAGS.alerts_eval_interval_sec <= 0:
        return _ENGINE
    if _ENGINE is not None:
        return _ENGINE
    engine = AlertEngine(rules=default_rules())
    install_engine(engine)
    engine.start(FLAGS.alerts_eval_interval_sec)
    return engine
