"""Event/span sinks for the TelemetryHub.

Event sinks receive one dict per emitted event (pass summaries,
watchdog alerts, warmup outcomes...); span sinks receive completed
timed spans. ``JsonlSink`` is the structured-log backend (one JSON
object per line, flushed per event — events fire at pass granularity,
not per batch, so durability beats buffering); ``MemorySink`` backs
tests; ``ChromeSpanSink`` adapts the existing
``utils.profiler.ChromeTraceWriter`` so hub spans land in the same
chrome://tracing timeline as StageTimers stages.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional


class JsonlSink:
    """Append one JSON line per event to ``path``.

    With ``max_bytes > 0`` the live segment rotates logrotate-style
    once it reaches that size: ``path`` → ``path.1``, older segments
    shift to ``path.2`` … ``path.<keep>``, anything beyond ``keep`` is
    dropped — an always-on daemon's event log stays bounded at roughly
    ``(keep + 1) * max_bytes``. ``scripts/telemetry_report.py`` reads a
    rotated set back oldest-first automatically."""

    def __init__(self, path: str, truncate: bool = False,
                 max_bytes: int = 0, keep: int = 3) -> None:
        self.path = path
        self.max_bytes = int(max_bytes)
        self.keep = max(int(keep), 1)
        self._lock = threading.Lock()
        self._fh = open(path, "w" if truncate else "a")

    def emit(self, event: Dict) -> None:
        line = json.dumps(event, default=str)
        with self._lock:
            if self._fh.closed:
                return
            self._fh.write(line + "\n")
            self._fh.flush()
            if self.max_bytes > 0 \
                    and self._fh.tell() >= self.max_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Shift ``path.i`` → ``path.i+1`` (dropping past ``keep``),
        move the live file to ``path.1`` and reopen fresh. Rename
        failures leave the sink appending to the live file — rotation
        is best-effort, losing events is not an option."""
        try:
            self._fh.close()
            last = f"{self.path}.{self.keep}"
            if os.path.exists(last):
                os.unlink(last)
            for i in range(self.keep - 1, 0, -1):
                seg = f"{self.path}.{i}"
                if os.path.exists(seg):
                    os.replace(seg, f"{self.path}.{i + 1}")
            os.replace(self.path, f"{self.path}.1")
        except OSError:
            pass
        self._fh = open(self.path, "a")

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()


class MemorySink:
    """In-process event buffer (tests, REPL inspection)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.events: List[Dict] = []

    def emit(self, event: Dict) -> None:
        with self._lock:
            self.events.append(event)

    def close(self) -> None:
        pass


class ChromeSpanSink:
    """Span sink → ChromeTraceWriter: hub spans render as X events on
    the same host-orchestration timeline as StageTimers stages. Pass an
    explicit writer, or None to follow whatever writer is installed via
    ``utils.profiler.set_chrome_trace`` at span time."""

    def __init__(self, writer=None) -> None:
        self._writer = writer

    def span(self, name: str, start_s: float, dur_s: float,
             attrs: Optional[Dict] = None) -> None:
        w = self._writer
        if w is None:
            from paddlebox_tpu.utils.profiler import chrome_trace
            w = chrome_trace()
        if w is not None:
            w.complete(name, start_s, dur_s, **(attrs or {}))

    def close(self) -> None:
        pass
