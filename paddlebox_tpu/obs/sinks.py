"""Event/span sinks for the TelemetryHub.

Event sinks receive one dict per emitted event (pass summaries,
watchdog alerts, warmup outcomes...); span sinks receive completed
timed spans. ``JsonlSink`` is the structured-log backend (one JSON
object per line, flushed per event — events fire at pass granularity,
not per batch, so durability beats buffering); ``MemorySink`` backs
tests; ``ChromeSpanSink`` adapts the existing
``utils.profiler.ChromeTraceWriter`` so hub spans land in the same
chrome://tracing timeline as StageTimers stages.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional


class JsonlSink:
    """Append one JSON line per event to ``path``."""

    def __init__(self, path: str, truncate: bool = False) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._fh = open(path, "w" if truncate else "a")

    def emit(self, event: Dict) -> None:
        line = json.dumps(event, default=str)
        with self._lock:
            if self._fh.closed:
                return
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()


class MemorySink:
    """In-process event buffer (tests, REPL inspection)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.events: List[Dict] = []

    def emit(self, event: Dict) -> None:
        with self._lock:
            self.events.append(event)

    def close(self) -> None:
        pass


class ChromeSpanSink:
    """Span sink → ChromeTraceWriter: hub spans render as X events on
    the same host-orchestration timeline as StageTimers stages. Pass an
    explicit writer, or None to follow whatever writer is installed via
    ``utils.profiler.set_chrome_trace`` at span time."""

    def __init__(self, writer=None) -> None:
        self._writer = writer

    def span(self, name: str, start_s: float, dur_s: float,
             attrs: Optional[Dict] = None) -> None:
        w = self._writer
        if w is None:
            from paddlebox_tpu.utils.profiler import chrome_trace
            w = chrome_trace()
        if w is not None:
            w.complete(name, start_s, dur_s, **(attrs or {}))

    def close(self) -> None:
        pass
