"""Typed telemetry instruments: Counter / Gauge / Histogram.

The reference system's monitors are an untyped int64 registry
(``StatRegistry``/``STAT_ADD``, platform/monitor.h:80) plus ad-hoc
per-stage timers. These instruments put a type system on top — monotone
counters, set-style gauges (with a high-watermark helper for HBM/queue
peaks), and fixed-bucket histograms — so one snapshot can render as
structured JSON or Prometheus text exposition (obs/hub.py).

Every instrument is thread-safe and label-aware: a labelless update
writes the ``()`` series; keyword labels key independent series
(``counter.inc(3, shard="0")``). Label values are stringified at update
time so snapshots are stable.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Instrument:
    """Base: name, help text, per-labelset series under one lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()

    def series(self) -> List[Tuple[LabelKey, object]]:
        raise NotImplementedError


class Counter(Instrument):
    """Monotone float counter (STAT_ADD with labels)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "") -> None:
        super().__init__(name, help)
        self._values: Dict[LabelKey, float] = {}

    def inc(self, value: float = 1.0, **labels: object) -> None:
        if value < 0:
            raise ValueError(f"counter {self.name}: negative inc {value}")
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def value(self, **labels: object) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def series(self) -> List[Tuple[LabelKey, float]]:
        with self._lock:
            return sorted(self._values.items())


class Gauge(Instrument):
    """Last-value gauge; ``set_max`` keeps running high watermarks."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "") -> None:
        super().__init__(name, help)
        self._values: Dict[LabelKey, float] = {}

    def set(self, value: float, **labels: object) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, value: float = 1.0, **labels: object) -> None:
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def set_max(self, value: float, **labels: object) -> None:
        """Watermark update: keep max(current, value)."""
        k = _label_key(labels)
        with self._lock:
            cur = self._values.get(k)
            if cur is None or value > cur:
                self._values[k] = float(value)

    def value(self, **labels: object) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def series(self) -> List[Tuple[LabelKey, float]]:
        with self._lock:
            return sorted(self._values.items())


# seconds-oriented default ladder (stage/pass timings span ms..minutes)
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class _HistSeries:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, nbuckets: int) -> None:
        self.counts = [0] * nbuckets  # cumulative at export, raw here
        self.sum = 0.0
        self.count = 0


class Histogram(Instrument):
    """Fixed-bucket histogram (Prometheus ``le`` semantics at export:
    bucket counts are cumulative, ``+Inf`` == count)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Sequence[float]] = None) -> None:
        super().__init__(name, help)
        bs = tuple(sorted(buckets)) if buckets else DEFAULT_BUCKETS
        if not bs:
            raise ValueError(f"histogram {name}: empty buckets")
        self.buckets = bs
        self._series: Dict[LabelKey, _HistSeries] = {}

    def observe(self, value: float, **labels: object) -> None:
        import bisect
        k = _label_key(labels)
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            s = self._series.get(k)
            if s is None:
                s = self._series[k] = _HistSeries(len(self.buckets))
            if i < len(self.buckets):
                s.counts[i] += 1
            s.sum += value
            s.count += 1

    def snapshot(self, **labels: object) -> Dict[str, object]:
        """{buckets: {le: cumulative_count}, sum, count} for one series."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None:
                return {"buckets": {}, "sum": 0.0, "count": 0}
            cum, acc = {}, 0
            for le, c in zip(self.buckets, s.counts):
                acc += c
                cum[le] = acc
            return {"buckets": cum, "sum": s.sum, "count": s.count}

    def series(self) -> List[Tuple[LabelKey, _HistSeries]]:
        with self._lock:
            return sorted(self._series.items(), key=lambda kv: kv[0])

    @staticmethod
    def _quantile_from_counts(buckets: Sequence[float], counts,
                              count: int, q: float) -> float:
        """Estimate quantile ``q`` from fixed-bucket counts the way
        Prometheus's ``histogram_quantile`` does: find the bucket the
        target rank lands in and interpolate linearly inside it. Ranks
        past the last finite bucket clamp to its upper bound (the +Inf
        bucket has no width to interpolate over)."""
        if count <= 0:
            return 0.0
        rank = q * count
        cum = 0
        lo = 0.0
        for le, c in zip(buckets, counts):
            if cum + c >= rank and c > 0:
                return lo + (le - lo) * (rank - cum) / c
            cum += c
            lo = le
        return float(buckets[-1])

    def quantile(self, q: float, **labels: object) -> float:
        """Interpolated quantile (0 < q <= 1) of one series; 0.0 for an
        empty series. Accuracy is bucket-bounded — pick buckets that
        bracket the latencies you care about (SERVING_LATENCY_BUCKETS
        for the serving path)."""
        if not 0.0 < q <= 1.0:
            raise ValueError(f"histogram {self.name}: quantile {q} "
                             "outside (0, 1]")
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None:
                return 0.0
            return self._quantile_from_counts(self.buckets, s.counts,
                                              s.count, q)


#: quantiles every histogram exports in the Prometheus text format
#: (scrapeable p50/p90/p99 without server-side histogram_quantile —
#: the serving latency SLO lines; docs/SERVING.md)
EXPORT_QUANTILES: Tuple[float, ...] = (0.5, 0.9, 0.99)

#: serving-latency ladder: batched CPU/TPU lookups + dense forwards sit
#: in the 100µs..100ms band the default seconds ladder cannot resolve
SERVING_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5)


def escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote and newline must be escaped or the exposition line is
    unparseable (label values are arbitrary strings — error reprs,
    file paths — by the time they reach a series key)."""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def iter_prom_lines(inst: Instrument) -> Iterator[str]:
    """Prometheus text-exposition lines for one instrument."""

    def fmt_labels(k: LabelKey, extra: str = "") -> str:
        parts = [f'{n}="{escape_label_value(v)}"' for n, v in k]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    if inst.help:
        yield f"# HELP {inst.name} {inst.help}"
    yield f"# TYPE {inst.name} {inst.kind}"
    if isinstance(inst, Histogram):
        series = inst.series()
        for k, s in series:
            acc = 0
            for le, c in zip(inst.buckets, s.counts):
                acc += c
                le_lbl = 'le="%s"' % le
                yield f"{inst.name}_bucket{fmt_labels(k, le_lbl)} {acc}"
            inf_lbl = 'le="+Inf"'
            yield f"{inst.name}_bucket{fmt_labels(k, inf_lbl)} {s.count}"
            yield f"{inst.name}_sum{fmt_labels(k)} {s.sum}"
            yield f"{inst.name}_count{fmt_labels(k)} {s.count}"
        # interpolated p50/p90/p99 as a SIBLING gauge family
        # (`<name>_quantile`) so dashboards scrape latency SLOs without
        # server-side histogram_quantile. A separate declared family on
        # purpose: bare-name quantile samples are summary-type syntax,
        # and strict parsers reject them inside a histogram family.
        if series:
            yield f"# TYPE {inst.name}_quantile gauge"
            for k, s in series:
                for q in EXPORT_QUANTILES:
                    v = Histogram._quantile_from_counts(
                        inst.buckets, s.counts, s.count, q)
                    q_lbl = 'quantile="%s"' % q
                    yield (f"{inst.name}_quantile"
                           f"{fmt_labels(k, q_lbl)} {v:g}")
    else:
        for k, v in inst.series():
            yield f"{inst.name}{fmt_labels(k)} {v}"
