"""Causal span tracing over the async pass pipeline (ISSUE 10).

PRs 4-8 turned every pass into a 4-deep concurrent machine — preloader
worker builds k+2, stage queue wires k+1, main thread trains k, the
epilogue lane drains k-1's write-back plus eviction and SSD demotion —
but the PR 1 telemetry still saw it as main-thread stage timers plus
counters. This module adds the missing CAUSAL view:

**Spans.** ``span(name, ...)`` times a region and emits a record
carrying ``(trace_id=run, pass_seq, span_id, parent_id, lane)`` to the
hub's span sinks. ``lane`` names the EXECUTING context — the catalog:

    main            the training/driver thread
    preload.worker  the depth-N PassPreloader worker (build + stage)
    epilogue.lane   the PassEpilogue single-lane write-back worker
    ssd.compact     SSD watermark demotion + segment compaction (rides
                    the epilogue worker, rendered as its own service row)
    stream.reader   dataset reader threads

Parent ids nest automatically per thread (a ``pass.stage`` span opened
inside a ``pass.build`` span becomes its child). Cross-thread causality
uses explicit LINKS: the producer stashes its span id (e.g. the build
span's id rides the built pass as ``rp._trace_span_id``), and the
consumer opens its span with ``link_from=that_id`` — the Chrome sink
renders the link as a flow arrow from the source span's end to the
linked span's start, across lane rows.

**Inert-when-off.** Every entry point guards on the same contract as
the hub (``hub.active`` + a span sink attached): with no sinks the
span() context manager is two attribute reads and yields a shared null
handle — default-off tracing costs nothing measurable per pass.

**Chrome rendering.** ``ChromeLaneTraceSink`` writes spans into a
``utils.profiler.ChromeTraceWriter`` with one STABLE tid row per lane
(thread-name metadata events name the rows) and flow ("s"/"f") events
for links — chrome://tracing / Perfetto shows the four-deep pipeline as
four labeled lanes with arrows from each pass's preloader build to its
main-thread consume.

**Critical path.** The pass drivers report each boundary stall into a
per-pass accumulator (``note_pass_part``); ``emit_pass_event`` consumes
it and attaches a ``critical_path`` block — wall time attributed across
train vs build-wait vs stage-wait vs fence-wait vs ssd-promote vs
evict-emergency — plus a per-pass ``bottleneck`` verdict, mirrored into
``pbox_pass_bottleneck_total{stage}``. Completed top-level spans
accumulate ``pbox_lane_busy_seconds_total{lane}``.
``scripts/telemetry_report.py`` renders the per-pass verdicts and the
whole-run summary ("7/8 passes device-bound, pass 2 build-bound").

See docs/OBSERVABILITY.md §Tracing for the span schema and the lane /
flow-link semantics.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict
from typing import Dict, Iterator, Optional

from paddlebox_tpu.obs.hub import get_hub
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: the lane catalog (docs/OBSERVABILITY.md §Tracing). Free-form lane
#: names are legal; these are the rows the shipped pipeline uses.
LANE_MAIN = "main"
LANE_PRELOAD = "preload.worker"
LANE_EPILOGUE = "epilogue.lane"
LANE_SSD = "ssd.compact"
LANE_READER = "stream.reader"
#: device-side exchange/compute attribution (ISSUE 11): the sharded
#: step's chunked embedding all_to_alls and their interleaved pooling,
#: measured by the decomposed probe (train/a2a_probe) — spans are
#: ``a2a.pull.<k>`` / ``pool.<k>`` / ``a2a.push`` on this row
LANE_DEVICE = "device.a2a"
#: per-kernel device attribution (ISSUE 12): the embed-pool-CVM kernel
#: family measured by the kernel microbench
#: (scripts/profile_keypath.py --set kernels) — spans are
#: ``kernel.{gather,pool_cvm,fused}[. _xla]`` on this row, one per
#: timed probe re-run, so a trace shows Pallas vs XLA cost side by side
LANE_KERNELS = "device.kernels"

_TLS = threading.local()   # .lane: str, .stack: List[int] (open span ids)
_ID_LOCK = threading.Lock()
_NEXT_ID = 1


def _new_span_id() -> int:
    global _NEXT_ID
    with _ID_LOCK:
        sid = _NEXT_ID
        _NEXT_ID += 1
    return sid


def tracing_active() -> bool:
    """True iff spans would actually be recorded: the hub is active AND
    at least one span sink is attached (the inert-when-off guard every
    span call site shares)."""
    hub = get_hub()
    return hub.active and bool(hub._span_sinks)


# ---- lanes -------------------------------------------------------------
def current_lane() -> str:
    """The calling thread's lane; defaults to ``main`` on the main
    thread and the thread's name elsewhere (workers that matter set
    their lane explicitly — PassPreloader, PassEpilogue, readers)."""
    lane = getattr(_TLS, "lane", None)
    if lane is not None:
        return lane
    t = threading.current_thread()
    return LANE_MAIN if t is threading.main_thread() else t.name


def set_lane(lane: str) -> None:
    """Pin the calling thread's lane for its lifetime (worker-thread
    entry points call this once at start)."""
    _TLS.lane = lane


@contextlib.contextmanager
def lane_scope(lane: str) -> Iterator[None]:
    """Temporarily relabel the calling thread's lane — e.g. the SSD
    demote/compact slot rides the epilogue worker but renders as the
    ``ssd.compact`` service row."""
    prev = getattr(_TLS, "lane", None)
    _TLS.lane = lane
    try:
        yield
    finally:
        _TLS.lane = prev


# ---- spans -------------------------------------------------------------
class SpanHandle:
    """What ``span()`` yields: enough identity for cross-thread links
    (stash ``span_id`` on the object crossing threads and pass it as the
    consumer span's ``link_from``)."""

    __slots__ = ("span_id", "lane", "name")

    def __init__(self, span_id: int, lane: str, name: str) -> None:
        self.span_id = span_id
        self.lane = lane
        self.name = name


#: shared null handle: the no-sink fast path allocates nothing
NULL_SPAN = SpanHandle(0, "", "")


def current_span_id() -> int:
    """The calling thread's innermost OPEN span id (0 when none) — the
    producer-side id for a cross-thread link created mid-span (e.g.
    end_pass links its submit span to the epilogue job it enqueues)."""
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else 0


@contextlib.contextmanager
def span(name: str, pass_seq: Optional[int] = None,
         lane: Optional[str] = None, link_from: int = 0,
         **attrs) -> Iterator[SpanHandle]:
    """Timed causal span → the hub's span sinks. Inert without sinks
    (yields ``NULL_SPAN``). ``link_from`` names a producer span on
    another thread; rich sinks render it as a flow arrow. Attrs ride the
    record (small, JSON-able values only)."""
    hub = get_hub()
    sinks = hub._span_sinks
    if not (hub.active and sinks):
        yield NULL_SPAN
        return
    ln = lane or current_lane()
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    parent = stack[-1] if stack else 0
    sid = _new_span_id()
    handle = SpanHandle(sid, ln, name)
    stack.append(sid)
    t0 = time.perf_counter()
    try:
        yield handle
    finally:
        dur = time.perf_counter() - t0
        stack.pop()
        rec = {"name": name, "span_id": sid, "parent_id": parent,
               "lane": ln, "trace_id": hub.run_id, "t0": t0, "dur": dur,
               "link_from": link_from}
        if pass_seq is not None:
            rec["pass_seq"] = pass_seq
        if attrs:
            rec["attrs"] = attrs
        for s in sinks:
            try:
                full = getattr(s, "span_full", None)
                if full is not None:
                    full(rec)
                else:
                    plain = dict(attrs)
                    plain["lane"] = ln
                    if pass_seq is not None:
                        plain["pass_seq"] = pass_seq
                    s.span(name, t0, dur, plain)
            except Exception:
                log.warning("trace span sink failed", exc_info=True)
        if parent == 0:
            # lane occupancy counts TOP-LEVEL spans only (children are
            # contained in their parent's wall — counting both would
            # double-book the lane)
            hub.counter("pbox_lane_busy_seconds_total",
                        "seconds each pipeline lane spent in top-level "
                        "spans").inc(dur, lane=ln)


# ---- Chrome sink: per-lane rows + flow arrows --------------------------
class ChromeLaneTraceSink:
    """Span sink rendering causal spans as PER-LANE tid rows with flow
    arrows for cross-thread links in a chrome://tracing JSON.

    Unlike the PR 1 ``ChromeSpanSink`` (which keys rows off the raw OS
    thread id), rows here are the LANE catalog: one stable tid per lane
    name, labeled via thread-name metadata, ordered by first
    appearance. A span whose ``link_from`` names an already-rendered
    span gets a flow ("s" at the source span's end, "f" at this span's
    start) so the build→consume hand-off draws as an arrow across
    lanes.

    Pass an explicit ``utils.profiler.ChromeTraceWriter`` (then call
    ``writer.save(path)`` yourself), or None to follow whatever writer
    ``utils.profiler.set_chrome_trace`` installed at span time."""

    _DONE_CAP = 1024   # remembered (end, tid) of recent spans for links

    def __init__(self, writer=None) -> None:
        self._writer = writer
        self._lock = threading.Lock()
        self._lane_tids: Dict[str, int] = {}
        self._done: "OrderedDict[int, tuple]" = OrderedDict()

    def _resolve(self):
        w = self._writer
        if w is None:
            from paddlebox_tpu.utils.profiler import chrome_trace
            w = chrome_trace()
        return w

    def _tid(self, w, lane: str) -> int:
        with self._lock:
            tid = self._lane_tids.get(lane)
            if tid is None:
                tid = self._lane_tids[lane] = len(self._lane_tids) + 1
                w.thread_meta(tid, lane, sort_index=tid)
            return tid

    def span_full(self, rec: Dict) -> None:
        w = self._resolve()
        if w is None:
            return
        tid = self._tid(w, rec["lane"])
        args = dict(rec.get("attrs") or {})
        args["span_id"] = rec["span_id"]
        if rec.get("parent_id"):
            args["parent_id"] = rec["parent_id"]
        if "pass_seq" in rec:
            args["pass_seq"] = rec["pass_seq"]
        args["lane"] = rec["lane"]
        t0, dur = rec["t0"], rec["dur"]
        w.complete(rec["name"], t0, dur, tid=tid, **args)
        link = rec.get("link_from", 0)
        with self._lock:
            self._done[rec["span_id"]] = (t0 + dur, tid)
            while len(self._done) > self._DONE_CAP:
                self._done.popitem(last=False)
            src = self._done.get(link) if link else None
        if src is not None:
            src_end, src_tid = src
            # the arrow leaves the source span's END and binds to this
            # span's START; a source that outlived its consumer's start
            # (a submit span closing after its job began) clamps so the
            # arrow still flows forward
            w.flow(link, "s", min(src_end, t0), src_tid,
                   name=rec["name"])
            w.flow(link, "f", t0, tid, name=rec["name"])

    def span(self, name: str, start_s: float, dur_s: float,
             attrs: Optional[Dict] = None) -> None:
        """Plain hub spans (TelemetryHub.span) land on the emitting
        thread's lane row too — same timeline, no links."""
        self.span_full({"name": name, "span_id": 0, "parent_id": 0,
                        "lane": current_lane(), "t0": start_s,
                        "dur": dur_s, "attrs": attrs or {},
                        "link_from": 0})

    def close(self) -> None:
        pass


# ---- per-pass critical-path attribution --------------------------------
#: boundary stage keys the drivers report (note_pass_part); "train" is
#: implicit (the pass event's elapsed_sec). Order = report/docs order.
#: ``exchange_wait`` is the sharded step's measured NON-overlapped
#: embedding-exchange seconds per pass (train/a2a_probe): the part of
#: the pull/push all_to_all the schedule could not hide behind compute.
BOUNDARY_STAGES = ("build_wait", "stage_wait", "fence_wait",
                   "ssd_promote", "evict_emergency", "evict_scatter",
                   "exchange_wait", "end_submit")

_PARTS_LOCK = threading.Lock()
_PENDING_PARTS: Dict[str, float] = {}


def note_pass_part(stage: str, sec: float) -> None:
    """Report one boundary stall component for the UPCOMING pass event
    (drivers call this as each boundary phase completes: preload wait,
    begin-stall pieces, the previous pass's end-submit and fence wait).
    Inert without sinks — the parts exist to ride the pass event."""
    if sec <= 0 or not get_hub().active:
        return
    with _PARTS_LOCK:
        _PENDING_PARTS[stage] = _PENDING_PARTS.get(stage, 0.0) + sec


def consume_pass_parts() -> Dict[str, float]:
    """Pop the accumulated boundary parts (emit_pass_event calls this
    exactly once per pass event)."""
    with _PARTS_LOCK:
        if not _PENDING_PARTS:
            return {}
        parts = dict(_PENDING_PARTS)
        _PENDING_PARTS.clear()
        return parts


def critical_path_block(train_sec: float,
                        parts: Dict[str, float]) -> Dict:
    """Attribute one pass's wall time across lanes: ``wall_sec`` =
    train + every reported boundary part (so the block SUMS to the
    pass's critical-path wall by construction), with a ``bottleneck``
    verdict — ``device`` when training dominates, else the largest
    stall's stage name, with that stall's seconds as ``stall_sec``."""
    parts = {k: round(float(v), 6) for k, v in parts.items() if v > 0}
    wall = float(train_sec) + sum(parts.values())
    block: Dict = {"train_sec": round(float(train_sec), 6)}
    for k in BOUNDARY_STAGES:
        if k in parts:
            block[f"{k}_sec"] = parts[k]
    for k in sorted(parts):   # free-form extra stages still ship
        if k not in BOUNDARY_STAGES:
            block[f"{k}_sec"] = parts[k]
    block["wall_sec"] = round(wall, 6)
    worst = max(parts, key=parts.get) if parts else None
    if worst is None or train_sec >= parts[worst]:
        block["bottleneck"] = "device"
        block["stall_sec"] = round(max(wall - train_sec, 0.0), 6)
    else:
        block["bottleneck"] = worst
        block["stall_sec"] = parts[worst]
    return block


def reset() -> None:
    """Test hook: drop pending parts (span ids keep counting — they
    only need process-uniqueness)."""
    with _PARTS_LOCK:
        _PENDING_PARTS.clear()
