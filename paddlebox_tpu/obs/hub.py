"""TelemetryHub — the unified observability surface.

One hub per process unifies the pre-existing primitives (``StatRegistry``
counters, ``StageTimers`` per-pass reports, ``ChromeTraceWriter`` spans,
``device_mem_used`` HBM probes) behind typed instruments (obs/instruments)
with pluggable sinks:

- **event sinks** (``JsonlSink``...) get one structured record per
  pass/alert — the machine-readable PrintSyncTimer;
- **span sinks** (``ChromeSpanSink``) get completed timed spans;
- **Prometheus**: ``snapshot_prom()`` renders every instrument (plus the
  legacy ``STATS`` registry, bridged as ``pbox_stat`` gauges) in text
  exposition format; ``start_prom_http`` serves it from a background
  thread.

Hot-loop contract: with no sinks attached the hub is INERT — call sites
guard on ``hub.active`` (a plain bool attribute, one dict-free attribute
read) before building any event payload, so default-off telemetry costs
nothing measurable per step.

Enable via flags: ``FLAGS.telemetry_jsonl=/path/run.jsonl`` attaches a
JSONL sink, ``FLAGS.telemetry_prom_port>=0`` starts the HTTP endpoint
(``configure_from_flags`` is called by Trainer init and bench.py).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence

from paddlebox_tpu.obs.instruments import (Counter, Gauge, Histogram,
                                           Instrument, iter_prom_lines)
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class TelemetryHub:
    def __init__(self, run_id: Optional[str] = None) -> None:
        self.run_id = run_id or f"{int(time.time())}-{os.getpid()}"
        self._lock = threading.Lock()
        self._instruments: Dict[str, Instrument] = {}
        self._event_sinks: List = []
        self._span_sinks: List = []
        self._prom_server = None
        self._proc: Optional[int] = None
        self._seq = 0
        # liveness surface (/healthz on the prom endpoint): run start +
        # the last pass event's wall clock / count
        self.started_at = time.time()
        self._last_pass_ts: Optional[float] = None
        self._pass_count = 0
        # serving surface (serving.ServingModel/ReloadLoop register a
        # probe): /healthz grows a "serving" block and /readyz refuses
        # (503) until the probe reports a first snapshot adoption
        self._serving_probe = None
        # alerts surface (obs/alerts.AlertEngine registers its status):
        # /healthz grows an "alerts" block and /alertz serves it whole
        self._alerts_probe = None
        # online-daemon surface (online.OnlineLearner registers its
        # status): /healthz grows an "online" block — windows, backlog,
        # publish/shrink timestamps, and the daemon's degrade mode
        self._online_probe = None
        # elastic-membership surface (distributed.elastic.ElasticManager
        # registers its status on register()): /healthz grows a
        # "membership" block — alive set, np window, last scale event,
        # re-shard count (docs/RESILIENCE.md §Elastic membership)
        self._membership_probe = None
        # per-sink CONSECUTIVE failure counts (sink fault isolation): a
        # sink that keeps raising gets quarantined — removed from the
        # fan-out — after FLAGS.telemetry_sink_errors_max failures
        self._sink_fails: Dict[int, int] = {}
        # fast-path flag: any sink attached / endpoint running. Hot call
        # sites read this one attribute and skip all payload assembly.
        self.active = False

    # ---- instruments ---------------------------------------------------
    def _get(self, cls, name: str, help: str, **kw) -> Instrument:
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name, help, **kw)
            elif not isinstance(inst, cls):
                raise TypeError(f"instrument {name!r} already registered "
                                f"as {inst.kind}")
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get(Histogram, name, help,
                         **({"buckets": buckets} if buckets else {}))

    # ---- sinks ---------------------------------------------------------
    def _refresh_active(self) -> None:
        self.active = bool(self._event_sinks or self._span_sinks
                           or self._prom_server is not None)

    def add_sink(self, sink, kind: Optional[str] = None) -> None:
        """Attach an event sink (has ``emit(dict)``), a span sink (has
        ``span(name, start, dur, attrs)`` or the rich
        ``span_full(rec)``), or BOTH — a dual-capability sink registers
        in both lists (the pre-fix behavior silently filed it as
        span-only, dropping its events). ``kind`` overrides the
        auto-classification: "event", "span", or "both"."""
        if kind not in (None, "event", "span", "both"):
            raise ValueError(f"unknown sink kind: {kind!r}")
        as_span = (hasattr(sink, "span") or hasattr(sink, "span_full")
                   if kind is None else kind in ("span", "both"))
        as_event = (hasattr(sink, "emit") if kind is None
                    else kind in ("event", "both"))
        if kind is not None:
            # an explicit kind must be honorable: registering a sink
            # for a capability it lacks would fail at first emit
            if kind in ("event", "both") and not hasattr(sink, "emit"):
                raise TypeError(f"sink {sink!r} has no emit()")
            if kind in ("span", "both") and not (
                    hasattr(sink, "span") or hasattr(sink, "span_full")):
                raise TypeError(f"sink {sink!r} has no span()/span_full()")
        if not (as_span or as_event):
            raise TypeError(
                f"sink {sink!r} exposes neither emit() nor span()")
        with self._lock:
            if as_span:
                self._span_sinks.append(sink)
            if as_event:
                self._event_sinks.append(sink)
            self._refresh_active()

    def remove_sink(self, sink) -> None:
        with self._lock:
            for ls in (self._event_sinks, self._span_sinks):
                if sink in ls:
                    ls.remove(sink)
            self._refresh_active()

    def close_sinks(self) -> None:
        with self._lock:
            # dual-capability sinks sit in both lists — close once
            sinks = list({id(s): s for s in
                          self._event_sinks + self._span_sinks}.values())
            self._event_sinks = []
            self._span_sinks = []
            self._refresh_active()
        for s in sinks:
            try:
                s.close()
            except Exception:  # a dying sink must not take the run down
                log.warning("telemetry sink close failed", exc_info=True)

    def event_sinks(self) -> List:
        return list(self._event_sinks)

    def span_sinks(self) -> List:
        return list(self._span_sinks)

    # ---- events --------------------------------------------------------
    def _process_index(self) -> int:
        if self._proc is None:
            try:
                import jax
                self._proc = jax.process_index()
            except Exception:
                self._proc = 0
        return self._proc

    def emit(self, event: str, **fields) -> None:
        """Emit one structured event to every event sink. Timestamps are
        wall-clock and ``seq`` is a per-hub monotone sequence number, so
        JSONL consumers can order events even across clock steps."""
        sinks = self._event_sinks
        if not sinks:
            return
        with self._lock:
            self._seq += 1
            seq = self._seq
        ev = {"ts": time.time(), "seq": seq, "event": event,
              "run": self.run_id, "proc": self._process_index()}
        ev.update(fields)
        for s in sinks:
            try:
                s.emit(ev)
                if self._sink_fails:
                    self._sink_fails.pop(id(s), None)
            except Exception:
                self._sink_error(s, "emit")

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[None]:
        """Run-scoped timed span → span sinks (no-op without any)."""
        sinks = self._span_sinks
        if not sinks:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            for s in sinks:
                try:
                    s.span(name, t0, dur, attrs)
                    if self._sink_fails:
                        self._sink_fails.pop(id(s), None)
                except Exception:
                    self._sink_error(s, "span")

    def _sink_error(self, sink, surface: str) -> None:
        """Sink fault isolation: a raising sink never reaches the
        training hot path — book the failure, and after
        ``FLAGS.telemetry_sink_errors_max`` CONSECUTIVE failures
        quarantine it (remove from the fan-out) so a wedged sink can't
        keep burning the emit path on exceptions."""
        name = type(sink).__name__
        log.warning("telemetry %s sink failed (%s)", surface, name,
                    exc_info=True)
        try:
            self.counter("pbox_sink_errors_total",
                         "telemetry sink emit/span failures").inc(
                             sink=name)
            try:
                from paddlebox_tpu.config import FLAGS
                limit = int(FLAGS.telemetry_sink_errors_max)
            except Exception:
                limit = 8
            fails = self._sink_fails.get(id(sink), 0) + 1
            self._sink_fails[id(sink)] = fails
            if limit > 0 and fails >= limit:
                self._sink_fails.pop(id(sink), None)
                self.remove_sink(sink)
                self.counter("pbox_sinks_quarantined_total",
                             "sinks removed after consecutive "
                             "failures").inc(sink=name)
                log.error("telemetry sink %s QUARANTINED after %d "
                          "consecutive failures", name, fails)
        except Exception:
            log.debug("sink error bookkeeping failed", exc_info=True)

    # ---- snapshots -----------------------------------------------------
    def snapshot(self) -> Dict[str, Dict]:
        """Structured dump: {name: {kind, series: {label_str: value}}}
        (histograms dump {sum, count} per series)."""
        with self._lock:
            insts = list(self._instruments.values())
        out: Dict[str, Dict] = {}
        for inst in insts:
            series: Dict[str, object] = {}
            for k, v in inst.series():
                key = ",".join(f"{n}={val}" for n, val in k)
                series[key] = ({"sum": v.sum, "count": v.count}
                               if inst.kind == "histogram" else v)
            out[inst.name] = {"kind": inst.kind, "series": series}
        return out

    def snapshot_prom(self) -> str:
        """Prometheus text exposition of every instrument + the legacy
        StatRegistry (bridged as ``pbox_stat{name=...}`` gauges)."""
        with self._lock:
            insts = sorted(self._instruments.values(),
                           key=lambda i: i.name)
        lines: List[str] = []
        for inst in insts:
            lines.extend(iter_prom_lines(inst))
        from paddlebox_tpu.obs.instruments import escape_label_value
        from paddlebox_tpu.utils.monitor import STATS
        stats = STATS.snapshot()
        if stats:
            lines.append("# TYPE pbox_stat gauge")
            for name, val in sorted(stats.items()):
                lines.append(
                    f'pbox_stat{{name="{escape_label_value(name)}"}}'
                    f' {val}')
        return "\n".join(lines) + "\n"

    def note_pass(self) -> None:
        """Stamp a completed pass for the /healthz liveness surface
        (emit_pass_event calls this on the active path)."""
        with self._lock:
            self._last_pass_ts = time.time()
            self._pass_count += 1

    # ---- serving surface (docs/SERVING.md) -----------------------------
    def set_serving_probe(self, probe) -> None:
        """Register (or clear, with None) the process's serving status
        provider — a callable returning the ``serving`` block for
        /healthz: ``{adopted, epoch, last_reload_ts, staleness_sec,
        stale}`` (serving.ServingModel.serving_status). One serving
        model per process owns the block; the last registration wins."""
        with self._lock:
            self._serving_probe = probe

    def serving_info(self) -> Optional[Dict]:
        """The registered probe's current block (None: no serving model
        in this process, or the probe failed — a broken probe must not
        take the health endpoint down)."""
        with self._lock:
            probe = self._serving_probe
        if probe is None:
            return None
        try:
            return probe()
        except Exception:
            log.warning("serving health probe failed", exc_info=True)
            return {"adopted": None, "error": "probe failed"}

    # ---- online-daemon surface (docs/ONLINE.md) ------------------------
    def set_online_probe(self, probe) -> None:
        """Register (or clear, with None) the online-learning daemon's
        status provider — a callable returning the ``online`` block for
        /healthz: ``{mode, windows_completed, files_backlog,
        last_publish_ts, last_shrink_ts, shrunk_rows_total, ...}``
        (online.OnlineLearner.online_status). One daemon per process;
        the last registration wins."""
        with self._lock:
            self._online_probe = probe

    def online_info(self) -> Optional[Dict]:
        """The registered daemon probe's current block (None: no online
        daemon in this process; a broken probe must not take the
        health endpoint down)."""
        with self._lock:
            probe = self._online_probe
        if probe is None:
            return None
        try:
            return probe()
        except Exception:
            log.warning("online daemon probe failed", exc_info=True)
            return {"mode": "unknown", "error": "probe failed"}

    # ---- elastic-membership surface (RESILIENCE.md §Elastic) -----------
    def set_membership_probe(self, probe) -> None:
        """Register (or clear, with None) the elastic manager's status
        provider — a callable returning the ``membership`` block for
        /healthz: ``{alive, np, min_np, max_np, last_scale_event_ts,
        reshard_count}`` (ElasticManager.membership_status). One manager
        per process; the last registration wins."""
        with self._lock:
            self._membership_probe = probe

    def membership_info(self) -> Optional[Dict]:
        """The registered membership probe's current block (None: no
        elastic manager in this process; a broken probe must not take
        the health endpoint down)."""
        with self._lock:
            probe = self._membership_probe
        if probe is None:
            return None
        try:
            return probe()
        except Exception:
            log.warning("membership health probe failed", exc_info=True)
            return {"alive": None, "error": "probe failed"}

    # ---- alerts surface (docs/OBSERVABILITY.md §Alerts) ----------------
    def set_alerts_probe(self, probe) -> None:
        """Register (or clear, with None) the alert engine's status
        provider (obs/alerts.AlertEngine.status) — the ``alerts`` block
        for /healthz and the whole /alertz payload."""
        with self._lock:
            self._alerts_probe = probe

    def alerts_info(self) -> Optional[Dict]:
        with self._lock:
            probe = self._alerts_probe
        if probe is None:
            return None
        try:
            return probe()
        except Exception:
            log.warning("alerts probe failed", exc_info=True)
            return {"error": "probe failed"}

    def dump_blackbox(self, reason: str) -> Optional[str]:
        """Explicitly publish a flight-recorder postmortem bundle (the
        ``manual`` trigger). Returns the bundle path, or None when no
        recorder is installed (``FLAGS.flightrec_dir`` unset) or the
        trigger was debounced."""
        from paddlebox_tpu.obs import flightrec
        return flightrec.trigger("manual", reason=reason)

    def readiness(self) -> Dict:
        """The /readyz payload: ready only after the serving model's
        FIRST snapshot adoption (a serving process must not receive
        traffic while it still answers from an empty table). Processes
        with no serving probe registered are unready by definition —
        /readyz is a serving-role endpoint; training liveness is
        /healthz."""
        info = self.serving_info()
        if info is None:
            return {"ready": False, "reason": "no serving model"}
        if not info.get("adopted"):
            return {"ready": False, "reason": "no snapshot adopted yet",
                    "serving": info}
        return {"ready": True, "serving": info}

    def health(self) -> Dict:
        """The /healthz payload: run identity, uptime, and how stale
        the latest pass is — the liveness probe the serving/streaming
        loops poll (a wedged always-on trainer shows a growing
        ``last_pass_age_sec`` while the process still answers). When a
        serving model registered its probe, a ``serving`` block rides
        along (adopted version, last reload, snapshot staleness)."""
        now = time.time()
        with self._lock:
            last = self._last_pass_ts
            count = self._pass_count
        out = {
            "status": "ok",
            "run_id": self.run_id,
            "uptime_sec": round(now - self.started_at, 3),
            "passes_total": count,
            "last_pass_ts": last,
            "last_pass_age_sec": (None if last is None
                                  else round(now - last, 3)),
        }
        serving = self.serving_info()
        if serving is not None:
            out["serving"] = serving
        online = self.online_info()
        if online is not None:
            # the daemon's train+publish+serve verdict in one block:
            # mode != "full" means a leg degraded (docs/ONLINE.md)
            out["online"] = online
        membership = self.membership_info()
        if membership is not None:
            # the elastic world in one block: alive set vs the
            # [min_np, max_np] window, last scale event, re-shards
            out["membership"] = membership
        alerts = self.alerts_info()
        if alerts is not None:
            # /healthz carries the compact alarm view; /alertz the
            # full per-rule table
            out["alerts"] = {"firing": alerts.get("firing", 0),
                             "active": alerts.get("active", []),
                             "rules": len(alerts.get("rules", []))}
        return out

    # ---- Prometheus HTTP endpoint --------------------------------------
    def start_prom_http(self, port: int = 0):
        """Serve ``snapshot_prom()`` from a daemon thread — plus
        ``/healthz`` (JSON liveness: run_id, uptime, last-pass age);
        returns the server (``server.server_address[1]`` is the bound
        port — pass port=0 for an ephemeral one). Idempotent."""
        if self._prom_server is not None:
            return self._prom_server
        import http.server
        import json as _json

        hub = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                route = self.path.split("?", 1)[0]
                status = 200
                if route == "/healthz":
                    body = _json.dumps(hub.health()).encode()
                    ctype = "application/json"
                elif route == "/readyz":
                    # the serving readiness gate: 503 until the first
                    # snapshot adoption (docs/SERVING.md)
                    ready = hub.readiness()
                    status = 200 if ready["ready"] else 503
                    body = _json.dumps(ready).encode()
                    ctype = "application/json"
                elif route == "/alertz":
                    # the alert engine's full rule table (503 with the
                    # firing list non-empty — a dumb prober can alarm
                    # on status alone)
                    alerts = hub.alerts_info()
                    if alerts is None:
                        alerts = {"firing": 0, "active": [],
                                  "rules": [],
                                  "note": "no alert engine installed"}
                    status = 503 if alerts.get("firing") else 200
                    body = _json.dumps(alerts).encode()
                    ctype = "application/json"
                else:
                    body = hub.snapshot_prom().encode()
                    ctype = "text/plain; version=0.0.4"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        srv = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
        threading.Thread(target=srv.serve_forever, daemon=True,
                         name="pbox-prom-http").start()
        with self._lock:
            self._prom_server = srv
            self._refresh_active()
        log.info("prometheus endpoint on :%d", srv.server_address[1])
        return srv

    def stop_prom_http(self) -> None:
        with self._lock:
            srv, self._prom_server = self._prom_server, None
            self._refresh_active()
        if srv is not None:
            srv.shutdown()
            srv.server_close()


_HUB = TelemetryHub()
_configured_jsonl: Optional[str] = None


def get_hub() -> TelemetryHub:
    return _HUB


def reset_hub() -> TelemetryHub:
    """Fresh global hub (tests). Closes the old hub's sinks/endpoint
    and uninstalls the flag-configured flight recorder / alert engine /
    quality monitor so the next configure_from_flags starts clean."""
    global _HUB, _configured_jsonl
    _HUB.close_sinks()
    _HUB.stop_prom_http()
    try:
        from paddlebox_tpu.obs import alerts, flightrec, quality
        flightrec.install_recorder(None)
        alerts.install_engine(None, register_probe=False)
        quality.reset_monitor()
    except Exception:
        log.debug("obs singleton reset failed", exc_info=True)
    _HUB = TelemetryHub()
    _configured_jsonl = None
    return _HUB


def configure_from_flags() -> TelemetryHub:
    """Attach flag-selected sinks to the global hub (idempotent; called
    by Trainer init and bench.py so ``FLAGS_telemetry_jsonl=...`` in the
    environment is all a run needs)."""
    global _configured_jsonl
    from paddlebox_tpu.config import FLAGS
    hub = _HUB
    path = FLAGS.telemetry_jsonl
    if path and path != _configured_jsonl:
        from paddlebox_tpu.obs.sinks import JsonlSink
        hub.add_sink(JsonlSink(
            path,
            max_bytes=int(FLAGS.telemetry_jsonl_max_mb * 1024 * 1024),
            keep=FLAGS.telemetry_jsonl_keep))
        _configured_jsonl = path
    if FLAGS.telemetry_prom_port >= 0:
        hub.start_prom_http(FLAGS.telemetry_prom_port)
    # the anomaly flight recorder and the SLO alert engine ride the
    # same flag seam (both default-off; docs/OBSERVABILITY.md)
    from paddlebox_tpu.obs import alerts, flightrec
    flightrec.configure_from_flags()
    alerts.configure_from_flags()
    return hub


def emit_pass_event(kind: str, metrics: Dict, stage_timers=None,
                    table=None, examples: Optional[int] = None,
                    auc_state=None) -> None:
    """THE per-pass telemetry record: pass metrics + stage timers +
    channel gauges + table occupancy + HBM watermarks, in one event and
    mirrored into instruments for the Prometheus view. Trainers call
    this at every pass end; it returns immediately when no sink is
    attached (the no-sink fast path)."""
    hub = _HUB
    if not hub.active:
        return
    ev: Dict = {"kind": kind}
    for k in ("batches", "elapsed_sec", "examples_per_sec", "auc",
              "last_loss", "global_step", "pass_seq",
              "exchange_overlap_frac", "actual_ctr", "predicted_ctr"):
        if k in metrics:
            ev[k] = metrics[k]
    if examples is not None:
        ev["examples"] = examples
    if stage_timers is not None:
        ev["stage_sec"] = {k: round(v, 6)
                           for k, v in stage_timers.as_dict().items()}
        ev["stage_count"] = stage_timers.counts()
        h = hub.histogram("pbox_stage_seconds",
                          "per-pass stage wall seconds")
        for k, v in ev["stage_sec"].items():
            h.observe(v, stage=k)
    # channel gauges (cumulative across the process; consumers diff
    # between consecutive pass events — scripts/telemetry_report.py)
    from paddlebox_tpu.utils.channel import channel_stats_snapshot
    chans = channel_stats_snapshot()
    if chans:
        ev["channels"] = chans
        depth_g = hub.gauge("pbox_channel_depth",
                            "items queued in named channels")
        hwm_g = hub.gauge("pbox_channel_high_watermark",
                          "peak queued items per named channel")
        bput = hub.counter("pbox_channel_blocked_put_seconds_total",
                           "producer seconds blocked on a full channel")
        bget = hub.counter("pbox_channel_blocked_get_seconds_total",
                           "consumer seconds blocked on an empty channel")
        for name, st in chans.items():
            depth_g.set(st["depth"], channel=name)
            hwm_g.set_max(st["high_watermark"], channel=name)
            # counters are monotone: add only the delta since last mirror
            for ctr, key in ((bput, "blocked_put_sec"),
                             (bget, "blocked_get_sec")):
                prev = ctr.value(channel=name)
                if st[key] > prev:
                    ctr.inc(st[key] - prev, channel=name)
    # table occupancy (+ the tiered tables' per-pass delta stats)
    if table is not None:
        tstats = {}
        if hasattr(table, "obs_stats"):
            tstats.update(table.obs_stats())
        lp = getattr(table, "last_pass_stats", None)
        if lp:
            tstats["last_pass"] = dict(lp)
        # async pass epilogue (ps/epilogue): cumulative write-back /
        # fence-wait / overlap seconds ride every pass event so the
        # JSONL alone shows how much end_pass left the critical path
        # (pbox_endpass_* gauges mirror from the epilogue itself)
        eps = getattr(table, "endpass_stats", None)
        if eps is not None:
            tstats["endpass"] = {k: (round(v, 6)
                                     if isinstance(v, float) else v)
                                 for k, v in eps().items()}
        if tstats:
            ev["table"] = tstats
            if "used" in tstats:
                hub.gauge("pbox_table_rows_used",
                          "occupied embedding rows").set(tstats["used"])
            if "capacity" in tstats:
                hub.gauge("pbox_table_rows_capacity",
                          "embedding row capacity").set(tstats["capacity"])
    # HBM watermarks (zeros on backends without allocator stats, e.g.
    # virtual CPU devices — the keys still ship so consumers are uniform)
    try:
        from paddlebox_tpu.utils.monitor import device_mem_used
        hbm = device_mem_used()
    except Exception:
        hbm = {"bytes_in_use": 0, "peak_bytes_in_use": 0, "bytes_limit": 0}
    ev["hbm"] = hbm
    # resilience counters (retries/quarantines/faults/pass retries) ride
    # every pass event so chaos runs are diagnosable from the JSONL
    # alone (docs/RESILIENCE.md; zeros ship for consumer uniformity)
    try:
        from paddlebox_tpu.resilience.retry import retry_counters
        ev["resilience"] = retry_counters()
    except Exception:
        pass
    # critical-path attribution (obs/trace; docs/OBSERVABILITY.md
    # §Tracing): the pass drivers reported each boundary stall
    # (preload wait, stage wait, emergency eviction, the previous
    # pass's end-submit + fence wait) into the trace accumulator —
    # consume them here so every TRAIN pass event carries the wall
    # attribution + bottleneck verdict telemetry_report renders
    if "elapsed_sec" in ev and kind.startswith(("train_pass",
                                                "stream")):
        from paddlebox_tpu.obs import trace
        cp = trace.critical_path_block(ev["elapsed_sec"],
                                       trace.consume_pass_parts())
        ev["critical_path"] = cp
        hub.counter("pbox_pass_bottleneck_total",
                    "passes by critical-path bottleneck verdict"
                    ).inc(stage=cp["bottleneck"])
    hub.note_pass()
    hub.gauge("pbox_hbm_bytes_in_use",
              "device bytes in use").set(hbm["bytes_in_use"])
    hub.gauge("pbox_hbm_peak_bytes",
              "device peak bytes in use").set_max(hbm["peak_bytes_in_use"])
    hub.counter("pbox_passes_total", "completed passes").inc(kind=kind)
    if examples:
        hub.counter("pbox_examples_total",
                    "examples trained/evaluated").inc(examples)
    if "examples_per_sec" in ev:
        hub.gauge("pbox_last_pass_examples_per_sec",
                  "throughput of the latest pass").set(
                      ev["examples_per_sec"], kind=kind)
    # model-quality drift monitor (obs/quality; docs/OBSERVABILITY.md
    # §Model quality): windowed per-slot coverage/churn, norm drift,
    # calibration buckets and the AUC-trend verdict ride THIS seam —
    # off (the default) costs one flag read
    from paddlebox_tpu.config import FLAGS
    if FLAGS.quality_window_passes > 0 and kind.startswith(
            ("train_pass", "stream")):
        from paddlebox_tpu.obs import quality
        quality.note_pass_event(ev, table=table, auc_state=auc_state,
                                hub=hub)
    hub.emit("pass", **ev)
