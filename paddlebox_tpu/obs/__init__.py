"""Unified telemetry: typed instruments, run events, Prometheus export,
straggler watchdog (see docs/OBSERVABILITY.md for the catalog)."""

from paddlebox_tpu.obs.alerts import AlertEngine, Rule, default_rules
from paddlebox_tpu.obs.flightrec import FlightRecorder
from paddlebox_tpu.obs.hub import (TelemetryHub, configure_from_flags,
                                   emit_pass_event, get_hub, reset_hub)
from paddlebox_tpu.obs.instruments import Counter, Gauge, Histogram
from paddlebox_tpu.obs.quality import QualityMonitor
from paddlebox_tpu.obs.sinks import ChromeSpanSink, JsonlSink, MemorySink
from paddlebox_tpu.obs.trace import (ChromeLaneTraceSink, lane_scope,
                                     set_lane, span, tracing_active)
from paddlebox_tpu.obs.watchdog import (DirHeartbeatStore,
                                        LocalHeartbeatStore,
                                        StragglerReport, StragglerTimeout,
                                        StragglerWatchdog)

__all__ = [
    "TelemetryHub", "get_hub", "reset_hub", "configure_from_flags",
    "emit_pass_event", "Counter", "Gauge", "Histogram",
    "JsonlSink", "MemorySink", "ChromeSpanSink", "ChromeLaneTraceSink",
    "span", "lane_scope", "set_lane", "tracing_active",
    "StragglerWatchdog", "StragglerReport", "StragglerTimeout",
    "LocalHeartbeatStore", "DirHeartbeatStore",
    "FlightRecorder", "QualityMonitor", "AlertEngine", "Rule",
    "default_rules",
]
