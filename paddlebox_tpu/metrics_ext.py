"""Metric variants beyond plain AUC.

Reference: fleet/metrics.h:198-567 (same classes re-declared at
box_wrapper.h:265-376) — ``MetricMsg`` (auc), ``MultiTaskMetricMsg``
(:198, per-instance task selection by cmatch), ``CmatchRankMetricMsg``
(:279, filter by (cmatch,rank) pairs), ``MaskMetricMsg`` (:369, extra
0/1 mask input), ``CmatchRankMaskMetricMsg`` (:414), ``WuAucMetricMsg``
(:497, per-user AUC via uid-collected records; calculator at
metrics.h:48-57/metrics.cc computeWuAuc), plus continue-value MSE/RMSE
(``BasicAucCalculator::compute_continue_value``) and the NaN/Inf counters
(``GetNanInfMetricMsg``, box_wrapper.h:792).

TPU-native: every filtered variant reduces to a *selection weight* fed to
the same jittable bucketed ``auc_add_batch`` — the filter math stays on
device inside the train step; only WuAUC collects (uid, pred, label)
records host-side (as the reference does) and computes tie-averaged
per-user Mann-Whitney AUC in vectorized numpy.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.metrics import (AucResult, auc_add_batch, auc_compute,
                                   init_auc_state)


def parse_cmatch_rank_group(group: str) -> List[Tuple[int, int]]:
    """"401:0,402:0" → [(401,0),(402,0)]; entries without ':' get rank 0
    (MetricMsg parse_cmatch_rank, metrics.h helpers)."""
    out = []
    for part in group.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            c, r = part.split(":")
            out.append((int(c), int(r)))
        else:
            out.append((int(part), 0))
    return out


class AucMetric:
    """Plain bucketed AUC (MetricMsg). Base for the filtered variants."""

    method = "auc"

    def __init__(self, name: str, label: str = "label", pred: str = "pred",
                 phase: int = -1, nbins: Optional[int] = None) -> None:
        self.name = name
        self.label_var = label
        self.pred_var = pred
        self.phase = phase  # -1: both phases (join+update)
        self._nbins = nbins
        self.state = init_auc_state(nbins)

    def selection_weight(self, weight: jax.Array, **inputs) -> jax.Array:
        return weight

    def add(self, pred: jax.Array, label: jax.Array,
            weight: Optional[jax.Array] = None, **inputs) -> None:
        w = jnp.ones_like(pred) if weight is None else weight
        self.state = auc_add_batch(self.state, pred, label,
                                   self.selection_weight(w, **inputs))

    def compute(self) -> Dict[str, float]:
        return auc_compute(self.state).as_dict()

    def reset(self) -> None:
        self.state = init_auc_state(self._nbins)


class CmatchRankAucMetric(AucMetric):
    """AUC over instances whose (cmatch, rank) is in the configured group
    (CmatchRankMetricMsg, metrics.h:279; ignore_rank ⇒ match cmatch only)."""

    method = "cmatch_rank_auc"
    REQUIRED = ("cmatch",)

    def __init__(self, name: str, cmatch_rank_group: str,
                 ignore_rank: bool = False, **kw) -> None:
        super().__init__(name, **kw)
        self.group = parse_cmatch_rank_group(cmatch_rank_group)
        self.ignore_rank = ignore_rank

    def selection_weight(self, weight, *, cmatch, rank=None, **_):
        sel = jnp.zeros_like(weight, dtype=bool)
        for c, r in self.group:
            m = cmatch == c
            if not self.ignore_rank and rank is not None:
                m = m & (rank == r)
            sel = sel | m
        return weight * sel.astype(weight.dtype)


class MaskAucMetric(AucMetric):
    """AUC over instances with mask==1 (MaskMetricMsg, metrics.h:369)."""

    method = "mask_auc"
    REQUIRED = ("mask",)

    def selection_weight(self, weight, *, mask, **_):
        return weight * (mask > 0).astype(weight.dtype)


class CmatchRankMaskAucMetric(CmatchRankAucMetric):
    """Both filters (CmatchRankMaskMetricMsg, metrics.h:414)."""

    REQUIRED = ("cmatch", "mask")

    method = "cmatch_rank_mask_auc"

    def selection_weight(self, weight, *, cmatch, rank=None, mask=None, **_):
        w = super().selection_weight(weight, cmatch=cmatch, rank=rank)
        if mask is not None:
            w = w * (mask > 0).astype(w.dtype)
        return w


class MultiTaskAucMetric(AucMetric):
    REQUIRED = ("cmatch",)
    """Per-instance task head selected by cmatch (MultiTaskMetricMsg,
    metrics.h:198): pred[i] = preds[i, task_of(cmatch[i])]."""

    method = "multi_task_auc"

    def __init__(self, name: str, cmatch_rank_group: str, **kw) -> None:
        super().__init__(name, **kw)
        self.group = parse_cmatch_rank_group(cmatch_rank_group)
        max_c = max(c for c, _ in self.group)
        lut = np.full(max_c + 2, -1, np.int32)
        for c, task in self.group:
            lut[c] = task
        self._lut = jnp.asarray(lut)

    def add(self, preds: jax.Array, label: jax.Array,
            weight: Optional[jax.Array] = None, *, cmatch, **_) -> None:
        """preds: [B, num_tasks]."""
        w = jnp.ones(preds.shape[0], preds.dtype) if weight is None else weight
        c = jnp.clip(cmatch, 0, self._lut.shape[0] - 1)
        task = self._lut[c]
        sel = (task >= 0)
        pred = jnp.take_along_axis(
            preds, jnp.maximum(task, 0)[:, None], axis=1)[:, 0]
        self.state = auc_add_batch(self.state, pred, label,
                                   w * sel.astype(w.dtype))


class ContinueValueMetric:
    """Regression metric: mae/mse/rmse only (compute_continue_value)."""

    method = "continue_value"

    def __init__(self, name: str, label: str = "label", pred: str = "pred",
                 phase: int = -1) -> None:
        self.name = name
        self.label_var = label
        self.pred_var = pred
        self.phase = phase
        self.reset()

    def add(self, pred, label, weight=None, **_):
        w = jnp.ones_like(pred) if weight is None else weight
        err = (pred - label) * w
        self._abs += float(jnp.sum(jnp.abs(err)))
        self._sqr += float(jnp.sum(err * err))
        self._n += float(jnp.sum(w))

    def compute(self) -> Dict[str, float]:
        n = max(self._n, 1e-12)
        return {"mae": self._abs / n, "mse": self._sqr / n,
                "rmse": float(np.sqrt(self._sqr / n)), "ins_num": self._n}

    def reset(self):
        self._abs = 0.0
        self._sqr = 0.0
        self._n = 0.0


class NanInfMetric:
    """NaN/Inf prediction counters (box_wrapper.h:792)."""

    method = "nan_inf"

    def __init__(self, name: str, pred: str = "pred", phase: int = -1):
        self.name = name
        self.pred_var = pred
        self.phase = phase
        self.reset()

    def add(self, pred, **_):
        self.nan_cnt += int(jnp.sum(jnp.isnan(pred)))
        self.inf_cnt += int(jnp.sum(jnp.isinf(pred)))
        self.total += int(pred.shape[0])

    def compute(self) -> Dict[str, float]:
        return {"nan": float(self.nan_cnt), "inf": float(self.inf_cnt),
                "ins_num": float(self.total)}

    def reset(self):
        self.nan_cnt = 0
        self.inf_cnt = 0
        self.total = 0


def _tie_averaged_user_auc(uid: np.ndarray, pred: np.ndarray,
                           label: np.ndarray) -> Tuple[float, float, int]:
    """Vectorized per-user Mann-Whitney AUC with tie-averaged ranks.
    Returns (wuauc, uauc, users_counted): wuauc weighs each user's AUC by
    its instance count; uauc is the unweighted mean (computeWuAuc)."""
    if len(uid) == 0:
        return 0.0, 0.0, 0
    s = np.lexsort((pred, uid))
    u, p, l = uid[s], pred[s], label[s].astype(np.float64)
    n = len(u)
    new_user = np.empty(n, bool)
    new_user[0] = True
    new_user[1:] = u[1:] != u[:-1]
    g = np.cumsum(new_user) - 1                      # user group id
    start = np.flatnonzero(new_user)                 # first idx per user
    pos_in_grp = np.arange(n) - start[g]
    # tie runs: same user AND same pred
    new_tie = new_user.copy()
    new_tie[1:] |= p[1:] != p[:-1]
    tie_id = np.cumsum(new_tie) - 1
    tie_start = np.flatnonzero(new_tie)
    tie_cnt = np.diff(np.append(tie_start, n))
    # average 1-based rank within the user for each tie run
    avg_rank = (pos_in_grp[tie_start][tie_id] + 1
                + (tie_cnt[tie_id] - 1) / 2.0)
    num_users = int(g[-1]) + 1
    n_u = np.bincount(g, minlength=num_users).astype(np.float64)
    n_pos = np.bincount(g, weights=l, minlength=num_users)
    n_neg = n_u - n_pos
    rank_pos = np.bincount(g, weights=avg_rank * l, minlength=num_users)
    ok = (n_pos > 0) & (n_neg > 0)
    auc_u = np.zeros(num_users)
    auc_u[ok] = ((rank_pos[ok] - n_pos[ok] * (n_pos[ok] + 1) / 2.0)
                 / (n_pos[ok] * n_neg[ok]))
    w = n_u * ok
    wuauc = float((auc_u * w).sum() / max(w.sum(), 1e-12))
    uauc = float(auc_u[ok].mean()) if ok.any() else 0.0
    return wuauc, uauc, int(ok.sum())


class WuAucMetric:
    """Per-user (weighted-user) AUC (WuAucMetricMsg, metrics.h:497).
    Collects (uid, pred, label) host-side per batch, like the reference's
    record-based WuAucCalculator. NOTE: host-side accumulate — adding it
    to a trainer registry forces a device sync per batch."""

    method = "wuauc"
    REQUIRED = ("uid",)

    def __init__(self, name: str, label: str = "label", pred: str = "pred",
                 uid: str = "uid", phase: int = -1) -> None:
        self.name = name
        self.label_var = label
        self.pred_var = pred
        self.uid_var = uid
        self.phase = phase
        self.reset()

    def add(self, pred, label, weight=None, *, uid, **_) -> None:
        pred = np.asarray(pred)
        mask = (np.asarray(weight) > 0 if weight is not None
                else np.ones(len(pred), bool))
        self._uid.append(np.asarray(uid)[mask])
        self._pred.append(pred[mask])
        self._label.append(np.asarray(label)[mask])

    def compute(self) -> Dict[str, float]:
        uid = np.concatenate(self._uid) if self._uid else np.empty(0, np.int64)
        pred = np.concatenate(self._pred) if self._pred else np.empty(0)
        label = (np.concatenate(self._label) if self._label
                 else np.empty(0))
        wuauc, uauc, users = _tie_averaged_user_auc(uid, pred, label)
        return {"wuauc": wuauc, "uauc": uauc, "user_count": float(users),
                "ins_num": float(len(uid))}

    def reset(self) -> None:
        self._uid: List[np.ndarray] = []
        self._pred: List[np.ndarray] = []
        self._label: List[np.ndarray] = []


METRIC_METHODS = {
    cls.method: cls
    for cls in (AucMetric, CmatchRankAucMetric, MaskAucMetric,
                CmatchRankMaskAucMetric, MultiTaskAucMetric,
                ContinueValueMetric, NanInfMetric, WuAucMetric)
}
