"""Metric variants beyond plain AUC.

Reference: fleet/metrics.h:198-567 (same classes re-declared at
box_wrapper.h:265-376) — ``MetricMsg`` (auc), ``MultiTaskMetricMsg``
(:198, per-instance task selection by cmatch), ``CmatchRankMetricMsg``
(:279, filter by (cmatch,rank) pairs), ``MaskMetricMsg`` (:369, extra
0/1 mask input), ``CmatchRankMaskMetricMsg`` (:414), ``WuAucMetricMsg``
(:497, per-user AUC via uid-collected records; calculator at
metrics.h:48-57/metrics.cc computeWuAuc), plus continue-value MSE/RMSE
(``BasicAucCalculator::compute_continue_value``) and the NaN/Inf counters
(``GetNanInfMetricMsg``, box_wrapper.h:792).

TPU-native: every filtered variant reduces to a *selection weight* fed to
the same jittable bucketed ``auc_add_batch`` — the filter math stays on
device inside the train step; only WuAUC collects (uid, pred, label)
records host-side (as the reference does) and computes tie-averaged
per-user Mann-Whitney AUC in vectorized numpy.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.metrics import (AucResult, auc_add_batch, auc_compute,
                                   init_auc_state)


def _allgather_bits(a: np.ndarray) -> np.ndarray:
    """process_allgather with BIT-EXACT transport: with x64 disabled,
    jax canonicalizes int64→int32 / float64→float32 on device_put
    (inside process_allgather), silently truncating 64-bit uids and
    large f64 sums. 8-byte dtypes therefore ride the wire as uint32
    PAIRS and reassemble by view — no value ever passes through a jax
    64-bit array. Returns the [P, ...] stacked gather."""
    from jax.experimental import multihost_utils
    a = np.ascontiguousarray(a)
    if a.dtype.itemsize == 8:
        bits = a.view(np.uint32).reshape(a.shape + (2,))
        g = np.asarray(multihost_utils.process_allgather(bits))
        return np.ascontiguousarray(g).view(a.dtype).reshape(
            g.shape[:-1])
    return np.asarray(multihost_utils.process_allgather(a))


def _pod_sum_tree(tree):
    """Sum per-process partial accumulators across a multi-controller
    pod — the MPI metric allreduce of the reference
    (fleet/metrics.cc:288-304: every trainer allreduces its bucket
    tables before computing ONE global AUC). Rides the jax distributed
    runtime (process_allgather, 64-bit-safe via _allgather_bits; the
    sum itself happens on host in the source dtype), so it needs no
    extra rendezvous; on a single-controller mesh it is the identity.
    COLLECTIVE: on a pod, every process must call
    compute()/get_metric_msg in lockstep (the SPMD host contract that
    already governs batch prep)."""
    if jax.process_count() == 1:
        return tree
    return jax.tree.map(
        lambda a: _allgather_bits(np.asarray(a)).sum(axis=0), tree)


def _pod_gather_varlen(arrays: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Concatenate per-process variable-length record arrays across the
    pod (the record-collecting WuAuc calculator's gather). Pads to the
    pod-max length for the fixed-shape allgather, then drops pads.
    64-bit dtypes (uids are 64-bit hashes) transport bit-exactly."""
    if jax.process_count() == 1:
        return list(arrays)
    ns = _allgather_bits(np.asarray(len(arrays[0]), np.int64))
    m = max(int(ns.max()), 1)
    out = []
    for a in arrays:
        pad = np.zeros(m - len(a), a.dtype)
        g = _allgather_bits(np.concatenate([a, pad]))
        out.append(np.concatenate([g[p, :int(ns[p])]
                                   for p in range(g.shape[0])]))
    return out


def parse_cmatch_rank_group(group: str) -> List[Tuple[int, int]]:
    """"401:0,402:0" → [(401,0),(402,0)]; entries without ':' get rank 0
    (MetricMsg parse_cmatch_rank, metrics.h helpers)."""
    out = []
    for part in group.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            c, r = part.split(":")
            out.append((int(c), int(r)))
        else:
            out.append((int(part), 0))
    return out


class AucMetric:
    """Plain bucketed AUC (MetricMsg). Base for the filtered variants."""

    method = "auc"

    def __init__(self, name: str, label: str = "label", pred: str = "pred",
                 phase: int = -1, nbins: Optional[int] = None) -> None:
        self.name = name
        self.label_var = label
        self.pred_var = pred
        self.phase = phase  # -1: both phases (join+update)
        self._nbins = nbins
        self.state = init_auc_state(nbins)

    def selection_weight(self, weight: jax.Array, **inputs) -> jax.Array:
        return weight

    def add(self, pred: jax.Array, label: jax.Array,
            weight: Optional[jax.Array] = None, **inputs) -> None:
        w = jnp.ones_like(pred) if weight is None else weight
        self.state = auc_add_batch(self.state, pred, label,
                                   self.selection_weight(w, **inputs))

    def compute(self) -> Dict[str, float]:
        # pod: transient global sum of the bucket tables (non-mutating,
        # so compute() stays repeatable while accumulation continues)
        return auc_compute(_pod_sum_tree(self.state)).as_dict()

    def reset(self) -> None:
        self.state = init_auc_state(self._nbins)


class CmatchRankAucMetric(AucMetric):
    """AUC over instances whose (cmatch, rank) is in the configured group
    (CmatchRankMetricMsg, metrics.h:279; ignore_rank ⇒ match cmatch only)."""

    method = "cmatch_rank_auc"
    REQUIRED = ("cmatch",)

    def __init__(self, name: str, cmatch_rank_group: str,
                 ignore_rank: bool = False, **kw) -> None:
        super().__init__(name, **kw)
        self.group = parse_cmatch_rank_group(cmatch_rank_group)
        self.ignore_rank = ignore_rank

    def selection_weight(self, weight, *, cmatch, rank=None, **_):
        sel = jnp.zeros_like(weight, dtype=bool)
        for c, r in self.group:
            m = cmatch == c
            if not self.ignore_rank and rank is not None:
                m = m & (rank == r)
            sel = sel | m
        return weight * sel.astype(weight.dtype)


class MaskAucMetric(AucMetric):
    """AUC over instances with mask==1 (MaskMetricMsg, metrics.h:369)."""

    method = "mask_auc"
    REQUIRED = ("mask",)

    def selection_weight(self, weight, *, mask, **_):
        return weight * (mask > 0).astype(weight.dtype)


class CmatchRankMaskAucMetric(CmatchRankAucMetric):
    """Both filters (CmatchRankMaskMetricMsg, metrics.h:414)."""

    REQUIRED = ("cmatch", "mask")

    method = "cmatch_rank_mask_auc"

    def selection_weight(self, weight, *, cmatch, rank=None, mask=None, **_):
        w = super().selection_weight(weight, cmatch=cmatch, rank=rank)
        if mask is not None:
            w = w * (mask > 0).astype(w.dtype)
        return w


class MultiTaskAucMetric(AucMetric):
    REQUIRED = ("cmatch",)
    """Per-instance task head selected by cmatch (MultiTaskMetricMsg,
    metrics.h:198): pred[i] = preds[i, task_of(cmatch[i])]."""

    method = "multi_task_auc"

    def __init__(self, name: str, cmatch_rank_group: str, **kw) -> None:
        super().__init__(name, **kw)
        self.group = parse_cmatch_rank_group(cmatch_rank_group)
        max_c = max(c for c, _ in self.group)
        lut = np.full(max_c + 2, -1, np.int32)
        for c, task in self.group:
            lut[c] = task
        self._lut = jnp.asarray(lut)

    def add(self, preds: jax.Array, label: jax.Array,
            weight: Optional[jax.Array] = None, *, cmatch, **_) -> None:
        """preds: [B, num_tasks]."""
        w = jnp.ones(preds.shape[0], preds.dtype) if weight is None else weight
        c = jnp.clip(cmatch, 0, self._lut.shape[0] - 1)
        task = self._lut[c]
        sel = (task >= 0)
        pred = jnp.take_along_axis(
            preds, jnp.maximum(task, 0)[:, None], axis=1)[:, 0]
        self.state = auc_add_batch(self.state, pred, label,
                                   w * sel.astype(w.dtype))


class ContinueValueMetric:
    """Regression metric: mae/mse/rmse only (compute_continue_value)."""

    method = "continue_value"

    def __init__(self, name: str, label: str = "label", pred: str = "pred",
                 phase: int = -1) -> None:
        self.name = name
        self.label_var = label
        self.pred_var = pred
        self.phase = phase
        self.reset()

    def add(self, pred, label, weight=None, **_):
        w = jnp.ones_like(pred) if weight is None else weight
        err = (pred - label) * w
        self._abs += float(jnp.sum(jnp.abs(err)))
        self._sqr += float(jnp.sum(err * err))
        self._n += float(jnp.sum(w))

    def compute(self) -> Dict[str, float]:
        s_abs, s_sqr, s_n = (float(x) for x in _pod_sum_tree(
            np.array([self._abs, self._sqr, self._n])))
        n = max(s_n, 1e-12)
        return {"mae": s_abs / n, "mse": s_sqr / n,
                "rmse": float(np.sqrt(s_sqr / n)), "ins_num": s_n}

    def reset(self):
        self._abs = 0.0
        self._sqr = 0.0
        self._n = 0.0


class NanInfMetric:
    """NaN/Inf prediction counters (box_wrapper.h:792)."""

    method = "nan_inf"

    def __init__(self, name: str, pred: str = "pred", phase: int = -1):
        self.name = name
        self.pred_var = pred
        self.phase = phase
        self.reset()

    def add(self, pred, **_):
        self.nan_cnt += int(jnp.sum(jnp.isnan(pred)))
        self.inf_cnt += int(jnp.sum(jnp.isinf(pred)))
        self.total += int(pred.shape[0])

    def compute(self) -> Dict[str, float]:
        s = _pod_sum_tree(np.array([self.nan_cnt, self.inf_cnt,
                                    self.total], np.float64))
        return {"nan": float(s[0]), "inf": float(s[1]),
                "ins_num": float(s[2])}

    def reset(self):
        self.nan_cnt = 0
        self.inf_cnt = 0
        self.total = 0


def _tie_averaged_user_auc(uid: np.ndarray, pred: np.ndarray,
                           label: np.ndarray) -> Tuple[float, float, int]:
    """Vectorized per-user Mann-Whitney AUC with tie-averaged ranks.
    Returns (wuauc, uauc, users_counted): wuauc weighs each user's AUC by
    its instance count; uauc is the unweighted mean (computeWuAuc)."""
    if len(uid) == 0:
        return 0.0, 0.0, 0
    s = np.lexsort((pred, uid))
    u, p, l = uid[s], pred[s], label[s].astype(np.float64)
    n = len(u)
    new_user = np.empty(n, bool)
    new_user[0] = True
    new_user[1:] = u[1:] != u[:-1]
    g = np.cumsum(new_user) - 1                      # user group id
    start = np.flatnonzero(new_user)                 # first idx per user
    pos_in_grp = np.arange(n) - start[g]
    # tie runs: same user AND same pred
    new_tie = new_user.copy()
    new_tie[1:] |= p[1:] != p[:-1]
    tie_id = np.cumsum(new_tie) - 1
    tie_start = np.flatnonzero(new_tie)
    tie_cnt = np.diff(np.append(tie_start, n))
    # average 1-based rank within the user for each tie run
    avg_rank = (pos_in_grp[tie_start][tie_id] + 1
                + (tie_cnt[tie_id] - 1) / 2.0)
    num_users = int(g[-1]) + 1
    n_u = np.bincount(g, minlength=num_users).astype(np.float64)
    n_pos = np.bincount(g, weights=l, minlength=num_users)
    n_neg = n_u - n_pos
    rank_pos = np.bincount(g, weights=avg_rank * l, minlength=num_users)
    ok = (n_pos > 0) & (n_neg > 0)
    auc_u = np.zeros(num_users)
    auc_u[ok] = ((rank_pos[ok] - n_pos[ok] * (n_pos[ok] + 1) / 2.0)
                 / (n_pos[ok] * n_neg[ok]))
    w = n_u * ok
    wuauc = float((auc_u * w).sum() / max(w.sum(), 1e-12))
    uauc = float(auc_u[ok].mean()) if ok.any() else 0.0
    return wuauc, uauc, int(ok.sum())


class WuAucMetric:
    """Per-user (weighted-user) AUC (WuAucMetricMsg, metrics.h:497).
    Collects (uid, pred, label) host-side per batch, like the reference's
    record-based WuAucCalculator. NOTE: host-side accumulate — adding it
    to a trainer registry forces a device sync per batch."""

    method = "wuauc"
    REQUIRED = ("uid",)

    def __init__(self, name: str, label: str = "label", pred: str = "pred",
                 uid: str = "uid", phase: int = -1) -> None:
        self.name = name
        self.label_var = label
        self.pred_var = pred
        self.uid_var = uid
        self.phase = phase
        self.reset()

    def add(self, pred, label, weight=None, *, uid, **_) -> None:
        pred = np.asarray(pred)
        mask = (np.asarray(weight) > 0 if weight is not None
                else np.ones(len(pred), bool))
        self._uid.append(np.asarray(uid)[mask])
        self._pred.append(pred[mask])
        self._label.append(np.asarray(label)[mask])

    def compute(self) -> Dict[str, float]:
        uid = (np.concatenate(self._uid) if self._uid
               else np.empty(0, np.int64))
        pred = np.concatenate(self._pred) if self._pred else np.empty(0)
        label = (np.concatenate(self._label) if self._label
                 else np.empty(0))
        # pod: gather every process's records (dtype-stable for the
        # fixed-shape allgather; a user's records may span processes —
        # the per-user math runs on the concatenated whole)
        uid, pred, label = _pod_gather_varlen(
            [uid.astype(np.int64, copy=False),
             pred.astype(np.float64, copy=False),
             label.astype(np.float64, copy=False)])
        wuauc, uauc, users = _tie_averaged_user_auc(uid, pred, label)
        return {"wuauc": wuauc, "uauc": uauc, "user_count": float(users),
                "ins_num": float(len(uid))}

    def reset(self) -> None:
        self._uid: List[np.ndarray] = []
        self._pred: List[np.ndarray] = []
        self._label: List[np.ndarray] = []


METRIC_METHODS = {
    cls.method: cls
    for cls in (AucMetric, CmatchRankAucMetric, MaskAucMetric,
                CmatchRankMaskAucMetric, MultiTaskAucMetric,
                ContinueValueMetric, NanInfMetric, WuAucMetric)
}
