"""Asynchronous pass epilogue: serialized background end-pass write-back.

The reference overlaps its PRE-build thread against the open pass
(ps_gpu_wrapper.cc:913); this module overlaps the EPILOGUE too — the
``EndPass`` HBM→host dump (ps_gpu_wrapper.cc:983) leaves the critical
path, so device compute for pass N+1 starts while pass N's touched rows
are still draining to the host tier.

Contract (the tables build on it — ps/tiered.py, ps/pass_table.py):

- ``submit(fn)`` enqueues one write-back job and returns immediately.
  Jobs run STRICTLY IN SUBMISSION ORDER on a single worker, so two
  overlapping passes' write-backs of the same key land oldest-first and
  the host tier never observes a reordering.
- ``fence()`` blocks until every submitted job completed, then re-raises
  the first job failure (once). Every correctness surface must fence
  before reading or wholesale-mutating the host tier — the tables route
  all HostStore *read* entry points through ``HostStore.read_barrier``,
  so ``save``/``shrink``/``merge_model``/checkpoint capture/serving
  fetches each drain the epilogue implicitly.
- A job failure is NEVER silent: it is held until the next
  ``fence()``/``submit()`` surfaces it (the ``endpass.writeback`` fault
  seam in the tables exercises exactly this path).

The D2H gather itself is dispatched by the CALLER (end_pass) against the
then-current immutable device buffers — only the blocking ``device_get``
and the host-store update run here. Dispatch-before-return matters: a
later jit step may DONATE the table buffer, so the gather must already
be enqueued against it when end_pass returns.

Telemetry (docs/OBSERVABILITY.md, docs/PERFORMANCE.md): write-back /
fence-wait second counters, queue-depth gauge, and the cumulative
overlapped-seconds gauge ``pbox_endpass_overlap_sec`` = write-back time
that ran while nothing was fenced on it (the seconds the async epilogue
actually bought).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Deque, Dict, Optional, Tuple

from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class EndPassWritebackError(RuntimeError):
    """An asynchronous end-pass write-back failed. Raised at the first
    fence after the failure (host reads, the next stage fetch, save /
    shrink / checkpoint capture, or the next end_pass submit) — the
    failed pass's touched rows did NOT reach the host tier; recover by
    restoring a checkpoint, never by continuing."""


class PipelineHangError(RuntimeError):
    """A pipeline wait (epilogue fence / preload wait) made NO progress
    for ``FLAGS.pipeline_wait_timeout_sec`` — a worker is wedged (stuck
    IO, a deadlocked device transfer). The message names the stuck
    stage and dumps the queue-depth telemetry; raised INSTEAD of
    blocking forever so the straggler watchdog's grace window is spent
    on diagnosis, not on a silent hang. Progress is observed at
    whole-job granularity (a job/build COMPLETING resets the deadline),
    so a pipeline whose every job beats the deadline never trips it —
    but a single job slower than the deadline does, even if its worker
    is alive: set the timeout above the worst-case single job/build
    duration."""


def hang_timeout() -> float:
    """Shared hang-deadline infrastructure for all pipeline waits —
    public because train/device_pass.PassPreloader.wait consumes it
    alongside the fence below."""
    from paddlebox_tpu.config import FLAGS
    return float(FLAGS.pipeline_wait_timeout_sec)


def note_hang(stage: str) -> None:
    try:
        from paddlebox_tpu.obs.hub import get_hub
        get_hub().counter(
            "pbox_pipeline_hangs_total",
            "pipeline waits aborted by the hang deadline").inc(
                stage=stage)
    except Exception:
        log.debug("hang telemetry emit failed", exc_info=True)
    try:
        # black-box seam (obs/flightrec): the hang deadline tripping is
        # exactly when the live thread stacks in the bundle matter —
        # they name the wedged worker the PipelineHangError can't see
        from paddlebox_tpu.obs import flightrec
        flightrec.trigger("pipeline_hang", reason=f"stage {stage}",
                          stage=stage)
    except Exception:
        log.debug("flightrec trigger failed", exc_info=True)


def wait_with_deadline(cv: threading.Condition, done: Callable[[], bool],
                       progress: Callable[[], object], stage: str,
                       message: Callable[[], str]) -> None:
    """The ONE timed-condition-wait-with-hang-deadline loop, shared by
    every pipeline wait (``PassEpilogue.fence``,
    ``train/device_pass.PassPreloader.wait``). Call with ``cv`` HELD;
    returns once ``done()`` is true. With
    ``FLAGS.pipeline_wait_timeout_sec > 0``, an unchanged ``progress()``
    value for that long bumps the hang counter for ``stage`` and raises
    ``PipelineHangError`` with ``message()``."""
    hang = hang_timeout()
    deadline = (time.monotonic() + hang) if hang > 0 else None
    last = progress()
    while not done():
        if deadline is None:
            cv.wait()
            continue
        cv.wait(min(0.2, hang))
        cur = progress()
        if cur != last:  # progress resets the clock
            last = cur
            deadline = time.monotonic() + hang
        elif time.monotonic() > deadline:
            note_hang(stage)
            raise PipelineHangError(message())


def fence_under_pressure(lock: threading.Lock, fence: Callable[[], None],
                         pressure: Callable[[], bool]) -> float:
    """THE fence-outside-the-lock discipline for begin-boundary
    eviction, shared by the pass-window tables (ps/tiered.py,
    ps/pass_table.py). Call with ``lock`` HELD. While ``pressure()``
    holds and the epilogue hasn't been fenced yet: release the lock,
    ``fence()``, reacquire, re-check — the fence must never run under
    a lock the epilogue lane itself takes (``_evict_ahead`` takes
    ``host_lock``; fencing under it would deadlock the pipeline), and
    re-checking under the SAME lock hold as the following promote
    means pressure appearing between check and promote (a concurrent
    plan-assign) re-triggers the fence instead of evicting unfenced.
    Returns the fence-wait seconds; on return the lock is held again
    and either pressure() is False or the fence ran."""
    fence_sec = 0.0
    fenced = False
    while not fenced and pressure():
        lock.release()
        try:
            t0 = time.perf_counter()
            fence()
            fence_sec += time.perf_counter() - t0
            fenced = True
        finally:
            lock.acquire()
    return fence_sec


class PassEpilogue:
    """Single-lane background worker serializing end-pass write-backs."""

    def __init__(self, name: str = "endpass") -> None:
        self.name = name
        self._cv = threading.Condition(threading.Lock())
        self._jobs: Deque[Tuple[Callable[[], None], str, int]] = \
            collections.deque()
        self._submitted = 0
        self._done = 0
        self._running = False   # a drainer thread is live
        self._error: Optional[BaseException] = None
        # telemetry accumulators (read via stats(); the hub mirrors are
        # updated inline, guarded on hub.active)
        self.jobs_run = 0
        self.total_writeback_sec = 0.0
        self.total_fence_wait_sec = 0.0
        # fence waits on the MAIN thread only — the pipeline's critical
        # path. A stage thread fencing before its host fetch also waits,
        # but that wait itself overlaps training, so it must not count
        # against the overlap the epilogue bought.
        self.critical_fence_wait_sec = 0.0
        self.last_writeback_sec = 0.0

    # ---- submission ----------------------------------------------------
    def submit(self, fn: Callable[[], None], label: str = "",
               link_from: int = 0) -> None:
        """Enqueue a write-back job; returns immediately. Raises the
        previous job failure first (continuing to train atop a lost
        write-back would compound the damage silently). ``link_from``
        names the submitter's trace span (obs/trace) — the job's
        ``endpass.writeback`` span on the epilogue lane links back to
        it, so the Chrome trace draws the submit→drain hand-off."""
        with self._cv:
            self._raise_pending_locked()
            self._jobs.append((fn, label, link_from))
            self._submitted += 1
            depth = len(self._jobs)
            if not self._running:
                self._running = True
                threading.Thread(target=self._drain, daemon=True,
                                 name=f"pbox-{self.name}").start()
        self._mirror_depth(depth)

    def _drain(self) -> None:
        from paddlebox_tpu.obs import trace
        trace.set_lane(trace.LANE_EPILOGUE)
        while True:
            with self._cv:
                if not self._jobs:
                    self._running = False
                    self._cv.notify_all()
                    return
                fn, label, link = self._jobs.popleft()
            t0 = time.perf_counter()
            try:
                with trace.span("endpass.writeback", link_from=link,
                                job=label or self.name):
                    fn()
            except BaseException as e:  # held for the next fence
                log.error("async end_pass write-back failed (%s): %r",
                          label or self.name, e)
                with self._cv:
                    if self._error is None:
                        self._error = e
            dur = time.perf_counter() - t0
            with self._cv:
                self._done += 1
                self.jobs_run += 1
                self.last_writeback_sec = dur
                self.total_writeback_sec += dur
                depth = len(self._jobs)
                self._cv.notify_all()
            self._mirror_job(dur, depth)

    # ---- fencing -------------------------------------------------------
    @property
    def pending(self) -> int:
        with self._cv:
            return self._submitted - self._done

    def fence(self) -> None:
        """Wait for every submitted write-back to land, then surface the
        first failure (once). Cheap when nothing is queued: one lock
        round-trip. With ``FLAGS.pipeline_wait_timeout_sec > 0`` a wait
        that makes no progress for that long raises
        ``PipelineHangError`` naming this stage instead of blocking
        forever on a wedged worker."""
        t0 = time.perf_counter()
        critical = threading.current_thread() is threading.main_thread()
        with self._cv:
            if self._done >= self._submitted and self._error is None:
                return
            try:
                wait_with_deadline(
                    self._cv,
                    done=lambda: self._done >= self._submitted,
                    progress=lambda: self._done,
                    stage="endpass.writeback",
                    message=lambda: (
                        f"end-pass epilogue fence hung: stage "
                        f"'endpass.writeback' ({self.name}) made no "
                        f"progress for {hang_timeout():.1f}s — "
                        f"{self._submitted - self._done} job(s) "
                        f"outstanding (submitted={self._submitted}, "
                        f"done={self._done}, queued={len(self._jobs)}, "
                        f"worker_running={self._running}, "
                        f"last_writeback_sec="
                        f"{self.last_writeback_sec:.3f})"))
            except PipelineHangError:
                # the hang window still counts as fence wait — a
                # postmortem reconciling fence-wait counters against
                # wall time must see the stall, not a gap
                waited = time.perf_counter() - t0
                self.total_fence_wait_sec += waited
                if critical:
                    self.critical_fence_wait_sec += waited
                raise
            waited = time.perf_counter() - t0
            self.total_fence_wait_sec += waited
            if critical:
                self.critical_fence_wait_sec += waited
            err = self._take_error_locked()
        if waited > 1e-4:
            self._mirror_fence(waited)
        if err is not None:
            raise err

    def _take_error_locked(self) -> Optional[BaseException]:
        err, self._error = self._error, None
        if err is None:
            return None
        if isinstance(err, EndPassWritebackError):
            return err
        out = EndPassWritebackError(
            f"async end_pass write-back failed ({self.name}): {err!r} — "
            "the pass's touched rows did not reach the host tier")
        out.__cause__ = err
        return out

    def _raise_pending_locked(self) -> None:
        err = self._take_error_locked()
        if err is not None:
            raise err

    # ---- telemetry -----------------------------------------------------
    def stats(self) -> Dict[str, float]:
        """Cumulative accounting; ``overlap_sec`` = write-back seconds
        that never blocked the MAIN thread (writeback − critical fence
        waits, clamped ≥ 0) — the seconds the async epilogue took off
        the pass critical path. Background-thread fence waits (a stage
        fetch draining first) are reported separately: they themselves
        overlap training."""
        with self._cv:
            return {
                "pending": self._submitted - self._done,
                "jobs_run": self.jobs_run,
                "writeback_sec": self.total_writeback_sec,
                "fence_wait_sec": self.total_fence_wait_sec,
                "critical_fence_wait_sec": self.critical_fence_wait_sec,
                "last_writeback_sec": self.last_writeback_sec,
                "overlap_sec": max(
                    0.0, self.total_writeback_sec
                    - self.critical_fence_wait_sec),
            }

    def _mirror_depth(self, depth: int) -> None:
        hub = self._hub()
        if hub is not None:
            hub.gauge("pbox_endpass_queue_depth",
                      "end-pass write-back jobs queued").set(depth)

    def _mirror_job(self, dur: float, depth: int) -> None:
        hub = self._hub()
        if hub is None:
            return
        hub.counter("pbox_endpass_writebacks_total",
                    "async end-pass write-back jobs completed").inc()
        hub.counter("pbox_endpass_writeback_seconds_total",
                    "seconds spent in end-pass write-back jobs").inc(dur)
        hub.gauge("pbox_endpass_queue_depth",
                  "end-pass write-back jobs queued").set(depth)
        with self._cv:
            overlap = max(0.0, self.total_writeback_sec
                          - self.critical_fence_wait_sec)
        hub.gauge("pbox_endpass_overlap_sec",
                  "cumulative end-pass write-back seconds overlapped "
                  "with the next pass (writeback - fence waits)"
                  ).set(overlap)

    def _mirror_fence(self, waited: float) -> None:
        hub = self._hub()
        if hub is None:
            return
        hub.counter("pbox_endpass_fence_wait_seconds_total",
                    "seconds callers blocked on the epilogue fence"
                    ).inc(waited)
        # a critical fence just consumed overlap — refresh the gauge so
        # it tracks stats() (job completion alone would leave it stale)
        with self._cv:
            overlap = max(0.0, self.total_writeback_sec
                          - self.critical_fence_wait_sec)
        hub.gauge("pbox_endpass_overlap_sec",
                  "cumulative end-pass write-back seconds overlapped "
                  "with the next pass (writeback - fence waits)"
                  ).set(overlap)

    @staticmethod
    def _hub():
        from paddlebox_tpu.obs.hub import get_hub
        hub = get_hub()
        return hub if hub.active else None
