"""SSD third tier: disk-backed embedding segments behind the HostStore.

Reference capability: the BoxPS closed core is an HBM + host-mem + SSD
hierarchy — ``BeginFeedPass`` schedules SSD→mem promotion for the pass
working set (``LoadSSD2Mem``, box_wrapper.cc:1415) and the PSCore
``ssd_sparse_table`` keeps the long tail of a trillion-feature table on
disk. This module is the TPU-native third tier: rows the host RAM cannot
hold DEMOTE into append-only, log-structured segment files, and PROMOTE
back on demand (transparently inside ``HostStore.fetch`` — the stage
thread of the tiered pass pipeline, so promotion overlaps training the
way the PR 4/5 pipeline overlaps the epilogue and prologue).

Design (docs/STORAGE.md):

- **Segments** are append-only files of self-describing record blocks::

      [int64 n][uint64 keys[n]][uint8 touched[n]][f32 rows[n, width]]

  ``width`` is the logical row width (ps/table.NUM_FIXED + mf_dim +
  opt_ext — exactly the ``rows_from_store_fields`` layout, so a
  demote→promote round trip is bit-exact). A segment SEALS at
  ``FLAGS.ssd_segment_rows`` rows (or at manifest time) and is immutable
  from then on — the spill manifest can record its sha256 and a later
  restore can verify it like any checkpoint chain link.
- **Index**: one in-memory ``key → (segment, byte offset, touched)``
  map. Promoted (or superseded) keys leave the index immediately, so a
  stale on-disk copy can never resurrect into a fetch or a base export;
  rows they leave behind are DEAD and only compaction reclaims them.
- **Touched bit**: a demoted row whose update has not been exported yet
  carries ``touched=True`` through the tier; ``export_rows(delta=True)``
  returns it and promotion restores the flag — demotion never loses a
  pending ``save_delta`` row.
- **Compaction**: ``maybe_compact`` rewrites sealed segments whose live
  fraction fell below ``FLAGS.ssd_compact_live_frac`` (live rows
  re-append, the old file unlinks). Segments are never rewritten in
  place, so a manifested (sealed) file either exists with its recorded
  digest or is gone — a sha256 mismatch on restore is always real
  corruption (``SegmentCorruptError`` / ``CheckpointCorruptError``).
- **Fault seam** ``ssd.io`` fires on every segment file read/write/
  unlink; transient failures retry on the seeded ``RetryPolicy``
  (site ``ssd.io``), so scripts/chaos_check.py can prove recovery.

Durability contract: the tier is a CAPACITY tier, not the durability
root — checkpoints stay self-contained (``save_base`` merges the tier,
``save_delta`` merges its touched rows) and the spill manifest recorded
in checkpoint meta (train/checkpoint.py) lets a restore verify that the
segment files it may promote from again are intact.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from paddlebox_tpu.resilience import faults
from paddlebox_tpu.resilience.retry import RetryPolicy
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

_BLOCK_HDR = np.dtype(np.int64).itemsize


class SegmentCorruptError(RuntimeError):
    """A segment file's content does not match the spill manifest —
    refuse to promote from it (train/checkpoint.py re-raises this as
    ``CheckpointCorruptError`` on restore)."""


def _io_retry() -> RetryPolicy:
    """Segment file IO runs under the flag-configured retry policy —
    the same transient-NFS story as checkpoint.io."""
    return RetryPolicy.from_flags(site="ssd.io")


def file_sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            buf = fh.read(chunk)
            if not buf:
                break
            h.update(buf)
    return h.hexdigest()


class _Segment:
    __slots__ = ("seg_id", "path", "rows", "live", "nbytes", "sealed",
                 "external", "pending", "sha256")

    def __init__(self, seg_id: int, path: str,
                 external: bool = False) -> None:
        self.seg_id = seg_id
        self.path = path
        self.rows = 0      # rows ever appended (reserved included)
        self.live = 0      # rows still indexed
        self.nbytes = 0
        self.sealed = False
        # external = a caller-addressed spill file (spill_cold compat):
        # an immutable snapshot the caller may re-read from another
        # process — drop it from the registry when dead, never unlink
        self.external = external
        # blocks reserved by an in-flight append (disk write outside
        # the index lock) — guards the file against dead-segment unlink
        self.pending = 0
        # sha256 cached at first manifest after sealing (immutable from
        # then on — every checkpoint after the first reuses it)
        self.sha256: Optional[str] = None


def read_segment_file(path: str, width: int
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Scan a whole segment file → (keys, rows [k, width], touched).
    Later blocks supersede earlier ones for duplicate keys (append
    order), mirroring the in-memory index semantics — this is how a
    FRESH process adopts a spill file (``HostStore.load_from_disk``
    compat path) without any tier state."""
    def scan():
        faults.inject("ssd.io", path=path, op=f"read:{path}")
        with open(path, "rb") as fh:
            raw = fh.read()
        return raw
    raw = _io_retry().call(scan)
    keys_l: List[np.ndarray] = []
    rows_l: List[np.ndarray] = []
    tch_l: List[np.ndarray] = []
    off = 0
    while off < len(raw):
        if off + _BLOCK_HDR > len(raw):
            raise SegmentCorruptError(
                f"{path}: truncated block header at byte {off}")
        n = int(np.frombuffer(raw, np.int64, count=1, offset=off)[0])
        off += _BLOCK_HDR
        need = n * 8 + n + n * width * 4
        if n < 0 or off + need > len(raw):
            raise SegmentCorruptError(
                f"{path}: torn block (n={n}) at byte {off - _BLOCK_HDR}")
        keys_l.append(np.frombuffer(raw, np.uint64, count=n, offset=off))
        off += n * 8
        tch_l.append(np.frombuffer(raw, np.uint8, count=n, offset=off))
        off += n
        rows_l.append(np.frombuffer(
            raw, np.float32, count=n * width,
            offset=off).reshape(n, width))
        off += n * width * 4
    if not keys_l:
        return (np.empty(0, np.uint64), np.empty((0, width), np.float32),
                np.empty(0, bool))
    keys = np.concatenate(keys_l)
    rows = np.concatenate(rows_l)
    tch = np.concatenate(tch_l).astype(bool)
    # last write wins per key
    _, last = np.unique(keys[::-1], return_index=True)
    sel = len(keys) - 1 - last
    return keys[sel], rows[sel].copy(), tch[sel]


class SsdTier:
    """Disk tier of one ``HostStore``: log-structured segments + an
    in-memory key→location index. Thread-safe (demote runs on the
    async-epilogue worker while the stage thread promotes)."""

    def __init__(self, root: str, width: int,
                 segment_rows: Optional[int] = None,
                 compact_live_frac: Optional[float] = None,
                 name: str = "ssd") -> None:
        from paddlebox_tpu.config import FLAGS
        self.root = root
        self.width = int(width)
        self.name = name
        self.segment_rows = int(segment_rows or FLAGS.ssd_segment_rows)
        self.compact_live_frac = (FLAGS.ssd_compact_live_frac
                                  if compact_live_frac is None
                                  else float(compact_live_frac))
        os.makedirs(root, exist_ok=True)
        # a previous process's leftover segments are unreachable (their
        # index died with it) and APPENDING to one would hand out byte
        # offsets into the old content — sweep them. The tier is a
        # capacity cache: checkpoints are self-contained, and a spill
        # manifest treats missing segments as legitimately gone.
        stale = [n for n in sorted(os.listdir(root))
                 if n.startswith("seg-") and n.endswith(".pbseg")]
        for n in stale:
            try:
                os.unlink(os.path.join(root, n))
            except OSError:
                log.warning("ssd tier (%s): could not sweep stale "
                            "segment %s", name, n, exc_info=True)
        if stale:
            log.warning(
                "ssd tier (%s): swept %d leftover segment file(s) from "
                "a previous process out of %s — the tier is a capacity "
                "cache; restore re-imports every row from the "
                "checkpoint", name, len(stale), root)
        # _lock guards the index + segment registry; _io_lock
        # serializes segment WRITERS (append order must match offset
        # reservation order). Disk writes run under _io_lock only, so
        # a concurrent promote (take — index lock + committed-block
        # reads) never waits out a demote's segment write.
        self._lock = threading.RLock()
        self._io_lock = threading.Lock()
        # key -> (seg_id, byte offset of the row's f32 block, touched)
        self._index: Dict[int, Tuple[int, int, bool]] = {}
        self._segments: Dict[int, _Segment] = {}
        self._next_seg = 0
        self._active: Optional[int] = None
        # cumulative accounting (ssd_check / bench / obs mirrors)
        self.demoted_rows = 0
        self.promoted_rows = 0
        self.compacted_rows = 0
        self.demote_sec = 0.0
        self.promote_sec = 0.0
        # promote seconds spent on the MAIN thread — the critical-path
        # share (a stage-thread promote overlaps training, exactly like
        # the epilogue's critical_fence_wait accounting)
        self.promote_wait_sec = 0.0

    # ---- introspection -------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    @property
    def live_rows(self) -> int:
        return len(self)

    def segment_paths(self) -> List[str]:
        """Paths of segments still holding live rows (oldest first) —
        the ``HostStore._spill_files`` compat view."""
        with self._lock:
            return [s.path for s in
                    sorted(self._segments.values(),
                           key=lambda s: s.seg_id) if s.live > 0]

    def has_live_path(self, path: str) -> bool:
        with self._lock:
            return any(s.path == path and s.live > 0
                       for s in self._segments.values())

    def keys_in_path(self, path: str) -> np.ndarray:
        """Live keys whose current copy resides in the segment(s) at
        ``path`` (the load_from_disk compat view of one spill file)."""
        with self._lock:
            sids = {sid for sid, s in self._segments.items()
                    if s.path == path}
            if not sids:
                return np.empty(0, np.uint64)
            out = [k for k, loc in self._index.items() if loc[0] in sids]
            return np.array(sorted(out), np.uint64)

    def contains(self, keys: np.ndarray) -> np.ndarray:
        idx = self._index
        with self._lock:
            return np.fromiter((int(k) in idx for k in keys),
                               bool, count=len(keys))

    def keys(self) -> np.ndarray:
        with self._lock:
            return np.fromiter(self._index.keys(), np.uint64,
                               count=len(self._index))

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "live_rows": len(self._index),
                "segments": sum(1 for s in self._segments.values()
                                if s.rows > 0),
                "bytes": sum(s.nbytes for s in self._segments.values()),
                "demoted_rows": self.demoted_rows,
                "promoted_rows": self.promoted_rows,
                "compacted_rows": self.compacted_rows,
                "demote_sec": self.demote_sec,
                "promote_sec": self.promote_sec,
                "promote_wait_sec": self.promote_wait_sec,
            }

    # ---- write path (demotion) -----------------------------------------
    def _new_segment(self, path: Optional[str] = None) -> _Segment:
        seg_id = self._next_seg
        self._next_seg += 1
        external = path is not None
        if path is None:
            path = os.path.join(self.root, f"seg-{seg_id:06d}.pbseg")
        seg = _Segment(seg_id, path, external=external)
        self._segments[seg_id] = seg
        return seg

    @staticmethod
    def _block_blob(keys: np.ndarray, rows: np.ndarray,
                    touched: np.ndarray) -> bytes:
        return (np.int64(len(keys)).tobytes()
                + np.ascontiguousarray(keys, np.uint64).tobytes()
                + np.ascontiguousarray(touched, np.uint8).tobytes()
                + np.ascontiguousarray(rows, np.float32).tobytes())

    def _write_at(self, seg: _Segment, base: int, blob: bytes) -> None:
        """Write one reserved block at byte ``base`` (caller holds
        ``_io_lock``, NOT ``_lock``). Truncate-then-write makes a
        retried attempt idempotent: a torn earlier try can never leave
        the file longer than its reservation."""
        def write() -> None:
            faults.inject("ssd.io", path=seg.path, op=f"append:{seg.path}")
            mode = "r+b" if os.path.exists(seg.path) else "wb"
            with open(seg.path, mode) as fh:
                fh.seek(0, os.SEEK_END)
                if fh.tell() > base:
                    fh.truncate(base)   # torn previous attempt
                fh.seek(base)
                fh.write(blob)
        _io_retry().call(write)

    def _commit_block(self, seg: _Segment, data_off: int,
                      keys: np.ndarray, touched: np.ndarray) -> None:
        """Index one written block (caller holds ``_lock``): re-appended
        keys supersede their old copy — the old row goes dead."""
        for i, k in enumerate(keys.tolist()):
            old = self._index.get(k)
            if old is not None:
                self._segments[old[0]].live -= 1
            self._index[k] = (seg.seg_id, data_off + i * self.width * 4,
                              bool(touched[i]))
        seg.live += len(keys)

    def append(self, keys: np.ndarray, rows: np.ndarray,
               touched: Optional[np.ndarray] = None,
               book: bool = True) -> int:
        """Demote ``rows`` (logical [k, width] layout) under ``keys``;
        returns the number of rows written. Three-step so the disk
        write blocks neither a concurrent promote nor the index:
        reserve the block's offsets under ``_lock``, write under
        ``_io_lock`` alone, then commit the index under ``_lock``
        (readers only ever see fully-written blocks).

        ``book=False`` (compaction's internal rewrite) skips the
        demote counters/timers and the telemetry mirror."""
        if len(keys) == 0:
            return 0
        keys = np.ascontiguousarray(keys, np.uint64)
        if touched is None:
            touched = np.zeros(len(keys), bool)
        n = len(keys)
        t0 = time.perf_counter()
        blob = self._block_blob(keys, rows, touched)
        with self._io_lock:
            with self._lock:
                seg = (self._segments.get(self._active)
                       if self._active is not None else None)
                if seg is None or seg.sealed \
                        or seg.rows >= self.segment_rows:
                    seg = self._new_segment()
                    self._active = seg.seg_id
                base = seg.nbytes
                seg.nbytes += len(blob)
                seg.rows += n
                seg.pending += 1
                sealed_here = seg.rows >= self.segment_rows
                if sealed_here:
                    seg.sealed = True
                    self._active = None
            try:
                self._write_at(seg, base, blob)
            except BaseException:
                with self._lock:   # roll the reservation back — the
                    seg.nbytes = base          # next append must land
                    seg.rows -= n              # at the true file end
                    seg.pending -= 1
                    if sealed_here:
                        seg.sealed = False
                        self._active = seg.seg_id
                raise
            with self._lock:
                self._commit_block(seg, base + _BLOCK_HDR + n * 8 + n,
                                   keys, touched)
                seg.pending -= 1
                if book:
                    self.demoted_rows += n
                    self.demote_sec += time.perf_counter() - t0
        if book:
            self._mirror()
        return n

    def append_sealed_file(self, path: str, keys: np.ndarray,
                           rows: np.ndarray,
                           touched: Optional[np.ndarray] = None) -> int:
        """One-shot sealed segment at an explicit ``path`` — the
        ``spill_cold`` compat shim (each manual spill stays one
        addressable, immutable file). Refuses a path that is already a
        live segment (overwriting would lose its still-spilled rows)."""
        keys = np.ascontiguousarray(keys, np.uint64)
        if touched is None:
            touched = np.zeros(len(keys), bool)
        n = len(keys)
        blob = self._block_blob(keys, rows, touched)
        with self._io_lock:
            with self._lock:
                for s in self._segments.values():
                    if s.path == path and s.live > 0:
                        raise ValueError(
                            f"{path} already holds an active spill — "
                            "overwriting would lose its still-spilled "
                            "rows; use a fresh path per spill")
                seg = self._new_segment(path)
                seg.nbytes = len(blob)
                seg.rows = n
                seg.pending += 1
                seg.sealed = True
            try:
                if os.path.exists(path):
                    self._unlink(path)
                self._write_at(seg, 0, blob)
            except BaseException:
                with self._lock:
                    self._segments.pop(seg.seg_id, None)
                raise
            with self._lock:
                self._commit_block(seg, _BLOCK_HDR + n * 8 + n,
                                   keys, touched)
                seg.pending -= 1
                self.demoted_rows += n
        self._mirror()
        return n

    # ---- read path (promotion) -----------------------------------------
    def _read_rows(self, path: str, offs: np.ndarray) -> np.ndarray:
        """Gather rows at byte offsets ``offs`` from one segment file."""
        def read() -> np.ndarray:
            faults.inject("ssd.io", path=path, op=f"read:{path}")
            mm = np.memmap(path, dtype=np.uint8, mode="r")
            out = np.empty((len(offs), self.width), np.float32)
            w = self.width * 4
            for i, off in enumerate(offs.tolist()):
                out[i] = np.frombuffer(mm[off:off + w].tobytes(),
                                       np.float32)
            del mm
            return out
        return _io_retry().call(read)

    def take(self, keys: np.ndarray, book: bool = True
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Promote: read + REMOVE ``keys`` (the found subset) from the
        tier → (found_keys, rows [k, width], touched). Promoted keys
        leave the index atomically with the read, so no later fetch or
        export can observe the stale disk copy. ``book=False``
        (compaction) skips the promote counters/timers + mirror."""
        if len(keys) == 0:
            return (np.empty(0, np.uint64),
                    np.empty((0, self.width), np.float32),
                    np.empty(0, bool))
        t0 = time.perf_counter()
        critical = threading.current_thread() is threading.main_thread()
        with self._lock:
            found: List[int] = []
            locs: List[Tuple[int, int, bool]] = []
            seen = set()   # a duplicated key promotes (and deletes) once
            for k in np.ascontiguousarray(keys, np.uint64).tolist():
                ik = int(k)
                if ik in seen:
                    continue
                loc = self._index.get(ik)
                if loc is not None:
                    seen.add(ik)
                    found.append(k)
                    locs.append(loc)
            if not found:
                return (np.empty(0, np.uint64),
                        np.empty((0, self.width), np.float32),
                        np.empty(0, bool))
            fkeys = np.array(found, np.uint64)
            segs = np.array([l[0] for l in locs], np.int64)
            offs = np.array([l[1] for l in locs], np.int64)
            tch = np.array([l[2] for l in locs], bool)
            rows = np.empty((len(fkeys), self.width), np.float32)
            for sid in np.unique(segs):
                m = segs == sid
                rows[m] = self._read_rows(self._segments[int(sid)].path,
                                          offs[m])
            # removal AFTER the read succeeded: a transient read failure
            # (retried/raised above) must not lose the rows
            for k, sid in zip(found, segs.tolist()):
                del self._index[int(k)]
                self._segments[int(sid)].live -= 1
            self._drop_dead_segments()
            if book:
                self.promoted_rows += len(fkeys)
                dur = time.perf_counter() - t0
                self.promote_sec += dur
                if critical:
                    self.promote_wait_sec += dur
        if book:
            self._mirror()
        return fkeys, rows, tch

    def export_rows(self, delta: bool = False, clear_touched: bool = True
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Snapshot (keys, rows, touched) of every live row —
        ``delta=True`` restricts to touched rows (a pending
        ``save_delta`` export) and, with ``clear_touched``, marks them
        exported. Rows stay in the tier (export is a read)."""
        with self._lock:
            items = [(k, loc) for k, loc in self._index.items()
                     if not delta or loc[2]]
            if not items:
                return (np.empty(0, np.uint64),
                        np.empty((0, self.width), np.float32),
                        np.empty(0, bool))
            fkeys = np.array([k for k, _ in items], np.uint64)
            segs = np.array([loc[0] for _, loc in items], np.int64)
            offs = np.array([loc[1] for _, loc in items], np.int64)
            tch = np.array([loc[2] for _, loc in items], bool)
            rows = np.empty((len(fkeys), self.width), np.float32)
            for sid in np.unique(segs):
                m = segs == sid
                rows[m] = self._read_rows(self._segments[int(sid)].path,
                                          offs[m])
            if clear_touched:
                for k in fkeys.tolist():
                    sid, off, _ = self._index[int(k)]
                    self._index[int(k)] = (sid, off, False)
            return fkeys, rows, tch

    def clear_touched(self) -> int:
        """Drop the pending-delta bit from every tier row — the
        post-commit half of a STAGED export (HostStore.
        clear_touched_flags): index-only, no segment IO. Returns how
        many rows were marked."""
        n = 0
        with self._lock:
            for k, (sid, off, tch) in list(self._index.items()):
                if tch:
                    self._index[k] = (sid, off, False)
                    n += 1
        return n

    def discard(self, keys: np.ndarray) -> int:
        """Drop keys from the tier (shrink-deleted features, superseded
        demote snapshots) — their rows go dead; no stale copy can
        resurrect. Returns how many were present."""
        n = 0
        with self._lock:
            for k in np.ascontiguousarray(keys, np.uint64).tolist():
                loc = self._index.pop(int(k), None)
                if loc is not None:
                    self._segments[loc[0]].live -= 1
                    n += 1
            if n:
                self._drop_dead_segments()
        if n:
            self._mirror()
        return n

    def shrink(self, delete_threshold: float, decay: float,
               nonclk_coeff: float = 0.1, clk_coeff: float = 1.0,
               batch: int = 65536) -> int:
        """Age DEMOTED rows — the disk half of ShrinkTable (ctr_accessor
        shrink rules applied to rows RAM never sees): decay
        show/clk/delta_score, drop rows whose decayed score falls below
        threshold, rewrite the survivors. Rewrites go through
        take/append with ``book=False`` (compaction-style internal
        churn, not demote/promote traffic), so the vacated copies age
        their old segments toward ``maybe_compact``'s live-fraction
        trigger and fully-dead segments unlink immediately. Survivors'
        pending-delta (touched) bits are preserved; the decayed values
        themselves are NOT re-marked touched — a shrink cycle must be
        followed by a BASE save (train/checkpoint), which captures every
        live row regardless. Batched so the working set stays bounded on
        a large tier. Returns rows dropped."""
        keys = self.keys()
        dropped = 0
        for i in range(0, len(keys), batch):
            fkeys, rows, tch = self.take(keys[i:i + batch], book=False)
            if not len(fkeys):
                continue
            rows[:, 0:3] *= decay  # decay show/clk/delta_score
            score = (nonclk_coeff * (rows[:, 0] - rows[:, 1])
                     + clk_coeff * rows[:, 1])
            keep = score >= delete_threshold
            dropped += int((~keep).sum())
            if keep.any():
                self.append(fkeys[keep], rows[keep],
                            touched=tch[keep], book=False)
        if dropped:
            self._mirror()
        return dropped

    def clear(self) -> None:
        """Reset the tier (a wholesale host-store load: the old model's
        tiers don't carry over). Segment files unlink — they belong to
        the discarded model. Takes the writer lock too, so no in-flight
        append can land a block in an unlinked file."""
        with self._io_lock, self._lock:
            for s in self._segments.values():
                if not s.external and os.path.exists(s.path):
                    self._unlink(s.path)
            self._segments.clear()
            self._index.clear()
            self._active = None
        self._mirror()

    # ---- compaction ----------------------------------------------------
    def _drop_dead_segments(self) -> None:
        """Unlink segments with zero live rows (caller holds lock).
        Segments with a reserved-but-uncommitted block (``pending``)
        are about to gain live rows — never unlink under a writer."""
        dead = [sid for sid, s in self._segments.items()
                if s.live <= 0 and s.rows > 0 and s.pending == 0
                and sid != self._active]
        for sid in dead:
            s = self._segments.pop(sid)
            if not s.external and os.path.exists(s.path):
                self._unlink(s.path)

    def _unlink(self, path: str) -> None:
        def rm() -> None:
            faults.inject("ssd.io", path=path, op=f"unlink:{path}")
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
        _io_retry().call(rm)

    def maybe_compact(self) -> int:
        """Rewrite sealed segments whose live fraction fell below
        ``compact_live_frac``: live rows re-append (index moves with
        them), the old file unlinks. Returns rows rewritten. Runs on
        the background demote worker — never on the pass critical
        path."""
        thr = self.compact_live_frac
        if thr <= 0:
            return 0
        moved = 0
        seen = set()
        while True:
            with self._lock:
                victim = None
                for sid in sorted(self._segments):
                    s = self._segments[sid]
                    if (s.sealed and sid != self._active and s.rows > 0
                            and sid not in seen
                            and 0 < s.live < thr * s.rows):
                        victim = sid
                        break
                if victim is None:
                    break
                seen.add(victim)
                live_keys = np.array(
                    [k for k, loc in self._index.items()
                     if loc[0] == victim], np.uint64)
            # rewrite OUTSIDE the index lock (append takes the writer
            # lock — holding _lock across it would invert the locking
            # order); book=False keeps the rows out of the real
            # demote/promote accounting and off the promote-wait
            # critical-path attribution. A key promoted between the
            # snapshot and the take simply isn't rewritten.
            fkeys, rows, tch = self.take(live_keys, book=False)
            if len(fkeys):
                self.append(fkeys, rows, tch, book=False)
                with self._lock:
                    self.compacted_rows += len(fkeys)
                moved += len(fkeys)
        if moved:
            log.info("ssd compact (%s): rewrote %d live rows", self.name,
                     moved)
            self._mirror()
        return moved

    # ---- spill manifest (checkpoint integration) -----------------------
    def manifest(self) -> Optional[dict]:
        """Seal the active segment and describe the tier for checkpoint
        meta: per-segment path + sha256 + row accounting. Sealing means
        every manifested file is immutable from here on — appends after
        this checkpoint open a NEW segment, so a digest mismatch on
        restore is always real corruption, never a legitimate append."""
        # writer lock first: an in-flight append must commit before we
        # seal/hash (no half-written tail can enter a digest)
        with self._io_lock, self._lock:
            if self._active is not None:
                seg = self._segments.get(self._active)
                if seg is not None and seg.rows > 0:
                    seg.sealed = True
                self._active = None
            segs = [s for s in sorted(self._segments.values(),
                                      key=lambda s: s.seg_id)
                    if s.live > 0]
            if not segs:
                return None
            for s in segs:   # sealed => immutable: hash once, reuse
                if s.sha256 is None:
                    s.sha256 = _io_retry().call(file_sha256, s.path)
            m = {
                "width": self.width,
                "live_rows": len(self._index),
                "segments": [{
                    "path": os.path.abspath(s.path),
                    "sha256": s.sha256,
                    "rows": int(s.rows),
                    "live": int(s.live),
                } for s in segs],
            }
            # one digest NAMING this tier state — what an artifact
            # manifest records as its spill-manifest REFERENCE
            # (artifacts.py refs block): location-independent (segment
            # basenames, not paths), so the same tier content yields
            # the same reference wherever the registry lives
            m["digest"] = manifest_digest(m)
            return m

    # ---- telemetry -----------------------------------------------------
    _MIRRORED = (("demoted_rows", "pbox_ssd_demoted_rows_total",
                  "rows demoted host-RAM -> SSD tier"),
                 ("promoted_rows", "pbox_ssd_promoted_rows_total",
                  "rows promoted SSD tier -> host RAM"),
                 ("compacted_rows", "pbox_ssd_compacted_rows_total",
                  "live rows rewritten by segment compaction"),
                 ("demote_sec", "pbox_ssd_demote_seconds_total",
                  "seconds spent writing demoted rows to segments"),
                 ("promote_sec", "pbox_ssd_promote_seconds_total",
                  "seconds spent reading promoted rows from segments"),
                 ("promote_wait_sec",
                  "pbox_ssd_promote_wait_seconds_total",
                  "promote seconds paid on the MAIN thread (critical "
                  "path; stage-thread promotes overlap training)"))

    def _mirror(self) -> None:
        """Mirror the cumulative accounting into hub counters (inc by
        delta since the last mirror) + occupancy gauges."""
        try:
            from paddlebox_tpu.obs.hub import get_hub
            hub = get_hub()
            if not hub.active:
                return
            st = self.stats()
            last = getattr(self, "_mirrored", None)
            if last is None:
                last = self._mirrored = {}
            for attr, name, help_ in self._MIRRORED:
                delta = st[attr] - last.get(attr, 0.0)
                if delta > 0:
                    hub.counter(name, help_).inc(delta)
                last[attr] = st[attr]
            hub.gauge("pbox_ssd_segments",
                      "live SSD tier segment files").set(st["segments"])
            hub.gauge("pbox_ssd_bytes",
                      "bytes held by SSD tier segments").set(st["bytes"])
            hub.gauge("pbox_ssd_live_rows",
                      "rows resident only in the SSD tier").set(
                          st["live_rows"])
        except Exception:
            log.debug("ssd telemetry mirror failed", exc_info=True)


def manifest_digest(manifest: dict) -> str:
    """Stable sha256 naming a spill manifest's CONTENT: the sorted
    (segment basename, sha256, rows) triples + width/live_rows. Used
    as the spill-manifest reference in artifact manifests
    (artifacts.py / train/checkpoint._publish_artifact) — two
    checkpoints whose tiers hold the same bytes reference the same
    digest, path layout notwithstanding."""
    h = hashlib.sha256()
    h.update(f"w{manifest.get('width')}:n{manifest.get('live_rows')}"
             .encode())
    for seg in sorted(manifest.get("segments", []),
                      key=lambda s: os.path.basename(s["path"])):
        h.update(os.path.basename(seg["path"]).encode())
        h.update(str(seg["sha256"]).encode())
        h.update(str(seg.get("rows", 0)).encode())
    return h.hexdigest()


def verify_manifest(manifest: dict) -> List[str]:
    """Check every manifested segment still on disk against its
    recorded sha256; raises ``SegmentCorruptError`` on the first
    mismatch. Missing files are FINE (compaction unlinks segments and
    a tier reset clears them — the checkpoint itself is self-contained)
    and are returned for the caller's log."""
    missing: List[str] = []
    for seg in manifest.get("segments", []):
        path = seg["path"]
        if not os.path.isfile(path):
            missing.append(path)
            continue
        got = _io_retry().call(file_sha256, path)
        if got != seg["sha256"]:
            raise SegmentCorruptError(
                f"SSD segment {path} is corrupt: sha256 {got[:12]}… != "
                f"manifest {seg['sha256'][:12]}… — refuse to trust the "
                "spill tier; restore re-imports rows from the "
                "checkpoint itself after the operator clears the tier "
                "directory")
    return missing
