"""Multi-mf × sharded: per-slot embedding dims on the mesh PS.

Reference: the dynamic-mf accessor IS the sharded multi-GPU PS's value
layout — ``CommonFeatureValueAccessor`` (feature_value.h:42-185) with the
multi-mf build pipeline running per dim class across GPUs
(ps_gpu_wrapper.cc BuildGPUTask multi_mf paths).

TPU-native composition: one :class:`ShardedEmbeddingTable` per dim class
(each with its static row width and its own key%N shard layout over the
SAME mesh), routed by the shared :class:`SlotClassMap`. A global batch
yields C per-class routing plans; the mesh train step runs C pull/push
all_to_all pairs inside one jit program and concatenates the pooled
blocks in canonical slot order (train/multi_mf_sharded.py)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.ps.multi_mf import SlotClassMap
from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable, ShardedPullIndex
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class MultiMfShardedTable(SlotClassMap):
    """One ShardedEmbeddingTable per distinct slot mf_dim, same mesh."""

    def __init__(self, num_shards: int, slot_mf_dims: Sequence[int],
                 capacity_per_shard: Optional[int] = None,
                 capacity_per_class: Optional[Dict[int, int]] = None,
                 cfg: Optional[SparseSGDConfig] = None,
                 req_bucket_min: int = 512,
                 serve_bucket_min: int = 1024, **table_kw) -> None:
        super().__init__(slot_mf_dims)
        self.n = num_shards
        self.cfg = cfg or SparseSGDConfig()
        caps = capacity_per_class or {}
        self.tables: List[ShardedEmbeddingTable] = [
            self._make_class_table(
                num_shards, d,
                capacity_per_shard=caps.get(d, capacity_per_shard),
                cfg=cfg, req_bucket_min=req_bucket_min,
                serve_bucket_min=serve_bucket_min, **table_kw)
            for d in self.dims]

    def _make_class_table(self, num_shards: int, mf_dim: int, **kw):
        return ShardedEmbeddingTable(num_shards, mf_dim=mf_dim, **kw)

    # ------------------------------------------------------------------
    def prepare_global(self, batches: List[SlotBatch], assign: bool = True,
                       req_capacities: Optional[List[int]] = None,
                       serve_capacities: Optional[List[int]] = None
                       ) -> List[ShardedPullIndex]:
        """[N] device batches → per-class routing plans. serve_slot is
        remapped from class-local slot ranks (the sub-batch numbering)
        back to GLOBAL slot ids, so the persisted FeatureValue slot field
        stays globally meaningful (feature_value.h:570)."""
        subs = [self.split_batch(b)[0] for b in batches]   # [N][C]
        return self.prepare_global_from_subs(
            subs, assign=assign, req_capacities=req_capacities,
            serve_capacities=serve_capacities)

    def prepare_global_from_subs(self, subs, assign: bool = True,
                                 req_capacities=None,
                                 serve_capacities=None
                                 ) -> List[ShardedPullIndex]:
        """prepare_global over ALREADY-SPLIT per-class sub-batches
        (``subs[d][c]`` from split_batch) — callers that also need the
        sub-batches (segments) split once, not twice."""
        plans = []
        for c, t in enumerate(self.tables):
            plan = t.prepare_global(
                [subs[d][c] for d in range(len(subs))], assign=assign,
                req_capacity=(req_capacities[c] if req_capacities
                              else None),
                serve_capacity=(serve_capacities[c] if serve_capacities
                                else None))
            gslot = self.class_slots[c][
                plan.serve_slot.astype(np.int32)].astype(np.float32)
            plans.append(plan._replace(serve_slot=gslot))
        return plans

    def prepare_global_eval(self, batches: List[SlotBatch]
                            ) -> List[ShardedPullIndex]:
        return self.prepare_global(batches, assign=False)

    # ---- lifecycle: delegate per class (multi-mf save format) ----
    def feature_count(self) -> int:
        return sum(t.feature_count() for t in self.tables)

    def save_base(self, path: str) -> int:
        return sum(t.save_base(f"{path}.mf{d}.npz")
                   for t, d in zip(self.tables, self.dims))

    def save_delta(self, path: str) -> int:
        return sum(t.save_delta(f"{path}.mf{d}.npz")
                   for t, d in zip(self.tables, self.dims))

    def load(self, path: str, merge: bool = False) -> int:
        return sum(t.load(f"{path}.mf{d}.npz", merge=merge)
                   for t, d in zip(self.tables, self.dims))

    def shrink(self, **kw) -> int:
        return sum(t.shrink(**kw) for t in self.tables)

    def merge_model(self, path: str) -> int:
        return sum(t.merge_model(f"{path}.mf{d}.npz")
                   for t, d in zip(self.tables, self.dims))

    def merge_models(self, paths, update_type: str = "stats") -> int:
        """MergeMultiModels across dim classes (box_wrapper.h:812-815) —
        defined once here; the tiered subclass inherits it and its calls
        dispatch to the tiered merge_model/load overrides."""
        if update_type not in ("stats", "overwrite"):
            raise ValueError(f"unknown update_type {update_type!r}")
        return sum((self.merge_model(p) if update_type == "stats"
                    else self.load(p, merge=True)) for p in paths)

    def split_keys_by_class(self, keys: np.ndarray, slots: np.ndarray):
        """Unique (key, slot-class) routing for pass working sets: each
        key goes to its slot's class table. Returns per-class key
        arrays."""
        keys = np.ascontiguousarray(keys, np.uint64)
        slots = np.asarray(slots, np.int32)
        cls = self.class_of_slot[slots]
        return [np.unique(keys[cls == c]) for c in range(self.num_classes)]

    def pull(self, keys: np.ndarray, slots: np.ndarray) -> np.ndarray:
        """Host-side per-key pull padded to the MAX class width — the
        dy_mf CopyForPull contract; routes each key to its slot's class
        table, then to its owner shard inside it. Unknown keys zeros."""
        import jax
        from paddlebox_tpu.ps.table import host_pull_block
        keys = np.ascontiguousarray(keys, np.uint64)
        slots = np.asarray(slots, np.int32)
        out = np.zeros((len(keys), 3 + max(self.dims)), np.float32)
        for c, t in enumerate(self.tables):
            m = self.class_of_slot[slots] == c
            if not m.any():
                continue
            kc = keys[m]
            data = np.asarray(jax.device_get(t.state.data))
            vals = np.zeros((len(kc), 3 + t.mf_dim), np.float32)
            owners = (kc % np.uint64(t.n)).astype(np.int64)
            for s in range(t.n):
                sm = owners == s
                if not sm.any():
                    continue
                rows = t.indexes[s].lookup(kc[sm])
                known = rows >= 0
                block = host_pull_block(data[s][rows[known]], t.mf_dim)
                tmp = np.zeros((int(sm.sum()), 3 + t.mf_dim), np.float32)
                tmp[known] = block
                vals[np.nonzero(sm)[0]] = tmp
            out[np.nonzero(m)[0], :vals.shape[1]] = vals
        return out


class MultiMfTieredShardedTable(MultiMfShardedTable):
    """Per-slot embedding dims × beyond-HBM tiering × mesh sharding — the
    full cross-product: each dim class is a TieredShardedEmbeddingTable
    (per-shard HostStores with pass windows), routed by the shared
    SlotClassMap. The pass lifecycle fans out across classes; the
    lifecycle/save surface is inherited (per-class delegation, and each
    class table's methods already run on its host tier).

    Pass keys must arrive WITH their slots (``stage(keys, slots)``) —
    a key's dim class is a property of its slot, not its value
    (feature_value.h: mf_dim rides the slot config)."""

    wants_slot_keys = True  # BoxPSHelper passes (keys, slots)
    supports_overlap_stage = True  # per-class tiered tables reconcile

    def __init__(self, num_shards: int, slot_mf_dims: Sequence[int],
                 capacity_per_shard: Optional[int] = None,
                 capacity_per_class: Optional[Dict[int, int]] = None,
                 cfg: Optional[SparseSGDConfig] = None,
                 req_bucket_min: int = 512,
                 serve_bucket_min: int = 1024,
                 host_capacity: Optional[int] = None) -> None:
        super().__init__(num_shards, slot_mf_dims,
                         capacity_per_shard=capacity_per_shard,
                         capacity_per_class=capacity_per_class, cfg=cfg,
                         req_bucket_min=req_bucket_min,
                         serve_bucket_min=serve_bucket_min,
                         host_capacity=host_capacity)

    def _make_class_table(self, num_shards: int, mf_dim: int, **kw):
        from paddlebox_tpu.ps.tiered import TieredShardedEmbeddingTable
        return TieredShardedEmbeddingTable(num_shards, mf_dim=mf_dim, **kw)

    @property
    def in_pass(self) -> bool:
        return any(t.in_pass for t in self.tables)

    # ---- pass lifecycle across classes ----
    def stage(self, keys: np.ndarray, slots: np.ndarray,
              background: bool = True) -> None:
        per = self.split_keys_by_class(keys, slots)
        # validate EVERY class's per-shard capacity BEFORE any class
        # spawns its stage — a mid-fan-out failure would leave a
        # half-staged wrapper whose pending stages block the next
        # stage/begin_pass with no recovery path
        for c, (t, ks) in enumerate(zip(self.tables, per)):
            for s, sk in enumerate(t._split_by_owner(ks)):
                if len(sk) > t.capacity:
                    raise ValueError(
                        f"class {c} shard {s} working set ({len(sk)}) "
                        f"exceeds capacity_per_shard ({t.capacity})")
        for c, ks in enumerate(per):
            self.tables[c].stage(ks, background=background)

    def wait_stage_done(self) -> None:
        for t in self.tables:
            t.wait_stage_done()

    def drop_window(self) -> None:
        """Invalidate every class table's HBM residency (between
        passes) — discards pending stages; see
        TieredShardedEmbeddingTable.drop_window."""
        for t in self.tables:
            t.drop_window()

    def begin_pass(self, keys: Optional[np.ndarray] = None,
                   slots: Optional[np.ndarray] = None) -> int:
        if keys is not None:
            per = self.split_keys_by_class(keys, slots)
            return sum(t.begin_pass(ks)
                       for t, ks in zip(self.tables, per))
        return sum(t.begin_pass() for t in self.tables)

    def end_pass(self) -> int:
        # each class table closes + submits its own async epilogue job;
        # fence() below drains all of them (checkpoint/lifecycle callers)
        return sum(t.end_pass() for t in self.tables)

    def fence(self) -> None:
        """Drain every class table's async end_pass epilogue (surfaces
        the first write-back failure — see ps/epilogue.py)."""
        for t in self.tables:
            t.fence()

    def endpass_stats(self) -> dict:
        """Epilogue accounting aggregated across the dim classes:
        additive fields sum (counts stay ints); ``last_writeback_sec``
        takes the max — summing per-class "last job" durations would
        fabricate a duration no job had."""
        parts = [t.endpass_stats() for t in self.tables]
        out: dict = {}
        for k in parts[0] if parts else ():
            vals = [p[k] for p in parts]
            out[k] = (max(vals) if k == "last_writeback_sec"
                      else sum(vals))
        return out

    def spill_cold(self, path_prefix: str, threshold: float) -> int:
        return sum(t.spill_cold(f"{path_prefix}.mf{d}", threshold)
                   for t, d in zip(self.tables, self.dims))

    def pull(self, keys: np.ndarray, slots: np.ndarray) -> np.ndarray:
        """Host-tier pull with per-slot widths (the parent reads the HBM
        window's indexes — between passes those hold only the last
        window; the FULL model lives in the per-shard host stores)."""
        keys = np.ascontiguousarray(keys, np.uint64)
        slots = np.asarray(slots, np.int32)
        out = np.zeros((len(keys), 3 + max(self.dims)), np.float32)
        for c, t in enumerate(self.tables):
            m = self.class_of_slot[slots] == c
            if not m.any():
                continue
            kc = keys[m]
            vals = np.zeros((len(kc), 3 + t.mf_dim), np.float32)
            owners = (kc % np.uint64(t.n)).astype(np.int64)
            for s in range(t.n):
                sm = owners == s
                if not sm.any():
                    continue
                f = t.hosts[s].fetch(kc[sm])
                gate = (f["mf_size"][:, None] > 0)
                vals[np.nonzero(sm)[0]] = np.concatenate(
                    [f["show"][:, None], f["clk"][:, None],
                     f["embed_w"][:, None], f["embedx_w"] * gate], axis=1)
            out[np.nonzero(m)[0], :vals.shape[1]] = vals
        return out
