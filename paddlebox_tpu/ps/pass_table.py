"""Pass-scoped HBM table: per-pass working set promoted from the HostStore.

Reference lifecycle (SURVEY.md §3.3): ``BeginFeedPass`` schedules SSD→mem
for the pass's key set, ``BeginPass`` buffers the pass embeddings into HBM,
training pulls/pushes hit only that working set, ``EndPass`` writes back
HBM→mem (box_wrapper.cc:129-186; open analogue BuildGPUTask/EndPass,
ps_gpu_wrapper.cc:684,983).

TPU-native: the device TableState stays statically shaped (pass_capacity
rows); begin_pass assigns every pass key a fresh row, scatters host-fetched
values in with one vectorized np write per field, and device_puts the SoA.
The host fetch can run on a background thread (``stage()``) between
end_pass and begin_pass (overlapping dataset columnarization); what
overlaps the previous pass's *training* is the dataset IO/parse/dedup
(PreLoadIntoMemory/WaitFeedPassDone), since staged values must reflect
that pass's write-back.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import jax
import numpy as np

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.ps.host_store import FIELDS, HostStore
from paddlebox_tpu.ps.kv import make_kv
from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.table import (NUM_FIXED, EmbeddingTable, TableState,
                                    field_assign)
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class PassStage:
    """Host-side staging of one pass (keys + fetched values)."""

    def __init__(self, keys: np.ndarray, values: Dict[str, np.ndarray]):
        self.keys = keys
        self.values = values


class PassScopedTable(EmbeddingTable):
    """EmbeddingTable whose contents are one pass's working set."""

    def __init__(self, host: HostStore, pass_capacity: Optional[int] = None,
                 cfg: Optional[SparseSGDConfig] = None, seed: int = 0,
                 unique_bucket_min: int = 1024) -> None:
        from paddlebox_tpu.ps.sgd import opt_ext_width
        need = opt_ext_width(cfg, host.mf_dim) if cfg is not None else 0
        have = getattr(host, "opt_ext", 0)
        if need > have:
            raise ValueError(
                f"optimizer needs a {need}-wide extension block but the "
                f"HostStore persists {have} — construct "
                f"HostStore(mf_dim=..., opt_ext={need}) so SparseAdam "
                "state survives pass windows.")
        if need < have:
            raise ValueError(
                f"the HostStore carries a {have}-wide optimizer "
                f"extension but this table's optimizer uses {need} — "
                "pass the matching SparseAdamConfig (rebuilding the "
                "store with a smaller block would DISCARD the persisted "
                "optimizer state).")
        super().__init__(mf_dim=host.mf_dim,
                         capacity=pass_capacity or
                         FLAGS.table_capacity_per_shard,
                         cfg=cfg, seed=seed,
                         unique_bucket_min=unique_bucket_min)
        self.host = host
        self._stage: Optional[PassStage] = None
        self._stage_thread: Optional[threading.Thread] = None
        self._stage_exc: Optional[BaseException] = None
        self.in_pass = False

    # ---- feed-pass staging (BeginFeedPass/EndFeedPass) ----
    def stage(self, pass_keys: np.ndarray, background: bool = True) -> None:
        """Fetch the pass working set from the host store. Only legal
        between the previous end_pass and the next begin_pass: staging
        while a pass is open would read host rows the open pass has not
        written back yet (the reference's closed PS enforces the same
        EndPass→BeginPass order). What overlaps training is the dataset
        IO/parse/key-dedup (BoxPSHelper.preload_into_memory), not this."""
        if self.in_pass:
            raise RuntimeError(
                "stage() while a pass is open — the open pass's updates "
                "are not in the host store yet; end_pass first")
        if self._stage_thread is not None:
            raise RuntimeError("a feed pass is already staging")
        if len(pass_keys) > self.capacity:
            raise ValueError(
                f"pass working set ({len(pass_keys)}) exceeds table "
                f"capacity ({self.capacity})")
        self._stage_exc = None

        def run() -> None:
            try:
                self._stage = PassStage(pass_keys,
                                        self.host.fetch(pass_keys))
            except BaseException as e:
                self._stage_exc = e

        if background:
            self._stage_thread = threading.Thread(target=run, daemon=True)
            self._stage_thread.start()
        else:
            run()
            if self._stage_exc is not None:
                raise self._stage_exc

    def wait_stage_done(self) -> None:
        if self._stage_thread is not None:
            self._stage_thread.join()
            self._stage_thread = None
        if self._stage_exc is not None:
            exc, self._stage_exc = self._stage_exc, None
            raise exc

    # ---- pass window (BeginPass/EndPass) ----
    def begin_pass(self, pass_keys: Optional[np.ndarray] = None) -> int:
        """Promote the staged (or given) working set into the device table.
        Returns the number of working-set rows."""
        if self.in_pass:
            raise RuntimeError("begin_pass while a pass is open")
        if pass_keys is not None:
            if self._stage_thread is not None or self._stage is not None:
                # a stage exists: it must be for the same key set, else
                # promoting it would corrupt rows for keys only in one set
                self.wait_stage_done()
                if (self._stage is None
                        or not np.array_equal(self._stage.keys, pass_keys)):
                    raise RuntimeError(
                        "begin_pass keys differ from the staged key set")
            else:
                self.stage(pass_keys, background=False)
        self.wait_stage_done()
        st = self._stage
        if st is None:
            raise RuntimeError("begin_pass with nothing staged")
        self._stage = None

        self.index = make_kv(self.capacity)
        rows = self.index.assign(st.keys)
        c1 = self.capacity + 1
        mf_end = NUM_FIXED + self.mf_dim
        data = np.zeros((c1, mf_end + self.opt_ext), np.float32)
        for f in FIELDS:
            field_assign(data, rows, f, st.values[f])
        if self.opt_ext:
            data[rows, mf_end:] = st.values["opt_ext"]
        # slot is HOST metadata (_gather_host reads slot_host, never the
        # device column) and the index was just rebuilt (make_kv
        # reassigns row ids) — reset it wholesale, then seed the staged
        # slots so a working-set row survives begin_pass → end_pass even
        # when no prepare()/record_slots touches it during the window
        # (eval-only passes, staged key supersets)
        self.slot_host[:] = 0
        self.slot_host[rows] = st.values["slot"].astype(np.int16)
        self.state = TableState.from_logical(data, self.capacity,
                                             ext=self.opt_ext)
        self._touched[:] = False
        self.in_pass = True
        log.info("begin_pass: %d working-set rows in HBM", len(st.keys))
        return len(st.keys)

    def end_pass(self) -> int:
        """Write the (jit-updated) working set back to the host store."""
        if not self.in_pass:
            raise RuntimeError("end_pass without begin_pass")
        keys, rows = self.index.items()
        data = self._gather_host(rows)
        self.host.update(keys, {f: data[f] for f in self.host.fields})
        self.in_pass = False
        log.info("end_pass: %d rows written back to host store", len(keys))
        return len(keys)
