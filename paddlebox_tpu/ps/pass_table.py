"""Pass-scoped HBM table: PERSISTENT pass windows promoted from the HostStore.

Reference lifecycle (SURVEY.md §3.3): ``BeginFeedPass`` schedules SSD→mem
for the pass's key set, ``BeginPass`` buffers the pass embeddings into HBM,
training pulls/pushes hit only that working set, ``EndPass`` writes back
HBM→mem (box_wrapper.cc:129-186; open analogue BuildGPUTask/EndPass,
ps_gpu_wrapper.cc:684,983).

TPU-native, incremental (the single-chip mirror of
``TieredShardedEmbeddingTable`` — see ps/tiered.py for the full design
notes): rows stay RESIDENT in HBM across passes, matching the reference's
incremental FeedPass (only SSD→mem *misses* are scheduled) and persistent
HBM windows. ``stage`` fetches host values only for keys NOT already in
the window and is legal while a pass is OPEN (the overlapped
pre_build_thread, ps_gpu_wrapper.cc:913) — missing keys are outside the
open pass's write-back set, so the fetch cannot race ``end_pass``;
``begin_pass`` reconciles (a key that entered the window mid-pass keeps
its fresher resident row), evicts only under capacity pressure (clean
rows first; dirty evictees write back), and device-scatters only the
delta; ``end_pass`` gathers and writes back only rows touched since the
last write-back. Host↔HBM wire per pass ∝ the working-set DELTA.

Host-tier mutations outside the pass protocol (``host.load`` / ``shrink``
/ ``merge``) must be followed by ``drop_window()`` — resident rows would
otherwise shadow the updated host values (BoxPSHelper does this for its
lifecycle methods).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.ps.epilogue import PassEpilogue, fence_under_pressure
from paddlebox_tpu.ps.host_store import HostStore
from paddlebox_tpu.ps.kv import make_kv
from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.table import (EmbeddingTable,
                                    dispatch_packed_row_gather,
                                    promote_window_delta,
                                    rows_from_store_fields,
                                    scatter_logical_rows,
                                    start_scatter_warmup)
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class PassStage:
    """Host-side staging of one pass: the full key set, the keys that
    were missing from the window at stage time, and their host values."""

    def __init__(self, keys: np.ndarray, new_keys: np.ndarray,
                 values: Dict[str, np.ndarray]):
        self.keys = keys
        self.new_keys = new_keys
        self.values = values


class PassScopedTable(EmbeddingTable):
    """EmbeddingTable whose contents are a persistent window of the
    working set; the full model lives in the backing HostStore."""

    # stage() is legal while a pass is open (missing keys are outside
    # the open window's write-back set) — BoxPSHelper.stage_pass gates
    # on this
    supports_overlap_stage = True

    def __init__(self, host: HostStore, pass_capacity: Optional[int] = None,
                 cfg: Optional[SparseSGDConfig] = None, seed: int = 0,
                 unique_bucket_min: int = 1024) -> None:
        from paddlebox_tpu.ps.sgd import opt_ext_width
        need = opt_ext_width(cfg, host.mf_dim) if cfg is not None else 0
        have = getattr(host, "opt_ext", 0)
        if need > have:
            raise ValueError(
                f"optimizer needs a {need}-wide extension block but the "
                f"HostStore persists {have} — construct "
                f"HostStore(mf_dim=..., opt_ext={need}) so SparseAdam "
                "state survives pass windows.")
        if need < have:
            raise ValueError(
                f"the HostStore carries a {have}-wide optimizer "
                f"extension but this table's optimizer uses {need} — "
                "pass the matching SparseAdamConfig (rebuilding the "
                "store with a smaller block would DISCARD the persisted "
                "optimizer state).")
        super().__init__(mf_dim=host.mf_dim,
                         capacity=pass_capacity or
                         FLAGS.table_capacity_per_shard,
                         cfg=cfg, seed=seed,
                         unique_bucket_min=unique_bucket_min)
        self.host = host
        self._stage: Optional[PassStage] = None
        self._stage_thread: Optional[threading.Thread] = None
        self._stage_exc: Optional[BaseException] = None
        self.in_pass = False
        # async pass epilogue (ps/epilogue — the single-chip mirror of
        # the tiered table's): end_pass snapshots + dispatches, the
        # worker drains; every HostStore read entry point fences first
        self._epilogue = PassEpilogue(name="pass-endpass")
        host.read_barrier = self._epilogue.fence
        # per-pass delta accounting (same keys as the tiered table)
        self.last_pass_stats: Dict[str, int] = {}
        start_scatter_warmup(self.state, sharded=False)

    def fence(self) -> None:
        """Drain the asynchronous end_pass write-back and surface the
        first failure (ps/epilogue.PassEpilogue.fence). Implicit on
        every ``self.host`` read entry point."""
        self._epilogue.fence()

    def endpass_stats(self) -> Dict[str, float]:
        """Cumulative epilogue accounting (obs/hub pass events, bench)."""
        return self._epilogue.stats()

    def spill_manifest(self) -> Optional[dict]:
        """Checkpoint spill manifest of the backing store's SSD tier
        (train/checkpoint.py), single-shard shape — None without a
        tier."""
        self.fence()
        m = self.host.spill_manifest()
        if m is None:
            return None
        return {"version": 1, "shards": {"0": m},
                "live_rows": m["live_rows"]}

    def ssd_stats(self) -> Dict[str, float]:
        return self.host.ssd_stats()

    # ---- host field <-> logical row conversion --------------------------
    def _logical_rows(self, vals: Dict[str, np.ndarray]) -> np.ndarray:
        return rows_from_store_fields(vals, self.mf_dim, self.opt_ext)

    def _gather_rows_device(self, rows: np.ndarray) -> np.ndarray:
        """Device-side row gather → host [k, feat]: D2H wire is the
        gathered rows, not the whole table (shared jitted bucketed
        gather — ps/table.dispatch_packed_row_gather)."""
        dev, k = dispatch_packed_row_gather(self.state, None, rows)
        return np.asarray(jax.device_get(dev))[:k]

    # ---- feed-pass staging (BeginFeedPass/EndFeedPass) ----
    def stage(self, pass_keys: np.ndarray, background: bool = True) -> None:
        """Fetch host values for the pass keys NOT already resident.
        Legal while a pass is open — see the module docstring for the
        overlap contract."""
        if self._stage_thread is not None or self._stage is not None:
            raise RuntimeError("a feed pass is already staging")
        pass_keys = np.unique(np.ascontiguousarray(pass_keys, np.uint64))
        if len(pass_keys) > self.capacity:
            raise ValueError(
                f"pass working set ({len(pass_keys)}) exceeds table "
                f"capacity ({self.capacity})")
        with self.host_lock:
            new = pass_keys[self.index.lookup(pass_keys) < 0]
        self._stage_exc = None

        def run() -> None:
            try:
                self._stage = PassStage(pass_keys, new,
                                        self.host.fetch(new))
            except BaseException as e:
                self._stage_exc = e

        if background:
            self._stage_thread = threading.Thread(target=run, daemon=True)
            self._stage_thread.start()
        else:
            run()
            if self._stage_exc is not None:
                raise self._stage_exc

    def wait_stage_done(self) -> None:
        if self._stage_thread is not None:
            self._stage_thread.join()
            self._stage_thread = None
        if self._stage_exc is not None:
            exc, self._stage_exc = self._stage_exc, None
            raise exc

    # ---- pass window (BeginPass/EndPass) ----
    def begin_pass(self, pass_keys: Optional[np.ndarray] = None) -> int:
        """Promote the staged (or given) working set into the device
        window: reconcile against live residency, evict only under
        capacity pressure, scatter only the genuinely new rows. Returns
        the number of working-set rows."""
        if self.in_pass:
            raise RuntimeError("begin_pass while a pass is open")
        if pass_keys is not None:
            pass_keys = np.unique(
                np.ascontiguousarray(pass_keys, np.uint64))
            if self._stage_thread is not None or self._stage is not None:
                # a stage exists: it must be for the same key set, else
                # promoting it would corrupt rows for keys only in one set
                self.wait_stage_done()
                if (self._stage is None
                        or not np.array_equal(self._stage.keys, pass_keys)):
                    raise RuntimeError(
                        "begin_pass keys differ from the staged key set")
            else:
                self.stage(pass_keys, background=False)
        self.wait_stage_done()
        st = self._stage
        if st is None:
            raise RuntimeError("begin_pass with nothing staged")
        self._stage = None

        self.host_lock.acquire()
        try:
            # promote may EVICT under capacity pressure: order the
            # dirty-evictee write-backs (and released rows' later
            # re-fetches) after the in-flight epilogue. The shared
            # fence-outside-the-lock loop (ps/epilogue.
            # fence_under_pressure) re-checks under this same lock
            # hold — a concurrent preload build's bulk assign cannot
            # create unfenced pressure between check and evict.
            fence_sec = fence_under_pressure(
                self.host_lock, self._epilogue.fence,
                lambda: (len(self.index) + len(st.new_keys)
                         > self.capacity))
            rows_new, still, stats = promote_window_delta(
                self.index, self._touched, self.capacity,
                st.keys, st.new_keys,
                gather_rows=self._gather_rows_device,
                writeback=lambda ks, rs, sub: self.host.update_rows(
                    ks, sub,
                    slot_override=self.slot_host[rs].astype(np.float32)),
                on_freed=lambda freed:
                    self.slot_host.__setitem__(freed, 0))
            # window promote assigns/releases kv rows behind the device
            # index's back — re-seed (or degrade) on the next bulk assign
            self._reset_dev_index()
            ins_vals = {f: v[still] for f, v in st.values.items()}
            self.slot_host[rows_new] = ins_vals["slot"].astype(np.int16)
            if len(rows_new):
                self.state = scatter_logical_rows(
                    self.state, None, rows_new,
                    self._logical_rows(ins_vals))
        finally:
            self.host_lock.release()
        stats["written_back"] = 0
        # begin-boundary eviction attribution (the tiered table's
        # begin_stall_breakdown keys, single-chip): all inline here —
        # the emergency path — as this table has no stage queue yet
        stats["evict_emergency_sec"] = round(
            fence_sec + stats.pop("evict_sec", 0.0), 6)
        self.in_pass = True
        self.last_pass_stats = stats
        log.info("begin_pass: %d working-set rows (%d resident, %d "
                 "staged, %d evicted) in HBM", len(st.keys),
                 stats["resident"], stats["staged"], stats["evicted"])
        return len(st.keys)

    def end_pass(self) -> int:
        """Close the pass and write back ASYNCHRONOUSLY (the tiered
        table's epilogue contract, single chip — see
        TieredShardedEmbeddingTable.end_pass): snapshot touched rows +
        slot metadata, dispatch the D2H gather against the immutable
        device buffers, and drain on the background epilogue;
        ``FLAGS.async_end_pass=False`` runs the job inline. Write-back
        stays touched-rows-sized; the window stays resident."""
        if not self.in_pass:
            raise RuntimeError("end_pass without begin_pass")
        t0 = time.perf_counter()
        job = None
        with self.host_lock:
            keys, rows = self.index.items()
            m = self._touched[rows]
            keys, rows = keys[m], rows[m]
            if len(rows):
                # dispatch now (buffer-donation safety), pull on the
                # worker; slot metadata snapshots HERE — slot_host may
                # be rewritten by the next pass's prepare before the
                # write-back lands
                sub_dev, k = dispatch_packed_row_gather(self.state, None,
                                                        rows)
                slots = self.slot_host[rows].astype(np.float32)
                self._touched[rows] = False

                def job(keys=keys, sub_dev=sub_dev, k=k,
                        slots=slots) -> None:
                    from paddlebox_tpu.resilience import faults
                    faults.inject("endpass.writeback", op="single",
                                  rows=len(keys))
                    sub = np.asarray(jax.device_get(sub_dev))[:k]
                    self.host.update_rows(keys, sub, slot_override=slots)
                    if self.host.ssd is not None:
                        # watermark demotion on the epilogue lane,
                        # strictly after the write-back (ps/tiered.py's
                        # identical discipline; barrier=False — fencing
                        # from the worker would deadlock the lane)
                        self.host.demote_to_watermark(barrier=False)
                        self.host.ssd.maybe_compact()
        self.in_pass = False
        self.last_pass_stats["written_back"] = len(keys)
        if job is not None:
            if FLAGS.async_end_pass:
                self._epilogue.submit(job, label="end_pass")
            else:
                job()
        self.last_pass_stats["end_pass_submit_sec"] = round(
            time.perf_counter() - t0, 6)
        log.info("end_pass: %d touched rows -> host store (%s)",
                 len(keys),
                 "async" if FLAGS.async_end_pass else "sync")
        return len(keys)

    def shrink(self, delete_threshold: Optional[float] = None,
               decay: Optional[float] = None) -> int:
        """Age the FULL model, not just the resident window: fence the
        async epilogue (a draining end_pass job's counters must land
        before they are decayed or scored — see
        tests/test_shrink_fence.py), delegate to ``HostStore.shrink``
        (RAM + SSD tiers), then ``drop_window`` so stale resident rows
        cannot shadow the aged host values. Refused mid-pass: the open
        window's updates are not in the host store yet and a shrink
        under them would resurrect dropped rows at write-back."""
        if self.in_pass:
            raise RuntimeError(
                "shrink while a pass is open — the window's updates are "
                "not written back yet; end_pass first")
        self.fence()  # pre-write-back counters must not drive aging
        freed = self.host.shrink(delete_threshold=delete_threshold,
                                 decay=decay,
                                 nonclk_coeff=self.cfg.nonclk_coeff,
                                 clk_coeff=self.cfg.clk_coeff)
        self.drop_window()
        return freed

    def drop_window(self) -> None:
        """Invalidate HBM residency (between passes): the next
        begin_pass re-fetches everything from the host store. Required
        after host-store mutations outside the pass protocol
        (load/shrink/merge on ``self.host``) — resident rows would
        shadow them. Discards any pending stage and zeroes the device
        rows (released rows must read as fresh zero rows)."""
        if self.in_pass:
            raise RuntimeError(
                "drop_window while a pass is open — the window's updates "
                "are not in the host store yet; end_pass first")
        self.fence()  # the dropped window's write-backs must land first
        try:
            if self._stage_thread is not None or self._stage is not None:
                self.wait_stage_done()
        finally:
            self._stage = None
            with self.host_lock:
                self.index = make_kv(self.capacity)
                self._touched[:] = False
                self.slot_host[:] = 0
                self.state = self.state.with_packed(
                    jnp.zeros_like(self.state.packed))
