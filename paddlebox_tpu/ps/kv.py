"""Host key→row index: native (C++) fast path with a python-dict fallback.

See paddlebox_tpu/native/kv_index.cpp for the role citation. Both
implementations share the contract used by the tables: assign / lookup /
release / items / len, uint64 keys → int32 rows with free-list reuse and a
hard row capacity (raises when full — Phase-5 eviction is the relief valve).
"""

from __future__ import annotations

import ctypes
from typing import Dict, Tuple

import numpy as np


class TableFullError(RuntimeError):
    pass


def _full_error(capacity: int) -> TableFullError:
    return TableFullError(
        f"embedding table full ({capacity} rows); raise "
        "FLAGS.table_capacity_per_shard or enable shrink")


class PyKV:
    """Pure-python fallback (the original HostKV)."""

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._map: Dict[int, int] = {}
        self._free: list[int] = []
        self._next = 0

    def __len__(self) -> int:
        return len(self._map)

    def assign(self, keys: np.ndarray) -> np.ndarray:
        rows = np.empty(len(keys), dtype=np.int32)
        m = self._map
        for i, k in enumerate(keys.tolist()):
            r = m.get(k)
            if r is None:
                if self._free:
                    r = self._free.pop()
                elif self._next < self.capacity:
                    r = self._next
                    self._next += 1
                else:
                    raise _full_error(self.capacity)
                m[k] = r
            rows[i] = r
        return rows

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        m = self._map
        return np.array([m.get(k, -1) for k in keys.tolist()], dtype=np.int32)

    def release(self, keys: np.ndarray) -> np.ndarray:
        rows = np.empty(len(keys), dtype=np.int32)
        for i, k in enumerate(keys.tolist()):
            r = self._map.pop(k, -1)
            if r >= 0:
                self._free.append(r)
            rows[i] = r
        return rows[rows >= 0]

    def items(self) -> Tuple[np.ndarray, np.ndarray]:
        if not self._map:
            return (np.empty(0, np.uint64), np.empty(0, np.int32))
        ks = np.fromiter(self._map.keys(), dtype=np.uint64,
                         count=len(self._map))
        rs = np.fromiter(self._map.values(), dtype=np.int32,
                         count=len(self._map))
        return ks, rs

    def assign_unique(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(unique rows, inverse): dedup keys and assign rows to the uniques."""
        uniq, inv = np.unique(keys, return_inverse=True)
        return self.assign(uniq), inv.astype(np.int32, copy=False)

    def lookup_unique(self, keys: np.ndarray,
                      sentinel: int) -> Tuple[np.ndarray, np.ndarray]:
        """Read-only dedup: ALL unknown keys collapse into ONE unique
        entry holding the sentinel row (same contract as the native
        kv_lookup_unique — keeps unique_rows duplicate-free)."""
        uniq, inv = np.unique(keys, return_inverse=True)
        rows = self.lookup(uniq)
        miss = rows < 0
        if not miss.any():
            return rows.astype(np.int32, copy=False), \
                inv.astype(np.int32, copy=False)
        # renumber: known uniques keep relative order, misses share one slot
        remap = np.empty(len(uniq), np.int32)
        known_idx = np.nonzero(~miss)[0]
        remap[known_idx] = np.arange(len(known_idx), dtype=np.int32)
        remap[np.nonzero(miss)[0]] = len(known_idx)
        out_rows = np.empty(len(known_idx) + 1, np.int32)
        out_rows[:len(known_idx)] = rows[known_idx]
        out_rows[len(known_idx)] = sentinel
        return out_rows, remap[inv].astype(np.int32, copy=False)


class NativeKV:
    """ctypes wrapper over native/kv_index.cpp."""

    def __init__(self, capacity: int, lib) -> None:
        self.capacity = capacity
        self._lib = lib
        self._h = lib.kv_create(min(capacity, 1 << 22), capacity)

    def __del__(self) -> None:
        h = getattr(self, "_h", None)
        if h:
            self._lib.kv_destroy(h)
            self._h = None

    def __len__(self) -> int:
        return int(self._lib.kv_size(self._h))

    @staticmethod
    def _buf(a: np.ndarray):
        return a.ctypes.data_as(ctypes.c_void_p)

    def assign(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        rows = np.empty(len(keys), dtype=np.int32)
        done = self._lib.kv_assign(self._h, self._buf(keys), len(keys),
                                   self._buf(rows))
        if done != len(keys):
            raise _full_error(self.capacity)
        return rows

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        rows = np.empty(len(keys), dtype=np.int32)
        self._lib.kv_lookup(self._h, self._buf(keys), len(keys),
                            self._buf(rows))
        return rows

    def release(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        rows = np.empty(len(keys), dtype=np.int32)
        self._lib.kv_release(self._h, self._buf(keys), len(keys),
                             self._buf(rows))
        return rows[rows >= 0]

    def items(self) -> Tuple[np.ndarray, np.ndarray]:
        n = len(self)
        ks = np.empty(n, dtype=np.uint64)
        rs = np.empty(n, dtype=np.int32)
        if n:
            self._lib.kv_items(self._h, self._buf(ks), self._buf(rs))
        return ks, rs

    def assign_unique(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """One-pass hash dedup + row assign (O(n), no sort); uniques come in
        first-occurrence order. Contract matches PyKV.assign_unique."""
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        n = len(keys)
        uniq_rows = np.empty(n, dtype=np.int32)
        inv = np.empty(n, dtype=np.int32)
        u = self._lib.kv_assign_unique(self._h, self._buf(keys), n,
                                       self._buf(uniq_rows), self._buf(inv))
        if u < 0:
            raise _full_error(self.capacity)
        return uniq_rows[:u].copy(), inv

    def lookup_unique(self, keys: np.ndarray,
                      sentinel: int) -> Tuple[np.ndarray, np.ndarray]:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        n = len(keys)
        uniq_rows = np.empty(max(n, 1), dtype=np.int32)
        inv = np.empty(n, dtype=np.int32)
        u = self._lib.kv_lookup_unique(self._h, self._buf(keys), n,
                                       sentinel, self._buf(uniq_rows),
                                       self._buf(inv))
        return uniq_rows[:u].copy(), inv


def make_kv(capacity: int):
    """Native index when buildable, python fallback otherwise."""
    from paddlebox_tpu.native import load_native
    lib = load_native()
    if lib is not None:
        return NativeKV(capacity, lib)
    return PyKV(capacity)
