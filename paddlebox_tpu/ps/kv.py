"""Host key→row index: native (C++) fast path with a python-dict fallback.

See paddlebox_tpu/native/kv_index.cpp for the role citation. Both
implementations share the contract used by the tables: assign / lookup /
release / items / len, uint64 keys → int32 rows with free-list reuse and a
hard row capacity (raises when full — Phase-5 eviction is the relief valve).
"""

from __future__ import annotations

import ctypes
from typing import Dict, Tuple

import numpy as np


class TableFullError(RuntimeError):
    pass


def _full_error(capacity: int) -> TableFullError:
    return TableFullError(
        f"embedding table full ({capacity} rows); raise "
        "FLAGS.table_capacity_per_shard or enable shrink")


class _PyArena:
    """Slot-arena allocator state (mirror of the native Arena struct):
    rows are carved from chunk-aligned extents owned by one slot each, so
    (slot, local) addresses any row compactly — the compact resident-pass
    wire's foundation (train/device_pass.py)."""

    def __init__(self, chunk_bits: int, n_slots: int, max_rows: int):
        self.chunk_bits = chunk_bits
        self.n_slots = n_slots  # default (slotless) arena = id n_slots
        self.max_chunks = (max_rows + (1 << chunk_bits) - 1) >> chunk_bits
        self.chunk_slot = np.full(self.max_chunks, -1, np.int32)
        self.chunk_rank = np.full(self.max_chunks, -1, np.int32)
        self.next_chunk = 0
        self.slot_nchunks = [0] * (n_slots + 1)
        self.slot_tail = [-1] * (n_slots + 1)
        self.slot_fill = [0] * (n_slots + 1)
        self.slot_free: list[list[int]] = [[] for _ in range(n_slots + 1)]

    def alloc(self, s: int, max_rows: int) -> int:
        if self.slot_free[s]:
            return self.slot_free[s].pop()
        cs = 1 << self.chunk_bits
        if self.slot_tail[s] < 0 or self.slot_fill[s] == cs:
            if self.next_chunk >= self.max_chunks:
                return -2
            c = self.next_chunk
            self.next_chunk += 1
            self.chunk_slot[c] = s
            self.chunk_rank[c] = self.slot_nchunks[s]
            self.slot_nchunks[s] += 1
            self.slot_tail[s] = c
            self.slot_fill[s] = 0
        row = (self.slot_tail[s] << self.chunk_bits) + self.slot_fill[s]
        self.slot_fill[s] += 1
        return row if row < max_rows else -2

    def local_of(self, row: int, s: int) -> int:
        if not 0 <= s < self.n_slots:  # incl. the default arena id
            return -1
        c = row >> self.chunk_bits
        if self.chunk_slot[c] != s:
            return -1
        return ((int(self.chunk_rank[c]) << self.chunk_bits)
                | (row & ((1 << self.chunk_bits) - 1)))


class PyKV:
    """Pure-python fallback (the original HostKV)."""

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._map: Dict[int, int] = {}
        self._free: list[int] = []
        self._next = 0
        self._arena: _PyArena | None = None

    def __len__(self) -> int:
        return len(self._map)

    def arena_enable(self, chunk_bits: int, n_slots: int) -> None:
        if self._map or self._next:
            raise RuntimeError("arena_enable after rows were assigned")
        self._arena = _PyArena(chunk_bits, n_slots, self.capacity)

    @property
    def arena_enabled(self) -> bool:
        return self._arena is not None

    def _alloc(self, slot: int = -1) -> int:
        if self._arena is not None:
            # out-of-range slots clamp to the default (slotless) arena —
            # mirrors the native clamp_slot; the compact wire then sees
            # local = -1 and falls back instead of corrupting state
            s = (slot if 0 <= slot < self._arena.n_slots
                 else self._arena.n_slots)
            r = self._arena.alloc(s, self.capacity)
            if r == -2:
                raise _full_error(self.capacity)
            return r
        if self._free:
            return self._free.pop()
        if self._next < self.capacity:
            r = self._next
            self._next += 1
            return r
        raise _full_error(self.capacity)

    def assign(self, keys: np.ndarray) -> np.ndarray:
        rows = np.empty(len(keys), dtype=np.int32)
        m = self._map
        for i, k in enumerate(keys.tolist()):
            r = m.get(k)
            if r is None:
                r = self._alloc()
                m[k] = r
            rows[i] = r
        return rows

    def assign_slotted(self, keys: np.ndarray, slots: np.ndarray
                       ) -> Tuple[np.ndarray, np.ndarray]:
        """(global rows, slot-local rows); local = -1 where the key's row
        lives in another slot's arena (caller falls back to dedup wire)."""
        assert self._arena is not None
        rows = np.empty(len(keys), dtype=np.int32)
        locs = np.empty(len(keys), dtype=np.int32)
        m = self._map
        for i, (k, s) in enumerate(zip(keys.tolist(), slots.tolist())):
            r = m.get(k)
            if r is None:
                r = self._alloc(s)
                m[k] = r
            rows[i] = r
            locs[i] = self._arena.local_of(r, s)
        return rows, locs

    def assign_unique_slotted(self, keys: np.ndarray, slots: np.ndarray
                              ) -> Tuple[np.ndarray, np.ndarray]:
        """Slotted assign_unique: dedup in first-occurrence order, new
        keys allocate in their slot's arena."""
        uniq, first_idx, inv = np.unique(keys, return_index=True,
                                         return_inverse=True)
        rows = np.empty(len(uniq), dtype=np.int32)
        m = self._map
        for j, k in enumerate(uniq.tolist()):
            r = m.get(k)
            if r is None:
                r = self._alloc(int(slots[first_idx[j]]))
                m[k] = r
            rows[j] = r
        return rows, inv.astype(np.int32, copy=False)

    def arena_export(self) -> Tuple[np.ndarray, np.ndarray]:
        a = self._arena
        assert a is not None
        n = a.next_chunk
        return a.chunk_slot[:n].copy(), a.chunk_rank[:n].copy()

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        m = self._map
        return np.array([m.get(k, -1) for k in keys.tolist()], dtype=np.int32)

    def release(self, keys: np.ndarray) -> np.ndarray:
        rows = np.empty(len(keys), dtype=np.int32)
        a = self._arena
        for i, k in enumerate(keys.tolist()):
            r = self._map.pop(k, -1)
            if r >= 0:
                if a is not None:  # back to the OWNING arena
                    a.slot_free[a.chunk_slot[r >> a.chunk_bits]].append(r)
                else:
                    self._free.append(r)
            rows[i] = r
        return rows[rows >= 0]

    def items(self) -> Tuple[np.ndarray, np.ndarray]:
        if not self._map:
            return (np.empty(0, np.uint64), np.empty(0, np.int32))
        ks = np.fromiter(self._map.keys(), dtype=np.uint64,
                         count=len(self._map))
        rs = np.fromiter(self._map.values(), dtype=np.int32,
                         count=len(self._map))
        return ks, rs

    def assign_unique(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(unique rows, inverse): dedup keys and assign rows to the uniques."""
        uniq, inv = np.unique(keys, return_inverse=True)
        return self.assign(uniq), inv.astype(np.int32, copy=False)

    def lookup_unique(self, keys: np.ndarray,
                      sentinel: int) -> Tuple[np.ndarray, np.ndarray]:
        """Read-only dedup: ALL unknown keys collapse into ONE unique
        entry holding the sentinel row (same contract as the native
        kv_lookup_unique — keeps unique_rows duplicate-free)."""
        uniq, inv = np.unique(keys, return_inverse=True)
        rows = self.lookup(uniq)
        miss = rows < 0
        if not miss.any():
            return rows.astype(np.int32, copy=False), \
                inv.astype(np.int32, copy=False)
        # renumber: known uniques keep relative order, misses share one slot
        remap = np.empty(len(uniq), np.int32)
        known_idx = np.nonzero(~miss)[0]
        remap[known_idx] = np.arange(len(known_idx), dtype=np.int32)
        remap[np.nonzero(miss)[0]] = len(known_idx)
        out_rows = np.empty(len(known_idx) + 1, np.int32)
        out_rows[:len(known_idx)] = rows[known_idx]
        out_rows[len(known_idx)] = sentinel
        return out_rows, remap[inv].astype(np.int32, copy=False)


class NativeKV:
    """ctypes wrapper over native/kv_index.cpp."""

    def __init__(self, capacity: int, lib) -> None:
        self.capacity = capacity
        self._lib = lib
        self._h = lib.kv_create(min(capacity, 1 << 22), capacity)
        self.arena_enabled = False

    def arena_enable(self, chunk_bits: int, n_slots: int) -> None:
        if self._lib.kv_arena_enable(self._h, chunk_bits, n_slots) != 0:
            raise RuntimeError("arena_enable after rows were assigned")
        self.arena_enabled = True

    def assign_slotted(self, keys: np.ndarray, slots: np.ndarray
                       ) -> Tuple[np.ndarray, np.ndarray]:
        """(global rows, slot-local rows); local = -1 where the key's row
        lives in another slot's arena (caller falls back to dedup wire)."""
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        slots = np.ascontiguousarray(slots, dtype=np.uint16)
        n = len(keys)
        rows = np.empty(n, dtype=np.int32)
        locs = np.empty(n, dtype=np.int32)
        done = self._lib.kv_assign_slotted(
            self._h, self._buf(keys), self._buf(slots), n,
            self._buf(rows), self._buf(locs))
        if done != n:
            raise _full_error(self.capacity)
        return rows, locs

    def assign_unique_slotted(self, keys: np.ndarray, slots: np.ndarray
                              ) -> Tuple[np.ndarray, np.ndarray]:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        slots = np.ascontiguousarray(slots, dtype=np.uint16)
        n = len(keys)
        uniq_rows = np.empty(n, dtype=np.int32)
        inv = np.empty(n, dtype=np.int32)
        u = self._lib.kv_assign_unique_slotted(
            self._h, self._buf(keys), self._buf(slots), n,
            self._buf(uniq_rows), self._buf(inv))
        if u < 0:
            raise _full_error(self.capacity)
        return uniq_rows[:u].copy(), inv

    def arena_export(self) -> Tuple[np.ndarray, np.ndarray]:
        n = int(self._lib.kv_arena_chunk_count(self._h))
        cs = np.empty(max(n, 1), dtype=np.int32)
        cr = np.empty(max(n, 1), dtype=np.int32)
        if n:
            self._lib.kv_arena_export(self._h, self._buf(cs), self._buf(cr))
        return cs[:n], cr[:n]

    def __del__(self) -> None:
        h = getattr(self, "_h", None)
        if h:
            self._lib.kv_destroy(h)
            self._h = None

    def __len__(self) -> int:
        return int(self._lib.kv_size(self._h))

    @staticmethod
    def _buf(a: np.ndarray):
        return a.ctypes.data_as(ctypes.c_void_p)

    def assign(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        rows = np.empty(len(keys), dtype=np.int32)
        done = self._lib.kv_assign(self._h, self._buf(keys), len(keys),
                                   self._buf(rows))
        if done != len(keys):
            raise _full_error(self.capacity)
        return rows

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        rows = np.empty(len(keys), dtype=np.int32)
        self._lib.kv_lookup(self._h, self._buf(keys), len(keys),
                            self._buf(rows))
        return rows

    def release(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        rows = np.empty(len(keys), dtype=np.int32)
        self._lib.kv_release(self._h, self._buf(keys), len(keys),
                             self._buf(rows))
        return rows[rows >= 0]

    def items(self) -> Tuple[np.ndarray, np.ndarray]:
        n = len(self)
        ks = np.empty(n, dtype=np.uint64)
        rs = np.empty(n, dtype=np.int32)
        if n:
            self._lib.kv_items(self._h, self._buf(ks), self._buf(rs))
        return ks, rs

    def assign_unique(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """One-pass hash dedup + row assign (O(n), no sort); uniques come in
        first-occurrence order. Contract matches PyKV.assign_unique."""
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        n = len(keys)
        uniq_rows = np.empty(n, dtype=np.int32)
        inv = np.empty(n, dtype=np.int32)
        u = self._lib.kv_assign_unique(self._h, self._buf(keys), n,
                                       self._buf(uniq_rows), self._buf(inv))
        if u < 0:
            raise _full_error(self.capacity)
        return uniq_rows[:u].copy(), inv

    def lookup_unique(self, keys: np.ndarray,
                      sentinel: int) -> Tuple[np.ndarray, np.ndarray]:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        n = len(keys)
        uniq_rows = np.empty(max(n, 1), dtype=np.int32)
        inv = np.empty(n, dtype=np.int32)
        u = self._lib.kv_lookup_unique(self._h, self._buf(keys), n,
                                       sentinel, self._buf(uniq_rows),
                                       self._buf(inv))
        return uniq_rows[:u].copy(), inv


def make_kv(capacity: int):
    """Native index when buildable, python fallback otherwise."""
    from paddlebox_tpu.native import load_native
    lib = load_native()
    if lib is not None:
        return NativeKV(capacity, lib)
    return PyKV(capacity)


def dedup_first_seen_native(keys: np.ndarray):
    """Native one-pass first-seen dedup (kv_dedup_first_seen) — the fast
    route of ps/table.dedup_first_seen. Returns (uniq, first_idx, inv)
    with the oracle's exact dtypes, or None when the native library is
    unavailable (callers keep the python path unchanged)."""
    from paddlebox_tpu.native import load_native
    lib = load_native()
    if lib is None or not hasattr(lib, "kv_dedup_first_seen"):
        return None
    keys = np.ascontiguousarray(keys, np.uint64)
    n = len(keys)
    uniq = np.empty(max(n, 1), np.uint64)
    first = np.empty(max(n, 1), np.int64)
    inv = np.empty(max(n, 1), np.int32)
    u = lib.kv_dedup_first_seen(
        keys.ctypes.data_as(ctypes.c_void_p), n,
        uniq.ctypes.data_as(ctypes.c_void_p),
        first.ctypes.data_as(ctypes.c_void_p),
        inv.ctypes.data_as(ctypes.c_void_p))
    return uniq[:u].copy(), first[:u].copy(), inv[:n].astype(np.int64)
