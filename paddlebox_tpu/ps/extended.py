"""Extended (expand) embedding pulls — pull_box_extended_sparse.

Reference: paddle/fluid/operators/pull_box_extended_sparse_op.{cc,cu,h} —
one lookup returns TWO embeddings per key: the base ``emb_size`` vector
and an ``emb_extended_size`` "expand" vector from a second value space
(Python surface ``_pull_box_extended_sparse``, contrib/layers/nn.py:1678);
slots listed in ``skip_extend_slots`` only produce the base output (their
expand values read zero and train nothing — see ``prepare``).

TPU-native: the expand space is a second EmbeddingTable over the same
keys (the BoxPS core versions them inside one FeatureValue; two SoA
tables give identical math with independent mf dims and optimizers, and
both pulls land in the same jit step).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np

from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.table import (EmbeddingTable, PullIndex,
                                    fill_oob_pads, next_bucket)


class ExtendedEmbeddingTable:
    """Base + expand table pair sharing key traffic.

    ``skip_extend_slots`` (attr `skip_extend_slots` of the reference op):
    keys in those slots pull zeros from the expand space and push no
    expand grads — only the base embedding trains for them."""

    def __init__(self, mf_dim: int, extend_mf_dim: int,
                 capacity: Optional[int] = None,
                 cfg: Optional[SparseSGDConfig] = None,
                 extend_cfg: Optional[SparseSGDConfig] = None,
                 seed: int = 0, unique_bucket_min: int = 1024,
                 skip_extend_slots: Sequence[int] = ()) -> None:
        self.base = EmbeddingTable(mf_dim, capacity, cfg, seed,
                                   unique_bucket_min)
        self.extend = EmbeddingTable(extend_mf_dim, capacity,
                                     extend_cfg or cfg, seed + 1,
                                     unique_bucket_min)
        self.skip_extend_slots = frozenset(skip_extend_slots)

    def prepare(self, batch: SlotBatch) -> Tuple[PullIndex, PullIndex]:
        # dedup once; both tables share the unique set (the reference's
        # single dedup feeding both value spaces)
        valid = batch.keys[:batch.num_keys]
        uniq, inv = np.unique(valid, return_inverse=True)
        slot_k = (batch.segments[:batch.num_keys]
                  % batch.num_slots).astype(np.int16)
        # same locking discipline as EmbeddingTable.prepare (this runs on
        # the prefetch thread; shrink/save may run on the main thread)
        with self.base.host_lock:
            rows_b = self.base.index.assign(uniq)
            self.base._touched[rows_b] = True
            self.base.record_slots(rows_b, inv.astype(np.int32), slot_k)
        idx_b = self.base._build_index(batch, rows_b, inv.astype(np.int32))
        if not self.skip_extend_slots:
            with self.extend.host_lock:
                rows_e = self.extend.index.assign(uniq)
                self.extend._touched[rows_e] = True
                self.extend.record_slots(rows_e, inv.astype(np.int32),
                                         slot_k)
            idx_e = self.extend._build_index(batch, rows_e,
                                             inv.astype(np.int32))
        else:
            keep = ~np.isin(slot_k, list(self.skip_extend_slots))
            uniq_e, inv_e = np.unique(valid[keep], return_inverse=True)
            with self.extend.host_lock:
                rows_e = self.extend.index.assign(uniq_e)
                self.extend._touched[rows_e] = True
                self.extend.record_slots(rows_e, inv_e.astype(np.int32),
                                         slot_k[keep])
            u = len(uniq_e)
            cap = next_bucket(self.extend.unique_bucket_min, u + 1)
            unique_rows = np.empty(cap, np.int32)
            unique_rows[:u] = rows_e
            fill_oob_pads(unique_rows, u, self.extend.capacity)
            k_pad = batch.keys.shape[0]
            # skipped keys point at the sentinel slot: zero pulls, and
            # key_valid=0 drops their expand grads in merge_push
            gather_idx = np.full(k_pad, u, dtype=np.int32)
            gather_idx[:batch.num_keys][keep] = inv_e.astype(np.int32)
            key_valid = np.zeros(k_pad, dtype=np.float32)
            key_valid[:batch.num_keys][keep] = 1.0
            idx_e = PullIndex(unique_rows, gather_idx, key_valid, u)
        return idx_b, idx_e

    def pull(self, idx: Tuple[PullIndex, PullIndex]
             ) -> Tuple[jax.Array, jax.Array]:
        """→ (values [K, 3+mf], expand_values [K, 3+extend_mf])."""
        return self.base.pull(idx[0]), self.extend.pull(idx[1])

    def push(self, idx: Tuple[PullIndex, PullIndex],
             key_grads: jax.Array, extend_key_grads: jax.Array,
             slot_of_key=None) -> None:
        self.base.push(idx[0], key_grads, slot_of_key)
        self.extend.push(idx[1], extend_key_grads, slot_of_key)

    @property
    def feature_count(self) -> int:
        return self.base.feature_count
