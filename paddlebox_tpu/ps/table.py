"""HBM-resident embedding table — the BoxPS/HeterPS store, single shard.

Reference capabilities re-implemented (SURVEY.md §2.1-2.2):
- ``BoxWrapper::PullSparse/PushSparseGrad`` (fleet/box_wrapper.h:488,526)
  with key dedup (``DedupKeysAndFillIdx``, box_wrapper_impl.h:129);
- the HeterPS GPU hashtable value store (heter_ps/hashtable.h:113,
  feature_value.h:570 ``FeatureValue`` layout) with in-table optimizer
  application (optimizer.cuh.h);
- pass/save lifecycle hooks (BeginPass/EndPass/SaveBase/SaveDelta/
  ShrinkTable, box_wrapper.cc:171-186,1383-1415).

TPU-native redesign: XLA needs static shapes, so the device side is a
statically-sized SoA of ``[capacity+1]`` arrays (row ``capacity`` is a
permanent zero "sentinel" used for padding); the key→row mapping is a host
hash index updated during batch preparation (overlapped with device compute
by the trainer's prefetch pipeline). Per-batch key dedup happens on host
(np.unique == DedupKeysAndFillIdx), so the device step is three fused ops:
gather unique rows → model fwd/bwd → segment-sum grads + one scatter update.
No dynamic growth inside jit — the riskiest reference behavior (SSD-backed
dynamic hashtable) maps to host-index growth + static device capacity
(+ Phase-5 host backing store).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.ops.pallas_kernels import gather_rows, scatter_rows
from paddlebox_tpu.ps.sgd import RowState, SparseSGDConfig, adagrad_update
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class TableState(NamedTuple):
    """Device SoA, leaves shaped [C+1] / [C+1, mf_dim]; row C is the zero
    sentinel (FeatureValue fields, feature_value.h:570). 2-D leaves are
    listed in TWO_D_FIELDS below — host-side mirrors (HostStore) derive
    their layouts from these two definitions only."""

    show: jax.Array
    clk: jax.Array
    delta_score: jax.Array
    slot: jax.Array
    embed_w: jax.Array
    embed_g2sum: jax.Array
    embedx_w: jax.Array
    embedx_g2sum: jax.Array
    mf_size: jax.Array

    @property
    def capacity(self) -> int:
        return self.show.shape[0] - 1

    @property
    def mf_dim(self) -> int:
        return self.embedx_w.shape[1]


TWO_D_FIELDS = ("embedx_w",)  # [C+1, mf_dim] leaves; all others are [C+1]


class PullIndex(NamedTuple):
    """Host-built per-batch dedup index (DedupKeysAndFillIdx analogue)."""

    unique_rows: np.ndarray  # int32 [U_pad]; pads → sentinel row C
    gather_idx: np.ndarray   # int32 [K_pad]; pads → sentinel slot
    key_valid: np.ndarray    # f32   [K_pad]; 1.0 for real keys
    num_unique: int


# Host key→row index implementations live in ps/kv.py (native C++ fast path
# + python fallback). HostKV is the factory used across the tables.
from paddlebox_tpu.ps.kv import make_kv as HostKV  # noqa: N813


def init_table_state(capacity: int, mf_dim: int,
                     dtype=jnp.float32) -> TableState:
    c1 = capacity + 1
    z = lambda *shape: jnp.zeros(shape, dtype)
    return TableState(
        show=z(c1), clk=z(c1), delta_score=z(c1), slot=z(c1),
        embed_w=z(c1), embed_g2sum=z(c1),
        embedx_w=z(c1, mf_dim), embedx_g2sum=z(c1), mf_size=z(c1),
    )


def pull_rows(state: TableState, unique_rows: jax.Array) -> jax.Array:
    """Gather pull-values for deduped rows → [U, 3+mf_dim] laid out as
    [show, clk, embed_w, embedx…] (FeaturePullValue, feature_value.h:161).
    Non-materialized mf (mf_size==0) reads as zeros, as in CopyForPull."""
    show = state.show[unique_rows]
    clk = state.clk[unique_rows]
    w = state.embed_w[unique_rows]
    gate = (state.mf_size[unique_rows] > 0).astype(state.embedx_w.dtype)
    if FLAGS.use_pallas_gather:
        mf = gather_rows(state.embedx_w, unique_rows) * gate[:, None]
    else:
        mf = state.embedx_w[unique_rows] * gate[:, None]
    return jnp.concatenate(
        [show[:, None], clk[:, None], w[:, None], mf], axis=1)


def expand_pull(values_u: jax.Array, gather_idx: jax.Array) -> jax.Array:
    """[U, D] unique values → [K, D] per-key-occurrence values."""
    return values_u[gather_idx]


def merge_push(key_grads: jax.Array, gather_idx: jax.Array,
               key_valid: jax.Array, slot_of_key: jax.Array,
               num_unique: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Dedup-merge per-key-occurrence grads into per-unique-row grads —
    PushMergeCopy (box_wrapper.cu:417). Returns (unique_grads [U, D],
    touched [U] bool, slot_val [U]). NOTE: when grads come from autodiff
    through ``expand_pull`` they are ALREADY occurrence-merged; use
    ``push_stats`` for just touched/slot then."""
    g = jax.ops.segment_sum(key_grads * key_valid[:, None], gather_idx,
                            num_segments=num_unique)
    touched, slot_val = push_stats(gather_idx, key_valid, slot_of_key,
                                   num_unique)
    return g, touched, slot_val


def push_stats(gather_idx: jax.Array, key_valid: jax.Array,
               slot_of_key: jax.Array,
               num_unique: int) -> Tuple[jax.Array, jax.Array]:
    """Per-unique-row touched flag and mean slot id."""
    cnt = jax.ops.segment_sum(key_valid, gather_idx, num_segments=num_unique)
    slot_sum = jax.ops.segment_sum(slot_of_key * key_valid, gather_idx,
                                   num_segments=num_unique)
    touched = cnt > 0
    slot_val = jnp.where(touched, slot_sum / jnp.maximum(cnt, 1.0), 0.0)
    return touched, slot_val


def apply_push(
    state: TableState,
    unique_rows: jax.Array,   # int32 [U_pad]
    unique_grads: jax.Array,  # [U_pad, 3+mf_dim]: [g_show, g_clk, g_embed, g_embedx…]
    touched: jax.Array,       # bool [U_pad]
    slot_val: jax.Array,      # f32 [U_pad]
    cfg: SparseSGDConfig,
    rng: jax.Array,
) -> TableState:
    """In-table optimizer on merged grads — dy_mf_update_value
    (optimizer.cuh.h:80) + scatter write-back."""
    g = unique_grads
    rows = RowState(
        show=state.show[unique_rows], clk=state.clk[unique_rows],
        delta_score=state.delta_score[unique_rows],
        embed_w=state.embed_w[unique_rows],
        embed_g2sum=state.embed_g2sum[unique_rows],
        embedx_w=state.embedx_w[unique_rows],
        embedx_g2sum=state.embedx_g2sum[unique_rows],
        mf_size=state.mf_size[unique_rows],
    )
    mf_dim = state.mf_dim
    new = adagrad_update(rows, g[:, 0], g[:, 1], g[:, 2], g[:, 3:3 + mf_dim],
                         touched, cfg, rng)
    slot_new = jnp.where(touched, slot_val,
                         state.slot[unique_rows])

    if FLAGS.use_pallas_scatter:
        embedx_w_new = scatter_rows(state.embedx_w, unique_rows, new.embedx_w)
    else:
        embedx_w_new = state.embedx_w.at[unique_rows].set(new.embedx_w)
    st = TableState(
        show=state.show.at[unique_rows].set(new.show),
        clk=state.clk.at[unique_rows].set(new.clk),
        delta_score=state.delta_score.at[unique_rows].set(new.delta_score),
        slot=state.slot.at[unique_rows].set(slot_new),
        embed_w=state.embed_w.at[unique_rows].set(new.embed_w),
        embed_g2sum=state.embed_g2sum.at[unique_rows].set(new.embed_g2sum),
        embedx_w=embedx_w_new,
        embedx_g2sum=state.embedx_g2sum.at[unique_rows].set(new.embedx_g2sum),
        mf_size=state.mf_size.at[unique_rows].set(new.mf_size),
    )
    # restore the zero sentinel row (pads scatter pass-through values there)
    c = state.capacity
    return TableState(*[
        leaf.at[c].set(0.0) for leaf in st
    ])


class EmbeddingTable:
    """Single-shard embedding PS facade (BoxWrapper role)."""

    def __init__(self, mf_dim: int = 8, capacity: Optional[int] = None,
                 cfg: Optional[SparseSGDConfig] = None, seed: int = 0,
                 unique_bucket_min: int = 1024) -> None:
        self.mf_dim = mf_dim
        self.capacity = capacity or FLAGS.table_capacity_per_shard
        self.cfg = cfg or SparseSGDConfig()
        self.index = HostKV(self.capacity)
        self.state = init_table_state(self.capacity, mf_dim)
        self._rng = jax.random.PRNGKey(seed)
        self._push_count = 0
        self.unique_bucket_min = unique_bucket_min
        self._touched = np.zeros(self.capacity + 1, dtype=bool)

    # ---- per-batch host prep (dedup + row assignment) ----
    def _build_index(self, batch: SlotBatch, rows: np.ndarray,
                     inv: np.ndarray) -> PullIndex:
        """Shared padding/bucketing tail of prepare/prepare_eval."""
        u = len(rows)
        cap = self.unique_bucket_min
        while cap < u + 1:
            cap *= 2
        unique_rows = np.full(cap, self.capacity, dtype=np.int32)
        unique_rows[:u] = rows
        k_pad = batch.keys.shape[0]
        gather_idx = np.full(k_pad, u, dtype=np.int32)  # pads → sentinel slot
        gather_idx[:batch.num_keys] = inv
        key_valid = np.zeros(k_pad, dtype=np.float32)
        key_valid[:batch.num_keys] = 1.0
        return PullIndex(unique_rows, gather_idx, key_valid, u)

    def prepare(self, batch: SlotBatch) -> PullIndex:
        valid = batch.keys[:batch.num_keys]
        rows, inv = self.index.assign_unique(valid)
        self._touched[rows] = True
        return self._build_index(batch, rows, inv)

    def prepare_eval(self, batch: SlotBatch) -> PullIndex:
        """Read-only prepare: unknown keys map to the zero sentinel row
        instead of allocating (inference path — no index mutation)."""
        valid = batch.keys[:batch.num_keys]
        rows, inv = self.index.lookup_unique(valid, self.capacity)
        return self._build_index(batch, rows, inv)

    def next_rng(self) -> jax.Array:
        self._push_count += 1
        return jax.random.fold_in(self._rng, self._push_count)

    # ---- eager convenience (tests / small runs) ----
    def pull(self, idx: PullIndex) -> jax.Array:
        vals_u = pull_rows(self.state, jnp.asarray(idx.unique_rows))
        return expand_pull(vals_u, jnp.asarray(idx.gather_idx))

    def push(self, idx: PullIndex, key_grads: jax.Array,
             slot_of_key: Optional[jax.Array] = None) -> None:
        """Per-key-occurrence grads in → dedup-merge → optimizer apply."""
        if slot_of_key is None:
            slot_of_key = jnp.zeros(idx.gather_idx.shape[0], jnp.float32)
        gi = jnp.asarray(idx.gather_idx)
        kv = jnp.asarray(idx.key_valid)
        g, touched, slot_val = merge_push(
            key_grads, gi, kv, slot_of_key, idx.unique_rows.shape[0])
        self.state = apply_push(
            self.state, jnp.asarray(idx.unique_rows), g, touched, slot_val,
            self.cfg, self.next_rng())

    # ---- lifecycle: save / load / shrink (box_wrapper.cc:1383-1415) ----
    def _gather_host(self, rows: np.ndarray) -> Dict[str, np.ndarray]:
        st = jax.device_get(self.state)
        return {f: np.asarray(leaf)[rows] for f, leaf in zip(TableState._fields, st)}

    def save_base(self, path: str) -> int:
        """Full model dump (day-level batch model). Returns rows saved."""
        keys, rows = self.index.items()
        data = self._gather_host(rows)
        np.savez_compressed(path, keys=keys, **data)
        self._touched[:] = False
        return len(keys)

    def save_delta(self, path: str) -> int:
        """Incremental dump of rows touched since last save ("xbox delta")."""
        keys, rows = self.index.items()
        mask = self._touched[rows]
        keys, rows = keys[mask], rows[mask]
        data = self._gather_host(rows)
        np.savez_compressed(path, keys=keys, **data)
        self._touched[:] = False
        return len(keys)

    def load(self, path: str, merge: bool = False) -> int:
        """Load a save_base/save_delta file; merge=True keeps existing rows
        (delta apply), else resets the table first."""
        blob = np.load(path)
        keys = blob["keys"]
        if not merge:
            self.index = HostKV(self.capacity)
            self.state = init_table_state(self.capacity, self.mf_dim)
            self._touched[:] = False
        rows = self.index.assign(keys)
        st = jax.device_get(self.state)
        new_leaves = []
        for f, leaf in zip(TableState._fields, st):
            arr = np.asarray(leaf).copy()
            arr[rows] = blob[f]
            new_leaves.append(jnp.asarray(arr))
        self.state = TableState(*new_leaves)
        return len(keys)

    def shrink(self, delete_threshold: Optional[float] = None,
               decay: Optional[float] = None) -> int:
        """Age features: decay show/clk/delta_score, then drop rows whose
        decayed score falls below threshold (ShrinkTable semantics:
        box_wrapper.h:638, ctr_accessor shrink rules). Returns rows freed."""
        thr = (FLAGS.shrink_delete_threshold
               if delete_threshold is None else delete_threshold)
        dk = FLAGS.show_click_decay_rate if decay is None else decay
        keys, rows = self.index.items()
        if len(keys) == 0:
            return 0
        st = jax.device_get(self.state)
        show = np.asarray(st.show).copy() * dk
        clk = np.asarray(st.clk).copy() * dk
        delta = np.asarray(st.delta_score).copy() * dk
        score = (self.cfg.nonclk_coeff * (show[rows] - clk[rows])
                 + self.cfg.clk_coeff * clk[rows])
        drop = score < thr
        drop_keys = keys[drop]
        freed_rows = self.index.release(drop_keys)
        zero_mask = np.zeros(self.capacity + 1, dtype=bool)
        zero_mask[freed_rows] = True
        new_leaves = []
        for f, leaf in zip(TableState._fields, st):
            arr = np.asarray(leaf).copy()
            if f == "show":
                arr = show
            elif f == "clk":
                arr = clk
            elif f == "delta_score":
                arr = delta
            arr[zero_mask] = 0.0
            new_leaves.append(jnp.asarray(arr))
        self.state = TableState(*new_leaves)
        self._touched[freed_rows] = False
        log.info("shrink: freed %d/%d rows", len(freed_rows), len(keys))
        return int(len(freed_rows))

    @property
    def feature_count(self) -> int:
        return len(self.index)
