"""HBM-resident embedding table — the BoxPS/HeterPS store, single shard.

Reference capabilities re-implemented (SURVEY.md §2.1-2.2):
- ``BoxWrapper::PullSparse/PushSparseGrad`` (fleet/box_wrapper.h:488,526)
  with key dedup (``DedupKeysAndFillIdx``, box_wrapper_impl.h:129);
- the HeterPS GPU hashtable value store (heter_ps/hashtable.h:113,
  feature_value.h:570 ``FeatureValue`` layout) with in-table optimizer
  application (optimizer.cuh.h);
- pass/save lifecycle hooks (BeginPass/EndPass/SaveBase/SaveDelta/
  ShrinkTable, box_wrapper.cc:171-186,1383-1415).

TPU-native redesign: XLA needs static shapes, so the device side is a
statically-sized SoA of ``[capacity+1]`` arrays (row ``capacity`` is a
permanent zero "sentinel" used for padding); the key→row mapping is a host
hash index updated during batch preparation (overlapped with device compute
by the trainer's prefetch pipeline). Per-batch key dedup happens on host
(np.unique == DedupKeysAndFillIdx), so the device step is three fused ops:
gather unique rows → model fwd/bwd → segment-sum grads + one scatter update.
No dynamic growth inside jit — the riskiest reference behavior (SSD-backed
dynamic hashtable) maps to host-index growth + static device capacity
(+ Phase-5 host backing store).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.ops.pallas_kernels import _book_dispatch, gather_rows
from paddlebox_tpu.ps.sgd import (RowState, SparseSGDConfig,
                                  opt_ext_width, sparse_update)
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


NUM_FIXED = 8  # scalar columns before the embedx block


def _f_pad(feat: int) -> int:
    """Smallest divisor of 128 ≥ feat — the padded logical row width so
    rows pack evenly into 128-lane storage lines."""
    for d in (1, 2, 4, 8, 16, 32, 64, 128):
        if d >= feat:
            return d
    raise ValueError(f"feature width {feat} > 128 unsupported")


def _lane_onehot(sub: jax.Array, rpl: int, dtype) -> jax.Array:
    """[..., 1]-hot row-in-line selector mask (THE lane-packing
    selector, shared by gather_full_rows / expand_pull / merge_rows /
    apply_push): 1.0 at each element's row slot within its 128-lane
    line, 0 elsewhere."""
    return (jnp.arange(rpl, dtype=jnp.int32)[None, :]
            == sub.astype(jnp.int32)[:, None]).astype(dtype)


def _lane_select(mask: jax.Array, values: jax.Array) -> jax.Array:
    """Masked lane select: ``where(mask, values, 0)`` with the [N, rpl]
    one-hot broadcast over the trailing feature axis. Semantically the
    ``mask * values`` reduce every lane-packing site used to do, but
    NaN-ISOLATING: ``0 * NaN`` is NaN, so one diverging row's NaN used
    to bleed into every healthy row sharing its 128-lane storage line
    (and, through the scatter-add transpose, into their updates) —
    ``where`` keeps a NaN confined to its own lane span, which is what
    lets telemetry localize a NaN to ONE key (round-5 advisor finding).
    Exact f32 either way (select, no arithmetic)."""
    return jnp.where(mask.astype(bool)[:, :, None], values, 0)


def pack_geometry(capacity: int, feat: int):
    """(rows_per_line, f_pad, n_lines) for a [capacity+1, feat] logical
    table stored as [n_lines, 128] lane-aligned lines."""
    fp = _f_pad(feat)
    rpl = 128 // fp
    n_lines = (capacity + 1 + rpl - 1) // rpl
    return rpl, fp, n_lines


def unpack_host(packed: np.ndarray, capacity: int, feat: int) -> np.ndarray:
    """Packed [..., L, 128] → logical [..., C+1, F] (numpy; returns a
    copy only for the final column slice)."""
    rpl, fp, n_lines = pack_geometry(capacity, feat)
    lead = packed.shape[:-2]
    flat = packed.reshape(*lead, n_lines * rpl, fp)
    return flat[..., :capacity + 1, :feat]


def pack_host(logical: np.ndarray, capacity: int, feat: int) -> np.ndarray:
    """Logical [..., C+1, F] → packed [..., L, 128] (numpy)."""
    rpl, fp, n_lines = pack_geometry(capacity, feat)
    lead = logical.shape[:-2]
    out = np.zeros((*lead, n_lines * rpl, fp), logical.dtype)
    out[..., :capacity + 1, :feat] = logical
    return out.reshape(*lead, n_lines, 128)


@jax.tree_util.register_pytree_node_class
class TableState:
    """AoS feature-value store in PACKED line layout.

    Logical view: ``[..., C+1, 8+mf_dim]`` rows mirroring the reference's
    contiguous ``FeatureValue`` struct (feature_value.h:570) — cols 0..7
    = show, clk, delta_score, slot, embed_w, embed_g2sum, embedx_g2sum,
    mf_size; cols 8.. = embedx_w. Row C is the zero sentinel used by
    padding (pads that alias real storage lines read the zeroed padding
    columns instead — same zeros).

    Physical storage: ``packed [..., L, 128]`` with ``128 // f_pad``
    logical rows per 128-lane line (f_pad = feat rounded up to a divisor
    of 128). Why: XLA lays [C+1, 16] out COLUMN-major on TPU (minor dim
    must tile to 128 lanes without 8x padding), which makes every row
    gather/scatter touch 16 strided tiles — measured 2.2x slower than
    one contiguous line per row. The packed layout keeps rows lane-
    contiguous at zero memory waste; gathers fetch whole lines and
    extract in-register, pushes scatter-ADD masked line deltas.

    Why AoS and not per-field SoA: a TPU scatter/gather costs per INDEX,
    not per byte — nine per-field scatters were 9x the price of one
    row-matrix scatter. Host-side mirrors (HostStore) derive their
    layouts from FIELDS/TWO_D_FIELDS below; host code converts with
    pack_host/unpack_host (or the ``.data`` logical property)."""

    def __init__(self, packed: jax.Array, capacity: int, feat: int,
                 ext: int = 0) -> None:
        self.packed = packed
        self._capacity = int(capacity)
        self._feat = int(feat)
        # optimizer extension width appended after embedx_w
        # (ps/sgd.opt_ext_width): feat = NUM_FIXED + mf_dim + ext
        self._ext = int(ext)

    @classmethod
    def from_logical(cls, data, capacity: Optional[int] = None,
                     ext: int = 0) -> "TableState":
        """Build from a logical [..., C+1, F] matrix (host np or jnp)."""
        cap = data.shape[-2] - 1 if capacity is None else capacity
        feat = data.shape[-1]
        packed = pack_host(np.asarray(data), cap, feat)
        return cls(jnp.asarray(packed), cap, feat, ext)

    def tree_flatten(self):
        return (self.packed,), (self._capacity, self._feat, self._ext)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], *aux)

    def with_packed(self, packed: jax.Array) -> "TableState":
        return TableState(packed, self._capacity, self._feat, self._ext)

    @property
    def geometry(self):
        return pack_geometry(self._capacity, self._feat)

    @property
    def data(self) -> jax.Array:
        """LOGICAL [..., C+1, F] view (materialized — host/save paths and
        tests; the jit hot path uses gather_full_rows/apply_push on
        ``packed`` directly)."""
        rpl, fp, n_lines = self.geometry
        lead = self.packed.shape[:-2]
        flat = self.packed.reshape(*lead, n_lines * rpl, fp)
        return flat[..., :self._capacity + 1, :self._feat]

    @property
    def show(self) -> jax.Array:
        return self.data[..., 0]

    @property
    def clk(self) -> jax.Array:
        return self.data[..., 1]

    @property
    def delta_score(self) -> jax.Array:
        return self.data[..., 2]

    @property
    def slot(self) -> jax.Array:
        return self.data[..., 3]

    @property
    def embed_w(self) -> jax.Array:
        return self.data[..., 4]

    @property
    def embed_g2sum(self) -> jax.Array:
        return self.data[..., 5]

    @property
    def embedx_g2sum(self) -> jax.Array:
        return self.data[..., 6]

    @property
    def mf_size(self) -> jax.Array:
        return self.data[..., 7]

    @property
    def embedx_w(self) -> jax.Array:
        return self.data[..., NUM_FIXED:NUM_FIXED + self.mf_dim]

    @property
    def opt_ext(self) -> jax.Array:
        return self.data[..., NUM_FIXED + self.mf_dim:]

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def ext(self) -> int:
        return self._ext

    @property
    def mf_dim(self) -> int:
        return self._feat - NUM_FIXED - self._ext


# field-name → column mapping (host mirrors and save files use names)
FIELD_COL = {"show": 0, "clk": 1, "delta_score": 2, "slot": 3,
             "embed_w": 4, "embed_g2sum": 5, "embedx_g2sum": 6,
             "mf_size": 7}
FIELDS = tuple(FIELD_COL) + ("embedx_w",)
TWO_D_FIELDS = ("embedx_w",)  # [*, mf_dim] blocks; all others are scalar


def field_slice(data, name: str):
    """Column view of a field on a data matrix (numpy or jax)."""
    if name == "embedx_w":
        return data[..., NUM_FIXED:]
    return data[..., FIELD_COL[name]]


def field_assign(data: np.ndarray, rows: np.ndarray, name: str,
                 values: np.ndarray) -> None:
    """Write counterpart of field_slice: data[rows, <field cols>] = values.
    The single place that knows which fields are the embedx block (whose
    width follows the values — tables with an optimizer extension write
    mf-only blocks, field_slice round-trips write the full tail)."""
    if name == "embedx_w":
        data[rows, NUM_FIXED:NUM_FIXED + values.shape[-1]] = values
    else:
        data[rows, FIELD_COL[name]] = values


def next_bucket(minimum: int, need: int) -> int:
    """Power-of-two padding ladder: the smallest doubling of ``minimum``
    that is ≥ ``need`` (bounds distinct XLA compilations). THE bucket
    rule for unique-row capacities across all index builders."""
    cap = minimum
    while cap < need:
        cap *= 2
    return cap


def next_bucket_fine(minimum: int, need: int) -> int:
    """FINE bucket ladder for resident whole-pass shapes: round ``need``
    up to a step of ~1/16 its magnitude (pow2 steps, ≥512). A resident
    pass compiles one runner for its uniform shape either way, so the
    pow2 ladder's ≤100% padding is pure wire waste — this caps it at
    ~6% while steps stay coarse enough that successive passes of one
    workload almost always land on the same rung (bounded recompiles).
    Steps are multiples of 512, preserving the wire packers' alignment
    (pack_u18/pack_u16m need length % 4 == 0)."""
    if need <= minimum:
        return minimum  # exactly-tuned minimums stay padding-free
    step = max(512, 1 << max(need.bit_length() - 5, 0))
    return -(-need // step) * step


def _flatten_sharded_blob(blob):
    """Adapt a sharded-format save (``n`` + per-shard ``keys_s``/field_s
    blocks, written by ShardedEmbeddingTable._dump and the tiered table)
    to the single-table mapping ``load``/``merge_model`` consume."""
    if "n" not in blob:
        return blob
    fn = int(blob["n"])
    out = {"keys": np.concatenate([blob[f"keys_{s}"] for s in range(fn)])}
    for f in list(FIELDS) + ["opt_ext"]:
        if f"{f}_0" in blob:
            out[f] = np.concatenate([blob[f"{f}_{s}"] for s in range(fn)])
    return out


def store_fields_from_rows(sub: np.ndarray, mf_dim: int, opt_ext: int,
                           slot_override: Optional[np.ndarray] = None
                           ) -> Dict[str, np.ndarray]:
    """Logical rows [k, feat] → HostStore field dict — THE shared
    write-back assembly (tiered/pass-scoped end_pass + eviction).
    embedx is sliced to mf_dim explicitly: field_slice's tail is
    unbounded and would leak the opt_ext columns into the host store's
    (k, mf_dim) array. ``slot_override`` substitutes host slot metadata
    for tables that do not maintain the device slot column."""
    mf_end = NUM_FIXED + mf_dim
    vals = {f: (sub[:, NUM_FIXED:mf_end] if f == "embedx_w"
                else field_slice(sub, f)) for f in FIELDS}
    if slot_override is not None:
        vals["slot"] = slot_override
    if opt_ext:
        vals["opt_ext"] = sub[:, mf_end:]
    return vals


def rows_from_store_fields(vals: Dict[str, np.ndarray], mf_dim: int,
                           opt_ext: int) -> np.ndarray:
    """HostStore field dict → logical rows [k, feat] (the scatter input
    of delta staging) — inverse of store_fields_from_rows."""
    k = len(vals["show"])
    mf_end = NUM_FIXED + mf_dim
    out = np.zeros((k, mf_end + opt_ext), np.float32)
    idx = np.arange(k)
    for f in FIELDS:
        field_assign(out, idx, f, vals[f])
    if opt_ext:
        out[:, mf_end:] = vals["opt_ext"]
    return out


def promote_window_delta(index, touched: np.ndarray, capacity: int,
                         want_keys: np.ndarray, new_keys: np.ndarray,
                         gather_rows, writeback, on_freed=None,
                         pending: Optional[np.ndarray] = None,
                         protect: Optional[np.ndarray] = None):
    """THE shared per-window delta-promotion core (tiered shards and the
    single-chip PassScopedTable — box_wrapper.cc:129-186's incremental
    window, one place): reconcile the staged delta against the live
    window (keys that became resident since stage() keep their fresher
    rows), evict only under capacity pressure (clean rows first; dirty
    evictees go through ``writeback(keys, rows, gather_rows(rows))``),
    assign the remaining new keys as clean rows.

    ``pending`` (sorted uint64) lists keys whose rows were assigned by
    a ROUTING-PLAN build before their values staged (the overlapped
    preloader, ps/tiered.plan_scope): they look resident to the index
    but hold fresh ZERO rows, so the usual resident-is-fresher rule
    must NOT apply — their staged values win, and their (plan-baked)
    rows are pinned against eviction.

    ``protect`` lists additional keys PINNED against eviction: with the
    depth-N pass pipeline (ps/tiered stage queue) several FUTURE passes'
    working sets may be staged ahead of this begin — evicting a queued
    pass's resident row would invalidate the missing-split its stage
    already computed (the capacity contract is the union over open +
    queued passes; ps/tiered.py module docstring).

    Caller holds the host lock and scatters the staged values for the
    returned ``rows_new``. Returns (rows_new, still_missing_mask,
    stats) — ``stats["evict_sec"]`` is the wall spent in the eviction
    block (the begin-boundary's inline/emergency eviction cost; the
    async lane's eviction is accounted by the table).
    ``on_freed(rows)`` hooks per-row host metadata cleanup."""
    miss = index.lookup(new_keys) < 0
    still = miss
    if pending is not None and len(pending):
        still = miss | np.isin(new_keys, pending, assume_unique=False)
    ins_keys = new_keys[still]
    stats = dict(resident=len(want_keys) - len(ins_keys),
                 staged=len(ins_keys), evicted=0, evicted_writeback=0,
                 evict_sec=0.0)
    # capacity pressure counts only truly-missing keys: pending keys
    # already own rows, re-assigning them allocates nothing
    overflow = len(index) + int(miss.sum()) - capacity
    if overflow > 0:
        t0 = time.perf_counter()
        live_keys, live_rows = index.items()
        cand = ~np.isin(live_keys, want_keys)
        if pending is not None and len(pending):
            # plan-baked rows for a FUTURE pass: their row ids are
            # already encoded in that pass's staged wire — evicting
            # them would hand the rows to other keys
            cand &= ~np.isin(live_keys, pending)
        if protect is not None and len(protect):
            cand &= ~np.isin(live_keys, protect)
        ck, cr = live_keys[cand], live_rows[cand]
        t = touched[cr]
        order = np.argsort(t, kind="stable")[:overflow]
        ck, cr, t = ck[order], cr[order], t[order]
        if t.any():
            writeback(ck[t], cr[t], gather_rows(cr[t]))
            stats["evicted_writeback"] = int(t.sum())
        freed = index.release(ck)
        touched[freed] = False
        if on_freed is not None:
            on_freed(freed)
        stats["evicted"] = len(ck)
        stats["evict_sec"] = time.perf_counter() - t0
    rows_new = index.assign(ins_keys)
    touched[rows_new] = False  # freshly loaded = clean
    from paddlebox_tpu.obs.hub import get_hub
    hub = get_hub()
    if hub.active:  # per-pass window accounting → Prometheus counters
        for k, help_txt in (("staged", "rows fetched+scattered into the "
                             "HBM window"),
                            ("resident", "working-set rows already "
                             "resident at begin_pass"),
                            ("evicted", "rows evicted under capacity "
                             "pressure"),
                            ("evicted_writeback", "dirty evictions "
                             "written back to the host tier")):
            if stats[k]:
                hub.counter(f"pbox_table_{k}_rows_total",
                            help_txt).inc(stats[k])
    return rows_new, still, stats


_ROW_GATHER_FNS: Dict[tuple, object] = {}


def dispatch_packed_row_gather(state: "TableState", shard: Optional[int],
                               rows: np.ndarray) -> Tuple[jax.Array, int]:
    """Dispatch a ``[bucket, feat]`` logical-row gather straight off the
    packed lines (shard ``shard`` of a stacked [N, L, 128] state, or the
    single table with ``shard=None``) and return the un-fetched device
    array + the real row count (callers slice ``[:k]`` after
    ``device_get``).

    THE async-epilogue D2H primitive (ps/epilogue): end_pass must
    dispatch its gathers before returning (the dispatch pins the
    immutable buffers against a later donating jit step), so dispatch
    cost IS the end_pass critical path. Eager ops re-trace per call and
    touch the full packed buffer (~0.8 s/dispatch measured on the CPU
    bench at 4M rows); this is ONE jitted executable per table geometry
    — row indices pad to a pow2 bucket (pads read the zero sentinel
    row), so delta-sized passes reuse the compile."""
    rpl, fp, _ = state.geometry
    feat = state._feat
    k = len(rows)
    bucket = next_bucket(1024, max(k, 1))
    idx = np.full(bucket, state.capacity, np.int32)  # pads → sentinel
    idx[:k] = rows
    sharded = shard is not None
    key = (sharded, rpl, fp, feat)
    fn = _ROW_GATHER_FNS.get(key)
    if fn is None:
        cols = jnp.arange(feat, dtype=jnp.int32)

        if sharded:
            def run(packed, s, idx):
                lines = packed[s, idx // rpl]            # [K, 128]
                off = (idx % rpl * fp)[:, None] + cols[None, :]
                return jnp.take_along_axis(lines, off, axis=1)
        else:
            def run(packed, idx):
                lines = packed[idx // rpl]
                off = (idx % rpl * fp)[:, None] + cols[None, :]
                return jnp.take_along_axis(lines, off, axis=1)
        fn = jax.jit(run)
        _ROW_GATHER_FNS[key] = fn
    if sharded:
        out = fn(state.packed, jnp.asarray(shard, jnp.int32),
                 jnp.asarray(idx))
    else:
        out = fn(state.packed, jnp.asarray(idx))
    return out, k


def host_pull_block(vals: np.ndarray, mf_dim: int) -> np.ndarray:
    """[k, F] gathered logical rows → [k, 3+mf] pull values (show, clk,
    embed_w, mf_size-gated embedx) — THE host-side CopyForPull block
    assembly, shared by every host pull (EmbeddingTable.host_pull,
    MultiMfShardedTable.pull)."""
    mf_end = NUM_FIXED + mf_dim
    gate = vals[:, FIELD_COL["mf_size"]:FIELD_COL["mf_size"] + 1] > 0
    return np.concatenate(
        [vals[:, FIELD_COL["show"]:FIELD_COL["clk"] + 1],
         vals[:, FIELD_COL["embed_w"]:FIELD_COL["embed_w"] + 1],
         vals[:, NUM_FIXED:mf_end] * gate], axis=1)


def dedup_first_seen(keys: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Dedup ``keys`` in FIRST-SEEN order → (uniq, first_idx, inv).

    The bulk pass-assign front half (EmbeddingTable.bulk_assign_unique):
    dedup runs OUTSIDE host_lock, and first-seen order makes the single
    bulk ``index.assign`` allocate new rows in exactly the order a
    serial batch-by-batch walk of the native hash index would (the
    native assign_unique is first-occurrence by construction), so bulk
    and per-batch builds are row-for-row identical there.

    Routed through the native one-pass dedup (ps/kv.
    dedup_first_seen_native) when the library is available — the
    python formulation below walks the stream three times (unique +
    argsort + rank scatter); both produce bitwise-identical outputs
    (tests/test_pallas_index.py gates it), and the cut shows up in
    ``pbox_preload_build_seconds_total{stage=dedup}``."""
    from paddlebox_tpu.ps.kv import dedup_first_seen_native
    out = dedup_first_seen_native(keys)
    if out is not None:
        return out
    return _dedup_first_seen_py(keys)


def _dedup_first_seen_py(keys: np.ndarray
                         ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """The pure-python three-pass formulation (the oracle the native
    and device paths are gated against)."""
    uniq_s, first_s, inv_s = np.unique(keys, return_index=True,
                                       return_inverse=True)
    order = np.argsort(first_s, kind="stable")
    rank = np.empty(len(order), np.int64)
    rank[order] = np.arange(len(order), dtype=np.int64)
    return uniq_s[order], first_s[order], rank[inv_s]


def fill_oob_pads(unique_rows: np.ndarray, u: int, capacity: int) -> None:
    """Fill positions [u:] with DISTINCT out-of-bounds row ids (> capacity).

    This is the unique-scatter invariant shared by every host index
    builder: pads must never collide with real rows OR each other, so
    gathers through them clamp to the zero sentinel row, scatters drop
    them, and apply_push can promise ``unique_indices`` to XLA."""
    n = len(unique_rows) - u
    unique_rows[u:] = capacity + np.arange(1, n + 1, dtype=np.int32)


class PullIndex(NamedTuple):
    """Host-built per-batch dedup index (DedupKeysAndFillIdx analogue)."""

    unique_rows: np.ndarray  # int32 [U_pad]; pads → sentinel row C
    gather_idx: np.ndarray   # int32 [K_pad]; pads → sentinel slot
    key_valid: np.ndarray    # f32   [K_pad]; 1.0 for real keys
    num_unique: int


# Host key→row index implementations live in ps/kv.py (native C++ fast path
# + python fallback). HostKV is the factory used across the tables.
from paddlebox_tpu.ps.kv import make_kv as HostKV  # noqa: N813


def init_table_state(capacity: int, mf_dim: int,
                     dtype=jnp.float32, ext: int = 0) -> TableState:
    feat = NUM_FIXED + mf_dim + ext
    _, _, n_lines = pack_geometry(capacity, feat)
    return TableState(jnp.zeros((n_lines, 128), dtype), capacity, feat,
                      ext)


def gather_full_rows(state: TableState, unique_rows: jax.Array) -> jax.Array:
    """ONE line-gather of complete feature rows → [U, 8+mf_dim].

    Each logical row lives lane-contiguous inside one 128-wide storage
    line (see TableState); the gather fetches whole lines and a ONE-HOT
    mask + sum over the rows-per-line axis extracts the row's slice
    in-register. The earlier take_along_axis extract lowered to a SECOND
    per-index gather and cost as much as the line fetch itself — the
    mask extract is pure VPU work (measured: 23.3 → 12.9 ms at U=491k,
    scripts/profile_keypath2.py, round 5). Pad/OOB ids are clamped to
    the SENTINEL row before the line split so they read its zeros —
    clamping raw line indices instead would let a far-OOB id alias a
    real row when capacity % rows_per_line == rpl-1."""
    rpl, fp, _ = state.geometry
    u = unique_rows.shape[0]
    rows = jnp.minimum(unique_rows, state.capacity)
    if FLAGS.use_pallas_gather:
        _book_dispatch("gather_rows", "pallas")
        lines = gather_rows(state.packed, rows // rpl)
    else:
        _book_dispatch("gather_rows", "xla")
        lines = state.packed[rows // rpl]                 # [U, 128]
    grouped = lines.reshape(u, rpl, fp)
    onehot = _lane_onehot(rows % rpl, rpl, lines.dtype)   # [U, rpl]
    # elementwise mask+reduce, NOT einsum (default-precision dot_general
    # would round through bf16 on TPU); where-select, NOT multiply, so a
    # NaN row cannot bleed across its storage line (_lane_select)
    vals = _lane_select(onehot, grouped).sum(axis=1)
    return vals[:, :state._feat] if fp != state._feat else vals


_SCATTER_CHUNK_FNS: Dict[tuple, object] = {}


def _scatter_chunk_fn(sharded: bool, rpl: int, fp: int, feat: int):
    """Jitted FIXED-SHAPE chunk scatter (one executable per geometry ×
    chunk size, reused across every pass boundary): rows arrive padded
    to the chunk with out-of-bounds line ids, ``mode="drop"`` discards
    them. The packed buffer is DONATED — the caller must treat the input
    state as consumed."""
    key = (sharded, rpl, fp, feat)
    fn = _SCATTER_CHUNK_FNS.get(key)
    if fn is not None:
        return fn
    cols_off = jnp.arange(feat, dtype=jnp.int32)

    if sharded:
        def run(packed, shard_c, rows_c, vals_c):
            lines = rows_c // rpl
            cols = (rows_c % rpl * fp)[:, None] + cols_off[None, :]
            return packed.at[shard_c[:, None], lines[:, None],
                             cols].set(vals_c, mode="drop")
    else:
        def run(packed, rows_c, vals_c):
            lines = rows_c // rpl
            cols = (rows_c % rpl * fp)[:, None] + cols_off[None, :]
            return packed.at[lines[:, None], cols].set(vals_c,
                                                       mode="drop")
    fn = jax.jit(run, donate_argnums=(0,))
    _SCATTER_CHUNK_FNS[key] = fn
    return fn


def scatter_logical_rows(state: TableState, shard_idx,
                         rows: np.ndarray,
                         values: np.ndarray,
                         chunk: Optional[int] = None) -> TableState:
    """Device scatter of logical rows into a packed state — stacked
    [N, L, 128] with ``shard_idx`` per row, or a single table [L, 128]
    with ``shard_idx=None``: row ``rows[k]`` (of shard ``shard_idx[k]``)
    becomes ``values[k]`` (logical width feat). The delta-staging
    primitive (tiered/pass-scoped begin_pass): wire cost is just
    ``values`` — the table itself never crosses the host↔device
    boundary. (shard, row) pairs must be unique; pad columns
    [feat:f_pad] of the line stay untouched (zero by the init/push
    invariants).

    The scatter runs in FIXED-SIZE chunks (``FLAGS.scatter_chunk_rows``)
    so XLA compiles ONE executable per table geometry instead of one per
    delta size — the per-pass-boundary scatter compile measured ~20 s on
    TPU (docs/BENCH_SHAPES.md tiered row, round 4) and delta sizes vary
    every pass. Chunk pads are out-of-bounds line ids (dropped on
    device); values ship exact-size and are zero-padded on device, so no
    pad bytes ride the wire. The input state stays VALID (unchanged
    semantics for callers that keep references, e.g. trainers that
    adopted it): one explicit device copy feeds the first chunk and the
    chunks donate intermediates to each other — total table traffic is
    one copy regardless of chunk count."""
    rpl, fp, n_lines = state.geometry
    feat = state._feat
    n = len(rows)
    if n == 0:
        return state
    from paddlebox_tpu.config import FLAGS
    c = int(chunk or FLAGS.scatter_chunk_rows)
    sharded = shard_idx is not None
    rows = np.ascontiguousarray(rows, np.int32)
    if sharded:
        shard_idx = np.ascontiguousarray(shard_idx, np.int32)
        n_shards = state.packed.shape[0]
    vals_np = np.asarray(values)
    fn = _scatter_chunk_fn(sharded, rpl, fp, feat)
    # the chunk executable donates its input; feed it a copy so callers
    # (trainers that adopted this state) keep a live buffer
    packed = jnp.copy(state.packed)
    oob_row = n_lines * rpl  # line index == n_lines → dropped
    np_dtype = np.dtype(packed.dtype)
    for off in range(0, n, c):
        m = min(c, n - off)
        r_c = np.full(c, oob_row, np.int32)
        r_c[:m] = rows[off:off + m]
        if m == c:
            v_c = jnp.asarray(
                np.ascontiguousarray(vals_np[off:off + m], np_dtype))
        else:
            # tail chunk: pad on HOST — a device-side pad
            # (dynamic_update_slice) would compile per remainder size,
            # re-introducing a per-delta compile at the pass boundary;
            # the ≤1-chunk of zero pad bytes compresses on the wire
            v_full = np.zeros((c, feat), np_dtype)
            v_full[:m] = vals_np[off:off + m]
            v_c = jnp.asarray(v_full)
        if sharded:
            s_c = np.full(c, n_shards, np.int32)
            s_c[:m] = shard_idx[off:off + m]
            packed = fn(packed, jnp.asarray(s_c), jnp.asarray(r_c), v_c)
        else:
            packed = fn(packed, jnp.asarray(r_c), v_c)
    return state.with_packed(packed)


def warmup_begin_scatter(state: TableState, sharded: bool,
                         chunk: Optional[int] = None) -> TableState:
    """Compile the begin_pass chunk scatter AHEAD of the first pass
    boundary (a no-op scatter of one dropped row): with the persistent
    compilation cache enabled this also seeds the on-disk cache, so a
    cold process's first delta begin_pass deserializes instead of
    paying the ~20 s scatter compile. Returns the (unchanged-content)
    state."""
    rpl, _, n_lines = state.geometry
    oob = np.array([n_lines * rpl], np.int32)
    z = np.zeros((1, state._feat), np.float32)
    sh = np.array([state.packed.shape[0]], np.int32) if sharded else None
    return scatter_logical_rows(state, sh, oob, z, chunk=chunk)


def aot_warmup_scatter(shape, dtype, sharded: bool, rpl: int, fp: int,
                       feat: int, chunk: Optional[int] = None) -> float:
    """AOT-compile the pass-boundary chunk scatter from
    ``jax.ShapeDtypeStruct`` inputs — NO device buffers are allocated
    (the old warmup materialized a throwaway TABLE-SIZED zeros buffer,
    which could nondeterministically OOM a box whose HBM was already
    committed to the live table + staging). The AOT executable does NOT
    land in jit's dispatch cache, so the warmup's value rides the
    PERSISTENT cache: the real begin_pass deserializes (~0.1-1 s)
    instead of paying the ~20 s scatter compile — which is why the
    on-disk cache is enabled HERE, before lowering (tables construct
    before Trainer init, and jax decides cache put at compile
    initiation; without this the warmup compiled into the void and
    still reported ok). Returns compile seconds (telemetry)."""
    import time as _time
    from paddlebox_tpu.config import FLAGS as _F
    from paddlebox_tpu.utils.compile_cache import enable_compilation_cache
    enable_compilation_cache()
    c = int(chunk or _F.scatter_chunk_rows)
    fn = _scatter_chunk_fn(sharded, rpl, fp, feat)
    sds = jax.ShapeDtypeStruct
    args = [sds(shape, dtype)]
    if sharded:
        args.append(sds((c,), jnp.int32))
    args += [sds((c,), jnp.int32), sds((c, feat), dtype)]
    t0 = _time.perf_counter()
    fn.lower(*args).compile()
    return _time.perf_counter() - t0


def start_scatter_warmup(state: TableState, sharded: bool) -> None:
    """Background-compile the pass-boundary chunk scatter at table
    construction (FLAGS.warmup_pass_scatter) via ``aot_warmup_scatter``:
    abstract ShapeDtypeStruct inputs mean the warmup costs ZERO device
    memory — same shapes → same executable in the (persistent) compile
    cache, and the live buffer is never donated behind the backs of
    trainers that already adopted it. Outcome is emitted as a
    ``scatter_warmup`` telemetry event either way (a silent warmup
    failure used to be invisible until the first pass boundary stalled
    ~20 s)."""
    from paddlebox_tpu.config import FLAGS
    if not FLAGS.warmup_pass_scatter:
        return

    rpl, fp, n_lines = state.geometry
    feat = state._feat
    shape = state.packed.shape
    dtype = state.packed.dtype

    def run() -> None:
        from paddlebox_tpu.obs.hub import get_hub
        hub = get_hub()
        try:
            secs = aot_warmup_scatter(shape, dtype, sharded, rpl, fp,
                                      feat)
            if hub.active:
                hub.counter("pbox_scatter_warmup_total",
                            "pass-scatter warmup attempts").inc(
                                outcome="ok")
                hub.emit("scatter_warmup", outcome="ok",
                         compile_sec=round(secs, 3),
                         sharded=sharded, feat=feat)
        except Exception as e:  # warmup only — training still works
            from paddlebox_tpu.utils.logging import get_logger
            get_logger(__name__).warning("pass-scatter warmup failed: %s",
                                         e)
            if hub.active:
                hub.counter("pbox_scatter_warmup_total",
                            "pass-scatter warmup attempts").inc(
                                outcome="failed")
                hub.emit("scatter_warmup", outcome="failed", error=str(e))

    threading.Thread(target=run, daemon=True).start()


def pull_values(rows_full: jax.Array,
                mf_dim: Optional[int] = None) -> jax.Array:
    """Pull-value view of gathered rows → [U, 3+mf_dim] laid out as
    [show, clk, embed_w, embedx…] (FeaturePullValue, feature_value.h:161).
    Non-materialized mf (mf_size==0) reads as zeros, as in CopyForPull.
    ``mf_dim`` must be passed for tables with an optimizer extension
    block (defaults to everything after the fixed columns)."""
    gate = (rows_full[:, 7] > 0).astype(rows_full.dtype)
    end = rows_full.shape[1] if mf_dim is None else NUM_FIXED + mf_dim
    mf = rows_full[:, NUM_FIXED:end] * gate[:, None]
    return jnp.concatenate(
        [rows_full[:, 0:2], rows_full[:, 4:5], mf], axis=1)


def pull_rows(state: TableState, unique_rows: jax.Array) -> jax.Array:
    """gather_full_rows + pull_values (kept for callers that don't reuse
    the full rows for the push)."""
    return pull_values(gather_full_rows(state, unique_rows), state.mf_dim)


def expand_pull(values_u: jax.Array, gather_idx: jax.Array) -> jax.Array:
    """[U, D] unique values → [K, D] per-key-occurrence values.

    LANE-PACKED formulation (round 5): the naive ``values_u[gather_idx]``
    row gather — and, worse, its autodiff transpose (the per-unique grad
    merge) — pay XLA's per-index cost on narrow strided rows. Packing
    the unique values into 128-lane lines (8 rows/line at D ≤ 16) makes
    the forward a line fetch + one-hot VPU extract and the TRANSPOSE a
    line-granular scatter-add of masked deltas (the apply_push trick,
    derived by autodiff for free). Measured at the ragged bench shape
    (K=557k, U=491k): fwd 18.1 → 11.0 ms, transpose 39.4 → 13.3 ms
    (scripts/profile_keypath3.py, exact f32 both ways). Falls back to
    the plain gather when the shapes don't line-align."""
    u, d = values_u.shape
    fp = _f_pad(d) if d <= 128 else 0
    rpl = 128 // fp if fp else 0
    if not fp or u % rpl:
        return values_u[gather_idx]
    padded = (values_u if fp == d else
              jnp.pad(values_u, ((0, 0), (0, fp - d))))
    packed = padded.reshape(u // rpl, 128)
    # clamp BEFORE the line split so out-of-range indices read row u-1,
    # exactly like the plain gather's clamp semantics (line-clamping
    # alone would read row u-rpl)
    gi = jnp.clip(gather_idx, 0, u - 1)
    lines = packed[gi // rpl]                          # [K, 128]
    grouped = lines.reshape(-1, rpl, fp)
    onehot = _lane_onehot(gi % rpl, rpl, lines.dtype)  # [K, rpl]
    # elementwise mask+reduce, NOT einsum: a dot_general would run at
    # default (bf16-pass) matmul precision on TPU and break the exact-
    # f32 contract of this op and its autodiff transpose; where-select,
    # NOT multiply, so a NaN unique row stays confined to its own keys
    # (_lane_select — the transpose derives the same select)
    vals = _lane_select(onehot, grouped).sum(axis=1)
    return vals[:, :d] if fp != d else vals


def merge_rows(values: jax.Array, idx: jax.Array,
               num_segments: int) -> jax.Array:
    """segment_sum of narrow rows in LANE-PACKED form: [M, D] values
    summed by ``idx`` into [num_segments, D]. A scatter-add into a
    [num, D<16] accumulator is random-access RMW on strided narrow rows
    (~3x slower than line-granular — DESIGN_NOTES §4i); this packs each
    contribution into its row's lane span of a 128-lane line delta and
    scatter-adds whole lines (disjoint-lane adds commute exactly, the
    apply_push trick). Exact f32; falls back to jax.ops.segment_sum when
    shapes don't line-align."""
    m, d = values.shape
    fp = _f_pad(d) if d <= 128 else 0
    rpl = 128 // fp if fp else 0
    # the line form wins in the RMW-bound regime (large accumulators):
    # measured 13.3 vs 39.4 ms into 491k segments but ~13 vs 12.6 into
    # 106k — below the crossover the plain scatter-add is already fast
    # and the [M, 128] delta materialization is pure overhead
    if not fp or num_segments % rpl or num_segments <= (1 << 17):
        return jax.ops.segment_sum(values, idx, num_segments=num_segments)
    v = (values if fp == d else
         jnp.pad(values, ((0, 0), (0, fp - d))))
    onehot = _lane_onehot(idx % rpl, rpl, v.dtype)      # [M, rpl]
    d_lines = _lane_select(onehot, v[:, None, :]).reshape(m, 128)
    out = jnp.zeros((num_segments // rpl, 128), v.dtype).at[
        idx // rpl].add(d_lines, mode="drop")
    out = out.reshape(num_segments, fp)
    return out[:, :d] if fp != d else out


def merge_push(key_grads: jax.Array, gather_idx: jax.Array,
               key_valid: jax.Array, slot_of_key: jax.Array,
               num_unique: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Dedup-merge per-key-occurrence grads into per-unique-row grads —
    PushMergeCopy (box_wrapper.cu:417). Returns (unique_grads [U, D],
    touched [U] bool, slot_val [U]). NOTE: when grads come from autodiff
    through ``expand_pull`` they are ALREADY occurrence-merged; use
    ``push_stats`` for just touched/slot then."""
    g = jax.ops.segment_sum(key_grads * key_valid[:, None], gather_idx,
                            num_segments=num_unique)
    touched, slot_val = push_stats(gather_idx, key_valid, slot_of_key,
                                   num_unique)
    return g, touched, slot_val


def push_stats(gather_idx: jax.Array, key_valid: jax.Array,
               slot_of_key: jax.Array,
               num_unique: int) -> Tuple[jax.Array, jax.Array]:
    """Per-unique-row touched flag and mean slot id."""
    cnt = jax.ops.segment_sum(key_valid, gather_idx, num_segments=num_unique)
    slot_sum = jax.ops.segment_sum(slot_of_key * key_valid, gather_idx,
                                   num_segments=num_unique)
    touched = cnt > 0
    slot_val = jnp.where(touched, slot_sum / jnp.maximum(cnt, 1.0), 0.0)
    return touched, slot_val


def apply_push(
    state: TableState,
    unique_rows: jax.Array,   # int32 [U_pad]
    unique_grads: jax.Array,  # [U_pad, 3+mf_dim]: [g_show, g_clk, g_embed, g_embedx…]
    cfg: SparseSGDConfig,
    rng: jax.Array,
    rows_full: Optional[jax.Array] = None,  # [U_pad, F] from gather_full_rows
    touched: Optional[jax.Array] = None,    # bool [U_pad]; None → derived
    slot_val: Optional[jax.Array] = None,   # f32 [U_pad]; None → keep col
) -> TableState:
    """In-table optimizer on merged grads — dy_mf_update_value
    (optimizer.cuh.h:80) + scatter write-back.

    The whole table write is ONE line-granular scatter-ADD of masked
    deltas (packed layout — see TableState): each updated row contributes
    ``new − old`` placed at its lane span inside a zero [U, 128] line
    delta. Line indices may REPEAT (several logical rows share a storage
    line) — their deltas occupy disjoint lanes, so the add commutes
    exactly; pad rows are masked to zero delta so in-bounds-aliasing pads
    write nothing. NOTE: ``old + (new − old)`` can differ from ``new`` by
    1 ulp — both train paths share this op, so path-parity is exact.

    ``rows_full`` lets the caller reuse the rows gathered for the pull
    (gather_full_rows) instead of re-gathering here. ``touched`` defaults
    to the dup-free contract (every in-bounds row was hit); ``slot_val``
    None keeps the stored slot column — the single-process tables track
    slot as HOST metadata (EmbeddingTable.slot_host), so no device
    segment op is spent on it."""
    g = unique_grads
    if touched is None:
        # strictly < capacity: real rows are always below the sentinel.
        # The compact wire maps pad keys to row == capacity and dedup_rows
        # emits that as an in-bounds unique entry — the optimizer must
        # never run on it (lazy mf creation would seed it from RNG before
        # the trailing re-zero).
        touched = unique_rows < state.capacity
    if rows_full is None:
        rows_full = gather_full_rows(state, unique_rows)
    mf_dim = state.mf_dim
    mf_end = NUM_FIXED + mf_dim
    rows = RowState(
        show=rows_full[:, 0], clk=rows_full[:, 1],
        delta_score=rows_full[:, 2],
        embed_w=rows_full[:, 4], embed_g2sum=rows_full[:, 5],
        embedx_w=rows_full[:, NUM_FIXED:mf_end],
        embedx_g2sum=rows_full[:, 6],
        mf_size=rows_full[:, 7],
        opt_ext=rows_full[:, mf_end:],
    )
    new = sparse_update(rows, g[:, 0], g[:, 1], g[:, 2], g[:, 3:3 + mf_dim],
                        touched, cfg, rng)
    if slot_val is None:
        slot_new = rows_full[:, 3]
    else:
        slot_new = jnp.where(touched, slot_val, rows_full[:, 3])
    new_mat = jnp.concatenate([
        new.show[:, None], new.clk[:, None], new.delta_score[:, None],
        slot_new[:, None], new.embed_w[:, None], new.embed_g2sum[:, None],
        new.embedx_g2sum[:, None], new.mf_size[:, None], new.embedx_w,
        new.opt_ext,
    ], axis=1)
    rpl, fp, _ = state.geometry
    u = new_mat.shape[0]
    # where, not multiply: an untouched row holding NaN would otherwise
    # turn its masked-out delta into NaN (0 * NaN) and poison the line
    delta = jnp.where(touched[:, None], new_mat - rows_full, 0)
    if fp != state._feat:
        delta = jnp.concatenate(
            [delta, jnp.zeros((u, fp - state._feat), delta.dtype)], axis=1)
    onehot = _lane_onehot(unique_rows % rpl, rpl, delta.dtype)
    d_lines = _lane_select(onehot, delta[:, None, :]).reshape(u, 128)
    packed = state.packed.at[unique_rows // rpl].add(d_lines, mode="drop")
    # keep the sentinel row zero (defense in depth — pad deltas are
    # masked, but eval's miss collapse reads it)
    cap = state.capacity
    s0 = (cap % rpl) * fp
    packed = packed.at[cap // rpl, s0:s0 + fp].set(0.0)
    return state.with_packed(packed)


class EmbeddingTable:
    """Single-shard embedding PS facade (BoxWrapper role)."""

    def __init__(self, mf_dim: int = 8, capacity: Optional[int] = None,
                 cfg: Optional[SparseSGDConfig] = None, seed: int = 0,
                 unique_bucket_min: int = 1024,
                 arena_slots: Optional[int] = None,
                 arena_chunk_bits: int = 12) -> None:
        """``arena_slots``: enable the slot-arena row allocator (native
        kv_index Arena) for ``arena_slots`` feature slots — rows cluster
        into per-slot chunk extents so the resident-pass COMPACT wire can
        ship ~17-bit slot-local rows instead of dedup streams
        (train/device_pass.py). Purely an allocation policy: every other
        table path (save/load/shrink/streaming prepare) is unchanged and
        correct either way; keys that enter through slotless paths make
        the compact wire fall back to the dedup wire for passes touching
        them."""
        self.mf_dim = mf_dim
        self.capacity = capacity or FLAGS.table_capacity_per_shard
        self.cfg = cfg or SparseSGDConfig()
        self.opt_ext = opt_ext_width(self.cfg, mf_dim)
        self.index = HostKV(self.capacity)
        self.arena_slots = arena_slots
        self.arena_chunk_bits = arena_chunk_bits
        if arena_slots is not None:
            self.index.arena_enable(arena_chunk_bits, arena_slots)
        self.state = init_table_state(self.capacity, mf_dim,
                                      ext=self.opt_ext)
        self._rng = jax.random.PRNGKey(seed)
        self._push_count = 0
        self.unique_bucket_min = unique_bucket_min
        self._touched = np.zeros(self.capacity + 1, dtype=bool)
        # per-row slot id — HOST metadata (the FeatureValue slot field,
        # feature_value.h:570). Slot never changes for a key, and the host
        # sees every key at assign time, so no device work tracks it.
        self.slot_host = np.zeros(self.capacity + 1, dtype=np.int16)
        # serializes host-side index/touched mutation across threads
        # (prefetch prepare, ResidentPass.build preload, shrink/save/load)
        self.host_lock = threading.Lock()
        # device-resident key index (FLAGS.use_pallas_index seam):
        # created lazily on first flag-on bulk assign, dropped whenever
        # the host kv's allocation may stop being dense (load/merge/
        # shrink) — see _device_index
        self._dev_index = None
        # last bulk_assign_unique timing split, host-lock mirror work vs
        # device insert work — surfaced as the preloader's `index` build
        # stage (train/device_pass._dedup_phase)
        self.last_assign_seconds = {"index_host": 0.0, "index_device": 0.0}

    # ---- per-batch host prep (dedup + row assignment) ----
    def _build_index(self, batch: SlotBatch, rows: np.ndarray,
                     inv: np.ndarray) -> PullIndex:
        """Shared padding/bucketing tail of prepare/prepare_eval.

        Padding positions (u.., where padded KEYS also point) get the
        fill_oob_pads treatment, keeping unique_rows duplicate-free.
        (rows itself is dup-free: assign_unique returns distinct rows;
        lookup_unique collapses all misses into ONE sentinel entry.)"""
        u = len(rows)
        cap = next_bucket(self.unique_bucket_min, u + 1)
        unique_rows = np.empty(cap, dtype=np.int32)
        unique_rows[:u] = rows
        fill_oob_pads(unique_rows, u, self.capacity)
        k_pad = batch.keys.shape[0]
        gather_idx = np.full(k_pad, u, dtype=np.int32)  # pads → sentinel slot
        gather_idx[:batch.num_keys] = inv
        key_valid = np.zeros(k_pad, dtype=np.float32)
        key_valid[:batch.num_keys] = 1.0
        return PullIndex(unique_rows, gather_idx, key_valid, u)

    def host_pull(self, keys: np.ndarray,
                  data: Optional[np.ndarray] = None) -> np.ndarray:
        """[n] keys → [n, 3+mf] pull values on HOST (show, clk, embed_w,
        embedx…); unknown keys → zeros. Shared by the serving mirror and
        MultiMfEmbeddingTable.pull — THE host-side CopyForPull.
        ``data`` lets callers pass a cached logical mirror."""
        keys = np.ascontiguousarray(keys, np.uint64)
        rows, inv = self.index.lookup_unique(keys, self.capacity)
        if data is None:
            data = np.asarray(jax.device_get(self.state.data))
        vals = data[np.minimum(rows, self.capacity)]  # OOB pads clamp
        return host_pull_block(vals, self.mf_dim)[inv]

    def record_slots(self, rows: np.ndarray, inv: np.ndarray,
                     slot_of_key: np.ndarray) -> None:
        """Record each unique row's slot (first key occurrence wins via
        the reversed assignment). Caller holds host_lock."""
        self.slot_host[rows[inv[::-1]]] = slot_of_key[::-1]

    def bulk_assign_unique(self, keys: np.ndarray,
                           slot_of_key: np.ndarray
                           ) -> Tuple[np.ndarray, np.ndarray]:
        """Whole-PASS bulk row assignment (the resident-pass build's
        critical path): dedup the concatenated key stream outside
        ``host_lock`` (first-seen order — see dedup_first_seen), then
        ONE index round-trip under the lock instead of one per batch.
        Returns (rows of the first-seen uniques, inverse). Slot
        metadata records the key's PASS-level first-occurrence slot;
        the serial per-batch path nets out to the last batch's
        first occurrence instead — identical under the one-slot-per-key
        contract (CTR feasigns are slot-qualified,
        Dataset.pass_key_slots), which is the only input either path
        supports.

        Arena tables assign slotted so first-seen keys land in their
        slot's arena (same rationale as the per-batch dedup path:
        slotless assigns would poison the compact wire forever).

        ``FLAGS.use_pallas_index`` routes this through the device hash
        index (_bulk_assign_device): raw ids go to the chip, dedup and
        row assignment happen there, and the host kv is mirrored with
        ONLY the new keys — one O(new) append instead of the O(all)
        round trip. Any call the device route cannot serve exactly
        (probe/capacity overflow, kv divergence) falls back here,
        loudly, and books ``index.assign/host``."""
        keys = np.ascontiguousarray(keys, np.uint64)
        if FLAGS.use_pallas_index:
            dev = self._device_index()
            if not dev.degraded:
                out = self._bulk_assign_device(keys, slot_of_key, dev)
                if out is not None:
                    return out
            from paddlebox_tpu.ops.pallas_index import book_index_dispatch
            book_index_dispatch("assign", "host")
        uniq, first_idx, inv = dedup_first_seen(keys)
        slots_first = slot_of_key[first_idx]
        t1 = time.perf_counter()
        with self.host_lock:
            if getattr(self.index, "arena_enabled", False):
                rows, _ = self.index.assign_slotted(
                    uniq, slots_first.astype(np.uint16, copy=False))
            else:
                rows = self.index.assign(uniq)
            self.slot_host[rows] = slots_first.astype(np.int16,
                                                      copy=False)
        self.last_assign_seconds = {
            "index_host": time.perf_counter() - t1, "index_device": 0.0}
        return rows, inv

    # ---- device-resident key index (FLAGS.use_pallas_index) ----
    def _device_index(self):
        """Lazy DeviceKeyIndex for this table. On creation it seeds from
        the host kv (possible only while kv allocation is dense) and
        marks itself degraded — sticky, loud — when it can't mirror
        (arena-slotted allocation, free-list holes)."""
        dev = self._dev_index
        if dev is None:
            from paddlebox_tpu.ops.pallas_index import DeviceKeyIndex
            dev = DeviceKeyIndex(self.capacity)
            with self.host_lock:
                if getattr(self.index, "arena_enabled", False):
                    dev.degrade("arena-slotted row allocation has no "
                                "dense device mirror")
                elif not dev.seed_from_kv(self.index):
                    dev.degrade("host kv rows are not dense "
                                "(free-list holes) — cannot seed")
            self._dev_index = dev
        return dev

    def _reset_dev_index(self) -> None:
        """Drop the device index after a host-kv lifecycle mutation
        (load/merge/shrink/window eviction); the next flag-on bulk
        assign re-seeds from the kv, or degrades loudly if it can't."""
        self._dev_index = None

    def _bulk_assign_device(self, keys: np.ndarray,
                            slot_of_key: np.ndarray, dev
                            ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Device route of bulk_assign_unique: on-device first-seen
        dedup + hash insert, host kv mirrored with the NEW keys only.
        Returns None (after degrading ``dev``) whenever the result
        cannot be trusted bit-for-bit — the caller redoes the call on
        the host path, which is always authoritative."""
        from paddlebox_tpu.ops.pallas_index import book_index_dispatch
        t0 = time.perf_counter()
        pre_rows = dev.next_row
        out = dev.assign_raw(keys)
        t_dev = time.perf_counter() - t0
        if out is None:
            dev.degrade("probe/capacity overflow "
                        f"({len(keys)} keys at {pre_rows} rows, "
                        f"capacity {self.capacity})")
            return None
        uniq, first_idx, inv, rows_u, new_mask = out
        t1 = time.perf_counter()
        slots_first = slot_of_key[first_idx]
        with self.host_lock:
            if len(self.index) != pre_rows:
                dev.degrade(f"host kv diverged ({len(self.index)} keys "
                            f"vs {pre_rows} mirrored)")
                return None
            if new_mask.any():
                krows = self.index.assign(uniq[new_mask])
                if not np.array_equal(
                        krows, rows_u[new_mask].astype(np.int32)):
                    dev.degrade("host kv allocated different rows than "
                                "the device index (free-list holes)")
                    return None
            self.slot_host[rows_u] = slots_first.astype(np.int16,
                                                        copy=False)
        self.last_assign_seconds = {
            "index_host": time.perf_counter() - t1,
            "index_device": t_dev}
        book_index_dispatch("assign", "pallas")
        return (rows_u.astype(np.int32, copy=False),
                inv.astype(np.int64, copy=False))

    def prepare(self, batch: SlotBatch) -> PullIndex:
        valid = batch.keys[:batch.num_keys]
        with self.host_lock:
            rows, inv = self.index.assign_unique(valid)
            self._touched[rows] = True
            self.record_slots(
                rows, inv,
                (batch.segments[:batch.num_keys]
                 % batch.num_slots).astype(np.int16))
        return self._build_index(batch, rows, inv)

    def prepare_eval(self, batch: SlotBatch) -> PullIndex:
        """Read-only prepare: unknown keys map to the zero sentinel row
        instead of allocating (inference path — no index mutation)."""
        valid = batch.keys[:batch.num_keys]
        with self.host_lock:
            rows, inv = self.index.lookup_unique(valid, self.capacity)
        return self._build_index(batch, rows, inv)

    def next_rng(self) -> jax.Array:
        self._push_count += 1
        return jax.random.fold_in(self._rng, self._push_count)

    # ---- eager convenience (tests / small runs) ----
    def pull(self, idx: PullIndex) -> jax.Array:
        vals_u = pull_rows(self.state, jnp.asarray(idx.unique_rows))
        return expand_pull(vals_u, jnp.asarray(idx.gather_idx))

    def push(self, idx: PullIndex, key_grads: jax.Array,
             slot_of_key: Optional[jax.Array] = None) -> None:
        """Per-key-occurrence grads in → dedup-merge → optimizer apply.
        ``slot_of_key`` (per padded key) records the rows' slot ids into
        the host-side slot metadata (save files read slot from there)."""
        if slot_of_key is not None:
            sok = np.asarray(slot_of_key)
            kvm = np.asarray(idx.key_valid) > 0
            with self.host_lock:
                self.record_slots(idx.unique_rows, idx.gather_idx[kvm],
                                  sok[kvm].astype(np.int16))
        gi = jnp.asarray(idx.gather_idx)
        kv = jnp.asarray(idx.key_valid)
        # grad merge only (PushMergeCopy) — touched derives from the
        # dup-free _build_index contract inside apply_push, slot is host
        # metadata: no segment-stat scatters
        g = jax.ops.segment_sum(key_grads * kv[:, None], gi,
                                num_segments=idx.unique_rows.shape[0])
        self.state = apply_push(
            self.state, jnp.asarray(idx.unique_rows), g,
            self.cfg, self.next_rng())

    # ---- lifecycle: save / load / shrink (box_wrapper.cc:1383-1415) ----
    def _gather_host(self, rows: np.ndarray) -> Dict[str, np.ndarray]:
        """Per-field host dict (the save-file format stays field-named,
        independent of the device AoS layout). The slot field comes from
        host metadata — the device column is not maintained."""
        data = np.asarray(jax.device_get(self.state.data))
        sub = data[rows]
        mf_end = NUM_FIXED + self.mf_dim
        out = {f: (sub[:, NUM_FIXED:mf_end] if f == "embedx_w"
                   else field_slice(sub, f)) for f in FIELDS}
        out["slot"] = self.slot_host[rows].astype(np.float32)
        if self.opt_ext:
            out["opt_ext"] = sub[:, mf_end:]
        return out

    def save_base(self, path: str, clear_touched: bool = True) -> int:
        """Full model dump (day-level batch model). Returns rows saved.

        ``clear_touched=False`` = a MID-PASS snapshot (checkpoint resume
        cursor): the touched set is prepare-time bookkeeping, and with a
        prefetch pipeline running ahead a mid-pass clear would drop rows
        that are assigned but not yet pushed from every later delta.
        Only pass-boundary saves (pipeline drained) may clear."""
        with self.host_lock:
            keys, rows = self.index.items()
            if clear_touched:
                # clear only snapshotted rows under the lock (rows touched
                # by a concurrent preload keep their delta flag)
                self._touched[rows] = False
        data = self._gather_host(rows)
        np.savez_compressed(path, keys=keys, **data)
        return len(keys)

    def save_delta(self, path: str, clear_touched: bool = True) -> int:
        """Incremental dump of rows touched since last save ("xbox delta").

        With ``clear_touched=False`` (mid-pass cursor checkpoints) the
        flags survive, so successive in-pass deltas are CUMULATIVE over
        the pass — a superset each time, which keeps the chain correct
        while the prefetch pipeline's prepare-ahead makes any mid-pass
        flag clearing unsound (see save_base)."""
        with self.host_lock:
            keys, rows = self.index.items()
            mask = self._touched[rows]
            keys, rows = keys[mask], rows[mask]
            if clear_touched:
                self._touched[rows] = False
        data = self._gather_host(rows)
        np.savez_compressed(path, keys=keys, **data)
        return len(keys)

    def clear_touched_flags(self) -> None:
        """Post-commit half of a STAGED export (artifacts publish,
        BoxPSHelper.publish_*): a ``save_*(clear_touched=False)`` into
        the stage dir followed by this after the publish COMMITS is
        equivalent to the plain clearing save — but a publish failure
        in between loses no delta rows (the flags survive for the
        retry). Call only between passes."""
        with self.host_lock:
            self._touched[:] = False

    def rows_digest(self) -> str:
        """sha256 over the logical rows sorted by feasign — the
        read-only full-model fingerprint (row-assignment order cancels
        out; no touched flags change, so digesting is inert). The
        single-table sibling of ``HostStore.rows_digest`` /
        ``TieredShardedEmbeddingTable.rows_digest`` — serving gates
        compare served snapshots against it (scripts/serve_check.py)."""
        import hashlib
        with self.host_lock:
            keys, rows = self.index.items()
        order = np.argsort(keys)
        data = np.asarray(jax.device_get(self.state.data))
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(keys[order]).tobytes())
        h.update(np.ascontiguousarray(data[rows[order]]).tobytes())
        return h.hexdigest()

    def _assign_file_rows(self, keys: np.ndarray,
                          slots_b: np.ndarray) -> np.ndarray:
        """Assign rows for a save-file's keys — slotted when the arena is
        on and the file's slots fit, so the compact wire stays available
        after a restore. Caller holds host_lock."""
        if (getattr(self.index, "arena_enabled", False)
                and (0 <= slots_b).all()
                and (slots_b < (self.arena_slots or 0)).all()):
            rows, _ = self.index.assign_slotted(
                keys, slots_b.astype(np.uint16))
        else:
            rows = self.index.assign(keys)
        self.slot_host[rows] = slots_b
        return rows

    def _insert_file_rows(self, data: np.ndarray, rows: np.ndarray,
                          blob, sel=slice(None)) -> None:
        """Write a save-file's field blocks (all but slot, which is host
        metadata) into the logical data matrix at ``rows``; ``sel``
        restricts to a subset of the file's rows (merge_model)."""
        mf_end = NUM_FIXED + self.mf_dim
        for f in FIELDS:
            if f == "slot":
                continue
            if f == "embedx_w":
                data[rows, NUM_FIXED:mf_end] = blob[f][sel]
            else:
                field_assign(data, rows, f, blob[f][sel])
        if self.opt_ext:
            if "opt_ext" in blob \
                    and blob["opt_ext"].shape[1] == self.opt_ext:
                data[rows, mf_end:mf_end + self.opt_ext] = \
                    blob["opt_ext"][sel]
            else:
                log.warning("load: file has no matching opt_ext block; "
                            "optimizer state starts fresh for loaded "
                            "rows")

    def load(self, path: str, merge: bool = False) -> int:
        """Load a save_base/save_delta file; merge=True keeps existing rows
        (delta apply), else resets the table first. Sharded-format saves
        (ShardedEmbeddingTable/tiered, any shard count) load too — their
        per-shard blocks concatenate into one table (the serving consumer
        of a pod-trained model)."""
        blob = _flatten_sharded_blob(np.load(path))
        keys = blob["keys"]
        with self.host_lock:
            if not merge:
                self.index = HostKV(self.capacity)
                if self.arena_slots is not None:
                    self.index.arena_enable(self.arena_chunk_bits,
                                            self.arena_slots)
                self.state = init_table_state(self.capacity, self.mf_dim,
                                              ext=self.opt_ext)
                self._touched[:] = False
                self.slot_host[:] = 0
            rows = self._assign_file_rows(keys,
                                          blob["slot"].astype(np.int16))
            self._reset_dev_index()
        data = np.asarray(jax.device_get(self.state.data)).copy()
        self._insert_file_rows(data, rows, blob)
        self.state = TableState.from_logical(data, self.capacity,
                                             ext=self.opt_ext)
        return len(keys)

    def merge_model(self, path: str) -> int:
        """MergeModel (box_wrapper.h:801-803, bound at box_helper_py.cc):
        fold another saved model's rows into the LIVE table — unlike
        ``load(merge=True)``, which OVERWRITES rows from a delta file,
        this MERGES statistics:

        - keys present in both: show/clk/delta_score ACCUMULATE (the
          other model's traffic counts add to ours); embedding weights
          and optimizer state keep the live values (the live model is
          the training continuation);
        - unseen keys: inserted wholesale (all fields from the file).

        Returns the number of rows merged."""
        blob = _flatten_sharded_blob(np.load(path))
        keys = blob["keys"]
        if len(keys) == 0:
            return 0
        slots_b = blob["slot"].astype(np.int16)
        with self.host_lock:
            existing = self.index.lookup(keys) >= 0
            rows_new = self._assign_file_rows(keys[~existing],
                                              slots_b[~existing])
            rows_all = self.index.lookup(keys)
            data = np.asarray(jax.device_get(self.state.data)).copy()
            # new rows: full insert (shared with load)
            self._insert_file_rows(data, rows_new, blob, sel=~existing)
            # existing rows: statistics accumulate
            rows_old = rows_all[existing]
            for f in ("show", "clk", "delta_score"):
                data[rows_old, FIELD_COL[f]] += blob[f][existing]
            self.state = TableState.from_logical(data, self.capacity,
                                                 ext=self.opt_ext)
            self._touched[rows_all] = True
            self._reset_dev_index()
        log.info("merge_model: %d rows (%d new, %d stat-merged) from %s",
                 len(keys), len(rows_new), int(existing.sum()), path)
        return len(keys)

    def merge_models(self, paths, update_type: str = "stats") -> int:
        """MergeMultiModels (box_wrapper.h:812-815): fold several saved
        models into the live table in order. ``update_type`` mirrors the
        closed-core knob's observable surface: "stats" accumulates
        show/clk/delta_score for shared keys and keeps live weights
        (merge_model semantics per file); "overwrite" applies each file
        as a delta (load(merge=True) — later files win). Returns total
        rows merged."""
        if update_type not in ("stats", "overwrite"):
            raise ValueError(f"unknown update_type {update_type!r}")
        total = 0
        for p in paths:
            total += (self.merge_model(p) if update_type == "stats"
                      else self.load(p, merge=True))
        return total

    def shrink(self, delete_threshold: Optional[float] = None,
               decay: Optional[float] = None) -> int:
        """Age features: decay show/clk/delta_score, then drop rows whose
        decayed score falls below threshold (ShrinkTable semantics:
        box_wrapper.h:638, ctr_accessor shrink rules). Returns rows freed."""
        thr = (FLAGS.shrink_delete_threshold
               if delete_threshold is None else delete_threshold)
        dk = FLAGS.show_click_decay_rate if decay is None else decay
        fence = getattr(self, "fence", None)
        if callable(fence):
            # tables with an async end_pass epilogue (pass_table,
            # tiered) must drain in-flight write-backs first: aging on
            # pre-write-back counters would drop rows the draining job
            # is about to refresh (HostStore.shrink has the same
            # audit via _barrier)
            fence()
        with self.host_lock:
            keys, rows = self.index.items()
            if len(keys) == 0:
                return 0
            data = np.asarray(jax.device_get(self.state.data)).copy()
            data[:, 0:3] *= dk  # decay show/clk/delta_score
            show, clk = data[rows, 0], data[rows, 1]
            score = (self.cfg.nonclk_coeff * (show - clk)
                     + self.cfg.clk_coeff * clk)
            drop = score < thr
            drop_keys = keys[drop]
            freed_rows = self.index.release(drop_keys)
            data[freed_rows] = 0.0
            self.state = TableState.from_logical(data, self.capacity,
                                                 ext=self.opt_ext)
            self._touched[freed_rows] = False
            self.slot_host[freed_rows] = 0
            self._reset_dev_index()
        log.info("shrink: freed %d/%d rows", len(freed_rows), len(keys))
        return int(len(freed_rows))

    @property
    def feature_count(self) -> int:
        return len(self.index)

    def obs_stats(self) -> Dict[str, float]:
        """Occupancy gauges for pass events (obs/hub.emit_pass_event)."""
        used = len(self.index)
        return {"capacity": self.capacity, "used": used,
                "fill_frac": round(used / max(self.capacity, 1), 6)}
