from paddlebox_tpu.ps.sgd import SparseSGDConfig, SparseAdamConfig
from paddlebox_tpu.ps.multi_mf import MultiMfEmbeddingTable
from paddlebox_tpu.ps.table import (
    EmbeddingTable, TableState, PullIndex, pull_rows, expand_pull,
    apply_push, merge_push, push_stats, init_table_state,
)
from paddlebox_tpu.ps.host_store import HostStore
from paddlebox_tpu.ps.pass_table import PassScopedTable
from paddlebox_tpu.ps.box_helper import BoxPSHelper
from paddlebox_tpu.ps.extended import ExtendedEmbeddingTable
from paddlebox_tpu.ps.replica_cache import InputTable, ReplicaCache
from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
from paddlebox_tpu.ps.tiered import TieredShardedEmbeddingTable
from paddlebox_tpu.ps.tiered_multihost import MultihostTieredShardedTable
from paddlebox_tpu.ps.multi_mf_sharded import (MultiMfShardedTable,
                                               MultiMfTieredShardedTable)

__all__ = ["SparseSGDConfig", "SparseAdamConfig", "EmbeddingTable",
           "MultiMfEmbeddingTable",
           "TableState", "PullIndex", "pull_rows", "expand_pull",
           "apply_push", "merge_push", "push_stats", "init_table_state",
           "HostStore", "PassScopedTable", "BoxPSHelper",
           "ExtendedEmbeddingTable", "InputTable", "ReplicaCache",
           "ShardedEmbeddingTable", "TieredShardedEmbeddingTable",
           "MultihostTieredShardedTable",
           "MultiMfShardedTable", "MultiMfTieredShardedTable"]
