from paddlebox_tpu.ps.sgd import SparseSGDConfig, SparseAdamConfig
from paddlebox_tpu.ps.table import (
    EmbeddingTable, TableState, PullIndex, pull_rows, expand_pull,
    apply_push, merge_push, push_stats, init_table_state,
)

__all__ = ["SparseSGDConfig", "SparseAdamConfig", "EmbeddingTable",
           "TableState", "PullIndex", "pull_rows", "expand_pull",
           "apply_push", "merge_push", "push_stats", "init_table_state"]
