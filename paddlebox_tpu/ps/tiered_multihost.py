"""Tiered sharded PS on a MULTI-CONTROLLER mesh (the pod topology).

Reference: each AIBox node owns its slice of the PS — SSD + host memory
are per-node, coordinated over MPI (box_wrapper.h:446-450; SURVEY §2.6
multi-node rows). TPU-native mapping: ONE global mesh spans every
process (train/multihost.py); the stacked table state [N, L, 128] is
sharded over it, so shard s's HBM slice physically lives on the process
that owns device s. This table puts shard s's HOST TIER (HostStore) on
that same process:

- key→row INDEXES and ``_touched`` stay replicated on every process —
  the SPMD host contract (every process builds identical batches and
  routing plans) makes every assign/evict deterministic and identical,
  so the bookkeeping never needs communication.
- host VALUE stores exist only for owned shards. ``stage`` fetches only
  owned shards' missing keys (the ``_fetch_stage_values`` hook);
  ``begin_pass`` runs the shared reconcile/evict core for ALL shards
  (bookkeeping) but moves values only for owned shards — each process
  scatters ON DEVICE into its addressable slices and the new global
  state is reassembled with ``make_array_from_single_device_arrays``
  (no cross-process value motion, ever); ``end_pass`` writes back owned
  shards' touched rows via small on-device row gathers.
- save/load operate per process on the owned shards (the per-node
  SaveBase files of the reference); ``feature_count`` is per-process.

Every process must call stage/begin_pass/end_pass/drop_window
collectively (same keys, same order) — the same discipline as running
the jitted step itself.
"""

from __future__ import annotations

import os

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.table import (HostKV, TableState, pack_geometry,
                                    promote_window_delta)
from paddlebox_tpu.ps.tiered import TieredShardedEmbeddingTable
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class MultihostTieredShardedTable(TieredShardedEmbeddingTable):
    """TieredShardedEmbeddingTable whose host tiers are per-process."""

    def __init__(self, mesh: Mesh, mf_dim: int = 8,
                 capacity_per_shard: Optional[int] = None,
                 cfg: Optional[SparseSGDConfig] = None,
                 host_capacity: Optional[int] = None,
                 host_init_rows: int = 1 << 14,
                 req_bucket_min: int = 512,
                 serve_bucket_min: int = 1024) -> None:
        devs = list(mesh.devices.ravel())
        # set before super().__init__: _make_stacked_state needs the mesh
        self.mesh = mesh
        super().__init__(len(devs), mf_dim=mf_dim,
                         capacity_per_shard=capacity_per_shard, cfg=cfg,
                         host_capacity=host_capacity,
                         host_init_rows=host_init_rows,
                         req_bucket_min=req_bucket_min,
                         serve_bucket_min=serve_bucket_min)
        me = jax.process_index()
        self.owned = {s for s, d in enumerate(devs)
                      if d.process_index == me}
        # shard s's value store lives on the process owning device s
        self.hosts = [h if s in self.owned else None
                      for s, h in enumerate(self.hosts)]

    def _make_stacked_state(self, single: TableState, n: int) -> TableState:
        """Zero-init directly SHARDED over the global mesh — never
        materialize N windows on one device (at pod scale one window is
        sized near a device's HBM)."""
        from paddlebox_tpu.train.multihost import stage_global
        host = np.zeros((n,) + single.packed.shape,
                        np.asarray(single.packed).dtype)
        return single.with_packed(stage_global(self.mesh, host))

    # ---- local-shard plumbing ------------------------------------------
    @staticmethod
    def _shard_id(sh) -> int:
        idx = sh.index[0]
        return idx.start if isinstance(idx, slice) else int(idx)

    def _addressable(self) -> Dict[int, object]:
        return {self._shard_id(sh): sh
                for sh in self.state.packed.addressable_shards}

    def _gather_local_rows(self, s: int, rows: np.ndarray) -> np.ndarray:
        """On-device row gather on the owned shard's single-device
        array; only the requested rows cross to host."""
        data = self._addressable()[s].data        # [1, L, 128] on-device
        rpl, fp, nl = pack_geometry(self.capacity, self.state._feat)
        flat = data.reshape(nl * rpl, fp)
        out = flat[jnp.asarray(np.ascontiguousarray(rows, np.int32))]
        return np.asarray(jax.device_get(out))[:, :self.state._feat]

    def _reassemble(self, new_shards: Dict[int, jax.Array]) -> None:
        """Swap owned shards' device arrays into a new global array (no
        cross-process transfer; unchanged shards are reused as-is)."""
        packed = self.state.packed
        locals_ = []
        for sh in packed.addressable_shards:
            s = self._shard_id(sh)
            if s in new_shards:
                a = new_shards[s]
                if not isinstance(a, jax.Array) or a.ndim == 2:
                    a = jax.device_put(np.asarray(a)[None]
                                       if np.ndim(a) == 2 else np.asarray(a),
                                       sh.device)
                locals_.append(a)
            else:
                locals_.append(sh.data)
        out = jax.make_array_from_single_device_arrays(
            packed.shape, packed.sharding, locals_)
        self.state = self.state.with_packed(out)

    # ---- pass lifecycle (collective) -----------------------------------
    def _fetch_stage_values(self, s: int, new_keys: np.ndarray):
        return (self.hosts[s].fetch(new_keys)
                if s in self.owned else None)

    def begin_pass(self, pass_keys: Optional[np.ndarray] = None) -> int:
        st = self._resolve_stage(pass_keys)
        stats = dict(resident=0, staged=0, evicted=0, evicted_writeback=0,
                     written_back=0)
        total = 0
        new_shards: Dict[int, jax.Array] = {}
        rpl, fp, nl = pack_geometry(self.capacity, self.state._feat)
        feat = self.state._feat
        try:
            with self.host_lock:
                self._open_keys = st.keys
                addr = self._addressable()
                for s in range(self.n):
                    owned = s in self.owned

                    def gather(rows, s=s, owned=owned):
                        return (self._gather_local_rows(s, rows)
                                if owned else None)

                    def writeback(ks, rs, sub, s=s, owned=owned):
                        if owned:
                            self.hosts[s].update(
                                ks, self._store_fields(sub))

                    rows_new, still, st_s = promote_window_delta(
                        self.indexes[s], self._touched[s], self.capacity,
                        st.keys[s], st.new_keys[s],
                        gather_rows=gather, writeback=writeback,
                        pending=self._pending_of(s),
                        protect=self._queued_protect(s))
                    # pending keys promoted by THIS pass leave the
                    # pending set (same bookkeeping as the single-
                    # controller table; identical on every process per
                    # the SPMD host contract)
                    self._unpin_pending(s, st.keys[s])
                    for k in st_s:
                        stats[k] = stats.get(k, 0) + st_s[k]
                    total += len(st.keys[s])
                    if owned and len(rows_new):
                        vals = self._logical_rows(
                            {f: v[still]
                             for f, v in st.values[s].items()})
                        data = addr[s].data       # [1, L, 128] on-device
                        flat = data.reshape(nl * rpl, fp)
                        flat = flat.at[
                            jnp.asarray(np.ascontiguousarray(rows_new,
                                                             np.int32)),
                            :feat].set(jnp.asarray(vals))
                        new_shards[s] = flat.reshape(data.shape)
                if new_shards:
                    self._reassemble(new_shards)
                ev_sec, ev_rows = (self._evict_async_sec,
                                   self._evict_async_rows)
        except BaseException:
            # the base class's restore contract (PassPipeline relies on
            # it): a begin that fails after consuming a queued stage
            # puts the stage back at the head and drops the open pin —
            # drain/discard can still release every plan-pending pin
            with self.host_lock:
                if getattr(st, "from_queue", False):
                    self._stage_q.appendleft(st)
                self._open_keys = [np.empty(0, np.uint64)
                                   for _ in range(self.n)]
            raise
        self.in_pass = True
        # the single-controller table's eviction attribution keys, so
        # telemetry consumers (BEGIN_STALL_COLS) see one schema: inline
        # promote eviction is the emergency path here too, and the
        # ahead-of-time eviction (inline in this class's end_pass, but
        # the same accounting) diffs off the cumulative marks
        stats["stage_wait_sec"] = round(
            getattr(self, "_last_stage_wait_sec", 0.0), 6)
        stats["evict_emergency_sec"] = round(
            stats.pop("evict_sec", 0.0), 6)
        mark_sec, mark_rows = self._evict_async_mark
        self._evict_async_mark = (ev_sec, ev_rows)
        stats["evict_async_sec"] = round(ev_sec - mark_sec, 6)
        stats["evict_async_rows"] = int(ev_rows - mark_rows)
        self.last_pass_stats = stats
        log.info("begin_pass (mh, %d owned shards): %d rows (%d resident "
                 "%d staged %d evicted)", len(self.owned), total,
                 stats["resident"], stats["staged"], stats["evicted"])
        return total

    def end_pass(self) -> int:
        # SYNCHRONOUS on purpose: the pass lifecycle is collective here
        # (every process must agree the write-back landed before any
        # process's next collective op), so the single-controller async
        # epilogue does not apply; fence() is inherited and trivially
        # idle. The owned-shard gathers are already small on-device row
        # gathers, not window-sized pulls.
        if not self.in_pass:
            raise RuntimeError("end_pass without begin_pass")
        total = 0
        with self.host_lock:
            for s in range(self.n):
                keys, rows = self.indexes[s].items()
                m = self._touched[s][rows]
                keys, rows = keys[m], rows[m]
                if s in self.owned and len(rows):
                    sub = self._gather_local_rows(s, rows)
                    self.hosts[s].update(keys, self._store_fields(sub))
                self._touched[s][rows] = False
                # written-back pending keys: host value authoritative
                # again (see TieredShardedEmbeddingTable.end_pass)
                self._unpin_pending(s, keys)
                total += len(rows)
            self._open_keys = [np.empty(0, np.uint64)
                               for _ in range(self.n)]
        self.in_pass = False
        self.last_pass_stats["written_back"] = total
        # async capacity eviction, INLINE here (end_pass is collective
        # and synchronous on the pod): the index/_touched bookkeeping
        # is replicated and the selection deterministic, so every
        # process frees the identical rows for the next queued pass
        self._evict_ahead()
        # per-node SSD tier: watermark demotion after the (synchronous)
        # write-back — owned shards only; host-local bookkeeping, so no
        # collective coordination is needed (each AIBox node manages its
        # own SSD, box_wrapper.h:446-450)
        self._demote_after_writeback()
        return total

    def drop_window(self) -> None:
        self._no_pass("drop_window")
        try:
            if self._stage_thread is not None or self._stage is not None:
                self.wait_stage_done()
        finally:
            self._stage = None
            with self.host_lock:
                self._stage_q.clear()
                self._stage_gen += 1
                self._open_keys = [np.empty(0, np.uint64)
                                   for _ in range(self.n)]
                self.indexes = [HostKV(self.capacity)
                                for _ in range(self.n)]
                self._touched[:] = False
                self._pending = [np.empty(0, np.uint64)
                                 for _ in range(self.n)]
                self._pending_chunks = [[] for _ in range(self.n)]
                zeros = {
                    self._shard_id(sh): jax.device_put(
                        np.zeros(sh.data.shape, sh.data.dtype), sh.device)
                    for sh in self.state.packed.addressable_shards}
                self._reassemble(zeros)

    # ---- per-process model lifecycle (owned shards only) ---------------
    def feature_count(self) -> int:
        """Rows in THIS process's host tiers (per-node count, as each
        AIBox node reports its own shard)."""
        return sum(len(h) for h in self.hosts if h is not None)

    def save_base(self, path: str) -> int:
        """Owned shards only → a per-process file (the per-node SaveBase
        convention); restore each process from its own file."""
        self._no_pass("save_base")
        blobs: Dict[str, np.ndarray] = {}
        total = 0
        for s in sorted(self.owned):
            keys, fields = self.hosts[s].export_rows()
            blobs[f"keys_{s}"] = keys
            for f, v in fields.items():
                blobs[f"{f}_{s}"] = v
            total += len(keys)
        np.savez_compressed(path, n=self.n,
                            owned=np.array(sorted(self.owned)), **blobs)
        return total

    def save_delta(self, path: str) -> int:
        self._no_pass("save_delta")
        blobs: Dict[str, np.ndarray] = {}
        total = 0
        for s in sorted(self.owned):
            keys, fields = self.hosts[s].export_rows(delta=True)
            blobs[f"keys_{s}"] = keys
            for f, v in fields.items():
                blobs[f"{f}_{s}"] = v
            total += len(keys)
        np.savez_compressed(path, n=self.n,
                            owned=np.array(sorted(self.owned)), **blobs)
        return total

    def load(self, path: str, merge: bool = False) -> int:
        self._no_pass("load")
        blob = np.load(path)
        if "n" not in blob or int(blob["n"]) != self.n:
            # a shard-count mismatch would need key%N re-splitting across
            # PROCESSES (keys_0..3 imported here may route to shards this
            # process does not own) — refuse rather than silently skip
            raise ValueError(
                f"per-process load needs a save written by an {self.n}-"
                f"shard multihost table (got n="
                f"{blob.get('n', 'missing')}); use the single-controller "
                "table to re-shard a foreign save")
        total = 0
        for s in sorted(self.owned):
            if f"keys_{s}" not in blob:
                continue
            want = list(self.hosts[s].fields)
            fields = {f: blob[f"{f}_{s}"] for f in want
                      if f"{f}_{s}" in blob}
            total += self.hosts[s].import_rows(blob[f"keys_{s}"], fields,
                                               merge=merge)
        self.drop_window()
        return total

    def load_reshard(self, paths, merge: bool = False) -> int:
        """Re-import saves written at ANY shard count — the elastic
        re-shard path (docs/RESILIENCE.md §Elastic membership). Unlike
        ``load`` (which refuses foreign counts), every process reads
        EVERY file of one logical save epoch (``paths`` = the full
        per-process file set, or one single-controller/base file),
        re-splits all keys by ``key % n`` via ``_file_per_shard``, and
        imports only the rows routed to its OWNED shards — so a
        6-process world can adopt an 8-process save without a
        single-controller intermediary. Call in lockstep across the new
        world, outside a pass window."""
        self._no_pass("load_reshard")
        if isinstance(paths, (str, os.PathLike)):
            paths = [paths]
        total = 0
        fresh: set = set()
        for path in paths:
            blob = np.load(path)
            for s, (keys, fields) in enumerate(self._file_per_shard(blob)):
                if s not in self.owned or not len(keys):
                    continue
                want = set(self.hosts[s].fields)
                use = {f: v for f, v in fields.items() if f in want}
                # first import into a shard resets it (load semantics);
                # rows from the remaining files merge on top
                first = not merge and s not in fresh
                fresh.add(s)
                total += self.hosts[s].import_rows(keys, use,
                                                   merge=not first)
        if not merge:
            # owned shards no file routed keys to must still reset —
            # load(merge=False) semantics are "the file set IS the model"
            for s in sorted(self.owned - fresh):
                self.hosts[s].import_rows(
                    np.empty(0, np.uint64), {}, merge=False)
        self.drop_window()
        return total

    def shrink(self, delete_threshold: Optional[float] = None,
               decay: Optional[float] = None) -> int:
        self._no_pass("shrink")
        freed = sum(
            self.hosts[s].shrink(delete_threshold=delete_threshold,
                                 decay=decay,
                                 nonclk_coeff=self.cfg.nonclk_coeff,
                                 clk_coeff=self.cfg.clk_coeff)
            for s in sorted(self.owned))
        self.drop_window()
        return freed

    def spill_cold(self, path_prefix: str, threshold: float) -> int:
        self._no_pass("spill_cold")
        return sum(
            self.hosts[s].spill_cold(
                f"{path_prefix}.s{s}.npz", threshold,
                nonclk_coeff=self.cfg.nonclk_coeff,
                clk_coeff=self.cfg.clk_coeff)
            for s in sorted(self.owned))

    def merge_model(self, path: str) -> int:
        self._no_pass("merge_model")
        blob = np.load(path)
        total = 0
        for s, (keys, fields) in enumerate(self._file_per_shard(blob)):
            if s in self.owned:
                total += self.hosts[s].merge_model_rows(keys, fields)
        self.drop_window()
        return total
