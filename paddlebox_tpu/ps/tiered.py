"""Tiered sharded PS: HostStore-backed PERSISTENT pass windows per HBM shard.

The reference's core capability — a table BIGGER than device memory on a
multi-device PS: per pass, ``BuildPull`` fetches the pass's values from
the CPU store (ps_gpu_wrapper.cc:337), ``BuildGPUTask`` fills the per-GPU
HBM pools (:684), training hits only the resident working set, and
``EndPass`` dumps updated values back to the CPU store (:983); the SSD
tier promotes via ``LoadSSD2Mem`` (box_wrapper.cc:1415).

TPU-native composition: ``ShardedEmbeddingTable`` keeps its whole routing
machinery (key%N owner shards, two all_to_alls in the jit step) but its
per-shard HBM slice becomes a PASS WINDOW — each shard fronted by a
``HostStore`` (host RAM + disk spill) holding the full model. The pass
lifecycle mirrors ``PassScopedTable``:

    table.stage(ds.pass_keys())     # BuildPull: host fetch per shard
    table.begin_pass()              # BuildGPUTask: scatter → HBM shards
    trainer.adopt_table()
    ...train (streaming or resident)...
    trainer.sync_table(); table.end_pass()   # EndPass: HBM → host

INCREMENTAL windows (the reference's pass machinery is incremental by
construction — BeginFeedPass schedules only SSD→mem *misses* and the HBM
table persists across BeginPass/EndPass windows, box_wrapper.cc:129-186):
rows stay RESIDENT in the HBM shards across passes. ``stage`` fetches
host values only for keys NOT already in the window; ``begin_pass``
reconciles (drops fetched values for keys that became resident
meanwhile), evicts only what capacity demands (write-back of touched
evictees), and device-scatters just the delta; ``end_pass`` gathers and
writes back only rows touched since the last write-back. Host↔HBM wire
per pass is therefore proportional to the working-set DELTA, not its
size.

ASYNC EPILOGUE (ps/epilogue.py; docs/PERFORMANCE.md): ``end_pass``
snapshots the touched-row indices, DISPATCHES the D2H gathers against
the then-current (immutable) device buffers, clears the flags, and
returns — the blocking pull + HostStore write-back drain on a single
serialized background worker, overlapping pass N+1's begin/train.
``fence()`` orders every consumer: all HostStore read entry points
drain the epilogue first (HostStore.read_barrier), ``begin_pass``
fences before capacity-pressure eviction (write-back/write-back
ordering), and checkpoint capture / save / shrink / merge_model /
load / drop_window fence too, so the old bit-for-bit delta==full
semantics hold unchanged (scripts/pipeline_check.py gates this). A
write-back failure surfaces at the next fence as
``EndPassWritebackError`` — never as silent row loss. Overlapping
``begin_pass`` reconciles against in-flight write-backs by
construction: its staged values were fetched for keys OUTSIDE the open
window (the write-back set is resident-only), and any fetch that could
observe a stale host row happens behind the read barrier.

OVERLAPPED staging (pre_build_thread, ps_gpu_wrapper.cc:913): ``stage``
is legal while a pass is OPEN. Keys missing from the window are by
definition outside the open pass's write-back set, so fetching them
during training cannot race ``end_pass``; a key that does enter the
window mid-pass (streaming assigns outside the staged set) is caught by
the begin_pass reconcile, which drops its fetched value in favor of the
fresher resident row.

Contract (same as the reference's pass windows): the staged key set must
cover every key the pass's batches touch — keys outside it allocate fresh
zero rows in the window. ``ds.pass_keys()`` provides exactly that set.
Host-tier mutations outside the pass protocol (load/merge_model/shrink)
invalidate residency — the next begin_pass re-fetches everything.

OVERLAPPED PLAN BUILD (preload_into_memory, box_wrapper.h:1142-1156 —
the reference overlaps the ENTIRE next-pass feed with training):
``PassPreloader(build_fn=trainer.build_resident_pass)`` is legal over a
tiered table. The trainer brackets plan builds in ``plan_scope()``:
keys newly assigned by a future pass's routing plan are recorded
PENDING (value-less zero rows, pinned against eviction, not marked
touched); ``stage`` treats them as missing so their host values still
fetch, and ``begin_pass``'s reconcile scatters the staged values into
the plan-baked rows instead of keeping the zeros. The begin_pass
boundary is then reconcile-only — plan construction, host fetch AND
upload all ride the previous pass's training. Capacity contract: the
window must hold the UNION of the open pass's and the planned pass's
working sets (pending rows are pinned; promotion raises when eviction
cannot free enough). With a DEPTH-N preloader (train/device_pass,
FLAGS.preload_depth) several future passes' plans can be pending at
once — plan builds stay serialized in pass order on the preloader
worker, each bracketed in its own ``plan_scope``, and keys recorded by
a later pass's plan stay pinned until THAT pass's begin_pass; the
capacity union extends over every queued pass accordingly.

QUEUED STAGES + ASYNC CAPACITY EVICTION (the tiered pass pipeline,
ISSUE 9 — train/device_pass.PassPipeline): ``stage(..., queue=True)``
runs the host fetch on the CALLING thread (the preloader worker) and
appends the result to a stage QUEUE consumed in pass order by
``begin_pass`` — with depth N several future passes' stages sit queued
at once, so the whole begin boundary (plan build, dedup/pack, H2D
wire, host fetch, SSD promote) rides the persistent worker and the
boundary itself is reconcile-only. Eviction moves off that boundary
too: right after each end_pass write-back lands on the epilogue lane
(the same slot as watermark demotion), ``_evict_ahead`` frees the rows
the NEXT queued stage will need — candidates are CLEAN by construction
(the write-back that just landed cleared their touched bits, so the
host tier already holds their values and eviction is index release +
accounting, no D2H). Never evicted: the open pass's working set, any
queued stage's working set, and plan-pending rows (the capacity-union
contract above). Rows dirtied after the end_pass snapshot are skipped
and fall to the EMERGENCY inline path in begin_pass (the pre-pipeline
eviction, with its fence + dirty write-back), reported separately as
``evict_emergency_sec`` vs ``evict_async_sec`` in the bench's
``begin_stall_breakdown``. ``FLAGS.async_capacity_evict=False``
restores fully-inline eviction.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.ps.epilogue import PassEpilogue, fence_under_pressure
from paddlebox_tpu.ps.host_store import HostStore
from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.table import (HostKV, dispatch_packed_row_gather,
                                    promote_window_delta,
                                    rows_from_store_fields,
                                    scatter_logical_rows,
                                    start_scatter_warmup,
                                    store_fields_from_rows)
from paddlebox_tpu.obs import trace
from paddlebox_tpu.resilience import faults
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class _ShardStage:
    def __init__(self, keys: List[np.ndarray], new_keys: List[np.ndarray],
                 values: List[Dict[str, np.ndarray]]) -> None:
        self.keys = keys          # per shard: FULL working set (sorted)
        self.new_keys = new_keys  # per shard: keys missing at stage time
        self.values = values      # per shard: host values for new_keys


class TieredShardedEmbeddingTable(ShardedEmbeddingTable):
    """ShardedEmbeddingTable whose HBM shards hold a persistent window of
    the working set; the full model lives in N per-shard HostStores
    (+ disk spill)."""

    # stage() is legal while a pass is open (missing keys are outside
    # the open window's write-back set) — BoxPSHelper.stage_pass gates
    # on this; PassScopedTable carries the same contract single-chip
    supports_overlap_stage = True

    def __init__(self, num_shards: int, mf_dim: int = 8,
                 capacity_per_shard: Optional[int] = None,
                 cfg: Optional[SparseSGDConfig] = None,
                 host_capacity: Optional[int] = None,
                 host_init_rows: int = 1 << 14,
                 req_bucket_min: int = 512,
                 serve_bucket_min: int = 1024,
                 ssd_dir: Optional[str] = None) -> None:
        super().__init__(num_shards, mf_dim=mf_dim,
                         capacity_per_shard=capacity_per_shard, cfg=cfg,
                         req_bucket_min=req_bucket_min,
                         serve_bucket_min=serve_bucket_min)
        # SSD third tier (ps/ssd.py): an explicit ssd_dir pins each
        # shard's tier under <dir>/s<K>; otherwise HostStore follows
        # FLAGS.ssd_dir (auto subdirs) or stays two-tier
        self.hosts = [HostStore(mf_dim, capacity=host_capacity,
                                init_rows=host_init_rows,
                                opt_ext=self.opt_ext,
                                ssd_dir=(f"{ssd_dir}/s{s}" if ssd_dir
                                         else None))
                      for s in range(self.n)]
        self.in_pass = False
        self._stage: Optional[_ShardStage] = None
        self._stage_thread: Optional[threading.Thread] = None
        self._stage_exc: Optional[BaseException] = None
        # QUEUED feed-pass stages (the depth-N pass pipeline,
        # train/device_pass.PassPipeline): stage(queue=True) appends,
        # begin_pass consumes in pass order. Guarded by host_lock.
        self._stage_q: "collections.deque[_ShardStage]" = \
            collections.deque()
        # generation counter: discard_queued_stages / drop_window bump
        # it, so an in-flight queued fetch that straddled the discard
        # cannot append a zombie stage afterwards (its raise rolls the
        # build's plan pins back through the PassPipeline bracket)
        self._stage_gen = 0
        # the IN-FLIGHT queued stage's per-shard keys: its missing
        # split is computed before the (lock-free) host fetch, so the
        # whole working set must be pinned against eviction from that
        # moment — a key it classified as resident and then lost to
        # _evict_ahead (or an emergency promote) would never be
        # re-inserted at its begin_pass. Set/cleared under host_lock.
        self._staging_keys: Optional[List[np.ndarray]] = None
        # the last consumed (≈ open) pass's per-shard working set —
        # pinned against the lane's _evict_ahead; set at stage-queue
        # pop / begin_pass, cleared at end_pass (all under host_lock)
        self._open_keys: List[np.ndarray] = [np.empty(0, np.uint64)
                                             for _ in range(self.n)]
        # async capacity-eviction accounting (cumulative; the lane
        # updates under host_lock, begin_pass diffs per pass)
        self._evict_async_sec = 0.0
        self._evict_async_rows = 0
        self._evict_async_mark = (0.0, 0)
        # async pass epilogue (ps/epilogue): end_pass hands the D2H pull
        # + host write-back to this worker; every HostStore read entry
        # point drains it first (read_barrier), so no consumer observes
        # a partially written-back pass
        self._epilogue = PassEpilogue(name="tiered-endpass")
        for h in self.hosts:
            if h is not None:
                h.read_barrier = self._epilogue.fence
        # keys assigned by a future pass's plan build (plan_scope)
        # whose values haven't been promoted yet: a consolidated sorted
        # array per shard + O(1)-append chunk lists merged lazily by
        # _pending_of (the hot plan-assign path no longer rebuilds the
        # sorted array under host_lock per call — ADVICE r5)
        self._pending: List[np.ndarray] = [np.empty(0, np.uint64)
                                           for _ in range(self.n)]
        self._pending_chunks: List[List[np.ndarray]] = [
            [] for _ in range(self.n)]
        # per-pass delta accounting (asserted by tests, reported by
        # bench): resident = working-set keys already in the window,
        # staged = keys fetched+scattered, evicted / evicted_writeback,
        # written_back = rows end_pass shipped to the host tier
        self.last_pass_stats: Dict[str, int] = {}
        start_scatter_warmup(self.state, sharded=True)

    def obs_stats(self) -> Dict[str, float]:
        out = super().obs_stats()
        # rows a future pass's plan build assigned before their values
        # staged — they pin window capacity until begin_pass promotes
        with self.host_lock:
            out["pending"] = int(sum(len(self._pending_of(s))
                                     for s in range(self.n)))
        return out

    # ---- async epilogue fence ----------------------------------------
    def fence(self) -> None:
        """Drain the asynchronous end_pass write-back and surface the
        first failure. Called implicitly by every HostStore read entry
        point (read_barrier), by lifecycle ops, and by checkpoint
        capture; callers that white-box the host tiers directly should
        fence first."""
        self._epilogue.fence()

    def endpass_stats(self) -> Dict[str, float]:
        """Cumulative epilogue accounting (obs/hub pass events, bench)."""
        return self._epilogue.stats()

    # ---- SSD third tier (ps/ssd.py; docs/STORAGE.md) -----------------
    def ssd_stats(self) -> Dict[str, float]:
        """Summed disk-tier accounting across shards (bench / obs);
        empty when no shard has a tier."""
        out: Dict[str, float] = {}
        for h in self.hosts:
            if h is None or h.ssd is None:
                continue
            for k, v in h.ssd.stats().items():
                out[k] = out.get(k, 0.0) + v
        return out

    def spill_manifest(self) -> Optional[dict]:
        """Merged spill manifest over every shard's tier (checkpoint
        integration — train/checkpoint.py records it in the ckpt dir
        and verifies segment digests on restore); None when no tier
        holds rows. Fences first: an in-flight end_pass write-back may
        still trigger a demotion that belongs in this manifest."""
        self.fence()
        shards = {}
        for s, h in enumerate(self.hosts):
            if h is None:
                continue
            m = h.spill_manifest()
            if m is not None:
                shards[str(s)] = m
        if not shards:
            return None
        import hashlib
        # merged reference digest: fold the per-shard tier digests in
        # shard order — the one name an artifact manifest records for
        # this whole table's spill state (artifacts.py refs block)
        h = hashlib.sha256()
        for s in sorted(shards, key=int):
            h.update(f"{s}:{shards[s].get('digest', '')}".encode())
        return {"version": 1, "shards": shards,
                "live_rows": sum(m["live_rows"] for m in shards.values()),
                "digest": h.hexdigest()}

    def rows_digest(self) -> str:
        """Full-model fingerprint: the shard host stores' read-only
        ``rows_digest`` folded in shard order (fences first so every
        in-flight write-back is included). Publish gates compare a
        consumer's adopted state against this."""
        import hashlib
        self.fence()
        h = hashlib.sha256()
        for s, host in enumerate(self.hosts):
            if host is None:
                continue
            h.update(f"{s}:{host.rows_digest()}".encode())
        return h.hexdigest()

    def has_spilled_rows(self) -> bool:
        """Cheap guard for the preloader's promote prefetch: True when
        any shard's tier holds live rows."""
        return any(h is not None and h.ssd is not None and len(h.ssd)
                   for h in self.hosts)

    def prefetch_promote(self, pass_keys: np.ndarray) -> int:
        """LoadSSD2Mem prefetch for a FUTURE pass, run from the depth-N
        ``PassPreloader`` build stage (train/sharded.build_resident_pass):
        promote the pass keys' spilled rows SSD→host-RAM on the
        preloader worker, overlapping the open pass's training — the
        later ``stage`` fetch then hits RAM instead of stalling
        ``begin_pass`` on segment reads (the measured 26 s
        ``begin_stall_shrink`` path). Rows land in the HOST tier only;
        window promotion stays with begin_pass's reconcile."""
        total = 0
        for s, ks in enumerate(self._split_by_owner(pass_keys)):
            h = self.hosts[s]
            if h is None or h.ssd is None or not len(h.ssd) \
                    or not len(ks):
                continue
            h._barrier()  # order behind in-flight write-backs
            with h._lock:
                missing = h.index.lookup(ks) < 0
            if missing.any():
                total += h._promote(ks[missing])
        if total:
            log.info("prefetch_promote: %d spilled rows -> host RAM "
                     "(overlapped)", total)
        return total

    # ---- async capacity eviction (ISSUE 9; epilogue-lane slot) -------
    def pin_working_set(self, pass_keys: np.ndarray) -> None:
        """Pin a FUTURE pass's working set against eviction BEFORE its
        plan build starts (PassPipeline does this around build+stage):
        the build bakes row ids for RESIDENT keys too — not just the
        plan-pending new ones — so an eviction between the plan's row
        lookup and the stage() pin would leave the staged wire
        addressing a stale (possibly reassigned) row. The pin is the
        same ``_staging_keys`` slot the queued stage fetch uses;
        ``stage(queue=True)`` for the same keys keeps it, and its
        completion (or ``unpin_working_set`` on a failed build)
        releases it — from then on the queued stage itself carries the
        pin."""
        per_shard = self._split_by_owner(pass_keys)
        with self.host_lock:
            if self._staging_keys is not None:
                raise RuntimeError(
                    "a working set is already pinned — pipeline builds "
                    "serialize on one worker")
            self._staging_keys = per_shard

    def unpin_working_set(self) -> None:
        """Release a ``pin_working_set`` pin (idempotent) — the failed-
        build path; a completed ``stage(queue=True)`` releases it
        itself."""
        with self.host_lock:
            self._staging_keys = None

    def _queued_protect(self, s: int) -> Optional[np.ndarray]:
        """Shard s's eviction-pinned keys beyond the current want set
        (caller holds host_lock): the union of every QUEUED stage's
        working set plus the IN-FLIGHT stage's (_staging_keys) —
        evicting one would invalidate the missing-split its stage
        already computed (the capacity contract is the union over
        open + queued passes). THE single source of the queued-pin
        rule — _evict_ahead and the inline promote both use it."""
        arrs = [q.keys[s] for q in self._stage_q if len(q.keys[s])]
        if self._staging_keys is not None \
                and len(self._staging_keys[s]):
            arrs.append(self._staging_keys[s])
        if not arrs:
            return None
        return arrs[0] if len(arrs) == 1 else \
            np.unique(np.concatenate(arrs))

    def _evict_ahead(self) -> int:
        """Capacity-pressure eviction for the NEXT queued pass, run ON
        the epilogue lane right after an end_pass write-back lands (the
        watermark-demotion slot — strictly ordered after the
        write-back). Every candidate's latest value is already in the
        host tier (the write-back that just landed cleared its touched
        bit), so eviction here is index release + accounting — no D2H
        gather, no host write rides the lane. Clean rows only; anything
        dirtied since the snapshot keeps its row and falls to the
        emergency inline path. Pinned (never evicted): the open pass's
        working set (``_open_keys``), every queued stage's working set,
        and plan-pending rows. No-op without queued stages or with
        ``FLAGS.async_capacity_evict=False``."""
        if not FLAGS.async_capacity_evict:
            return 0
        freed_total = 0
        with self.host_lock:
            # timer starts INSIDE the lock: lane lock-wait behind a
            # main-thread promote is not eviction work
            t0 = time.perf_counter()
            if not self._stage_q:
                return 0
            head = self._stage_q[0]
            for s in range(self.n):
                # rows the head stage will allocate at its begin_pass:
                # its still-missing keys (pending keys own rows already)
                need = int((self.indexes[s].lookup(head.new_keys[s])
                            < 0).sum())
                overflow = len(self.indexes[s]) + need - self.capacity
                if overflow <= 0:
                    continue
                live_keys, live_rows = self.indexes[s].items()
                cand = ~self._touched[s][live_rows]   # clean rows only
                # pins: every queued + in-flight stage's working set
                # (_queued_protect — the shared rule, head included),
                # the open pass, and plan-pending rows
                pin = [self._open_keys[s]]
                qp = self._queued_protect(s)
                if qp is not None:
                    pin.append(qp)
                pend = self._pending_of(s)
                if len(pend):
                    pin.append(pend)
                pin = [p for p in pin if len(p)]
                if pin:
                    cand &= ~np.isin(live_keys, np.concatenate(pin))
                ck = live_keys[cand][:overflow]
                if not len(ck):
                    continue
                freed = self.indexes[s].release(ck)
                self._touched[s][freed] = False
                freed_total += len(ck)
            if freed_total:
                self._reset_dev_indexes()
            self._evict_async_rows += freed_total
            self._evict_async_sec += time.perf_counter() - t0
        if freed_total:
            from paddlebox_tpu.obs.hub import get_hub
            hub = get_hub()
            if hub.active:
                hub.counter(
                    "pbox_table_evict_async_rows_total",
                    "rows evicted on the epilogue lane ahead of the "
                    "next queued pass").inc(freed_total)
            log.info("evict_ahead: %d clean rows released on the "
                     "epilogue lane for the next queued pass",
                     freed_total)
        return freed_total

    def discard_queued_stages(self) -> int:
        """Drop every queued feed-pass stage (pipeline shutdown — e.g.
        PassPipeline.drain when queued passes will never begin).
        Releases the plan-pending rows those stages' builds assigned
        (the _rollback_plan rule: untrained rows only — a row whose
        updates await write-back follows the normal resident rules) so
        abandoned stages never pin window capacity. Returns the number
        of stages discarded."""
        with self.host_lock:
            n = len(self._stage_q)
            for q in self._stage_q:
                for s in range(self.n):
                    pend = self._pending_of(s)
                    if not len(pend):
                        continue
                    ks = q.keys[s][np.isin(q.keys[s], pend)]
                    if not len(ks):
                        continue
                    rows = self.indexes[s].lookup(ks)
                    ok = rows >= 0
                    ks_ok, rows_ok = ks[ok], rows[ok]
                    untouched = ~self._touched[s][rows_ok]
                    if untouched.any():
                        self.indexes[s].release(ks_ok[untouched])
                        self._reset_dev_indexes()
                    self._unpin_pending(s, ks)
            self._stage_q.clear()
            self._stage_gen += 1   # reject straddling in-flight fetches
        return n

    def _demote_after_writeback(self) -> None:
        """Watermark demotion + compaction, run ON the epilogue lane
        right after an end_pass write-back lands (so demote IO never
        blocks host_lock and is strictly ordered AFTER the write-back —
        rows the pass just touched are marked and never selected).
        barrier=False: fencing from the single-lane worker itself would
        deadlock. Renders on the ``ssd.compact`` trace lane: the work
        rides the epilogue worker but is logically the SSD maintenance
        service, so it gets its own row in the pass trace."""
        tiers = [h for h in self.hosts
                 if h is not None and h.ssd is not None]
        if not tiers:
            return
        with trace.lane_scope(trace.LANE_SSD), \
                trace.span("ssd.maintain"):
            for h in tiers:
                h.demote_to_watermark(barrier=False)
                h.ssd.maybe_compact()

    # ---- overlapped plan builds (preload_into_memory) ----------------
    @contextlib.contextmanager
    def plan_scope(self):
        """Bracket a FUTURE pass's routing-plan build (the preloader's
        background thread): new-key assigns by THIS thread inside the
        scope become PENDING zero rows that the next begin_pass
        reconciles with their staged values (see module docstring).
        A build that RAISES rolls its pending records back — its pass
        will never open, and leaked pendings would pin window capacity
        forever (eviction excludes pending rows)."""
        tls = self._plan_tls
        tls.depth = getattr(tls, "depth", 0) + 1
        outer_added = getattr(tls, "added", None)
        tls.added = [[] for _ in range(self.n)]
        try:
            yield
            if outer_added is not None:  # propagate to the outer scope
                for s in range(self.n):
                    # chunk OBJECTS propagate (identity is what the
                    # outer scope's rollback removes from the queue)
                    outer_added[s].extend(tls.added[s])
        except BaseException:
            self._rollback_plan(tls.added)
            raise
        finally:
            tls.depth -= 1
            tls.added = outer_added

    def _rollback_plan(self, added_chunks: List[List[np.ndarray]]) -> None:
        """Undo a failed plan build's pending records. The expensive
        set-differences run OUTSIDE host_lock (ADVICE r5): lock pass 1
        drops this scope's unmerged chunks (by object identity) and
        releases the build's untrained rows; the consolidated-array
        filter computes unlocked and lands with a pointer swap, with an
        identity check catching a racing consolidation."""
        added = [np.unique(np.concatenate(ch)) if ch
                 else np.empty(0, np.uint64) for ch in added_chunks]
        own = [set(map(id, ch)) for ch in added_chunks]
        snap: List[Optional[np.ndarray]] = [None] * self.n
        with self.host_lock:
            for s in range(self.n):
                ks = added[s]
                if not len(ks):
                    continue
                self._pending_chunks[s] = [
                    c for c in self._pending_chunks[s]
                    if id(c) not in own[s]]
                snap[s] = self._pending[s]
                # ALSO release the rows this build assigned:
                # unpinned-but-still-assigned keys would read as
                # resident at a later pass's reconcile and silently
                # keep their zero rows over the staged values.
                # Keys a concurrent streaming assign trained
                # meanwhile (touched) stay — releasing a row whose
                # updates await write-back would corrupt it; they
                # follow the normal resident-is-fresher rule.
                rows = self.indexes[s].lookup(ks)
                ok = rows >= 0
                ks, rows = ks[ok], rows[ok]
                untouched = ~self._touched[s][rows]
                if untouched.any():
                    self.indexes[s].release(ks[untouched])
                    self._reset_dev_indexes()
        filtered: List[Optional[np.ndarray]] = [None] * self.n
        for s in range(self.n):
            p = snap[s]
            if p is None or not len(p) or not len(added[s]):
                filtered[s] = p
                continue
            filtered[s] = p[~np.isin(p, added[s])]
        with self.host_lock:
            for s in range(self.n):
                if snap[s] is None:
                    continue
                if self._pending[s] is snap[s]:
                    self._pending[s] = filtered[s]
                else:  # a reader consolidated between the locks — redo
                    self._pending[s] = self._pending[s][
                        ~np.isin(self._pending[s], added[s])]

    def _note_plan_assigned(self, s: int, new_keys: np.ndarray) -> None:
        # under host_lock (prepare_global holds it around the assign).
        # O(1) list-append: the old per-call np.union1d rebuilt the
        # sorted pending array on the preloader thread while holding
        # host_lock, serializing against the open pass's streaming
        # assigns (ADVICE r5); readers consolidate once via _pending_of
        self._pending_chunks[s].append(new_keys)
        added = getattr(self._plan_tls, "added", None)
        if added is not None:
            added[s].append(new_keys)

    def _pending_of(self, s: int) -> np.ndarray:
        """Shard s's consolidated sorted pending keys (caller holds
        host_lock): lazily merges the plan-assign chunks, once per
        reader instead of once per assign."""
        ch = self._pending_chunks[s]
        if ch:
            self._pending[s] = np.union1d(self._pending[s],
                                          np.concatenate(ch))
            ch.clear()
        return self._pending[s]

    def _unpin_pending(self, s: int, keys: np.ndarray) -> None:
        """Remove ``keys`` from shard s's pending set (under host_lock):
        their values were promoted (begin_pass) or written back
        (end_pass), so the usual resident-is-fresher reconcile and
        eviction rules apply to them again."""
        pend = self._pending_of(s)
        if len(pend) and len(keys):
            self._pending[s] = pend[~np.isin(pend, keys)]

    # ------------------------------------------------------------------
    def _gather_rows_sync(self, s: int, rows: np.ndarray) -> np.ndarray:
        """Blocking [k, feat] row gather from shard s (eviction
        write-back path) via the shared jitted bucketed gather."""
        dev, k = dispatch_packed_row_gather(self.state, s, rows)
        return np.asarray(jax.device_get(dev))[:k]

    def _split_by_owner(self, keys: np.ndarray) -> List[np.ndarray]:
        keys = np.unique(np.ascontiguousarray(keys, np.uint64))
        owners = (keys % np.uint64(self.n)).astype(np.int64)
        return [keys[owners == s] for s in range(self.n)]

    def _store_fields(self, sub: np.ndarray) -> Dict[str, np.ndarray]:
        return store_fields_from_rows(sub, self.mf_dim, self.opt_ext)

    def _logical_rows(self, vals: Dict[str, np.ndarray]) -> np.ndarray:
        return rows_from_store_fields(vals, self.mf_dim, self.opt_ext)

    # ---- feed-pass staging (BuildPull, ps_gpu_wrapper.cc:337) ----
    def _fetch_stage_values(self, s: int, new_keys: np.ndarray):
        """Subclass hook: host values for shard s's missing keys — the
        multihost table returns None for shards it does not own."""
        return self.hosts[s].fetch(new_keys)

    def stage(self, pass_keys: np.ndarray, background: bool = True,
              queue: bool = False) -> None:
        """Fetch host values for the pass keys NOT already resident in
        the HBM window. Legal while a pass is open (the overlapped
        pre_build_thread, ps_gpu_wrapper.cc:913): missing keys are
        outside the open window, so the open pass's end_pass write-back
        cannot touch them; any key that becomes resident between stage
        and begin_pass has its fetched value dropped by the reconcile.

        ``queue=True`` (the depth-N pass pipeline): the fetch runs on
        the CALLING thread (the preloader worker — already background
        to training) and the completed stage is APPENDED to a queue
        that ``begin_pass`` consumes in pass order, so several future
        passes can sit staged at once. The capacity contract extends
        to the union over open + queued passes; queued working sets
        are pinned against eviction until their own begin_pass. A
        fetch failure queues nothing (the caller — the preload worker
        — holds and re-raises it at the consuming ``wait()``)."""
        if queue and background:
            raise ValueError("queued stages fetch on the calling thread "
                             "(background staging is the single-slot "
                             "protocol)")
        if self._stage_thread is not None or self._stage is not None:
            raise RuntimeError("a feed pass is already staging")
        if self._stage_q and not queue:
            raise RuntimeError(
                "queued feed-pass stages are pending — single-slot "
                "stage() cannot interleave with the stage queue "
                "(consume the queue via begin_pass, or "
                "discard_queued_stages())")
        per_shard = self._split_by_owner(pass_keys)
        for s, ks in enumerate(per_shard):
            if len(ks) > self.capacity:
                raise ValueError(
                    f"shard {s} working set ({len(ks)}) exceeds "
                    f"capacity_per_shard ({self.capacity})")
        with self.host_lock:
            if queue and self._staging_keys is not None \
                    and not all(np.array_equal(a, b) for a, b in
                                zip(self._staging_keys, per_shard)):
                # a pre-build pin_working_set for THIS pass is fine
                # (PassPipeline pins before the plan build); a
                # different in-flight stage is a protocol violation
                raise RuntimeError(
                    "a different queued feed-pass stage is already "
                    "pinned/fetching — queued stages serialize on one "
                    "worker")
            # "missing" includes PENDING plan rows: they sit in the
            # index but hold zero values, so their host values must
            # still fetch (begin_pass scatters them at the reconcile)
            new = []
            for s in range(self.n):
                ks = per_shard[s]
                miss = self.indexes[s].lookup(ks) < 0
                pend = self._pending_of(s)
                if len(pend):
                    miss |= np.isin(ks, pend)
                new.append(ks[miss])
            if queue:
                # pin the working set for the whole fetch: the missing
                # split above is only valid while no eviction touches
                # these keys (see _staging_keys)
                self._staging_keys = per_shard
                gen = self._stage_gen
        if queue:
            try:
                # queued feed-pass fetch: runs on the preloader worker
                # — the pass trace's "pass.stage" span on that lane,
                # child of the enclosing build span
                with trace.span("pass.stage",
                                new_rows=int(sum(len(a) for a in new))):
                    vals = [self._fetch_stage_values(s, new[s])
                            for s in range(self.n)]
                with self.host_lock:
                    if self._stage_gen != gen:
                        raise RuntimeError(
                            "the stage queue was discarded while this "
                            "feed-pass fetch was in flight — the pass "
                            "will never begin")
                    self._stage_q.append(
                        _ShardStage(per_shard, new, vals))
            finally:
                with self.host_lock:
                    self._staging_keys = None
            return
        self._stage_exc = None

        def run() -> None:
            try:
                vals = [self._fetch_stage_values(s, new[s])
                        for s in range(self.n)]
                self._stage = _ShardStage(per_shard, new, vals)
            except BaseException as e:
                self._stage_exc = e

        if background:
            self._stage_thread = threading.Thread(target=run, daemon=True)
            self._stage_thread.start()
        else:
            run()
            if self._stage_exc is not None:
                raise self._stage_exc

    def wait_stage_done(self) -> None:
        if self._stage_thread is not None:
            self._stage_thread.join()
            self._stage_thread = None
        if self._stage_exc is not None:
            exc, self._stage_exc = self._stage_exc, None
            raise exc

    # ---- pass window (BuildGPUTask/EndPass, ps_gpu_wrapper.cc:684,983) --
    def _resolve_stage(self, pass_keys: Optional[np.ndarray]) -> _ShardStage:
        """Shared begin_pass prologue: consume the HEAD of the stage
        queue (pipeline mode), the pending single-slot stage (after
        validating its keys against ``pass_keys``), or stage
        synchronously."""
        if self.in_pass:
            raise RuntimeError("begin_pass while a pass is open")
        t0 = time.perf_counter()
        with self.host_lock:
            if self._stage_q:
                st = self._stage_q.popleft()
                if pass_keys is not None:
                    want = self._split_by_owner(pass_keys)
                    if not all(np.array_equal(a, b) for a, b in
                               zip(st.keys, want)):
                        self._stage_q.appendleft(st)
                        raise RuntimeError(
                            "begin_pass keys differ from the HEAD "
                            "queued stage — the pipeline consumes "
                            "stages strictly in pass order")
                # the consumed pass's working set is pinned against the
                # lane's _evict_ahead from this moment (atomically with
                # the pop, so the lane can never see it unprotected)
                self._open_keys = st.keys
                st.from_queue = True  # begin_pass restores it on failure
                self._last_stage_wait_sec = time.perf_counter() - t0
                return st
        if pass_keys is not None:
            if self._stage_thread is not None or self._stage is not None:
                self.wait_stage_done()
                want = self._split_by_owner(pass_keys)
                if (self._stage is None
                        or not all(np.array_equal(a, b) for a, b in
                                   zip(self._stage.keys, want))):
                    raise RuntimeError(
                        "begin_pass keys differ from the staged key set")
            else:
                self.stage(pass_keys, background=False)
        self.wait_stage_done()
        # critical-path stall spent WAITING on the stage (host fetch +
        # any SSD promote it triggered) — near zero when the stage
        # overlapped the previous pass's training (bench begin_stall
        # breakdown; docs/STORAGE.md)
        self._last_stage_wait_sec = time.perf_counter() - t0
        st = self._stage
        if st is None:
            raise RuntimeError("begin_pass with nothing staged")
        self._stage = None
        return st

    def begin_pass(self, pass_keys: Optional[np.ndarray] = None) -> int:
        """Promote the staged (or given) working set into the HBM shards:
        reconcile the stage against the live window, evict only what
        capacity demands, scatter only the genuinely new rows. Returns
        the number of working-set rows across shards."""
        # promote attribution spans since the PREVIOUS begin_pass (the
        # overlapped stage promotes during the previous pass's train)
        ssd0 = getattr(self, "_ssd_mark", {})
        with trace.span("pass.begin"):
            return self._begin_pass_traced(pass_keys, ssd0)

    def _begin_pass_traced(self, pass_keys, ssd0) -> int:
        st = self._resolve_stage(pass_keys)

        stats = dict(resident=0, staged=0, evicted=0, evicted_writeback=0,
                     written_back=0)
        sh_l: List[np.ndarray] = []
        row_l: List[np.ndarray] = []
        val_l: List[np.ndarray] = []
        total = 0
        fence_sec = 0.0
        t_evict0 = time.perf_counter()
        self.host_lock.acquire()
        try:
            # capacity pressure → promote may EVICT: a dirty evictee's
            # write-back and pass N's in-flight epilogue write-back
            # could reorder on the host store, and a released row's
            # stale host value must be fully landed before a later
            # stage re-fetches it — fence first (the common
            # non-evicting boundary stays fence-free). The shared
            # fence-outside-the-lock loop (ps/epilogue.
            # fence_under_pressure) re-checks under this same lock
            # hold. With the async lane eviction this is the EMERGENCY
            # path — the lane usually freed the rows already.
            fence_sec = fence_under_pressure(
                self.host_lock, self._epilogue.fence,
                lambda: any(len(self.indexes[s]) + len(st.new_keys[s])
                            > self.capacity for s in range(self.n)))
            self._open_keys = st.keys
            for s in range(self.n):
                rows_new, still, st_s = promote_window_delta(
                    self.indexes[s], self._touched[s], self.capacity,
                    st.keys[s], st.new_keys[s],
                    gather_rows=lambda rs, s=s: self._gather_rows_sync(
                        s, rs),
                    writeback=lambda ks, rs, sub, s=s:
                        self.hosts[s].update_rows(ks, sub),
                    pending=self._pending_of(s),
                    protect=self._queued_protect(s))
                # pending keys promoted by THIS pass leave the pending
                # set; keys a concurrent plan build (the pass after
                # next) recorded stay pinned until their own begin
                self._unpin_pending(s, st.keys[s])
                ins_vals = {f: v[still] for f, v in st.values[s].items()}
                sh_l.append(np.full(len(rows_new), s, np.int32))
                row_l.append(rows_new)
                val_l.append(self._logical_rows(ins_vals))
                for k in st_s:
                    stats[k] = stats.get(k, 0) + st_s[k]
                total += len(st.keys[s])
            # promote assigned/released kv rows behind the device
            # mirrors' back — re-seed (or degrade) on next prepare
            self._reset_dev_indexes()
            rows = np.concatenate(row_l) if row_l else np.empty(0, np.int32)
            if len(rows):
                self.state = scatter_logical_rows(
                    self.state, np.concatenate(sh_l), rows,
                    np.concatenate(val_l))
            ev_sec, ev_rows = self._evict_async_sec, self._evict_async_rows
        except BaseException:
            # a begin that fails AFTER consuming a queued stage must
            # not strand the pipeline's bookkeeping: restore the stage
            # to the queue head (its pins release via drain/
            # discard_queued_stages, and the driver's key queue stays
            # aligned) and drop the open-pass pin. NOTE: promote may
            # have partially applied before the raise — the restored
            # stage exists for clean shutdown/diagnosis, not blind
            # retry.
            if getattr(st, "from_queue", False):
                self._stage_q.appendleft(st)
            self._open_keys = [np.empty(0, np.uint64)
                               for _ in range(self.n)]
            raise
        finally:
            self.host_lock.release()
        self.in_pass = True
        # begin_stall breakdown (bench tiered mode): stage wait on the
        # critical path, evict+scatter time, and the SSD promote
        # seconds this pass's staging incurred (with its critical-path
        # share — overlapped promotes show promote_sec > 0 with
        # promote_wait_sec ~ 0). Eviction attribution splits into the
        # lane's overlapped work since the previous begin
        # (evict_async_*) and the inline emergency remainder
        # (evict_emergency_sec = fence wait + promote eviction wall).
        stats["stage_wait_sec"] = round(
            getattr(self, "_last_stage_wait_sec", 0.0), 6)
        stats["evict_scatter_sec"] = round(
            time.perf_counter() - t_evict0, 6)
        stats["evict_emergency_sec"] = round(
            fence_sec + stats.pop("evict_sec", 0.0), 6)
        mark_sec, mark_rows = self._evict_async_mark
        self._evict_async_mark = (ev_sec, ev_rows)
        stats["evict_async_sec"] = round(ev_sec - mark_sec, 6)
        stats["evict_async_rows"] = int(ev_rows - mark_rows)
        ssd1 = self.ssd_stats()
        self._ssd_mark = ssd1
        for k, ok in (("promote_sec", "ssd_promote_sec"),
                      ("promote_wait_sec", "ssd_promote_wait_sec"),
                      ("promoted_rows", "ssd_promoted_rows")):
            if ssd1:
                stats[ok] = round(ssd1.get(k, 0.0) - ssd0.get(k, 0.0), 6)
        self.last_pass_stats = stats
        log.info("begin_pass: %d working-set rows (%d resident, %d staged, "
                 "%d evicted) across %d HBM shards", total,
                 stats["resident"], stats["staged"], stats["evicted"],
                 self.n)
        return total

    def end_pass(self) -> int:
        """Close the pass and WRITE BACK ASYNCHRONOUSLY: snapshot the
        touched-row indices, dispatch the D2H gathers against the
        current (immutable) device buffers, clear the flags, and hand
        the blocking pull + HostStore update to the background epilogue
        — end_pass returns in dispatch time, and pass N+1's begin/train
        overlap the drain (``fence()`` orders every consumer; see
        ps/epilogue.py). ``FLAGS.async_end_pass=False`` runs the same
        job inline (the pre-overlap behavior, bit-for-bit identical —
        scripts/pipeline_check.py gates it). The gather stays
        touched-rows-sized, not window-sized, and now runs OUTSIDE
        host_lock; the window stays resident for the next pass's
        reuse."""
        if not self.in_pass:
            raise RuntimeError("end_pass without begin_pass")
        with trace.span("pass.end_submit") as _sp:
            return self._end_pass_traced(_sp.span_id)

    def _end_pass_traced(self, submit_span: int) -> int:
        total = 0
        t0 = time.perf_counter()
        t_dispatch = 0.0
        jobs: List[tuple] = []
        with self.host_lock:
            for s in range(self.n):
                keys, rows = self.indexes[s].items()
                m = self._touched[s][rows]
                keys, rows = keys[m], rows[m]
                if len(rows):
                    # DISPATCH the device gather now — the captured
                    # buffers are immutable and the dispatch pins them,
                    # so a later jit step donating the (possibly same)
                    # live table buffer cannot invalidate this read
                    t_d = time.perf_counter()
                    dev = dispatch_packed_row_gather(self.state, s, rows)
                    t_dispatch += time.perf_counter() - t_d
                    jobs.append((s, keys, dev))
                    self._touched[s][rows] = False
                    # a PENDING key that trained anyway (a key outside
                    # its pass's staged set) is being written back — the
                    # host value is authoritative again, so the usual
                    # resident-is-fresher reconcile may resume for it
                    self._unpin_pending(s, keys)
                total += len(rows)
            # nothing is open between passes: the closed pass's set no
            # longer pins the lane's _evict_ahead (its un-shared rows
            # are exactly the right victims for the next queued pass)
            self._open_keys = [np.empty(0, np.uint64)
                               for _ in range(self.n)]
        self.in_pass = False
        self.last_pass_stats["written_back"] = total

        tiered_ssd = any(h is not None and h.ssd is not None
                         for h in self.hosts)
        if jobs or tiered_ssd or self._stage_q:
            def run(jobs=jobs) -> None:
                for s, keys, (sub_dev, k) in jobs:
                    # chaos seam: a mid-write-back failure must surface
                    # at the fence, never as silent row loss
                    faults.inject("endpass.writeback", op=f"shard{s}",
                                  shard=s, rows=len(keys))
                    sub = np.asarray(jax.device_get(sub_dev))[:k]
                    self.hosts[s].update_rows(keys, sub)
                # async capacity eviction rides the SAME job, strictly
                # AFTER this pass's rows landed (their touched bits just
                # cleared, so candidates are clean and eviction is pure
                # index release): free the rows the next queued pass
                # will need so its begin_pass pays no inline eviction
                with trace.span("evict.ahead"):
                    self._evict_ahead()
                # watermark demotion rides the SAME job: strictly after
                # this pass's rows landed and are marked touched —
                # selection is untouched-first, so a row whose write-back
                # just landed spills only when nothing colder exists
                # (and then its touched bit rides the tier). Off the
                # critical path; disk IO outside host_lock.
                self._demote_after_writeback()

            if FLAGS.async_end_pass:
                # link: the writeback job's span on the epilogue lane
                # points back at this end_submit span (flow arrow)
                self._epilogue.submit(run, label="end_pass",
                                      link_from=submit_span)
            else:
                run()
        # submit-time parity audit (ISSUE 9): the ONLY synchronous
        # portion is touched-row snapshot + bucketed D2H dispatch —
        # split out so a regressed boundary names which half grew
        self.last_pass_stats["end_pass_submit_sec"] = round(
            time.perf_counter() - t0, 6)
        self.last_pass_stats["end_pass_dispatch_sec"] = round(
            t_dispatch, 6)
        log.info("end_pass: %d touched rows -> %d host stores (%s)",
                 total, self.n,
                 "async" if FLAGS.async_end_pass else "sync")
        return total

    def drop_window(self) -> None:
        """Invalidate HBM residency (between passes): the next begin_pass
        re-fetches everything from the host tier. Called automatically
        after host-tier mutations outside the pass protocol
        (load/merge_model/shrink), whose updates would otherwise be
        shadowed by stale resident rows; also the recovery entry point
        after a host-tier restore (LoadSSD2Mem, box_wrapper.cc:1415).

        Discards any pending stage (its fetched values predate the
        host-tier mutation, and its resident/missing split predates the
        residency drop) and zeroes the device rows (released rows must
        read as fresh zero rows if a later mid-pass assign reuses them
        before a scatter initializes them)."""
        self._no_pass("drop_window")
        self.fence()  # the dropped window's write-backs must land first
        try:
            if self._stage_thread is not None or self._stage is not None:
                self.wait_stage_done()
        finally:
            # the reset must run even when the pending stage raised —
            # callers that swallow the stage error would otherwise keep
            # pre-mutation rows resident, shadowing the host tier
            self._stage = None
            with self.host_lock:
                # queued stages predate the mutation too — their
                # fetched values and missing-splits are stale (the gen
                # bump also rejects any fetch still in flight)
                self._stage_q.clear()
                self._stage_gen += 1
                self._open_keys = [np.empty(0, np.uint64)
                                   for _ in range(self.n)]
                self.indexes = [HostKV(self.capacity)
                                for _ in range(self.n)]
                self._touched[:] = False
                self._pending = [np.empty(0, np.uint64)
                                 for _ in range(self.n)]
                self._pending_chunks = [[] for _ in range(self.n)]
                self.state = self.state.with_packed(
                    jnp.zeros_like(self.state.packed))

    def _no_pass(self, what: str) -> None:
        if self.in_pass:
            raise RuntimeError(
                f"{what} while a pass is open — the window's updates are "
                "not in the host stores yet; end_pass first")

    # ---- lifecycle on the FULL (host-tier) model ------------------------
    def feature_count(self) -> int:
        return sum(len(h) for h in self.hosts)

    def save_base(self, path: str, clear_touched: bool = True) -> int:
        """Full model dump, single file, ShardedEmbeddingTable._dump
        format (n + keys_s/field_s blocks, + opt_ext_s) — includes
        disk-spilled rows (SaveBase, box_wrapper.cc:1383).
        ``clear_touched=False`` = staged artifact publish: the delta
        bookkeeping survives until the publish commits
        (``clear_touched_flags`` is the post-commit half)."""
        self._no_pass("save_base")
        blobs: Dict[str, np.ndarray] = {}
        total = 0
        for s, hs in enumerate(self.hosts):
            keys, fields = hs.export_rows(clear_touched=clear_touched)
            blobs[f"keys_{s}"] = keys
            for f, v in fields.items():
                blobs[f"{f}_{s}"] = v
            total += len(keys)
        np.savez_compressed(path, n=self.n, **blobs)
        log.info("tiered save_base: %d rows -> %s", total, path)
        return total

    def save_delta(self, path: str, clear_touched: bool = True) -> int:
        """Rows written back since the last save ("xbox delta");
        ``clear_touched=False`` = staged artifact publish (save_base)."""
        self._no_pass("save_delta")
        blobs: Dict[str, np.ndarray] = {}
        total = 0
        for s, hs in enumerate(self.hosts):
            keys, fields = hs.export_rows(delta=True,
                                          clear_touched=clear_touched)
            blobs[f"keys_{s}"] = keys
            for f, v in fields.items():
                blobs[f"{f}_{s}"] = v
            total += len(keys)
        np.savez_compressed(path, n=self.n, **blobs)
        log.info("tiered save_delta: %d rows -> %s", total, path)
        return total

    def clear_touched_flags(self) -> None:
        """Post-commit half of a staged publish: clear every shard's
        delta bookkeeping (RAM + disk tier). Fences first."""
        self.fence()
        for hs in self.hosts:
            if hs is not None:
                hs.clear_touched_flags()

    def load(self, path: str, merge: bool = False) -> int:
        self._no_pass("load")
        blob = np.load(path)
        total = 0
        # shard-splitting shared with the parent (same file formats)
        for s, (keys, fields) in enumerate(self._file_per_shard(blob)):
            total += self.hosts[s].import_rows(keys, fields, merge=merge)
        self.drop_window()  # resident rows may shadow the loaded values
        return total

    def merge_model(self, path: str) -> int:
        """MergeModel on the full host tier (box_wrapper.h:801-803):
        shared keys accumulate show/clk/delta_score, keep live weights;
        unseen keys insert wholesale. merge_models is inherited — the
        parent loop dispatches back to these overrides."""
        self._no_pass("merge_model")
        blob = np.load(path)
        total = 0
        for s, (keys, fields) in enumerate(self._file_per_shard(blob)):
            total += self.hosts[s].merge_model_rows(keys, fields)
        self.drop_window()
        return total

    def shrink(self, delete_threshold: Optional[float] = None,
               decay: Optional[float] = None) -> int:
        """ShrinkTable over every shard's host store (box_wrapper.h:638)."""
        self._no_pass("shrink")
        self.fence()  # draining end_pass write-backs must land before
        # aging — per-host _barrier repeats the audit, but fencing once
        # here keeps the contract visible at the entry point
        freed = sum(h.shrink(delete_threshold=delete_threshold, decay=decay,
                             nonclk_coeff=self.cfg.nonclk_coeff,
                             clk_coeff=self.cfg.clk_coeff)
                    for h in self.hosts)
        self.drop_window()  # resident rows hold pre-decay stats
        return freed

    def spill_cold(self, path_prefix: str, threshold: float) -> int:
        """Move cold rows of every shard to disk-tier files
        ``{path_prefix}.s{K}.npz`` (the host-RAM ↔ SSD boundary). Values
        are unchanged, so HBM residency stays valid — spilled keys that
        are still resident simply keep serving from the window."""
        self._no_pass("spill_cold")
        return sum(h.spill_cold(f"{path_prefix}.s{s}.npz", threshold,
                                nonclk_coeff=self.cfg.nonclk_coeff,
                                clk_coeff=self.cfg.clk_coeff)
                   for s, h in enumerate(self.hosts))
