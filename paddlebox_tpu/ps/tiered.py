"""Tiered sharded PS: HostStore-backed pass windows per HBM shard.

The reference's core capability — a table BIGGER than device memory on a
multi-device PS: per pass, ``BuildPull`` fetches the pass's values from
the CPU store (ps_gpu_wrapper.cc:337), ``BuildGPUTask`` fills the per-GPU
HBM pools (:684), training hits only the resident working set, and
``EndPass`` dumps updated values back to the CPU store (:983); the SSD
tier promotes via ``LoadSSD2Mem`` (box_wrapper.cc:1415).

TPU-native composition: ``ShardedEmbeddingTable`` keeps its whole routing
machinery (key%N owner shards, two all_to_alls in the jit step) but its
per-shard HBM slice becomes a PASS WINDOW — each shard fronted by a
``HostStore`` (host RAM + disk spill) holding the full model. The pass
lifecycle mirrors ``PassScopedTable``:

    table.stage(ds.pass_keys())     # BuildPull: host fetch per shard
    table.begin_pass()              # BuildGPUTask: scatter → HBM shards
    trainer.adopt_table()
    ...train (streaming or resident)...
    trainer.sync_table(); table.end_pass()   # EndPass: HBM → host

Contract (same as the reference's pass windows): the staged key set must
cover every key the pass's batches touch — keys outside it allocate fresh
zero rows in the window and would overwrite their host values at
end_pass. ``ds.pass_keys()`` provides exactly that set.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import numpy as np

from paddlebox_tpu.ps.host_store import HostStore
from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.table import (FIELDS, NUM_FIXED, HostKV, TableState,
                                    field_assign, field_slice)
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class _ShardStage:
    def __init__(self, keys: List[np.ndarray],
                 values: List[Dict[str, np.ndarray]]) -> None:
        self.keys = keys        # per shard
        self.values = values    # per shard


class TieredShardedEmbeddingTable(ShardedEmbeddingTable):
    """ShardedEmbeddingTable whose HBM shards hold one pass's working set;
    the full model lives in N per-shard HostStores (+ disk spill)."""

    def __init__(self, num_shards: int, mf_dim: int = 8,
                 capacity_per_shard: Optional[int] = None,
                 cfg: Optional[SparseSGDConfig] = None,
                 host_capacity: Optional[int] = None,
                 host_init_rows: int = 1 << 14,
                 req_bucket_min: int = 512,
                 serve_bucket_min: int = 1024) -> None:
        super().__init__(num_shards, mf_dim=mf_dim,
                         capacity_per_shard=capacity_per_shard, cfg=cfg,
                         req_bucket_min=req_bucket_min,
                         serve_bucket_min=serve_bucket_min)
        self.hosts = [HostStore(mf_dim, capacity=host_capacity,
                                init_rows=host_init_rows,
                                opt_ext=self.opt_ext)
                      for _ in range(self.n)]
        self.in_pass = False
        self._stage: Optional[_ShardStage] = None
        self._stage_thread: Optional[threading.Thread] = None
        self._stage_exc: Optional[BaseException] = None

    # ------------------------------------------------------------------
    def _split_by_owner(self, keys: np.ndarray) -> List[np.ndarray]:
        keys = np.unique(np.ascontiguousarray(keys, np.uint64))
        owners = (keys % np.uint64(self.n)).astype(np.int64)
        return [keys[owners == s] for s in range(self.n)]

    # ---- feed-pass staging (BuildPull, ps_gpu_wrapper.cc:337) ----
    def stage(self, pass_keys: np.ndarray, background: bool = True) -> None:
        """Fetch the pass working set from every shard's host store. Only
        legal between end_pass and the next begin_pass (staged values must
        reflect the previous pass's write-back)."""
        if self.in_pass:
            raise RuntimeError(
                "stage() while a pass is open — end_pass first")
        if self._stage_thread is not None:
            raise RuntimeError("a feed pass is already staging")
        per_shard = self._split_by_owner(pass_keys)
        for s, ks in enumerate(per_shard):
            if len(ks) > self.capacity:
                raise ValueError(
                    f"shard {s} working set ({len(ks)}) exceeds "
                    f"capacity_per_shard ({self.capacity})")
        self._stage_exc = None

        def run() -> None:
            try:
                vals = [self.hosts[s].fetch(per_shard[s])
                        for s in range(self.n)]
                self._stage = _ShardStage(per_shard, vals)
            except BaseException as e:
                self._stage_exc = e

        if background:
            self._stage_thread = threading.Thread(target=run, daemon=True)
            self._stage_thread.start()
        else:
            run()
            if self._stage_exc is not None:
                raise self._stage_exc

    def wait_stage_done(self) -> None:
        if self._stage_thread is not None:
            self._stage_thread.join()
            self._stage_thread = None
        if self._stage_exc is not None:
            exc, self._stage_exc = self._stage_exc, None
            raise exc

    # ---- pass window (BuildGPUTask/EndPass, ps_gpu_wrapper.cc:684,983) --
    def begin_pass(self, pass_keys: Optional[np.ndarray] = None) -> int:
        """Promote the staged (or given) working set into the HBM shards.
        Returns the number of working-set rows across shards."""
        if self.in_pass:
            raise RuntimeError("begin_pass while a pass is open")
        if pass_keys is not None:
            if self._stage_thread is not None or self._stage is not None:
                self.wait_stage_done()
                want = self._split_by_owner(pass_keys)
                if (self._stage is None
                        or not all(np.array_equal(a, b) for a, b in
                                   zip(self._stage.keys, want))):
                    raise RuntimeError(
                        "begin_pass keys differ from the staged key set")
            else:
                self.stage(pass_keys, background=False)
        self.wait_stage_done()
        st = self._stage
        if st is None:
            raise RuntimeError("begin_pass with nothing staged")
        self._stage = None

        mf_end = NUM_FIXED + self.mf_dim
        data = np.zeros((self.n, self.capacity + 1, mf_end + self.opt_ext),
                        np.float32)
        total = 0
        with self.host_lock:
            for s in range(self.n):
                self.indexes[s] = HostKV(self.capacity)
                rows = self.indexes[s].assign(st.keys[s])
                for f in FIELDS:
                    field_assign(data[s], rows, f, st.values[s][f])
                if self.opt_ext:
                    data[s][rows, mf_end:] = st.values[s]["opt_ext"]
                total += len(rows)
            self._touched[:] = False
        self.state = TableState.from_logical(data, self.capacity,
                                             ext=self.opt_ext)
        self.in_pass = True
        log.info("begin_pass: %d working-set rows across %d HBM shards",
                 total, self.n)
        return total

    def end_pass(self) -> int:
        """Write the (jit-updated) working set back to the host stores."""
        if not self.in_pass:
            raise RuntimeError("end_pass without begin_pass")
        data = np.asarray(jax.device_get(self.state.data))
        mf_end = NUM_FIXED + self.mf_dim
        total = 0
        with self.host_lock:
            for s in range(self.n):
                keys, rows = self.indexes[s].items()
                sub = data[s][rows]
                # embedx sliced to mf_dim explicitly: field_slice's tail is
                # unbounded and would leak the opt_ext columns into the
                # host store's (k, mf_dim) array (EmbeddingTable.
                # _gather_host does the same)
                vals = {f: (sub[:, NUM_FIXED:mf_end] if f == "embedx_w"
                            else field_slice(sub, f)) for f in FIELDS}
                if self.opt_ext:
                    vals["opt_ext"] = sub[:, mf_end:]
                self.hosts[s].update(keys, vals)
                total += len(keys)
        self.in_pass = False
        log.info("end_pass: %d rows written back to %d host stores",
                 total, self.n)
        return total

    def _no_pass(self, what: str) -> None:
        if self.in_pass:
            raise RuntimeError(
                f"{what} while a pass is open — the window's updates are "
                "not in the host stores yet; end_pass first")

    # ---- lifecycle on the FULL (host-tier) model ------------------------
    def feature_count(self) -> int:
        return sum(len(h) for h in self.hosts)

    def save_base(self, path: str) -> int:
        """Full model dump, single file, ShardedEmbeddingTable._dump
        format (n + keys_s/field_s blocks, + opt_ext_s) — includes
        disk-spilled rows (SaveBase, box_wrapper.cc:1383)."""
        self._no_pass("save_base")
        blobs: Dict[str, np.ndarray] = {}
        total = 0
        for s, hs in enumerate(self.hosts):
            keys, fields = hs.export_rows()
            blobs[f"keys_{s}"] = keys
            for f, v in fields.items():
                blobs[f"{f}_{s}"] = v
            total += len(keys)
        np.savez_compressed(path, n=self.n, **blobs)
        log.info("tiered save_base: %d rows -> %s", total, path)
        return total

    def save_delta(self, path: str) -> int:
        """Rows written back since the last save ("xbox delta")."""
        self._no_pass("save_delta")
        blobs: Dict[str, np.ndarray] = {}
        total = 0
        for s, hs in enumerate(self.hosts):
            keys, fields = hs.export_rows(delta=True)
            blobs[f"keys_{s}"] = keys
            for f, v in fields.items():
                blobs[f"{f}_{s}"] = v
            total += len(keys)
        np.savez_compressed(path, n=self.n, **blobs)
        log.info("tiered save_delta: %d rows -> %s", total, path)
        return total

    def load(self, path: str, merge: bool = False) -> int:
        self._no_pass("load")
        blob = np.load(path)
        total = 0
        # shard-splitting shared with the parent (same file formats)
        for s, (keys, fields) in enumerate(self._file_per_shard(blob)):
            total += self.hosts[s].import_rows(keys, fields, merge=merge)
        return total

    def merge_model(self, path: str) -> int:
        """MergeModel on the full host tier (box_wrapper.h:801-803):
        shared keys accumulate show/clk/delta_score, keep live weights;
        unseen keys insert wholesale. merge_models is inherited — the
        parent loop dispatches back to these overrides."""
        self._no_pass("merge_model")
        blob = np.load(path)
        total = 0
        for s, (keys, fields) in enumerate(self._file_per_shard(blob)):
            total += self.hosts[s].merge_model_rows(keys, fields)
        return total

    def shrink(self, delete_threshold: Optional[float] = None,
               decay: Optional[float] = None) -> int:
        """ShrinkTable over every shard's host store (box_wrapper.h:638)."""
        self._no_pass("shrink")
        return sum(h.shrink(delete_threshold=delete_threshold, decay=decay,
                            nonclk_coeff=self.cfg.nonclk_coeff,
                            clk_coeff=self.cfg.clk_coeff)
                   for h in self.hosts)

    def spill_cold(self, path_prefix: str, threshold: float) -> int:
        """Move cold rows of every shard to disk-tier files
        ``{path_prefix}.s{K}.npz`` (the host-RAM ↔ SSD boundary)."""
        self._no_pass("spill_cold")
        return sum(h.spill_cold(f"{path_prefix}.s{s}.npz", threshold,
                                nonclk_coeff=self.cfg.nonclk_coeff,
                                clk_coeff=self.cfg.clk_coeff)
                   for s, h in enumerate(self.hosts))
