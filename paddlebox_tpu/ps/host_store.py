"""Host-RAM backing store for features beyond HBM capacity (Phase 5).

Reference capability: the BoxPS closed core keeps the full table on
host-mem+SSD and promotes each pass's working set into GPU HBM
(``BeginFeedPass``/``BeginPass``/``EndPass``, fleet/box_wrapper.cc:129-186);
the open HeterPS analogue is PSGPUWrapper's build pipeline — ``BuildPull``
fetching values from the CPU PS and ``BuildGPUTask`` filling HBM pools
(ps_gpu_wrapper.cc:337,684), with ``EndPass`` dumping updated values back
(:983). PSCore's ``memory_sparse_table``/``ssd_sparse_table`` define the
save/shrink semantics.

TPU-native redesign: one numpy SoA per feature field, grown geometrically
up to a hard capacity, fronted by the native C++ key→row index (ps/kv.py).
Fetch/update are fully vectorized (no per-key python). The pass working
set is fetched here and scattered into the statically-shaped device
TableState by PassScopedTable; spill granularity is the pass, not the key.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.ps.kv import make_kv
from paddlebox_tpu.ps.table import TWO_D_FIELDS, FIELDS
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

# host SoA fields — single source of truth is the device TableState
# (FeatureValue layout, heter_ps/feature_value.h:570)
_2D_FIELDS = TWO_D_FIELDS


class HostStore:
    """All-features host table; thread-safe for one writer at a time."""

    def __init__(self, mf_dim: int, capacity: Optional[int] = None,
                 init_rows: int = 1 << 16) -> None:
        self.mf_dim = mf_dim
        self.capacity = capacity or FLAGS.host_store_capacity
        self.index = make_kv(self.capacity)
        self._alloc = min(init_rows, self.capacity)
        self._arr: Dict[str, np.ndarray] = {
            f: np.zeros(self._shape(f, self._alloc), np.float32)
            for f in FIELDS
        }
        self._touched = np.zeros(self._alloc, dtype=bool)
        self._lock = threading.Lock()

    def _shape(self, field: str, n: int) -> Tuple[int, ...]:
        return (n, self.mf_dim) if field in _2D_FIELDS else (n,)

    def _ensure(self, max_row: int) -> None:
        if max_row < self._alloc:
            return
        new = self._alloc
        while new <= max_row:
            new *= 2
        new = min(new, self.capacity)
        for f in FIELDS:
            a = np.zeros(self._shape(f, new), np.float32)
            a[:self._alloc] = self._arr[f]
            self._arr[f] = a
        t = np.zeros(new, dtype=bool)
        t[:self._alloc] = self._touched
        self._touched = t
        self._alloc = new

    def __len__(self) -> int:
        return len(self.index)

    # ---- pass staging ----
    def fetch(self, keys: np.ndarray) -> Dict[str, np.ndarray]:
        """Values for ``keys``; unknown keys read as zero-initialized rows
        (they materialize on update — lazy feature creation)."""
        with self._lock:
            rows = self.index.lookup(np.ascontiguousarray(keys, np.uint64))
            known = rows >= 0
            out = {}
            for f in FIELDS:
                a = np.zeros(self._shape(f, len(keys)), np.float32)
                a[known] = self._arr[f][rows[known]]
                out[f] = a
            return out

    def update(self, keys: np.ndarray, data: Dict[str, np.ndarray]) -> None:
        """Write back a pass's updated rows (EndPass dump)."""
        with self._lock:
            rows = self.index.assign(np.ascontiguousarray(keys, np.uint64))
            if len(rows):
                self._ensure(int(rows.max()))
            for f in FIELDS:
                self._arr[f][rows] = data[f]
            self._touched[rows] = True

    # ---- checkpoint (SaveBase/SaveDelta, box_wrapper.cc:1383-1415) ----
    def _dump(self, path: str, keys: np.ndarray, rows: np.ndarray) -> int:
        np.savez_compressed(
            path, keys=keys, mf_dim=np.int32(self.mf_dim),
            **{f: self._arr[f][rows] for f in FIELDS})
        return len(keys)

    def save_base(self, path: str) -> int:
        with self._lock:
            keys, rows = self.index.items()
            n = self._dump(path, keys, rows)
            self._touched[:] = False
        log.info("save_base: %d rows -> %s", n, path)
        return n

    def save_delta(self, path: str) -> int:
        with self._lock:
            keys, rows = self.index.items()
            m = self._touched[rows]
            n = self._dump(path, keys[m], rows[m])
            self._touched[:] = False
        log.info("save_delta: %d rows -> %s", n, path)
        return n

    def load(self, path: str, merge: bool = False) -> int:
        blob = np.load(path)
        keys = blob["keys"]
        with self._lock:
            if not merge:
                self.index = make_kv(self.capacity)
                for f in FIELDS:
                    self._arr[f][:] = 0
                self._touched[:] = False
            rows = self.index.assign(keys)
            if len(rows):
                self._ensure(int(rows.max()))
            for f in FIELDS:
                self._arr[f][rows] = blob[f]
        return len(keys)

    # ---- feature aging (ShrinkTable, box_wrapper.h:638) ----
    def shrink(self, delete_threshold: Optional[float] = None,
               decay: Optional[float] = None,
               nonclk_coeff: float = 0.1, clk_coeff: float = 1.0) -> int:
        thr = (FLAGS.shrink_delete_threshold
               if delete_threshold is None else delete_threshold)
        dk = FLAGS.show_click_decay_rate if decay is None else decay
        with self._lock:
            keys, rows = self.index.items()
            if len(keys) == 0:
                return 0
            self._arr["show"] *= dk
            self._arr["clk"] *= dk
            self._arr["delta_score"] *= dk
            show, clk = self._arr["show"][rows], self._arr["clk"][rows]
            score = nonclk_coeff * (show - clk) + clk_coeff * clk
            drop = score < thr
            freed = self.index.release(keys[drop])
            for f in FIELDS:
                self._arr[f][freed] = 0
            self._touched[freed] = False
        log.info("host shrink: freed %d/%d rows", len(freed), len(keys))
        return int(len(freed))
