"""Host-RAM backing store for features beyond HBM capacity (Phase 5).

Reference capability: the BoxPS closed core keeps the full table on
host-mem+SSD and promotes each pass's working set into GPU HBM
(``BeginFeedPass``/``BeginPass``/``EndPass``, fleet/box_wrapper.cc:129-186);
the open HeterPS analogue is PSGPUWrapper's build pipeline — ``BuildPull``
fetching values from the CPU PS and ``BuildGPUTask`` filling HBM pools
(ps_gpu_wrapper.cc:337,684), with ``EndPass`` dumping updated values back
(:983). PSCore's ``memory_sparse_table``/``ssd_sparse_table`` define the
save/shrink semantics.

TPU-native redesign: one numpy SoA per feature field, grown geometrically
up to a hard capacity, fronted by the native C++ key→row index (ps/kv.py).
Fetch/update are fully vectorized (no per-key python). The pass working
set is fetched here and scattered into the statically-shaped device
TableState by PassScopedTable; spill granularity is the pass, not the key.

THIRD TIER (ps/ssd.py, docs/STORAGE.md): rows beyond host-RAM capacity
live in an attached ``SsdTier`` — log-structured segment files with an
in-memory key→(segment, offset) index. ``fetch`` promotes spilled keys
transparently (``LoadSSD2Mem``: on the tiered pipeline this runs on the
stage thread, overlapped with training); crossing the
``FLAGS.host_demote_watermark`` capacity fraction demotes the coldest
rows (two-phase, so segment IO never holds the store lock against a
concurrent stage fetch — the background path the tiered tables drive
from the async-epilogue worker). A demoted row's un-exported update
travels as a ``touched`` bit through the tier, so ``save_delta`` stays
complete; ``save_base``/``export_rows`` merge the tier, so exports stay
complete. ``spill_cold``/``load_from_disk`` remain as thin compat shims
over the tier (one sealed segment per manual spill file).
"""

from __future__ import annotations

import itertools
import os
import threading
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.ps.kv import make_kv
from paddlebox_tpu.ps.ssd import SsdTier, read_segment_file
from paddlebox_tpu.ps.table import (NUM_FIXED, TWO_D_FIELDS, FIELDS,
                                    rows_from_store_fields,
                                    store_fields_from_rows)
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

# host SoA fields — single source of truth is the device TableState
# (FeatureValue layout, heter_ps/feature_value.h:570)
_2D_FIELDS = TWO_D_FIELDS

#: distinct auto-created tier directories under FLAGS.ssd_dir
_TIER_SEQ = itertools.count()


class HostStore:
    """All-features host table; thread-safe for one writer at a time."""

    def __init__(self, mf_dim: int, capacity: Optional[int] = None,
                 init_rows: int = 1 << 16, opt_ext: int = 0,
                 ssd_dir: Optional[str] = None) -> None:
        """``opt_ext`` — width of the per-row optimizer extension block
        (ps/sgd.opt_ext_width) persisted alongside the base fields, so
        pass-scoped tables keep SparseAdam state across pass windows.
        ``ssd_dir`` attaches the disk tier explicitly; with
        ``FLAGS.ssd_dir`` set, every store auto-attaches one under a
        unique subdirectory; otherwise the tier materializes lazily on
        the first ``spill_cold``."""
        self.mf_dim = mf_dim
        self.opt_ext = opt_ext
        self.fields = tuple(FIELDS) + (("opt_ext",) if opt_ext else ())
        self.capacity = capacity or FLAGS.host_store_capacity
        self.index = make_kv(self.capacity)
        self._alloc = min(init_rows, self.capacity)
        self._arr: Dict[str, np.ndarray] = {
            f: np.zeros(self._shape(f, self._alloc), np.float32)
            for f in self.fields
        }
        self._touched = np.zeros(self._alloc, dtype=bool)
        # rows selected by an in-flight two-phase demote: a concurrent
        # write clears the mark, telling the demote's confirm phase the
        # row is fresher than the copy it just wrote to disk
        self._demote_mark = np.zeros(self._alloc, dtype=bool)
        self._lock = threading.Lock()
        # disk tier (ps/ssd.SsdTier); None = two-tier store (seed shape)
        self.ssd: Optional[SsdTier] = None
        if ssd_dir is None and FLAGS.ssd_dir:
            ssd_dir = os.path.join(FLAGS.ssd_dir,
                                   f"hs{next(_TIER_SEQ):04d}")
        if ssd_dir:
            self.ssd = SsdTier(ssd_dir, self._row_width)
        # async-epilogue fence (ps/epilogue.PassEpilogue.fence, installed
        # by the pass-window tables): EVERY read/wholesale-mutate entry
        # point drains in-flight end_pass write-backs first, so no
        # consumer — save/shrink/merge/serving fetch/len — can observe a
        # partially written-back pass. ``update`` deliberately does NOT
        # barrier: the epilogue worker itself lands rows through it.
        self.read_barrier: Optional[Callable[[], None]] = None

    @property
    def _row_width(self) -> int:
        """Logical row width (rows_from_store_fields layout) — the SSD
        tier's fixed record stride."""
        return NUM_FIXED + self.mf_dim + self.opt_ext

    @property
    def _spill_files(self) -> list:
        """Compat view of the disk tier: segment paths still holding
        live (disk-only) rows, oldest first."""
        return self.ssd.segment_paths() if self.ssd is not None else []

    def _barrier(self) -> None:
        b = self.read_barrier
        if b is not None:
            b()

    def _shape(self, field: str, n: int) -> Tuple[int, ...]:
        if field == "opt_ext":
            return (n, self.opt_ext)
        return (n, self.mf_dim) if field in _2D_FIELDS else (n,)

    def _ensure(self, max_row: int) -> None:
        if max_row < self._alloc:
            return
        new = self._alloc
        while new <= max_row:
            new *= 2
        new = min(new, self.capacity)
        for f in self.fields:
            a = np.zeros(self._shape(f, new), np.float32)
            a[:self._alloc] = self._arr[f]
            self._arr[f] = a
        for name in ("_touched", "_demote_mark"):
            t = np.zeros(new, dtype=bool)
            t[:self._alloc] = getattr(self, name)
            setattr(self, name, t)
        self._alloc = new

    def __len__(self) -> int:
        self._barrier()
        return len(self.index)

    def total_rows(self) -> int:
        """Logical model size: RAM rows + disk-tier-only rows."""
        self._barrier()
        with self._lock:
            n = len(self.index)
        return n + (len(self.ssd) if self.ssd is not None else 0)

    # ---- disk tier plumbing (ps/ssd.py) --------------------------------
    def attach_ssd(self, tier: SsdTier) -> None:
        if tier.width != self._row_width:
            raise ValueError(
                f"SSD tier row width {tier.width} != store row width "
                f"{self._row_width} (mf_dim/opt_ext mismatch)")
        self.ssd = tier

    def _ensure_tier(self, root_hint: str) -> SsdTier:
        """Lazily attach a tier for the spill_cold compat shim (manual
        spills get a tier rooted next to their first spill file)."""
        if self.ssd is None:
            self.ssd = SsdTier(
                os.path.join(root_hint or ".", ".pbox_ssd"),
                self._row_width)
        return self.ssd

    def _pack_rows(self, rows: np.ndarray) -> np.ndarray:
        """Host rows (SoA field arrays at ``rows``) → logical [k, width]
        block — the demote wire format (bit-exact round trip with
        store_fields_from_rows on promote)."""
        return rows_from_store_fields(
            {f: self._arr[f][rows] for f in self.fields},
            self.mf_dim, self.opt_ext)

    def _select_cold(self, count: int,
                     exclude: Optional[np.ndarray] = None,
                     include_touched: bool = True,
                     nonclk_coeff: float = 0.1, clk_coeff: float = 1.0
                     ) -> Tuple[np.ndarray, np.ndarray]:
        """Deterministic demote victim selection (caller holds _lock):
        coldest first by (untouched-first, score asc, key asc) — the
        ctr_accessor shrink rule's heat over show/clk. Touched rows are
        LAST resorts (their delta rides the tier's touched bit)."""
        keys, rows = self.index.items()
        if len(keys) == 0 or count <= 0:
            return np.empty(0, np.uint64), np.empty(0, np.int32)
        keep = np.ones(len(keys), bool)
        if exclude is not None and len(exclude):
            keep &= ~np.isin(keys, exclude)
        if not include_touched:
            keep &= ~self._touched[rows]
        keys, rows = keys[keep], rows[keep]
        if len(keys) == 0:
            return np.empty(0, np.uint64), np.empty(0, np.int32)
        score = self._score(rows, nonclk_coeff, clk_coeff)
        order = np.lexsort((keys, score,
                            self._touched[rows].astype(np.int8)))
        sel = order[:min(count, len(order))]
        return keys[sel], rows[sel]

    def _headroom_locked(self, need: int,
                         exclude: Optional[np.ndarray] = None) -> None:
        """Free index capacity for ``need`` new rows by demoting cold
        rows synchronously (caller holds _lock; tier IO under the lock
        — the EMERGENCY path; the watermark keeps it rare). Without a
        tier this is a no-op and the index raises TableFullError as
        before."""
        if self.ssd is None:
            return
        free = self.capacity - len(self.index)
        if free >= need:
            return
        ck, cr = self._select_cold(need - free, exclude=exclude)
        if len(ck) == 0:
            return
        self.ssd.append(ck, self._pack_rows(cr),
                        touched=self._touched[cr].copy())
        self._free(ck)
        log.info("host headroom: demoted %d cold rows to the SSD tier",
                 len(ck))

    def demote_cold(self, count: Optional[int] = None,
                    include_touched: bool = True,
                    barrier: bool = True,
                    nonclk_coeff: float = 0.1,
                    clk_coeff: float = 1.0) -> int:
        """Demote the ``count`` coldest rows (None = every eligible row)
        to the SSD tier — TWO-PHASE so the segment write never holds the
        store lock against a concurrent stage fetch: select+copy under
        the lock, write outside it, then confirm-free only rows no
        writer touched meanwhile (a raced row keeps its fresher RAM
        state and its just-written disk copy is discarded).

        ``barrier=False`` is for callers already ordered BEHIND the
        async epilogue (the tiered end_pass write-back job runs this on
        the epilogue lane itself — fencing there would deadlock the
        single-lane worker)."""
        if self.ssd is None:
            return 0
        if barrier:
            self._barrier()
        with self._lock:
            if count is None:
                count = len(self.index)
            ck, cr = self._select_cold(count,
                                       include_touched=include_touched,
                                       nonclk_coeff=nonclk_coeff,
                                       clk_coeff=clk_coeff)
            if len(ck) == 0:
                return 0
            sub = self._pack_rows(cr)
            tch = self._touched[cr].copy()
            self._demote_mark[cr] = True
        # phase 2: segment IO with the store lock RELEASED
        self.ssd.append(ck, sub, touched=tch)
        # phase 3: free only rows whose mark survived (no writer raced)
        with self._lock:
            cur = self.index.lookup(ck)
            same = cur == cr          # still the same key→row binding
            ok = same.copy()
            ok[same] = self._demote_mark[cr[same]]
            self._demote_mark[cr] = False
            freed_keys = ck[ok]
            self._free(freed_keys)
            # a concurrent write superseded the copy we just demoted —
            # RAM stays authoritative, so the disk copy must not shadow
            # it. INSIDE the lock, and only while the key is still
            # RAM-live: a raced key someone ELSE demoted-and-freed
            # meanwhile has its (fresher) tier copy as the only copy
            # left — discarding that would lose the row.
            stale = ck[~ok & (cur >= 0)]
            if len(stale):
                self.ssd.discard(stale)
        if len(freed_keys):
            log.info("demote_cold: %d rows -> SSD tier (%d raced and "
                     "stayed in RAM)", len(freed_keys), len(stale))
        return int(len(freed_keys))

    def demote_to_watermark(self, barrier: bool = True) -> int:
        """Background demotion policy: above
        ``FLAGS.host_demote_watermark × capacity`` RAM rows, demote the
        coldest down to ``FLAGS.host_demote_target × capacity``. The
        tiered tables run this on the async-epilogue worker right after
        each end_pass write-back lands (ordered, off the critical
        path). No-op without a tier or below the watermark."""
        if self.ssd is None:
            return 0
        wm = FLAGS.host_demote_watermark
        if wm <= 0:
            return 0
        with self._lock:
            n = len(self.index)
        if n <= int(wm * self.capacity):
            return 0
        target = int(max(0.0, min(FLAGS.host_demote_target, wm))
                     * self.capacity)
        return self.demote_cold(count=n - target, barrier=barrier)

    def _promote(self, keys: np.ndarray) -> int:
        """LoadSSD2Mem: move ``keys``' rows (the subset found in the
        tier) back into host RAM. Promoted keys leave the tier index
        atomically with the read — no stale copy can resurrect — and a
        key that became RAM-resident meanwhile keeps its fresher RAM
        state (the promoted copy is dropped)."""
        if self.ssd is None or len(keys) == 0:
            return 0
        fkeys, sub, tch = self.ssd.take(keys)
        if len(fkeys) == 0:
            return 0
        try:
            fields = store_fields_from_rows(sub, self.mf_dim,
                                            self.opt_ext)
            with self._lock:
                live = self.index.lookup(fkeys) >= 0
                ins = ~live                    # RAM wins over the tier
                ik = fkeys[ins]
                if len(ik):
                    self._headroom_locked(len(ik), exclude=ik)
                    rows = self.index.assign(ik)
                    self._ensure(int(rows.max()))
                    for f in self.fields:
                        self._arr[f][rows] = fields[f][ins]
                    self._touched[rows] = tch[ins]
                    self._demote_mark[rows] = False
            return int(len(ik))
        except BaseException:
            # the rows left the tier but never landed in RAM — put them
            # back rather than lose them
            self.ssd.append(fkeys, sub, touched=tch)
            raise

    def spill_manifest(self) -> Optional[dict]:
        """The tier's checkpoint manifest (segment paths + sha256), or
        None without a tier / with an empty tier. Sealing side effect:
        see SsdTier.manifest."""
        self._barrier()
        return self.ssd.manifest() if self.ssd is not None else None

    def ssd_stats(self) -> Dict[str, float]:
        return self.ssd.stats() if self.ssd is not None else {}

    # ---- pass staging ----
    def fetch(self, keys: np.ndarray) -> Dict[str, np.ndarray]:
        """Values for ``keys``; unknown keys read as zero-initialized rows
        (they materialize on update — lazy feature creation). Keys that
        live only in the disk tier are promoted transparently first (the
        LoadSSD2Mem step of the pass lifecycle), so PassScopedTable.stage
        never trains a spilled feature from zero — and on the tiered
        pipeline this fetch runs on the STAGE thread, so the promotion
        IO overlaps the open pass's training."""
        self._barrier()  # in-flight end_pass write-backs land first
        keys_u64 = np.ascontiguousarray(keys, np.uint64)
        if self.ssd is not None and len(self.ssd):
            with self._lock:
                missing = self.index.lookup(keys_u64) < 0
            if missing.any():
                self._promote(keys_u64[missing])
        with self._lock:
            rows = self.index.lookup(keys_u64)
            known = rows >= 0
            out = {}
            for f in self.fields:
                a = np.zeros(self._shape(f, len(keys)), np.float32)
                a[known] = self._arr[f][rows[known]]
                out[f] = a
            return out

    def update(self, keys: np.ndarray, data: Dict[str, np.ndarray]) -> None:
        """Write back a pass's updated rows (EndPass dump)."""
        keys_u64 = np.ascontiguousarray(keys, np.uint64)
        with self._lock:
            if self.ssd is not None:
                new = int((self.index.lookup(keys_u64) < 0).sum())
                if new:
                    self._headroom_locked(new, exclude=keys_u64)
            rows = self.index.assign(keys_u64)
            if len(rows):
                self._ensure(int(rows.max()))
            for f in self.fields:
                self._arr[f][rows] = data[f]
            self._touched[rows] = True
            self._demote_mark[rows] = False
            if self.ssd is not None and len(self.ssd):
                # tier copies of freshly written keys are stale now (a
                # key demoted earlier and re-created by this write) —
                # drop them so no export or later promote can see the
                # old values. INSIDE the store lock: released, a racing
                # demote could re-spill one of these keys and this
                # discard would then delete the only remaining copy.
                self.ssd.discard(keys_u64)

    def update_rows(self, keys: np.ndarray, sub: np.ndarray,
                    slot_override: Optional[np.ndarray] = None) -> None:
        """Batched write-back of gathered LOGICAL rows ``[k, feat]``
        (gather_full_rows layout) — the async-epilogue fast path: one
        call converts fields and lands the whole shard delta under a
        single lock acquisition, instead of the caller assembling a
        field dict first."""
        self.update(keys, store_fields_from_rows(
            sub, self.mf_dim, self.opt_ext, slot_override=slot_override))

    # ---- shared helpers (score / eviction / dump format) ----
    def _score(self, rows: np.ndarray, nonclk_coeff: float,
               clk_coeff: float) -> np.ndarray:
        """Feature heat (ctr_accessor shrink rule): coeffs over show/clk."""
        show, clk = self._arr["show"][rows], self._arr["clk"][rows]
        return nonclk_coeff * (show - clk) + clk_coeff * clk

    def _free(self, keys: np.ndarray) -> np.ndarray:
        """Release keys and zero their rows; returns freed row ids."""
        freed = self.index.release(keys)
        for f in self.fields:
            self._arr[f][freed] = 0
        self._touched[freed] = False
        self._demote_mark[freed] = False
        return freed

    # ---- checkpoint (SaveBase/SaveDelta, box_wrapper.cc:1383-1415) ----
    def _dump(self, path: str, keys: np.ndarray, rows: np.ndarray,
              extra: Optional[Dict[str, np.ndarray]] = None) -> int:
        """npz dump of rows; ``extra`` appends out-of-RAM rows (spilled
        tiers) as {field: values} with their own key array."""
        blobs = {f: self._arr[f][rows] for f in self.fields}
        if extra:
            keys = np.concatenate([keys, extra["keys"]])
            for f in self.fields:
                blobs[f] = np.concatenate([blobs[f], extra[f]])
        np.savez_compressed(path, keys=keys, mf_dim=np.int32(self.mf_dim),
                            **blobs)
        return len(keys)

    def _ssd_extra(self, delta: bool = False,
                   clear_touched: bool = True
                   ) -> Optional[Dict[str, np.ndarray]]:
        """Tier rows for a save/export merge: {field: values, "keys"}.
        ``delta`` restricts to tier rows carrying the touched bit (their
        update never reached a save yet). RAM-live keys are filtered
        defensively — RAM is always the fresher copy."""
        if self.ssd is None or len(self.ssd) == 0:
            return None
        tk, trows, _tch = self.ssd.export_rows(delta=delta,
                                               clear_touched=clear_touched)
        if len(tk) == 0:
            return None
        dead = self.index.lookup(tk) < 0
        tk, trows = tk[dead], trows[dead]
        if len(tk) == 0:
            return None
        out = store_fields_from_rows(trows, self.mf_dim, self.opt_ext)
        out["keys"] = tk
        return out

    def save_base(self, path: str, clear_touched: bool = True) -> int:
        """Full model dump — includes rows currently spilled to the disk
        tier, so the exported base is always the COMPLETE model.
        ``clear_touched=False`` = a STAGED export (artifact publish):
        the delta bookkeeping survives until the publish commits, so a
        failed publish loses nothing (``clear_touched_flags`` is the
        post-commit half)."""
        self._barrier()
        with self._lock:
            keys, rows = self.index.items()
            n = self._dump(path, keys, rows,
                           extra=self._ssd_extra(
                               clear_touched=clear_touched))
            if clear_touched:
                self._touched[:] = False
        log.info("save_base: %d rows -> %s", n, path)
        return n

    # ---- in-memory export/import (sharded single-file save format) ----
    def export_rows(self, delta: bool = False, clear_touched: bool = True
                    ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """(keys, {field: values}) snapshot — base includes disk-tier
        rows so the export is the COMPLETE model; ``delta`` restricts to
        rows touched since the last export/save (including tier rows
        demoted with un-exported updates) and clears their flags —
        unless ``clear_touched=False`` (staged artifact publish; see
        save_base)."""
        self._barrier()
        with self._lock:
            keys, rows = self.index.items()
            if delta:
                m = self._touched[rows]
                keys, rows = keys[m], rows[m]
            out = {f: self._arr[f][rows].copy() for f in self.fields}
            extra = self._ssd_extra(delta=delta,
                                    clear_touched=clear_touched)
            if extra is not None:
                keys = np.concatenate([keys, extra["keys"]])
                for f in self.fields:
                    out[f] = np.concatenate([out[f], extra[f]])
            if clear_touched:
                if not delta:
                    self._touched[:] = False
                else:
                    self._touched[rows] = False
        return keys, out

    def clear_touched_flags(self) -> None:
        """Post-commit half of a STAGED export: clear the delta
        bookkeeping for every row, RAM and disk tier alike. Call only
        between passes (the publish protocol fences first) — a staged
        ``save_*(clear_touched=False)`` followed by this on publish
        success is equivalent to the plain clearing save, but a publish
        failure in between loses no delta rows."""
        self._barrier()
        with self._lock:
            self._touched[:] = False
            if self.ssd is not None:
                self.ssd.clear_touched()

    def rows_digest(self) -> str:
        """sha256 over the store's COMPLETE logical content (RAM + disk
        tier), keyed and sorted by feasign so row-assignment order
        cancels out. Read-only: rides ``export_rows(clear_touched=
        False)``, so it fingerprints exactly what a base export would
        dump while clearing no delta bookkeeping. The bit-identity
        oracle of the publish gates (scripts/publish_check.py,
        scripts/chaos_check.py)."""
        import hashlib
        keys, out = self.export_rows(clear_touched=False)
        order = np.argsort(keys)
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(keys[order]).tobytes())
        for f in sorted(out):
            h.update(f.encode())
            h.update(np.ascontiguousarray(
                out[f][order], np.float32).tobytes())
        return h.hexdigest()

    def import_rows(self, keys: np.ndarray, fields: Dict[str, np.ndarray],
                    merge: bool = False) -> int:
        """Write rows wholesale (load semantics); merge=False resets the
        store first (the old model's disk tier does not carry over).
        Missing/mismatched opt_ext starts fresh. With a tier attached,
        an import larger than the RAM watermark routes the COLDEST rows
        straight to the tier — the restore path for models bigger than
        host RAM."""
        self._barrier()  # an in-flight write-back must not land AFTER
        keys_u64 = np.ascontiguousarray(keys, np.uint64)
        with self._lock:  # a reset/load overwrote the store
            if not merge:
                self.index = make_kv(self.capacity)
                for f in self.fields:
                    self._arr[f][:] = 0
                self._touched[:] = False
                self._demote_mark[:] = False
                if self.ssd is not None:
                    self.ssd.clear()  # old model's tiers don't carry over
            ram_sel, cold_sel = self._split_import(keys_u64, fields)
            rows = self.index.assign(keys_u64[ram_sel])
            if len(rows):
                self._ensure(int(rows.max()))
            for f in self.fields:
                self._write_field(f, rows, fields, "import_rows",
                                  sel=ram_sel)
            self._demote_mark[rows] = False
            if merge and self.ssd is not None and len(self.ssd):
                # imported keys that also had a tier copy: the import
                # wins. Inside the store lock — released, a racing
                # demote could re-spill one of these keys first and
                # this discard would delete the only remaining copy.
                self.ssd.discard(keys_u64[ram_sel])
        if cold_sel is not None and cold_sel.any():
            sub = rows_from_store_fields(
                {f: (fields[f][cold_sel] if f in fields
                     else np.zeros(self._shape(f, int(cold_sel.sum())),
                                   np.float32))
                 for f in self.fields}, self.mf_dim, self.opt_ext)
            self.ssd.append(keys_u64[cold_sel], sub)
            log.info("import_rows: %d rows routed to the SSD tier "
                     "(host RAM watermark)", int(cold_sel.sum()))
        return len(keys)

    def _split_import(self, keys: np.ndarray,
                      fields: Dict[str, np.ndarray]
                      ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """(ram_mask, cold_mask) for an import: without a tier all rows
        go to RAM (TableFullError stays the relief valve); with one,
        rows beyond the watermark budget spill coldest-first (score over
        the incoming show/clk, key-tiebroken — deterministic)."""
        n = len(keys)
        all_ram = np.ones(n, bool)
        if self.ssd is None:
            return all_ram, None
        wm = FLAGS.host_demote_watermark
        budget = int((wm if wm > 0 else 1.0) * self.capacity) \
            - len(self.index)
        # re-imported keys reuse their existing rows — only truly new
        # keys consume budget
        existing = self.index.lookup(keys) >= 0
        new_n = int((~existing).sum())
        if new_n <= max(0, budget):
            return all_ram, None
        show = np.asarray(fields.get("show", np.zeros(n)), np.float32)
        clk = np.asarray(fields.get("clk", np.zeros(n)), np.float32)
        score = 0.1 * (show - clk) + 1.0 * clk
        order = np.lexsort((keys, -score))   # hottest first, key tiebreak
        keep_new = max(0, budget)
        ram = existing.copy()
        picked = 0
        for i in order.tolist():
            if ram[i]:
                continue
            if picked < keep_new:
                ram[i] = True
                picked += 1
        return ram, ~ram

    def merge_model_rows(self, keys: np.ndarray,
                         fields: Dict[str, np.ndarray]) -> int:
        """MergeModel semantics (box_wrapper.h:801-803) on the host tier:
        keys present in both ACCUMULATE show/clk/delta_score and keep the
        live weights/optimizer state; unseen keys insert wholesale.
        Tier-resident keys count as present: they promote first so the
        accumulate lands on their real values."""
        if len(keys) == 0:
            return 0
        self._barrier()
        keys = np.ascontiguousarray(keys, np.uint64)
        if self.ssd is not None and len(self.ssd):
            self._promote(keys)   # accumulate needs the real rows in RAM
        with self._lock:
            existing = self.index.lookup(keys) >= 0
        new_keys = keys[~existing]
        self.import_rows(new_keys,
                         {f: v[~existing] for f, v in fields.items()},
                         merge=True)
        with self._lock:
            rows_old = self.index.lookup(keys[existing])
            for f in ("show", "clk", "delta_score"):
                self._arr[f][rows_old] += fields[f][existing]
            self._touched[rows_old] = True
            self._demote_mark[rows_old] = False
            lk = self.index.lookup(new_keys)
            rows_new = lk[lk >= 0]   # watermark may have routed some
            self._touched[rows_new] = True   # new rows to the tier
        return len(keys)

    def save_delta(self, path: str, clear_touched: bool = True) -> int:
        """Touched-rows dump ("xbox delta"); ``clear_touched=False`` =
        staged artifact publish (see save_base)."""
        self._barrier()
        with self._lock:
            keys, rows = self.index.items()
            m = self._touched[rows]
            n = self._dump(path, keys[m], rows[m],
                           extra=self._ssd_extra(
                               delta=True, clear_touched=clear_touched))
            if clear_touched:
                self._touched[:] = False
        log.info("save_delta: %d rows -> %s", n, path)
        return n

    def _write_field(self, f: str, rows, blob, who: str,
                     sel=slice(None)) -> None:
        """Write one field from a save file, tolerating files written
        WITHOUT (or with a different-width) opt_ext block — optimizer
        state then starts fresh for those rows, with a warning (the
        EmbeddingTable.load degradation contract)."""
        if f == "opt_ext" and (f not in blob
                               or blob[f].shape[1] != self.opt_ext):
            log.warning("%s: file has no matching opt_ext block; "
                        "optimizer state starts fresh for loaded rows",
                        who)
            self._arr[f][rows] = 0.0
            return
        self._arr[f][rows] = blob[f][sel]

    def load(self, path: str, merge: bool = False) -> int:
        blob = np.load(path)
        keys = blob["keys"]
        fields = {f: blob[f] for f in self.fields if f in blob}
        return self.import_rows(keys, fields, merge=merge)

    # ---- disk tier compat shims (SSD role: LoadSSD2Mem,
    # box_wrapper.cc:1415 — thin wrappers over ps/ssd.SsdTier) ----
    def spill_cold(self, path: str, threshold: float,
                   nonclk_coeff: float = 0.1, clk_coeff: float = 1.0) -> int:
        """Move COLD rows (score < threshold) into ONE sealed tier
        segment at ``path`` and free their host rows — the manual
        host-RAM ↔ SSD boundary (hot rows stay in mem, cold spill to SSD
        until a later ``load_from_disk``/``fetch`` promotes them back).

        Only rows whose updates are already exported spill here (touched
        rows stay in RAM — the conservative legacy contract; the
        watermark demoter is the path that may spill touched rows, with
        the touched bit carried through the tier)."""
        if not path.endswith(".npz"):
            path += ".npz"  # legacy savez convention; registry must match
        self._barrier()
        with self._lock:
            tier = self._ensure_tier(os.path.dirname(path))
            if tier.has_live_path(path):
                raise ValueError(
                    f"{path} already holds an active spill — overwriting "
                    "would lose its still-spilled rows; use a fresh path "
                    "per spill")
            keys, rows = self.index.items()
            if len(keys) == 0:
                return 0
            cold = self._score(rows, nonclk_coeff, clk_coeff) < threshold
            cold &= ~self._touched[rows]  # unsaved updates never spill
            ck, cr = keys[cold], rows[cold]
            if len(ck) == 0:
                return 0
            tier.append_sealed_file(path, ck, self._pack_rows(cr))
            self._free(ck)
        log.info("spill_cold: %d/%d rows -> %s", len(ck), len(keys), path)
        return int(len(ck))

    def load_from_disk(self, path: str, keys: Optional[np.ndarray] = None
                       ) -> int:
        """Promote spilled rows back into host RAM (LoadSSD2Mem). With
        ``keys``, only the requested subset (a pass working set) loads;
        rows already live in RAM keep their fresher in-memory state.

        Promoted (or RAM-superseded) keys leave the tier index — a later
        shrink of a promoted key can never resurrect its stale spilled
        copy into a base export. A path unknown to this store's tier
        (another process's spill file) is scanned directly and adopted
        row-by-row — the fresh-restore path."""
        if not path.endswith(".npz"):
            path += ".npz"
        self._barrier()  # "RAM wins" needs in-flight rows IN RAM first
        if self.ssd is not None and self.ssd.has_live_path(path):
            want = self.ssd.keys_in_path(path)
            if keys is not None:
                want = want[np.isin(want,
                                    np.ascontiguousarray(keys, np.uint64))]
            n = self._promote(want)
            log.info("load_from_disk: %d rows <- %s (tier)", n, path)
            return n
        dkeys, sub, tch = read_segment_file(path, self._row_width)
        sel = np.ones(len(dkeys), bool)
        if keys is not None:
            sel = np.isin(dkeys, np.ascontiguousarray(keys, np.uint64))
        fields = store_fields_from_rows(sub, self.mf_dim, self.opt_ext)
        with self._lock:
            live = self.index.lookup(dkeys) >= 0
            sel &= ~live  # RAM state wins over the spilled copy
            lk = dkeys[sel]
            if len(lk):
                self._headroom_locked(len(lk), exclude=lk)
                rows = self.index.assign(lk)
                self._ensure(int(rows.max()))
                for f in self.fields:
                    self._arr[f][rows] = fields[f][sel]
                self._touched[rows] = tch[sel]
                self._demote_mark[rows] = False
        log.info("load_from_disk: %d rows <- %s", len(lk), path)
        return int(len(lk))

    # ---- feature aging (ShrinkTable, box_wrapper.h:638) ----
    def shrink(self, delete_threshold: Optional[float] = None,
               decay: Optional[float] = None,
               nonclk_coeff: float = 0.1, clk_coeff: float = 1.0) -> int:
        thr = (FLAGS.shrink_delete_threshold
               if delete_threshold is None else delete_threshold)
        dk = FLAGS.show_click_decay_rate if decay is None else decay
        self._barrier()  # decay/score must see every written-back row
        freed: np.ndarray = np.empty(0, np.int64)
        with self._lock:
            keys, rows = self.index.items()
            if len(keys):
                self._arr["show"] *= dk
                self._arr["clk"] *= dk
                self._arr["delta_score"] *= dk
                drop = self._score(rows, nonclk_coeff, clk_coeff) < thr
                freed = self._free(keys[drop])
                if self.ssd is not None and len(self.ssd):
                    # an aged-out feature's disk copy must never
                    # resurrect
                    self.ssd.discard(keys[drop])
        dropped_ssd = 0
        if self.ssd is not None and len(self.ssd):
            # age the DEMOTED rows too (SsdTier.shrink) — without this
            # the disk tier is immortal and an always-on stream's SSD
            # footprint never plateaus; compact afterward so the
            # vacated + dropped copies actually free disk
            dropped_ssd = self.ssd.shrink(thr, dk, nonclk_coeff,
                                          clk_coeff)
            self.ssd.maybe_compact()
        log.info("host shrink: freed %d/%d RAM rows, %d SSD rows",
                 len(freed), len(keys), dropped_ssd)
        return int(len(freed)) + dropped_ssd
