"""Host-RAM backing store for features beyond HBM capacity (Phase 5).

Reference capability: the BoxPS closed core keeps the full table on
host-mem+SSD and promotes each pass's working set into GPU HBM
(``BeginFeedPass``/``BeginPass``/``EndPass``, fleet/box_wrapper.cc:129-186);
the open HeterPS analogue is PSGPUWrapper's build pipeline — ``BuildPull``
fetching values from the CPU PS and ``BuildGPUTask`` filling HBM pools
(ps_gpu_wrapper.cc:337,684), with ``EndPass`` dumping updated values back
(:983). PSCore's ``memory_sparse_table``/``ssd_sparse_table`` define the
save/shrink semantics.

TPU-native redesign: one numpy SoA per feature field, grown geometrically
up to a hard capacity, fronted by the native C++ key→row index (ps/kv.py).
Fetch/update are fully vectorized (no per-key python). The pass working
set is fetched here and scattered into the statically-shaped device
TableState by PassScopedTable; spill granularity is the pass, not the key.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.ps.kv import make_kv
from paddlebox_tpu.ps.table import (TWO_D_FIELDS, FIELDS,
                                    store_fields_from_rows)
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

# host SoA fields — single source of truth is the device TableState
# (FeatureValue layout, heter_ps/feature_value.h:570)
_2D_FIELDS = TWO_D_FIELDS


class HostStore:
    """All-features host table; thread-safe for one writer at a time."""

    def __init__(self, mf_dim: int, capacity: Optional[int] = None,
                 init_rows: int = 1 << 16, opt_ext: int = 0) -> None:
        """``opt_ext`` — width of the per-row optimizer extension block
        (ps/sgd.opt_ext_width) persisted alongside the base fields, so
        pass-scoped tables keep SparseAdam state across pass windows."""
        self.mf_dim = mf_dim
        self.opt_ext = opt_ext
        self.fields = tuple(FIELDS) + (("opt_ext",) if opt_ext else ())
        self.capacity = capacity or FLAGS.host_store_capacity
        self.index = make_kv(self.capacity)
        self._alloc = min(init_rows, self.capacity)
        self._arr: Dict[str, np.ndarray] = {
            f: np.zeros(self._shape(f, self._alloc), np.float32)
            for f in self.fields
        }
        self._touched = np.zeros(self._alloc, dtype=bool)
        self._lock = threading.Lock()
        self._spill_files: list = []  # active disk-tier files (spill_cold)
        self._spill_keys: Dict[str, np.ndarray] = {}  # path → spilled keys
        # async-epilogue fence (ps/epilogue.PassEpilogue.fence, installed
        # by the pass-window tables): EVERY read/wholesale-mutate entry
        # point drains in-flight end_pass write-backs first, so no
        # consumer — save/shrink/merge/serving fetch/len — can observe a
        # partially written-back pass. ``update`` deliberately does NOT
        # barrier: the epilogue worker itself lands rows through it.
        self.read_barrier: Optional[Callable[[], None]] = None

    def _barrier(self) -> None:
        b = self.read_barrier
        if b is not None:
            b()

    def _shape(self, field: str, n: int) -> Tuple[int, ...]:
        if field == "opt_ext":
            return (n, self.opt_ext)
        return (n, self.mf_dim) if field in _2D_FIELDS else (n,)

    def _ensure(self, max_row: int) -> None:
        if max_row < self._alloc:
            return
        new = self._alloc
        while new <= max_row:
            new *= 2
        new = min(new, self.capacity)
        for f in self.fields:
            a = np.zeros(self._shape(f, new), np.float32)
            a[:self._alloc] = self._arr[f]
            self._arr[f] = a
        t = np.zeros(new, dtype=bool)
        t[:self._alloc] = self._touched
        self._touched = t
        self._alloc = new

    def __len__(self) -> int:
        self._barrier()
        return len(self.index)

    # ---- pass staging ----
    def fetch(self, keys: np.ndarray) -> Dict[str, np.ndarray]:
        """Values for ``keys``; unknown keys read as zero-initialized rows
        (they materialize on update — lazy feature creation). Keys that
        live only in a disk-tier spill file are promoted transparently
        first (the LoadSSD2Mem step of the pass lifecycle), so
        PassScopedTable.stage never trains a spilled feature from zero."""
        self._barrier()  # in-flight end_pass write-backs land first
        keys_u64 = np.ascontiguousarray(keys, np.uint64)
        if self._spill_files:
            with self._lock:
                missing = self.index.lookup(keys_u64) < 0
                want = keys_u64[missing]
                candidates = [
                    p for p in self._spill_files
                    if np.isin(want, self._spill_keys[p]).any()
                ] if missing.any() else []
            for p in candidates:
                self.load_from_disk(p, keys=want)
        with self._lock:
            rows = self.index.lookup(keys_u64)
            known = rows >= 0
            out = {}
            for f in self.fields:
                a = np.zeros(self._shape(f, len(keys)), np.float32)
                a[known] = self._arr[f][rows[known]]
                out[f] = a
            return out

    def update(self, keys: np.ndarray, data: Dict[str, np.ndarray]) -> None:
        """Write back a pass's updated rows (EndPass dump)."""
        with self._lock:
            rows = self.index.assign(np.ascontiguousarray(keys, np.uint64))
            if len(rows):
                self._ensure(int(rows.max()))
            for f in self.fields:
                self._arr[f][rows] = data[f]
            self._touched[rows] = True

    def update_rows(self, keys: np.ndarray, sub: np.ndarray,
                    slot_override: Optional[np.ndarray] = None) -> None:
        """Batched write-back of gathered LOGICAL rows ``[k, feat]``
        (gather_full_rows layout) — the async-epilogue fast path: one
        call converts fields and lands the whole shard delta under a
        single lock acquisition, instead of the caller assembling a
        field dict first."""
        self.update(keys, store_fields_from_rows(
            sub, self.mf_dim, self.opt_ext, slot_override=slot_override))

    # ---- shared helpers (score / eviction / dump format) ----
    def _score(self, rows: np.ndarray, nonclk_coeff: float,
               clk_coeff: float) -> np.ndarray:
        """Feature heat (ctr_accessor shrink rule): coeffs over show/clk."""
        show, clk = self._arr["show"][rows], self._arr["clk"][rows]
        return nonclk_coeff * (show - clk) + clk_coeff * clk

    def _free(self, keys: np.ndarray) -> np.ndarray:
        """Release keys and zero their rows; returns freed row ids."""
        freed = self.index.release(keys)
        for f in self.fields:
            self._arr[f][freed] = 0
        self._touched[freed] = False
        return freed

    # ---- checkpoint (SaveBase/SaveDelta, box_wrapper.cc:1383-1415) ----
    def _dump(self, path: str, keys: np.ndarray, rows: np.ndarray,
              extra: Optional[Dict[str, Dict[str, np.ndarray]]] = None
              ) -> int:
        """npz dump of rows; ``extra`` appends out-of-RAM rows (spilled
        tiers) as {field: values} with their own key array."""
        blobs = {f: self._arr[f][rows] for f in self.fields}
        if extra:
            keys = np.concatenate([keys, extra["keys"]])
            for f in self.fields:
                blobs[f] = np.concatenate([blobs[f], extra[f]])
        np.savez_compressed(path, keys=keys, mf_dim=np.int32(self.mf_dim),
                            **blobs)
        return len(keys)

    def _purge_spilled(self, keys: np.ndarray) -> None:
        """Drop keys from every spill file's in-memory REGISTRY (the files
        themselves are immutable snapshots; _spill_keys is the only
        authority on which rows are still disk-resident) — called with
        shrink-deleted keys so an aged-out feature's stale spilled copy
        can never resurrect into a base export. Caller holds _lock."""
        if not self._spill_files or len(keys) == 0:
            return
        for p in list(self._spill_files):
            reg = self._spill_keys[p]
            keep = ~np.isin(reg, keys)
            if keep.all():
                continue
            if keep.any():
                self._spill_keys[p] = reg[keep]
            else:
                self._spill_files.remove(p)
                self._spill_keys.pop(p, None)

    def _spilled_not_in_ram(self) -> Optional[Dict[str, np.ndarray]]:
        """Rows living only in spill files (for complete base exports)."""
        if not self._spill_files:
            return None
        out = {f: [] for f in self.fields}
        out_keys = []
        for p in list(self._spill_files):
            blob = np.load(p)
            dkeys = blob["keys"]
            reg = self._spill_keys[p]
            dead = self.index.lookup(
                np.ascontiguousarray(dkeys, np.uint64)) < 0
            sel = dead & np.isin(dkeys, reg)
            out_keys.append(dkeys[sel])
            for f in self.fields:
                out[f].append(blob[f][sel])
        res = {f: np.concatenate(v) for f, v in out.items()}
        res["keys"] = np.concatenate(out_keys)
        return res if len(res["keys"]) else None

    def save_base(self, path: str) -> int:
        """Full model dump — includes rows currently spilled to disk
        tiers, so the exported base is always the COMPLETE model."""
        self._barrier()
        with self._lock:
            keys, rows = self.index.items()
            n = self._dump(path, keys, rows,
                           extra=self._spilled_not_in_ram())
            self._touched[:] = False
        log.info("save_base: %d rows -> %s", n, path)
        return n

    # ---- in-memory export/import (sharded single-file save format) ----
    def export_rows(self, delta: bool = False
                    ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """(keys, {field: values}) snapshot — base includes disk-spilled
        rows so the export is the COMPLETE model; ``delta`` restricts to
        rows touched since the last export/save and clears their flags."""
        self._barrier()
        with self._lock:
            keys, rows = self.index.items()
            if delta:
                m = self._touched[rows]
                keys, rows = keys[m], rows[m]
            out = {f: self._arr[f][rows].copy() for f in self.fields}
            if not delta:
                extra = self._spilled_not_in_ram()
                if extra is not None:
                    keys = np.concatenate([keys, extra["keys"]])
                    for f in self.fields:
                        out[f] = np.concatenate([out[f], extra[f]])
                self._touched[:] = False
            else:
                self._touched[rows] = False
        return keys, out

    def import_rows(self, keys: np.ndarray, fields: Dict[str, np.ndarray],
                    merge: bool = False) -> int:
        """Write rows wholesale (load semantics); merge=False resets the
        store first. Missing/mismatched opt_ext starts fresh."""
        self._barrier()  # an in-flight write-back must not land AFTER
        with self._lock:  # a reset/load overwrote the store
            if not merge:
                self.index = make_kv(self.capacity)
                for f in self.fields:
                    self._arr[f][:] = 0
                self._touched[:] = False
                self._spill_files = []
                self._spill_keys = {}
            rows = self.index.assign(np.ascontiguousarray(keys, np.uint64))
            if len(rows):
                self._ensure(int(rows.max()))
            for f in self.fields:
                self._write_field(f, rows, fields, "import_rows")
        return len(keys)

    def merge_model_rows(self, keys: np.ndarray,
                         fields: Dict[str, np.ndarray]) -> int:
        """MergeModel semantics (box_wrapper.h:801-803) on the host tier:
        keys present in both ACCUMULATE show/clk/delta_score and keep the
        live weights/optimizer state; unseen keys insert wholesale."""
        if len(keys) == 0:
            return 0
        self._barrier()
        keys = np.ascontiguousarray(keys, np.uint64)
        with self._lock:
            existing = self.index.lookup(keys) >= 0
        new_keys = keys[~existing]
        self.import_rows(new_keys,
                         {f: v[~existing] for f, v in fields.items()},
                         merge=True)
        with self._lock:
            rows_old = self.index.lookup(keys[existing])
            for f in ("show", "clk", "delta_score"):
                self._arr[f][rows_old] += fields[f][existing]
            self._touched[rows_old] = True
            rows_new = self.index.lookup(new_keys)
            self._touched[rows_new] = True
        return len(keys)

    def save_delta(self, path: str) -> int:
        self._barrier()
        with self._lock:
            keys, rows = self.index.items()
            m = self._touched[rows]
            n = self._dump(path, keys[m], rows[m])
            self._touched[:] = False
        log.info("save_delta: %d rows -> %s", n, path)
        return n

    def _write_field(self, f: str, rows, blob, who: str,
                     sel=slice(None)) -> None:
        """Write one field from a save file, tolerating files written
        WITHOUT (or with a different-width) opt_ext block — optimizer
        state then starts fresh for those rows, with a warning (the
        EmbeddingTable.load degradation contract)."""
        if f == "opt_ext" and (f not in blob
                               or blob[f].shape[1] != self.opt_ext):
            log.warning("%s: file has no matching opt_ext block; "
                        "optimizer state starts fresh for loaded rows",
                        who)
            self._arr[f][rows] = 0.0
            return
        self._arr[f][rows] = blob[f][sel]

    def load(self, path: str, merge: bool = False) -> int:
        self._barrier()  # same reset-vs-in-flight hazard as import_rows
        blob = np.load(path)
        keys = blob["keys"]
        with self._lock:
            if not merge:
                self.index = make_kv(self.capacity)
                for f in self.fields:
                    self._arr[f][:] = 0
                self._touched[:] = False
                self._spill_files = []  # old model's tiers don't carry over
                self._spill_keys = {}
            rows = self.index.assign(keys)
            if len(rows):
                self._ensure(int(rows.max()))
            for f in self.fields:
                self._write_field(f, rows, blob, "load")
        return len(keys)

    # ---- disk tier (SSD role: LoadSSD2Mem, box_wrapper.cc:1415) ----
    def spill_cold(self, path: str, threshold: float,
                   nonclk_coeff: float = 0.1, clk_coeff: float = 1.0) -> int:
        """Move COLD rows (score < threshold) to a disk file and free
        their host rows — the host-RAM ↔ SSD boundary of the reference's
        tiered store (hot rows stay in mem, cold spill to SSD until a
        later ``load_from_disk`` promotes them back for a pass).

        Only rows whose updates are already exported spill (touched rows
        stay in RAM): a spilled row is on disk in BOTH the spill file and
        the last base, so no save_delta update can be lost, and
        ``save_base`` merges spill files in so exports stay complete."""
        if not path.endswith(".npz"):
            path += ".npz"  # savez appends it; the registry must match
        self._barrier()
        with self._lock:
            if path in self._spill_files:
                raise ValueError(
                    f"{path} already holds an active spill — overwriting "
                    "would lose its still-spilled rows; use a fresh path "
                    "per spill")
            keys, rows = self.index.items()
            if len(keys) == 0:
                return 0
            cold = self._score(rows, nonclk_coeff, clk_coeff) < threshold
            cold &= ~self._touched[rows]  # unsaved updates never spill
            ck, cr = keys[cold], rows[cold]
            if len(ck) == 0:
                return 0
            self._dump(path, ck, cr)
            self._free(ck)
            # the file is IMMUTABLE from here on; _spill_keys[path] is the
            # live accounting of which of its rows are still disk-only
            self._spill_files.append(path)
            self._spill_keys[path] = ck
        log.info("spill_cold: %d/%d rows -> %s", len(ck), len(keys), path)
        return int(len(ck))

    def load_from_disk(self, path: str, keys: Optional[np.ndarray] = None
                       ) -> int:
        """Promote spilled rows back into host RAM (LoadSSD2Mem). With
        ``keys``, only the requested subset (a pass working set) loads;
        rows already live in RAM keep their fresher in-memory state.

        Promoted (or RAM-superseded) keys leave the spill ACCOUNTING
        (_spill_keys — the file itself is immutable): a later shrink of a
        promoted key can never resurrect its stale spilled copy into a
        base export, and no call ever rewrites a spill file."""
        self._barrier()  # "RAM wins" needs in-flight rows IN RAM first
        blob = np.load(path)  # immutable file: safe to read unlocked
        dkeys = blob["keys"]
        if len(dkeys) == 0:
            return 0
        sel = np.ones(len(dkeys), bool)
        if keys is not None:
            sel = np.isin(dkeys, np.ascontiguousarray(keys, np.uint64))
        with self._lock:
            reg0 = self._spill_keys.get(path)
            if reg0 is not None:
                # the file is a snapshot; only its REGISTERED keys are
                # still disk-authoritative — a promoted-then-updated key's
                # stale copy must never load back over fresher state
                sel &= np.isin(dkeys, reg0)
            live = self.index.lookup(
                np.ascontiguousarray(dkeys, np.uint64)) >= 0
            sel &= ~live  # RAM state wins over the spilled copy
            lk = dkeys[sel]
            rows = self.index.assign(lk)
            if len(rows):
                self._ensure(int(rows.max()))
            for f in self.fields:
                self._write_field(f, rows, blob, "load_from_disk",
                                  sel=sel)
            reg = self._spill_keys.get(path)
            if reg is not None:
                gone = dkeys[sel | live]
                remaining = reg[~np.isin(reg, gone)]
                if len(remaining):
                    self._spill_keys[path] = remaining
                else:
                    self._spill_files.remove(path)
                    self._spill_keys.pop(path, None)
        log.info("load_from_disk: %d rows <- %s", len(lk), path)
        return int(len(lk))

    # ---- feature aging (ShrinkTable, box_wrapper.h:638) ----
    def shrink(self, delete_threshold: Optional[float] = None,
               decay: Optional[float] = None,
               nonclk_coeff: float = 0.1, clk_coeff: float = 1.0) -> int:
        thr = (FLAGS.shrink_delete_threshold
               if delete_threshold is None else delete_threshold)
        dk = FLAGS.show_click_decay_rate if decay is None else decay
        self._barrier()  # decay/score must see every written-back row
        with self._lock:
            keys, rows = self.index.items()
            if len(keys) == 0:
                return 0
            self._arr["show"] *= dk
            self._arr["clk"] *= dk
            self._arr["delta_score"] *= dk
            drop = self._score(rows, nonclk_coeff, clk_coeff) < thr
            freed = self._free(keys[drop])
            self._purge_spilled(keys[drop])
        log.info("host shrink: freed %d/%d rows", len(freed), len(keys))
        return int(len(freed))
