"""Mesh-sharded embedding table: the HeterComm redesign for TPU.

Reference: paddle/fluid/framework/fleet/heter_ps/heter_comm_inl.h — the
table is sharded by ``key % num_devices`` (calc_shard_index_kernel,
heter_comm_kernel.cu:91); pull sorts/splits keys per shard
(split_input_to_shard :1117), P2P-copies keys to the owner GPU
(walk_to_dest :273), gathers on the owner, walks values back
(walk_to_src :428) and restores order with dedup (pull_merge_sparse
:1329-1472); push merges grads (merge_grad cub sort+reduce) and applies the
optimizer on the owner.

TPU-native redesign: all P2P walks become TWO ``lax.all_to_all`` ops over
the mesh axis inside one jit step (ICI-routed, overlappable by XLA), and all
sort/dedup/index work happens on HOST during batch prep (overlapped with
device compute by the trainer's prefetch pipeline):

  host prep (per global batch):
    for each device d: unique keys of d's local batch, bucketed by owner
    shard s = key % N → request lists [N, A] (A = padded per-pair capacity);
    for each owner s: dedup of ALL requests it will serve → serve_rows [A2]
    and response index resp_idx [N, A] into it (so duplicate rows requested
    by several devices are served and grad-merged once).
  device step (per shard, under shard_map):
    serve_vals = gather(table, serve_rows)          # local HBM gather
    resp      = serve_vals[resp_idx]                # [N, A, D]
    recv      = all_to_all(resp)                    # values to requesters
    … model fwd/bwd on local batch …
    g_back    = all_to_all(g_recv)                  # grads to owners
    g_serve   = segment_sum(g_back, resp_idx)       # merge across requesters
    table     = apply_push(table, serve_rows, g_serve)

No RPC plane, no NCCL rings, no device-side sort: the only cross-chip
traffic is the two value-sized all-to-alls (+ the dense psum), exactly the
ICI-friendly schedule.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.table import (FIELD_COL, FIELDS, NUM_FIXED, HostKV,
                                    TableState, field_assign, field_slice,
                                    fill_oob_pads, init_table_state,
                                    next_bucket)
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class ShardedPullIndex(NamedTuple):
    """Host-built routing plan for one global batch; leading dim = device.

    Shapes: N devices, A = per-(dst,src) request capacity, A2 = per-owner
    serve capacity, K = padded keys per local batch. ``req_need`` /
    ``serve_need`` are the UNPADDED maxima behind A/A2 — the resident
    builder re-buckets a whole pass with the fine ladder from them."""

    resp_idx: np.ndarray     # int32 [N_owner, N_dst, A] → slot in serve_rows
    serve_rows: np.ndarray   # int32 [N_owner, A2]; pads → sentinel row C
    serve_valid: np.ndarray  # f32   [N_owner, A2]
    serve_slot: np.ndarray   # f32   [N_owner, A2] slot id of the row's key
    gather_idx: np.ndarray   # int32 [N_dst, K] → index into recv [N*A]
    key_valid: np.ndarray    # f32   [N_dst, K]
    req_capacity: int        # A
    serve_capacity: int      # A2
    req_need: int = 0        # max real requests per (dst, owner)
    serve_need: int = 0      # max real serve rows per owner (+1 sentinel)


def _bucket(n: int, bucket_min: int) -> int:
    return next_bucket(bucket_min, n)


class ShardedEmbeddingTable:
    """N-shard embedding store driven from a single host process.

    Key → owner shard ``key % N`` (heter_comm_kernel.cu:91); each shard has
    its own HostKV index and a [C+1]-row slice of the device table state,
    stacked on a leading mesh axis."""

    def __init__(self, num_shards: int, mf_dim: int = 8,
                 capacity_per_shard: Optional[int] = None,
                 cfg: Optional[SparseSGDConfig] = None,
                 req_bucket_min: int = 512,
                 serve_bucket_min: int = 1024) -> None:
        self.n = num_shards
        self.mf_dim = mf_dim
        self.capacity = capacity_per_shard or FLAGS.table_capacity_per_shard
        self.cfg = cfg or SparseSGDConfig()
        from paddlebox_tpu.ps.sgd import opt_ext_width
        self.opt_ext = opt_ext_width(self.cfg, mf_dim)
        self.indexes = [HostKV(self.capacity) for _ in range(num_shards)]
        self.req_bucket_min = req_bucket_min
        self.serve_bucket_min = serve_bucket_min
        # stacked state [N, L, 128] — sharded over the mesh axis
        single = init_table_state(self.capacity, mf_dim, ext=self.opt_ext)
        self.state = self._make_stacked_state(single, num_shards)
        self._touched = np.zeros((num_shards, self.capacity + 1), dtype=bool)
        # serializes host index/touched mutation across threads (resident
        # pass preloading vs save/shrink — same discipline as
        # EmbeddingTable.host_lock)
        self.host_lock = threading.Lock()
        # THREAD-LOCAL plan marker (tiered plan_scope): while the
        # CALLING thread builds a routing plan for a *future* pass, its
        # new-key assigns are recorded via _note_plan_assigned instead
        # of being marked touched — they have no values yet and train
        # only after their pass's begin_pass promotes the staged values.
        # Thread-local, not table-global: a concurrent streaming
        # prepare_global on another thread (training the OPEN pass)
        # must keep the normal assign semantics
        self._plan_tls = threading.local()

    @property
    def _plan_depth(self) -> int:
        return getattr(self._plan_tls, "depth", 0)

    def _make_stacked_state(self, single: TableState, n: int) -> TableState:
        """Subclass hook: build the stacked [N, L, 128] device state —
        the multihost table stages it SHARDED over the global mesh
        instead of materializing N windows on one device."""
        return single.with_packed(
            jnp.broadcast_to(single.packed[None],
                             (n,) + single.packed.shape).copy())

    # ------------------------------------------------------------------
    def prepare_global_eval(self, batches: List[SlotBatch],
                            req_capacity: Optional[int] = None,
                            serve_capacity: Optional[int] = None
                            ) -> ShardedPullIndex:
        """Read-only routing plan: unknown keys serve the zero sentinel
        row instead of allocating (inference; no index mutation). Only
        legal for pull-only steps — serve_rows may repeat the sentinel,
        which the push path's unique-scatter promise forbids."""
        return self.prepare_global(batches, req_capacity, serve_capacity,
                                   assign=False)

    def prepare_global(self, batches: List[SlotBatch],
                       req_capacity: Optional[int] = None,
                       serve_capacity: Optional[int] = None,
                       assign: bool = True) -> ShardedPullIndex:
        """Build the routing plan for N per-device batches (one global
        batch). All batches must share K_pad/batch_size/num_slots.
        ``req_capacity``/``serve_capacity`` force the A/A2 buckets — the
        resident-pass builder uses this to give every batch in a pass
        identical shapes (gather_idx encodes positions as owner*A + j, so
        A must be uniform across the staged pass)."""
        n = self.n
        assert len(batches) == n, f"need {n} local batches, got {len(batches)}"
        k_pad = max(b.keys.shape[0] for b in batches)
        C = self.capacity

        # per device: unique local keys + their owner shard + owner-local row
        # + slot id (first occurrence) for the table's slot field
        dev_uniq: List[np.ndarray] = []
        dev_inv: List[np.ndarray] = []
        dev_uniq_slot: List[np.ndarray] = []
        for b in batches:
            uniq, first, inv = np.unique(
                b.keys[:b.num_keys], return_index=True, return_inverse=True)
            occ_slot = (b.segments[:b.num_keys] % b.num_slots).astype(np.float32)
            dev_uniq.append(uniq)
            dev_inv.append(inv)
            dev_uniq_slot.append(occ_slot[first])

        # request lists per (dst, owner)
        req_rows = [[None] * n for _ in range(n)]      # [dst][owner] → rows
        req_slots = [[None] * n for _ in range(n)]     # [dst][owner] → slots
        req_pos_of_uniq: List[np.ndarray] = []         # per dst: (owner, j)
        a_max = 1
        for d in range(n):
            uniq = dev_uniq[d]
            owners = (uniq % np.uint64(n)).astype(np.int64)
            pos = np.empty((len(uniq), 2), dtype=np.int64)
            for s in range(n):
                sel = np.nonzero(owners == s)[0]
                keys_s = uniq[sel]
                with self.host_lock:
                    if assign and self._plan_depth:
                        pre = self.indexes[s].lookup(keys_s)
                        rows_s = self.indexes[s].assign(keys_s)
                        if (pre < 0).any():
                            self._note_plan_assigned(s, keys_s[pre < 0])
                        # touched stays clear: plan rows train only
                        # after their pass opens; mark_trained_rows
                        # flags them post-training
                    elif assign:
                        rows_s = self.indexes[s].assign(keys_s)
                        self._touched[s][rows_s] = True
                    else:
                        rows_s = self.indexes[s].lookup(keys_s)
                        rows_s = np.where(rows_s < 0, C,
                                          rows_s).astype(rows_s.dtype)
                req_rows[d][s] = rows_s
                req_slots[d][s] = dev_uniq_slot[d][sel]
                pos[sel, 0] = s
                pos[sel, 1] = np.arange(len(sel))
                a_max = max(a_max, len(sel))
            req_pos_of_uniq.append(pos)
        A = _bucket(a_max, self.req_bucket_min)
        if req_capacity is not None:
            if req_capacity < a_max:
                raise ValueError(
                    f"forced req_capacity {req_capacity} < needed {a_max}")
            A = req_capacity

        # owner-side dedup: all (dst, j) requests to owner s → serve slots
        resp_idx = np.zeros((n, n, A), dtype=np.int32)
        serve_rows_l: List[np.ndarray] = []
        serve_slot_l: List[np.ndarray] = []
        a2_max = 1
        for s in range(n):
            all_rows = np.concatenate([req_rows[d][s] for d in range(n)])
            all_slots = np.concatenate([req_slots[d][s] for d in range(n)])
            su, sinv = (np.unique(all_rows, return_inverse=True)
                        if len(all_rows) else
                        (np.empty(0, np.int64), np.empty(0, np.int64)))
            serve_rows_l.append(su)
            slot_l = np.zeros(len(su), np.float32)
            slot_l[sinv] = all_slots  # any requester's slot id for the key
            serve_slot_l.append(slot_l)
            a2_max = max(a2_max, len(su) + 1)
            off = 0
            for d in range(n):
                cnt = len(req_rows[d][s])
                resp_idx[s, d, :cnt] = sinv[off:off + cnt]
                # pads: point at the sentinel serve slot (last)
                resp_idx[s, d, cnt:] = len(su)
                off += cnt
        A2 = _bucket(a2_max, self.serve_bucket_min)
        if serve_capacity is not None:
            if serve_capacity < a2_max:
                raise ValueError(
                    f"forced serve_capacity {serve_capacity} < {a2_max}")
            A2 = serve_capacity

        serve_rows = np.empty((n, A2), dtype=np.int32)
        serve_valid = np.zeros((n, A2), dtype=np.float32)
        serve_slot = np.zeros((n, A2), dtype=np.float32)
        for s in range(n):
            u = len(serve_rows_l[s])
            serve_rows[s, :u] = serve_rows_l[s]
            fill_oob_pads(serve_rows[s], u, C)
            serve_valid[s, :u] = 1.0
            serve_slot[s, :u] = serve_slot_l[s]
            # pad requests point at the sentinel slot (zero row)
            resp_idx[s][resp_idx[s] == u] = A2 - 1

        # dst-side gather: local key occurrence → position in recv [N*A]
        gather_idx = np.full((n, k_pad), n * A - 1, dtype=np.int32)
        key_valid = np.zeros((n, k_pad), dtype=np.float32)
        for d in range(n):
            b = batches[d]
            pos = req_pos_of_uniq[d]             # per-unique (owner, j)
            occ = dev_inv[d]                     # per occurrence → unique
            oi = pos[occ]                        # [nk, 2]
            gather_idx[d, :b.num_keys] = (oi[:, 0] * A + oi[:, 1]).astype(np.int32)
            key_valid[d, :b.num_keys] = 1.0
        return ShardedPullIndex(
            resp_idx=resp_idx, serve_rows=serve_rows, serve_valid=serve_valid,
            serve_slot=serve_slot, gather_idx=gather_idx,
            key_valid=key_valid, req_capacity=A, serve_capacity=A2,
            req_need=a_max, serve_need=a2_max)

    def _note_plan_assigned(self, s: int, new_keys: np.ndarray) -> None:
        """Hook (called under host_lock) for keys newly assigned during
        a plan build — the tiered table records them as value-less
        PENDING rows; the plain HBM-resident table needs nothing (fresh
        zero rows ARE its contract for unseen keys)."""

    # ---- host save/load mirrors EmbeddingTable, per shard ----
    def feature_count(self) -> int:
        return sum(len(ix) for ix in self.indexes)

    def obs_stats(self) -> Dict[str, float]:
        """Occupancy gauges for pass events (obs/hub.emit_pass_event):
        totals across shards plus the fullest shard's fill (the key%N
        split skews, and one full shard stalls the whole mesh).
        Subclasses with plan-pending rows (tiered) override to add
        ``pending``."""
        per_shard = [len(ix) for ix in self.indexes]
        used = sum(per_shard)
        cap = self.capacity * self.n
        return {"capacity": cap, "used": used,
                "fill_frac": round(used / max(cap, 1), 6),
                "max_shard_fill_frac": round(
                    max(per_shard) / max(self.capacity, 1), 6)}

    def _dump(self, path: str, row_filter) -> int:
        data = np.asarray(jax.device_get(self.state.data))
        mf_end = NUM_FIXED + self.mf_dim
        blobs = {}
        total = 0
        for s in range(self.n):
            with self.host_lock:
                keys, rows = self.indexes[s].items()
                keys, rows = row_filter(s, keys, rows)
                # clear only the SNAPSHOTTED rows, inside the lock — rows
                # touched concurrently (preload thread) keep their flag
                # for the next delta
                self._touched[s][rows] = False
            blobs[f"keys_{s}"] = keys
            sub = data[s][rows]
            for f in FIELDS:
                # embedx sliced to mf_dim explicitly — field_slice's tail
                # is unbounded and would duplicate opt_ext into embedx_w
                blobs[f"{f}_{s}"] = (sub[:, NUM_FIXED:mf_end]
                                     if f == "embedx_w"
                                     else field_slice(sub, f))
            if self.opt_ext:
                blobs[f"opt_ext_{s}"] = sub[:, mf_end:]
            total += len(keys)
        np.savez_compressed(path, n=self.n, **blobs)
        return total

    def save_base(self, path: str) -> int:
        """Full model dump (SaveBase, box_wrapper.cc:1383)."""
        return self._dump(path, lambda s, keys, rows: (keys, rows))

    def save_delta(self, path: str) -> int:
        """Rows touched since last save (SaveDelta "xbox delta",
        box_wrapper.cc:1406)."""
        def flt(s, keys, rows):
            m = self._touched[s][rows]
            return keys[m], rows[m]
        return self._dump(path, flt)

    def load(self, path: str, merge: bool = False) -> int:
        """Load a base/delta dump; merge=True applies on top of the live
        table, else the table (host index AND device rows) is reset first."""
        blob = np.load(path)
        if merge:
            data = np.asarray(jax.device_get(self.state.data)).copy()
        else:
            data = np.zeros(
                (self.n, self.capacity + 1,
                 NUM_FIXED + self.mf_dim + self.opt_ext),
                np.float32)
            self.indexes = [HostKV(self.capacity) for _ in range(self.n)]
            self._touched[:] = False
        total = 0
        mf_end = NUM_FIXED + self.mf_dim
        for s, (keys, fields) in enumerate(self._file_per_shard(blob)):
            rows = self.indexes[s].assign(keys)
            for f in FIELDS:
                field_assign(data[s], rows, f, fields[f])
            if self.opt_ext:
                if "opt_ext" in fields \
                        and fields["opt_ext"].shape[1] == self.opt_ext:
                    data[s][rows, mf_end:mf_end + self.opt_ext] = \
                        fields["opt_ext"]
                elif len(keys):
                    # keep the log honest: starting "fresh" must also hold
                    # under merge=True, where the loaded rows may carry live
                    # optimizer state from before the load
                    data[s][rows, mf_end:mf_end + self.opt_ext] = 0.0
                    log.warning("load: file has no matching opt_ext block "
                                "for shard %d; optimizer state starts "
                                "fresh", s)
            total += len(keys)
        self.state = TableState.from_logical(data, self.capacity,
                                             ext=self.opt_ext)
        return total

    # ---- lifecycle: shrink / merge (box_wrapper.h:638-640,801-815) ----
    def shrink(self, delete_threshold: Optional[float] = None,
               decay: Optional[float] = None) -> int:
        """ShrinkTable over every HBM shard: decay show/clk/delta_score,
        drop rows whose decayed score falls below threshold — the same
        accessor rules as EmbeddingTable.shrink (ps/table.py), applied
        shard-parallel on the stacked state."""
        thr = (FLAGS.shrink_delete_threshold
               if delete_threshold is None else delete_threshold)
        dk = FLAGS.show_click_decay_rate if decay is None else decay
        freed_total = 0
        with self.host_lock:
            data = np.asarray(jax.device_get(self.state.data)).copy()
            data[:, :, 0:3] *= dk
            for s in range(self.n):
                keys, rows = self.indexes[s].items()
                if len(keys) == 0:
                    continue
                show, clk = data[s][rows, 0], data[s][rows, 1]
                score = (self.cfg.nonclk_coeff * (show - clk)
                         + self.cfg.clk_coeff * clk)
                drop = score < thr
                freed = self.indexes[s].release(keys[drop])
                data[s][freed] = 0.0
                self._touched[s][freed] = False
                freed_total += len(freed)
            self.state = TableState.from_logical(data, self.capacity,
                                                 ext=self.opt_ext)
        log.info("sharded shrink: freed %d rows across %d shards",
                 freed_total, self.n)
        return freed_total

    def _file_per_shard(self, blob):
        """(keys, fields-dict) per owner shard from a save file — fast
        path when the file's shard count matches; otherwise (different
        mesh size, or a single-table EmbeddingTable/HostStore save) keys
        re-split by key % N."""
        want = list(FIELDS) + (["opt_ext"] if self.opt_ext else [])
        if "n" in blob and int(blob["n"]) == self.n:
            for s in range(self.n):
                fields = {f: blob[f"{f}_{s}"] for f in want
                          if f"{f}_{s}" in blob}
                yield blob[f"keys_{s}"], fields
            return
        if "n" in blob:
            fn = int(blob["n"])
            keys = np.concatenate([blob[f"keys_{s}"] for s in range(fn)])
            fields = {f: np.concatenate([blob[f"{f}_{s}"]
                                         for s in range(fn)])
                      for f in want if f"{f}_0" in blob}
        else:
            keys = blob["keys"]
            fields = {f: blob[f] for f in want if f in blob}
        owners = (np.ascontiguousarray(keys, np.uint64)
                  % np.uint64(self.n)).astype(np.int64)
        for s in range(self.n):
            m = owners == s
            yield keys[m], {f: v[m] for f, v in fields.items()}

    def merge_model(self, path: str) -> int:
        """MergeModel (box_wrapper.h:801-803) shard-parallel: keys present
        in both ACCUMULATE show/clk/delta_score and keep live weights /
        optimizer state; unseen keys insert wholesale. Accepts sharded
        saves (any shard count) and single-table saves (split by key%N)."""
        blob = np.load(path)
        mf_end = NUM_FIXED + self.mf_dim
        total = 0
        with self.host_lock:
            data = np.asarray(jax.device_get(self.state.data)).copy()
            for s, (keys, fields) in enumerate(self._file_per_shard(blob)):
                if len(keys) == 0:
                    continue
                existing = self.indexes[s].lookup(keys) >= 0
                rows_new = self.indexes[s].assign(keys[~existing])
                for f in FIELDS:
                    field_assign(data[s], rows_new, f, fields[f][~existing])
                if self.opt_ext and "opt_ext" in fields \
                        and fields["opt_ext"].shape[1] == self.opt_ext:
                    data[s][rows_new, mf_end:] = fields["opt_ext"][~existing]
                rows_old = self.indexes[s].lookup(keys[existing])
                for f in ("show", "clk", "delta_score"):
                    data[s][rows_old, FIELD_COL[f]] += fields[f][existing]
                rows_all = self.indexes[s].lookup(keys)
                self._touched[s][rows_all] = True
                total += len(keys)
            self.state = TableState.from_logical(data, self.capacity,
                                                 ext=self.opt_ext)
        log.info("sharded merge_model: %d rows from %s", total, path)
        return total

    def merge_models(self, paths, update_type: str = "stats") -> int:
        """MergeMultiModels (box_wrapper.h:812-815): "stats" accumulates
        per file (merge_model); "overwrite" applies each file as a delta
        (load(merge=True) — later files win)."""
        if update_type not in ("stats", "overwrite"):
            raise ValueError(f"unknown update_type {update_type!r}")
        total = 0
        for p in paths:
            total += (self.merge_model(p) if update_type == "stats"
                      else self.load(p, merge=True))
        return total
