"""Mesh-sharded embedding table: the HeterComm redesign for TPU.

Reference: paddle/fluid/framework/fleet/heter_ps/heter_comm_inl.h — the
table is sharded by ``key % num_devices`` (calc_shard_index_kernel,
heter_comm_kernel.cu:91); pull sorts/splits keys per shard
(split_input_to_shard :1117), P2P-copies keys to the owner GPU
(walk_to_dest :273), gathers on the owner, walks values back
(walk_to_src :428) and restores order with dedup (pull_merge_sparse
:1329-1472); push merges grads (merge_grad cub sort+reduce) and applies the
optimizer on the owner.

TPU-native redesign: all P2P walks become TWO ``lax.all_to_all`` ops over
the mesh axis inside one jit step (ICI-routed, overlappable by XLA), and all
sort/dedup/index work happens on HOST during batch prep (overlapped with
device compute by the trainer's prefetch pipeline):

  host prep (per global batch):
    for each device d: unique keys of d's local batch, bucketed by owner
    shard s = key % N → request lists [N, A] (A = padded per-pair capacity);
    for each owner s: dedup of ALL requests it will serve → serve_rows [A2]
    and response index resp_idx [N, A] into it (so duplicate rows requested
    by several devices are served and grad-merged once).
  device step (per shard, under shard_map):
    serve_vals = gather(table, serve_rows)          # local HBM gather
    resp      = serve_vals[resp_idx]                # [N, A, D]
    recv      = all_to_all(resp)                    # values to requesters
    … model fwd/bwd on local batch …
    g_back    = all_to_all(g_recv)                  # grads to owners
    g_serve   = segment_sum(g_back, resp_idx)       # merge across requesters
    table     = apply_push(table, serve_rows, g_serve)

No RPC plane, no NCCL rings, no device-side sort: the only cross-chip
traffic is the two value-sized all-to-alls (+ the dense psum), exactly the
ICI-friendly schedule.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.table import (FIELD_COL, FIELDS, NUM_FIXED, HostKV,
                                    TableState, field_assign, field_slice,
                                    fill_oob_pads, init_table_state,
                                    next_bucket)
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class ShardedPullIndex(NamedTuple):
    """Host-built routing plan for one global batch; leading dim = device.

    Shapes: N devices, A = per-(dst,src) request capacity, A2 = per-owner
    serve capacity, K = padded keys per local batch. ``req_need`` /
    ``serve_need`` are the UNPADDED maxima behind A/A2 — the resident
    builder re-buckets a whole pass with the fine ladder from them."""

    resp_idx: np.ndarray     # int32 [N_owner, N_dst, A] → slot in serve_rows
    serve_rows: np.ndarray   # int32 [N_owner, A2]; pads → sentinel row C
    serve_valid: np.ndarray  # f32   [N_owner, A2]
    serve_slot: np.ndarray   # f32   [N_owner, A2] slot id of the row's key
    gather_idx: np.ndarray   # int32 [N_dst, K] → index into recv [N*A]
    key_valid: np.ndarray    # f32   [N_dst, K]
    req_capacity: int        # A
    serve_capacity: int      # A2
    req_need: int = 0        # max real requests per (dst, owner)
    serve_need: int = 0      # max real serve rows per owner (+1 sentinel)
    # ---- chunked exchange layout (FLAGS.a2a_chunks > 1; ISSUE 11) ----
    # empty/None = the monolithic plan (exactly the pre-chunking bytes).
    # When set, the A axis is partitioned into per-slot-group sections
    # (sum(a2a_sections) == A) so chunk g's all_to_all ships only its
    # section, and the key stream is re-laid group-contiguous
    # (sum(key_sections) == gather_idx.shape[1]) with the group's
    # segments shipped as ``key_segments`` (the batch's own segment
    # stream is in the ORIGINAL key order and no longer applies).
    a2a_sections: Tuple[int, ...] = ()   # per-group A section widths
    key_sections: Tuple[int, ...] = ()   # per-group K section widths
    slot_sections: Tuple[int, ...] = ()  # per-group slot counts (contig)
    key_segments: Optional[np.ndarray] = None  # int32 [N_dst, sum(K_g)]


def plan_sections(idx: "ShardedPullIndex") -> Tuple:
    """The static chunk-schedule key of a plan: ``(a2a_sections,
    key_sections, slot_sections)`` for a grouped plan, ``()`` for a
    monolithic one. The device step compiles one executable per
    distinct value (train/sharded.ShardedTrainStep._step_fn_for)."""
    if getattr(idx, "a2a_sections", ()):
        return (tuple(idx.a2a_sections), tuple(idx.key_sections),
                tuple(idx.slot_sections))
    return ()


def section_offsets(sections) -> List[int]:
    """Start offset of each contiguous section (exclusive-prefix sum).
    Shared by every consumer of a grouped plan's static layout — the
    chunked device step and the exchange probe must slice the SAME
    positions (train/sharded._device_step, train/a2a_probe)."""
    off, t = [], 0
    for x in sections:
        off.append(t)
        t += x
    return off


def chunk_local_positions(gi, a_total: int, a_lo: int, ag: int):
    """Global exchange positions ``owner*A + j`` → chunk-local
    ``owner*A_g + (j - a_lo)`` for the section at [a_lo, a_lo+ag).
    Operator-only arithmetic: works on np AND traced jnp arrays — ONE
    definition of the remap for the step and the probe."""
    owner = gi // a_total
    return owner * ag + (gi - owner * a_total) - a_lo


def _bucket(n: int, bucket_min: int) -> int:
    return next_bucket(bucket_min, n)


class ShardedEmbeddingTable:
    """N-shard embedding store driven from a single host process.

    Key → owner shard ``key % N`` (heter_comm_kernel.cu:91); each shard has
    its own HostKV index and a [C+1]-row slice of the device table state,
    stacked on a leading mesh axis."""

    def __init__(self, num_shards: int, mf_dim: int = 8,
                 capacity_per_shard: Optional[int] = None,
                 cfg: Optional[SparseSGDConfig] = None,
                 req_bucket_min: int = 512,
                 serve_bucket_min: int = 1024) -> None:
        self.n = num_shards
        self.mf_dim = mf_dim
        self.capacity = capacity_per_shard or FLAGS.table_capacity_per_shard
        self.cfg = cfg or SparseSGDConfig()
        from paddlebox_tpu.ps.sgd import opt_ext_width
        self.opt_ext = opt_ext_width(self.cfg, mf_dim)
        self.indexes = [HostKV(self.capacity) for _ in range(num_shards)]
        self.req_bucket_min = req_bucket_min
        self.serve_bucket_min = serve_bucket_min
        # stacked state [N, L, 128] — sharded over the mesh axis
        single = init_table_state(self.capacity, mf_dim, ext=self.opt_ext)
        self.state = self._make_stacked_state(single, num_shards)
        self._touched = np.zeros((num_shards, self.capacity + 1), dtype=bool)
        # serializes host index/touched mutation across threads (resident
        # pass preloading vs save/shrink — same discipline as
        # EmbeddingTable.host_lock)
        self.host_lock = threading.Lock()
        # THREAD-LOCAL plan marker (tiered plan_scope): while the
        # CALLING thread builds a routing plan for a *future* pass, its
        # new-key assigns are recorded via _note_plan_assigned instead
        # of being marked touched — they have no values yet and train
        # only after their pass's begin_pass promotes the staged values.
        # Thread-local, not table-global: a concurrent streaming
        # prepare_global on another thread (training the OPEN pass)
        # must keep the normal assign semantics
        self._plan_tls = threading.local()

    @property
    def _plan_depth(self) -> int:
        return getattr(self._plan_tls, "depth", 0)

    # ------------------------------------------------------------------
    # device-resident key assignment (FLAGS.use_pallas_index): lazy
    # per-shard Pallas open-addressing mirrors of the host kvs — same
    # contract as EmbeddingTable._bulk_assign_device: the host kv stays
    # AUTHORITATIVE, any state the mirror cannot reproduce exactly
    # degrades that shard loudly and stickily back to the host path.
    def _dev_index_for(self, s: int):
        """Per-shard DeviceKeyIndex, lazily seeded from the shard's
        host kv (call under host_lock)."""
        if getattr(self, "_dev_indexes", None) is None:
            self._dev_indexes = [None] * self.n
        dev = self._dev_indexes[s]
        if dev is None:
            from paddlebox_tpu.ops.pallas_index import DeviceKeyIndex
            dev = DeviceKeyIndex(self.capacity)
            if not dev.seed_from_kv(self.indexes[s]):
                dev.degrade(f"shard {s}: host kv rows are not dense "
                            "(free-list holes) — cannot mirror")
            self._dev_indexes[s] = dev
        return dev

    def _reset_dev_indexes(self) -> None:
        """Forget every shard's device mirror after a host-side kv
        lifecycle mutation (load/shrink/merge/release/promote): the
        next flag-on prepare re-seeds from the kv, or degrades loudly
        if the allocation is no longer dense."""
        self._dev_indexes = None

    def _shard_rows_device(self, s: int, keys_s: np.ndarray,
                           assign: bool) -> Optional[np.ndarray]:
        """Device route for one owner-shard request list: probe the
        shard's device hash index instead of the host kv. Returns
        int32 rows (assign) or rows with miss→C (lookup), or None to
        fall back to the host kv."""
        dev = self._dev_index_for(s)
        if dev.degraded:
            return None
        if len(self.indexes[s]) != dev.next_row:
            dev.degrade(f"shard {s}: host kv diverged "
                        f"({len(self.indexes[s])} keys vs "
                        f"{dev.next_row} mirrored)")
            return None
        if not assign:
            rows = dev.lookup_rows(keys_s)
            return np.where(rows < 0, self.capacity,
                            rows).astype(np.int32)
        out = dev.assign_unique(keys_s)
        if out is None:
            dev.degrade(f"shard {s}: probe/capacity overflow "
                        f"({len(keys_s)} keys at {dev.next_row} rows, "
                        f"capacity {self.capacity})")
            return None
        rows_u, new_mask = out
        if new_mask.any():
            # mirror ONLY the new keys into the host kv; kv.assign
            # allocates in stream order, so a dense kv must reproduce
            # the device rows exactly — anything else means holes
            krows = self.indexes[s].assign(keys_s[new_mask])
            if not np.array_equal(
                    krows, rows_u[new_mask].astype(krows.dtype)):
                dev.degrade(f"shard {s}: host kv allocated different "
                            "rows than the device index (free-list "
                            "holes)")
                return None
        return rows_u.astype(np.int32, copy=False)

    def _shard_rows(self, s: int, keys_s: np.ndarray,
                    assign: bool) -> np.ndarray:
        """Resolve owner-local rows for one (dst, owner) request list
        (call under host_lock; ``keys_s`` sorted unique keys owned by
        shard ``s``). The single seam shared by the monolithic and
        grouped plans: plan-depth assigns stay host-side (plan rows
        need the pre-lookup miss mask and roll back on abort), the
        streaming assign / read-only lookup paths route through the
        per-shard device probe table behind FLAGS.use_pallas_index,
        with both decisions booked in pbox_kernel_dispatch_total."""
        C = self.capacity
        if assign and self._plan_depth:
            pre = self.indexes[s].lookup(keys_s)
            rows_s = self.indexes[s].assign(keys_s)
            if (pre < 0).any():
                self._note_plan_assigned(s, keys_s[pre < 0])
            # touched stays clear: plan rows train only after their
            # pass opens; mark_trained_rows flags them post-training
            if getattr(self, "_dev_indexes", None) is not None:
                # the mirror missed these assigns — re-seed on next use
                self._dev_indexes[s] = None
            return rows_s
        if FLAGS.use_pallas_index:
            from paddlebox_tpu.ops.pallas_index import book_index_dispatch
            op = "assign" if assign else "lookup"
            rows_s = self._shard_rows_device(s, keys_s, assign)
            if rows_s is not None:
                if assign:
                    self._touched[s][rows_s] = True
                book_index_dispatch(op, "pallas")
                return rows_s
            book_index_dispatch(op, "host")
        if assign:
            rows_s = self.indexes[s].assign(keys_s)
            self._touched[s][rows_s] = True
        else:
            rows_s = self.indexes[s].lookup(keys_s)
            rows_s = np.where(rows_s < 0, C, rows_s).astype(rows_s.dtype)
        return rows_s

    def _make_stacked_state(self, single: TableState, n: int) -> TableState:
        """Subclass hook: build the stacked [N, L, 128] device state —
        the multihost table stages it SHARDED over the global mesh
        instead of materializing N windows on one device."""
        return single.with_packed(
            jnp.broadcast_to(single.packed[None],
                             (n,) + single.packed.shape).copy())

    # ------------------------------------------------------------------
    def prepare_global_eval(self, batches: List[SlotBatch],
                            req_capacity: Optional[int] = None,
                            serve_capacity: Optional[int] = None
                            ) -> ShardedPullIndex:
        """Read-only routing plan: unknown keys serve the zero sentinel
        row instead of allocating (inference; no index mutation). Only
        legal for pull-only steps — serve_rows may repeat the sentinel,
        which the push path's unique-scatter promise forbids."""
        return self.prepare_global(batches, req_capacity, serve_capacity,
                                   assign=False)

    def prepare_global(self, batches: List[SlotBatch],
                       req_capacity: Optional[int] = None,
                       serve_capacity: Optional[int] = None,
                       assign: bool = True,
                       groups: int = 1,
                       req_sections: Optional[Tuple[int, ...]] = None,
                       key_sections: Optional[Tuple[int, ...]] = None
                       ) -> ShardedPullIndex:
        """Build the routing plan for N per-device batches (one global
        batch). All batches must share K_pad/batch_size/num_slots.
        ``req_capacity``/``serve_capacity`` force the A/A2 buckets — the
        resident-pass builder uses this to give every batch in a pass
        identical shapes (gather_idx encodes positions as owner*A + j, so
        A must be uniform across the staged pass).

        ``groups > 1`` builds the CHUNKED exchange layout (ISSUE 11;
        FLAGS.a2a_chunks): the A axis is partitioned into contiguous
        per-slot-group sections so the device step can run one
        all_to_all per group overlapped with the previous group's
        pooling. Requires slot-qualified keys (every key's occurrences
        in ONE slot group); a violating batch falls back to the
        monolithic plan with a warning. ``req_sections``/
        ``key_sections`` force per-group section widths (the resident
        builder's uniform-shape contract, the grouped analogue of
        ``req_capacity``)."""
        if groups > 1:
            return self._prepare_global_grouped(
                batches, groups, serve_capacity=serve_capacity,
                assign=assign, req_sections=req_sections,
                key_sections=key_sections)
        n = self.n
        assert len(batches) == n, f"need {n} local batches, got {len(batches)}"
        k_pad = max(b.keys.shape[0] for b in batches)
        C = self.capacity

        # per device: unique local keys + their owner shard + owner-local row
        # + slot id (first occurrence) for the table's slot field
        dev_uniq: List[np.ndarray] = []
        dev_inv: List[np.ndarray] = []
        dev_uniq_slot: List[np.ndarray] = []
        for b in batches:
            uniq, first, inv = np.unique(
                b.keys[:b.num_keys], return_index=True, return_inverse=True)
            occ_slot = (b.segments[:b.num_keys] % b.num_slots).astype(np.float32)
            dev_uniq.append(uniq)
            dev_inv.append(inv)
            dev_uniq_slot.append(occ_slot[first])

        # request lists per (dst, owner)
        req_rows = [[None] * n for _ in range(n)]      # [dst][owner] → rows
        req_slots = [[None] * n for _ in range(n)]     # [dst][owner] → slots
        req_pos_of_uniq: List[np.ndarray] = []         # per dst: (owner, j)
        a_max = 1
        for d in range(n):
            uniq = dev_uniq[d]
            owners = (uniq % np.uint64(n)).astype(np.int64)
            pos = np.empty((len(uniq), 2), dtype=np.int64)
            for s in range(n):
                sel = np.nonzero(owners == s)[0]
                keys_s = uniq[sel]
                with self.host_lock:
                    rows_s = self._shard_rows(s, keys_s, assign)
                req_rows[d][s] = rows_s
                req_slots[d][s] = dev_uniq_slot[d][sel]
                pos[sel, 0] = s
                pos[sel, 1] = np.arange(len(sel))
                a_max = max(a_max, len(sel))
            req_pos_of_uniq.append(pos)
        A = _bucket(a_max, self.req_bucket_min)
        if req_capacity is not None:
            if req_capacity < a_max:
                raise ValueError(
                    f"forced req_capacity {req_capacity} < needed {a_max}")
            A = req_capacity

        # owner-side dedup: all (dst, j) requests to owner s → serve slots
        resp_idx = np.zeros((n, n, A), dtype=np.int32)
        serve_rows_l: List[np.ndarray] = []
        serve_slot_l: List[np.ndarray] = []
        a2_max = 1
        for s in range(n):
            all_rows = np.concatenate([req_rows[d][s] for d in range(n)])
            all_slots = np.concatenate([req_slots[d][s] for d in range(n)])
            su, sinv = (np.unique(all_rows, return_inverse=True)
                        if len(all_rows) else
                        (np.empty(0, np.int64), np.empty(0, np.int64)))
            serve_rows_l.append(su)
            slot_l = np.zeros(len(su), np.float32)
            slot_l[sinv] = all_slots  # any requester's slot id for the key
            serve_slot_l.append(slot_l)
            a2_max = max(a2_max, len(su) + 1)
            off = 0
            for d in range(n):
                cnt = len(req_rows[d][s])
                resp_idx[s, d, :cnt] = sinv[off:off + cnt]
                # pads: point at the sentinel serve slot (last)
                resp_idx[s, d, cnt:] = len(su)
                off += cnt
        A2 = _bucket(a2_max, self.serve_bucket_min)
        if serve_capacity is not None:
            if serve_capacity < a2_max:
                raise ValueError(
                    f"forced serve_capacity {serve_capacity} < {a2_max}")
            A2 = serve_capacity

        serve_rows = np.empty((n, A2), dtype=np.int32)
        serve_valid = np.zeros((n, A2), dtype=np.float32)
        serve_slot = np.zeros((n, A2), dtype=np.float32)
        for s in range(n):
            u = len(serve_rows_l[s])
            serve_rows[s, :u] = serve_rows_l[s]
            fill_oob_pads(serve_rows[s], u, C)
            serve_valid[s, :u] = 1.0
            serve_slot[s, :u] = serve_slot_l[s]
            # pad requests point at the sentinel slot (zero row)
            resp_idx[s][resp_idx[s] == u] = A2 - 1

        # dst-side gather: local key occurrence → position in recv [N*A]
        gather_idx = np.full((n, k_pad), n * A - 1, dtype=np.int32)
        key_valid = np.zeros((n, k_pad), dtype=np.float32)
        for d in range(n):
            b = batches[d]
            pos = req_pos_of_uniq[d]             # per-unique (owner, j)
            occ = dev_inv[d]                     # per occurrence → unique
            oi = pos[occ]                        # [nk, 2]
            gather_idx[d, :b.num_keys] = (oi[:, 0] * A + oi[:, 1]).astype(np.int32)
            key_valid[d, :b.num_keys] = 1.0
        return ShardedPullIndex(
            resp_idx=resp_idx, serve_rows=serve_rows, serve_valid=serve_valid,
            serve_slot=serve_slot, gather_idx=gather_idx,
            key_valid=key_valid, req_capacity=A, serve_capacity=A2,
            req_need=a_max, serve_need=a2_max)

    def _prepare_global_grouped(
            self, batches: List[SlotBatch], groups: int,
            serve_capacity: Optional[int] = None, assign: bool = True,
            req_sections: Optional[Tuple[int, ...]] = None,
            key_sections: Optional[Tuple[int, ...]] = None
            ) -> ShardedPullIndex:
        """Chunked-exchange plan (see prepare_global). Layout contract:

        - Rows ASSIGN in the monolithic order (sorted-unique per
          (dst, owner) pair) before any group re-layout, so new-key row
          ids — and therefore the whole table state — are bit-identical
          to an ``a2a_chunks=1`` run over the same stream.
        - The A axis is ``sum(a2a_sections)`` wide; pair (dst, owner)'s
          group-g requests sit at ``[a_lo[g], a_lo[g]+cnt)``. Every
          section keeps ≥ 1 trailing pad position (A_g ≥ need_g + 1) so
          the group's pad keys have an in-section zero read.
        - The key stream re-lays group-contiguous (key_sections), each
          section padded with keys that gather the section's last (pad)
          position and pool into the discard bin; the matching segment
          stream ships as ``key_segments``.
        - Serve side is UNCHANGED: one canonical per-owner dedup, so
          the push's merge_rows/apply_push segmentation — and the
          per-row grad summation order (src-major, one contribution per
          src) — match the monolithic plan exactly.

        The slot-qualified check is deliberately PER-DEVICE: a key that
        lands in different slot groups on different devices is still
        exact, because groups only shape each device's OWN request
        layout and key partition (each dst gathers from its own
        sections; pooling bins are per-(device-local) occurrence slot),
        while the serve side is group-agnostic — dedup is over row ids,
        and the slot last-writer is decided by the cross-device concat
        order, which the within-pair reorder preserves. Only a
        within-device conflict (one key, occurrences in two groups on
        the SAME batch) breaks the section layout, and that is exactly
        what the check rejects."""
        from paddlebox_tpu.ops.seqpool_cvm import slot_group_bounds
        n = self.n
        assert len(batches) == n, \
            f"need {n} local batches, got {len(batches)}"
        k_pad = max(b.keys.shape[0] for b in batches)
        C = self.capacity
        S = batches[0].num_slots
        bounds = slot_group_bounds(S, groups)
        c = len(bounds)
        if c <= 1:
            return self.prepare_global(batches, assign=assign,
                                       serve_capacity=serve_capacity)
        grp_of_slot = np.zeros(S, np.int64)
        for g, (lo, hi) in enumerate(bounds):
            grp_of_slot[lo:hi] = g

        # uniques + the slot-qualified check BEFORE any index mutation,
        # so the monolithic fallback is side-effect clean
        dev_uniq: List[np.ndarray] = []
        dev_inv: List[np.ndarray] = []
        dev_uniq_slot: List[np.ndarray] = []
        dev_key_grp: List[np.ndarray] = []
        for b in batches:
            uniq, first, inv = np.unique(
                b.keys[:b.num_keys], return_index=True,
                return_inverse=True)
            occ_slot = (b.segments[:b.num_keys]
                        % b.num_slots).astype(np.int64)
            occ_grp = grp_of_slot[occ_slot]
            key_grp = occ_grp[first]
            if (occ_grp != key_grp[inv]).any():
                log.warning(
                    "a2a_chunks=%d: a key's occurrences span slot "
                    "groups (keys are not slot-qualified) — falling "
                    "back to the monolithic exchange for this batch", c)
                return self.prepare_global(batches, assign=assign,
                                           serve_capacity=serve_capacity)
            dev_uniq.append(uniq)
            dev_inv.append(inv)
            dev_uniq_slot.append(occ_slot[first].astype(np.float32))
            dev_key_grp.append(key_grp)

        # request lists per (dst, owner): rows assigned in monolithic
        # order, then re-laid group-contiguous with per-group ranks
        req_rows = [[None] * n for _ in range(n)]
        req_slots = [[None] * n for _ in range(n)]
        req_grp = [[None] * n for _ in range(n)]
        need_g = np.zeros(c, np.int64)
        req_pos_of_uniq: List[np.ndarray] = []  # per dst: (owner, g, rank)
        for d in range(n):
            uniq = dev_uniq[d]
            owners = (uniq % np.uint64(n)).astype(np.int64)
            pos = np.empty((len(uniq), 3), dtype=np.int64)
            for s in range(n):
                sel = np.nonzero(owners == s)[0]
                keys_s = uniq[sel]
                with self.host_lock:
                    rows_s = self._shard_rows(s, keys_s, assign)
                grp_s = dev_key_grp[d][sel]
                order = np.argsort(grp_s, kind="stable")
                req_rows[d][s] = rows_s[order]
                req_slots[d][s] = dev_uniq_slot[d][sel][order]
                req_grp[d][s] = grp_s[order]
                ranks = np.empty(len(sel), np.int64)
                for g in range(c):
                    m = grp_s == g
                    cnt = int(m.sum())
                    ranks[m] = np.arange(cnt)
                    need_g[g] = max(need_g[g], cnt)
                pos[sel, 0] = s
                pos[sel, 1] = grp_s
                pos[sel, 2] = ranks
            req_pos_of_uniq.append(pos)
        if req_sections is not None:
            a_secs = tuple(int(x) for x in req_sections)
            for g in range(c):
                if a_secs[g] < int(need_g[g]) + 1:
                    raise ValueError(
                        f"forced req_sections[{g}]={a_secs[g]} < needed "
                        f"{int(need_g[g]) + 1}")
        else:
            bmin = max(1, self.req_bucket_min // c)
            a_secs = tuple(_bucket(int(need_g[g]) + 1, bmin)
                           for g in range(c))
        a_lo = np.concatenate([[0], np.cumsum(a_secs)]).astype(np.int64)
        A = int(a_lo[-1])

        # owner-side dedup: IDENTICAL to the monolithic plan (same rows,
        # same sorted-unique order); only resp positions move
        resp_idx = np.zeros((n, n, A), dtype=np.int32)
        serve_rows_l: List[np.ndarray] = []
        serve_slot_l: List[np.ndarray] = []
        a2_max = 1
        for s in range(n):
            all_rows = np.concatenate([req_rows[d][s] for d in range(n)])
            all_slots = np.concatenate([req_slots[d][s] for d in range(n)])
            su, sinv = (np.unique(all_rows, return_inverse=True)
                        if len(all_rows) else
                        (np.empty(0, np.int64), np.empty(0, np.int64)))
            serve_rows_l.append(su)
            slot_l = np.zeros(len(su), np.float32)
            slot_l[sinv] = all_slots
            serve_slot_l.append(slot_l)
            a2_max = max(a2_max, len(su) + 1)
            off = 0
            for d in range(n):
                cnt = len(req_rows[d][s])
                row = np.full(A, len(su), np.int64)
                if cnt:
                    jpos = a_lo[req_grp[d][s]] + \
                        np.concatenate([np.arange(int((req_grp[d][s] == g
                                                       ).sum()))
                                        for g in range(c)])
                    row[jpos] = sinv[off:off + cnt]
                resp_idx[s, d] = row
                off += cnt
        A2 = _bucket(a2_max, self.serve_bucket_min)
        if serve_capacity is not None:
            if serve_capacity < a2_max:
                raise ValueError(
                    f"forced serve_capacity {serve_capacity} < {a2_max}")
            A2 = serve_capacity

        serve_rows = np.empty((n, A2), dtype=np.int32)
        serve_valid = np.zeros((n, A2), dtype=np.float32)
        serve_slot = np.zeros((n, A2), dtype=np.float32)
        for s in range(n):
            u = len(serve_rows_l[s])
            serve_rows[s, :u] = serve_rows_l[s]
            fill_oob_pads(serve_rows[s], u, C)
            serve_valid[s, :u] = 1.0
            serve_slot[s, :u] = serve_slot_l[s]
            resp_idx[s][resp_idx[s] == u] = A2 - 1

        # dst-side gather: group-contiguous key sections
        k_need = np.zeros(c, np.int64)
        occ_grp_dev: List[np.ndarray] = []
        for d in range(n):
            og = dev_key_grp[d][dev_inv[d]]
            occ_grp_dev.append(og)
            for g in range(c):
                k_need[g] = max(k_need[g], int((og == g).sum()))
        if key_sections is not None:
            k_secs = tuple(int(x) for x in key_sections)
            for g in range(c):
                if k_secs[g] < int(k_need[g]):
                    raise ValueError(
                        f"forced key_sections[{g}]={k_secs[g]} < needed "
                        f"{int(k_need[g])}")
        else:
            # pow2 ladder from a FIXED min — never from the batch's
            # k_pad, whose per-batch wobble would mint gratuitously
            # distinct section tuples (and one jitted step executable
            # per tuple in streaming mode)
            k_secs = tuple(_bucket(max(1, int(k_need[g])), 8)
                           for g in range(c))
        k_lo = np.concatenate([[0], np.cumsum(k_secs)]).astype(np.int64)
        kp = int(k_lo[-1])
        gather_idx = np.empty((n, kp), dtype=np.int32)
        key_valid = np.zeros((n, kp), dtype=np.float32)
        key_segments = np.empty((n, kp), dtype=np.int32)
        for d, b in enumerate(batches):
            pos = req_pos_of_uniq[d]
            oi = pos[dev_inv[d]]                       # [nk, 3]
            gidx = (oi[:, 0] * A + a_lo[oi[:, 1]]
                    + oi[:, 2]).astype(np.int32)
            seg = b.segments[:b.num_keys]
            og = occ_grp_dev[d]
            for g in range(c):
                m = np.nonzero(og == g)[0]             # original order
                lo, kg = int(k_lo[g]), int(k_secs[g])
                # section pads gather the section's guaranteed-pad
                # exchange position (A_g ≥ need_g + 1 ⇒ the last j of
                # every pair's section serves the zero sentinel row)
                pad_flat = (n - 1) * A + int(a_lo[g]) + a_secs[g] - 1
                gather_idx[d, lo:lo + kg] = pad_flat
                gather_idx[d, lo:lo + len(m)] = gidx[m]
                key_valid[d, lo:lo + len(m)] = 1.0
                key_segments[d, lo:lo + kg] = b.pad_segment
                key_segments[d, lo:lo + len(m)] = seg[m]
        return ShardedPullIndex(
            resp_idx=resp_idx, serve_rows=serve_rows,
            serve_valid=serve_valid, serve_slot=serve_slot,
            gather_idx=gather_idx, key_valid=key_valid,
            req_capacity=A, serve_capacity=A2,
            req_need=int(need_g.max()) if c else 0, serve_need=a2_max,
            a2a_sections=a_secs, key_sections=k_secs,
            slot_sections=tuple(hi - lo for lo, hi in bounds),
            key_segments=key_segments)

    def _note_plan_assigned(self, s: int, new_keys: np.ndarray) -> None:
        """Hook (called under host_lock) for keys newly assigned during
        a plan build — the tiered table records them as value-less
        PENDING rows; the plain HBM-resident table needs nothing (fresh
        zero rows ARE its contract for unseen keys)."""

    # ---- host save/load mirrors EmbeddingTable, per shard ----
    def feature_count(self) -> int:
        return sum(len(ix) for ix in self.indexes)

    def obs_stats(self) -> Dict[str, float]:
        """Occupancy gauges for pass events (obs/hub.emit_pass_event):
        totals across shards plus the fullest shard's fill (the key%N
        split skews, and one full shard stalls the whole mesh).
        Subclasses with plan-pending rows (tiered) override to add
        ``pending``."""
        per_shard = [len(ix) for ix in self.indexes]
        used = sum(per_shard)
        cap = self.capacity * self.n
        return {"capacity": cap, "used": used,
                "fill_frac": round(used / max(cap, 1), 6),
                "max_shard_fill_frac": round(
                    max(per_shard) / max(self.capacity, 1), 6)}

    def _dump(self, path: str, row_filter) -> int:
        data = np.asarray(jax.device_get(self.state.data))
        mf_end = NUM_FIXED + self.mf_dim
        blobs = {}
        total = 0
        for s in range(self.n):
            with self.host_lock:
                keys, rows = self.indexes[s].items()
                keys, rows = row_filter(s, keys, rows)
                # clear only the SNAPSHOTTED rows, inside the lock — rows
                # touched concurrently (preload thread) keep their flag
                # for the next delta
                self._touched[s][rows] = False
            blobs[f"keys_{s}"] = keys
            sub = data[s][rows]
            for f in FIELDS:
                # embedx sliced to mf_dim explicitly — field_slice's tail
                # is unbounded and would duplicate opt_ext into embedx_w
                blobs[f"{f}_{s}"] = (sub[:, NUM_FIXED:mf_end]
                                     if f == "embedx_w"
                                     else field_slice(sub, f))
            if self.opt_ext:
                blobs[f"opt_ext_{s}"] = sub[:, mf_end:]
            total += len(keys)
        np.savez_compressed(path, n=self.n, **blobs)
        return total

    def save_base(self, path: str) -> int:
        """Full model dump (SaveBase, box_wrapper.cc:1383)."""
        return self._dump(path, lambda s, keys, rows: (keys, rows))

    def save_delta(self, path: str) -> int:
        """Rows touched since last save (SaveDelta "xbox delta",
        box_wrapper.cc:1406)."""
        def flt(s, keys, rows):
            m = self._touched[s][rows]
            return keys[m], rows[m]
        return self._dump(path, flt)

    def load(self, path: str, merge: bool = False) -> int:
        """Load a base/delta dump; merge=True applies on top of the live
        table, else the table (host index AND device rows) is reset first."""
        blob = np.load(path)
        if merge:
            data = np.asarray(jax.device_get(self.state.data)).copy()
        else:
            data = np.zeros(
                (self.n, self.capacity + 1,
                 NUM_FIXED + self.mf_dim + self.opt_ext),
                np.float32)
            self.indexes = [HostKV(self.capacity) for _ in range(self.n)]
            self._touched[:] = False
        total = 0
        mf_end = NUM_FIXED + self.mf_dim
        for s, (keys, fields) in enumerate(self._file_per_shard(blob)):
            rows = self.indexes[s].assign(keys)
            for f in FIELDS:
                field_assign(data[s], rows, f, fields[f])
            if self.opt_ext:
                if "opt_ext" in fields \
                        and fields["opt_ext"].shape[1] == self.opt_ext:
                    data[s][rows, mf_end:mf_end + self.opt_ext] = \
                        fields["opt_ext"]
                elif len(keys):
                    # keep the log honest: starting "fresh" must also hold
                    # under merge=True, where the loaded rows may carry live
                    # optimizer state from before the load
                    data[s][rows, mf_end:mf_end + self.opt_ext] = 0.0
                    log.warning("load: file has no matching opt_ext block "
                                "for shard %d; optimizer state starts "
                                "fresh", s)
            total += len(keys)
        self.state = TableState.from_logical(data, self.capacity,
                                             ext=self.opt_ext)
        self._reset_dev_indexes()
        return total

    # ---- lifecycle: shrink / merge (box_wrapper.h:638-640,801-815) ----
    def shrink(self, delete_threshold: Optional[float] = None,
               decay: Optional[float] = None) -> int:
        """ShrinkTable over every HBM shard: decay show/clk/delta_score,
        drop rows whose decayed score falls below threshold — the same
        accessor rules as EmbeddingTable.shrink (ps/table.py), applied
        shard-parallel on the stacked state."""
        thr = (FLAGS.shrink_delete_threshold
               if delete_threshold is None else delete_threshold)
        dk = FLAGS.show_click_decay_rate if decay is None else decay
        freed_total = 0
        with self.host_lock:
            data = np.asarray(jax.device_get(self.state.data)).copy()
            data[:, :, 0:3] *= dk
            for s in range(self.n):
                keys, rows = self.indexes[s].items()
                if len(keys) == 0:
                    continue
                show, clk = data[s][rows, 0], data[s][rows, 1]
                score = (self.cfg.nonclk_coeff * (show - clk)
                         + self.cfg.clk_coeff * clk)
                drop = score < thr
                freed = self.indexes[s].release(keys[drop])
                data[s][freed] = 0.0
                self._touched[s][freed] = False
                freed_total += len(freed)
            self._reset_dev_indexes()
            self.state = TableState.from_logical(data, self.capacity,
                                                 ext=self.opt_ext)
        log.info("sharded shrink: freed %d rows across %d shards",
                 freed_total, self.n)
        return freed_total

    def _file_per_shard(self, blob):
        """(keys, fields-dict) per owner shard from a save file — fast
        path when the file's shard count matches; otherwise (different
        mesh size, or a single-table EmbeddingTable/HostStore save) keys
        re-split by key % N."""
        want = list(FIELDS) + (["opt_ext"] if self.opt_ext else [])
        if "n" in blob and int(blob["n"]) == self.n \
                and all(f"keys_{s}" in blob for s in range(self.n)):
            for s in range(self.n):
                fields = {f: blob[f"{f}_{s}"] for f in want
                          if f"{f}_{s}" in blob}
                yield blob[f"keys_{s}"], fields
            return
        if "n" in blob:
            # tolerate files holding only SOME shards (a multihost
            # per-process save): concatenate what is present — the
            # key%N re-split below re-derives ownership either way
            fn = int(blob["n"])
            present = [s for s in range(fn) if f"keys_{s}" in blob]
            if present:
                keys = np.concatenate([blob[f"keys_{s}"]
                                       for s in present])
                fields = {f: np.concatenate([blob[f"{f}_{s}"]
                                             for s in present])
                          for f in want if f"{f}_{present[0]}" in blob}
            else:
                keys = np.zeros(0, np.uint64)
                fields = {}
        else:
            keys = blob["keys"]
            fields = {f: blob[f] for f in want if f in blob}
        owners = (np.ascontiguousarray(keys, np.uint64)
                  % np.uint64(self.n)).astype(np.int64)
        for s in range(self.n):
            m = owners == s
            yield keys[m], {f: v[m] for f, v in fields.items()}

    def merge_model(self, path: str) -> int:
        """MergeModel (box_wrapper.h:801-803) shard-parallel: keys present
        in both ACCUMULATE show/clk/delta_score and keep live weights /
        optimizer state; unseen keys insert wholesale. Accepts sharded
        saves (any shard count) and single-table saves (split by key%N)."""
        blob = np.load(path)
        mf_end = NUM_FIXED + self.mf_dim
        total = 0
        with self.host_lock:
            data = np.asarray(jax.device_get(self.state.data)).copy()
            for s, (keys, fields) in enumerate(self._file_per_shard(blob)):
                if len(keys) == 0:
                    continue
                existing = self.indexes[s].lookup(keys) >= 0
                rows_new = self.indexes[s].assign(keys[~existing])
                for f in FIELDS:
                    field_assign(data[s], rows_new, f, fields[f][~existing])
                if self.opt_ext and "opt_ext" in fields \
                        and fields["opt_ext"].shape[1] == self.opt_ext:
                    data[s][rows_new, mf_end:] = fields["opt_ext"][~existing]
                rows_old = self.indexes[s].lookup(keys[existing])
                for f in ("show", "clk", "delta_score"):
                    data[s][rows_old, FIELD_COL[f]] += fields[f][existing]
                rows_all = self.indexes[s].lookup(keys)
                self._touched[s][rows_all] = True
                total += len(keys)
            self._reset_dev_indexes()
            self.state = TableState.from_logical(data, self.capacity,
                                                 ext=self.opt_ext)
        log.info("sharded merge_model: %d rows from %s", total, path)
        return total

    def merge_models(self, paths, update_type: str = "stats") -> int:
        """MergeMultiModels (box_wrapper.h:812-815): "stats" accumulates
        per file (merge_model); "overwrite" applies each file as a delta
        (load(merge=True) — later files win)."""
        if update_type not in ("stats", "overwrite"):
            raise ValueError(f"unknown update_type {update_type!r}")
        total = 0
        for p in paths:
            total += (self.merge_model(p) if update_type == "stats"
                      else self.load(p, merge=True))
        return total
