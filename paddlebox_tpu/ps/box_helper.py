"""BoxPSHelper — the pass-pipeline driver (BoxHelper/``core.BoxPS`` role).

Reference: fleet/box_wrapper.h:1043-1295 — ``ReadData2Memory`` (:1086),
``PreLoadIntoMemory``/``WaitFeedPassDone`` (:1142,:1156) double-buffered
pass pipelining, and the Python pass protocol in SURVEY.md §3.3:

    ds.preload_into_memory()     # pass k+1 IO overlaps pass k training
    ...train pass k...
    ds.wait_feed_pass_done()
    ds.begin_pass()              # working set → HBM
    trainer.train_pass(ds)
    ds.end_pass(save_delta)      # HBM → host store

TPU-native split of work: dataset IO/parse/key-dedup runs on reader
threads (overlappable); the host-store fetch + HBM promotion runs inside
``begin_pass`` after the previous ``end_pass`` write-back so values are
never stale (the reference's closed PS enforces the same order between
EndPass and the next BeginPass).
"""

from __future__ import annotations

from typing import Optional

from paddlebox_tpu.data.dataset import PaddleBoxDataset
from paddlebox_tpu.ps.pass_table import PassScopedTable
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class BoxPSHelper:
    """Couples a pass-scoped table (+ optional trainer) to the pass
    protocol. Works with both ``PassScopedTable`` (single chip, backing
    store at ``table.host``) and ``TieredShardedEmbeddingTable`` (mesh,
    per-shard host stores with lifecycle methods on the table itself)."""

    def __init__(self, table, trainer=None) -> None:
        self.table = table
        self.trainer = trainer
        self.pass_id = 0
        #: last artifact published through this helper (the parent
        #: lineage link for the next publish_delta)
        self._published_tip = None

    def _store(self):
        """The full-model lifecycle surface: the single HostStore behind a
        PassScopedTable, or the tiered sharded table itself."""
        return getattr(self.table, "host", self.table)

    # ---- dataset attachment (Paddle-style ds.begin_pass() hooks) ----
    def attach(self, ds: PaddleBoxDataset) -> PaddleBoxDataset:
        ds.on_begin_pass = lambda d: self.begin_pass(d)
        ds.on_end_pass = lambda d, save_delta: self.end_pass(
            d, need_save_delta=save_delta)
        return ds

    # ---- pass protocol ----
    def read_data_to_memory(self, ds: PaddleBoxDataset) -> None:
        """Synchronous load (ReadData2Memory, box_wrapper.h:1086)."""
        ds.load_into_memory()

    def preload_into_memory(self, ds: PaddleBoxDataset) -> None:
        """Start pass k+1's IO while pass k trains (box_wrapper.h:1142)."""
        ds.preload_into_memory()

    def wait_feed_pass_done(self, ds: PaddleBoxDataset) -> None:
        ds.wait_preload_done()

    def stage_pass(self, ds: PaddleBoxDataset) -> None:
        """Overlap the NEXT pass's host-tier fetch with the OPEN pass's
        training (pre_build_thread, ps_gpu_wrapper.cc:913) — tiered
        tables only fetch keys missing from the resident HBM window,
        which are by construction outside the open pass's write-back
        set. Call after wait_feed_pass_done(ds_next), while the current
        pass still trains; the later begin_pass(ds_next) consumes the
        stage after reconciling it against the window.

        Overlap (staging while a pass is open) requires a table with the
        persistent-window reconcile (``supports_overlap_stage`` — both
        PassScopedTable and the tiered sharded tables have it; the guard
        below protects third-party tables without it)."""
        if (getattr(self.table, "in_pass", False)
                and not getattr(self.table, "supports_overlap_stage",
                                False)):
            raise RuntimeError(
                f"{type(self.table).__name__} cannot stage while a pass "
                "is open — call stage_pass between end_pass and "
                "begin_pass, or use a tiered sharded table")
        if getattr(self.table, "wants_slot_keys", False):
            self.table.stage(*ds.pass_key_slots())
        else:
            self.table.stage(ds.pass_keys())

    def begin_pass(self, ds: PaddleBoxDataset) -> int:
        """Promote the pass working set into HBM and point the trainer's
        jit state at it."""
        self.pass_id += 1
        if getattr(self.table, "wants_slot_keys", False):
            # multi-mf tiered: keys route by their slot's dim class
            n = self.table.begin_pass(*ds.pass_key_slots())
        else:
            n = self.table.begin_pass(ds.pass_keys())
        if self.trainer is not None:
            self.trainer.adopt_table()
        return n

    def train_pass(self, ds: PaddleBoxDataset, **kw) -> dict:
        if self.trainer is None:
            raise RuntimeError("no trainer bound")
        return self.trainer.train_pass(ds, **kw)

    def end_pass(self, ds: Optional[PaddleBoxDataset] = None,
                 need_save_delta: bool = False,
                 delta_path: Optional[str] = None) -> int:
        """Close the pass. With the async epilogue (ps/epilogue,
        FLAGS.async_end_pass) ``table.end_pass()`` returns in dispatch
        time and the HBM→host write-back drains in the background —
        the delta dump below fences implicitly (every HostStore read
        entry point drains the epilogue first), so the saved delta
        always contains the full pass."""
        if self.trainer is not None:
            self.trainer.sync_table()
        n = self.table.end_pass()
        if need_save_delta:
            path = delta_path or f"xbox_delta_pass{self.pass_id}.npz"
            self._store().save_delta(path)
        return n

    def fence(self) -> None:
        """Drain the table's async end_pass epilogue (no-op for tables
        without one); surfaces the first write-back failure."""
        f = getattr(self.table, "fence", None)
        if f is not None:
            f()

    # ---- model lifecycle (box_helper_py.cc:70-165) ----
    def save_base(self, path: str) -> int:
        return self._store().save_base(path)

    def save_delta(self, path: str) -> int:
        return self._store().save_delta(path)

    # ---- versioned publishing (artifacts.ArtifactStore — the xbox
    # day/delta publish flow, docs/RESILIENCE.md §Publishing) ----
    # Two-phase flag discipline: the save STAGES with
    # clear_touched=False (writer callables dump straight into the
    # store's stage dir), and the delta bookkeeping is cleared only
    # AFTER the publish commits — a publish that fails (or crashes)
    # between the two loses no delta rows; the retry re-exports them.

    def _publish_store(self):
        """The staged-publish capability check: a clear error up front
        beats a TypeError from inside the stage writer for table types
        whose save surface predates the two-phase kwargs."""
        store = self._store()
        if not hasattr(store, "clear_touched_flags"):
            raise TypeError(
                f"{type(store).__name__} does not support staged "
                "publishing — it needs save_base/save_delta("
                "clear_touched=) plus clear_touched_flags() "
                "(EmbeddingTable, HostStore and the tiered sharded "
                "table have them); save to a file and publish the "
                "path instead")
        return store

    def publish_base(self, artifacts, **meta) -> str:
        """``save_base`` straight into a crash-safe artifact version;
        returns the artifact id, which becomes the parent of the next
        :meth:`publish_delta`."""
        self._check_no_pass("publish_base")
        store = self._publish_store()
        self.fence()
        refs = {}
        manifest_fn = getattr(self.table, "spill_manifest", None)
        if manifest_fn is not None:
            m = manifest_fn()
            if m:
                refs["spill_manifest"] = {"digest": m.get("digest"),
                                          "live_rows": m.get("live_rows")}
        aid = artifacts.publish(
            {"sparse.npz":
             lambda p: store.save_base(p, clear_touched=False)},
            kind="base", refs=refs,
            meta={"pass_id": self.pass_id, "producer": "box_helper",
                  **meta})
        store.clear_touched_flags()   # the publish COMMITTED
        self._published_tip = aid
        return aid

    def publish_delta(self, artifacts, **meta) -> str:
        """``save_delta`` as a lineage-linked artifact version on top
        of the last publish through THIS helper. Refuses without a
        published parent — an unparented delta could never be
        chain-verified by a consumer (serving.ServingModel.adopt)."""
        parent = getattr(self, "_published_tip", None)
        if parent is None:
            from paddlebox_tpu.artifacts import ArtifactLineageError
            raise ArtifactLineageError(
                "publish_delta before any publish_base — the delta "
                "would have no verifiable parent version")
        self._check_no_pass("publish_delta")
        store = self._publish_store()
        self.fence()
        aid = artifacts.publish(
            {"sparse_delta.npz":
             lambda p: store.save_delta(p, clear_touched=False)},
            kind="delta", parent=parent,
            meta={"pass_id": self.pass_id, "producer": "box_helper",
                  **meta})
        store.clear_touched_flags()   # the publish COMMITTED
        self._published_tip = aid
        return aid

    def _check_no_pass(self, what: str) -> None:
        """Refuse host-tier mutation BEFORE applying it when a pass is
        open — the guard must precede the mutation or a caller that
        catches the error is left with a half-applied lifecycle op whose
        load/decay the still-resident window would overwrite at
        end_pass (tiered tables guard internally; this covers the
        PassScopedTable path where the store is mutated directly)."""
        if getattr(self.table, "in_pass", False):
            raise RuntimeError(
                f"{what} while a pass is open — the window's updates "
                "are not in the host store yet; end_pass first")

    def _invalidate_window(self) -> None:
        """After a host-tier mutation through a store that is NOT the
        table itself (PassScopedTable's HostStore), resident window rows
        would shadow the updated host values — drop them. Tiered tables
        drop their own window inside load/shrink/merge."""
        if (self._store() is not self.table
                and hasattr(self.table, "drop_window")):
            self.table.drop_window()

    def load_model(self, path: str, merge: bool = False) -> int:
        self._check_no_pass("load_model")
        self.fence()  # an in-flight write-back must not land atop a load
        n = self._store().load(path, merge=merge)
        self._invalidate_window()
        return n

    def shrink_table(self, **kw) -> int:
        self._check_no_pass("shrink_table")
        self.fence()  # decay/score must see every written-back row
        store = self._store()
        if store is self.table:  # tiered: scores with its own cfg coeffs
            return store.shrink(**kw)
        # score with the table's optimizer coefficients so host- and
        # device-side shrink agree on what to drop
        kw.setdefault("nonclk_coeff", self.table.cfg.nonclk_coeff)
        kw.setdefault("clk_coeff", self.table.cfg.clk_coeff)
        n = store.shrink(**kw)
        self._invalidate_window()
        return n
