"""Replicated small dense-embedding caches + host side-input table.

Reference:
- ``GpuReplicaCache`` (fleet/box_wrapper.h:63-122 + box_wrapper.cu:1210):
  a small dense embedding table built on host (``AddItems``), replicated
  into every GPU's HBM (``ToHBM``) and looked up in-kernel by row id
  (``pull_cache_value_kernel``) — used for tiny high-traffic vocabularies
  that would waste PS round-trips.
- ``InputTable`` (fleet/box_wrapper.h:124-197): string-keyed dense
  side-input rows on host, batch-looked-up and copied to device
  (``LookupInput``), feeding the ``InputTableDataFeed`` variant.

TPU-native: the replica cache is one jnp array — under pjit it is
replicated to every chip by giving it a fully-replicated sharding, and
lookups are jit gathers; the input table keeps a host string→row dict
and materializes per-batch rows as a device array.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class ReplicaCache:
    """GpuReplicaCache analogue: build rows on host, freeze to device."""

    def __init__(self, emb_dim: int) -> None:
        self.emb_dim = emb_dim
        self._rows: List[np.ndarray] = []
        self._dev: Optional[jax.Array] = None

    def add_items(self, rows: np.ndarray) -> int:
        """Append [n, emb_dim] rows; returns the first new row id."""
        rows = np.asarray(rows, np.float32).reshape(-1, self.emb_dim)
        first = self.size
        self._rows.append(rows)
        self._dev = None
        return first

    @property
    def size(self) -> int:
        return sum(r.shape[0] for r in self._rows)

    def to_hbm(self) -> jax.Array:
        """Freeze to a device array (ToHBM). Under pjit, pass this array
        with a replicated PartitionSpec to mirror the per-GPU copies."""
        if self._dev is None:
            host = (np.concatenate(self._rows, axis=0) if self._rows
                    else np.zeros((0, self.emb_dim), np.float32))
            self._dev = jnp.asarray(host)
        return self._dev

    def pull(self, ids: jax.Array) -> jax.Array:
        """Row lookup (pull_cache_value_kernel): [.., ] ids → [.., dim].
        Ids are clamped into range (the CUDA kernel does no bounds check
        either); an empty cache is a caller bug and raises at trace time."""
        table = self.to_hbm()
        if table.shape[0] == 0:
            raise ValueError("ReplicaCache.pull on an empty cache — "
                             "add_items first")
        return table[jnp.clip(ids, 0, table.shape[0] - 1)]


class InputTable:
    """Host string-keyed dense side-input (InputTable, box_wrapper.h:124)."""

    def __init__(self, dim: int) -> None:
        self.dim = dim
        self._map: Dict[str, int] = {}
        self._rows: List[np.ndarray] = []

    def add_input(self, key: str, values: Sequence[float]) -> int:
        v = np.asarray(values, np.float32)
        if v.shape != (self.dim,):
            raise ValueError(f"row for {key!r} has shape {v.shape}, "
                             f"want ({self.dim},)")
        if key in self._map:
            self._rows[self._map[key]] = v
            return self._map[key]
        self._map[key] = len(self._rows)
        self._rows.append(v)
        return self._map[key]

    def __len__(self) -> int:
        return len(self._rows)

    def lookup(self, keys: Sequence[str]) -> jax.Array:
        """Batch lookup → [n, dim] device array; misses read zeros
        (LookupInput H2D copy)."""
        out = np.zeros((len(keys), self.dim), np.float32)
        for i, k in enumerate(keys):
            r = self._map.get(k)
            if r is not None:
                out[i] = self._rows[r]
        return jnp.asarray(out)

    def load_index_filelist(self, filelist: Sequence[str],
                            parse_index_line=None,
                            thread_num: int = 4) -> int:
        """The ``InputIndexDataFeed`` role (data_feed.h:2289,
        data_feed.cc:4637; driven by InputTableDataset::
        LoadIndexIntoMemory, data_set.cc:3195): load index files of
        ``key → float vector`` rows into this table with a reader-thread
        pool and a pluggable line parser.

        ``parse_index_line(line) -> (key, values) | None`` is the
        ``ISlotParser::ParseIndexData`` hook; the default parses
        ``key<TAB>v0 v1 ...`` (space- or comma-separated floats). Bad
        LINES/ROWS are skipped with a warning (the reference's reader
        callback contract); a missing/unreadable FILE raises. Files
        parse in parallel but apply in FILELIST ORDER — a key appearing
        in several files deterministically keeps the last file's row.
        Returns the number of rows applied (overwrites included)."""
        import threading
        from paddlebox_tpu.utils.logging import get_logger
        log = get_logger(__name__)

        def default_parse(line: str):
            parts = line.rstrip("\n").split("\t", 1)
            if len(parts) != 2:
                return None
            vals = parts[1].replace(",", " ").split()
            return parts[0], [float(v) for v in vals]

        parse = parse_index_line or default_parse
        lock = threading.Lock()
        files = list(filelist)
        fidx = [0]
        parsed: List[Optional[list]] = [None] * len(files)
        errors: List[BaseException] = []

        def worker() -> None:
            while True:
                with lock:
                    if errors or fidx[0] >= len(files):
                        return
                    i = fidx[0]
                    fidx[0] += 1
                path = files[i]
                try:
                    rows = []
                    with open(path, "r") as fh:
                        for line in fh:
                            try:
                                item = parse(line)
                            except (ValueError, IndexError):
                                item = None
                            if item is None:
                                log.warning("index feed: bad line in %s "
                                            "skipped", path)
                                continue
                            rows.append(item)
                    parsed[i] = rows
                except BaseException as e:
                    # a missing/unreadable FILE is an error, not a skip —
                    # surface it instead of returning a partial count
                    with lock:
                        errors.append(e)
                    return

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(max(1, thread_num))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        # apply in FILELIST order: duplicate keys deterministically keep
        # the last file's row regardless of thread completion order
        added = 0
        for i, rows in enumerate(parsed):
            for key, vals in rows or ():
                try:
                    self.add_input(key, vals)
                    added += 1
                except ValueError:
                    # wrong-width vector: skip the row, as the
                    # reference's reader callback does
                    log.warning("index feed: bad row %r in %s skipped",
                                key, files[i])
        return added
