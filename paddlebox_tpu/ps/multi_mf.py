"""Per-slot embedding dims (multi_mf_dim) — dim-class sharded tables.

Reference: ``CommonFeatureValueAccessor`` stores a per-feature ``mf_dim``
and lays every value out dynamically (feature_value.h:42-185); the build
pipeline groups keys by their slot's dim class (``multi_mf_dim_`` paths in
ps_gpu_wrapper.cc BuildGPUTask) and the pull/push copy kernels
(``CopyForPull/CopyForPush`` dy_mf variants) read per-slot widths.

TPU-native redesign: dynamic per-row widths are hostile to XLA (no static
shapes, ragged gathers), but the DIMENSIONALITY only varies by SLOT, and
slots partition the key space. So: one full :class:`EmbeddingTable` per
DIM CLASS (each with its static row width, packed-line layout, optimizer
and slot arena), a per-slot class map, and a batch splitter that routes
each key to its class sub-batch. Gather/scatter cost on TPU is per INDEX,
so C class-wise pulls cost the same total as one mixed pull — the only
overhead is C small dispatches. Pooled outputs keep their per-slot widths
and concatenate in canonical slot order (the fused_seqpool_cvm +
concat contract downstream of pull_gpups_sparse)."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.table import (EmbeddingTable, PullIndex,
                                    next_bucket)
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


@dataclasses.dataclass
class ClassBatch:
    """One dim class's slice of a batch: a synthetic SlotBatch over the
    class's slots (S_c bins) plus its PullIndex."""

    batch: SlotBatch
    index: PullIndex


class SlotClassMap:
    """Slot → dim-class routing metadata shared by every multi-mf table
    (single-chip, sharded, serving): ``slot_mf_dims[i]`` is the embedx
    width of sparse slot i; slots with equal widths form a class."""

    def __init__(self, slot_mf_dims: Sequence[int]) -> None:
        self.slot_mf_dims = np.asarray(slot_mf_dims, np.int32)
        if (self.slot_mf_dims <= 0).any():
            raise ValueError("slot mf dims must be positive")
        self.dims: List[int] = sorted(set(int(d) for d in slot_mf_dims))
        self.num_slots = len(self.slot_mf_dims)
        self.class_of_slot = np.array(
            [self.dims.index(int(d)) for d in self.slot_mf_dims], np.int32)
        # rank of each slot within its class (segment renumbering)
        self.slot_rank = np.zeros(self.num_slots, np.int32)
        self.class_slots: List[np.ndarray] = []
        for c in range(len(self.dims)):
            idx = np.nonzero(self.class_of_slot == c)[0]
            self.slot_rank[idx] = np.arange(len(idx), dtype=np.int32)
            self.class_slots.append(idx.astype(np.int32))

    @property
    def num_classes(self) -> int:
        return len(self.dims)

    def class_dim(self, c: int) -> int:
        return self.dims[c]

    def pooled_width(self, cvm_offset: int = 2, use_cvm: bool = True) -> int:
        """Per-record width of the canonical slot-ordered pooled concat."""
        per = (cvm_offset if use_cvm else 0) + 1
        return int(sum(per + d for d in self.slot_mf_dims))

    def slot_route(self):
        """Canonical reassembly order: (class, rank) per global slot."""
        return [(int(self.class_of_slot[s]), int(self.slot_rank[s]))
                for s in range(self.num_slots)]

    def split_batch(self, batch: SlotBatch
                    ) -> Tuple[List[SlotBatch], List[np.ndarray]]:
        """Route keys to per-class synthetic SlotBatches (the multi-mf
        BuildGPUTask grouping, done per batch on the host)."""
        nk = batch.num_keys
        s = batch.num_slots
        if s != self.num_slots:
            raise ValueError(
                f"batch has {s} slots, table configured for "
                f"{self.num_slots}")
        segs = batch.segments[:nk]
        slot_of_key = (segs % s).astype(np.int32)
        rec_of_key = segs // s
        cls_of_key = self.class_of_slot[slot_of_key]
        out = []
        gslots = []
        for c in range(self.num_classes):
            m = cls_of_key == c
            keys_c = batch.keys[:nk][m]
            gslots.append(slot_of_key[m].astype(np.int16))
            s_c = len(self.class_slots[c])
            segs_c = (rec_of_key[m] * s_c
                      + self.slot_rank[slot_of_key[m]]).astype(np.int32)
            kcap = next_bucket(1024, len(keys_c) + 1)
            keys_pad = np.zeros(kcap, np.uint64)
            keys_pad[:len(keys_c)] = keys_c
            segs_pad = np.full(kcap, batch.batch_size * s_c, np.int32)
            segs_pad[:len(keys_c)] = segs_c
            out.append(SlotBatch(
                keys=keys_pad, segments=segs_pad, num_keys=len(keys_c),
                dense=batch.dense, label=batch.label, show=batch.show,
                clk=batch.clk, batch_size=batch.batch_size,
                num_slots=s_c,
                segments_trivial=batch.segments_trivial))
        return out, gslots


class MultiMfEmbeddingTable(SlotClassMap):
    """Facade over one EmbeddingTable per distinct slot mf_dim.

    Keys are routed by their slot's class; each class table sees a
    synthetic batch over only its slots, with segments renumbered to
    ``record * S_c + rank_of_slot_in_class``."""

    def __init__(self, slot_mf_dims: Sequence[int],
                 capacity_per_class: Optional[Dict[int, int]] = None,
                 capacity: Optional[int] = None,
                 cfg: Optional[SparseSGDConfig] = None, seed: int = 0,
                 unique_bucket_min: int = 1024,
                 arena_chunk_bits: Optional[int] = None) -> None:
        super().__init__(slot_mf_dims)
        caps = capacity_per_class or {}
        self.tables: List[EmbeddingTable] = []
        for c, d in enumerate(self.dims):
            n_slots_c = len(self.class_slots[c])
            self.tables.append(EmbeddingTable(
                mf_dim=d, capacity=caps.get(d, capacity), cfg=cfg,
                seed=seed + c, unique_bucket_min=unique_bucket_min,
                arena_slots=(n_slots_c if arena_chunk_bits is not None
                             else None),
                arena_chunk_bits=arena_chunk_bits or 12))

    # ------------------------------------------------------------------
    @property
    def feature_count(self) -> int:
        return sum(t.feature_count for t in self.tables)

    def prepare(self, batch: SlotBatch) -> List[ClassBatch]:
        """Per-class dedup + row assignment (DedupKeysAndFillIdx per dim
        class). Returns one ClassBatch per class, in class order."""
        subs, gslots = self.split_batch(batch)
        out = []
        for b, t, gs in zip(subs, self.tables, gslots):
            idx = t.prepare(b)
            # re-record GLOBAL slot ids: the sub-batch's segments carry
            # class-local ranks, and the persisted FeatureValue slot
            # field must stay globally meaningful (feature_value.h:570)
            with t.host_lock:
                t.record_slots(idx.unique_rows[:idx.num_unique],
                               idx.gather_idx[:b.num_keys], gs)
            out.append(ClassBatch(b, idx))
        return out

    # ---- lifecycle: delegate per class ----
    def save_base(self, path: str) -> int:
        return sum(t.save_base(f"{path}.mf{d}.npz")
                   for t, d in zip(self.tables, self.dims))

    def save_delta(self, path: str) -> int:
        return sum(t.save_delta(f"{path}.mf{d}.npz")
                   for t, d in zip(self.tables, self.dims))

    def load(self, path: str, merge: bool = False) -> int:
        return sum(t.load(f"{path}.mf{d}.npz", merge=merge)
                   for t, d in zip(self.tables, self.dims))

    def shrink(self, **kw) -> int:
        return sum(t.shrink(**kw) for t in self.tables)

    def pull(self, keys: np.ndarray, slots: np.ndarray) -> np.ndarray:
        """Host-side lookup: per-key pull values, padded to the MAX class
        width ([n, 3 + max_mf]; columns beyond the key's slot width are
        zero) — the dy_mf CopyForPull contract with per-slot widths.
        Unknown keys read zeros."""
        keys = np.ascontiguousarray(keys, np.uint64)
        slots = np.asarray(slots, np.int32)
        out = np.zeros((len(keys), 3 + max(self.dims)), np.float32)
        for c in range(self.num_classes):
            m = self.class_of_slot[slots] == c
            if not m.any():
                continue
            vals = self.tables[c].host_pull(keys[m])
            out[np.nonzero(m)[0], :vals.shape[1]] = vals
        return out

